// interpose — LD_PRELOAD syscall-interposition shim (pipelined +
// speculative output commit).
//
// Native-equivalent of the reference's spec_hooks.cpp: hooks
// __libc_start_main (init before the app's main, :48-100), accept/accept4
// (:102-141), read (:161-178) and close (:143-159), filtering sockets via
// fstat S_IFSOCK (:113-116). Where the reference calls straight into the
// in-process proxy (proxy_on_accept/read/close, rsm-interface.h:12-15),
// this shim forwards each event over a Unix domain socket to the replica
// driver daemon.
//
// TWO commit-wait disciplines:
//
// * SYNC (RP_SPEC=0): the calling thread blocks inside read() until the
//   driver acks — the reference's spin-until-committed-and-applied
//   semantics (proxy.c:160), pipelined across threads (each app thread
//   waits only for ITS OWN event).
//
// * SPECULATIVE (default): read() forwards the inbound bytes to the
//   driver and returns IMMEDIATELY — the app executes on not-yet-
//   committed input — while the shim additionally hooks the app's
//   OUTPUT syscalls (write/send/writev/sendmsg) on tracked client fds
//   and holds every reply until the commit frontier covers all input
//   events forwarded before that reply was produced (output commit).
//   Externally the guarantee is unchanged — a client that HAS a reply
//   knows its request committed — but the app's event loop never
//   stalls, so a single-threaded server (redis) keeps a deep pipeline
//   of events in flight instead of one-read-per-commit-RTT. This is
//   the TPU-native redesign of the reference's µs-scale blocking hot
//   path: with a host-loop commit latency in the milliseconds, blocking
//   the app thread caps throughput at one read-buffer per RTT;
//   speculation + output commit decouples app execution rate from
//   commit latency entirely. Mis-speculation (a deposed leader whose
//   app consumed input that never committed) is surfaced to the driver,
//   which quarantines the app until it is restarted and rebuilt from
//   the committed store (ClusterDriver.reset_app).
//
// Env:
//   RP_PROXY_SOCK  — path of the driver's Unix socket. Unset => all hooks
//                    pass through untouched (the app runs unreplicated).
//   RP_SPEC        — "0" selects the SYNC discipline (default "1").
//
// Wire format (little-endian), unchanged from the sync-only revision:
//   request : [u8 op][u32 seq][i32 fd][u32 len][len bytes]
//                                  op: 1=HELLO 2=CONNECT 3=SEND 4=CLOSE
//   response: [u32 seq][i32 status]   >=0 ok / pass; <0 drop connection
//   HELLO carries one payload byte: bit0 = speculative mode.
//
// Build: make -C native  ->  interpose.so

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <deque>
#include <string>

#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <pthread.h>
#include <stdlib.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

enum Op : uint8_t { OP_HELLO = 1, OP_CONNECT = 2, OP_SEND = 3, OP_CLOSE = 4 };

using accept_fn = int (*)(int, struct sockaddr*, socklen_t*);
using accept4_fn = int (*)(int, struct sockaddr*, socklen_t*, int);
using read_fn = ssize_t (*)(int, void*, size_t);
using write_fn = ssize_t (*)(int, const void*, size_t);
using send_fn = ssize_t (*)(int, const void*, size_t, int);
using writev_fn = ssize_t (*)(int, const struct iovec*, int);
using sendmsg_fn = ssize_t (*)(int, const struct msghdr*, int);
using close_fn = int (*)(int);
using main_fn = int (*)(int, char**, char**);

accept_fn real_accept;
accept4_fn real_accept4;
read_fn real_read;
write_fn real_write;
send_fn real_send;
writev_fn real_writev;
sendmsg_fn real_sendmsg;
close_fn real_close;
main_fn real_main;

int proxy_fd = -1;                    // UDS to the driver daemon
bool spec_mode = true;                // RP_SPEC != "0"
pthread_mutex_t send_mu = PTHREAD_MUTEX_INITIALIZER;  // write serialization
constexpr int kMaxFd = 65536;
unsigned char tracked[kMaxFd];        // fds that arrived through accept()
unsigned char severed[kMaxFd];        // negative-acked: drop held output
uint32_t fd_gen[kMaxFd];              // bumps on real close (reuse guard)

// ---- outstanding-event ring (ack bookkeeping) ----------------------------
//
// Every forwarded event claims one monotone 64-bit seq; the ring slot at
// seq % kRing tracks its ack. The FRONTIER is the largest seq such that
// every seq <= it is acked — held replies whose watermark is <= the
// frontier are releasable (all input the app had consumed when the reply
// was produced has committed). The wire carries the low 32 seq bits;
// outstanding count < kRing << 2^32, so slot.seq disambiguates.

constexpr uint32_t kRing = 1 << 15;   // max outstanding events
enum SlotState : uint8_t { FREE = 0, SENT = 1, DONE = 2 };
struct AckSlot {
  uint64_t seq;
  int32_t status;
  SlotState state;
  int32_t fd;                         // tracked fd (sever on negative ack)
  uint32_t gen;
  bool waited;                        // a sync caller will consume status
};
AckSlot ring[kRing];
pthread_mutex_t resp_mu = PTHREAD_MUTEX_INITIALIZER;
pthread_cond_t resp_cv = PTHREAD_COND_INITIALIZER;
uint64_t next_seq = 1;
uint64_t frontier = 0;                // all seqs <= frontier are acked
uint64_t last_sent = 0;               // last seq claimed (any op)
bool driver_dead = false;

// ---- held output (speculative mode) --------------------------------------

struct OutChunk {
  int32_t fd;
  uint32_t gen;
  uint64_t watermark;                 // flush once frontier >= watermark
  bool is_close;                      // real_close(fd) instead of write
  std::string data;
};
std::deque<OutChunk>* outq;           // FIFO; watermarks are monotone
size_t outq_bytes = 0;
bool flushing = false;                // exactly one flusher at a time
constexpr size_t kOutCap = 64u << 20; // writer backpressure bound

void resolve() {
  real_accept = (accept_fn)dlsym(RTLD_NEXT, "accept");
  real_accept4 = (accept4_fn)dlsym(RTLD_NEXT, "accept4");
  real_read = (read_fn)dlsym(RTLD_NEXT, "read");
  real_write = (write_fn)dlsym(RTLD_NEXT, "write");
  real_send = (send_fn)dlsym(RTLD_NEXT, "send");
  real_writev = (writev_fn)dlsym(RTLD_NEXT, "writev");
  real_sendmsg = (sendmsg_fn)dlsym(RTLD_NEXT, "sendmsg");
  real_close = (close_fn)dlsym(RTLD_NEXT, "close");
}

bool io_exact(int fd, void* buf, size_t n, bool writing) {
  size_t done = 0;
  while (done < n) {
    ssize_t r = writing
        ? real_write(fd, static_cast<char*>(buf) + done, n - done)
        : real_read(fd, static_cast<char*>(buf) + done, n - done);
    if (r < 0 && errno == EINTR) continue;  // signals during the commit
                                            // wait must not kill the link
    if (r <= 0) return false;
    done += static_cast<size_t>(r);
  }
  return true;
}

// Write held bytes to the app's client socket. Blocking (the fd is the
// app's; a pathologically slow client stalls the flusher and thus all
// held output — global backpressure, the same failure mode as the
// reference leader writing replies synchronously from the app thread).
void flush_write(int fd, const std::string& data) {
  size_t done = 0;
  while (done < data.size()) {
    // MSG_NOSIGNAL: a vanished client must not SIGPIPE the flusher
    ssize_t r = real_send(fd, data.data() + done, data.size() - done,
                          MSG_NOSIGNAL);
    if (r < 0 && errno == EINTR) continue;
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      // event-loop apps set client fds O_NONBLOCK: a full socket
      // buffer is backpressure, not death — wait for drainage
      struct pollfd p;
      p.fd = fd;
      p.events = POLLOUT;
      if (poll(&p, 1, 5000) <= 0) return;  // stuck client: drop
      continue;
    }
    if (r <= 0) return;               // client died: drop the remainder
    done += static_cast<size_t>(r);
  }
}

// Release every held chunk whose watermark the frontier now covers.
// Called with resp_mu held; drops the lock across the actual writes
// (the socket write must not serialize ack processing). The `flushing`
// flag keeps exactly one active flusher — two threads draining the
// queue concurrently could reorder same-fd replies — and gates the
// hold_output fast path for the same reason.
void flush_outq_locked() {
  if (flushing) return;               // the active flusher will pick up
  flushing = true;
  while (outq && !outq->empty() && outq->front().watermark <= frontier) {
    OutChunk c = std::move(outq->front());
    outq->pop_front();
    outq_bytes -= c.data.size();
    // gen mismatch => the fd number was really closed (and possibly
    // reused by a NEW connection) since this chunk was queued: skip it
    // entirely — data must never leak to a different client, and a
    // stale close chunk's fd is no longer ours to close. The gen bump
    // for a deferred close happens HERE, under resp_mu, so the reader
    // thread's generation checks can never race it.
    bool gen_ok = c.fd >= 0 && c.fd < kMaxFd && fd_gen[c.fd] == c.gen;
    bool do_close = gen_ok && c.is_close;
    bool do_write = gen_ok && !c.is_close && !severed[c.fd];
    if (do_close) fd_gen[c.fd]++;     // deferred real close (severed or not)
    pthread_cond_broadcast(&resp_cv);   // space freed for blocked writers
    pthread_mutex_unlock(&resp_mu);
    if (do_close) real_close(c.fd);
    else if (do_write) flush_write(c.fd, c.data);
    pthread_mutex_lock(&resp_mu);
  }
  flushing = false;
}

// Advance the frontier over contiguous DONE slots, freeing them; then
// flush newly releasable held output. resp_mu held.
void advance_frontier_locked() {
  bool moved = false;
  for (;;) {
    AckSlot& s = ring[(frontier + 1) % kRing];
    if (s.state != DONE || s.seq != frontier + 1 || s.waited) break;
    s.state = FREE;
    frontier++;
    moved = true;
  }
  if (moved) {
    pthread_cond_broadcast(&resp_cv);
    flush_outq_locked();
  }
}

// Driver death — the SPECULATIVE output-commit discipline's hard case.
// Replies held for input the dead driver never acked must NOT be
// released: the input may never have committed, and releasing the
// reply fabricates an ack for a write that is lost (the client would
// hold an +OK for data no surviving replica has). So: flush only the
// chunks the PRE-DEATH commit frontier already covers (their input
// committed — releasing them is correct and avoids spurious client
// retries), DROP the speculative remainder, and sever every tracked
// connection so clients observe a reset — they retry against the new
// leader/world, exactly as on a refused event. The app itself has
// executed uncommitted input (diverged); its supervisor replaces it
// with a store-rebuilt instance at the next generation. resp_mu held.
void driver_death_locked() {
  driver_dead = true;
  proxy_fd = -1;
  flush_outq_locked();                // committed-covered chunks only
  if (outq) {
    // speculative data replies are DROPPED; deferred is_close chunks
    // must still run their real close (the fd was handed to us by the
    // app's close() — dropping the chunk would leak it open with the
    // client hanging instead of reset)
    while (!outq->empty()) {
      OutChunk c = std::move(outq->front());
      outq->pop_front();
      outq_bytes -= c.data.size();
      bool gen_ok = c.fd >= 0 && c.fd < kMaxFd && fd_gen[c.fd] == c.gen;
      if (gen_ok && c.is_close) {
        fd_gen[c.fd]++;
        real_close(c.fd);
      }
    }
  }
  for (int fd = 0; fd < kMaxFd; fd++) {
    if (tracked[fd]) {
      severed[fd] = 1;
      tracked[fd] = 0;
      shutdown(fd, SHUT_RDWR);
    }
  }
  pthread_cond_broadcast(&resp_cv);
}

// Reader thread: distributes seq-tagged responses. EOF / error => the
// driver died: every waiter is released with a refusal and all tracked
// connections sever (see driver_death_locked — held speculative output
// is dropped, never flushed).
void* reader_main(void*) {
  for (;;) {
    uint8_t buf[8];
    if (!io_exact(proxy_fd, buf, sizeof buf, false)) break;
    uint32_t wseq;
    int32_t status;
    memcpy(&wseq, buf, 4);
    memcpy(&status, buf + 4, 4);
    pthread_mutex_lock(&resp_mu);
    // the slot index depends only on the low bits of the 64-bit seq,
    // which equal the low bits of the wire seq
    AckSlot& s = ring[wseq % kRing];
    if (s.state == SENT && (uint32_t)s.seq == wseq) {
      s.status = status;
      s.state = DONE;
      if (status < 0 && s.fd >= 0 && s.fd < kMaxFd &&
          fd_gen[s.fd] == s.gen) {
        // the driver refused this event (leadership lost): the bytes
        // must never be acked to the client — sever the connection and
        // drop its held output so the client retries elsewhere
        severed[s.fd] = 1;
        tracked[s.fd] = 0;
        shutdown(s.fd, SHUT_RDWR);
      }
      if (s.waited)
        pthread_cond_broadcast(&resp_cv);   // sync caller consumes it
      else
        advance_frontier_locked();
    }
    pthread_mutex_unlock(&resp_mu);
  }
  pthread_mutex_lock(&resp_mu);
  driver_death_locked();
  pthread_mutex_unlock(&resp_mu);
  return nullptr;
}

// Claim a seq + ring slot (resp_mu held). Waits if the ring is full.
// Returns 0 on driver death.
uint64_t claim_slot_locked(int32_t fd, bool waited) {
  for (;;) {
    if (driver_dead) return 0;
    AckSlot& s = ring[next_seq % kRing];
    if (s.state == FREE) break;
    pthread_cond_wait(&resp_cv, &resp_mu);  // ring full: wait for acks
  }
  uint64_t seq = next_seq++;
  AckSlot& s = ring[seq % kRing];
  s.seq = seq;
  s.status = 0;
  s.state = SENT;
  s.fd = fd;
  s.gen = (fd >= 0 && fd < kMaxFd) ? fd_gen[fd] : 0;
  s.waited = waited;
  last_sent = seq;
  return seq;
}

bool send_event(uint64_t seq, uint8_t op, int32_t fd, const void* data,
                uint32_t len) {
  uint8_t hdr[13];
  uint32_t wseq = (uint32_t)seq;
  hdr[0] = op;
  memcpy(hdr + 1, &wseq, 4);
  memcpy(hdr + 5, &fd, 4);
  memcpy(hdr + 9, &len, 4);
  pthread_mutex_lock(&send_mu);       // short: enqueue order only
  int pfd = proxy_fd;
  bool ok = pfd >= 0 && io_exact(pfd, hdr, sizeof hdr, true) &&
            (len == 0 ||
             io_exact(pfd, const_cast<void*>(data), len, true));
  pthread_mutex_unlock(&send_mu);
  return ok;
}

// Synchronous event: send and wait for the driver's verdict (CONNECT
// always; SEND/CLOSE in sync mode). Other threads' events proceed
// concurrently (per-thread slots).
int32_t proxy_call(uint8_t op, int32_t fd, const void* data, uint32_t len) {
  if (proxy_fd < 0) return 0;
  pthread_mutex_lock(&resp_mu);
  uint64_t seq = claim_slot_locked(fd, /*waited=*/true);
  if (seq == 0) {
    pthread_mutex_unlock(&resp_mu);
    return 0;
  }
  pthread_mutex_unlock(&resp_mu);

  bool ok = send_event(seq, op, fd, data, len);

  pthread_mutex_lock(&resp_mu);
  AckSlot& s = ring[seq % kRing];
  if (!ok) driver_dead = true;
  while (s.state != DONE && !driver_dead)
    pthread_cond_wait(&resp_cv, &resp_mu);
  // death => REFUSE (the event's fate is unknown; the caller severs the
  // connection so the client retries elsewhere — never a silent
  // unreplicated pass-through)
  int32_t status = driver_dead ? -1 : s.status;
  s.waited = false;                   // frontier may now pass this slot
  if (s.state != DONE) s.state = DONE;
  advance_frontier_locked();
  if (driver_dead) driver_death_locked();
  pthread_mutex_unlock(&resp_mu);
  return status;
}

// Asynchronous event (speculative mode SEND/CLOSE): forward and return.
// The ack is consumed by the reader thread; ordering/visibility is
// enforced at output time via the frontier.
void proxy_cast(uint8_t op, int32_t fd, const void* data, uint32_t len) {
  if (proxy_fd < 0) return;
  pthread_mutex_lock(&resp_mu);
  uint64_t seq = claim_slot_locked(fd, /*waited=*/false);
  pthread_mutex_unlock(&resp_mu);
  if (seq == 0) return;
  if (!send_event(seq, op, fd, data, len)) {
    pthread_mutex_lock(&resp_mu);
    driver_death_locked();
    pthread_mutex_unlock(&resp_mu);
  }
}

// Hold (or pass) app output on a tracked fd. Returns the byte count the
// app should believe it wrote. `flags` carries the caller's send()
// flags for the pass-through path (MSG_NOSIGNAL always added: the app
// may rely on it rather than ignoring SIGPIPE process-wide; a tracked
// fd is always a socket, so real_send is valid even for write()).
ssize_t hold_output(int fd, const void* buf, size_t count, int flags) {
  pthread_mutex_lock(&resp_mu);
  if (severed[fd]) {
    pthread_mutex_unlock(&resp_mu);
    errno = ECONNRESET;
    return -1;
  }
  // fast path: nothing speculative outstanding, nothing queued, and no
  // flusher mid-write — the reply depends only on committed input and
  // cannot overtake a held one, so write straight through
  if ((!outq || outq->empty()) && frontier >= last_sent && !flushing) {
    pthread_mutex_unlock(&resp_mu);
    return real_send(fd, buf, count, flags | MSG_NOSIGNAL);
  }
  while (outq_bytes > kOutCap && !driver_dead)
    pthread_cond_wait(&resp_cv, &resp_mu);  // backpressure the app
  if (driver_dead) {
    // a tracked fd only reaches here by racing the death handler,
    // which severed it — this reply's input may never have committed,
    // so it must NOT reach the client
    pthread_mutex_unlock(&resp_mu);
    errno = ECONNRESET;
    return -1;
  }
  if (!outq) outq = new std::deque<OutChunk>();
  OutChunk c;
  c.fd = fd;
  c.gen = fd_gen[fd];
  c.watermark = last_sent;
  c.is_close = false;
  c.data.assign(static_cast<const char*>(buf), count);
  outq_bytes += count;
  outq->push_back(std::move(c));
  pthread_mutex_unlock(&resp_mu);
  return (ssize_t)count;
}

void rp_init() {
  resolve();
  const char* path = getenv("RP_PROXY_SOCK");
  if (!path) return;
  const char* spec = getenv("RP_SPEC");
  spec_mode = !(spec && spec[0] == '0');
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof addr);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof addr) != 0) {
    real_close(fd);
    return;
  }
  proxy_fd = fd;
  pthread_t thr;
  if (pthread_create(&thr, nullptr, reader_main, nullptr) != 0) {
    real_close(fd);
    proxy_fd = -1;
    return;
  }
  pthread_detach(thr);
  uint8_t flags = spec_mode ? 1 : 0;
  int32_t pid = static_cast<int32_t>(getpid());
  proxy_call(OP_HELLO, pid, &flags, 1);
}

bool is_socket(int fd) {
  struct stat st;
  return fstat(fd, &st) == 0 && S_ISSOCK(st.st_mode);
}

void on_accepted(int fd) {
  if (fd >= 0 && fd < kMaxFd && is_socket(fd)) {
    tracked[fd] = 1;
    severed[fd] = 0;
    // CONNECT carries the peer's address so the driver can tell its own
    // replay connections apart from real clients.
    uint8_t info[6] = {0, 0, 0, 0, 0, 0};
    struct sockaddr_in sa;
    socklen_t sl = sizeof sa;
    if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&sa), &sl) == 0 &&
        sa.sin_family == AF_INET) {
      memcpy(info, &sa.sin_addr.s_addr, 4);
      memcpy(info + 4, &sa.sin_port, 2);  // network byte order
    }
    if (proxy_call(OP_CONNECT, fd, info, 6) < 0) {
      // driver refused the connection (e.g. replicated session on a
      // deposed leader): sever it so the client reconnects elsewhere
      tracked[fd] = 0;
      shutdown(fd, SHUT_RDWR);
    }
  }
}

int wrapped_main(int argc, char** argv, char** envp) {
  rp_init();
  return real_main(argc, argv, envp);
}

}  // namespace

extern "C" {

int __libc_start_main(main_fn main, int argc, char** ubp_av,
                      void (*init)(void), void (*fini)(void),
                      void (*rtld_fini)(void), void* stack_end) {
  real_main = main;
  auto real = (int (*)(main_fn, int, char**, void (*)(void), void (*)(void),
                       void (*)(void), void*))
      dlsym(RTLD_NEXT, "__libc_start_main");
  return real(wrapped_main, argc, ubp_av, init, fini, rtld_fini, stack_end);
}

int accept(int sockfd, struct sockaddr* addr, socklen_t* addrlen) {
  if (!real_accept) resolve();
  int fd = real_accept(sockfd, addr, addrlen);
  if (proxy_fd >= 0) on_accepted(fd);
  // post-death quarantine: the speculative app has executed input that
  // never committed — NEW sessions must not be served from its
  // diverged state either (they get a reset and retry elsewhere)
  else if (driver_dead && fd >= 0) shutdown(fd, SHUT_RDWR);
  return fd;
}

int accept4(int sockfd, struct sockaddr* addr, socklen_t* addrlen,
            int flags) {
  if (!real_accept4) resolve();
  int fd = real_accept4(sockfd, addr, addrlen, flags);
  if (proxy_fd >= 0) on_accepted(fd);
  else if (driver_dead && fd >= 0) shutdown(fd, SHUT_RDWR);
  return fd;
}

ssize_t read(int fd, void* buf, size_t count) {
  if (!real_read) resolve();
  ssize_t n = real_read(fd, buf, count);
  // Replicate inbound client bytes. SYNC: block until the driver acks
  // (ack == committed on the leader); a negative status means the event
  // could NOT be committed (e.g. leadership was lost mid-session): the
  // bytes must never reach the app, so the connection is severed and
  // the client retries against the new leader. SPECULATIVE: forward and
  // return — the app executes immediately; its replies are held until
  // the commit frontier covers this event (output commit), and a late
  // negative ack severs the fd from the reader thread.
  if (n > 0 && proxy_fd >= 0 && fd >= 0 && fd < kMaxFd && tracked[fd]) {
    if (spec_mode) {
      proxy_cast(OP_SEND, fd, buf, static_cast<uint32_t>(n));
    } else if (proxy_call(OP_SEND, fd, buf,
                          static_cast<uint32_t>(n)) < 0) {
      tracked[fd] = 0;
      shutdown(fd, SHUT_RDWR);
      errno = ECONNRESET;
      return -1;
    }
  }
  return n;
}

ssize_t write(int fd, const void* buf, size_t count) {
  if (!real_write) resolve();
  if (spec_mode && proxy_fd >= 0 && fd >= 0 && fd < kMaxFd && tracked[fd])
    return hold_output(fd, buf, count, 0);
  return real_write(fd, buf, count);
}

ssize_t send(int sockfd, const void* buf, size_t len, int flags) {
  if (!real_send) resolve();
  if (spec_mode && proxy_fd >= 0 && sockfd >= 0 && sockfd < kMaxFd &&
      tracked[sockfd])
    return hold_output(sockfd, buf, len, flags);
  return real_send(sockfd, buf, len, flags);
}

ssize_t writev(int fd, const struct iovec* iov, int iovcnt) {
  if (!real_writev) resolve();
  if (spec_mode && proxy_fd >= 0 && fd >= 0 && fd < kMaxFd && tracked[fd]) {
    ssize_t total = 0;
    for (int i = 0; i < iovcnt; i++) {
      if (iov[i].iov_len == 0) continue;
      ssize_t r = hold_output(fd, iov[i].iov_base, iov[i].iov_len, 0);
      if (r < 0) return total > 0 ? total : r;
      total += r;
    }
    return total;
  }
  return real_writev(fd, iov, iovcnt);
}

ssize_t sendmsg(int sockfd, const struct msghdr* msg, int flags) {
  if (!real_sendmsg) resolve();
  if (spec_mode && proxy_fd >= 0 && sockfd >= 0 && sockfd < kMaxFd &&
      tracked[sockfd]) {
    ssize_t total = 0;
    for (size_t i = 0; i < msg->msg_iovlen; i++) {
      if (msg->msg_iov[i].iov_len == 0) continue;
      ssize_t r = hold_output(sockfd, msg->msg_iov[i].iov_base,
                              msg->msg_iov[i].iov_len, flags);
      if (r < 0) return total > 0 ? total : r;
      total += r;
    }
    return total;
  }
  return real_sendmsg(sockfd, msg, flags);
}

int close(int fd) {
  if (!real_close) resolve();
  if (proxy_fd >= 0 && fd >= 0 && fd < kMaxFd && tracked[fd]) {
    tracked[fd] = 0;
    if (spec_mode) {
      // the CLOSE is sequenced after this fd's pending input, and the
      // real close is deferred behind any held replies (a reply must
      // reach the client before its connection is torn down); the fd
      // number stays open until then, so the kernel cannot reuse it
      proxy_cast(OP_CLOSE, fd, nullptr, 0);
      pthread_mutex_lock(&resp_mu);
      // defer also while a flusher is mid-write: it may be blocked
      // inside the last popped chunk for THIS fd with resp_mu dropped —
      // closing now would truncate that reply (or race an fd reuse)
      bool defer = ((outq && !outq->empty()) || flushing) && !driver_dead;
      if (defer) {
        if (!outq) outq = new std::deque<OutChunk>();
        OutChunk c;
        c.fd = fd;
        c.gen = fd_gen[fd];
        c.watermark = last_sent;
        c.is_close = true;
        outq->push_back(std::move(c));
      } else {
        fd_gen[fd]++;
      }
      pthread_mutex_unlock(&resp_mu);
      if (defer) return 0;
      return real_close(fd);
    }
    proxy_call(OP_CLOSE, fd, nullptr, 0);
  }
  // any real close invalidates pending held chunks for this fd NUMBER —
  // the kernel may hand it to the next accepted connection immediately
  // (e.g. an fd severed by a negative ack is closed by the app on this
  // untracked path)
  if (fd >= 0 && fd < kMaxFd) {
    pthread_mutex_lock(&resp_mu);
    fd_gen[fd]++;
    pthread_mutex_unlock(&resp_mu);
  }
  return real_close(fd);
}

}  // extern "C"
