// interpose — LD_PRELOAD syscall-interposition shim (pipelined).
//
// Native-equivalent of the reference's spec_hooks.cpp: hooks
// __libc_start_main (init before the app's main, :48-100), accept/accept4
// (:102-141), read (:161-178) and close (:143-159), filtering sockets via
// fstat S_IFSOCK (:113-116). Where the reference calls straight into the
// in-process proxy (proxy_on_accept/read/close, rsm-interface.h:12-15),
// this shim forwards each event over a Unix domain socket to the replica
// driver daemon and blocks the CALLING THREAD until the driver
// acknowledges — on the leader the ack arrives only after the event is
// committed by the consensus core, reproducing the reference's
// spin-until-committed-and-applied semantics (proxy.c:160).
//
// Pipelined: the reference splits its hot path into a spinlock-protected
// tailq INSERT followed by a per-thread spin on the commit counter
// (proxy.c:114-160), so every app thread can have an event in flight
// concurrently. This shim does the same: the socket write (the enqueue)
// holds a short mutex, a dedicated reader thread distributes seq-tagged
// responses, and each app thread waits only for ITS OWN event — a
// multithreaded app commits many events per commit-latency, instead of
// one per process.
//
// Env:
//   RP_PROXY_SOCK  — path of the driver's Unix socket. Unset => all hooks
//                    pass through untouched (the app runs unreplicated).
//
// Wire format (little-endian):
//   request : [u8 op][u32 seq][i32 fd][u32 len][len bytes]
//                                  op: 1=HELLO 2=CONNECT 3=SEND 4=CLOSE
//   response: [u32 seq][i32 status]   >=0 ok / pass; <0 drop connection
//
// Build: make -C native  ->  interpose.so

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <pthread.h>
#include <stdlib.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

enum Op : uint8_t { OP_HELLO = 1, OP_CONNECT = 2, OP_SEND = 3, OP_CLOSE = 4 };

using accept_fn = int (*)(int, struct sockaddr*, socklen_t*);
using accept4_fn = int (*)(int, struct sockaddr*, socklen_t*, int);
using read_fn = ssize_t (*)(int, void*, size_t);
using close_fn = int (*)(int);
using main_fn = int (*)(int, char**, char**);

accept_fn real_accept;
accept4_fn real_accept4;
read_fn real_read;
close_fn real_close;
main_fn real_main;

int proxy_fd = -1;                    // UDS to the driver daemon
pthread_mutex_t send_mu = PTHREAD_MUTEX_INITIALIZER;  // write serialization
constexpr int kMaxFd = 65536;
unsigned char tracked[kMaxFd];        // fds that arrived through accept()

// ---- pipelined response plumbing -----------------------------------------

constexpr int kPendingCap = 256;      // max in-flight events per process
struct Pending {
  uint32_t seq;                       // 0 = slot free
  int32_t status;
  bool done;
};
Pending pending[kPendingCap];
pthread_mutex_t resp_mu = PTHREAD_MUTEX_INITIALIZER;
pthread_cond_t resp_cv = PTHREAD_COND_INITIALIZER;
uint32_t next_seq = 1;
bool driver_dead = false;

void resolve() {
  real_accept = (accept_fn)dlsym(RTLD_NEXT, "accept");
  real_accept4 = (accept4_fn)dlsym(RTLD_NEXT, "accept4");
  real_read = (read_fn)dlsym(RTLD_NEXT, "read");
  real_close = (close_fn)dlsym(RTLD_NEXT, "close");
}

bool io_exact(int fd, void* buf, size_t n, bool writing) {
  size_t done = 0;
  while (done < n) {
    ssize_t r = writing
        ? write(fd, static_cast<char*>(buf) + done, n - done)
        : real_read(fd, static_cast<char*>(buf) + done, n - done);
    if (r < 0 && errno == EINTR) continue;  // signals during the commit
                                            // wait must not kill the link
    if (r <= 0) return false;
    done += static_cast<size_t>(r);
  }
  return true;
}

// Reader thread: distributes seq-tagged responses to waiting app threads.
// EOF / error => the driver died: stop interposing, release every waiter
// with pass-through status 0 (the app keeps serving unreplicated — same
// fallback as before, now process-wide in one place).
void* reader_main(void*) {
  for (;;) {
    uint8_t buf[8];
    if (!io_exact(proxy_fd, buf, sizeof buf, false)) break;
    uint32_t seq;
    int32_t status;
    memcpy(&seq, buf, 4);
    memcpy(&status, buf + 4, 4);
    pthread_mutex_lock(&resp_mu);
    for (int i = 0; i < kPendingCap; i++) {
      if (pending[i].seq == seq) {
        pending[i].status = status;
        pending[i].done = true;
        break;
      }
    }
    pthread_cond_broadcast(&resp_cv);
    pthread_mutex_unlock(&resp_mu);
  }
  pthread_mutex_lock(&resp_mu);
  driver_dead = true;
  proxy_fd = -1;                      // hooks pass through from now on
  pthread_cond_broadcast(&resp_cv);
  pthread_mutex_unlock(&resp_mu);
  return nullptr;
}

// Send one event and wait for the driver's verdict. The calling thread
// blocks; other threads' events proceed concurrently.
int32_t proxy_call(uint8_t op, int32_t fd, const void* data, uint32_t len) {
  if (proxy_fd < 0) return 0;

  // claim a pending slot + a seq (the tailq-insert half)
  pthread_mutex_lock(&resp_mu);
  int slot = -1;
  for (;;) {
    if (driver_dead) {
      pthread_mutex_unlock(&resp_mu);
      return 0;
    }
    for (int i = 0; i < kPendingCap; i++) {
      if (pending[i].seq == 0) {
        slot = i;
        break;
      }
    }
    if (slot >= 0) break;
    pthread_cond_wait(&resp_cv, &resp_mu);   // all slots in flight
  }
  uint32_t seq = next_seq++;
  if (next_seq == 0) next_seq = 1;
  pending[slot].seq = seq;
  pending[slot].status = 0;
  pending[slot].done = false;
  pthread_mutex_unlock(&resp_mu);

  uint8_t hdr[13];
  hdr[0] = op;
  memcpy(hdr + 1, &seq, 4);
  memcpy(hdr + 5, &fd, 4);
  memcpy(hdr + 9, &len, 4);
  pthread_mutex_lock(&send_mu);       // short: enqueue order only
  int pfd = proxy_fd;
  bool ok = pfd >= 0 && io_exact(pfd, hdr, sizeof hdr, true) &&
            (len == 0 ||
             io_exact(pfd, const_cast<void*>(data), len, true));
  pthread_mutex_unlock(&send_mu);

  pthread_mutex_lock(&resp_mu);
  if (!ok) driver_dead = true;
  while (!pending[slot].done && !driver_dead)
    pthread_cond_wait(&resp_cv, &resp_mu);
  int32_t status = driver_dead ? 0 : pending[slot].status;
  pending[slot].seq = 0;              // free the slot
  pthread_cond_broadcast(&resp_cv);   // wake slot-waiters
  if (driver_dead) proxy_fd = -1;
  pthread_mutex_unlock(&resp_mu);
  return status;
}

void rp_init() {
  resolve();
  const char* path = getenv("RP_PROXY_SOCK");
  if (!path) return;
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return;
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof addr);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof addr) != 0) {
    real_close(fd);
    return;
  }
  proxy_fd = fd;
  pthread_t thr;
  if (pthread_create(&thr, nullptr, reader_main, nullptr) != 0) {
    real_close(fd);
    proxy_fd = -1;
    return;
  }
  pthread_detach(thr);
  int32_t pid = static_cast<int32_t>(getpid());
  proxy_call(OP_HELLO, pid, nullptr, 0);
}

bool is_socket(int fd) {
  struct stat st;
  return fstat(fd, &st) == 0 && S_ISSOCK(st.st_mode);
}

void on_accepted(int fd) {
  if (fd >= 0 && fd < kMaxFd && is_socket(fd)) {
    tracked[fd] = 1;
    // CONNECT carries the peer's address so the driver can tell its own
    // replay connections apart from real clients.
    uint8_t info[6] = {0, 0, 0, 0, 0, 0};
    struct sockaddr_in sa;
    socklen_t sl = sizeof sa;
    if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&sa), &sl) == 0 &&
        sa.sin_family == AF_INET) {
      memcpy(info, &sa.sin_addr.s_addr, 4);
      memcpy(info + 4, &sa.sin_port, 2);  // network byte order
    }
    if (proxy_call(OP_CONNECT, fd, info, 6) < 0) {
      // driver refused the connection (e.g. replicated session on a
      // deposed leader): sever it so the client reconnects elsewhere
      tracked[fd] = 0;
      shutdown(fd, SHUT_RDWR);
    }
  }
}

int wrapped_main(int argc, char** argv, char** envp) {
  rp_init();
  return real_main(argc, argv, envp);
}

}  // namespace

extern "C" {

int __libc_start_main(main_fn main, int argc, char** ubp_av,
                      void (*init)(void), void (*fini)(void),
                      void (*rtld_fini)(void), void* stack_end) {
  real_main = main;
  auto real = (int (*)(main_fn, int, char**, void (*)(void), void (*)(void),
                       void (*)(void), void*))
      dlsym(RTLD_NEXT, "__libc_start_main");
  return real(wrapped_main, argc, ubp_av, init, fini, rtld_fini, stack_end);
}

int accept(int sockfd, struct sockaddr* addr, socklen_t* addrlen) {
  if (!real_accept) resolve();
  int fd = real_accept(sockfd, addr, addrlen);
  if (proxy_fd >= 0) on_accepted(fd);
  return fd;
}

int accept4(int sockfd, struct sockaddr* addr, socklen_t* addrlen,
            int flags) {
  if (!real_accept4) resolve();
  int fd = real_accept4(sockfd, addr, addrlen, flags);
  if (proxy_fd >= 0) on_accepted(fd);
  return fd;
}

ssize_t read(int fd, void* buf, size_t count) {
  if (!real_read) resolve();
  ssize_t n = real_read(fd, buf, count);
  // Replicate inbound client bytes before the app acts on them; the
  // driver's ack means "committed by a quorum" on the leader. A negative
  // status means the event could NOT be committed (e.g. leadership was
  // lost mid-session): the bytes must never reach the app, so the
  // connection is severed and the client retries against the new leader.
  if (n > 0 && proxy_fd >= 0 && fd >= 0 && fd < kMaxFd && tracked[fd]) {
    if (proxy_call(OP_SEND, fd, buf, static_cast<uint32_t>(n)) < 0) {
      tracked[fd] = 0;
      shutdown(fd, SHUT_RDWR);
      errno = ECONNRESET;
      return -1;
    }
  }
  return n;
}

int close(int fd) {
  if (!real_close) resolve();
  if (proxy_fd >= 0 && fd >= 0 && fd < kMaxFd && tracked[fd]) {
    tracked[fd] = 0;
    proxy_call(OP_CLOSE, fd, nullptr, 0);
  }
  return real_close(fd);
}

}  // extern "C"
