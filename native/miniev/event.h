/* miniev — a minimal, self-contained implementation of the libevent-1.4
 * compatibility API, exactly the surface memcached 1.4.21 consumes
 * (event_init / event_set / event_base_set / event_add / event_del /
 * event_base_loop / event_get_version + the evtimer_* macros).
 *
 * Why it exists: this image ships libevent 2.1 RUNTIME libraries but no
 * development headers, and `struct event` is embedded BY VALUE in
 * memcached's conn struct — faking libevent's internal struct layout in
 * a hand-written header against the real .so would be ABI roulette.
 * Instead the whole event loop is reimplemented (~200 lines over epoll)
 * against THIS header, and memcached links the static libevent.a built
 * from it, so header and implementation can never disagree.
 *
 * Model: one event_base per thread (memcached's usage — the base is
 * single-threaded by design, like libevent's unlocked 1.4 default).
 * fd events via epoll (EV_PERSIST honored; non-persistent events are
 * auto-deleted before their callback fires, matching libevent). Timer
 * events (fd == -1) in a simple linked list — memcached arms one clock
 * timer per process.
 */
#ifndef MINIEV_EVENT_H
#define MINIEV_EVENT_H

#include <sys/time.h>

#ifdef __cplusplus
extern "C" {
#endif

#define EV_TIMEOUT 0x01
#define EV_READ    0x02
#define EV_WRITE   0x04
#define EV_SIGNAL  0x08
#define EV_PERSIST 0x10

struct event_base;

struct event {
    struct event_base *ev_base;
    int ev_fd;
    short ev_events;               /* EV_* flags requested */
    void (*ev_callback)(int, short, void *);
    void *ev_arg;
    /* internal */
    int ev_added;
    struct timeval ev_deadline;    /* absolute, for timer events */
    struct event *ev_next;         /* base's registration list */
};

struct event_base *event_base_new(void);
struct event_base *event_init(void);     /* new base, set as current */
void event_base_free(struct event_base *);

void event_set(struct event *, int fd, short events,
               void (*cb)(int, short, void *), void *arg);
int event_base_set(struct event_base *, struct event *);
int event_add(struct event *, const struct timeval *timeout);
int event_del(struct event *);
int event_base_loop(struct event_base *, int flags);
int event_base_loopexit(struct event_base *, const struct timeval *);
const char *event_get_version(void);

#define evtimer_set(ev, cb, arg) event_set(ev, -1, 0, cb, arg)
#define evtimer_add(ev, tv)      event_add(ev, tv)
#define evtimer_del(ev)          event_del(ev)

#define EVLOOP_ONCE     0x01
#define EVLOOP_NONBLOCK 0x02

#ifdef __cplusplus
}
#endif

#endif /* MINIEV_EVENT_H */
