/* miniev — implementation. See event.h for scope and rationale. */

#include "event.h"

#include <errno.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <unistd.h>

struct event_base {
    int epfd;
    struct event *events;          /* singly-linked registration list */
    int nadded;
    int loopexit;
};

static __thread struct event_base *current_base;

struct event_base *event_base_new(void) {
    struct event_base *b = calloc(1, sizeof *b);
    if (!b) return NULL;
    b->epfd = epoll_create1(0);
    if (b->epfd < 0) { free(b); return NULL; }
    return b;
}

struct event_base *event_init(void) {
    current_base = event_base_new();
    return current_base;
}

void event_base_free(struct event_base *b) {
    if (!b) return;
    close(b->epfd);
    free(b);
}

void event_set(struct event *ev, int fd, short events,
               void (*cb)(int, short, void *), void *arg) {
    ev->ev_base = current_base;
    ev->ev_fd = fd;
    ev->ev_events = events;
    ev->ev_callback = cb;
    ev->ev_arg = arg;
    ev->ev_added = 0;
    ev->ev_next = NULL;
}

int event_base_set(struct event_base *b, struct event *ev) {
    ev->ev_base = b;
    return 0;
}

static void list_remove(struct event_base *b, struct event *ev) {
    struct event **p = &b->events;
    while (*p && *p != ev) p = &(*p)->ev_next;
    if (*p) *p = ev->ev_next;
    ev->ev_next = NULL;
}

int event_add(struct event *ev, const struct timeval *tv) {
    struct event_base *b = ev->ev_base;
    if (!b) return -1;
    if (ev->ev_added) event_del(ev);
    if (ev->ev_fd >= 0) {
        struct epoll_event ee;
        memset(&ee, 0, sizeof ee);
        ee.data.ptr = ev;
        if (ev->ev_events & EV_READ) ee.events |= EPOLLIN;
        if (ev->ev_events & EV_WRITE) ee.events |= EPOLLOUT;
        if (epoll_ctl(b->epfd, EPOLL_CTL_ADD, ev->ev_fd, &ee) != 0)
            return -1;
    }
    if (tv) {
        struct timeval now;
        gettimeofday(&now, NULL);
        timeradd(&now, tv, &ev->ev_deadline);
    } else {
        timerclear(&ev->ev_deadline);
    }
    ev->ev_next = b->events;
    b->events = ev;
    ev->ev_added = 1;
    b->nadded++;
    return 0;
}

int event_del(struct event *ev) {
    struct event_base *b = ev->ev_base;
    if (!b || !ev->ev_added) return 0;
    if (ev->ev_fd >= 0)
        epoll_ctl(b->epfd, EPOLL_CTL_DEL, ev->ev_fd, NULL);
    list_remove(b, ev);
    ev->ev_added = 0;
    b->nadded--;
    return 0;
}

/* ms until the earliest armed deadline, or -1 for none */
static int next_timeout_ms(struct event_base *b) {
    struct timeval now, d;
    int best = -1;
    gettimeofday(&now, NULL);
    for (struct event *e = b->events; e; e = e->ev_next) {
        if (!timerisset(&e->ev_deadline)) continue;
        int ms;
        if (timercmp(&e->ev_deadline, &now, <=)) {
            ms = 0;
        } else {
            timersub(&e->ev_deadline, &now, &d);
            ms = (int)(d.tv_sec * 1000 + d.tv_usec / 1000 + 1);
        }
        if (best < 0 || ms < best) best = ms;
    }
    return best;
}

static void fire_expired_timers(struct event_base *b) {
    struct timeval now;
    gettimeofday(&now, NULL);
    /* re-walk after each callback: callbacks may add/del events */
    int fired;
    do {
        fired = 0;
        for (struct event *e = b->events; e; e = e->ev_next) {
            if (!timerisset(&e->ev_deadline)) continue;
            if (timercmp(&e->ev_deadline, &now, <=)) {
                event_del(e);
                e->ev_callback(e->ev_fd, EV_TIMEOUT, e->ev_arg);
                fired = 1;
                break;
            }
        }
    } while (fired);
}

int event_base_loop(struct event_base *b, int flags) {
    b->loopexit = 0;
    do {
        if (b->nadded == 0) return 1;      /* nothing to wait for */
        int ms = next_timeout_ms(b);
        if (flags & EVLOOP_NONBLOCK) ms = 0;
        struct epoll_event out[64];
        int n = epoll_wait(b->epfd, out, 64, ms);
        if (n < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        for (int i = 0; i < n; i++) {
            struct event *e = out[i].data.ptr;
            if (!e->ev_added)
                continue;   /* deleted by an earlier callback this batch */
            short what = 0;
            if (out[i].events & (EPOLLHUP | EPOLLERR))
                what |= (short)(e->ev_events & (EV_READ | EV_WRITE));
            if (out[i].events & EPOLLIN) what |= EV_READ;
            if (out[i].events & EPOLLOUT) what |= EV_WRITE;
            what &= e->ev_events;
            if (!what)
                continue;
            if (!(e->ev_events & EV_PERSIST))
                event_del(e);
            e->ev_callback(e->ev_fd, what, e->ev_arg);
        }
        fire_expired_timers(b);
    } while (!b->loopexit && !(flags & (EVLOOP_ONCE | EVLOOP_NONBLOCK)));
    return 0;
}

int event_base_loopexit(struct event_base *b, const struct timeval *tv) {
    (void)tv;
    b->loopexit = 1;
    return 0;
}

const char *event_get_version(void) {
    return "miniev-1.4-compat 0.1";
}
