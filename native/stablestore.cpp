// stablestore — append-only record store for replicated socket events.
//
// Native-equivalent of the reference's BerkeleyDB RECNO layer
// (src/db/db-interface.c: initialize_db :21, store_record :65 with
// DB_APPEND, dump_records/get_records_len :98-134): every committed client
// event is persisted in arrival order; the whole store serializes into a
// single buffer for joiner snapshot transfer and replays back on the other
// side (proxy.c:306-339 stablestorage_load_records).
//
// Format: a single file of length-prefixed records:
//   [u64 magic][u64 base]            (header, new files only)
//   [u32 len][len bytes] ...
// ``base`` is the ABSOLUTE index of the first retained record: a store
// COMPACTED after an app-state checkpoint drops its prefix (the
// checkpoint covers it) and keeps indices stable — record i lives at
// position i - base. Legacy headerless files read as base = 0. All API
// indices are absolute; ss_count returns base + live records. An
// in-memory offset index is rebuilt by scanning on open (truncated tail
// records from a crash are discarded — they were un-synced and thus
// un-acked). Compaction is crash-safe: the surviving suffix is written
// to <path>.compact and renamed over the original. Exposed as a flat C
// API for ctypes.
//
// Build: make -C native   ->  libstablestore.so

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>
#include <mutex>

#include <fcntl.h>
#include <unistd.h>
#include <sys/stat.h>

namespace {

constexpr uint64_t kMagic = 0x52505353544f5231ull;  // "RPSSTOR1"

struct Store {
  int fd = -1;
  std::string path;
  uint64_t base = 0;              // absolute index of offsets[0]
  uint64_t data_start = 0;        // file offset of the first record
  std::vector<uint64_t> offsets;  // file offset of each record's header
  uint64_t end = 0;               // valid data end (scan watermark)
  std::mutex mu;
};

bool read_exact(int fd, void* buf, size_t n, uint64_t off) {
  size_t done = 0;
  while (done < n) {
    ssize_t r = pread(fd, static_cast<char*>(buf) + done, n - done,
                      static_cast<off_t>(off + done));
    if (r <= 0) return false;
    done += static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  size_t done = 0;
  while (done < n) {
    ssize_t r = write(fd, static_cast<const char*>(buf) + done, n - done);
    if (r < 0) return false;
    done += static_cast<size_t>(r);
  }
  return true;
}

}  // namespace

extern "C" {

// Open (creating if absent) and index the store. Returns NULL on error.
void* ss_open(const char* path) {
  int fd = open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  auto* s = new Store;
  s->fd = fd;
  s->path = path;
  struct stat st;
  if (fstat(fd, &st) != 0) { delete s; close(fd); return nullptr; }
  uint64_t size = static_cast<uint64_t>(st.st_size), off = 0;
  if (size >= 16) {
    uint64_t magic = 0, base = 0;
    if (read_exact(fd, &magic, 8, 0) && magic == kMagic &&
        read_exact(fd, &base, 8, 8)) {
      s->base = base;
      off = 16;
    }
  } else if (size == 0) {
    // fresh store: stamp the header so compaction can persist a base
    uint64_t hdr[2] = {kMagic, 0};
    if (write_exact(fd, hdr, 16)) off = 16;
  }
  s->data_start = off;
  while (off + 4 <= size) {
    uint32_t len;
    if (!read_exact(fd, &len, 4, off)) break;
    if (off + 4 + len > size) break;  // torn tail record: drop
    s->offsets.push_back(off);
    off += 4 + len;
  }
  s->end = off;
  if (off < size) {
    if (ftruncate(fd, static_cast<off_t>(off)) != 0) { /* keep going */ }
  }
  lseek(fd, static_cast<off_t>(off), SEEK_SET);
  return s;
}

// Append one record; returns its ABSOLUTE index, or -1 on error.
int64_t ss_append(void* h, const void* buf, uint32_t len) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  uint32_t l = len;
  if (!write_exact(s->fd, &l, 4) || !write_exact(s->fd, buf, len)) {
    // roll back a partial write so the file cursor and the offset index
    // stay consistent — a later successful append must land at s->end
    if (ftruncate(s->fd, static_cast<off_t>(s->end)) != 0) { /* best effort */ }
    lseek(s->fd, static_cast<off_t>(s->end), SEEK_SET);
    return -1;
  }
  s->offsets.push_back(s->end);
  s->end += 4 + len;
  return static_cast<int64_t>(s->base + s->offsets.size()) - 1;
}

// Append a PRE-FRAMED batch of records (([u32 len][len bytes])* — the
// same framing as the file itself): one write syscall for the whole
// batch instead of two per record. Validates the framing before
// touching the file; a partial write rolls back like ss_append.
// Returns the number of records appended, or -1.
int64_t ss_append_many(void* h, const void* buf, uint64_t len) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  const char* p = static_cast<const char*>(buf);
  uint64_t off = 0;
  int64_t n = 0;
  while (off + 4 <= len) {
    uint32_t l;
    memcpy(&l, p + off, 4);
    if (off + 4 + l > len) return -1;
    off += 4 + l;
    n++;
  }
  if (off != len) return -1;
  if (len && !write_exact(s->fd, buf, len)) {
    if (ftruncate(s->fd, static_cast<off_t>(s->end)) != 0) { /* best effort */ }
    lseek(s->fd, static_cast<off_t>(s->end), SEEK_SET);
    return -1;
  }
  off = 0;
  for (int64_t i = 0; i < n; i++) {
    uint32_t l;
    memcpy(&l, p + off, 4);
    s->offsets.push_back(s->end + off);
    off += 4 + l;
  }
  s->end += len;
  return n;
}

int ss_sync(void* h) {
  auto* s = static_cast<Store*>(h);
  return fdatasync(s->fd) == 0 ? 0 : -1;
}

// Total records ever appended (absolute): base + live.
int64_t ss_count(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return static_cast<int64_t>(s->base + s->offsets.size());
}

// Absolute index of the first RETAINED record (0 unless compacted).
int64_t ss_base(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return static_cast<int64_t>(s->base);
}

// Read record at ABSOLUTE idx into out (cap bytes). Returns record
// length (may exceed cap, in which case only cap bytes were copied), or
// -1 if out of range / compacted away.
int64_t ss_read(void* h, uint64_t idx, void* out, uint32_t cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (idx < s->base || idx - s->base >= s->offsets.size()) return -1;
  uint64_t off = s->offsets[idx - s->base];
  uint32_t len;
  if (!read_exact(s->fd, &len, 4, off)) return -1;
  uint32_t n = len < cap ? len : cap;
  if (n && !read_exact(s->fd, out, n, off + 4)) return -1;
  return static_cast<int64_t>(len);
}

// Total bytes of a full dump (the snapshot payload for joiner recovery).
// The dump is the raw file image, so a compacted store's dump CARRIES
// its base header — the receiving side restores the same absolute
// indexing.
int64_t ss_dump_len(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return static_cast<int64_t>(s->end);
}

// Serialize the whole store into out; returns bytes written or -1.
int64_t ss_dump(void* h, void* out, uint64_t cap) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (cap < s->end) return -1;
  if (s->end && !read_exact(s->fd, out, s->end, 0)) return -1;
  return static_cast<int64_t>(s->end);
}

// Append every record of a dump produced by ss_dump (joiner side). A
// headered dump's base is adopted IF this store is empty (the reset +
// load path); loading a based dump into a non-empty or already-based
// store is refused (-1) — appending those records would misalign the
// absolute indexing ss_read/replay depend on. Returns records loaded,
// or -1 on malformed input / base conflict.
int64_t ss_load(void* h, const void* buf, uint64_t len) {
  auto* s = static_cast<Store*>(h);
  const char* p = static_cast<const char*>(buf);
  uint64_t off = 0;
  if (len >= 16) {
    uint64_t magic, base;
    memcpy(&magic, p, 8);
    memcpy(&base, p + 8, 8);
    if (magic == kMagic) {
      off = 16;
      std::lock_guard<std::mutex> lk(s->mu);
      if (base != 0) {
        if (!s->offsets.empty() || s->base != 0) return -1;
        uint64_t hdr[2] = {kMagic, base};
        if (pwrite(s->fd, hdr, 16, 0) != 16) return -1;
        if (s->data_start == 0) {
          // legacy (headerless) empty file gained a header just now
          s->data_start = 16;
          s->end = 16;
          lseek(s->fd, 16, SEEK_SET);
        }
        s->base = base;
      }
    }
  }
  int64_t n = 0;
  while (off + 4 <= len) {
    uint32_t l;
    memcpy(&l, p + off, 4);
    if (off + 4 + l > len) return -1;
    if (ss_append(h, p + off + 4, l) < 0) return -1;
    off += 4 + l;
    n++;
  }
  return off == len ? n : -1;
}

// Discard ALL records and reset base to 0 (used before re-loading a
// snapshot dump so history is never duplicated by the append-only
// ss_load).
int ss_reset(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  uint64_t hdr[2] = {kMagic, 0};
  if (ftruncate(s->fd, 0) != 0) return -1;
  lseek(s->fd, 0, SEEK_SET);
  if (!write_exact(s->fd, hdr, 16)) return -1;
  s->offsets.clear();
  s->base = 0;
  s->data_start = 16;
  s->end = 16;
  return 0;
}

// Drop every record below ABSOLUTE index upto (their effects must be
// covered by an app-state checkpoint taken at upto). Crash-safe: the
// surviving suffix is written to <path>.compact, fsynced, and renamed
// over the original — a crash leaves either the old or the new file.
// Returns the new base, or -1.
int64_t ss_compact(void* h, uint64_t upto) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  if (upto <= s->base) return static_cast<int64_t>(s->base);
  uint64_t live = s->offsets.size();
  uint64_t drop = upto - s->base;
  if (drop > live) return -1;           // cannot compact unwritten history
  std::string tmp = s->path + ".compact";
  int nfd = open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (nfd < 0) return -1;
  uint64_t hdr[2] = {kMagic, upto};
  uint64_t keep_from = drop < live ? s->offsets[drop] : s->end;
  uint64_t tail = s->end - keep_from;
  bool ok = true;
  {
    size_t done = 0;
    ok = (pwrite(nfd, hdr, 16, 0) == 16);
    std::vector<char> cbuf(1 << 20);
    while (ok && done < tail) {
      size_t chunk = tail - done < cbuf.size() ? tail - done : cbuf.size();
      ok = read_exact(s->fd, cbuf.data(), chunk, keep_from + done) &&
           pwrite(nfd, cbuf.data(), chunk,
                  static_cast<off_t>(16 + done)) ==
               static_cast<ssize_t>(chunk);
      done += chunk;
    }
  }
  ok = ok && fdatasync(nfd) == 0;
  close(nfd);
  if (!ok) {
    unlink(tmp.c_str());
    return -1;
  }
  // reopen BEFORE the rename: if this open fails, compaction aborts
  // with the original file still in place — renaming first and then
  // failing to reopen would leave the process writing acked records
  // into an orphaned inode
  int fd = open(tmp.c_str(), O_RDWR);
  if (fd < 0 || rename(tmp.c_str(), s->path.c_str()) != 0) {
    if (fd >= 0) close(fd);
    unlink(tmp.c_str());
    return -1;
  }
  close(s->fd);
  s->fd = fd;
  // rebuild the in-memory index against the new layout
  uint64_t shift = keep_from - 16;
  std::vector<uint64_t> noff;
  for (uint64_t i = drop; i < live; i++)
    noff.push_back(s->offsets[i] - shift);
  s->offsets.swap(noff);
  s->base = upto;
  s->data_start = 16;
  s->end = 16 + tail;
  lseek(s->fd, static_cast<off_t>(s->end), SEEK_SET);
  return static_cast<int64_t>(s->base);
}

void ss_close(void* h) {
  auto* s = static_cast<Store*>(h);
  close(s->fd);
  delete s;
}

}  // extern "C"
