/* toyserver — a deliberately unmodified, plain-libc TCP key-value server.
 *
 * Plays the role of the reference's pristine Redis/memcached builds
 * (apps/redis/mk): the e2e tests replicate it via LD_PRELOAD=interpose.so
 * without it knowing. Protocol (newline-framed, one request per line):
 *   SET <key> <value>\n  -> +OK\n
 *   GET <key>\n          -> <value>\n or -\n
 *   DEL <key>\n          -> +OK\n
 *   COUNT\n              -> <n>\n
 * Uses accept()/read()/write()/close() directly — the exact syscall
 * surface the shim hooks. Two serving modes:
 *   toyserver <port>      poll-based single thread (redis-style)
 *   toyserver <port> -t   thread-per-connection (memcached-style) — many
 *                         reads block in the shim's commit wait
 *                         concurrently, exercising its pipelining
 */
#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <poll.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#define MAXKV 131072            /* open-addressing table, power of two */
#define MAXC 64
#define BUFSZ 65536

/* Open-addressing hash KVS (linear probing, tombstone-free deletes by
 * backward-shift) so benchmark-scale key counts stay O(1) per op. */
static char keys[MAXKV][64], vals[MAXKV][256];
static unsigned char used[MAXKV];
static int nkv = 0;

static unsigned kv_hash(const char* k) {
  unsigned h = 2166136261u;
  while (*k) h = (h ^ (unsigned char)*k++) * 16777619u;
  return h & (MAXKV - 1);
}
static int kv_find(const char* k) {      /* slot of key, or -1 */
  for (unsigned i = kv_hash(k), n = 0; n < MAXKV;
       i = (i + 1) & (MAXKV - 1), n++) {
    if (!used[i]) return -1;
    if (!strcmp(keys[i], k)) return (int)i;
  }
  return -1;
}
static const char* kv_get(const char* k) {
  int i = kv_find(k);
  return i < 0 ? NULL : vals[i];
}
static void kv_set(const char* k, const char* v) {
  for (unsigned i = kv_hash(k), n = 0; n < MAXKV;
       i = (i + 1) & (MAXKV - 1), n++) {
    if (used[i] && !strcmp(keys[i], k)) {
      snprintf(vals[i], 256, "%s", v);
      return;
    }
    if (!used[i]) {
      if (nkv >= MAXKV - 1) return;      /* table full: drop */
      used[i] = 1;
      snprintf(keys[i], 64, "%s", k);
      snprintf(vals[i], 256, "%s", v);
      nkv++;
      return;
    }
  }
}
static void kv_del(const char* k) {
  int i = kv_find(k);
  if (i < 0) return;
  used[i] = 0;
  nkv--;
  /* re-insert the probe chain after the hole */
  for (unsigned j = (i + 1) & (MAXKV - 1); used[j];
       j = (j + 1) & (MAXKV - 1)) {
    used[j] = 0;
    nkv--;
    char kk[64], vv[256];
    memcpy(kk, keys[j], 64);
    memcpy(vv, vals[j], 256);
    kv_set(kk, vv);
  }
}

struct conn { int fd; char buf[BUFSZ]; int len; };

static pthread_mutex_t kv_mu = PTHREAD_MUTEX_INITIALIZER;

static void handle_line(int fd, char* line) {
  char out[512], k[64], v[256];
  pthread_mutex_lock(&kv_mu);
  if (sscanf(line, "SET %63s %255[^\n]", k, v) == 2) {
    kv_set(k, v);
    snprintf(out, sizeof out, "+OK\n");
  } else if (sscanf(line, "GET %63s", k) == 1) {
    const char* r = kv_get(k);
    snprintf(out, sizeof out, "%s\n", r ? r : "-");
  } else if (sscanf(line, "DEL %63s", k) == 1) {
    kv_del(k);
    snprintf(out, sizeof out, "+OK\n");
  } else if (sscanf(line, "ECHO %255s", v) == 1) {
    /* request/response no-op: the reply embeds the caller's token, so a
     * barrier probe can identify its own response among buffered
     * replies to earlier pipelined commands */
    snprintf(out, sizeof out, "=%s\n", v);
  } else if (!strncmp(line, "COUNT", 5)) {
    snprintf(out, sizeof out, "%d\n", nkv);
  } else if (!strncmp(line, "DUMPALL", 7)) {
    /* full-state listing: "<key> <value>\n" per pair, "." terminator —
     * the app-level snapshot hook bounded recovery uses (the analog of
     * redis BGSAVE producing an RDB: app state without event history) */
    for (unsigned i = 0; i < MAXKV; i++) {
      if (!used[i]) continue;
      char lineb[512];
      int ln = snprintf(lineb, sizeof lineb, "%s %s\n", keys[i], vals[i]);
      ssize_t w0 = write(fd, lineb, (size_t)ln);
      (void)w0;
    }
    snprintf(out, sizeof out, ".\n");
  } else {
    snprintf(out, sizeof out, "-ERR\n");
  }
  pthread_mutex_unlock(&kv_mu);
  ssize_t w = write(fd, out, strlen(out));
  (void)w;
}

/* ---- thread-per-connection mode ---- */
static void* conn_main(void* arg) {
  struct conn* c = (struct conn*)arg;
  c->len = 0;
  for (;;) {
    ssize_t n = read(c->fd, c->buf + c->len, (size_t)(BUFSZ - c->len - 1));
    if (n <= 0) break;
    c->len += (int)n;
    c->buf[c->len] = 0;
    char* start = c->buf;
    char* nl;
    while ((nl = strchr(start, '\n'))) {
      *nl = 0;
      handle_line(c->fd, start);
      start = nl + 1;
    }
    int rest = (int)(c->buf + c->len - start);
    memmove(c->buf, start, (size_t)rest);
    c->len = rest;
  }
  close(c->fd);
  free(c);
  return NULL;
}

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 7000;
  int threaded = argc > 2 && !strcmp(argv[2], "-t");
  int ls = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(ls, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a = {0};
  a.sin_family = AF_INET;
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  a.sin_port = htons((unsigned short)port);
  if (bind(ls, (struct sockaddr*)&a, sizeof a) != 0) { perror("bind"); return 1; }
  listen(ls, 64);
  fprintf(stderr, "toyserver listening on %d%s\n", port,
          threaded ? " (threaded)" : "");

  if (threaded) {
    for (;;) {
      int fd = accept(ls, NULL, NULL);
      if (fd < 0) continue;
      struct conn* c = (struct conn*)malloc(sizeof *c);
      if (!c) { close(fd); continue; }
      c->fd = fd;
      pthread_t thr;
      if (pthread_create(&thr, NULL, conn_main, c) != 0) {
        close(fd);
        free(c);
        continue;
      }
      pthread_detach(thr);
    }
  }

  struct conn cs[MAXC];
  for (int i = 0; i < MAXC; i++) cs[i].fd = -1;

  for (;;) {
    struct pollfd pfds[MAXC + 1];
    int idx[MAXC + 1], np = 0;
    pfds[np].fd = ls; pfds[np].events = POLLIN; idx[np++] = -1;
    for (int i = 0; i < MAXC; i++)
      if (cs[i].fd >= 0) {
        pfds[np].fd = cs[i].fd; pfds[np].events = POLLIN; idx[np++] = i;
      }
    if (poll(pfds, (nfds_t)np, -1) < 0) continue;
    for (int p = 0; p < np; p++) {
      if (!(pfds[p].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      if (idx[p] < 0) {
        int fd = accept(ls, NULL, NULL);
        if (fd < 0) continue;
        int i;
        for (i = 0; i < MAXC && cs[i].fd >= 0; i++) {}
        if (i == MAXC) { close(fd); continue; }
        cs[i].fd = fd; cs[i].len = 0;
      } else {
        struct conn* c = &cs[idx[p]];
        ssize_t n = read(c->fd, c->buf + c->len,
                         (size_t)(BUFSZ - c->len - 1));
        if (n <= 0) { close(c->fd); c->fd = -1; continue; }
        c->len += (int)n;
        c->buf[c->len] = 0;
        char* start = c->buf;
        char* nl;
        while ((nl = strchr(start, '\n'))) {
          *nl = 0;
          handle_line(c->fd, start);
          start = nl + 1;
        }
        int rest = (int)(c->buf + c->len - start);
        memmove(c->buf, start, (size_t)rest);
        c->len = rest;
      }
    }
  }
}
