#!/usr/bin/env python
"""Replicated-application benchmark — the ``benchmarks/run.sh`` analog.

Boots N replicas of the unmodified toyserver under LD_PRELOAD interposition
+ the in-process consensus driver, finds the leader (same '] LEADER' grep
contract as the reference, or the driver API), then drives a SET/GET
workload against the leader's app — measuring committed-op throughput and
client-visible latency percentiles end to end through the full stack:
client TCP -> app read() -> shim -> UDS -> consensus step -> quorum commit
-> ack -> app reply.

    python benchmarks/run_bench.py --replicas 3 --requests 2000 --clients 4
"""

import argparse
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def _measure_flag_overhead(flag, proof, cfg=None, *, n_replicas=3,
                           steps=300, per_step=8, payload=64,
                           warmup=10, repeats=3, fanout="psum",
                           make=None, after_step=None):
    """The shared compiled-step-flag A/B harness: drive the identical
    closed-loop workload through a flag-off and a flag-on
    ``SimCluster`` and compare committed-entry throughput. The two
    variants run ALTERNATING for ``repeats`` rounds and each variant
    scores its fastest round (host-load noise on a shared machine
    easily exceeds the effect being measured). ``proof(on_cluster,
    out)`` attaches the flag-specific evidence the row carries.
    Returns ``{"off": {...}, "on": {...}, "overhead_pct": ...}`` (the
    <5% acceptance target the overhead bench rows share).

    ``make(variant, cfg, n_replicas)`` overrides cluster construction
    (for overheads that are not a bare SimCluster flag — e.g. the
    repair controller) and ``after_step(variant, cluster)`` runs after
    every step, both rounds identical except the measured delta —
    the one methodology all overhead rows share."""
    import time as _t

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.runtime.sim import SimCluster

    if cfg is None:
        cfg = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                        batch_slots=16)
    blob = b"x" * payload
    clusters = {}
    for variant in ("off", "on"):
        if make is not None:
            c = make(variant, cfg, n_replicas)
        else:
            c = SimCluster(cfg, n_replicas, fanout=fanout,
                           **{flag: variant == "on"})
            c.run_until_elected(0)
        for _ in range(warmup):
            c.submit(0, blob)
            c.step()
            if after_step is not None:
                after_step(variant, c)
        clusters[variant] = c
    out = {v: dict(steps=steps, seconds=None, committed=None,
                   ops_per_sec=0.0) for v in clusters}
    for _ in range(repeats):
        for variant, c in clusters.items():
            base = int(c.last["commit"].max()) + c.rebased_total
            t0 = _t.perf_counter()
            for _ in range(steps):
                for _ in range(per_step):
                    c.submit(0, blob)
                c.step()
                if after_step is not None:
                    after_step(variant, c)
            dt = _t.perf_counter() - t0
            done = int(c.last["commit"].max()) + c.rebased_total - base
            ops = round(done / dt, 1)
            if ops > out[variant]["ops_per_sec"]:
                out[variant] = dict(steps=steps, seconds=round(dt, 4),
                                    committed=done, ops_per_sec=ops)
    proof(clusters["on"], out)
    off, on = out["off"]["ops_per_sec"], out["on"]["ops_per_sec"]
    out["overhead_pct"] = round((off - on) / off * 100, 2)
    return out


def measure_host_path(cfg=None, *, n_replicas=3, steps=40,
                      per_step=2000, payload=24, warmup=4, repeats=4,
                      scan_k=8):
    """The host-data-plane A/B on the engine closed loop (the
    ``_measure_flag_overhead`` methodology — prewarmed clusters,
    ALTERNATING best-of rounds, same core): identical burst-driven
    workload through

    * ``off`` — the scalar reference host loops (per-entry pack /
      decode / replay-plan) + the plain burst path (per-field stacked
      readback + standalone replay-fetch dispatches);
    * ``on``  — the vectorized window batch ops + the device-resident
      K-window scan tier (one consolidated readback, replay rows
      in-dispatch).

    Committed-entries/s per variant, the speedup, and the scan's
    dispatch accounting (scan vs fetch dispatches) ride the row."""
    import time as _t

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.runtime import hostpath
    from rdma_paxos_tpu.runtime.sim import SimCluster, cap_scan_tiers

    if cfg is None:
        # the small-SET geometry: 64-byte slots fit a redis-style SET
        # fragment, and the thin window keeps the XLA-CPU window
        # programs from drowning the host-path delta being measured
        cfg = LogConfig(n_slots=32768, slot_bytes=64,
                        window_slots=1024, batch_slots=1024)
    blob = b"x" * payload
    clusters = {}
    for variant in ("off", "on"):
        c = SimCluster(cfg, n_replicas, fanout="psum")
        cap_scan_tiers(c, scan_k)
        c.run_until_elected(0)
        c.scan = variant == "on"   # prewarm compiles the ON tiers too
        c.prewarm()
        for _ in range(warmup):
            c.submit_many(0, [(3, 1, 0, blob)] * per_step)
            c.step_burst()
        clusters[variant] = c
    out = {v: dict(steps=steps, seconds=None, committed=None,
                   ops_per_sec=0.0) for v in clusters}
    for _ in range(repeats):
        for variant, c in clusters.items():
            hostpath.set_vectorized(variant == "on")
            base = int(c.last["commit"].max()) + c.rebased_total
            t0 = _t.perf_counter()
            for _ in range(steps):
                c.submit_many(0, [(3, 1, 0, blob)] * per_step)
                c.step_burst()
            while (int(c.last["commit"].min())
                   < int(c.last["end"].max())):
                c.step_burst()
            dt = _t.perf_counter() - t0
            done = (int(c.last["commit"].max()) + c.rebased_total
                    - base)
            ops = round(done / dt, 1)
            if ops > out[variant]["ops_per_sec"]:
                out[variant] = dict(steps=steps, seconds=round(dt, 4),
                                    committed=done, ops_per_sec=ops)
    hostpath.set_vectorized(True)
    on_c = clusters["on"]
    out["scan"] = dict(scan_dispatches=int(on_c.scan_dispatches),
                       scan_k=max(on_c.K_TIERS))
    out["speedup"] = round(
        out["on"]["ops_per_sec"]
        / max(out["off"]["ops_per_sec"], 1e-9), 3)
    return out


def measure_governor(trace_shape="bursty", cfg=None, *, n_replicas=3,
                     ticks=400, seed=0, repeats=3, payload=24,
                     hi=None, scan=False):
    """The adaptive-dispatch A/B on the engine closed loop: one seeded
    arrival trace (``benchmarks/arrival_traces.py``) replayed
    IDENTICALLY through

    * every static geometry on the ladder — the serial single step
      and each burst tier cap K (each variant dispatches every tick,
      the driver-poll analog: an idle tick still costs a heartbeat
      dispatch, which is exactly the idle bias being measured); and
    * the governed variant — the :class:`DispatchGovernor` picks the
      tier per tick, skips the dispatch entirely on idle ticks
      (quiescence), and holds admission for a bounded beat when the
      window is filling (coalescing).

    Alternating best-of rounds (the shared A/B methodology). Emitted:
    ``governor_speedup`` = governed committed-ops/s over the BEST
    single static geometry for this trace, and ``governor_p99_ratio``
    = governed per-entry commit-latency p99 over that same best
    static variant's (<= 1.1 acceptance: throughput is never bought
    with latency). The governed cluster's ``governor_tier`` trace
    events ride the result (the CI failure artifact)."""
    import collections as _coll
    import time as _t

    from benchmarks.arrival_traces import make_trace
    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.runtime.governor import attach_governor
    from rdma_paxos_tpu.runtime.sim import SimCluster

    if cfg is None:
        cfg = LogConfig(n_slots=4096, slot_bytes=64, window_slots=256,
                        batch_slots=64)
    B = cfg.batch_slots
    arrivals = make_trace(trace_shape, ticks, seed=seed, lo=0,
                          hi=(hi or 3 * B))
    total_entries = sum(arrivals)
    blob = b"x" * payload

    clusters = {}
    variants = ["serial"] + [f"burst{k}" for k in SimCluster.K_TIERS]
    for v in variants + ["governed"]:
        c = SimCluster(cfg, n_replicas, fanout="psum", scan=scan)
        c.run_until_elected(0)
        gov = None
        if v == "governed":
            c.obs = Observability()
            gov = attach_governor(c, obs=c.obs)
        c.prewarm()
        clusters[v] = (c, gov)

    def committed(c):
        return int(c.last["commit"].max()) + c.rebased_total

    def run_round(v):
        c, gov = clusters[v]
        base = committed(c)
        submitted = 0
        waiting = _coll.deque()    # (abs target index, t_submit, n)
        lats = []                  # (latency_s, n)
        coalesce_run = 0

        def harvest():
            done = committed(c) - base
            now = _t.perf_counter()
            while waiting and waiting[0][0] <= done:
                tgt, ts, n = waiting.popleft()
                lats.append((now - ts, n))

        def dispatch():
            nonlocal coalesce_run
            coalesce_run = 0
            if v == "serial":
                c.step()
            elif v == "governed":
                d = gov.decision
                if d.max_k > 1 and len(c.pending[0]):
                    c.step_burst(max_k=d.max_k)
                else:
                    c.step()
            else:
                k = int(v[len("burst"):])
                if len(c.pending[0]):
                    c.step_burst(max_k=k)
                else:
                    c.step()        # idle heartbeat dispatch
            harvest()

        t0 = _t.perf_counter()
        for n in arrivals:
            if n:
                c.submit_many(0, [(3, 1, 0, blob)] * n)
                submitted += n
                waiting.append((submitted, _t.perf_counter(), n))
            if v == "governed":
                backlog = len(c.pending[0])
                if backlog == 0 and not waiting:
                    continue        # idle quiescence: no dispatch
                d = gov.decision
                if (d.coalesce_us > 0 and coalesce_run < 3
                        and 0 < backlog < d.max_k * B // 2):
                    coalesce_run += 1
                    continue        # bounded admission coalesce
            dispatch()
        while committed(c) - base < submitted:
            dispatch()
        dt = _t.perf_counter() - t0
        weight = sum(n for _, n in lats)
        p99 = 0.0
        if weight:
            need = 0.99 * weight
            cum = 0
            for lat, n in sorted(lats):
                cum += n
                if cum >= need:
                    p99 = lat
                    break
        return dict(ops_per_sec=round(submitted / dt, 1),
                    seconds=round(dt, 4), committed=submitted,
                    p99_s=round(p99, 6))

    out = {v: dict(ops_per_sec=0.0) for v in variants + ["governed"]}
    for _ in range(repeats):
        for v in variants + ["governed"]:
            row = run_round(v)
            if row["ops_per_sec"] > out[v]["ops_per_sec"]:
                out[v] = row
    best_v = max(variants, key=lambda v: out[v]["ops_per_sec"])
    gov_row, best = out["governed"], out[best_v]
    c, gov = clusters["governed"]
    events = [e.as_dict() for e in c.obs.trace.events()
              if e.kind.startswith("governor")]
    return dict(
        trace=trace_shape, seed=seed, ticks=ticks,
        entries=total_entries,
        governed=gov_row, best_static=dict(variant=best_v, **best),
        all_static={v: out[v] for v in variants},
        governor=gov.status(),
        governor_events=events,
        governor_speedup=round(
            gov_row["ops_per_sec"]
            / max(best["ops_per_sec"], 1e-9), 3),
        governor_p99_ratio=round(
            gov_row["p99_s"] / max(best["p99_s"], 1e-9), 3))


def measure_audit_overhead(cfg=None, **kw):
    """A/B the compiled-step digest chain (``audit=``); the proof is
    the ON cluster's ledger summary — the workload ran digest-checked
    (the <5% acceptance target for the ``--audit`` bench row)."""
    def proof(on_c, out):
        out["audit"] = on_c.auditor.summary()
    return _measure_flag_overhead("audit", proof, cfg, **kw)


def measure_telemetry_overhead(cfg=None, **kw):
    """A/B the compiled-step device-counter vector (``telemetry=``);
    the proof is the ON cluster's device-counter totals — the counters
    flowed (the <5% acceptance target for the ``--telemetry`` bench
    row)."""
    def proof(on_c, out):
        from rdma_paxos_tpu.obs import device as device_mod
        out["device_counters"] = {
            name: [int(v) for v in
                   on_c.device_counters[:, device_mod.INDEX[name]]]
            for name in device_mod.NAMES}
    return _measure_flag_overhead("telemetry", proof, cfg, **kw)


def measure_export_overhead(cfg=None, *, sample_period_s=0.25,
                            scrape_period_s=0.5, **kw):
    """A/B the whole ops-plane host addition (the <2% acceptance
    target): the ON variant samples the registry into a
    TimeSeriesStore + evaluates the full default rule set (burn-rate
    SLO rules included) on the drivers' 0.25 s alert cadence AND
    answers a live ``/metrics`` scrape every ``scrape_period_s`` —
    the production configuration, measured wall-cadenced exactly as
    the drivers run it. The OFF variant is the bare cluster.
    Alternating best-of rounds, the shared methodology."""
    import time as _time
    import urllib.request

    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
    from rdma_paxos_tpu.obs.export import OpsExporter
    from rdma_paxos_tpu.obs.series import TimeSeriesStore
    from rdma_paxos_tpu.runtime.sim import SimCluster

    handles = {}

    def make(variant, cfg, n_replicas):
        c = SimCluster(cfg, n_replicas, fanout="psum")
        c.obs = Observability()
        c.run_until_elected(0)
        if variant == "on":
            store = TimeSeriesStore(capacity=256)
            eng = AlertEngine(c.obs.metrics, rules=default_rules(),
                              series=store)
            exp = OpsExporter(registry=c.obs.metrics, alerts=eng,
                              series=store,
                              health_fn=lambda: dict(ok=True)).start()
            handles[id(c)] = dict(store=store, eng=eng, exp=exp,
                                  n=0, scrapes=0,
                                  t_sample=float("-inf"),
                                  t_scrape=float("-inf"))
        return c

    def after_step(variant, c):
        h = handles.get(id(c))
        if h is None:
            return
        h["n"] += 1
        now = _time.monotonic()
        if now - h["t_sample"] >= sample_period_s:
            h["t_sample"] = now
            snap = c.obs.metrics.snapshot()
            h["store"].sample(snap, step=h["n"])
            h["eng"].evaluate(snap=snap)
        if now - h["t_scrape"] >= scrape_period_s:
            h["t_scrape"] = now
            urllib.request.urlopen(h["exp"].url + "/metrics",
                                   timeout=10).read()
            h["scrapes"] += 1

    def proof(on_c, out):
        h = handles[id(on_c)]
        out["export"] = dict(samples=h["store"].samples,
                             series=len(h["store"].names()),
                             rule_evals=h["eng"].evals,
                             scrapes=h["scrapes"])
        h["exp"].close()

    return _measure_flag_overhead("export", proof, cfg, make=make,
                                  after_step=after_step, **kw)


def measure_trace_overhead(cfg=None, *, sample_every=64, **kw):
    """A/B the causal-tracing plane end to end (the <2% acceptance
    target): identical closed-loop workloads where every step ALSO
    issues one stamped client-session put (the path that begins
    spans), with span sampling at the production default (ON,
    ``sample_every`` + a TraceContext attached) vs disabled (OFF,
    ``sample_every=0`` — the one switch that silences spans AND
    subsystem traces). Alternating best-of rounds, the shared
    methodology; the ON row carries the span/trace counts as proof
    that tracing actually ran."""
    from rdma_paxos_tpu.models.replicated_kvs import (ClientSession,
                                                      ReplicatedKVS)
    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.obs.spans import SpanRecorder
    from rdma_paxos_tpu.runtime.sim import SimCluster

    sessions = {}

    def make(variant, mcfg, n_replicas):
        c = SimCluster(mcfg, n_replicas, fanout="psum")
        c.obs = Observability(span_recorder=SpanRecorder(
            sample_every=(sample_every if variant == "on" else 0)))
        c.run_until_elected(0)
        sessions[id(c)] = ClientSession(ReplicatedKVS(c), client_id=7)
        return c

    def after_step(variant, c):
        s = sessions[id(c)]
        s.put(0, b"tk%03d" % (s.req_id % 512), b"v")
        # the drivers' ack-release tail (a no-op with sampling off):
        # retires acked spans so steady-state open_count stays
        # bounded, exactly as production runs it
        c.obs.spans.ack_release(0, s.req_id - 1)

    def proof(on_c, out):
        out["trace"] = dict(sample_every=sample_every,
                            spans=on_c.obs.spans.counts(),
                            traces=on_c.obs.tracectx.counts())

    return _measure_flag_overhead("trace", proof, cfg, make=make,
                                  after_step=after_step, **kw)


def measure_repair(cfg=None, *, n_replicas=3, steps=300, per_step=8,
                   payload=64, warmup=10, repeats=3,
                   corrupt_after=40, probation=6, mttr_budget=400):
    """The self-healing bench pair (``--repair``):

    * ``repair_overhead_pct`` — identical closed-loop workload through
      an audited cluster WITHOUT vs WITH a ``RepairController``
      attached (clean run: the controller's per-step findings scan is
      the overhead), ALTERNATING best-of rounds — the PR 5 audit A/B
      methodology.
    * ``mttr_steps`` — a scripted single-bit corruption of a
      follower's committed slot, then the full
      detect → quarantine → digest-verified re-install → backfill →
      re-admit loop, measured in PROTOCOL STEPS from the corrupting
      step to re-admission (step-domain: deterministic, host-load
      independent).
    """
    from rdma_paxos_tpu.chaos.faults import corrupt_slot
    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.runtime.repair import RepairController
    from rdma_paxos_tpu.runtime.sim import SimCluster

    if cfg is None:
        cfg = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                        batch_slots=16)
    blob = b"x" * payload
    ctls = {}

    # A/B rides the SHARED harness — only construction (controller
    # attached; fanout="gather" because quarantine isolation is a
    # peer-mask cut) and the per-step controller tick differ
    def make(variant, mcfg, n_rep):
        c = SimCluster(mcfg, n_rep, fanout="gather", audit=True)
        c.run_until_elected(0)
        if variant == "on":
            ctls[variant] = RepairController(
                c, probation_steps=probation)
        return c

    def after_step(variant, c):
        ctl = ctls.get(variant)
        if ctl is not None:
            ctl.observe()
            if ctl.needs_drain():
                ctl.drive()

    out = _measure_flag_overhead(
        "repair", lambda on_c, o: None, cfg, n_replicas=n_replicas,
        steps=steps, per_step=per_step, payload=payload,
        warmup=warmup, repeats=repeats, make=make,
        after_step=after_step)

    # --- MTTR round: scripted corruption, loop until re-admitted ---
    c = make("mttr", cfg, n_replicas)
    ctl = RepairController(c, probation_steps=probation)
    for _ in range(corrupt_after):
        c.submit(0, blob)
        c.step()
        ctl.observe()
    victim = 2
    target = int(c.last["commit"].min()) - 1
    corrupt_slot(c, victim, target)
    corrupt_step = c.step_index
    detected = quarantined = readmitted = None
    for _ in range(mttr_budget):
        c.submit(0, blob)
        c.step()
        ctl.observe()
        if detected is None and c.auditor.findings:
            detected = c.step_index
        if quarantined is None and ctl.states:
            quarantined = c.step_index
        if ctl.needs_drain():
            ctl.drive()
        if quarantined is not None and not ctl.states:
            readmitted = c.step_index
            break
    out["mttr"] = dict(
        corrupt_step=corrupt_step, detected_step=detected,
        quarantined_step=quarantined, readmitted_step=readmitted,
        mttr_steps=(readmitted - corrupt_step
                    if readmitted is not None else None),
        detection_steps=(detected - corrupt_step
                         if detected is not None else None),
        repairs_done=ctl.repairs_done,
        donors_rejected=ctl.donors_rejected,
        backfilled=c.auditor.backfilled,
        coverage_ok=(c.auditor.coverage(
            0, c.auditor.repairs[0]["lo"],
            c.auditor.repairs[0]["hi"])["ok"]
            if c.auditor.repairs else False),
        probation_steps=probation)
    return out


def measure_read_mix(read_ratio=0.9, cfg=None, *, n_replicas=3,
                     n_ops=3000, n_keys=32, repeats=3, seed=11,
                     payload=24):
    """The read-scaling A/B (``--read-ratio``): drive the IDENTICAL
    seeded read/write mix through two same-geometry clusters —

    * ``lease``  — reads served host-side by the leaseholder
      (``runtime/reads.py``): zero log traffic, batched local table
      lookups (``get_many``), writes ride the ring as usual;
    * ``log``    — the pre-lease baseline: every read rides the
      replicated log as a stamped ``OP_GET`` entry (appended,
      quorum-acked, committed, folded), competing with writes for
      ring slots and committed-ops bandwidth.

    Rounds ALTERNATE and each variant scores its fastest (the PR 5/6
    best-of methodology). The proof carried by the row: the lease
    variant's ``reads_served_total{path=lease}`` accounts for every
    read it claims, and both variants completed the same op mix."""
    import random as _random
    import time as _t

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.runtime import reads as reads_mod
    from rdma_paxos_tpu.runtime.reads import count_read
    from rdma_paxos_tpu.runtime.sim import SimCluster

    if cfg is None:
        cfg = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                        batch_slots=16)
    keys = [b"rk%d" % i for i in range(n_keys)]
    blob = b"x" * payload
    B = cfg.batch_slots
    CID = 5
    setups = {}
    for variant in ("log", "lease"):
        c = SimCluster(cfg, n_replicas, fanout="psum")
        c.obs = Observability()
        if variant == "lease":
            reads_mod.attach(c)
        c.run_until_elected(0)
        kv = ReplicatedKVS(c, cap=4096)
        # seed the keyspace so every GET hits a live value
        for i, k in enumerate(keys):
            kv.put(0, k, b"seed", client_id=CID, req_id=i + 1)
        while kv.last_req[0].get(CID, 0) < n_keys:
            c.step()
            kv._fold(0)
        # compile the batched-GET tiers outside the timed rounds (a
        # first-use JIT pause inside a round is not read cost)
        for t in (16, 64, 256, 512):
            kv.get_many(0, (keys * (t // n_keys + 1))[:t])
        setups[variant] = dict(c=c, kv=kv,
                               req=n_keys)   # stamped-req high water

    def run_round(variant, rep):
        c, kv = setups[variant]["c"], setups[variant]["kv"]
        rng = _random.Random(f"readmix:{seed}:{rep}")
        ops = [("r" if rng.random() < read_ratio else "w",
                rng.randrange(n_keys)) for _ in range(n_ops)]
        total_r = sum(1 for k, _ in ops if k == "r")
        total_w = n_ops - total_r
        req = setups[variant]["req"]
        pend_w: set = set()
        pend_r: dict = {}
        lease_batch: list = []
        reads_done = writes_done = 0
        steps = 0
        i = 0
        t0 = _t.perf_counter()
        while reads_done < total_r or writes_done < total_w:
            budget = B
            while i < len(ops) and budget > 0:
                kind, ki = ops[i]
                if kind == "w":
                    req += 1
                    kv.put(0, keys[ki], blob, client_id=CID,
                           req_id=req)
                    pend_w.add(req)
                    budget -= 1
                elif variant == "log":
                    req += 1
                    kv.submit_get(0, keys[ki], client_id=CID,
                                  req_id=req)
                    pend_r[req] = ki
                    budget -= 1
                else:
                    lease_batch.append(keys[ki])    # host-side: free
                i += 1
            if lease_batch:
                lm = c.leases
                assert lm is not None and lm.valid(0, 0), \
                    "leaseholder lost its lease mid-bench"
                kv.get_many(0, lease_batch)
                count_read(c.obs, "lease", 0, n=len(lease_batch))
                reads_done += len(lease_batch)
                lease_batch = []
            if writes_done < total_w or (variant == "log"
                                         and reads_done < total_r):
                c.step()
                steps += 1
                kv._fold(0)
                mark = kv.last_req[0].get(CID, 0)
                done_w = [q for q in pend_w if q <= mark]
                for q in done_w:
                    pend_w.discard(q)
                writes_done += len(done_w)
                done_r = [q for q in pend_r if q <= mark]
                if done_r:
                    kv.get_many(0, [keys[pend_r.pop(q)]
                                    for q in done_r])
                    count_read(c.obs, "log", 0, n=len(done_r))
                    reads_done += len(done_r)
        dt = _t.perf_counter() - t0
        setups[variant]["req"] = req
        return dict(seconds=round(dt, 4), steps=steps,
                    reads=reads_done, writes=writes_done,
                    read_ops_per_sec=round(reads_done / dt, 1),
                    write_ops_per_sec=round(writes_done / dt, 1),
                    total_ops_per_sec=round(n_ops / dt, 1))

    best = {v: None for v in setups}
    for rep in range(repeats):
        for variant in ("log", "lease"):
            r = run_round(variant, rep)
            if best[variant] is None or (r["read_ops_per_sec"]
                                         > best[variant]
                                         ["read_ops_per_sec"]):
                best[variant] = r
    from rdma_paxos_tpu.runtime.reads import read_counts
    out = dict(read_ratio=read_ratio, n_ops=n_ops, repeats=repeats,
               lease=best["lease"], log=best["log"],
               lease_read_speedup=round(
                   best["lease"]["read_ops_per_sec"]
                   / max(best["log"]["read_ops_per_sec"], 1e-9), 2),
               accounting=dict(
                   lease_variant=read_counts(setups["lease"]["c"].obs),
                   log_variant=read_counts(setups["log"]["c"].obs)),
               leases=setups["lease"]["c"].leases.status())
    return out


def measure_watch_mix(watch_ratio=0.5, cfg=None, *, n_replicas=3,
                      n_ops=2000, n_keys=32, n_watchers=4,
                      repeats=3, seed=11, payload=24,
                      cdc_dir=None):
    """The streams fan-out A/B (``--watch-ratio``): drive the
    IDENTICAL seeded write workload through two same-geometry
    clusters —

    * ``plain``    — no streams hub (the bare engine);
    * ``attached`` — the streams hub attached with ``n_watchers``
      subscribers each watching the first ``watch_ratio`` of the
      keyspace, plus a CDC JSONL sink, drained concurrently.

    Rounds ALTERNATE and each variant scores its fastest committed
    write throughput (the PR 5/6 best-of methodology). The row's
    claim: the whole streams surface — tail snapshots, pump decode,
    fan-out, CDC export — costs <3% committed-write throughput (it
    never enters the dispatch path; the engine only kicks a condition
    variable), while ``watch_fanout_events_per_sec`` reports the
    delivery rate and ``cdc_lag_entries`` the sink's distance from
    the committed frontier after the end-of-round flush (0 = the
    exporter kept up)."""
    import os as _os
    import random as _random
    import tempfile as _tempfile
    import time as _t

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.runtime.sim import SimCluster
    from rdma_paxos_tpu import streams as streams_mod

    if cfg is None:
        cfg = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                        batch_slots=16)
    keys = [b"wk%02d" % i for i in range(n_keys)]
    cut = max(1, min(n_keys, round(watch_ratio * n_keys)))
    blob = b"x" * payload
    B = cfg.batch_slots
    CID = 6
    if cdc_dir is None:
        cdc_dir = _tempfile.mkdtemp(prefix="watchmix")
    setups = {}
    for variant in ("plain", "attached"):
        c = SimCluster(cfg, n_replicas, fanout="psum")
        c.obs = Observability()
        entry = dict(c=c, req=0, subs=(), hub=None)
        if variant == "attached":
            hub = streams_mod.attach(
                c, cdc_path=_os.path.join(cdc_dir, "cdc.jsonl"))
            entry["hub"] = hub
            entry["subs"] = [
                hub.subscribe(0, lo=keys[0],
                              hi=None if cut >= n_keys else keys[cut])
                for _ in range(n_watchers)]
        c.run_until_elected(0)
        entry["kv"] = ReplicatedKVS(c, cap=4096)
        setups[variant] = entry

    def run_round(variant, rep):
        ent = setups[variant]
        c, kv, subs = ent["c"], ent["kv"], ent["subs"]
        rng = _random.Random(f"watchmix:{seed}:{rep}")
        order = [rng.randrange(n_keys) for _ in range(n_ops)]
        req = ent["req"]
        pend: set = set()
        done = steps = events = 0
        i = 0
        t0 = _t.perf_counter()
        while done < n_ops:
            budget = B
            while i < len(order) and budget > 0:
                req += 1
                kv.put(0, keys[order[i]], blob, client_id=CID,
                       req_id=req)
                pend.add(req)
                i += 1
                budget -= 1
            c.step()
            steps += 1
            kv._fold(0)
            mark = kv.last_req[0].get(CID, 0)
            done_now = [q for q in pend if q <= mark]
            for q in done_now:
                pend.discard(q)
            done += len(done_now)
            for s in subs:
                events += len(s.poll(max_n=1024))
        dt = _t.perf_counter() - t0
        ent["req"] = req
        hub = ent["hub"]
        lag = 0
        if hub is not None:
            # flush: the pump drains asynchronously — wait it out so
            # the fan-out count covers every committed write and the
            # reported CDC lag is the exporter's true residue
            target = hub.tails[0].length()
            deadline = _t.monotonic() + 10
            while (hub.watch.cursors().get(0, 0) < target
                   and _t.monotonic() < deadline):
                _t.sleep(0.002)
            for s in subs:
                events += len(s.poll(max_n=1 << 16))
            lag = max(0, target - hub.watch.cursors().get(0, 0))
        dt_total = _t.perf_counter() - t0
        return dict(seconds=round(dt, 4), steps=steps, writes=done,
                    write_ops_per_sec=round(done / dt, 1),
                    events=events,
                    watch_fanout_events_per_sec=round(
                        events / dt_total, 1),
                    cdc_lag_entries=lag)

    best = {v: None for v in setups}
    for rep in range(repeats):
        for variant in ("plain", "attached"):
            r = run_round(variant, rep)
            if best[variant] is None or (r["write_ops_per_sec"]
                                         > best[variant]
                                         ["write_ops_per_sec"]):
                best[variant] = r
    hub = setups["attached"]["hub"]
    overhead = round(
        100.0 * (best["plain"]["write_ops_per_sec"]
                 - best["attached"]["write_ops_per_sec"])
        / max(best["plain"]["write_ops_per_sec"], 1e-9), 2)
    out = dict(watch_ratio=watch_ratio, n_ops=n_ops, n_keys=n_keys,
               n_watchers=n_watchers, watched_keys=cut,
               repeats=repeats, plain=best["plain"],
               attached=best["attached"],
               watch_attach_overhead_pct=overhead,
               cdc=dict(exported=hub.cdc.exported(0),
                        lag=best["attached"]["cdc_lag_entries"]),
               watch=hub.watch.status())
    hub.fail_all("bench end")
    return out


def measure_txn(cfg=None, *, n_replicas=3, n_groups=3, n_probe=12,
                n_ops=400, n_keys=48, repeats=3, seed=17):
    """The transaction bench (``--txn``), three claims on one
    ``txn=True`` sharded geometry:

    * **dispatch-count proof** — each cross-group 2PC commit (a
      put-pair spanning two groups) is driven serially to completion
      while counting ``ShardedCluster.dispatches``: the in-dispatch
      commit lane resolves prepare votes + the commit decision in ~2
      protocol dispatches (the classic coordinator pays 2 network
      round trips PER PHASE);
    * **commit latency vs single-key** — the same probe for a plain
      stamped single-key put (1 dispatch), reported as a ratio;
    * **mergeable throughput** — seeded A/B, rounds ALTERNATING and
      each variant keeping its fastest round (the PR 5/6 best-of
      methodology): ``merge`` drives INCR transactions through the
      coordinator's fast path, ``plain`` drives the identical count
      of stamped single-key puts over the same keys; the fast path
      skips prepare entirely (one MERGE record per write), so its
      committed throughput must hold ~1x plain (target >=0.9x).
    """
    import random as _random
    import time as _t

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.shard.cluster import ShardedCluster
    from rdma_paxos_tpu.shard.kvs import ShardedKVS
    from rdma_paxos_tpu.txn import attach_coordinator
    from rdma_paxos_tpu.txn.chaos import keys_for_groups

    if cfg is None:
        cfg = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                        batch_slots=16)
    shard = ShardedCluster(cfg, n_replicas, n_groups, txn=True)
    shard.obs = Observability()
    kv = ShardedKVS(shard, cap=4096)
    attach_coordinator(kv, timeout_steps=256)
    shard.place_leaders()
    G = shard.G
    B = cfg.batch_slots
    CID = 9

    pools = keys_for_groups(kv.router, n_probe + n_keys // G + 2,
                            prefix=b"txb")

    # ---- serial probes: dispatches + wall latency per commit ----
    def probe_2pc(i):
        ga, gb = i % G, (i + 1) % G
        ka = pools[ga][i]
        kb = pools[gb][i]
        d0, t0 = shard.dispatches, _t.perf_counter()
        h = kv.transact([("put", ka, b"a%d" % i),
                         ("put", kb, b"b%d" % i)])
        steps = 0
        while not h.done and steps < 64:
            shard.step()
            steps += 1
        assert h.committed, f"probe txn aborted: {h.abort_reason}"
        return shard.dispatches - d0, _t.perf_counter() - t0, steps

    req = [0] * G
    def probe_put(i):
        g = i % G
        key = pools[g][n_probe + 1]
        req[g] += 1
        conn = kv.conn_for(CID, g)
        d0, t0 = shard.dispatches, _t.perf_counter()
        kv.put(key, b"p%d" % i, client_id=CID, req_id=req[g])
        steps = 0
        while steps < 64:
            shard.step()
            steps += 1
            kv.groups[g]._fold(shard.leader_hint(g))
            if kv.groups[g].last_req[
                    shard.leader_hint(g)].get(conn, 0) >= req[g]:
                break
        return shard.dispatches - d0, _t.perf_counter() - t0, steps

    # warmup: compile the txn-lane program + settle leaders before
    # timing (the probes report steady-state dispatch counts)
    h = kv.transact([("put", pools[0][n_probe], b"w"),
                     ("put", pools[1][n_probe], b"w")])
    for _ in range(8):
        if h.done:
            break
        shard.step()
    for g in range(G):      # first fold compiles each group's apply
        kv.put(pools[g][n_probe], b"w", client_id=CID, req_id=1)
        req[g] = 1
    shard.step()
    for g in range(G):
        kv.groups[g]._fold(shard.leader_hint(g))

    twopc = [probe_2pc(i) for i in range(n_probe)]
    single = [probe_put(i) for i in range(n_probe)]
    mean = lambda xs: sum(xs) / len(xs)
    probe = dict(
        twopc=dict(dispatches=round(mean([d for d, _, _ in twopc]), 2),
                   seconds=round(mean([s for _, s, _ in twopc]), 5),
                   steps=round(mean([st for _, _, st in twopc]), 2)),
        single=dict(dispatches=round(mean([d for d, _, _ in single]), 2),
                    seconds=round(mean([s for _, s, _ in single]), 5),
                    steps=round(mean([st for _, _, st in single]), 2)))
    probe["latency_ratio"] = round(
        probe["twopc"]["seconds"]
        / max(probe["single"]["seconds"], 1e-9), 2)

    # ---- throughput A/B: mergeable fast path vs plain puts ----
    # one op in flight per key slot (64-way closed loop); merge keys
    # and plain keys are the same set, so routing and fold cost match
    mkeys = [pools[i % G][n_probe + 2 + i // G]
             for i in range(n_keys)]
    mreq = [0] * G

    def run_round(variant, rep):
        rng = _random.Random(f"txnbench:{seed}:{rep}")
        order = [rng.randrange(n_keys) for _ in range(n_ops)]
        slot_busy = [None] * n_keys      # handle | (g, req) in flight
        i = done = steps = 0
        t0 = _t.perf_counter()
        while done < n_ops:
            budget = B
            while i < len(order) and budget > 0:
                k = order[i]
                if slot_busy[k] is not None:
                    break               # keep per-key FIFO: wait
                key = mkeys[k]
                if variant == "merge":
                    slot_busy[k] = kv.transact([("incr", key, 1)])
                else:
                    g = kv.group_of(key)
                    mreq[g] += 1
                    kv.put(key, b"v%d" % i, client_id=CID + 1,
                           req_id=mreq[g])
                    slot_busy[k] = (g, mreq[g])
                i += 1
                budget -= 1
            shard.step()
            steps += 1
            marks = {}
            for k, st in enumerate(slot_busy):
                if st is None:
                    continue
                if variant == "merge":
                    if st.done:
                        assert st.committed
                        slot_busy[k] = None
                        done += 1
                else:
                    g, q = st
                    if g not in marks:
                        lead = shard.leader_hint(g)
                        kv.groups[g]._fold(lead)
                        marks[g] = kv.groups[g].last_req[lead]
                    if marks[g].get(kv.conn_for(CID + 1, g), 0) >= q:
                        slot_busy[k] = None
                        done += 1
        dt = _t.perf_counter() - t0
        return dict(seconds=round(dt, 4), steps=steps, writes=done,
                    write_ops_per_sec=round(done / dt, 1))

    best = {"plain": None, "merge": None}
    for rep in range(repeats):
        for variant in ("plain", "merge"):
            r = run_round(variant, rep)
            if (best[variant] is None
                    or r["write_ops_per_sec"]
                    > best[variant]["write_ops_per_sec"]):
                best[variant] = r
    ratio = round(best["merge"]["write_ops_per_sec"]
                  / max(best["plain"]["write_ops_per_sec"], 1e-9), 3)
    coord = shard.txn.health()
    return dict(n_groups=G, n_probe=n_probe, n_ops=n_ops,
                n_keys=n_keys, repeats=repeats, seed=seed,
                probe=probe, plain=best["plain"],
                merge=best["merge"], merge_throughput_ratio=ratio,
                coordinator=coord)


def client_worker(port, n, lat, tid, pipeline=1, retries=5):
    """Pipelined client (the redis-benchmark -P analog): P commands per
    write — the app's read() picks them up as ONE buffer, so they ride a
    single consensus event; latency is measured per pipelined batch.
    A severed connection (a refused event during leadership churn — the
    shim fails fast with -1 and the session drops) reconnects and
    retries the batch, bounded, exactly as a real client would."""
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    f = s.makefile("rb")
    done = 0
    while done < n:
        k = min(pipeline, n - done)
        t0 = time.perf_counter()
        try:
            s.sendall(b"".join(b"SET k%d-%d v%d\n" % (tid, done + i, i)
                               for i in range(k)))
            for _ in range(k):
                if f.readline().strip() != b"+OK":
                    raise OSError("severed mid-batch")
        except OSError:
            if retries <= 0:
                raise
            retries -= 1
            try:
                s.close()
            except OSError:
                pass
            time.sleep(0.2)
            s = socket.create_connection(("127.0.0.1", port), timeout=30)
            f = s.makefile("rb")
            continue                 # re-issue the same batch
        lat.append(time.perf_counter() - t0)
        done += k
    s.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--port-base", type=int, default=7600)
    ap.add_argument("--period", type=float, default=0.02)
    # log geometry (defaults = the historical run_bench shape; the
    # REDIS_r05 headline geometry is 8192/256/1024/1024)
    ap.add_argument("--n-slots", type=int, default=2048)
    ap.add_argument("--slot-bytes", type=int, default=512)
    ap.add_argument("--window-slots", type=int, default=256)
    ap.add_argument("--batch-slots", type=int, default=256)
    ap.add_argument("--pipeline", type=int, default=1,
                    help="commands per client batch (redis-benchmark -P)")
    ap.add_argument("--threaded-app", action="store_true",
                    help="run toyserver thread-per-connection (memcached"
                         "-style): each client's reads block in the shim "
                         "commit wait concurrently, exercising the "
                         "pipelined shim")
    ap.add_argument("--json", default=None,
                    help="append a JSON result line to this file")
    ap.add_argument("--metrics-json", default=None,
                    help="write the full obs metrics snapshot here "
                         "(default: <workdir>/metrics.json)")
    ap.add_argument("--trace", action="store_true",
                    help="causal tracing at 100%% sampling: every "
                         "command gets an end-to-end span; writes the "
                         "raw span dump and a Perfetto-loadable Chrome "
                         "trace next to the metrics snapshot")
    ap.add_argument("--trace-json", default=None,
                    help="Chrome trace output path (default: "
                         "<workdir>/trace.perfetto.json)")
    ap.add_argument("--groups", type=int, default=0,
                    help="sharded mode: with no e2e flags, delegate to "
                         "benchmarks/shard_bench.py (the multi-group "
                         "one-dispatch sim bench); with --e2e (or any "
                         "e2e flag) run the FULL app path against a "
                         "ShardedClusterDriver — clients spread over "
                         "all replicas, connections key-prefix-routed "
                         "onto G consensus groups")
    ap.add_argument("--e2e", action="store_true",
                    help="with --groups: force the sharded end-to-end "
                         "app path instead of the shard_bench sim sweep")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="driver dispatch-pipeline depth (encode batch "
                         "k+1 while batch k runs on the device; 0/1 = "
                         "fully serial loop)")
    ap.add_argument("--scan", type=int, default=0, metavar="K",
                    help="device-resident K-window scan tier: burst "
                         "dispatches run up to K fused protocol steps "
                         "and return ONE consolidated minimal readback "
                         "(scalar matrix + in-dispatch replay rows) — "
                         "the host pays one dispatch + one transfer "
                         "per K steps. K caps the fused tier "
                         "(2/4/8/16). 0 = off")
    ap.add_argument("--ab-hostpath", type=int, default=2,
                    help="with --scan: rounds per variant for the "
                         "host-path A/B (vectorized data plane + scan "
                         "tier ON vs scalar reference loops + scan "
                         "OFF; alternating best-of); emits the "
                         "host_path_speedup row with per-phase us "
                         "attribution. 0 disables")
    ap.add_argument("--ab-pipeline", type=int, default=2,
                    help="rounds per variant for the pipeline on/off "
                         "A/B (alternating best-of, the --audit "
                         "methodology); emits a pipeline_speedup row. "
                         "0 disables")
    ap.add_argument("--fence", action="store_true",
                    help="fence each device step with block_until_ready "
                         "so step-phase histograms attribute device-sync "
                         "time separately from dispatch (profiling mode; "
                         "serializes the dispatch pipeline)")
    ap.add_argument("--audit", action="store_true",
                    help="silent-divergence auditing: compile the "
                         "digest-chain step variants, run the cluster "
                         "audit ledger + flight recorder + SLO alerts "
                         "during the workload, and emit an "
                         "audit-overhead A/B row (digests on vs off)")
    ap.add_argument("--repair", action="store_true",
                    help="self-healing bench: after the e2e run, A/B "
                         "an audited cluster with vs without the "
                         "RepairController attached "
                         "(repair_overhead_pct, alternating best-of) "
                         "and measure the full corruption→quarantine→"
                         "verified-reinstall→backfill→re-admit loop "
                         "in protocol steps (mttr_steps)")
    ap.add_argument("--read-ratio", type=float, default=0.0,
                    help="read-mix workload: after the e2e run, A/B "
                         "the read-scaling paths at this read "
                         "fraction (e.g. 0.9 = 10:1 read-heavy) — "
                         "leader-lease host-side serving vs the "
                         "reads-through-log baseline on the same "
                         "core; emits read_ops_per_sec / "
                         "write_ops_per_sec / lease_read_speedup "
                         "rows with path accounting")
    ap.add_argument("--watch-ratio", type=float, default=0.0,
                    help="streams fan-out workload: after the e2e "
                         "run, A/B the identical seeded write mix "
                         "with vs without the streams hub attached "
                         "(watchers covering this keyspace fraction "
                         "plus a CDC sink) — emits "
                         "watch_fanout_events_per_sec / "
                         "cdc_lag_entries and a "
                         "watch_attach_overhead_pct row (target <3%%)")
    ap.add_argument("--txn", action="store_true",
                    help="transaction bench: serial dispatch-count "
                         "probes proving a cross-group 2PC commit "
                         "resolves in ~2 dispatches (vs 1 for a "
                         "single-key put), plus a seeded alternating "
                         "best-of A/B of mergeable INCR transactions "
                         "vs plain single-key puts — emits "
                         "txn_commit_dispatches / "
                         "txn_commit_latency_ratio / "
                         "txn_merge_throughput_ratio rows "
                         "(target >=0.9x)")
    ap.add_argument("--telemetry", action="store_true",
                    help="device telemetry: compile the counter-vector "
                         "step variants (obs/device.py), export "
                         "device_*{replica=} series during the "
                         "workload, and emit a telemetry_overhead_pct "
                         "A/B row (counters on vs off, target <5%%)")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="A/B the causal-tracing plane: span sampling "
                         "at the production default + TraceContext vs "
                         "sampling disabled, identical stamped-session "
                         "workloads — emits a trace_overhead_pct row "
                         "(target <2%%)")
    ap.add_argument("--profile", action="store_true",
                    help="bounded jax.profiler capture of the client "
                         "wave; writes the raw capture, a "
                         "program_report.json (per-variant flops / "
                         "bytes / memory), and — with --trace — ONE "
                         "merged Perfetto timeline: client spans + "
                         "host phases + device execution on shared "
                         "clock anchors")
    ap.add_argument("--profile-secs", type=float, default=60.0,
                    help="hard bound on the --profile capture")
    ap.add_argument("--governor", action="store_true",
                    help="adaptive-dispatch A/B (standalone — no e2e "
                         "stack): replay seeded arrival traces "
                         "(bursty/diurnal/step) through the governed "
                         "engine vs every static geometry, emitting "
                         "governor_speedup (>= 1.15x target on the "
                         "bursty trace) and governor_p99_ratio "
                         "(<= 1.1: latency never traded away) rows")
    ap.add_argument("--governor-ticks", type=int, default=400,
                    help="trace length in ticks (CI smoke uses a "
                         "small value)")
    ap.add_argument("--governor-shapes", default="bursty,diurnal,step",
                    help="comma-separated trace shapes to run")
    ap.add_argument("--governor-seed", type=int, default=0)
    ap.add_argument("--governor-repeats", type=int, default=3)
    ap.add_argument("--governor-trace", default=None, metavar="PATH",
                    help="write the governed runs' decision trace "
                         "(governor_* events) as JSON — the CI "
                         "failure artifact")
    ap.add_argument("--serve-metrics", nargs="?", const=0,
                    default=None, type=int, metavar="PORT",
                    help="serve the live ops endpoints (/metrics "
                         "/healthz /series /alerts) on this localhost "
                         "port for the whole run (no value = "
                         "ephemeral) — watch a long bench with the "
                         "fleet console or any Prometheus scraper; "
                         "also emits the export_overhead_pct A/B row "
                         "(series+rules+scrape on vs off, target "
                         "<2%%)")
    args = ap.parse_args()

    sharded_e2e = bool(args.groups) and (
        args.e2e or args.fence or args.audit or args.metrics_json
        or args.threaded_app or args.trace or args.trace_json
        or args.telemetry or args.profile
        or args.serve_metrics is not None)
    if args.groups and not sharded_e2e:
        # plain --groups N: the sharded SIM sweep (shard_bench owns its
        # own cluster lifecycle). Any e2e flag routes to the sharded
        # end-to-end path below instead.
        from benchmarks.shard_bench import main as shard_main
        fwd = ["--groups", str(args.groups),
               "--replicas", str(args.replicas)]
        if args.json:
            fwd += ["--json", args.json]
        return shard_main(fwd)
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/rp_jax_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.2")
    import jax
    if os.environ.get("RP_BENCH_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")

    if args.governor:
        # standalone mode (like plain --groups): the governor A/B is
        # an engine-closed-loop measurement — no app/proxy stack
        import json as _json

        from benchmarks.reporting import emit
        all_events = {}
        speedups = {}
        for shape in [s.strip() for s in
                      args.governor_shapes.split(",") if s.strip()]:
            gv = measure_governor(shape, ticks=args.governor_ticks,
                                  seed=args.governor_seed,
                                  repeats=args.governor_repeats)
            best = gv["best_static"]
            print(f"governor [{shape}]: "
                  f"{gv['governed']['ops_per_sec']} ops/s governed vs "
                  f"{best['ops_per_sec']} ops/s best static "
                  f"({best['variant']}) -> {gv['governor_speedup']}x, "
                  f"p99 {gv['governed']['p99_s'] * 1e3:.2f}ms vs "
                  f"{best['p99_s'] * 1e3:.2f}ms "
                  f"({gv['governor_p99_ratio']}x)")
            detail = {k: v for k, v in gv.items()
                      if k != "governor_events"}
            emit("governor_speedup", gv["governor_speedup"], "x",
                 detail=detail, json_path=args.json)
            emit("governor_p99_ratio", gv["governor_p99_ratio"], "x",
                 detail=dict(trace=shape,
                             governed_p99_s=gv["governed"]["p99_s"],
                             best_static_p99_s=best["p99_s"]),
                 json_path=args.json)
            all_events[shape] = gv["governor_events"]
            speedups[shape] = gv["governor_speedup"]
        if args.governor_trace:
            with open(args.governor_trace, "w") as f:
                _json.dump(dict(ticks=args.governor_ticks,
                                seed=args.governor_seed,
                                speedups=speedups,
                                events=all_events), f, indent=2)
            print(f"governor decision trace: {args.governor_trace}")
        return

    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    cfg = LogConfig(n_slots=args.n_slots, slot_bytes=args.slot_bytes,
                    window_slots=args.window_slots,
                    batch_slots=args.batch_slots)
    ports = [args.port_base + i for i in range(args.replicas)]
    wd = tempfile.mkdtemp(prefix="rp_bench_")
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)

    tcfg = TimeoutConfig(elec_timeout_low=0.5, elec_timeout_high=1.0)
    if args.profile:
        # the profiler multiplies host + dispatch cost on a shared
        # box; a 0.5 s election timer reads that as a dead leader and
        # churns mid-capture — widen so the capture observes SERVING,
        # not election storms (boot takes a few seconds longer)
        tcfg = TimeoutConfig(elec_timeout_low=5.0,
                             elec_timeout_high=8.0)
    if sharded_e2e:
        from rdma_paxos_tpu.runtime.sharded_driver import (
            ShardedClusterDriver)
        driver = ShardedClusterDriver(
            cfg, args.replicas, args.groups, workdir=wd,
            app_ports=ports, timeout_cfg=tcfg, fanout="psum",
            fence=args.fence, audit=args.audit,
            telemetry=args.telemetry, pipeline=args.pipeline_depth,
            scan=bool(args.scan))
    else:
        driver = ClusterDriver(
            cfg, args.replicas, workdir=wd, app_ports=ports,
            timeout_cfg=tcfg, fanout="psum", fence=args.fence,
            audit=args.audit, telemetry=args.telemetry,
            pipeline=args.pipeline_depth, scan=bool(args.scan))
    if args.scan:
        from rdma_paxos_tpu.runtime.sim import cap_scan_tiers
        try:
            cap_scan_tiers(driver.cluster, args.scan)
        except ValueError as e:
            raise SystemExit(f"--scan: {e}")
    if args.trace:
        # 100% sampling (the default is rate-limited); capacity sized
        # so a full run's spans are retained for the export
        driver.obs.spans.resize(max(args.requests * 2, 4096))
        driver.obs.spans.set_sample_every(1)
    if args.serve_metrics is not None:
        exp = driver.serve_metrics(args.serve_metrics)
        print(f"ops endpoints: {exp.url}/metrics  /healthz  /series  "
              f"/alerts  (fleet console: python -m "
              f"rdma_paxos_tpu.obs.console --scrape {exp.url})")
    print("prewarming step/burst compiles...")
    driver.prewarm()
    apps = []
    for r, port in enumerate(ports):
        env = dict(os.environ)
        env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
        env["RP_PROXY_SOCK"] = os.path.join(wd, f"proxy{r}.sock")
        cmd = [os.path.join(NATIVE, "toyserver"), str(port)]
        if args.threaded_app:
            cmd.append("-t")
        apps.append(subprocess.Popen(cmd, env=env,
                                     stderr=subprocess.DEVNULL))
    time.sleep(0.3)
    driver.run(period=args.period)
    t0 = time.time()
    while driver.leader() < 0:
        time.sleep(0.05)
        if time.time() - t0 > 120:
            raise SystemExit("no leader elected")
    lead = driver.leader()
    if sharded_e2e:
        print(f"all {args.groups} groups led: {driver.leaders()} "
              f"(in {time.time() - t0:.1f}s)")
    else:
        print(f"leader: replica {lead} "
              f"(elected in {time.time() - t0:.1f}s)")

    def port_for(tid: int) -> int:
        # sharded: every replica is a serving front-end — spread the
        # clients; each client tid keys k<tid>-..., so a connection's
        # whole keyspace shares one routing prefix (the client contract)
        if sharded_e2e:
            return ports[tid % args.replicas]
        return ports[lead]

    def run_wave(total: int):
        """One full client wave; returns (ops/s, sorted latencies)."""
        per_w = total // args.clients
        lats_w = [[] for _ in range(args.clients)]
        threads = [threading.Thread(target=client_worker,
                                    args=(port_for(i), per_w, lats_w[i],
                                          i, args.pipeline))
                   for i in range(args.clients)]
        t0_w = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt_w = time.perf_counter() - t0_w
        flat: list = []
        for l in lats_w:
            flat.extend(l)
        flat.sort()
        return (per_w * args.clients) / dt_w, dt_w, flat

    profile_session = None
    if args.profile:
        # host-phase slices feed the merged timeline's middle track;
        # the device capture is bounded (the poll loop enforces it)
        driver._phase_prof.enable_events()
        profile_session = driver.start_profile(
            seconds=args.profile_secs,
            log_dir=os.path.join(wd, "profile"))
    ops, dt, lat = run_wave(args.requests)
    if profile_session is not None:
        driver.stop_profile()
    nb = len(lat)
    n = args.requests // args.clients * args.clients
    print(f"committed SETs: {n} in {dt:.2f}s -> {n / dt:.0f} ops/s "
          f"({args.clients} clients, pipeline {args.pipeline}, "
          f"dispatch depth {args.pipeline_depth}"
          f"{', %d groups' % args.groups if sharded_e2e else ''}"
          f"{', threaded app' if args.threaded_app else ''})")
    if nb:
        print(f"per-batch latency p50={lat[nb // 2] * 1e3:.2f}ms "
              f"p95={lat[int(nb * .95)] * 1e3:.2f}ms "
              f"p99={lat[int(nb * .99)] * 1e3:.2f}ms")
    else:
        # the workload died (all clients exhausted their retries) —
        # still fall through: the metrics/health export below is
        # exactly the post-mortem such a run needs
        print("per-batch latency: no completed batches")

    # observability export: the registry snapshot (commit-latency
    # histogram buckets, per-replica role/term gauges, rebase-headroom
    # gauge, proxy/replay counters) rides alongside the wall-clock
    # numbers so BENCH_* rounds carry protocol-level detail, and the
    # aggregated health view prints for the operator
    import json
    metrics_snap = driver.obs.metrics.snapshot()
    metrics_path = args.metrics_json or os.path.join(wd, "metrics.json")
    driver.obs.metrics.write_json(metrics_path)
    health = driver.health()
    print(f"metrics snapshot: {metrics_path} "
          f"({len(metrics_snap['counters'])} counters, "
          f"{len(metrics_snap['gauges'])} gauges, "
          f"{len(metrics_snap['histograms'])} histograms)")
    print("METRICS:" + json.dumps(metrics_snap))
    print("HEALTH:" + json.dumps(health))

    trace_detail = None
    if args.trace:
        # let the followers' commit/apply frontiers catch up so every
        # span carries all R replicas' marks before the export
        time.sleep(0.5)
        from rdma_paxos_tpu.obs import spans as spans_mod
        # ONE dump feeds both artifacts + the stats, so the on-disk
        # spans.json and the Perfetto trace can never disagree
        raw = driver.obs.spans.dump()
        spans_path = os.path.join(wd, "spans.json")
        with open(spans_path, "w") as sf:
            json.dump(raw, sf, indent=2)
        trace_path = (args.trace_json
                      or os.path.join(wd, "trace.perfetto.json"))
        with open(trace_path, "w") as tf:
            json.dump(spans_mod.to_chrome_trace(
                raw, max_cp_tracks=4096), tf)
        done = [s for s in raw["spans"] if s["status"] == "done"]
        corr = [s for s in done
                if s["term"] is not None
                and len({r for p, r, _ in s["events"]
                         if p == "commit"}) >= args.replicas]
        # denominator: every event the proxy layer SUBMITTED (counted
        # at intake; a few may have failed rather than committed)
        submitted = sum(
            v for k, v in metrics_snap["counters"].items()
            if k.startswith("proxy_events_total"))
        cover = len(done) / max(submitted, 1)
        trace_detail = dict(
            spans=len(raw["spans"]), completed=len(done),
            correlated_all_replicas=len(corr),
            submitted_events=submitted,
            coverage=round(cover, 4), dropped=raw["dropped"],
            spans_json=spans_path, perfetto_json=trace_path)
        print(f"spans: {len(raw['spans'])} sampled, {len(done)} "
              f"completed, {len(corr)} correlated across all "
              f"{args.replicas} replicas ({cover:.1%} of {submitted} "
              f"submitted events) -> {trace_path} (load in "
              f"https://ui.perfetto.dev)")
        print(spans_mod.format_breakdown(spans_mod.breakdown(raw)))

    from benchmarks.reporting import emit

    def phase_sums():
        """Per-phase StepPhaseProfiler sums — zero-sample phases
        suppressed (a fence-off run must not carry a dead
        device_sync column)."""
        return driver._phase_prof.sums()

    profile_detail = None
    if args.profile:
        from rdma_paxos_tpu.obs import device as device_mod

        # per-STEP_CACHE-variant compiled-program cost report: what
        # one dispatch COSTS, next to what it DID (the counters)
        report = device_mod.write_program_report(
            os.path.join(wd, "program_report.json"), driver.cluster,
            tiers=(2,))
        emit("program_report", len(report["variants"]), "variants",
             detail=dict(
                 path=report["path"], backend=report["backend"],
                 engine=report["engine"],
                 variants=[{k: v for k, v in row.items()
                            if k in ("variant", "flops",
                                     "bytes_accessed")}
                           for row in report["variants"]]),
             obs=driver.obs, json_path=args.json)
        merged_path = os.path.join(wd, "merged.perfetto.json")
        # the SAME dump that fed spans.json / trace.perfetto.json —
        # a second dump() here would capture spans that completed in
        # between and the three artifacts would disagree
        span_dumps = [raw] if args.trace else []
        merged = device_mod.merge_timeline(
            span_dumps,
            phase_events=list(driver._phase_prof.events or []),
            profiler=profile_session, max_cp_tracks=4096)
        with open(merged_path, "w") as mf:
            json.dump(merged, mf)
        profile_detail = dict(
            merged_perfetto=merged_path,
            profile_dir=profile_session.log_dir,
            device_events=merged["otherData"]["device_events"],
            device_events_dropped=merged["otherData"][
                "device_events_dropped"],
            host_phase_events=merged["otherData"]["host_phase_events"],
            span_tracks=merged["otherData"]["spans"],
            program_report=report["path"])
        print(f"profile: {profile_detail['device_events']} device "
              f"events ({profile_detail['device_events_dropped']} "
              f"dropped past the cap) + "
              f"{profile_detail['host_phase_events']} host-phase "
              f"slices + {profile_detail['span_tracks']} spans -> "
              f"{merged_path} (one timeline — load in "
              f"https://ui.perfetto.dev)")

    emit("e2e_committed_ops_per_sec", round(n / dt, 1), "ops/s",
         detail=dict(
             requests=n, seconds=round(dt, 3),
             clients=args.clients, pipeline=args.pipeline,
             pipeline_depth=args.pipeline_depth,
             groups=(args.groups if sharded_e2e else 1),
             max_inflight_dispatches=int(
                 driver.cluster.max_inflight_dispatches),
             threaded_app=bool(args.threaded_app),
             p50_ms=(round(lat[nb // 2] * 1e3, 2) if nb else None),
             p95_ms=(round(lat[int(nb * .95)] * 1e3, 2)
                     if nb else None),
             p99_ms=(round(lat[int(nb * .99)] * 1e3, 2)
                     if nb else None),
             fence=bool(args.fence), audit=bool(args.audit),
             telemetry=bool(args.telemetry),
             phases=phase_sums(),
             trace=trace_detail,
             profile=profile_detail,
             health=health),
         obs=driver.obs, json_path=args.json)

    if args.ab_pipeline > 0 and args.pipeline_depth >= 2:
        # pipeline on/off A/B — the --audit overhead methodology:
        # ALTERNATING rounds, each variant scored by its fastest
        # (host-load noise on a shared core exceeds the effect), the
        # in-flight-depth counter proving the ON rounds actually
        # overlapped dispatches, per-variant phase attribution
        from benchmarks.reporting import ab_pipeline_rounds
        ab = ab_pipeline_rounds(
            driver, args.ab_pipeline, args.pipeline_depth,
            lambda: run_wave(args.requests)[0])
        speedup = ab["on"] / max(ab["off"], 1e-9)
        print(f"pipeline A/B: {ab['off']:.0f} ops/s off vs "
              f"{ab['on']:.0f} ops/s on -> {speedup:.2f}x "
              f"(max in-flight dispatches {ab['depth_seen']})")
        emit("pipeline_speedup", round(speedup, 3), "x",
             detail=dict(off_ops_per_sec=round(ab["off"], 1),
                         on_ops_per_sec=round(ab["on"], 1),
                         rounds=args.ab_pipeline,
                         requests_per_round=n,
                         pipeline_depth=args.pipeline_depth,
                         max_inflight_dispatches=ab["depth_seen"],
                         groups=(args.groups if sharded_e2e else 1),
                         phases_on=ab["phases_on"],
                         phases_off=ab["phases_off"]),
             obs=driver.obs, json_path=args.json)

    if args.scan and args.ab_hostpath > 0:
        # host-path A/B — the one methodology every overhead/speedup
        # row shares (alternating best-of on the same shared core):
        # OFF = scalar per-entry host loops + per-field burst readback
        # + standalone replay fetch dispatches (the pre-PR data
        # plane); ON = vectorized window batch ops + the K-window
        # scan tier's consolidated readback. Phase sums attribute
        # exactly where the us went (host_encode / apply_replay_ack /
        # quorum_wait).
        from benchmarks.reporting import ab_variant_rounds
        from rdma_paxos_tpu.runtime import hostpath as hostpath_mod

        def apply_variant(on: bool):
            hostpath_mod.set_vectorized(on)
            driver.cluster.scan = on

        ab = ab_variant_rounds(driver, args.ab_hostpath,
                               apply_variant,
                               lambda: run_wave(args.requests)[0])
        speedup = ab["on"] / max(ab["off"], 1e-9)

        def us_per_op(ops):
            return round(1e6 / ops, 2) if ops else None

        print(f"host-path A/B: {ab['off']:.0f} ops/s scalar vs "
              f"{ab['on']:.0f} ops/s vectorized+scan -> "
              f"{speedup:.2f}x ({us_per_op(ab['off'])} -> "
              f"{us_per_op(ab['on'])} us/op; "
              f"{driver.cluster.scan_dispatches} scan dispatches)")
        emit("host_path_speedup", round(speedup, 3), "x",
             detail=dict(off_ops_per_sec=round(ab["off"], 1),
                         on_ops_per_sec=round(ab["on"], 1),
                         off_us_per_op=us_per_op(ab["off"]),
                         on_us_per_op=us_per_op(ab["on"]),
                         rounds=args.ab_hostpath,
                         requests_per_round=n,
                         scan_k=max(driver.cluster.K_TIERS),
                         scan_dispatches=int(
                             driver.cluster.scan_dispatches),
                         groups=(args.groups if sharded_e2e else 1),
                         shared_core_caveat=(
                             "alternating best-of on shared CPU "
                             "cores; see REDIS_r06"),
                         phases_on=ab["phases_on"],
                         phases_off=ab["phases_off"]),
             obs=driver.obs, json_path=args.json)

    if args.audit:
        # e2e audit verdict (the whole workload ran digest-checked)
        # plus the A/B overhead row the acceptance criteria ask for
        summary = health.get("audit") or {}
        print(f"audit: {summary.get('indices_checked', 0)} index "
              f"checks over {summary.get('windows', 0)} windows, "
              f"{summary.get('findings', 0)} divergence finding(s)")
        ab = measure_audit_overhead()
        print(f"audit overhead: {ab['off']['ops_per_sec']} ops/s off "
              f"vs {ab['on']['ops_per_sec']} ops/s on "
              f"({ab['overhead_pct']}% — target <5%)")
        emit("audit_overhead_pct", ab["overhead_pct"], "%",
             detail=dict(off=ab["off"], on=ab["on"],
                         audit=ab["audit"], e2e_audit=summary),
             obs=driver.obs, json_path=args.json)

    if args.telemetry:
        # e2e proof the counters flowed (the driver's own device_*
        # series); the A/B overhead row runs AFTER driver.stop() —
        # the live driver keeps dispatching its own telemetry-on idle
        # steps from the poll loop, and that background host work
        # biases the on-variant rounds by 10+ points on a small box
        snap_counters = {
            k: v for k, v in metrics_snap["counters"].items()
            if k.startswith("device_")}
        print(f"device telemetry: {len(snap_counters)} series "
              f"exported during the workload")

    # replication check: every replica's app must converge to the same
    # key count (sharded: all G groups' committed streams replayed
    # into every replica's app)
    time.sleep(1.0)

    def kv_count(port):
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        f = s.makefile("rb")
        s.sendall(b"COUNT\n")
        out = f.readline().strip().decode()
        s.close()
        return out

    deadline = time.time() + 30
    while True:
        counts = [kv_count(p) for p in ports]
        if len(set(counts)) == 1 or time.time() > deadline:
            break
        time.sleep(0.5)
    print(f"replica kv counts: {counts} "
          + ("OK" if len(set(counts)) == 1 else "MISMATCH"))

    driver.stop()
    for a in apps:
        a.kill()
        a.wait()

    if args.scan and args.ab_hostpath > 0:
        # engine-closed-loop host-path A/B on the now-quiet process
        # (the --telemetry reasoning): isolates the data-plane delta
        # from client-thread GIL contention and app socket I/O — the
        # e2e row above measures the whole serving stack, this row
        # measures the driver host path itself
        hp = measure_host_path()
        print(f"host-path engine A/B: {hp['off']['ops_per_sec']} "
              f"ops/s scalar+burst vs {hp['on']['ops_per_sec']} "
              f"ops/s vectorized+scan -> {hp['speedup']}x "
              f"({hp['scan']['scan_dispatches']} scan dispatches)")
        emit("host_path_speedup_engine", hp["speedup"], "x",
             detail=dict(off=hp["off"], on=hp["on"], **hp["scan"],
                         shared_core_caveat=(
                             "engine closed loop, alternating "
                             "best-of on shared CPU cores")),
             obs=driver.obs, json_path=args.json)

    if args.repair:
        # on the now-quiet process (same reasoning as --telemetry):
        # the A/B measures the controller's findings scan, and the
        # MTTR round measures the whole self-healing loop in
        # step-domain time (deterministic, host-load independent)
        ab = measure_repair()
        mttr = ab["mttr"]
        print(f"repair overhead: {ab['off']['ops_per_sec']} ops/s off "
              f"vs {ab['on']['ops_per_sec']} ops/s on "
              f"({ab['overhead_pct']}% — target <5%)")
        print(f"MTTR: {mttr['mttr_steps']} steps corruption->re-admit "
              f"(detect {mttr['detection_steps']}, probation "
              f"{mttr['probation_steps']}), coverage_ok="
              f"{mttr['coverage_ok']}")
        emit("repair_overhead_pct", ab["overhead_pct"], "%",
             detail=dict(off=ab["off"], on=ab["on"]),
             obs=driver.obs, json_path=args.json)
        emit("mttr_steps", mttr["mttr_steps"], "steps",
             detail=mttr, obs=driver.obs, json_path=args.json)

    if args.read_ratio > 0:
        # on the now-quiet process (the --repair/--telemetry
        # reasoning): the A/B measures the read paths, not poll-loop
        # contention. The lease variant serves reads host-side from
        # the leaseholder; the log variant rides every read through
        # the replicated ring — what every linearizable read cost
        # before PR 10.
        rm = measure_read_mix(args.read_ratio)
        acc = rm["accounting"]
        print(f"read mix ({args.read_ratio:.0%} reads): "
              f"{rm['lease']['read_ops_per_sec']:.0f} reads/s leased "
              f"vs {rm['log']['read_ops_per_sec']:.0f} reads/s "
              f"through-log -> {rm['lease_read_speedup']}x "
              f"(lease-path accounting: "
              f"{acc['lease_variant']['lease']} reads)")
        emit("read_ops_per_sec", rm["lease"]["read_ops_per_sec"],
             "ops/s", detail=dict(read_ratio=args.read_ratio,
                                  variant="lease", **rm["lease"]),
             obs=driver.obs, json_path=args.json)
        emit("write_ops_per_sec", rm["lease"]["write_ops_per_sec"],
             "ops/s", detail=dict(read_ratio=args.read_ratio,
                                  variant="lease", **rm["lease"]),
             obs=driver.obs, json_path=args.json)
        emit("lease_read_speedup", rm["lease_read_speedup"], "x",
             detail=rm, obs=driver.obs, json_path=args.json)

    if args.watch_ratio > 0:
        # on the now-quiet process (the --read-ratio reasoning): the
        # A/B isolates the streams surface's cost on the write path —
        # the pump and CDC exporter run concurrently with the
        # committed workload, exactly as deployed
        wm = measure_watch_mix(args.watch_ratio)
        at = wm["attached"]
        print(f"watch mix ({args.watch_ratio:.0%} keyspace watched, "
              f"{wm['n_watchers']} watchers): "
              f"{at['watch_fanout_events_per_sec']:.0f} events/s "
              f"fan-out, cdc lag {wm['cdc']['lag']} "
              f"({wm['cdc']['exported']} exported), attach overhead "
              f"{wm['watch_attach_overhead_pct']}% (target <3%)")
        emit("watch_fanout_events_per_sec",
             at["watch_fanout_events_per_sec"], "events/s",
             detail=dict(watch_ratio=args.watch_ratio, **at),
             obs=driver.obs, json_path=args.json)
        emit("cdc_lag_entries", wm["cdc"]["lag"], "entries",
             detail=wm["cdc"], obs=driver.obs, json_path=args.json)
        emit("watch_attach_overhead_pct",
             wm["watch_attach_overhead_pct"], "%", detail=wm,
             obs=driver.obs, json_path=args.json)

    if args.txn:
        # on the now-quiet process (the --read-ratio reasoning): the
        # probes count dispatches on a dedicated txn=True geometry,
        # and the A/B isolates the fast path's cost on the write path
        tm = measure_txn()
        pr = tm["probe"]
        print(f"txn: cross-group 2PC commit = "
              f"{pr['twopc']['dispatches']} dispatches "
              f"(single-key put = {pr['single']['dispatches']}), "
              f"latency ratio {pr['latency_ratio']}x; mergeable "
              f"{tm['merge']['write_ops_per_sec']:.0f} ops/s vs "
              f"plain {tm['plain']['write_ops_per_sec']:.0f} ops/s "
              f"-> {tm['merge_throughput_ratio']}x (target >=0.9x)")
        emit("txn_commit_dispatches", pr["twopc"]["dispatches"],
             "dispatches", detail=pr, obs=driver.obs,
             json_path=args.json)
        emit("txn_commit_latency_ratio", pr["latency_ratio"], "x",
             detail=pr, obs=driver.obs, json_path=args.json)
        emit("txn_merge_throughput_ratio",
             tm["merge_throughput_ratio"], "x", detail=tm,
             obs=driver.obs, json_path=args.json)

    if args.serve_metrics is not None:
        # ops-plane overhead on the now-quiet process (the
        # --telemetry reasoning): series sampling + full rule set +
        # live scrapes on vs the bare cluster — target <2%
        ab = measure_export_overhead()
        print(f"export overhead: {ab['off']['ops_per_sec']} ops/s "
              f"off vs {ab['on']['ops_per_sec']} ops/s on "
              f"({ab['overhead_pct']}% — target <2%)")
        emit("export_overhead_pct", ab["overhead_pct"], "%",
             detail=dict(off=ab["off"], on=ab["on"],
                         export=ab["export"]),
             obs=driver.obs, json_path=args.json)

    if args.telemetry:
        # counters on vs off, alternating best-of (the PR 5 audit
        # methodology) — on the now-quiet process, so the row measures
        # the counter vector, not poll-loop contention
        ab = measure_telemetry_overhead()
        print(f"telemetry overhead: {ab['off']['ops_per_sec']} ops/s "
              f"off vs {ab['on']['ops_per_sec']} ops/s on "
              f"({ab['overhead_pct']}% — target <5%)")
        emit("telemetry_overhead_pct", ab["overhead_pct"], "%",
             detail=dict(off=ab["off"], on=ab["on"],
                         device_counters=ab["device_counters"],
                         e2e_series=len(snap_counters)),
             obs=driver.obs, json_path=args.json)

    if args.trace_overhead:
        # sampling on (production default + TraceContext) vs off, on
        # the now-quiet process — the tracing counterpart of the
        # export row above, same <2% end-to-end target
        ab = measure_trace_overhead()
        print(f"trace overhead: {ab['off']['ops_per_sec']} ops/s "
              f"off vs {ab['on']['ops_per_sec']} ops/s on "
              f"({ab['overhead_pct']}% — target <2%)")
        emit("trace_overhead_pct", ab["overhead_pct"], "%",
             detail=dict(off=ab["off"], on=ab["on"],
                         trace=ab["trace"]),
             obs=driver.obs, json_path=args.json)


if __name__ == "__main__":
    main()
