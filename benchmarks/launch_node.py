#!/usr/bin/env python
"""Per-host node launcher — the per-machine half of ``benchmarks/run.sh``.

Run one of these on every host of the group (here: every process), with
the same coordinator address; each starts its replica daemon, optionally
its unmodified app under the interposition shim, and loops.

    server_idx=0 group_size=3 python benchmarks/launch_node.py \
        --coordinator host0:9900 --workdir /tmp/rp --app-port 7700 \
        --iterations 2000
"""

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--app-port", type=int, default=0)
    ap.add_argument("--app-cmd", default="")
    ap.add_argument("--iterations", type=int, default=5000)
    ap.add_argument("--period", type=float, default=0.0)
    ap.add_argument("--config", default="")
    args = ap.parse_args()

    idx = int(os.environ["server_idx"])
    n = int(os.environ["group_size"])

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # persistent compile cache: the step/burst programs are identical
    # across node restarts — never pay a mid-serving JIT pause twice
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/rp_jax_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.2")
    import jax
    if os.environ.get("RP_BENCH_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")

    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig, load_config
    from rdma_paxos_tpu.runtime.node import NodeDaemon

    if args.config:
        cfg, timing, _ = load_config(args.config)
    else:
        cfg = LogConfig(n_slots=1024, slot_bytes=256, window_slots=64,
                        batch_slots=64)
        timing = TimeoutConfig(elec_timeout_low=0.5, elec_timeout_high=1.0)

    node = NodeDaemon(cfg, process_id=idx, num_processes=n,
                      coordinator=args.coordinator, workdir=args.workdir,
                      app_port=args.app_port or None, timeout_cfg=timing)
    node.prewarm_burst()     # collective: compile bursts out of serving

    app = None
    if args.app_port:
        cmd = (args.app_cmd.split() if args.app_cmd
               else [os.path.join(NATIVE, "toyserver"),
                     str(args.app_port)])
        env = dict(os.environ)
        env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
        env["RP_PROXY_SOCK"] = node.sock_path
        app = subprocess.Popen(cmd, env=env, stderr=subprocess.DEVNULL)
        time.sleep(0.2)

    try:
        node.run_iterations(args.iterations, period=args.period)
    finally:
        node.close()
        if app is not None:
            app.kill()
            app.wait()


if __name__ == "__main__":
    main()
