#!/usr/bin/env python
"""Sharded multi-group throughput — the one-dispatch-per-step win.

Scales the group count G over {1, 2, 4, 8} (default) and measures
aggregate committed ops/s across ALL groups of a
:class:`~rdma_paxos_tpu.shard.cluster.ShardedCluster`, under a
saturating closed-loop workload (every group's leader fed a full batch
per step). The headline proof is the **dispatch count**: the
group-batched compiled step advances all G groups in ONE device
dispatch per protocol step — ``dispatch_per_step == 1.0`` regardless
of G — so aggregate throughput scales with G without multiplying host
dispatch overhead (the G-separate-clusters alternative pays G
dispatches per step).

Leaders are spread round-robin across the R replicas
(``place_leaders``), matching the production placement policy.

    python benchmarks/shard_bench.py --groups 1,2,4,8 --steps 60

Emits one standardized ``BENCH:`` line per G plus a scaling summary
(``benchmarks/reporting.emit``), and appends full registry-snapshot
rows to ``--json`` when given.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_one(G: int, *, replicas: int, steps: int, payload: int,
            burst: bool, json_path, cfg=None, mesh=None,
            telemetry: bool = False, read_ratio: float = 0.0,
            zipf: float = 0.0, zipf_n_keys: int = 64,
            metric="shard_aggregate_committed_ops_per_sec",
            extra_detail=None, obs=None, on_cluster=None):
    """Build, warm, and drive one G-group cluster; returns the result
    row dict (also emitted as a BENCH: line). ``mesh=(group_shards,
    replicas)`` runs the MULTI-CHIP engine — state sharded over a real
    2-D ``(group, replica)`` device mesh instead of one device.
    ``telemetry=True`` compiles the device-counter step variants and
    adds per-group (and, on a mesh, per-SHARD) committed-entry device
    counters to the row — scaling provable from device truth alone."""
    from benchmarks.reporting import emit
    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.obs import Observability
    from rdma_paxos_tpu.shard import ShardedCluster

    if cfg is None:
        cfg = LogConfig(n_slots=2048, slot_bytes=128,
                        window_slots=256, batch_slots=256)
    sc = ShardedCluster(cfg, replicas, G, mesh=mesh,
                        telemetry=telemetry)
    # a shared obs facade (--serve-metrics) keeps one registry across
    # the whole sweep so the live exporter's view survives cluster
    # swaps; on_cluster re-points the /healthz source at each new one
    sc.obs = obs if obs is not None else Observability()
    if on_cluster is not None:
        on_cluster(sc)
    targets = sc.place_leaders()
    B = cfg.batch_slots
    blob = b"x" * payload
    # read-mix column (read_ratio > 0): alongside every timed step's
    # write feed, each group's LEASEHOLDER serves a host-side batch of
    # lease reads sized read_ratio : (1-read_ratio) against the write
    # load — the per-group read fan-out place_leaders + leases buy,
    # visible per replica in the row
    kvs = None
    read_keys = None
    reads_per_step = 0
    if read_ratio > 0:
        from rdma_paxos_tpu.runtime import reads as reads_mod
        from rdma_paxos_tpu.shard.chaos import keys_for_groups
        from rdma_paxos_tpu.shard.kvs import ShardedKVS
        reads_mod.attach(sc)
        kvs = ShardedKVS(sc, cap=4096)
        read_keys = keys_for_groups(sc.router, 8, prefix=b"rmix")
        for g in range(G):
            for k in read_keys[g]:
                kvs.groups[g].put(sc.leader_hint(g), k, b"seed")
        sc.step()
        sc.step()
        # at least one read per group per step whenever the flag is
        # set (int() would truncate small ratios to zero and silently
        # disable the column), capped so extreme ratios stay feasible
        reads_per_step = max(1, min(
            int(B * read_ratio / max(1.0 - read_ratio, 1e-6)), 4 * B))

    # --zipf S: the offered load becomes KEY-shaped — each step offers
    # G*B ops whose keys are drawn Zipf(S) over a fixed pool and routed
    # by the router, so hot groups saturate their per-step batch while
    # cold ones idle. The row's zipf column carries offered vs admitted
    # per group — the skew the elastic-topology bench exists to fix.
    zipf_offered = [0] * G
    zipf_admitted = [0] * G
    if zipf:
        from benchmarks.arrival_traces import zipf_keys
        ztrace = zipf_keys((steps + 4) * G * B, s=zipf,
                           n_keys=zipf_n_keys, seed=0)
        key_group = {k: sc.router.group_of(k) for k in set(ztrace)}
        zstate = dict(pos=0)

    def feed():
        if zipf:
            sent = [0] * G
            take = ztrace[zstate["pos"]:zstate["pos"] + G * B]
            zstate["pos"] += len(take)
            for k in take:
                g = key_group[k]
                zipf_offered[g] += 1
                if sent[g] < B:
                    sent[g] += 1
                    zipf_admitted[g] += 1
                    sc.submit(g, sc.leader_hint(g), blob)
            return
        for g in range(G):
            lead = sc.leader_hint(g)
            for i in range(B):
                sc.submit(g, lead, blob)

    # warmup: compile both step variants (and the burst tiers when the
    # burst driver is measured) outside the timed window
    if burst:
        sc.prewarm()
    feed()
    sc.step()
    feed()
    sc.step()

    base_commit = [int(sc.last["commit"][g].max())
                   + int(sc.rebased_total[g]) for g in range(G)]
    d0, f0 = sc.dispatches, sc.fetch_dispatches
    n_dispatch_steps = 0
    reads_by_group = [0] * G
    reads_by_replica = [0] * replicas
    # zipf column: report the TIMED window only, not warmup
    zipf_offered = [0] * G
    zipf_admitted = [0] * G
    t0 = time.perf_counter()
    for _ in range(steps):
        feed()
        if burst:
            sc.step_burst()
        else:
            sc.step()
        n_dispatch_steps += 1
        if reads_per_step:
            from rdma_paxos_tpu.runtime.reads import count_read
            for g in range(G):
                holder = sc.leases.serving_holder(g)
                if holder < 0:
                    continue
                batch = (read_keys[g]
                         * (reads_per_step // len(read_keys[g]) + 1)
                         )[:reads_per_step]
                kvs.groups[g].get_many(holder, batch)
                count_read(sc.obs, "lease", holder, group=g,
                           n=len(batch))
                reads_by_group[g] += len(batch)
                reads_by_replica[holder] += len(batch)
    dt = time.perf_counter() - t0
    per_group = [int(sc.last["commit"][g].max())
                 + int(sc.rebased_total[g]) - base_commit[g]
                 for g in range(G)]
    committed = sum(per_group)
    dispatches = sc.dispatches - d0
    detail = dict(
        groups=G, replicas=replicas, steps=steps,
        driver=("burst" if burst else "step"),
        engine=("mesh" if mesh is not None else "single-device"),
        seconds=round(dt, 3),
        committed_total=committed,
        committed_per_group=per_group,
        leaders=targets,
        protocol_dispatches=dispatches,
        dispatch_per_step=round(dispatches
                                / max(n_dispatch_steps, 1), 3),
        replay_fetch_dispatches=sc.fetch_dispatches - f0,
        compiled_programs_used=len(sc.programs_used),
    )
    if telemetry:
        # device-truth committed work: the ON-DEVICE commit-advance
        # counter per group (max over the replica column — every
        # replica of a group advances the same committed prefix), and
        # its per-SHARD sums on a mesh (shard s owns the contiguous
        # group block [s*G/gs, (s+1)*G/gs) under P(group) sharding) —
        # the mesh scaling claim, provable without host bookkeeping
        from rdma_paxos_tpu.obs import device as device_mod
        col = device_mod.INDEX["committed_entries"]
        per_g = [int(sc.device_counters[g, :, col].max())
                 for g in range(G)]
        detail["device_committed_per_group"] = per_g
        if mesh is not None:
            gs = sc.mesh.devices.shape[0]
            blk = G // gs
            detail["device_committed_entries"] = [
                sum(per_g[s * blk:(s + 1) * blk]) for s in range(gs)]
    if reads_per_step:
        # honest ratio reporting: reads_per_step is capped at 4*B, so
        # at high requested ratios the EXECUTED mix can be leaner than
        # asked — the row carries both, never just the request
        total_reads = sum(reads_by_group)
        detail["read_mix"] = dict(
            requested_read_ratio=read_ratio,
            effective_read_ratio=round(
                total_reads / max(total_reads + committed, 1), 3),
            reads_per_group_per_step=reads_per_step,
            reads_total=total_reads,
            read_ops_per_sec=round(total_reads / dt, 1),
            reads_per_group=reads_by_group,
            # the fan-out column: lease reads served per REPLICA —
            # place_leaders spreads group leaseholds, so read serving
            # spreads with them instead of piling onto one replica
            reads_per_replica=reads_by_replica,
            lease_holders=sc.leases.holders())
    if zipf:
        # honest skew reporting: offered is the trace's routing truth,
        # admitted is what fit the per-step batch — the gap IS the
        # hot-group ceiling a static G cannot lift
        off_total = max(sum(zipf_offered), 1)
        detail["zipf"] = dict(
            s=zipf, n_keys=zipf_n_keys,
            offered_per_group=zipf_offered,
            admitted_per_group=zipf_admitted,
            dropped_total=sum(zipf_offered) - sum(zipf_admitted),
            hottest_offered_share=round(
                max(zipf_offered) / off_total, 3))
    if extra_detail:
        detail.update(extra_detail)
    row = emit(metric, round(committed / dt, 1), "ops/s",
               detail=detail, obs=sc.obs, json_path=json_path)
    label = (f"{mesh[0]}x{mesh[1]} mesh, G={G}" if mesh is not None
             else f"G={G}")
    print(f"  {label}: {committed} committed in {dt:.2f}s -> "
          f"{committed / dt:.0f} ops/s aggregate; "
          f"{dispatches} dispatches / {n_dispatch_steps} steps = "
          f"{dispatches / max(n_dispatch_steps, 1):.2f} per step; "
          f"leaders {targets}")
    return row


def run_mesh_sweep(layouts, *, groups_per_shard: int, steps: int,
                   payload: int, burst: bool, json_path,
                   read_ratio: float = 0.0, obs=None,
                   on_cluster=None) -> int:
    """The multi-chip layout sweep: each ``GSxR`` layout runs G =
    GS * groups_per_shard groups over a real ``(group, replica)``
    device mesh of GS*R devices, A/B'd against a SINGLE-chip baseline
    carrying the same per-shard load (groups_per_shard groups, the
    vmap engine). ``scaling_efficiency`` is the headline row:
    aggregate ÷ (GS × single-chip baseline aggregate) — 1.0 means
    every added device row contributed a full chip's worth of
    committed ops/s (near-linear scale-out in chips)."""
    import jax

    from benchmarks.reporting import emit

    n_dev = len(jax.devices())
    print(f"shard_bench mesh sweep: layouts {layouts}, "
          f"{groups_per_shard} group(s)/shard, {steps} steps, "
          f"driver={'burst' if burst else 'step'}, "
          f"{n_dev} devices available")
    baselines = {}          # R -> single-chip aggregate ops/s
    summary = {}
    for gs, R in layouts:
        if gs * R > n_dev:
            print(f"  {gs}x{R}: SKIPPED (needs {gs * R} devices, "
                  f"have {n_dev})")
            continue
        if R not in baselines:
            # telemetry ON for the baseline too: the A/B must compare
            # identical programs (counter overhead on both sides)
            base = run_one(
                groups_per_shard, replicas=R, steps=steps,
                payload=payload, burst=burst, json_path=json_path,
                telemetry=True, read_ratio=read_ratio,
                metric="mesh_baseline_committed_ops_per_sec",
                extra_detail=dict(role="single-chip baseline"),
                obs=obs, on_cluster=on_cluster)
            baselines[R] = base["value"]
        row = run_one(
            gs * groups_per_shard, replicas=R, steps=steps,
            payload=payload, burst=burst, json_path=json_path,
            mesh=(gs, R), telemetry=True, read_ratio=read_ratio,
            metric="mesh_aggregate_committed_ops_per_sec",
            extra_detail=dict(layout=f"{gs}x{R}", group_shards=gs,
                              devices=gs * R),
            obs=obs, on_cluster=on_cluster)
        eff = row["value"] / max(gs * baselines[R], 1e-9)
        emit("mesh_scaling_efficiency", round(eff, 3), "ratio",
             detail=dict(
                 layout=f"{gs}x{R}", group_shards=gs, replicas=R,
                 devices=gs * R, groups=gs * groups_per_shard,
                 aggregate_ops_per_sec=row["value"],
                 baseline_single_chip_ops_per_sec=baselines[R],
                 dispatch_per_step=row["detail"]["dispatch_per_step"],
                 device_committed_entries=row["detail"].get(
                     "device_committed_entries"),
                 driver=("burst" if burst else "step")),
             json_path=json_path)
        print(f"  {gs}x{R}: scaling efficiency {eff:.2f} "
              f"({row['value']:.0f} / ({gs} x {baselines[R]:.0f}))")
        summary[f"{gs}x{R}"] = dict(
            ops_per_sec=row["value"], scaling_efficiency=round(eff, 3),
            dispatch_per_step=row["detail"]["dispatch_per_step"])
    if not summary:
        # every layout was skipped: the artifact would carry no mesh
        # data — fail the run instead of handing CI a green no-op
        print(f"mesh sweep: NO layout fits the {n_dev} available "
              f"device(s) — nothing measured")
        return 1
    emit("mesh_scaling", detail=summary, json_path=json_path)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--groups", default=None,
                    help="comma-separated group counts to sweep "
                         "(default 1,2,4,8; incompatible with --mesh)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="replication factor (default 3; in --mesh "
                         "mode R comes from each GSxR layout token)")
    ap.add_argument("--steps", type=int, default=60,
                    help="timed protocol steps per group count")
    ap.add_argument("--payload", type=int, default=64,
                    help="bytes per committed entry")
    ap.add_argument("--burst", action="store_true",
                    help="drive with fused multi-step bursts "
                         "(step_burst) instead of single steps")
    ap.add_argument("--mesh", default=None,
                    help='multi-chip sweep: comma-separated device-'
                         'mesh layouts "GSxR" (e.g. "1x2,2x2,4x2") — '
                         'each runs G = GS * --groups-per-shard '
                         'groups over a real (group, replica) mesh of '
                         'GS*R devices, emitting aggregate ops/s + '
                         'scaling_efficiency rows vs a single-chip '
                         'baseline')
    ap.add_argument("--groups-per-shard", type=int, default=1,
                    help="groups per device row in --mesh mode")
    ap.add_argument("--read-ratio", type=float, default=0.0,
                    help="read-mix column: serve this read fraction "
                         "as host-side lease reads at each group's "
                         "leaseholder alongside the write feed — the "
                         "per-group read fan-out shows up as "
                         "reads_per_replica in every row")
    ap.add_argument("--zipf", type=float, default=0.0, metavar="S",
                    help="key-shaped offered load: draw each step's "
                         "G*B ops from a Zipf(S) key pool routed by "
                         "the router (hot groups saturate, cold ones "
                         "idle) — adds the offered/admitted skew "
                         "column to every row")
    ap.add_argument("--zipf-keys", type=int, default=64,
                    help="distinct keys in the --zipf pool")
    ap.add_argument("--json", default=None,
                    help="append JSON result rows to this file")
    ap.add_argument("--serve-metrics", nargs="?", const=0,
                    default=None, type=int, metavar="PORT",
                    help="serve live /metrics + /healthz on this "
                         "localhost port for the whole sweep (no "
                         "value = ephemeral port) — watch a long "
                         "bench with the fleet console or any "
                         "Prometheus scraper")
    args = ap.parse_args(argv)

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/rp_jax_cache")
    import jax
    if os.environ.get("RP_BENCH_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")

    from benchmarks.reporting import emit

    exporter = None
    shared_obs = None
    on_cluster = None
    if args.serve_metrics is not None:
        from rdma_paxos_tpu.obs import Observability
        from rdma_paxos_tpu.obs.export import OpsExporter
        shared_obs = Observability()
        holder = {}

        def on_cluster(sc):
            holder["c"] = sc
        exporter = OpsExporter(
            registry=shared_obs.metrics,
            health_fn=lambda: (holder["c"].health() if "c" in holder
                               else dict(ok=True)),
            port=args.serve_metrics).start()
        print(f"ops endpoints: {exporter.url}/metrics  /healthz")

    if args.mesh:
        if args.groups is not None or args.replicas is not None:
            # refuse loudly rather than silently drop: in --mesh mode
            # G and R come from the layout tokens + --groups-per-shard
            raise SystemExit(
                "--mesh is incompatible with --groups/--replicas: "
                "each GSxR layout fixes R, and G = GS * "
                "--groups-per-shard")
        layouts = []
        for tok in str(args.mesh).split(","):
            tok = tok.strip()
            if not tok:
                continue
            try:
                a, b = tok.lower().split("x")
                layouts.append((int(a), int(b)))
            except ValueError:
                raise SystemExit(
                    f"--mesh: bad layout {tok!r} — expected "
                    f'comma-separated "GSxR" tokens, e.g. "1x2,2x2,4x2"')
        rc = run_mesh_sweep(layouts,
                            groups_per_shard=args.groups_per_shard,
                            steps=args.steps, payload=args.payload,
                            burst=args.burst, json_path=args.json,
                            read_ratio=args.read_ratio,
                            obs=shared_obs, on_cluster=on_cluster)
        if exporter is not None:
            exporter.close()
        return rc

    if args.groups is None:
        args.groups = "1,2,4,8"
    if args.replicas is None:
        args.replicas = 3
    gs = [int(g) for g in str(args.groups).split(",") if g]
    print(f"shard_bench: G sweep {gs}, R={args.replicas}, "
          f"{args.steps} steps, "
          f"driver={'burst' if args.burst else 'step'}")
    scaling = {}
    for G in gs:
        row = run_one(G, replicas=args.replicas, steps=args.steps,
                      payload=args.payload, burst=args.burst,
                      json_path=args.json,
                      read_ratio=args.read_ratio,
                      zipf=args.zipf, zipf_n_keys=args.zipf_keys,
                      obs=shared_obs, on_cluster=on_cluster)
        scaling[G] = row
    emit("shard_scaling",
         detail={str(G): dict(
             ops_per_sec=scaling[G]["value"],
             dispatch_per_step=scaling[G]["detail"]["dispatch_per_step"])
             for G in gs},
         json_path=args.json)
    base = gs[0]
    for G in gs[1:]:
        speedup = scaling[G]["value"] / max(scaling[base]["value"], 1e-9)
        print(f"  aggregate G={G} vs G={base}: {speedup:.2f}x")
    if exporter is not None:
        exporter.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
