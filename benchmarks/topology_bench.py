#!/usr/bin/env python
"""Elastic-split payoff — autonomous topology vs every static G.

The device group count G is frozen at compile time, so the classic
answer to a skewed keyspace is "pick a better G up front". This bench
shows why that answer loses: under a Zipf-shaped offered load
(``arrival_traces.zipf_keys``) the hottest keys hash into ONE group
whose per-step batch ceiling caps aggregate admission no matter which
static G you picked, while the SAME cluster with the topology policy
attached detects the sustained skew (stock ``topology_group_skew``
rule → ``AlertEngine.add_hook`` → ``propose_split``), carves the hot
range out online, and admits what the static ceilings dropped.

Methodology — alternating best-of rounds on fresh clusters (the
shared A/B discipline): each round runs every static-G variant and
the autonomous variant once, interleaved; each variant keeps its best
round. The headline ``topology_split_speedup`` row is autonomous
ops/s over the BEST static G's ops/s, with the policy/controller
evidence (transitions, installed rules, per-group admission) in the
detail — a ratio above 1.0 means the online split beat every
compile-time G choice on the identical offered trace.

Admission (client puts accepted into group logs during the timed
window) is the measured rate: topology SEED records are protocol
traffic, not client work, so counting committed entries would flatter
the autonomous variant; admission counts only what the client got in.
The unit is ops per PROTOCOL STEP, not wall seconds: a protocol step
is one fused device dispatch regardless of G (``dispatch_per_step ==
1.0`` — shard_bench's headline), so the step is the clock on which
all G choices cost the same on the real device, while host-simulated
step wall time grows with G and would bias the cross-G comparison.
Step-domain admission is also fully deterministic — the CI smoke
re-derives the identical ratio. Wall ops/s rides in each row's detail.

    python benchmarks/topology_bench.py --steps 160 --rounds 2
"""

import argparse
import os
import sys
import time
from collections import deque

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_variant(G: int, *, topo: bool, steps: int,
                offered_per_step: int, zipf_s: float, zipf_n_keys: int,
                replicas: int = 3, skew_ratio: float = 1.5,
                adapt_steps: int = 120, cfg=None):
    """One fresh cluster driven through the seeded Zipf trace; returns
    (admitted_ops_per_step, evidence_detail). ``adapt_steps`` run the
    identical offered load UNTIMED first — the autonomous variant
    detects the skew and completes its transitions there, the statics
    reach their backlogged steady state — so the timed window compares
    converged behavior, not transition transients."""
    from benchmarks.arrival_traces import zipf_keys
    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.obs import AlertEngine, Observability
    from rdma_paxos_tpu.runtime import reads as reads_mod
    from rdma_paxos_tpu.shard import ShardedCluster
    from rdma_paxos_tpu.shard.kvs import ShardedKVS

    if cfg is None:
        cfg = LogConfig(n_slots=1024, slot_bytes=128,
                        window_slots=32, batch_slots=8)
    sc = ShardedCluster(cfg, replicas, G)
    obs = Observability()
    sc.obs = obs
    kvs = ShardedKVS(sc, cap=4096)
    reads_mod.attach(sc)
    ctl = engine = None
    if topo:
        from rdma_paxos_tpu.topology import attach_topology
        from rdma_paxos_tpu.topology.policy import TopologyPolicy
        engine = AlertEngine(obs.metrics, rules=[])
        pol = TopologyPolicy(window=16, skew_ratio=skew_ratio,
                             for_evals=4, cooldown_evals=8)
        ctl = attach_topology(kvs, policy=pol, alerts=engine,
                              cooldown_steps=8)
    sc.place_leaders()
    B = cfg.batch_slots
    blob = b"x" * 32
    trace = zipf_keys(offered_per_step * (adapt_steps + steps + 68),
                      s=zipf_s, n_keys=zipf_n_keys, seed=0)
    admitted_pg = [0] * G
    clock = dict(t=0)

    def pump_step(pending) -> int:
        """One protocol step: admit pending client puts up to the
        per-group batch ceiling (frozen-range keys deferred while the
        transition window holds them), then step + drive + evaluate."""
        sent = [0] * G
        kept = []
        # bounded head scan: routing every backlogged key every step
        # would charge variants O(backlog) host work — the cap makes
        # the per-step scan cost identical across variants
        scanned, limit = 0, 4 * G * B
        while pending and scanned < limit:
            k = pending.popleft()
            scanned += 1
            if ctl is not None and ctl.would_block(k):
                kept.append(k)
                continue
            g = kvs.group_of(k)
            if sent[g] >= B:
                kept.append(k)
                continue
            kvs.groups[g].put(sc.leader_hint(g), k, blob)
            sent[g] += 1
            admitted_pg[g] += 1
        pending.extendleft(reversed(kept))      # keep FIFO order
        sc.step()
        clock["t"] += 1
        if ctl is not None:
            ctl.drive()
            # drivers evaluate alerts on a poll cadence, not per step
            # — a full registry snapshot every step would charge the
            # autonomous variant host work no deployment pays
            if clock["t"] % 4 == 0:
                engine.evaluate()
        return sum(sent)

    # warmup: every pool key written once (the split's median scan
    # reads the keyspace from the store) + compile both step variants
    seedq = deque(sorted(set(trace)))
    while seedq:
        pump_step(seedq)
    sc.step()
    sc.step()
    for g in range(G):
        admitted_pg[g] = 0

    pending = deque()
    pos = 0
    for _ in range(adapt_steps):        # untimed: converge first
        pending.extend(trace[pos:pos + offered_per_step])
        pos += offered_per_step
        pump_step(pending)
    # close out any transition still open at the adaptation boundary
    # (bounded): the timed window measures the converged routing, not
    # a half-seeded one
    closeout = 0
    while (ctl is not None and ctl.in_window() and closeout < 64):
        pending.extend(trace[pos:pos + offered_per_step])
        pos += offered_per_step
        pump_step(pending)
        closeout += 1
    for g in range(G):
        admitted_pg[g] = 0
    admitted = 0
    timed_base = pos
    t0 = time.perf_counter()
    for _ in range(steps):
        pending.extend(trace[pos:pos + offered_per_step])
        pos += offered_per_step
        admitted += pump_step(pending)
    dt = time.perf_counter() - t0
    detail = dict(
        groups=G, replicas=replicas, steps=steps,
        adapt_steps=adapt_steps, closeout_steps=closeout,
        autonomous=topo, seconds=round(dt, 3),
        wall_ops_per_sec=round(admitted / dt, 1),
        offered=pos - timed_base, admitted=admitted,
        backlog_end=len(pending),
        admitted_per_group=admitted_pg,
        zipf=dict(s=zipf_s, n_keys=zipf_n_keys))
    if ctl is not None:
        st = ctl.status()
        detail["topology"] = dict(
            transitions=st["transitions_total"],
            abandoned=st["abandoned_total"],
            epoch=st["epoch"],
            overrides=[r.to_dict() for r in kvs.router.overrides],
            policy=st["policy"])
    return admitted / steps, detail


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--static-groups", default="2,4",
                    help="static G values the autonomous variant "
                         "must beat (comma-separated)")
    ap.add_argument("--topo-groups", type=int, default=4,
                    help="G for the autonomous (policy-attached) run")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--steps", type=int, default=160,
                    help="timed protocol steps per variant")
    ap.add_argument("--offered", type=int, default=24,
                    help="client puts offered per step")
    ap.add_argument("--zipf-s", type=float, default=0.9,
                    help="Zipf exponent of the offered key shape")
    ap.add_argument("--zipf-keys", type=int, default=32,
                    help="distinct keys in the pool")
    ap.add_argument("--rounds", type=int, default=2,
                    help="alternating best-of rounds per variant")
    ap.add_argument("--json", default=None,
                    help="append JSON result rows to this file")
    args = ap.parse_args(argv)

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/rp_jax_cache")
    import jax
    if os.environ.get("RP_BENCH_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")

    from benchmarks.reporting import emit

    static_gs = [int(g) for g in str(args.static_groups).split(",")
                 if g]
    variants = [(f"static_G{g}", g, False) for g in static_gs]
    variants.append((f"auto_G{args.topo_groups}", args.topo_groups,
                     True))
    kw = dict(steps=args.steps, offered_per_step=args.offered,
              zipf_s=args.zipf_s, zipf_n_keys=args.zipf_keys,
              replicas=args.replicas)
    print(f"topology_bench: static G {static_gs} vs autonomous "
          f"G={args.topo_groups}, zipf s={args.zipf_s} over "
          f"{args.zipf_keys} keys, {args.offered} offered/step, "
          f"{args.steps} steps x {args.rounds} round(s)")
    best = {}
    for r in range(args.rounds):
        for label, G, topo in variants:      # alternating best-of
            ops, detail = run_variant(G, topo=topo, **kw)
            print(f"  round {r} {label}: {ops:.2f} admitted ops/step "
                  f"(backlog {detail['backlog_end']}, "
                  f"{detail['wall_ops_per_sec']:.0f} wall ops/s)")
            if label not in best or ops > best[label][0]:
                best[label] = (ops, detail)
    for label, (ops, detail) in best.items():
        emit("topology_variant_admitted_ops_per_step", round(ops, 2),
             "ops/step", detail=dict(variant=label, **detail),
             json_path=args.json)
    auto_label = variants[-1][0]
    auto_ops, auto_detail = best[auto_label]
    stat_label = max((l for l in best if l != auto_label),
                     key=lambda l: best[l][0])
    speedup = auto_ops / max(best[stat_label][0], 1e-9)
    emit("topology_split_speedup", round(speedup, 3), "ratio",
         detail=dict(
             autonomous=auto_label,
             autonomous_ops_per_step=round(auto_ops, 2),
             best_static=stat_label,
             best_static_ops_per_step=round(best[stat_label][0], 2),
             statics={l: round(best[l][0], 2) for l in best
                      if l != auto_label},
             transitions=auto_detail.get("topology", {}).get(
                 "transitions"),
             overrides=auto_detail.get("topology", {}).get(
                 "overrides")),
         json_path=args.json)
    print(f"  speedup: {auto_label} {auto_ops:.2f} vs best static "
          f"{stat_label} {best[stat_label][0]:.2f} ops/step "
          f"-> {speedup:.2f}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
