#!/usr/bin/env python
"""The reference's EXACT headline benchmark: ``redis-benchmark -t set``
against the leader of a replicated group of pristine Redis servers under
``LD_PRELOAD=interpose.so`` (``benchmarks/run.sh:73-82``).

Builds Redis 2.8.17 from the reference tree's vendored upstream tarball
(the version ``apps/redis/mk`` targets), boots N replicas + the consensus
driver, elects, runs redis-benchmark with the reference's flags, and
checks follower state equality (DBSIZE).

    python benchmarks/redis_bench.py --replicas 3 -n 10000 -c 8 -P 64
"""

import argparse
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
TARBALL = "/root/reference/apps/redis/redis-2.8.17.tar.gz"
BUILD_ROOT = "/tmp/rp_redis_build"
SRC = os.path.join(BUILD_ROOT, "redis-2.8.17", "src")


def ensure_redis() -> str:
    """Build pristine Redis once from the reference tree's vendored
    upstream tarball; returns the redis-server path. Raises
    FileNotFoundError (no tarball) or RuntimeError (build failure) —
    the single build recipe shared by the bench and the e2e tests."""
    server = os.path.join(SRC, "redis-server")
    if os.path.exists(server):
        return server
    if not os.path.exists(TARBALL):
        raise FileNotFoundError("reference redis tarball unavailable")
    os.makedirs(BUILD_ROOT, exist_ok=True)
    subprocess.run(["tar", "xzf", TARBALL], cwd=BUILD_ROOT, check=True)
    r = subprocess.run(["make", "MALLOC=libc", "-j1"],
                       cwd=os.path.join(BUILD_ROOT, "redis-2.8.17"),
                       capture_output=True, timeout=900)
    if r.returncode != 0 or not os.path.exists(server):
        raise RuntimeError("redis build failed: %s"
                           % r.stderr.decode()[-300:])
    return server


def resp(port, line):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    f = s.makefile("rb")
    s.sendall(line + b"\r\n")
    out = f.readline().strip()
    s.close()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("-n", type=int, default=10000)
    ap.add_argument("-c", type=int, default=8)
    ap.add_argument("-P", type=int, default=64,
                    help="redis-benchmark pipeline depth")
    ap.add_argument("-r", type=int, default=0,
                    help="randomize keys over this keyspace (stronger "
                         "follower-equality evidence than the default "
                         "single-key workload)")
    ap.add_argument("--port-base", type=int, default=9860)
    ap.add_argument("--profile", action="store_true",
                    help="wall-time phase accounting of the driver poll "
                         "loop (device step / replay / apply / sync sums)")
    ap.add_argument("--n-slots", type=int, default=8192)
    ap.add_argument("--slot-bytes", type=int, default=256)
    ap.add_argument("--window-slots", type=int, default=1024)
    ap.add_argument("--batch-slots", type=int, default=1024)
    ap.add_argument("--fanout", default="psum",
                    choices=("psum", "gather"),
                    help="window fan-out: psum is the production "
                         "full-connectivity config (O(W) per replica)")
    ap.add_argument("--sync-period", type=float, default=0.2,
                    help="store fdatasync cadence (durability matches "
                         "the reference's quorum-memory contract)")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="driver dispatch-pipeline depth (0/1 = fully "
                         "serial loop)")
    ap.add_argument("--ab-pipeline", type=int, default=2,
                    help="rounds per variant for the pipeline on/off "
                         "A/B (alternating best-of); emits a "
                         "pipeline_speedup row. 0 disables")
    ap.add_argument("--scan", type=int, default=0, metavar="K",
                    help="device-resident K-window scan tier (see "
                         "run_bench --scan): one consolidated "
                         "readback per up-to-K fused steps")
    ap.add_argument("--ab-hostpath", type=int, default=2,
                    help="with --scan: rounds per variant for the "
                         "host-path A/B (vectorized+scan vs scalar "
                         "reference+no-scan, alternating best-of); "
                         "emits host_path_speedup. 0 disables")
    args = ap.parse_args()

    try:
        ensure_redis()
    except (FileNotFoundError, RuntimeError) as e:
        raise SystemExit(str(e))
    # persistent compile cache: burst-tier compiles are seconds each and
    # identical across runs — never pay them twice on one machine
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/rp_jax_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.2")
    import jax
    if os.environ.get("RP_BENCH_CPU", "1") == "1":
        jax.config.update("jax_platforms", "cpu")
    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    cfg = LogConfig(n_slots=args.n_slots, slot_bytes=args.slot_bytes,
                    window_slots=args.window_slots,
                    batch_slots=args.batch_slots)
    ports = [args.port_base + i for i in range(args.replicas)]
    wd = tempfile.mkdtemp(prefix="rp_redisbench_")
    subprocess.run(["make", "-C", NATIVE], check=True,
                   capture_output=True)

    driver = ClusterDriver(
        cfg, args.replicas, workdir=wd, app_ports=ports,
        timeout_cfg=TimeoutConfig(elec_timeout_low=0.5,
                                  elec_timeout_high=1.0),
        fanout=args.fanout, sync_period=args.sync_period,
        pipeline=args.pipeline_depth, scan=bool(args.scan))
    if args.scan:
        from rdma_paxos_tpu.runtime.sim import cap_scan_tiers
        try:
            cap_scan_tiers(driver.cluster, args.scan)
        except ValueError as e:
            raise SystemExit(f"--scan: {e}")
    apps = []
    for r, port in enumerate(ports):
        env = dict(os.environ)
        env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
        env["RP_PROXY_SOCK"] = os.path.join(wd, f"proxy{r}.sock")
        apps.append(subprocess.Popen(
            [os.path.join(SRC, "redis-server"), "--port", str(port),
             "--bind", "127.0.0.1", "--save", "", "--appendonly", "no"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))
    for port in ports:
        while True:
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=2).close()
                break
            except OSError:
                time.sleep(0.1)
    stats = None
    if args.profile:
        # direct wall-time phase accounting on the poll thread (cProfile
        # mis-attributes C-level waits under load): wraps the driver's
        # major sub-phases with monotonic sums
        stats = {"iters": 0, "step_wall": 0.0, "device": 0.0,
                 "replay_fetch": 0.0, "apply": 0.0, "sync": 0.0,
                 "loop_wall": [None, None]}

        def timed(obj, name, key):
            orig = getattr(obj, name)

            def wrap(*a, **kw):
                t0 = time.monotonic()
                try:
                    return orig(*a, **kw)
                finally:
                    stats[key] += time.monotonic() - t0
            setattr(obj, name, wrap)

        timed(driver.cluster, "step", "device")
        timed(driver.cluster, "step_burst", "device")
        timed(driver.cluster, "_replay_committed", "replay_fetch")
        timed(driver, "_apply_new_entries", "apply")
        for rt in driver.runtimes:
            if rt.store is not None:
                timed(rt.store, "sync", "sync")
        orig_step = driver.step

        def stat_step():
            if stats["loop_wall"][0] is None:
                stats["loop_wall"][0] = time.monotonic()
            t0 = time.monotonic()
            try:
                return orig_step()
            finally:
                now = time.monotonic()
                stats["step_wall"] += now - t0
                stats["iters"] += 1
                stats["loop_wall"][1] = now
        driver.step = stat_step
    print("prewarming step/burst compiles...")
    driver.prewarm()
    # idle heartbeat cadence 20 ms (event arrival wakes the loop
    # instantly): on a shared-core host the loop must not busy-poll the
    # CPU away from the app it serves
    driver.run(period=0.02)
    t0 = time.time()
    while driver.leader() < 0:
        time.sleep(0.05)
        if time.time() - t0 > 120:
            raise SystemExit("no leader elected")
    lead = driver.leader()
    print(f"leader: replica {lead} (redis on port {ports[lead]})")

    # the reference's client (run.sh:73-82), with pipelining
    def bench_round():
        cmd = [os.path.join(SRC, "redis-benchmark"), "-p",
               str(ports[lead]), "-t", "set", "-n", str(args.n),
               "-c", str(args.c), "-P", str(args.P)]
        if args.r:
            cmd += ["-r", str(args.r)]
        bench = subprocess.run(cmd, capture_output=True, timeout=600)
        out = bench.stdout.decode()
        rps_r = None
        for l in out.splitlines():
            if "requests per second" in l:
                try:
                    rps_r = float(l.split()[0].strip('"'))
                except ValueError:
                    pass
        return rps_r, out

    from benchmarks.reporting import (
        ab_pipeline_rounds, phase_accumulate, phase_snapshot)

    main_phases: dict = {}
    pre = phase_snapshot(driver)
    rps, out = bench_round()
    phase_accumulate(driver, pre, main_phases)
    print("\n".join(l for l in out.splitlines()
                    if "requests per second" in l or "SET" in l))

    ab_host = None
    if args.scan and args.ab_hostpath > 0:
        # host-path A/B on the REFERENCE headline workload: scalar
        # per-entry host loops + no scan vs the vectorized data plane
        # + K-window scan tier (alternating best-of, same core)
        from benchmarks.reporting import ab_variant_rounds
        from rdma_paxos_tpu.runtime import hostpath as hostpath_mod

        def apply_variant(on: bool):
            hostpath_mod.set_vectorized(on)
            driver.cluster.scan = on

        ab_host = ab_variant_rounds(driver, args.ab_hostpath,
                                    apply_variant,
                                    lambda: bench_round()[0])
        if ab_host["off"] and ab_host["on"]:
            print(f"host-path A/B: {ab_host['off']:.0f} SET/s scalar "
                  f"vs {ab_host['on']:.0f} SET/s vectorized+scan -> "
                  f"{ab_host['on'] / ab_host['off']:.2f}x")

    ab = None
    if args.ab_pipeline > 0 and args.pipeline_depth >= 2:
        # pipeline on/off A/B on the SAME core, same day — alternating
        # best-of rounds (the --audit overhead methodology); the
        # in-flight-depth counter proves the ON rounds overlapped,
        # per-variant phase attribution
        ab = ab_pipeline_rounds(driver, args.ab_pipeline,
                                args.pipeline_depth,
                                lambda: bench_round()[0])
        if ab["off"] and ab["on"]:
            print(f"pipeline A/B: {ab['off']:.0f} SET/s off vs "
                  f"{ab['on']:.0f} SET/s on -> "
                  f"{ab['on'] / ab['off']:.2f}x "
                  f"(max in-flight dispatches {ab['depth_seen']})")

    # follower state equality, the run.sh FindLeader+verify analog
    time.sleep(2.0)
    followers_equal = True
    lead_size = resp(ports[lead], b"DBSIZE")
    for r in range(args.replicas):
        if r == lead:
            continue
        deadline = time.time() + 30
        size = None
        while time.time() < deadline:
            size = resp(ports[r], b"DBSIZE")
            if size == lead_size:
                break
            time.sleep(0.5)
        followers_equal = followers_equal and size == lead_size
        print(f"replica {r} DBSIZE {size.decode()} "
              f"(leader {lead_size.decode()})"
              + ("  OK" if size == lead_size else "  MISMATCH"))

    driver.stop()
    from benchmarks.reporting import emit
    emit("redis_set_ops_per_sec", rps, "ops/s",
         detail=dict(replicas=args.replicas, n=args.n, c=args.c,
                     P=args.P, r=args.r, fanout=args.fanout,
                     pipeline_depth=args.pipeline_depth,
                     followers_equal=followers_equal,
                     phases=dict(sorted(main_phases.items())),
                     leader_dbsize=int(lead_size.lstrip(b":") or 0)),
         obs=driver.obs)
    if ab_host is not None and ab_host["off"] and ab_host["on"]:
        emit("host_path_speedup",
             round(ab_host["on"] / ab_host["off"], 3), "x",
             detail=dict(off_ops_per_sec=ab_host["off"],
                         on_ops_per_sec=ab_host["on"],
                         off_us_per_op=round(1e6 / ab_host["off"], 2),
                         on_us_per_op=round(1e6 / ab_host["on"], 2),
                         rounds=args.ab_hostpath,
                         n_per_round=args.n,
                         scan_k=max(driver.cluster.K_TIERS),
                         scan_dispatches=int(
                             driver.cluster.scan_dispatches),
                         shared_core_caveat=(
                             "alternating best-of on shared CPU "
                             "cores"),
                         phases_on=ab_host["phases_on"],
                         phases_off=ab_host["phases_off"]),
             obs=driver.obs)
    if ab is not None and ab["off"] and ab["on"]:
        emit("pipeline_speedup", round(ab["on"] / ab["off"], 3), "x",
             detail=dict(off_ops_per_sec=ab["off"],
                         on_ops_per_sec=ab["on"],
                         rounds=args.ab_pipeline,
                         n_per_round=args.n,
                         pipeline_depth=args.pipeline_depth,
                         max_inflight_dispatches=ab["depth_seen"],
                         phases_on=ab["phases_on"],
                         phases_off=ab["phases_off"]),
             obs=driver.obs)
    if stats is not None:
        lw = (stats["loop_wall"][1] - stats["loop_wall"][0]
              if stats["loop_wall"][0] is not None else 0.0)
        print(f"phase stats: iters={stats['iters']} "
              f"loop_wall={lw:.2f}s step_wall={stats['step_wall']:.2f}s "
              f"device={stats['device']:.2f}s "
              f"(of which replay_fetch={stats['replay_fetch']:.2f}s) "
              f"apply={stats['apply']:.2f}s sync={stats['sync']:.2f}s "
              f"idle={lw - stats['step_wall']:.2f}s")
    for a in apps:
        a.kill()
        a.wait()


if __name__ == "__main__":
    main()
