"""Seeded arrival-trace generator — offered-load shapes for the
adaptive-dispatch (governor) benches.

Real traffic is not the constant closed loop the flag-overhead benches
drive: it is bursty (request storms between idle valleys), diurnal
(a slow swell and ebb), or steps between regimes (a deploy doubling
load). A static dispatch geometry is tuned for exactly one point on
those curves; the governor's claim is that it tracks all of them. The
traces here make that testable: ``make_trace(shape, ticks, seed=s)``
returns the per-tick entry arrival counts, bit-identical for a given
``(shape, ticks, seed, lo, hi)`` — seeded through the string-seeded
RNG (PYTHONHASHSEED-independent), the ``GroupStepTimer`` discipline —
so every A/B variant replays the identical offered load and a CI
smoke re-derives the same trace forever.

Stdlib only (the benches import this before jax config lands).
"""

from __future__ import annotations

import bisect
import math
import random
from typing import List

SHAPES = ("bursty", "diurnal", "step")


def zipf_keys(n_ops: int, *, s: float = 1.2, n_keys: int = 64,
              seed: int = 0, prefix: bytes = b"key") -> List[bytes]:
    """``n_ops`` key draws, Zipf(``s``)-distributed over a pool of
    ``n_keys`` distinct keys — the KEY-shape companion to
    :func:`make_trace`'s arrival shapes.

    Rank ``i`` (0 = hottest) is drawn with probability proportional to
    ``1/(i+1)**s``; key NAMES are a seeded shuffle of ``prefix +
    b"%06d" % j`` over the ranks, so hotness is scattered across the
    byte order the way real keyspaces scatter it (a byte-range carve
    of any region carries real weight — rank-ordered names would hide
    all the heat below every median). Inverse-CDF sampling over the
    exact finite harmonic mass — stdlib only, bit-identical for a
    given ``(n_ops, s, n_keys, seed, prefix)`` via the same
    string-seeded RNG discipline as the arrival shapes.
    """
    n_ops, n_keys = int(n_ops), int(n_keys)
    if n_keys <= 0:
        raise ValueError("zipf_keys: n_keys must be positive")
    rng = random.Random(
        f"zipf:{s}:{n_keys}:{seed}:{prefix.decode('latin-1')}")
    names = list(range(n_keys))
    rng.shuffle(names)
    pool = [prefix + b"%06d" % j for j in names]
    cdf: List[float] = []
    acc = 0.0
    for i in range(n_keys):
        acc += 1.0 / float(i + 1) ** s
        cdf.append(acc)
    total = cdf[-1]
    return [pool[bisect.bisect_left(cdf, rng.random() * total)]
            for _ in range(n_ops)]


def make_trace(shape: str, ticks: int, *, seed: int = 0,
               lo: int = 0, hi: int = 128,
               period: int = 0) -> List[int]:
    """Per-tick arrival counts for one offered-load shape.

    * ``bursty`` — square-wave storms: alternating on/off phases of
      jittered length; on-phase ticks arrive near ``hi``, off-phase
      ticks near ``lo`` (idle valleys — where idle quiescence and
      tier descent earn their keep).
    * ``diurnal`` — one full sinusoidal swell over the trace (or per
      ``period`` ticks): the slow ramp that walks the governor up and
      down the whole ladder.
    * ``step`` — ``lo``-trickle first half, ``hi`` second half: the
      regime change (deploy / failover) that tests climb speed.

    Deterministic per ``(shape, ticks, seed, lo, hi, period)``.
    """
    if shape not in SHAPES:
        raise ValueError(f"unknown trace shape {shape!r} "
                         f"(known: {SHAPES})")
    ticks = int(ticks)
    rng = random.Random(f"arrival:{shape}:{seed}:{lo}:{hi}:{period}")
    out: List[int] = []
    if shape == "bursty":
        phase_hi = max(2, (period or max(ticks // 10, 8)) // 2)
        on = False
        while len(out) < ticks:
            length = rng.randint(max(2, phase_hi // 2), phase_hi * 2)
            for _ in range(min(length, ticks - len(out))):
                if on:
                    out.append(max(0, int(hi * rng.uniform(0.7, 1.3))))
                else:
                    out.append(int(lo * rng.uniform(0.0, 1.0)))
            on = not on
    elif shape == "diurnal":
        p = period or ticks
        for t in range(ticks):
            level = 0.5 - 0.5 * math.cos(2 * math.pi * t / max(p, 1))
            rate = lo + (hi - lo) * level
            out.append(max(0, int(rate * rng.uniform(0.9, 1.1))))
    else:  # step
        cut = ticks // 2
        for t in range(ticks):
            rate = lo if t < cut else hi
            out.append(max(0, int(rate * rng.uniform(0.9, 1.1))))
    return out
