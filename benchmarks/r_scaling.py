#!/usr/bin/env python
"""Per-replica step cost vs group size R, under shard_map at BENCH
geometry — the flat-in-R evidence for ANALYSIS_R_SCALING.md.

Every topology available in this environment executes all R replicas'
device work on one execution unit (virtual CPU devices share one core),
so total step time grows ~linearly with R; what the design controls —
and what a real R-chip mesh runs per chip — is step time DIVIDED BY R.
This driver measures exactly that, with the honest protocol (timed
region ends with a value read), at the same geometry bench.py runs
(n_slots=8192, slot_bytes=128, window=batch=2048), psum fan-out.

    python benchmarks/r_scaling.py [--json out.json]
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_row(R: int, iters: int) -> dict:
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--row", str(R), "--iters", str(iters)],
        capture_output=True, text=True)
    for ln in proc.stdout.splitlines():
        if ln.startswith("ROWJSON:"):
            return json.loads(ln[len("ROWJSON:"):])
    raise RuntimeError("R=%d failed: %s" % (R, proc.stderr[-2000:]))


def measure(R: int, iters: int) -> dict:
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import time

    import jax.numpy as jnp
    import numpy as np

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.consensus.log import (
        EntryType, M_LEN, M_TYPE, META_W)
    from rdma_paxos_tpu.consensus.step import StepInput
    from rdma_paxos_tpu.parallel.mesh import (
        build_spmd_burst, build_spmd_step, make_replica_mesh,
        stack_states)

    cfg = LogConfig(n_slots=8192, slot_bytes=128, window_slots=2048,
                    batch_slots=2048)
    mesh = make_replica_mesh(R)
    shard = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("replica"))
    kshard = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, "replica"))
    B, K = cfg.batch_slots, 8
    data = jax.device_put(
        np.zeros((K, R, B, cfg.slot_words), np.int32), kshard)
    meta_np = np.zeros((K, R, B, META_W), np.int32)
    meta_np[:, :, :, M_TYPE] = int(EntryType.SEND)
    meta_np[:, :, :, M_LEN] = 16
    meta = jax.device_put(meta_np, kshard)
    count = jax.device_put(np.full((K, R), B, np.int32), kshard)
    peer = jax.device_put(np.ones((R, R), np.int32), shard)

    step = build_spmd_step(cfg, R, mesh, fanout="psum", donate=False)
    burst = build_spmd_burst(cfg, R, mesh, fanout="psum")
    state = jax.device_put(stack_states(cfg, R, R), shard)
    inp = StepInput(
        batch_data=jax.device_put(
            np.zeros((R, B, cfg.slot_words), np.int32), shard),
        batch_meta=jax.device_put(
            np.zeros((R, B, META_W), np.int32), shard),
        batch_count=jax.device_put(np.zeros((R,), np.int32), shard),
        timeout_fired=jax.device_put(
            np.zeros((R,), np.int32).copy(), shard).at[0].set(1),
        peer_mask=peer,
        apply_done=jax.device_put(np.zeros((R,), np.int32), shard),
        queue_depth=jax.device_put(np.zeros((R,), np.int32), shard))
    state, _ = step(state, inp)            # election

    applied = jax.device_put(np.zeros((R,), np.int32), shard)
    qd = jax.device_put(np.zeros((R,), np.int32), shard)
    state, outs = burst(state, data, meta, count, peer,
                        applied, qd)       # warmup compile + run
    jax.block_until_ready(outs.commit)
    pre = int(np.asarray(state.commit)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        applied = state.commit.copy()      # echo applies => pruning (copy:
        # burst donates the state; the same buffer cannot also be an arg)
        state, outs = burst(state, data, meta, count, peer,
                            applied, qd)
    final = int(np.asarray(state.commit)[0])   # forces drain (uniform
    dt = time.perf_counter() - t0              # protocol w/ bench.py)
    steps = iters * K
    return dict(R=R, step_us=dt / steps * 1e6,
                per_replica_us=dt / steps / R * 1e6,
                committed=final - pre,
                ops=float((final - pre) / dt))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--row", type=int, default=None)
    ap.add_argument("--iters", type=int, default=6)
    args = ap.parse_args()
    if args.row is not None:
        print("ROWJSON:" + json.dumps(measure(args.row, args.iters)))
        return
    rows = [run_row(R, args.iters) for R in (3, 5, 7)]
    out = dict(metric="per_replica_step_cost_vs_R",
               topology="shard_map over virtual CPU devices "
                        "(one core!), bench geometry, psum fan-out",
               rows=rows)
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    from benchmarks.reporting import emit
    emit("per_replica_step_cost_vs_R", rows[0]["per_replica_us"], "us",
         detail=dict(topology=out["topology"], rows=rows))


if __name__ == "__main__":
    main()
