#!/usr/bin/env python
"""Chaos bench — nemesis throughput + checker cost for BENCH_* rounds.

Runs one (or several) seeded nemesis schedules through
``rdma_paxos_tpu.chaos.runner.NemesisRunner`` and reports what a
perf-PR gate needs: steps/s under fault injection, client ops checked,
linearizability-search states explored, and the verdict — so later
optimization rounds can demonstrate "still correct under chaos" with
one JSON line per seed.

    python benchmarks/chaos_bench.py --seed 7 --replicas 3 --steps 200
    python benchmarks/chaos_bench.py --seeds 0-9 --replicas 5 --json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_seeds(spec: str):
    out = []
    for part in spec.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--seeds", type=str, default=None,
                    help="e.g. 0-4 or 1,3,9 (overrides --seed)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--keys", type=int, default=3)
    ap.add_argument("--json", action="store_true",
                    help="one JSON result line per seed")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    seeds = (parse_seeds(args.seeds) if args.seeds
             else [args.seed if args.seed is not None else 0])
    failures = 0
    for i, seed in enumerate(seeds):
        t0 = time.perf_counter()
        runner = NemesisRunner(n_replicas=args.replicas, seed=seed,
                               steps=args.steps,
                               n_clients=args.clients,
                               n_keys=args.keys)
        verdict = runner.run()
        dt = time.perf_counter() - t0
        linz = verdict["linearizability"]
        states = linz["states"]        # checker search cost, from run()
        row = dict(
            seed=seed, replicas=args.replicas, steps=args.steps,
            ok=verdict["ok"],
            elapsed_s=round(dt, 3),
            steps_per_s=round((args.steps + runner.settle_steps) / dt,
                              1),
            schedule_events=verdict["schedule_events"],
            client_ops=verdict["client_ops"],
            checked_ops=linz["ops"],
            checker_states=states,
            invariant_violations=len(verdict["invariant_violations"]),
            linearizability_ok=linz["ok"],
            artifact=verdict.get("artifact"),
            warm=i > 0,     # first seed pays the one-time JIT compile
        )
        if args.json:
            print(json.dumps(row))
        else:
            print("seed %3d: %s  %6.2fs (%5.1f steps/s)  ops=%d "
                  "checked=%d states=%d%s"
                  % (seed, "OK  " if row["ok"] else "FAIL",
                     row["elapsed_s"], row["steps_per_s"],
                     row["client_ops"], row["checked_ops"],
                     row["checker_states"],
                     ("  artifact=" + row["artifact"])
                     if row["artifact"] else ""))
        from benchmarks.reporting import emit
        emit("chaos_steps_per_sec", row["steps_per_s"], "steps/s",
             detail=row, obs=runner.obs)
        if not verdict["ok"]:
            failures += 1
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
