#!/usr/bin/env python
"""Commit-latency benchmark — the p99<50µs frontier (BASELINE.md).

The reference commits in single-digit µs via a busy RDMA commit loop
(``rc_write_remote_logs(wait_for_commit=1)``, ``dare_ibv_rc.c:1870-1948``);
BASELINE.md sets the TPU target at p99 commit < 50 µs. This bench measures
the regimes that bound the TPU design:

* **bare mode** — a trivial jitted program's dispatch percentiles: the
  environment's irreducible host→device round-trip floor, the yardstick
  the step dispatch is judged against.
* **dispatch mode** — one host→device dispatch per protocol step at small
  batch (1..64): the client-visible commit latency of a step-per-poll
  driver. Reports p50/p95/p99 over individual dispatches.
* **pipelined mode** — D step dispatches kept in flight (async dispatch;
  block only on the oldest): per-step completion interval of an
  overlapped driver — the dispatch-overlap analog of the reference's
  busy commit loop always having work posted on the NIC.
* **scan mode** — K steps fused into one dispatch (``lax.scan``): the
  amortized per-step device latency — the floor a multi-step burst
  driver approaches.

CRITICAL HARNESS RULE (measured, round 5): every input array is PASSED AS
AN ARGUMENT to the jitted step — a closure-captured jnp/np array becomes
a lifted executable constant, and on the tunneled TPU backend any program
carrying lifted constants pays a flat ~100 ms per dispatch. That artifact
was the entirety of round 4's "123 ms dispatch floor".

Config is latency-tuned (small ring/window — ring gather cost scales with
rows), 3 replicas, psum fan-out, Pallas quorum scan on TPU.

    python benchmarks/latency_bench.py [--json out.json]
"""

import argparse
import collections
import dataclasses
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType, M_LEN, M_TYPE, META_W
from rdma_paxos_tpu.consensus.step import StepInput, replica_step
from rdma_paxos_tpu.parallel.mesh import REPLICA_AXIS, stack_states

R = 3
K_SCAN = 256


def _pcts(lat):
    lat = sorted(lat)
    n = len(lat)
    return dict(p50_us=float(lat[n // 2] * 1e6),
                p95_us=float(lat[int(n * .95)] * 1e6),
                p99_us=float(lat[min(int(n * .99), n - 1)] * 1e6))


def measure_bare(iters: int = 400):
    """Dispatch percentiles of a trivial program — the environment floor."""
    @jax.jit
    def triv(x):
        return x + 1
    x = jnp.zeros((8,), jnp.int32)
    x = triv(x)
    x.block_until_ready()
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        y = triv(x)
        y.block_until_ready()
        lat.append(time.perf_counter() - t0)
    return _pcts(lat)


def build(cfg: LogConfig, batch: int, use_pallas=None):
    if use_pallas is None:
        # the Pallas quorum kernel pays a fixed launch cost that only
        # amortizes at throughput geometry; the latency profile uses the
        # jnp scan
        use_pallas = (jax.default_backend() == "tpu"
                      and cfg.batch_slots >= 64)
    # the hot path dispatches the STABLE step (elections statically
    # removed — exactly what the production driver runs between timer
    # events); elections use the full step
    core = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum", elections=False)
    full = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum", elections=True)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    vfull = jax.vmap(full, in_axes=(0, 0), axis_name=REPLICA_AXIS)

    # input arrays built EAGERLY and passed as arguments (see module
    # docstring: captured constants poison dispatch on this backend)
    data = jnp.zeros((R, cfg.batch_slots, cfg.slot_words), jnp.int32)
    meta = jnp.zeros((R, cfg.batch_slots, META_W), jnp.int32)
    meta = meta.at[:, :, M_TYPE].set(int(EntryType.SEND))
    meta = meta.at[:, :, M_LEN].set(16)
    peer = jnp.ones((R, R), jnp.int32)
    consts = (data, meta, peer)

    def make_inp(state, count, data, meta, peer):
        return StepInput(
            batch_data=data, batch_meta=meta,
            batch_count=jnp.full((R,), count, jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=peer, apply_done=state.commit,
            queue_depth=jnp.zeros((R,), jnp.int32))

    @jax.jit
    def one(state, data, meta, peer):
        st, out = vstep(state, make_inp(state, batch, data, meta, peer))
        return st, out.commit[0]

    @jax.jit
    def scan_k(state, data, meta, peer):
        def body(st, _):
            st, out = vstep(st, make_inp(st, batch, data, meta, peer))
            return st, out.commit[0]
        return jax.lax.scan(body, state, None, length=K_SCAN)

    @jax.jit
    def elect(state, data, meta, peer):
        inp = dataclasses.replace(
            make_inp(state, 0, data, meta, peer),
            timeout_fired=jnp.zeros((R,), jnp.int32).at[0].set(1))
        st, _ = vfull(state, inp)
        return st

    return elect, one, scan_k, consts


def measure(cfg: LogConfig, batch: int, iters: int = 400,
            use_pallas=None, pipeline_depth: int = 4):
    elect, one, scan_k, consts = build(cfg, batch, use_pallas)
    state = stack_states(cfg, R, R)
    state = elect(state, *consts)
    # warmup / compile
    state, c = one(state, *consts)
    jax.block_until_ready(c)
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state, c = one(state, *consts)
        c.block_until_ready()
        lat.append(time.perf_counter() - t0)
    disp = _pcts(lat)

    # pipelined mode: keep D dispatches in flight; each iteration blocks
    # only on the oldest commit result. The completion interval is the
    # sustained per-step latency of an overlapped driver.
    q = collections.deque()
    for _ in range(pipeline_depth):
        state, c = one(state, *consts)
        q.append(c)
    intervals = []
    t_prev = time.perf_counter()
    for _ in range(iters):
        state, c = one(state, *consts)
        q.append(c)
        q.popleft().block_until_ready()
        t_now = time.perf_counter()
        intervals.append(t_now - t_prev)
        t_prev = t_now
    while q:
        q.popleft().block_until_ready()
    pipe = _pcts(intervals)

    # scan mode: amortized per-step latency; throughput from the REAL
    # commit advance (the ring's capacity clamp may throttle below
    # batch/step — never assume)
    state2 = stack_states(cfg, R, R)
    state2 = elect(state2, *consts)
    state2, cs = scan_k(state2, *consts)          # compile
    jax.block_until_ready(cs)
    c0 = int(np.asarray(state2.commit[0]))
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        state2, cs = scan_k(state2, *consts)
    jax.block_until_ready(cs)
    dt = time.perf_counter() - t0
    per_step_us = dt / (reps * K_SCAN) * 1e6
    committed = int(np.asarray(state2.commit[0])) - c0
    return dict(batch=batch, dispatch=disp,
                pipelined=dict(depth=pipeline_depth, **pipe),
                scan_step_us=float(per_step_us),
                commit_throughput_scan=float(committed / dt))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--iters", type=int, default=400)
    args = ap.parse_args()

    bare = measure_bare(args.iters)
    # latency profile: small ring/window/batch (gather and scatter cost
    # scales with rows; the reference's production profile likewise
    # shrinks its cadence for latency, target/nodes.local.cfg:23-28).
    # Throughput profile: the geometry the redis bench drives.
    lat_cfg = LogConfig(n_slots=256, slot_bytes=64, window_slots=16,
                        batch_slots=8)
    thr_cfg = LogConfig(n_slots=256, slot_bytes=64, window_slots=64,
                        batch_slots=64)
    rows = [measure(lat_cfg, 1, args.iters),
            measure(lat_cfg, 8, args.iters),
            measure(thr_cfg, 64, args.iters)]
    for row, c in zip(rows, (lat_cfg, lat_cfg, thr_cfg)):
        row["config"] = dict(n_slots=c.n_slots, slot_bytes=c.slot_bytes,
                             window_slots=c.window_slots,
                             batch_slots=c.batch_slots)
    out = dict(
        metric="commit_latency_frontier",
        backend=jax.default_backend(),
        replicas=R,
        target_p99_us=50.0,
        bare_dispatch=bare,
        batch1_vs_bare_p99=round(rows[0]["dispatch"]["p99_us"]
                                 / bare["p99_us"], 2),
        rows=rows,
    )
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
