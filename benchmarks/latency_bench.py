#!/usr/bin/env python
"""Commit-latency benchmark — the p99<50µs frontier (BASELINE.md).

The reference commits in single-digit µs via a busy RDMA commit loop
(``rc_write_remote_logs(wait_for_commit=1)``, ``dare_ibv_rc.c:1870-1948``);
BASELINE.md sets the TPU target at p99 commit < 50 µs. This bench measures
the regimes that bound the TPU design:

* **bare mode** — a trivial jitted program's dispatch percentiles: the
  environment's irreducible host→device round-trip floor, the yardstick
  the step dispatch is judged against.
* **dispatch mode** — one host→device dispatch per protocol step at small
  batch (1..64): the client-visible commit latency of a step-per-poll
  driver. Reports p50/p95/p99 over individual dispatches.
* **pipelined mode** — D step dispatches kept in flight (async dispatch;
  block only on the oldest): per-step completion interval of an
  overlapped driver — the dispatch-overlap analog of the reference's
  busy commit loop always having work posted on the NIC.
* **scan mode** — K steps fused into one dispatch (``lax.scan``): the
  amortized per-step device latency — the floor a multi-step burst
  driver approaches.

CRITICAL HARNESS RULE (measured, round 5): every input array is PASSED AS
AN ARGUMENT to the jitted step — a closure-captured jnp/np array becomes
a lifted executable constant, and on the tunneled TPU backend any program
carrying lifted constants pays a flat ~100 ms per dispatch. That artifact
was the entirety of round 4's "123 ms dispatch floor".

Config is latency-tuned (small ring/window — ring gather cost scales with
rows), 3 replicas, psum fan-out, Pallas quorum scan on TPU.

    python benchmarks/latency_bench.py [--json out.json]
"""

import argparse
import collections
import dataclasses
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType, M_LEN, M_TYPE, META_W
from rdma_paxos_tpu.consensus.step import StepInput, replica_step
from rdma_paxos_tpu.parallel.mesh import REPLICA_AXIS, stack_states

R = 3
K_SCAN = 256


def _pcts(lat):
    lat = sorted(lat)
    n = len(lat)
    return dict(p50_us=float(lat[n // 2] * 1e6),
                p95_us=float(lat[int(n * .95)] * 1e6),
                p99_us=float(lat[min(int(n * .99), n - 1)] * 1e6))


def measure_bare(iters: int = 400):
    """Dispatch percentiles of a trivial program — the environment floor."""
    @jax.jit
    def triv(x):
        return x + 1
    x = jnp.zeros((8,), jnp.int32)
    x = triv(x)
    x.block_until_ready()
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        y = triv(x)
        y.block_until_ready()
        lat.append(time.perf_counter() - t0)
    return _pcts(lat)


def build(cfg: LogConfig, batch: int, use_pallas=None):
    if use_pallas is None:
        # the Pallas quorum kernel pays a fixed launch cost that only
        # amortizes at throughput geometry; the latency profile uses the
        # jnp scan
        use_pallas = (jax.default_backend() == "tpu"
                      and cfg.batch_slots >= 64)
    # the hot path dispatches the STABLE step (elections statically
    # removed — exactly what the production driver runs between timer
    # events); elections use the full step
    core = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum", elections=False)
    full = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum", elections=True)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    vfull = jax.vmap(full, in_axes=(0, 0), axis_name=REPLICA_AXIS)

    # input arrays built EAGERLY and passed as arguments (see module
    # docstring: captured constants poison dispatch on this backend)
    data = jnp.zeros((R, cfg.batch_slots, cfg.slot_words), jnp.int32)
    meta = jnp.zeros((R, cfg.batch_slots, META_W), jnp.int32)
    meta = meta.at[:, :, M_TYPE].set(int(EntryType.SEND))
    meta = meta.at[:, :, M_LEN].set(16)
    peer = jnp.ones((R, R), jnp.int32)
    consts = (data, meta, peer)

    def make_inp(state, count, data, meta, peer):
        return StepInput(
            batch_data=data, batch_meta=meta,
            batch_count=jnp.full((R,), count, jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=peer, apply_done=state.commit,
            queue_depth=jnp.zeros((R,), jnp.int32))

    @jax.jit
    def one(state, data, meta, peer):
        st, out = vstep(state, make_inp(state, batch, data, meta, peer))
        return st, out.commit[0]

    @jax.jit
    def scan_k(state, data, meta, peer):
        def body(st, _):
            st, out = vstep(st, make_inp(st, batch, data, meta, peer))
            return st, out.commit[0]
        return jax.lax.scan(body, state, None, length=K_SCAN)

    @jax.jit
    def elect(state, data, meta, peer):
        inp = dataclasses.replace(
            make_inp(state, 0, data, meta, peer),
            timeout_fired=jnp.zeros((R,), jnp.int32).at[0].set(1))
        st, _ = vfull(state, inp)
        return st

    return elect, one, scan_k, consts


def measure(cfg: LogConfig, batch: int, iters: int = 400,
            use_pallas=None, pipeline_depth: int = 4):
    # every timed sample also lands in an obs registry histogram so the
    # row JSON carries full bucketed distributions (not just the three
    # percentiles) for future BENCH_* rounds
    from rdma_paxos_tpu.obs.metrics import (
        LATENCY_BUCKETS_US as US_BUCKETS, MetricsRegistry)
    reg = MetricsRegistry()
    elect, one, scan_k, consts = build(cfg, batch, use_pallas)
    state = stack_states(cfg, R, R)
    state = elect(state, *consts)
    # warmup / compile
    state, c = one(state, *consts)
    jax.block_until_ready(c)
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state, c = one(state, *consts)
        c.block_until_ready()
        lat.append(time.perf_counter() - t0)
        reg.observe("dispatch_latency_us", lat[-1] * 1e6,
                    buckets=US_BUCKETS, batch=batch)
    disp = _pcts(lat)

    # pipelined mode: keep D dispatches in flight; each iteration blocks
    # only on the oldest commit result. The completion interval is the
    # sustained per-step latency of an overlapped driver.
    q = collections.deque()
    for _ in range(pipeline_depth):
        state, c = one(state, *consts)
        q.append(c)
    intervals = []
    t_prev = time.perf_counter()
    for _ in range(iters):
        state, c = one(state, *consts)
        q.append(c)
        q.popleft().block_until_ready()
        t_now = time.perf_counter()
        intervals.append(t_now - t_prev)
        reg.observe("pipelined_interval_us", (t_now - t_prev) * 1e6,
                    buckets=US_BUCKETS, batch=batch)
        t_prev = t_now
    while q:
        q.popleft().block_until_ready()
    pipe = _pcts(intervals)

    # scan mode: amortized per-step device latency, honest protocol for
    # the relay-tunneled backend: (1) NO host value reads before this
    # point (the first read permanently exits speculative dispatch
    # pipelining); (2) block_until_ready is OPTIMISTIC under that
    # speculation, so the timed region ENDS WITH the commit read, which
    # forces the real device drain. One aggregate region; the single
    # ~100 ms RTT the read adds is amortized over reps*K_SCAN steps.
    state2 = stack_states(cfg, R, R)
    state2 = elect(state2, *consts)
    # compile WITHOUT executing (an executed warmup scan could still be
    # un-drained when the timer starts — block_until_ready is
    # optimistic here — and its device time would bleed into dt)
    scan_c = scan_k.lower(state2, *consts).compile()
    state_pre = state2
    reps = 8
    t0 = time.perf_counter()
    for _ in range(reps):
        state2, cs = scan_c(state2, *consts)
    final = int(np.asarray(state2.commit[0]))     # timed: forces drain
    scan_dt = time.perf_counter() - t0
    per_step_us = scan_dt / (reps * K_SCAN) * 1e6
    committed = final - int(np.asarray(state_pre.commit[0]))

    # honest host-visible number: one step PLUS reading its commit back
    # (the mode a per-step-readback driver lives in on this tunnel; on a
    # directly-attached TPU host D2H is µs-scale and this converges to
    # the dispatch row)
    rb = []
    st3, c3 = one(state2, *consts)
    for _ in range(20):
        t0 = time.perf_counter()
        st3, c3 = one(st3, *consts)
        _ = int(np.asarray(c3))
        rb.append(time.perf_counter() - t0)
    rb.sort()

    return dict(batch=batch, dispatch=disp,
                pipelined=dict(depth=pipeline_depth, **pipe),
                scan_step_us=float(per_step_us),
                commit_throughput_scan=float(committed / scan_dt),
                step_plus_readback_ms_p50=float(rb[len(rb) // 2] * 1e3),
                metrics=reg.snapshot())


# the three measured profiles: latency geometry at batch 1 and 8, and
# the throughput geometry the redis bench drives
ROWS = {
    "1": (dict(n_slots=256, slot_bytes=64, window_slots=16,
               batch_slots=8), 1),
    "8": (dict(n_slots=256, slot_bytes=64, window_slots=16,
               batch_slots=8), 8),
    "64": (dict(n_slots=256, slot_bytes=64, window_slots=64,
                batch_slots=64), 64),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--iters", type=int, default=400)
    # internal: run ONE row and print its JSON (each row runs in a
    # fresh process — on the tunneled backend, dispatch latency of a
    # program degrades once unrelated large executables accumulate in
    # the same process, so rows must not share one)
    ap.add_argument("--row", default=None,
                    choices=list(ROWS) + ["bare"])
    args = ap.parse_args()

    if args.row is not None:
        if args.row == "bare":
            row = measure_bare(args.iters)
        else:
            cfg_kw, batch = ROWS[args.row]
            row = measure(LogConfig(**cfg_kw), batch, args.iters)
            row["config"] = cfg_kw
        row["backend"] = jax.default_backend()
        print("ROWJSON:" + json.dumps(row))
        return

    # the parent NEVER touches the device: a parent-held TPU client
    # time-slices the tunneled chip against the row subprocesses and
    # poisons their numbers
    import subprocess

    def run_row(key):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--row", key, "--iters", str(args.iters)],
            capture_output=True, text=True)
        for ln in proc.stdout.splitlines():
            if ln.startswith("ROWJSON:"):
                return json.loads(ln[len("ROWJSON:"):])
        raise RuntimeError("row %s failed: %s" % (key,
                                                  proc.stderr[-2000:]))

    bare = run_row("bare")
    backend = bare.pop("backend")
    rows = [run_row(key) for key in ROWS]
    for r in rows:
        r.pop("backend", None)
    out = dict(
        metric="commit_latency_frontier",
        backend=backend,
        replicas=R,
        target_p99_us=50.0,
        methodology=(
            "Relay-tunneled backend: the tunnel speculates pure dispatch "
            "streams (block_until_ready is optimistic) and the first "
            "device->host VALUE read permanently drops the process to "
            "~100ms synchronous dispatches. 'dispatch'/'pipelined' rows "
            "time enqueue+optimistic-completion (the client-visible "
            "latency on a directly-attached TPU host, where readback is "
            "us-scale); 'scan_step_us' is true amortized device time "
            "(timed region ends with a drain-forcing read); "
            "'step_plus_readback_ms_p50' is the host-visible per-step "
            "cost ON THIS TUNNEL when reading every step - it measures "
            "the relay RTT, not the protocol. Each row runs in a fresh "
            "process."),
        bare_dispatch=bare,
        batch1_vs_bare_p99=round(rows[0]["dispatch"]["p99_us"]
                                 / bare["p99_us"], 2),
        rows=rows,
    )
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    # the standardized BENCH line (benchmarks.reporting): headline =
    # batch-1 dispatch p99 vs the 50 µs target; bulky per-row registry
    # snapshots stay in the artifact doc only
    from benchmarks.reporting import emit
    emit("commit_latency_frontier",
         rows[0]["dispatch"]["p99_us"], "us",
         detail=dict(
             backend=backend, target_p99_us=50.0,
             bare_p99_us=bare["p99_us"],
             batch1_vs_bare_p99=out["batch1_vs_bare_p99"],
             rows=[{k: v for k, v in r.items() if k != "metrics"}
                   for r in rows]))


if __name__ == "__main__":
    main()
