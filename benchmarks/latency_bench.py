#!/usr/bin/env python
"""Commit-latency benchmark — the p99<50µs frontier (BASELINE.md).

The reference commits in single-digit µs via a busy RDMA commit loop
(``rc_write_remote_logs(wait_for_commit=1)``, ``dare_ibv_rc.c:1870-1948``);
BASELINE.md sets the TPU target at p99 commit < 50 µs. This bench measures
the two regimes that bound the TPU design:

* **dispatch mode** — one host→device dispatch per protocol step at small
  batch (1..64): the client-visible commit latency floor of a step-per-poll
  driver. Reports p50/p95/p99 over individual dispatches.
* **scan mode** — K steps fused into one dispatch (``lax.scan``): the
  amortized per-step device latency with dispatch overhead divided by K —
  the floor a pipelined/multi-step driver approaches.

Config is latency-tuned (small ring/window — ring gather cost scales with
rows), 3 replicas, psum fan-out, Pallas quorum scan on TPU.

    python benchmarks/latency_bench.py [--json out.json]
"""

import argparse
import dataclasses
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType, M_LEN, M_TYPE, META_W
from rdma_paxos_tpu.consensus.step import StepInput, replica_step
from rdma_paxos_tpu.parallel.mesh import REPLICA_AXIS, stack_states

R = 3
K_SCAN = 256


def build(cfg: LogConfig, batch: int, use_pallas=None):
    if use_pallas is None:
        # the Pallas quorum kernel pays a fixed launch cost (~50 µs
        # measured on the tunneled v5e) that only amortizes at
        # throughput geometry; the latency profile uses the jnp scan
        use_pallas = (jax.default_backend() == "tpu"
                      and cfg.batch_slots >= 64)
    # the hot path dispatches the STABLE step (elections statically
    # removed — exactly what the production driver runs between timer
    # events); elections use the full step
    core = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum", elections=False)
    full = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum", elections=True)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    vfull = jax.vmap(full, in_axes=(0, 0), axis_name=REPLICA_AXIS)

    data = jnp.zeros((R, cfg.batch_slots, cfg.slot_words), jnp.int32)
    meta = jnp.zeros((R, cfg.batch_slots, META_W), jnp.int32)
    meta = meta.at[:, :, M_TYPE].set(int(EntryType.SEND))
    meta = meta.at[:, :, M_LEN].set(16)
    peer = jnp.ones((R, R), jnp.int32)

    def make_inp(state, count):
        return StepInput(
            batch_data=data, batch_meta=meta,
            batch_count=jnp.full((R,), count, jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=peer, apply_done=state.commit,
            queue_depth=jnp.zeros((R,), jnp.int32))

    @jax.jit
    def one(state):
        st, out = vstep(state, make_inp(state, batch))
        return st, out.commit[0]

    @jax.jit
    def scan_k(state):
        def body(st, _):
            st, out = vstep(st, make_inp(st, batch))
            return st, out.commit[0]
        return jax.lax.scan(body, state, None, length=K_SCAN)

    @jax.jit
    def elect(state):
        inp = dataclasses.replace(
            make_inp(state, 0),
            timeout_fired=jnp.zeros((R,), jnp.int32).at[0].set(1))
        st, _ = vfull(state, inp)
        return st

    return elect, one, scan_k


def measure(cfg: LogConfig, batch: int, iters: int = 400,
            use_pallas=None):
    elect, one, scan_k = build(cfg, batch, use_pallas)
    state = stack_states(cfg, R, R)
    state = elect(state)
    # warmup / compile
    state, c = one(state)
    jax.block_until_ready(c)
    lat = np.empty(iters)
    for i in range(iters):
        t0 = time.perf_counter()
        state, c = one(state)
        c.block_until_ready()
        lat[i] = time.perf_counter() - t0
    lat.sort()
    disp = dict(
        p50_us=float(lat[iters // 2] * 1e6),
        p95_us=float(lat[int(iters * .95)] * 1e6),
        p99_us=float(lat[int(iters * .99)] * 1e6),
    )
    # scan mode: amortized per-step latency
    state2 = stack_states(cfg, R, R)
    state2 = elect(state2)
    state2, cs = scan_k(state2)          # compile
    jax.block_until_ready(cs)
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        state2, cs = scan_k(state2)
    jax.block_until_ready(cs)
    per_step_us = (time.perf_counter() - t0) / (reps * K_SCAN) * 1e6
    return dict(batch=batch, dispatch=disp,
                scan_step_us=float(per_step_us),
                commit_throughput_scan=float(batch / per_step_us * 1e6))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--iters", type=int, default=400)
    args = ap.parse_args()

    # latency profile: small ring/window/batch (gather and scatter cost
    # scales with rows; the reference's production profile likewise
    # shrinks its cadence for latency, target/nodes.local.cfg:23-28).
    # Throughput profile: the geometry the redis bench drives.
    lat_cfg = LogConfig(n_slots=256, slot_bytes=64, window_slots=16,
                        batch_slots=8)
    thr_cfg = LogConfig(n_slots=256, slot_bytes=64, window_slots=64,
                        batch_slots=64)
    rows = [measure(lat_cfg, 1, args.iters),
            measure(lat_cfg, 8, args.iters),
            measure(thr_cfg, 64, args.iters)]
    for row, c in zip(rows, (lat_cfg, lat_cfg, thr_cfg)):
        row["config"] = dict(n_slots=c.n_slots, slot_bytes=c.slot_bytes,
                             window_slots=c.window_slots,
                             batch_slots=c.batch_slots)
    out = dict(
        metric="commit_latency_frontier",
        backend=jax.default_backend(),
        replicas=R,
        target_p99_us=50.0,
        rows=rows,
    )
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
