#!/usr/bin/env python
"""Reconfiguration benchmark — the ``benchmarks/reconf_bench.sh`` analog.

Scenarios under continuous client load (timings printed like the
reference's ``timer_start/stop`` around re-election,
``reconf_bench.sh:17-25,248-300``):

  remove-leader    — partition the leader; measure time to a new leader
                     and to the first committed write after failover
  remove-follower  — partition a follower; verify commit continues
  add-server       — joint-consensus upsize under load
  evict            — auto-eviction of the dead follower

    python benchmarks/reconf_bench.py [--json RECONF.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/rp_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
import jax  # noqa: E402

if os.environ.get("RP_BENCH_CPU", "1") == "1":
    jax.config.update("jax_platforms", "cpu")

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig  # noqa: E402
from rdma_paxos_tpu.consensus.state import Role  # noqa: E402
from rdma_paxos_tpu.runtime.driver import ClusterDriver  # noqa: E402

CFG = LogConfig(n_slots=1024, slot_bytes=128, window_slots=64,
                batch_slots=64)


def drive_until(driver, cond, timeout=240.0, load_replica=None, counter=[0]):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if load_replica is not None and load_replica() >= 0:
            counter[0] += 1
            driver.cluster.submit(load_replica(), b"load-%d" % counter[0])
        driver.step()
        if cond():
            return time.perf_counter() - t0
    raise TimeoutError


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None,
                    help="write timings as a JSON artifact")
    args = ap.parse_args()
    out = {"metric": "reconfiguration_timings",
           "backend": None, "scenarios": {}}
    # Election timeouts must exceed the per-step cost or timers fire on
    # every iteration and leadership never settles. On the relay-
    # tunneled TPU a host loop that reads results each step pays the
    # ~100 ms relay RTT per step (see LATENCY_r05.json methodology), so
    # the TPU profile scales the reference's 10x-heartbeat rule to that
    # step time; CPU keeps the tight profile.
    if jax.default_backend() == "cpu":
        tcfg = TimeoutConfig(elec_timeout_low=0.05, elec_timeout_high=0.15)
    else:
        tcfg = TimeoutConfig(elec_timeout_low=1.2, elec_timeout_high=2.5)
    d = ClusterDriver(CFG, 8, group_size=5,
                      timeout_cfg=tcfg,
                      auto_evict=False, fail_threshold=30)
    d.prewarm()          # compiles out of the timed windows
    d.cluster.run_until_elected(0)
    drive_until(d, lambda: d.leader() >= 0)
    lead = d.leader()
    print(f"boot: leader={lead}, group=5 (of 8-replica mesh)")

    # --- RemoveLeader ---
    d.cluster.partition([[lead], [r for r in range(8) if r != lead]])
    t = drive_until(d, lambda: d.leader() not in (-1, lead),
                    load_replica=lambda: -1)
    new_lead = d.leader()
    print(f"remove-leader: new leader {new_lead} in {t * 1e3:.0f} ms")
    out["scenarios"]["remove_leader_new_leader_ms"] = round(t * 1e3, 1)
    base = int(d.cluster.last["commit"][new_lead])
    d.cluster.submit(new_lead, b"first-after-failover")
    t = drive_until(
        d, lambda: int(d.cluster.last["commit"][new_lead]) > base)
    print(f"remove-leader: first commit after failover +{t * 1e3:.0f} ms")
    out["scenarios"]["remove_leader_first_commit_ms"] = round(t * 1e3, 1)

    # --- RemoveFollower under load ---
    d.cluster.heal()
    d.step()
    fol = next(r for r in range(5) if r != new_lead and r != lead)
    d.cluster.partition([[x for x in range(8) if x != fol], [fol]])
    base = int(d.cluster.last["commit"][new_lead])
    t = drive_until(
        d, lambda: int(d.cluster.last["commit"][new_lead]) >= base + 50,
        load_replica=lambda: d.leader())
    print(f"remove-follower: 50 commits under failure in {t * 1e3:.0f} ms "
          f"(no interruption)")
    out["scenarios"]["remove_follower_50_commits_ms"] = round(t * 1e3, 1)

    # --- AddServer (upsize 5 -> 7) under load ---
    d.cluster.heal()
    drive_until(d, lambda: d.leader() >= 0)   # settle post-heal elections
    cur_lead = d.leader()
    d.request_membership(0b1111111)
    t = drive_until(
        d, lambda: d._mm.current(cur_lead)["bitmask_new"] == 0b1111111
        and d._config_phase is None,
        load_replica=lambda: d.leader())
    print(f"add-server: upsize 5->7 committed in {t * 1e3:.0f} ms "
          f"under load")
    out["scenarios"]["add_server_upsize_ms"] = round(t * 1e3, 1)

    # --- Evict a dead member ---
    d.auto_evict = True
    d.cluster.partition([[x for x in range(8) if x != 6], [6]])
    t = drive_until(
        d, lambda: not (d._mm.current(d.leader())["bitmask_new"] >> 6) & 1
        if d.leader() >= 0 else False,
        load_replica=lambda: d.leader(), timeout=120)
    print(f"evict: dead member removed in {t * 1e3:.0f} ms")
    out["scenarios"]["evict_dead_member_ms"] = round(t * 1e3, 1)

    d.stop()
    print("all scenarios OK")
    out["backend"] = jax.default_backend()
    out["config"] = dict(n_slots=CFG.n_slots, slot_bytes=CFG.slot_bytes,
                         window_slots=CFG.window_slots,
                         batch_slots=CFG.batch_slots, replicas=8,
                         group_size=5)
    out["notes"] = (
        "in-process driver timings (the reference's reconf_bench.sh "
        "timer_start/stop contract, :17-25); election timeouts %s ms. "
        "On the relay-tunneled TPU every step pays the ~100 ms relay "
        "RTT (per-step readback mode — see LATENCY_r05.json), so "
        "absolute timings there measure tunnel RTT x protocol steps, "
        "not device time."
        % ("50-150" if jax.default_backend() == "cpu" else "1200-2500"))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    from benchmarks.reporting import emit
    emit("reconfiguration_timings",
         out["scenarios"].get("remove_leader_new_leader_ms"), "ms",
         detail=dict(backend=out["backend"],
                     scenarios=out["scenarios"],
                     config=out["config"]),
         obs=d.obs)


if __name__ == "__main__":
    main()
