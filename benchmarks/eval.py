#!/usr/bin/env python
"""Batch experiment harness — the ``eval/eval.py`` analog.

The reference's eval harness configures a cluster from a ``.cfg``, repeats
runs, collects logs, and plots. This one repeats any of the in-repo
benchmarks, aggregates their JSON/stdout results, and writes a summary
(plus a matplotlib plot when available).

    python benchmarks/eval.py --bench device --repeat 3 --out /tmp/eval
"""

import argparse
import json
import os
import re
import statistics
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_device_bench(env):
    out = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                         capture_output=True, text=True, env=env,
                         timeout=900)
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def run_reconf(env):
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks",
                                      "reconf_bench.py")],
        capture_output=True, text=True, env=env, timeout=900)
    res = {}
    for pat, key in [(r"new leader \d+ in (\d+) ms", "failover_ms"),
                     (r"first commit after failover \+(\d+) ms",
                      "first_commit_ms"),
                     (r"upsize 5->7 committed in (\d+) ms", "upsize_ms"),
                     (r"dead member removed in (\d+) ms", "evict_ms")]:
        m = re.search(pat, out.stdout)
        if m:
            res[key] = int(m.group(1))
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", choices=["device", "reconf"],
                    default="device")
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--out", default="/tmp/rp_eval")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    env = dict(os.environ)

    runs = []
    for i in range(args.repeat):
        t0 = time.time()
        r = (run_device_bench(env) if args.bench == "device"
             else run_reconf(env))
        r["_wall_s"] = round(time.time() - t0, 1)
        runs.append(r)
        print(f"run {i}: {json.dumps(r)}")

    summary = {"bench": args.bench, "repeat": args.repeat, "runs": runs}
    if args.bench == "device":
        vals = [r["value"] for r in runs]
        summary["median_ops"] = statistics.median(vals)
        summary["stdev_ops"] = (statistics.stdev(vals)
                                if len(vals) > 1 else 0.0)
    path = os.path.join(args.out, f"eval_{args.bench}.json")
    with open(path, "w") as f:
        json.dump(summary, f, indent=2)
    print(f"summary -> {path}")

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        if args.bench == "device":
            plt.plot([r["value"] for r in runs], marker="o")
            plt.ylabel("committed ops/s")
            plt.xlabel("run")
            plt.savefig(os.path.join(args.out, "eval_device.png"))
            print(f"plot -> {args.out}/eval_device.png")
    except Exception:
        pass


if __name__ == "__main__":
    main()
