"""Shared benchmark result emitter — ONE schema for every benchmark.

Before this module each benchmark invented its own output: only
``run_bench.py`` and ``latency_bench.py`` exported obs registry
snapshots, while ``r_scaling``/``reconf_bench``/``loggp``/
``chaos_bench``/``redis_bench`` printed ad-hoc text or bespoke JSON
docs — which is why the BENCH trajectory could not track them. Every
benchmark now routes its headline result through :func:`emit`, which
produces:

* a greppable ``BENCH:{...}`` stdout line — ``metric``/``value``/
  ``unit``/``detail`` (the BENCH_* round schema), WITHOUT the bulky
  snapshot, so logs stay readable;
* optionally, one full JSON line appended to ``json_path`` carrying
  the same fields PLUS the obs metrics registry snapshot and the
  shared ``(monotonic, wall)`` clock anchor (obs.clock) — so bench
  rows align on the same timebase as trace/health/span dumps.

Benchmarks keep their existing human-readable prints and artifact
files; the emitter is the machine-readable common denominator.
"""

from __future__ import annotations

import json
from typing import Optional


def emit(metric: str, value=None, unit: Optional[str] = None, *,
         detail: Optional[dict] = None, obs=None, registry=None,
         json_path: Optional[str] = None, stdout: bool = True) -> dict:
    """Build, print, and optionally append the standardized result row.

    ``obs`` (an Observability facade) or ``registry`` (a bare
    MetricsRegistry) supplies the snapshot; with neither, the
    process-global default registry is used (subprocess-fanout benches
    record little there — the snapshot is still stamped for schema
    uniformity). The snapshot is taken only when it will actually be
    persisted (``json_path`` set) — the stdout line never carries it.
    Returns the full row dict."""
    from rdma_paxos_tpu.obs.clock import anchor
    row = dict(schema=1, metric=metric, anchor=anchor())
    if value is not None:
        row["value"] = value
    if unit is not None:
        row["unit"] = unit
    if detail:
        row["detail"] = detail
    line = {k: v for k, v in row.items() if k != "anchor"}
    if stdout:
        print("BENCH:" + json.dumps(line))
    if json_path:
        if registry is None:
            if obs is not None:
                registry = obs.metrics
            else:
                from rdma_paxos_tpu.obs.metrics import default_registry
                registry = default_registry()
        row["metrics"] = registry.snapshot()
        with open(json_path, "a") as f:
            f.write(json.dumps(row) + "\n")
    return row
