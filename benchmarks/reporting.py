"""Shared benchmark result emitter — ONE schema for every benchmark.

Before this module each benchmark invented its own output: only
``run_bench.py`` and ``latency_bench.py`` exported obs registry
snapshots, while ``r_scaling``/``reconf_bench``/``loggp``/
``chaos_bench``/``redis_bench`` printed ad-hoc text or bespoke JSON
docs — which is why the BENCH trajectory could not track them. Every
benchmark now routes its headline result through :func:`emit`, which
produces:

* a greppable ``BENCH:{...}`` stdout line — ``metric``/``value``/
  ``unit``/``detail`` (the BENCH_* round schema), WITHOUT the bulky
  snapshot, so logs stay readable;
* optionally, one full JSON line appended to ``json_path`` carrying
  the same fields PLUS the obs metrics registry snapshot and the
  shared ``(monotonic, wall)`` clock anchor (obs.clock) — so bench
  rows align on the same timebase as trace/health/span dumps.

Benchmarks keep their existing human-readable prints and artifact
files; the emitter is the machine-readable common denominator.
"""

from __future__ import annotations

import json
from typing import Optional


def emit(metric: str, value=None, unit: Optional[str] = None, *,
         detail: Optional[dict] = None, obs=None, registry=None,
         json_path: Optional[str] = None, stdout: bool = True) -> dict:
    """Build, print, and optionally append the standardized result row.

    ``obs`` (an Observability facade) or ``registry`` (a bare
    MetricsRegistry) supplies the snapshot; with neither, the
    process-global default registry is used (subprocess-fanout benches
    record little there — the snapshot is still stamped for schema
    uniformity). The snapshot is taken only when it will actually be
    persisted (``json_path`` set) — the stdout line never carries it.
    Returns the full row dict."""
    from rdma_paxos_tpu.obs.clock import anchor
    row = dict(schema=1, metric=metric, anchor=anchor())
    if value is not None:
        row["value"] = value
    if unit is not None:
        row["unit"] = unit
    if detail:
        row["detail"] = detail
    line = {k: v for k, v in row.items() if k != "anchor"}
    if stdout:
        print("BENCH:" + json.dumps(line))
    if json_path:
        if registry is None:
            if obs is not None:
                registry = obs.metrics
            else:
                from rdma_paxos_tpu.obs.metrics import default_registry
                registry = default_registry()
        row["metrics"] = registry.snapshot()
        with open(json_path, "a") as f:
            f.write(json.dumps(row) + "\n")
    return row


def phase_snapshot(driver) -> dict:
    """Snapshot the driver's StepPhaseProfiler accumulator as
    ``{phase: (n, total_us)}`` — the baseline for a per-window delta."""
    return {p: (a[0], a[1])
            for p, a in list(driver._phase_prof.acc.items())}


def phase_accumulate(driver, pre: dict, agg: dict) -> dict:
    """Fold the accumulator's delta since ``pre`` into ``agg``
    (``{phase: {n, total_us}}``). The profiler accumulator is global,
    so emitting it raw would blend measurement windows — every A/B
    variant must carry only its own rounds' attribution. Phases with a
    ZERO delta are suppressed (never seeded into ``agg``): a phase
    that did not run in this window — ``device_sync`` with ``fence=``
    off, ``ack_release`` in a round with no acks — must not emit a
    dead n=0 column into the A/B detail rows."""
    for p, (n1, t1) in phase_snapshot(driver).items():
        n0, t0 = pre.get(p, (0, 0.0))
        if n1 - n0 <= 0 and p not in agg:
            continue
        row = agg.setdefault(p, dict(n=0, total_us=0.0))
        row["n"] += n1 - n0
        row["total_us"] = round(row["total_us"] + (t1 - t0), 1)
    return agg


def ab_variant_rounds(driver, rounds: int, apply_variant,
                      run_once) -> dict:
    """Generic alternating best-of A/B on the same core (the shared
    methodology): ``apply_variant(on: bool)`` flips the measured
    delta before each round, ``run_once()`` returns ops/s (None/0
    rounds are skipped in the best-of). Per-variant phase attribution
    rides the result. The ON configuration is restored before
    returning."""
    ab = {"off": 0.0, "on": 0.0}
    phases = {"off": {}, "on": {}}
    for _ in range(rounds):
        for variant in ("off", "on"):
            apply_variant(variant == "on")
            pre = phase_snapshot(driver)
            ops = run_once()
            phase_accumulate(driver, pre, phases[variant])
            if ops:
                ab[variant] = max(ab[variant], float(ops))
    apply_variant(True)
    return dict(off=ab["off"], on=ab["on"],
                phases_on=dict(sorted(phases["on"].items())),
                phases_off=dict(sorted(phases["off"].items())))


def ab_pipeline_rounds(driver, rounds: int, depth: int, run_once) -> dict:
    """Alternating best-of pipeline on/off A/B on the same core (the
    ``--audit`` overhead methodology, shared by run_bench and
    redis_bench). ``run_once()`` runs one round and returns ops/s (or
    None/0 for a failed round — skipped in the best-of). The in-flight
    depth counter is reset per ON round so ``depth_seen`` proves the
    ON rounds really overlapped dispatches. Restores
    ``driver.pipeline = depth`` before returning."""
    ab = {"off": 0.0, "on": 0.0}
    phases = {"off": {}, "on": {}}
    depth_seen = 0
    for _ in range(rounds):
        for variant, d in (("off", 0), ("on", depth)):
            driver.pipeline = d
            driver.cluster.max_inflight_dispatches = 0
            pre = phase_snapshot(driver)
            ops = run_once()
            phase_accumulate(driver, pre, phases[variant])
            if ops:
                ab[variant] = max(ab[variant], float(ops))
            if variant == "on":
                depth_seen = max(
                    depth_seen,
                    int(driver.cluster.max_inflight_dispatches))
    driver.pipeline = depth
    return dict(off=ab["off"], on=ab["on"], depth_seen=depth_seen,
                phases_on=dict(sorted(phases["on"].items())),
                phases_off=dict(sorted(phases["off"].items())))
