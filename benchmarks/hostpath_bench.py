#!/usr/bin/env python
"""Host-data-plane perf smoke: vectorized vs scalar, same bytes.

Times the three host hot-path operations (window encode, window decode
+ frame assembly, replay/ack planning) through BOTH implementations in
``runtime/hostpath.py`` on identical synthetic windows, emits one
``host_path_speedup_micro`` row per operation plus the aggregate, and
— with ``--check`` — exits non-zero unless the vectorized path is at
least as fast as the scalar reference (the loose CI non-regression
bound: a future PR reintroducing a per-entry Python loop into the
vectorized functions fails the tier-1 workflow here, before any e2e
bench would notice). numpy-only — runs in seconds on any CPU.

    python benchmarks/hostpath_bench.py --check
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rdma_paxos_tpu.consensus.log import (  # noqa: E402
    M_CONN, M_GEN, M_LEN, M_REQID, M_TYPE, META_W)
from rdma_paxos_tpu.runtime import hostpath  # noqa: E402


def make_take(rng, n, slot_bytes, payload):
    return [(3, int(rng.randint(1, 1 << 26)), i + 1,
             rng.bytes(payload)) for i, _ in enumerate(range(n))]


def make_window(rng, n, slot_bytes, payload):
    wm = np.zeros((n, META_W), np.int32)
    wd = rng.randint(-2**31, 2**31 - 1, size=(n, slot_bytes // 4),
                     dtype=np.int32)
    wm[:, M_TYPE] = 3
    wm[:, M_CONN] = rng.randint(1, 1 << 26, size=n)
    # ~1/8 own-origin entries (origin 0), the rest remote
    own = rng.rand(n) < 0.125
    wm[own, M_CONN] = (0 << 24) | rng.randint(1, 1 << 10, size=int(
        own.sum()))
    wm[~own, M_CONN] |= (1 << 24)
    wm[:, M_REQID] = np.arange(1, n + 1)
    wm[:, M_LEN] = payload
    return wm, wd


def best_of(fn, rounds, inner):
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(inner):
            fn()
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def run(n=2048, slot_bytes=128, payload=24, rounds=5, inner=3,
        json_path=None):
    rng = np.random.RandomState(7)
    take = make_take(rng, n, slot_bytes, payload)
    wm, wd = make_window(rng, n, slot_bytes, payload)
    data = np.zeros((n, slot_bytes // 4), np.int32)
    meta = np.zeros((n, META_W), np.int32)
    du8 = data.view(np.uint8).reshape(n, -1)

    def op_encode():
        data[:] = 0
        meta[:] = 0
        hostpath.pack_window(du8, meta, take, slot_bytes)

    def op_decode():
        hostpath.decode_batch(wm, wd, n).frames()

    batch = hostpath.decode_batch(wm, wd, n)
    own = (batch.conns >> 24) == 0

    def op_plan():
        hostpath.replay_plan(batch, own)

    from benchmarks.reporting import emit
    results = {}
    for name, op in (("encode", op_encode), ("decode", op_decode),
                     ("replay_ack_plan", op_plan)):
        timings = {}
        # alternating best-of rounds, the shared A/B methodology
        for variant in ("scalar", "vectorized"):
            hostpath.set_vectorized(variant == "vectorized")
            timings[variant] = best_of(op, rounds, inner)
        hostpath.set_vectorized(True)
        speedup = timings["scalar"] / max(timings["vectorized"], 1e-12)
        results[name] = dict(
            scalar_us=round(timings["scalar"] * 1e6, 1),
            vectorized_us=round(timings["vectorized"] * 1e6, 1),
            speedup=round(speedup, 2))
        emit("host_path_speedup_micro", round(speedup, 2), "x",
             detail=dict(op=name, entries=n, payload=payload,
                         slot_bytes=slot_bytes, **results[name]),
             json_path=json_path)
    agg = min(r["speedup"] for r in results.values())
    emit("host_path_speedup_micro_min", agg, "x",
         detail=dict(entries=n, payload=payload, ops=results),
         json_path=json_path)
    return agg, results


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--entries", type=int, default=2048,
                    help="entries per synthetic window")
    ap.add_argument("--payload", type=int, default=24)
    ap.add_argument("--slot-bytes", type=int, default=128)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero unless vectorized >= scalar "
                         "on every operation (CI non-regression)")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    agg, results = run(n=args.entries, slot_bytes=args.slot_bytes,
                       payload=args.payload, rounds=args.rounds,
                       json_path=args.json)
    for name, r in results.items():
        print(f"{name:16s} scalar {r['scalar_us']:9.1f} us  "
              f"vectorized {r['vectorized_us']:9.1f} us  "
              f"-> {r['speedup']:.2f}x")
    if args.check and agg < 1.0:
        print(f"FAIL: vectorized host path slower than scalar "
              f"(min speedup {agg:.2f}x < 1.0x)")
        return 1
    print(f"min speedup {agg:.2f}x" + (" (>= 1.0x OK)"
                                       if args.check else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
