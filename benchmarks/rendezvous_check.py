#!/usr/bin/env python
"""Cluster-fabric connectivity smoke test — the ``benchmarks/mckey.c``
analog. The reference ships a standalone RDMA-CM multicast test because a
broken multicast group silently breaks JOIN/bootstrap; the failure mode
here is a broken jax.distributed rendezvous or collective fabric, so this
spawns N local processes, initializes the coordinator, and runs one psum
across all of them.

    python benchmarks/rendezvous_check.py --procs 3
"""

import argparse
import os
import subprocess
import sys

WORKER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize("127.0.0.1:%s" % port, int(n), int(pid))
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("x",))
arr = jax.device_put(np.ones(int(n), np.float32),
                     NamedSharding(mesh, P("x")))
out = jax.jit(jax.shard_map(lambda v: jax.lax.psum(v, "x"), mesh=mesh,
                            in_specs=P("x"), out_specs=P()))(arr)
assert float(out[0]) == float(n), out
print("proc %s: fabric OK (psum=%d over %s procs)" % (pid, int(out[0]), n),
      flush=True)
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=3)
    ap.add_argument("--port", default="9941")
    args = ap.parse_args()
    import tempfile
    script = os.path.join(tempfile.mkdtemp(), "w.py")
    with open(script, "w") as f:
        f.write(WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen([sys.executable, script, str(i),
                               str(args.procs), args.port], env=env)
             for i in range(args.procs)]
    rc = [p.wait() for p in procs]
    if any(rc):
        raise SystemExit(f"fabric check FAILED: exit codes {rc}")
    print("rendezvous + collective fabric OK")


if __name__ == "__main__":
    main()
