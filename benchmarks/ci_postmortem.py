"""CI failure postmortem — boot a smoke cluster, dump every obs
surface, assemble ONE verified bundle.

When the tier-1 suite fails in CI, the raw pytest log says WHAT
failed but nothing about the environment it failed in. This script
(the workflow's ``if: failure()`` step) runs a short in-process
cluster session with the full ops plane attached, forces every dump
surface to disk (series JSONL, span dump, audit artifact, trace
ring, metrics snapshot, health files), and assembles them into one
``postmortem_bundle`` artifact via the fleet console — so the upload
carries a machine-checkable environment smoke (did elections work?
did commits flow? what did the burn-rate rules see?) next to the
test log. The last lines of the failing log ride in the bundle's
``reason``.

Usage: ``python benchmarks/ci_postmortem.py --out bundle.json
[--log /tmp/_t1.log]`` — exits 0 iff the assembled bundle verifies.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="tier1_bundle.json")
    ap.add_argument("--log", default=None,
                    help="failing test log; its tail becomes the "
                         "bundle reason")
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    reason = "tier1 failure"
    if args.log and os.path.exists(args.log):
        with open(args.log, errors="replace") as f:
            tail = f.readlines()[-15:]
        reason = "tier1 failure; log tail:\n" + "".join(tail)

    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
    from rdma_paxos_tpu.obs import console
    from rdma_paxos_tpu.obs.audit import write_audit_artifact
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    wd = tempfile.mkdtemp(prefix="rp_ci_postmortem_")
    cfg = LogConfig(n_slots=256, slot_bytes=128, window_slots=64,
                    batch_slots=16)
    d = ClusterDriver(cfg, 3, workdir=wd, timeout_cfg=TimeoutConfig(),
                      fanout="psum", audit=True, health_period=0.0)
    d.cluster.run_until_elected(0)
    for i in range(args.steps):
        d.cluster.submit(0, b"ci-smoke-%d" % i)
        d.step()
    d.evaluate_alerts()
    d.obs.spans.write_json(os.path.join(wd, "spans.json"))
    if d.obs.tracectx.counts()["by_kind"]:
        # subsystem traces exist only when txn/topology/watch ran
        d.obs.tracectx.write_json(os.path.join(wd, "traces.json"))
    write_audit_artifact(os.path.join(wd, "audit_dump.json"),
                         reason="ci postmortem smoke",
                         ledger=d.cluster.auditor,
                         flight=d.cluster.flight, obs=d.obs)
    d.obs.trace.dump_on_failure(os.path.join(wd, "trace_dump.json"),
                                reason="ci postmortem smoke")
    d.obs.metrics.write_json(os.path.join(wd, "metrics.json"))
    d.stop()

    rc = console.main(["bundle", "--workdir", wd, "--out", args.out,
                       "--reason", reason])
    if rc != 0:
        return rc
    return console.main(["bundle", "--verify", args.out])


if __name__ == "__main__":
    raise SystemExit(main())
