#!/usr/bin/env python
"""LogGP-style fabric measurement — the ``SRV_TYPE_LOGGP`` mode analog.

The reference measures o (send overhead), o_poll, L (latency), G (per-byte
gap) of the RDMA fabric with median-of-1000 sampling
(``rc_get_loggp_params``, ``dare_ibv_rc.c:3323-3597``). Here the unit of
communication is the replica step, so the measured quantities are:

  o+L  — fixed per-step overhead: step time with an empty window
         (heartbeat-only step) — control gather + claim gather + empty
         fan-out
  G    — per-byte gap: slope of step time vs window payload bytes
  g    — per-entry gap: slope vs entries per step at fixed bytes

measured separately for the psum fan-out (production O(W) broadcast) and
the gather fan-out (partition-capable O(R*W)).

HONEST-TIMING RULES for the relay-tunneled TPU backend (see
LATENCY_r05.json methodology): each (config, fill, fanout) sample runs in
its OWN subprocess, timing K-step scans whose timed region ends with a
drain-forcing value read; the parent never touches the device.

    python benchmarks/loggp.py [--json out.json]
    RP_BENCH_CPU=1 python benchmarks/loggp.py
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

R = 3
K = 64
REPS = 4
BASE = dict(n_slots=8192, window_slots=256, batch_slots=256)


def measure_row(slot_bytes: int, fill: int, fanout: str) -> float:
    """One subprocess: honest per-step µs for this configuration."""
    import time

    import jax
    if os.environ.get("RP_BENCH_CPU", "0") == "1":
        jax.config.update("jax_platforms", "cpu")
    import functools

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from rdma_paxos_tpu.config import LogConfig
    from rdma_paxos_tpu.consensus.log import (
        EntryType, M_LEN, M_TYPE, META_W)
    from rdma_paxos_tpu.consensus.step import StepInput, replica_step
    from rdma_paxos_tpu.parallel.mesh import REPLICA_AXIS, stack_states

    cfg = LogConfig(slot_bytes=slot_bytes, **BASE)
    use_pallas = jax.default_backend() == "tpu"
    core = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS,
                             use_pallas=use_pallas, fanout=fanout,
                             elections=False)
    fullc = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                              axis_name=REPLICA_AXIS,
                              use_pallas=use_pallas, fanout=fanout,
                              elections=True)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    vfull = jax.vmap(fullc, in_axes=(0, 0), axis_name=REPLICA_AXIS)
    B = cfg.batch_slots
    bd = jnp.zeros((R, B, cfg.slot_words), jnp.int32)
    bm = (jnp.zeros((R, B, META_W), jnp.int32)
          .at[:, :, M_TYPE].set(int(EntryType.SEND))
          .at[:, :, M_LEN].set(cfg.slot_bytes))
    peer = jnp.ones((R, R), jnp.int32)

    def make_inp(st, count, bd, bm, peer):
        return StepInput(
            batch_data=bd, batch_meta=bm,
            batch_count=jnp.full((R,), count, jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=peer, apply_done=st.commit,
            queue_depth=jnp.zeros((R,), jnp.int32))

    @jax.jit
    def elect(st, bd, bm, peer):
        import dataclasses
        inp = dataclasses.replace(
            make_inp(st, 0, bd, bm, peer),
            timeout_fired=jnp.zeros((R,), jnp.int32).at[0].set(1))
        s2, _ = vfull(st, inp)
        return s2

    @jax.jit
    def scan_k(st, bd, bm, peer):
        def body(s, _):
            s, out = vstep(s, make_inp(s, fill, bd, bm, peer))
            return s, out.commit[0]
        return lax.scan(body, st, None, length=K)

    st = stack_states(cfg, R, R)
    st = elect(st, bd, bm, peer)
    scan_c = scan_k.lower(st, bd, bm, peer).compile()
    t0 = time.perf_counter()
    for _ in range(REPS):
        st, cs = scan_c(st, bd, bm, peer)
    _ = int(np.asarray(st.commit[0]))     # timed: forces the drain
    dt = time.perf_counter() - t0
    return dt / (REPS * K) * 1e6


def run_row(slot_bytes: int, fill: int, fanout: str,
            samples: int = 3) -> float:
    """Best of ``samples`` independent subprocesses: the chip is
    time-shared with co-tenants and a contention burst inflates
    arbitrary samples ~10x; the best sample is the reproducible
    capability (same policy as bench.py / latency_bench.py)."""
    best = None
    for _ in range(samples):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--row",
             json.dumps([slot_bytes, fill, fanout])],
            capture_output=True, text=True)
        val = None
        for ln in proc.stdout.splitlines():
            if ln.startswith("ROWJSON:"):
                val = json.loads(ln[len("ROWJSON:"):])
                break
        if val is None:
            raise RuntimeError("row %s failed: %s"
                               % ((slot_bytes, fill, fanout),
                                  proc.stderr[-2000:]))
        best = val if best is None else min(best, val)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--row", default=None)
    args = ap.parse_args()
    if args.row is not None:
        sb, fill, fanout = json.loads(args.row)
        print("ROWJSON:" + json.dumps(measure_row(sb, fill, fanout)))
        return

    out = {"metric": "loggp_step_parameters",
           "samples_per_row": REPS * K,
           "rows": {}}
    for fanout in ("psum", "gather"):
        o_plus_l = run_row(256, 0, fanout)       # empty window
        t_small = run_row(128, 256, fanout)      # G: bytes slope
        t_big = run_row(1024, 256, fanout)
        dbytes = 256 * (1024 - 128)
        g_ns_byte = (t_big - t_small) * 1e3 / dbytes
        t_few = run_row(256, 32, fanout)         # g: entries slope
        t_many = run_row(256, 256, fanout)
        g_ns_entry = (t_many - t_few) * 1e3 / (256 - 32)
        out["rows"][fanout] = dict(
            o_plus_L_us=round(o_plus_l, 1),
            G_ns_per_byte=round(g_ns_byte, 3),
            g_ns_per_entry=round(g_ns_entry, 1),
            full_step_us=round(t_many, 1),
        )
    # backend from a child (the parent must not touch the device)
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax,os\n"
         "import sys\n"
         "sys.path.insert(0, %r)\n"
         "if os.environ.get('RP_BENCH_CPU','0')=='1':\n"
         "    jax.config.update('jax_platforms','cpu')\n"
         "print(jax.default_backend())" % os.path.dirname(
             os.path.dirname(os.path.abspath(__file__)))],
        capture_output=True, text=True)
    out["backend"] = probe.stdout.strip().splitlines()[-1] \
        if probe.stdout.strip() else "unknown"
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    from benchmarks.reporting import emit
    emit("loggp_step_parameters",
         out["rows"]["psum"]["o_plus_L_us"], "us",
         detail=dict(backend=out["backend"], rows=out["rows"],
                     samples_per_row=out["samples_per_row"]))


if __name__ == "__main__":
    main()
