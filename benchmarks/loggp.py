#!/usr/bin/env python
"""LogGP-style fabric measurement — the ``SRV_TYPE_LOGGP`` mode analog.

The reference measures o (send overhead), o_poll, L (latency), G (per-byte
gap) of the RDMA fabric with median-of-1000 sampling
(``rc_get_loggp_params``, ``dare_ibv_rc.c:3323-3597``). Here the unit of
communication is the replica step, so the measured quantities are:

  o+L  — fixed per-step overhead: median step wall time with an empty
         window (heartbeat-only step)
  G    — per-byte gap: slope of step time vs window payload bytes
  g    — per-entry gap: slope vs entries per step at fixed bytes

Prints one JSON line with the fitted parameters.

    python benchmarks/loggp.py            # real TPU
    RP_BENCH_CPU=1 python benchmarks/loggp.py
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

if os.environ.get("RP_BENCH_CPU", "0") == "1":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rdma_paxos_tpu.config import LogConfig  # noqa: E402
from rdma_paxos_tpu.consensus.log import M_LEN, M_TYPE, META_W, EntryType  # noqa: E402
from rdma_paxos_tpu.consensus.step import StepInput, replica_step  # noqa: E402
from rdma_paxos_tpu.parallel.mesh import REPLICA_AXIS, stack_states  # noqa: E402

R = 3
SAMPLES = 50


def step_time(cfg, batch_fill, reps=SAMPLES):
    import functools
    use_pallas = jax.default_backend() == "tpu"
    core = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas)
    vstep = jax.jit(jax.vmap(core, in_axes=(0, 0),
                             axis_name=REPLICA_AXIS),
                    donate_argnums=(0,))
    B = cfg.batch_slots
    bd = jnp.zeros((R, B, cfg.slot_words), jnp.int32)
    bm = jnp.zeros((R, B, META_W), jnp.int32).at[:, :, M_TYPE].set(
        int(EntryType.SEND)).at[:, :, M_LEN].set(cfg.slot_bytes)
    state = stack_states(cfg, R, R)

    def make_inp(count, tmo, commit):
        return StepInput(
            batch_data=bd, batch_meta=bm,
            batch_count=jnp.full((R,), count, jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32).at[0].set(tmo),
            peer_mask=jnp.ones((R, R), jnp.int32),
            apply_done=commit,
            queue_depth=jnp.zeros((R,), jnp.int32))

    state, _ = vstep(state, make_inp(0, 1, jnp.zeros((R,), jnp.int32)))
    ts = []
    for _ in range(reps):
        inp = make_inp(batch_fill, 0, state.commit)
        t0 = time.perf_counter()
        state, out = vstep(state, inp)
        jax.block_until_ready(out.commit)
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e6  # us


def main():
    base = dict(n_slots=8192, window_slots=256, batch_slots=256)
    # o+L: heartbeat-only step (empty window)
    o_plus_l = step_time(LogConfig(slot_bytes=256, **base), 0)
    # G: vary bytes at fixed entry count (slot_bytes 128 -> 1024)
    t_small = step_time(LogConfig(slot_bytes=128, **base), 256)
    t_big = step_time(LogConfig(slot_bytes=1024, **base), 256)
    dbytes = 256 * (1024 - 128)
    G_ns = (t_big - t_small) * 1e3 / dbytes
    # g: vary entries at fixed slot size
    t_few = step_time(LogConfig(slot_bytes=256, **base), 32)
    t_many = step_time(LogConfig(slot_bytes=256, **base), 256)
    g_ns = (t_many - t_few) * 1e3 / (256 - 32)
    print(json.dumps({
        "backend": jax.default_backend(),
        "o_plus_L_us": round(o_plus_l, 1),
        "G_ns_per_byte": round(G_ns, 3),
        "g_ns_per_entry": round(g_ns, 1),
        "full_step_us": round(t_many, 1),
        "samples": SAMPLES,
    }))


if __name__ == "__main__":
    main()
