"""Headline benchmark: committed client entries per second through the full
consensus hot path (append → fan-out → ack → quorum scan → commit), run on
real TPU hardware.

Methodology mirrors the reference's ``redis-benchmark -t set`` against the
leader (``benchmarks/run.sh:73-82``) at the consensus layer: every committed
entry corresponds to one replicated client operation. A 3-replica group runs
on one chip via the vmapped protocol step (identical collective semantics to
the multi-chip shard_map path); K steps are driven per jit call through
``lax.scan`` with the host apply echo folded into the carry, so the number
printed is device-side protocol throughput including quorum scan and commit
advance — the north-star metric of BASELINE.md (target ≥1M ops/s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import functools
import json
import time

import jax
import jax.numpy as jnp

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import M_LEN, M_TYPE, META_W, EntryType
from rdma_paxos_tpu.consensus.step import StepInput, replica_step
from rdma_paxos_tpu.parallel.mesh import REPLICA_AXIS, stack_states

K = 64          # protocol steps per jit call
# ring sized 4x the window: gather/scatter cost scales with ring rows (a
# right-sized ring nearly doubles throughput vs a 16k-slot ring), while the
# ring must absorb one full batch per step plus the one-step apply lag
# without hitting the capacity clamp. Geometry swept on hardware
# (round 3): 2048-entry batches at 128-byte slots measure ~1.6x the
# round-2 1024/256 shape back-to-back in one session; 8192-entry windows
# exceed the Pallas kernel's scoped-VMEM tile limit.
CFG = LogConfig(n_slots=8192, slot_bytes=128, window_slots=2048,
                batch_slots=2048)
BASELINE_OPS = 1_000_000.0   # BASELINE.md north-star: 1M Redis SET ops/s


def build(R, cfg=None):
    cfg = cfg or CFG
    use_pallas = jax.default_backend() == "tpu"
    # full-connectivity bench: the O(W) psum fan-out is the production
    # configuration (see replica_step's fanout docstring)
    core = functools.partial(replica_step, cfg=cfg, n_replicas=R,
                             axis_name=REPLICA_AXIS, use_pallas=use_pallas,
                             fanout="psum")
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=REPLICA_AXIS)

    B = cfg.batch_slots
    # batch arrays are PASSED AS ARGUMENTS, never closure-captured: a
    # captured jnp array is lifted into the executable as a constant,
    # and on the tunneled TPU backend a program carrying lifted
    # constants pays a flat ~100 ms per dispatch (measured round 5)
    batch_data = jnp.zeros((R, B, cfg.slot_words), jnp.int32).at[0, :, 0].set(
        jnp.arange(B))  # "SET k v" payload stand-in
    batch_meta = jnp.zeros((R, B, META_W), jnp.int32)
    batch_meta = batch_meta.at[:, :, M_TYPE].set(int(EntryType.SEND))
    batch_meta = batch_meta.at[:, :, M_LEN].set(16)
    peer = jnp.ones((R, R), jnp.int32)

    def one(carry, _):
        # host apply echo folded into the carry: applies track commit, so
        # pruning frees ring space exactly as the real driver does
        state, batch_data, batch_meta, peer = carry
        inp = StepInput(
            batch_data=batch_data,
            batch_meta=batch_meta,
            batch_count=jnp.full((R,), B, jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32),
            peer_mask=peer,
            apply_done=state.commit,
            queue_depth=jnp.zeros((R,), jnp.int32),
        )
        state, out = vstep(state, inp)
        return (state, batch_data, batch_meta, peer), out.commit[0]

    @jax.jit
    def run_k(state, batch_data, batch_meta, peer):
        carry, commits = jax.lax.scan(
            one, (state, batch_data, batch_meta, peer), None, length=K)
        return carry[0], commits

    @jax.jit
    def elect(state, batch_data, batch_meta, peer):
        inp = StepInput(
            batch_data=batch_data, batch_meta=batch_meta,
            batch_count=jnp.zeros((R,), jnp.int32),
            timeout_fired=jnp.zeros((R,), jnp.int32).at[0].set(1),
            peer_mask=peer, apply_done=state.commit,
            queue_depth=jnp.zeros((R,), jnp.int32))
        state, _ = vstep(state, inp)
        return state

    return elect, run_k, (batch_data, batch_meta, peer)


def run_group(R, cfg=None, reps=32):
    elect, run_k, consts = build(R, cfg)
    state = stack_states(cfg or CFG, R, R)
    state = elect(state, *consts)
    # compile WITHOUT executing — an executed warmup's device time could
    # still be un-drained (optimistic block) when the timer starts.
    # elect above does execute, but it is ONE step (<0.1% of the timed
    # work) and the compile below gives it time to drain.
    run_k = run_k.lower(state, *consts).compile()
    # Honest-timing protocol for the relay-tunneled backend (measured
    # round 5): (1) NO host value reads before the timed region — the
    # first device->host read permanently exits the tunnel's
    # speculative dispatch pipelining; (2) block_until_ready is
    # OPTIMISTIC under that speculation (it can return before the real
    # device work drains), so the timed region must END WITH the value
    # read itself, which forces the full drain. The single ~100 ms
    # relay RTT the read adds is amortized over reps*K steps.
    state_pre = state
    t0 = time.perf_counter()
    for _ in range(reps):
        state, commits = run_k(state, *consts)
    final = int(state.commit[0])                # timed: forces the drain
    dt = time.perf_counter() - t0
    committed = final - int(state_pre.commit[0])
    return committed / dt, dt / (reps * K) * 1e6, committed


def main():
    import argparse
    import os
    import subprocess
    import sys
    ap = argparse.ArgumentParser()
    # internal: run ONE group and print its result (each group runs in
    # a fresh process — the end-of-group commit readback permanently
    # exits the tunnel's speculative dispatch pipelining, so a shared
    # process would poison every later group's timing)
    ap.add_argument("--group", type=int, default=None)
    args = ap.parse_args()
    if args.group is not None:
        ops, step_us, committed = run_group(args.group)
        print("GROUPJSON:" + json.dumps(
            [ops, step_us, committed, jax.default_backend()]))
        return

    # headline: 3-replica group (BASELINE config #1); detail adds the 5-
    # and 7-replica groups of BASELINE configs #3/#4 and the reference's
    # maximum sizes 9/11/13 (MAX_SERVER_COUNT = 13, dare.h:26)
    def run_one(R):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--group", str(R)], capture_output=True, text=True)
        for ln in proc.stdout.splitlines():
            if ln.startswith("GROUPJSON:"):
                return tuple(json.loads(ln[len("GROUPJSON:"):]))
        raise RuntimeError("group %d failed: %s" % (R, proc.stderr[-2000:]))

    # the chip is TIME-SHARED with co-tenants: identical runs swing >10x
    # when a contention burst lands inside the timed region. Best-of-N
    # is the reproducible capability number (the headline group gets
    # N=3; the detail groups take their single sample as-is).
    per_group = {}
    for R in (3, 5, 7, 9, 11, 13):
        per_group[R] = run_one(R)
    for R in (3, 3, 5, 7, 9, 11, 13):       # headline gets 3 samples
        row = run_one(R)
        if row[0] > per_group[R][0]:
            per_group[R] = row
    ops, step_us, committed, backend = per_group[3]
    print(json.dumps({
        "metric": "consensus_committed_ops_per_sec",
        "value": round(ops, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops / BASELINE_OPS, 4),
        "detail": {
            "replicas": 3, "batch": CFG.batch_slots,
            "committed": committed, "step_latency_us": round(step_us, 2),
            "ops_5_replicas": round(per_group[5][0], 1),
            "ops_7_replicas": round(per_group[7][0], 1),
            "ops_9_replicas": round(per_group[9][0], 1),
            "ops_11_replicas": round(per_group[11][0], 1),
            "ops_13_replicas": round(per_group[13][0], 1),
            "backend": backend,
            # all R replicas' device work runs on ONE chip here (vmapped
            # axis), so ops/s ~ 1/R is the simulation topology, not the
            # protocol: per-replica work is R-invariant outside O(R)
            # scalar gathers — see ANALYSIS_R_SCALING.md
            "topology": "single-chip vmap simulation (R rings, 1 chip)",
        },
    }))


if __name__ == "__main__":
    main()
