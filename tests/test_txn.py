"""txn/ — cross-group atomic transactions: acceptance properties.

* the ``txn=`` flag is cache-key guarded exactly like ``audit=`` /
  ``telemetry=``: txn=False clusters add NOTHING to ``STEP_CACHE``
  (programs and keys bit-identical to the pre-txn world) and their
  step outputs are bit-identical to a txn=True cluster's on the same
  recorded workload;
* the device vote lane (``txn/lane.py``) answers the armed prepare
  watch from log facts only: committed-under-watched-term ⟹ PREPARED,
  overwritten ⟹ CONFLICT, not-yet-committed ⟹ PENDING — on
  ``SimCluster``, the vmap ``ShardedCluster``, AND the spmd mesh
  engine (mesh ≡ vmap vote parity is asserted bit-for-bit);
* the 2PC commit lane resolves a cross-group commit in ~2 protocol
  dispatches (counted), staged writes apply only at COMMIT (aborts
  leave no partial writes), lock conflicts abort immediately, and an
  unreachable participant aborts by step-domain timeout;
* the mergeable fast path (INCR/SADD/MAX) commits without prepare and
  converges through the same fold;
* the strict-serializability checker (``chaos/serialize.py``) accepts
  clean histories and rejects partial commits, commit+abort, and
  cross-group commit-order cycles;
* the seeded txn nemesis (coordinator-leader crash mid-prepare) is
  green and deterministic;
* the observability surfaces ride along: abort-rate alert rule,
  health/console columns, counters, and the graftlint jit-purity scan
  covering ``txn/lane.py``.
"""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.models.kvs import CMD_W, OP_INCR, OP_MAX, OP_SADD
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.shard import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS
from rdma_paxos_tpu.txn import (
    TXN_CONFLICT, TXN_NONE, TXN_PENDING, TXN_PREPARED,
    attach_coordinator)
from rdma_paxos_tpu.txn.chaos import keys_for_groups
from rdma_paxos_tpu.txn.merge import decode_merge_val, encode_merge_val
from rdma_paxos_tpu.txn.records import (
    TXN_ABORT, TXN_CMD_W, TXN_COMMIT, TXN_PREPARE, decode_record,
    encode_abort, encode_commit, encode_prepare)

# a geometry no other test uses: the cache-key guard below reasons
# about which keys THIS test file's clusters add to the shared cache
CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=16,
                batch_slots=8)


def _commit_one(c: SimCluster, payload: bytes) -> int:
    """Submit at the leader and step until committed; -> absolute
    index of the entry."""
    c.submit(0, payload)
    idx = int(c.last["end"][0])
    for _ in range(4):
        c.step()
        if int(c.last["commit"][0]) > idx:
            break
    assert int(c.last["commit"][0]) > idx
    return idx + int(c.rebased_total)


# ---------------------------------------------------------------------------
# device vote lane
# ---------------------------------------------------------------------------

def test_vote_lane_sim():
    c = SimCluster(CFG, 3, txn=True)
    c.run_until_elected(0)
    term = int(c.last["term"][0])
    idx = _commit_one(c, b"prep")
    # no watch armed: every replica reports NONE
    c.step()
    assert (np.asarray(c.last["txn_vote"]) == TXN_NONE).all()
    # committed under the watched term: PREPARED (the leader holds
    # the entry; every in-sync replica agrees)
    c.set_txn_watch(idx, term)
    c.step()
    votes = np.asarray(c.last["txn_vote"])
    assert votes[0] == TXN_PREPARED
    assert set(votes.tolist()) <= {TXN_PREPARED}
    # wrong watched term on a committed index: definitive CONFLICT
    c.set_txn_watch(idx, term + 5)
    c.step()
    assert np.asarray(c.last["txn_vote"])[0] == TXN_CONFLICT
    # a future index: PENDING (no fact yet, keep waiting)
    c.set_txn_watch(idx + 10, term)
    c.step()
    assert np.asarray(c.last["txn_vote"])[0] == TXN_PENDING
    c.clear_txn_watch()
    c.step()
    assert (np.asarray(c.last["txn_vote"]) == TXN_NONE).all()


def _vote_workload(c: ShardedCluster) -> list:
    """Recorded per-group watch workload; -> the txn_vote snapshots."""
    out = []
    for g in range(2):
        c.run_until_elected(g, g)
    lead = [c.leader(0), c.leader(1)]
    for g in (0, 1):
        c.submit(g, lead[g], b"w%d" % g)
    for _ in range(3):
        c.step()
    term0 = int(c.last["term"][0].max())
    idx0 = int(c.last["commit"][0].max()) - 1
    c.set_txn_watch(0, idx0, term0)
    c.step()
    out.append(np.asarray(c.last["txn_vote"]).copy())
    c.set_txn_watch(0, idx0, term0 + 3)     # wrong term: CONFLICT
    c.set_txn_watch(1, 10 ** 6, 1)          # far future: PENDING
    c.step()
    out.append(np.asarray(c.last["txn_vote"]).copy())
    c.clear_txn_watch()
    c.step()
    out.append(np.asarray(c.last["txn_vote"]).copy())
    return out


def test_vote_lane_sharded_per_group():
    c = ShardedCluster(CFG, 3, 2, txn=True)
    v1, v2, v3 = _vote_workload(c)
    assert v1[0].max() == TXN_PREPARED and (v1[1] == TXN_NONE).all()
    assert v2[0].max() == TXN_CONFLICT
    assert (v2[1] == TXN_PENDING).all()
    assert (v3 == TXN_NONE).all()


def test_vote_lane_mesh_bit_identical_to_vmap():
    """mesh ≡ vmap: the spmd engine threads the watch inputs and
    reports the identical stacked vote matrix."""
    a = ShardedCluster(CFG, 3, 2, txn=True)
    b = ShardedCluster(CFG, 3, 2, txn=True, mesh=(2, 3))
    va, vb = _vote_workload(a), _vote_workload(b)
    for x, y in zip(va, vb):
        assert np.array_equal(x, y)
    for k in ("term", "commit", "end", "apply", "role"):
        assert np.array_equal(np.asarray(a.last[k]),
                              np.asarray(b.last[k])), k


# ---------------------------------------------------------------------------
# txn=False bit-identity (the audit=/telemetry= discipline)
# ---------------------------------------------------------------------------

def test_txn_off_cache_keys_bit_identical():
    # fresh geometry: no other test (or earlier test here) has
    # populated the cache for it, so the added-key sets are exact
    cfg = LogConfig(n_slots=32, slot_bytes=128, window_slots=8,
                    batch_slots=4)
    plain = SimCluster(cfg, 3)
    plain.run_until_elected(0)
    plain.submit(0, b"x")
    plain.step()
    keys_before = set(STEP_CACHE)

    on = SimCluster(cfg, 3, txn=True)
    on.run_until_elected(0)
    on.submit(0, b"y")
    on.step()
    added = set(STEP_CACHE) - keys_before
    assert added and all("txn" in str(k) for k in added), (
        "txn variants must carry the 'txn' cache-key marker")
    assert keys_before <= set(STEP_CACHE)

    # a fresh txn=False cluster adds NOTHING: default keys (and
    # therefore default programs) are bit-identical to the seed
    after_txn = set(STEP_CACHE)
    plain2 = SimCluster(cfg, 3)
    plain2.run_until_elected(0)
    plain2.submit(0, b"z")
    plain2.step()
    assert set(STEP_CACHE) == after_txn


def test_txn_off_outputs_bit_identical():
    a = SimCluster(CFG, 3)
    b = SimCluster(CFG, 3, txn=True)
    for c in (a, b):
        c.run_until_elected(0)
        for t in range(4):
            c.submit(0, b"t%d" % t)
            c.step()
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(np.asarray(a.last[k]),
                              np.asarray(b.last[k])), k
    assert "txn_vote" not in a.last and "txn_vote" in b.last


# ---------------------------------------------------------------------------
# records + mergeable device ops
# ---------------------------------------------------------------------------

def test_txn_records_roundtrip_and_width():
    assert TXN_CMD_W == 3 + CMD_W
    p = encode_prepare(7, 1, b"k", b"v")
    assert len(p) == TXN_CMD_W * 4 and len(p) != CMD_W * 4
    op, tid, arg, cmd = decode_record(p)
    assert (op, tid) == (TXN_PREPARE, 7) and len(cmd) == CMD_W
    op, tid, arg, _ = decode_record(encode_commit(9, 0b101))
    assert (op, tid, arg) == (TXN_COMMIT, 9, 0b101)
    op, tid, arg, _ = decode_record(encode_abort(3, 2))
    assert (op, tid, arg) == (TXN_ABORT, 3, 2)


def test_mergeable_ops_fold_and_tombstone_base():
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=64)

    def pump(n=2):
        for _ in range(n):
            c.step()

    kv.merge(0, OP_INCR, b"ctr", encode_merge_val(OP_INCR, 5))
    pump()
    kv.merge(0, OP_INCR, b"ctr", encode_merge_val(OP_INCR, -2))
    pump()
    assert decode_merge_val(OP_INCR, kv.get(0, b"ctr")) == 3
    kv.merge(0, OP_MAX, b"hi", encode_merge_val(OP_MAX, 10))
    pump()
    kv.merge(0, OP_MAX, b"hi", encode_merge_val(OP_MAX, 4))
    pump()
    assert decode_merge_val(OP_MAX, kv.get(0, b"hi")) == 10
    for bit in (3, 3, 77):
        kv.merge(0, OP_SADD, b"set", encode_merge_val(OP_SADD, bit))
        pump()
    assert decode_merge_val(OP_SADD, kv.get(0, b"set")) == 2
    # a removed key's slot may hold a stale value — merges must read
    # their base through the live match only (start from zero)
    kv.put(0, b"ctr2", encode_merge_val(OP_INCR, 99))
    pump()
    kv.remove(0, b"ctr2")
    pump()
    kv.merge(0, OP_INCR, b"ctr2", encode_merge_val(OP_INCR, 1))
    pump()
    assert decode_merge_val(OP_INCR, kv.get(0, b"ctr2")) == 1


# ---------------------------------------------------------------------------
# coordinator: 2PC commit lane + fast path
# ---------------------------------------------------------------------------

def _txn_cluster(G=2, timeout_steps=64):
    shard = ShardedCluster(CFG, 3, G, txn=True)
    from rdma_paxos_tpu.obs import Observability
    shard.obs = Observability()
    kv = ShardedKVS(shard, cap=256)
    coord = attach_coordinator(kv, timeout_steps=timeout_steps)
    shard.place_leaders()
    keys = keys_for_groups(kv.router, 4)
    return shard, kv, coord, keys


def test_twopc_commit_two_dispatches_and_visibility():
    shard, kv, coord, keys = _txn_cluster()
    # warm the txn-lane program so the probe counts steady-state
    h = kv.transact([("put", keys[0][3], b"w"), ("put", keys[1][3],
                                                 b"w")])
    for _ in range(6):
        if h.done:
            break
        shard.step()
    assert h.committed

    d0 = shard.dispatches
    h = kv.transact([("put", keys[0][0], b"va"),
                     ("put", keys[1][0], b"vb")])
    steps = 0
    while not h.done and steps < 8:
        shard.step()
        steps += 1
    assert h.committed
    assert shard.dispatches - d0 == 2, (
        "cross-group commit must resolve in ~2 protocol dispatches")
    assert kv.get(keys[0][0]) == b"va"
    assert kv.get(keys[1][0]) == b"vb"
    assert coord.health()["committed_total"] == 2
    assert coord.health()["locks"] == 0


def test_twopc_read_set_at_serialization_point():
    shard, kv, coord, keys = _txn_cluster()
    h = kv.transact([("put", keys[0][1], b"base")])
    while not h.done:
        shard.step()
    h = kv.transact([("put", keys[1][1], b"x")],
                    reads=[keys[0][1]])
    while not h.done:
        shard.step()
    assert h.committed and h.reads[keys[0][1]] == b"base"


def test_conflict_aborts_immediately_no_partial_writes():
    shard, kv, coord, keys = _txn_cluster()
    a = kv.transact([("put", keys[0][0], b"A0"),
                     ("put", keys[1][0], b"A1")])
    # same key in the write set while A holds the lock: immediate
    # deterministic abort, nothing submitted anywhere
    b = kv.transact([("put", keys[0][0], b"B0"),
                     ("put", keys[1][2], b"B1")])
    assert b.done and not b.committed and b.abort_reason == "conflict"
    while not a.done:
        shard.step()
    assert a.committed and kv.get(keys[0][0]) == b"A0"
    assert kv.get(keys[1][2]) is None       # B left no partial write
    m = shard.obs.metrics.snapshot()["counters"]
    assert m.get("txn_committed_total") == 1
    assert m.get("txn_aborted_total{reason=conflict}") == 1


def test_unreachable_participant_times_out_and_aborts():
    shard, kv, coord, keys = _txn_cluster(timeout_steps=4)
    dead = shard.leader(0)
    shard.partition(0, [[dead], [r for r in range(3) if r != dead]])
    h = kv.transact([("put", keys[0][0], b"lost"),
                     ("put", keys[1][0], b"staged")])
    for _ in range(8):
        shard.step()
    # the decision is host-made at the step-domain deadline; the ABORT
    # record to the dead group waits for a live leader to land on
    assert h.state in ("aborting", "aborted")
    assert not h.committed and h.abort_reason == "timeout"
    shard.heal(0)
    cand = next(r for r in range(3) if r != dead)
    shard.step(timeouts={0: [cand]})
    for _ in range(16):
        if h.done:
            break
        shard.step()
    assert h.done and not h.committed
    # the staged write on the healthy group was dropped at ABORT
    assert kv.get(keys[1][0]) is None
    assert kv.get(keys[0][0]) is None


def test_merge_fast_path_skips_prepare():
    shard, kv, coord, keys = _txn_cluster()
    d0 = shard.dispatches
    h = kv.transact([("incr", keys[0][0], 5), ("incr", keys[1][0],
                                               11)])
    steps = 0
    while not h.done and steps < 8:
        shard.step()
        steps += 1
    assert h.committed
    assert shard.dispatches - d0 <= 2
    h2 = kv.transact([("incr", keys[0][0], 2)])
    while not h2.done:
        shard.step()
    raw = kv.get(keys[0][0])
    assert decode_merge_val(OP_INCR, raw) == 7
    assert coord.health()["aborted_total"] == {}


def test_attach_requires_txn_flag_and_transact_requires_attach():
    shard = ShardedCluster(CFG, 3, 2)           # txn=False
    kv = ShardedKVS(shard, cap=64)
    with pytest.raises(ValueError):
        attach_coordinator(kv)
    with pytest.raises(RuntimeError):
        kv.transact([("put", b"k", b"v")])


def test_txn_under_live_sharded_driver():
    """e2e: the driver's poll loop serves a transaction — bursts and
    pipelining give way while the commit lane is live (wants_serial),
    and health()/counters carry the txn surfaces."""
    import tempfile
    import time

    from rdma_paxos_tpu.obs.health import validate_cluster
    from rdma_paxos_tpu.runtime.sharded_driver import \
        ShardedClusterDriver

    cfg = LogConfig(n_slots=256, slot_bytes=128, window_slots=32,
                    batch_slots=16)
    wd = tempfile.mkdtemp(prefix="txn_drive")
    d = ShardedClusterDriver(cfg, 3, 2, workdir=wd, txn=True,
                             pipeline=2)
    kv = ShardedKVS(d.cluster, cap=256)
    coord = attach_coordinator(kv, timeout_steps=512)
    d.run(period=0.002)
    try:
        t0 = time.time()
        while time.time() - t0 < 30:
            if all(d.cluster.leader_hint(g) >= 0 for g in range(2)):
                break
            time.sleep(0.02)
        assert all(d.cluster.leader_hint(g) >= 0 for g in range(2))
        keys = keys_for_groups(kv.router, 4)
        h = kv.transact([("put", keys[0][0], b"live-a"),
                         ("put", keys[1][0], b"live-b")])
        t0 = time.time()
        while not h.done and time.time() - t0 < 30:
            time.sleep(0.005)
        assert h.committed, (h.state, h.abort_reason)
        assert kv.get(keys[0][0]) == b"live-a"
        assert kv.get(keys[1][0]) == b"live-b"
        h2 = kv.transact([("incr", keys[0][2], 7),
                          ("incr", keys[1][2], 3)])
        t0 = time.time()
        while not h2.done and time.time() - t0 < 30:
            time.sleep(0.005)
        assert h2.committed
        assert decode_merge_val(OP_INCR, kv.get(keys[0][2])) == 7
        hd = d.health()
        assert hd["txn"]["committed_total"] == 2
        assert hd["txn"]["active"] == 0 and hd["txn"]["locks"] == 0
        assert validate_cluster(hd) == []
        m = d.obs.metrics.snapshot()["counters"]
        assert m.get("txn_committed_total") == 2
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# strict-serializability checker
# ---------------------------------------------------------------------------

def _send(payload, conn=0, req=0):
    from rdma_paxos_tpu.consensus.log import EntryType
    return (int(EntryType.SEND), conn, req, payload)


def test_serialize_checker_accepts_clean_and_rejects_violations():
    from rdma_paxos_tpu.chaos.serialize import check_txn_streams
    p1 = encode_prepare(1, 1, b"a", b"x")
    p2 = encode_prepare(2, 1, b"b", b"y")
    c1 = encode_commit(1, 0b11)
    c2 = encode_commit(2, 0b11)
    # clean: both groups commit 1 then 2 — witness order [1, 2]
    v = check_txn_streams([[_send(p1), _send(c1), _send(p2),
                            _send(c2)],
                           [_send(p1), _send(c1), _send(p2),
                            _send(c2)]])
    assert v["ok"] and v["order"] == [1, 2]
    # partial commit: tid 1 commits in group 0 only
    v = check_txn_streams([[_send(p1), _send(c1)], [_send(p1)]])
    assert not v["ok"]
    assert any(x["kind"] == "partial_commit" for x in v["violations"])
    # commit + abort for the same tid
    v = check_txn_streams([[_send(p1), _send(c1)],
                           [_send(p1), _send(encode_abort(1, 1)),
                            _send(encode_commit(1, 0b11))]])
    assert any(x["kind"] == "commit_and_abort"
               for x in v["violations"])
    # cycle: the two groups commit 1/2 in OPPOSITE orders
    v = check_txn_streams([[_send(p1), _send(p2), _send(c1),
                            _send(c2)],
                           [_send(p1), _send(p2), _send(c2),
                            _send(c1)]])
    assert not v["ok"]
    assert any(x["kind"] == "serialization_cycle"
               for x in v["violations"])
    # commit with no prepare staged in that group
    v = check_txn_streams([[_send(c1)], [_send(p1), _send(c1)]])
    assert any(x["kind"] == "commit_without_prepare"
               for x in v["violations"])


# ---------------------------------------------------------------------------
# chaos: coordinator-leader crash mid-prepare (the CI smoke's twin)
# ---------------------------------------------------------------------------

def test_txn_nemesis_green_and_deterministic():
    import json
    from rdma_paxos_tpu.txn.chaos import run_txn_chaos
    v1 = run_txn_chaos(seed=5)
    assert v1["ok"], v1
    assert v1["serializability"]["ok"]
    assert v1["effect_violations"] == []
    assert v1["txns"]["straddler"]["state"] == "aborted"
    assert v1["linearizability"]["ok"] is True
    v2 = run_txn_chaos(seed=5)
    assert json.dumps(v1, sort_keys=True, default=str) == \
        json.dumps(v2, sort_keys=True, default=str)


# ---------------------------------------------------------------------------
# observability + lint surfaces
# ---------------------------------------------------------------------------

def test_abort_rate_alert_rule_in_default_set():
    from rdma_paxos_tpu.obs.alerts import default_rules
    rules = {r["name"]: r for r in default_rules()}
    r = rules["txn_abort_rate"]
    assert r["kind"] == "counter_rate"
    assert r["metric"] == "txn_aborted_total"
    assert r["severity"] == "warn"


def test_health_and_console_surface_txn():
    from rdma_paxos_tpu.obs.console import _txn_state
    from rdma_paxos_tpu.obs.health import CLUSTER_HEALTH_FIELDS
    assert "txn" in CLUSTER_HEALTH_FIELDS
    s = _txn_state(dict(txn=dict(committed_total=3, active=2,
                                 aborted_total=dict(conflict=1))))
    assert s == "3c/1a 2live"
    assert _txn_state(dict()) == "-"


def test_jit_safety_scan_covers_txn_lane():
    """txn/lane.py runs inside the compiled step: the graftlint
    jit-purity pass must scan it (DEVICE_MODULES) and find nothing."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    from rdma_paxos_tpu.analysis.purity import DEVICE_MODULES
    assert "rdma_paxos_tpu/txn/lane.py" in DEVICE_MODULES
    assert_jit_purity()
