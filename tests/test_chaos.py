"""Chaos subsystem (rdma_paxos_tpu.chaos): link-model/fault-DSL/
history/linearizability units plus the integration contracts:

* a seeded nemesis run is BIT-reproducible: same seed ⇒ same schedule,
  same history JSONL, same verdict;
* a short smoke schedule (tier-1, no ``slow`` marker) runs clean —
  invariants hold and the client history linearizes — under
  partitions, crash-restarts, drops, delays, duplication, and timer
  skew;
* an injected dedup bug (test-only monkeypatch of the fold) is CAUGHT
  by the linearizability checker and produces a replayable reproducer
  artifact;
* ``retransmit_put`` dedup survives leader failover AND crash-restart
  (the ``last_req`` registry rebuilds identically from the store);
* crash-restart wipes the volatile uncommitted suffix (the crash
  semantics that make the nemesis meaningful);
* the nemesis runner refuses/strips psum-incompatible schedules at
  construction — never mid-run;
* compiled-step cache keys are unchanged by the link model and chaos
  instrumentation (host-side-only guard, same style as test_obs).
"""

import json
import logging
import os

import jax.numpy as jnp
import numpy as np
import pytest

from rdma_paxos_tpu.chaos.artifact import load_reproducer, write_reproducer
from rdma_paxos_tpu.chaos.faults import (
    FaultSchedule, HardStateTracker, LinkModel, StepTimerModel,
    crash_replica, generate_schedule, restart_replica)
from rdma_paxos_tpu.chaos.history import HistoryRecorder
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.chaos.linearize import check_history, check_key
from rdma_paxos_tpu.chaos.runner import DEFAULT_KV_CFG, NemesisRunner
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.models.kvs import CMD_W
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.obs import Observability
from rdma_paxos_tpu.runtime.sim import SimCluster

KVCFG = DEFAULT_KV_CFG


# ---------------------------------------------------------------------------
# link model
# ---------------------------------------------------------------------------

def _full(n):
    return np.ones((n, n), np.int32)


def test_link_model_asymmetric_block():
    lm = LinkModel(3, seed=0)
    lm.block(0, 1)                       # 0 cannot hear 1
    m = lm.effective_mask(_full(3), 0)
    assert m[0, 1] == 0 and m[1, 0] == 1     # asymmetric
    lm.unblock(0, 1)
    assert lm.effective_mask(_full(3), 0).all()


def test_link_model_drop_is_seed_deterministic():
    lm = LinkModel(4, seed=9)
    lm.set_drop(0.5)
    m1 = lm.effective_mask(_full(4), 17)
    m2 = lm.effective_mask(_full(4), 17)
    assert (m1 == m2).all()              # pure in (state, step)
    assert (np.diag(m1) == 1).all()      # self-hearing survives
    # a different seed disagrees somewhere over a few steps
    lm2 = LinkModel(4, seed=10)
    lm2.set_drop(0.5)
    assert any(
        (lm.effective_mask(_full(4), t)
         != lm2.effective_mask(_full(4), t)).any() for t in range(16))


def test_link_model_delay_is_periodic():
    lm = LinkModel(3, seed=0)
    lm.set_delay(2, dst=0, src=1)        # delivers every 3rd step
    hears = [lm.effective_mask(_full(3), t)[0, 1] for t in range(9)]
    assert hears == [0, 0, 1, 0, 0, 1, 0, 0, 1]


def test_link_model_down_overrides_everything():
    lm = LinkModel(3, seed=0)
    lm.set_dup(1.0)                      # forced deliveries everywhere
    lm.down.add(2)
    m = lm.effective_mask(_full(3), 0)
    assert m[2, 0] == 0 and m[0, 2] == 0 and m[2, 2] == 1


def test_link_model_partition_composes_and_heals():
    lm = LinkModel(4, seed=0)
    lm.partition([[0, 1], [2, 3]])
    m = lm.effective_mask(_full(4), 0)
    assert m[0, 1] == 1 and m[0, 2] == 0 and m[2, 0] == 0
    lm.heal()
    assert lm.effective_mask(_full(4), 0).all()
    # unlisted replicas are ISOLATED singletons — identical semantics
    # to SimCluster.partition(), so schedules mean the same fault
    # under either API
    lm.partition([[0]])
    m = lm.effective_mask(_full(4), 0)
    assert m[1, 2] == 0 and m[2, 1] == 0 and m[1, 3] == 0
    assert (np.diag(m) == 1).all()


# ---------------------------------------------------------------------------
# fault-schedule DSL
# ---------------------------------------------------------------------------

def test_schedule_validates_structure():
    with pytest.raises(ValueError, match="unknown fault op"):
        FaultSchedule().at(0, "meteor")
    with pytest.raises(ValueError, match="missing kwargs"):
        FaultSchedule().at(0, "crash")
    s = FaultSchedule().at(3, "crash", replica=7)
    with pytest.raises(ValueError, match="out of range"):
        s.validate(3)
    with pytest.raises(ValueError, match="not down"):
        FaultSchedule().at(0, "restart", replica=1).validate(3)


def test_schedule_rejects_majority_crash():
    s = (FaultSchedule()
         .at(0, "crash", replica=0)
         .at(1, "crash", replica=1))
    with pytest.raises(ValueError, match="at most 1"):
        s.validate(3)
    # sequential (restart between) is fine
    s2 = (FaultSchedule()
          .at(0, "crash", replica=0)
          .at(2, "restart", replica=0)
          .at(4, "crash", replica=1)
          .at(6, "restart", replica=1))
    s2.validate(3)


def test_schedule_json_round_trip_and_generation_determinism():
    s1 = generate_schedule(42, 5, 120)
    s2 = generate_schedule(42, 5, 120)
    assert s1.to_json() == s2.to_json()
    assert len(s1) > 0
    assert FaultSchedule.from_json(s1.to_json()).to_json() == s1.to_json()
    assert generate_schedule(43, 5, 120).to_json() != s1.to_json()


# ---------------------------------------------------------------------------
# history recorder
# ---------------------------------------------------------------------------

def test_history_records_and_round_trips():
    h = HistoryRecorder()
    h.set_clock(1)
    w = h.invoke("put", b"k", b"v\xff", client=3, req_id=1, replica=0)
    r1 = h.invoke("get", b"k", replica=1, weak=True)
    h.ok(r1, None)
    h.set_clock(2)
    h.retransmit(w, replica=2)
    h.ok(w)
    r2 = h.invoke("get", b"k", replica=0)
    h.fail(r2, reason="leadership_unverified")
    dangling = h.invoke("put", b"k", b"v2", client=3, req_id=2)
    assert h.pending() == [dangling]
    h.timeout(dangling)
    assert h.op_id_for(3, 1) == w
    ops = h.ops()                         # weak excluded by default
    assert [o["op_id"] for o in ops] == [w, r2, dangling]
    assert len(h.ops(include_weak=True)) == 4
    # non-UTF8 bytes survive the JSONL round trip exactly
    h2 = HistoryRecorder.from_jsonl(h.to_jsonl())
    assert h2.to_jsonl() == h.to_jsonl()
    assert h2.ops() == h.ops()
    rec = h2.op(w)
    assert rec["value"].encode("latin-1") == b"v\xff"
    assert rec["inv"] == 1 and rec["res"] == 2 and rec["status"] == "ok"


# ---------------------------------------------------------------------------
# linearizability checker
# ---------------------------------------------------------------------------

def _op(op, value=None, out=None, inv=0, res=0, status="ok", key="k",
        op_id=0):
    return dict(op=op, key=key, value=value, out=out, inv=inv,
                res=res, status=status, op_id=op_id)


def test_checker_accepts_legal_histories():
    assert check_key([
        _op("put", value="v1", inv=0, res=1),
        _op("get", out="v1", inv=2, res=3, op_id=1),
    ])["ok"] is True
    # concurrent writes: either order is a valid linearization
    assert check_key([
        _op("put", value="a", inv=0, res=5),
        _op("put", value="b", inv=1, res=6, op_id=1),
        _op("get", out="a", inv=7, res=8, op_id=2),
    ])["ok"] is True
    # rm -> absent read
    assert check_key([
        _op("put", value="v", inv=0, res=1),
        _op("rm", inv=2, res=3, op_id=1),
        _op("get", out=None, inv=4, res=5, op_id=2),
    ])["ok"] is True


def test_checker_rejects_stale_read():
    r = check_key([
        _op("put", value="v1", inv=0, res=1),
        _op("put", value="v2", inv=2, res=3, op_id=1),
        _op("get", out="v1", inv=4, res=5, op_id=2),
    ])
    assert r["ok"] is False
    assert "linearizable_prefix" in r


def test_checker_treats_timeouts_as_ambiguous():
    # an unacked write may have applied...
    ops = [
        _op("put", value="v1", inv=0, res=1),
        _op("put", value="v2", inv=2, res=None, status="timeout",
            op_id=1),
        _op("get", out="v2", inv=4, res=5, op_id=2),
    ]
    assert check_key(ops)["ok"] is True
    # ...or not
    ops[2]["out"] = "v1"
    assert check_key(ops)["ok"] is True
    # but a value nobody ever wrote is still a violation
    ops[2]["out"] = "v9"
    assert check_key(ops)["ok"] is False


def test_checker_is_per_key_partitioned():
    res = check_history([
        _op("put", value="a", inv=0, res=1, key="x"),
        _op("get", out="a", inv=2, res=3, key="x", op_id=1),
        _op("put", value="b", inv=0, res=1, key="y", op_id=2),
        _op("get", out="stale", inv=2, res=3, key="y", op_id=3),
    ])
    assert res["ok"] is False
    assert res["violations"] == ["y"]
    assert res["keys"]["x"]["ok"] is True


# ---------------------------------------------------------------------------
# invariants (shared with tests/test_fuzz.py)
# ---------------------------------------------------------------------------

def _res(R=3, **over):
    base = dict(term=[1] * R, role=[int(Role.FOLLOWER)] * R,
                head=[0] * R, apply=[0] * R, commit=[0] * R,
                end=[0] * R)
    base.update(over)
    return base


def test_invariant_checker_catches_each_class():
    inv = InvariantChecker(3)
    inv.check_step(_res(commit=[5, 0, 0], end=[5, 0, 0],
                        apply=[5, 0, 0]))
    with pytest.raises(InvariantViolation, match="I2"):
        inv.check_step(_res(commit=[4, 0, 0], end=[4, 0, 0],
                            apply=[4, 0, 0]))
    inv2 = InvariantChecker(3)
    inv2.check_step(_res(role=[int(Role.LEADER), 1, 1], term=[3, 3, 3]))
    with pytest.raises(InvariantViolation, match="I4"):
        inv2.check_step(_res(role=[1, int(Role.LEADER), 1],
                             term=[3, 3, 3]))
    with pytest.raises(InvariantViolation, match="I5"):
        InvariantChecker(3).check_step(_res(commit=[1, 0, 0]))
    with pytest.raises(InvariantViolation, match="I1/I3"):
        InvariantChecker(2).check_convergence(
            [[(1, 1, 1, b"a")], [(1, 1, 1, b"b")]])
    InvariantChecker(2).check_convergence(
        [[(1, 1, 1, b"a")], [(1, 1, 1, b"a"), (1, 1, 2, b"b")]])


def test_invariant_checker_restart_rearms_commit_baseline():
    inv = InvariantChecker(3)
    inv.check_step(_res(commit=[9, 9, 9], end=[9, 9, 9],
                        apply=[9, 9, 9]))
    inv.reset_replica(0)                 # crash-restart incarnation
    inv.check_step(_res(commit=[3, 9, 9], end=[3, 9, 9],
                        apply=[3, 9, 9]))
    # rebases keep absolute commit monotone
    inv.check_step(_res(commit=[1, 7, 7], end=[1, 7, 7],
                        apply=[1, 7, 7]), rebased_total=2)


# ---------------------------------------------------------------------------
# timers + artifact
# ---------------------------------------------------------------------------

def test_step_timer_model_skew_biases_firing():
    idle = dict(hb_seen=[0, 0, 0], role=[int(Role.FOLLOWER)] * 3)
    tm = StepTimerModel(3, seed=5, lo=8, hi=12)
    tm.skew(0, 0.2)                      # trigger-happy replica 0
    fired = []
    for _ in range(60):
        fired += tm.fire(set())
        tm.observe(idle)
    assert fired, "no timer ever fired"
    assert fired.count(0) > fired.count(1)
    assert fired.count(0) > fired.count(2)
    # crashed replicas never fire
    tm2 = StepTimerModel(3, seed=5, lo=2, hi=3)
    for _ in range(10):
        assert 1 not in tm2.fire({1})
        tm2.observe(idle)


def test_reproducer_artifact_round_trip(tmp_path):
    sched = FaultSchedule().at(2, "crash", replica=1).at(
        5, "restart", replica=1)
    path = write_reproducer(
        str(tmp_path / "repro.json"), seed=77, schedule=sched,
        reason="unit", config={"n_replicas": 3},
        history='{"t": 0, "ev": "invoke"}',
        violation={"invariant": "I2"}, obs=Observability())
    doc = load_reproducer(path)
    assert doc["seed"] == 77 and doc["reason"] == "unit"
    assert doc["schedule"] == sched.events
    assert doc["violation"]["invariant"] == "I2"
    assert "metrics" in doc and "trace" in doc


# ---------------------------------------------------------------------------
# protocol-level link faults + crash-restart semantics
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_asymmetric_link_fault_partial_acks():
    """Replica 2 stops hearing the leader (one direction only): it
    stops acking while replica 1 keeps the quorum alive — commit still
    advances, and the leader sees exactly which peer went quiet."""
    c = SimCluster(KVCFG, 3)
    link = LinkModel(3, seed=0)
    c.link_model = link
    c.run_until_elected(0)
    link.block(2, 0)                     # 2 cannot hear 0
    c.submit(0, b"still-commits")
    res = c.step()
    res = c.step()
    assert res["peer_acked"][0][1] == 1
    assert res["peer_acked"][0][2] == 0
    assert any(p == b"still-commits" for (_, _, _, p) in c.replayed[0])
    link.heal()
    for _ in range(3):
        res = c.step()
    assert res["peer_acked"][0][2] == 1  # catches back up after heal


@pytest.mark.chaos
def test_crash_wipes_uncommitted_suffix():
    """An isolated leader's locally-appended (uncommitted) entries are
    volatile: crash-restart recovers only the applied/stable prefix —
    the suffix is gone, exactly what a real crash loses."""
    c = SimCluster(KVCFG, 3)
    link = LinkModel(3, seed=0)
    c.link_model = link
    c.run_until_elected(0)
    base = c.step()
    link.partition([[0], [1, 2]])
    c.submit(0, b"orphan")
    res = c.step()                       # appends locally, cannot commit
    assert int(res["end"][0]) > int(res["commit"][0])
    commit0 = int(res["commit"][0])
    crash_replica(c, 0, link)
    c.step()
    restart_replica(c, 0, link)
    link.heal()
    res = c.step()
    assert int(res["end"][0]) == commit0          # suffix wiped
    assert int(res["role"][0]) == int(Role.FOLLOWER)
    del base


@pytest.mark.chaos
def test_retransmit_dedup_survives_crash_restart():
    """Satellite: the ``last_req`` registry claims dedup survives
    reconnects and failover — prove it under crash-restart: the leader
    commits a PUT and dies before acking; the client retransmits
    (twice) against the new leader; the old leader restarts with wiped
    volatile state and a registry rebuilt from its store. Every
    replica — including the restarted one — applies the PUT exactly
    once."""
    c = SimCluster(KVCFG, 3)
    link = LinkModel(3, seed=0)
    c.link_model = link
    kv = ReplicatedKVS(c, cap=256)
    hard = HardStateTracker(3)
    c.run_until_elected(0)
    hard.observe(c.last)
    sess = kv.session(client_id=7)
    rid = sess.put(0, b"k", b"v1")
    c.step()
    c.step()
    hard.observe(c.last)
    assert kv.get(0, b"k", linearizable=True) == b"v1"   # committed...
    crash_replica(c, 0, link)            # ...but the ack never left
    res = None
    for _ in range(4):
        res = c.step(timeouts=[1])
        hard.observe(res)
        if res["role"][1] == int(Role.LEADER):
            break
    assert res["role"][1] == int(Role.LEADER)
    # client retries against the new leader — twice, as a lossy network
    # would
    sess.retransmit_put(1, b"k", b"v1", rid)
    sess.retransmit_put(1, b"k", b"v1", rid)
    c.step()
    c.step()
    hard.observe(c.last)
    restart_replica(c, 0, link, hard=hard, kvs=kv)
    for _ in range(6):
        hard.observe(c.step())
    for r in range(3):
        assert kv.get(r, b"k") == b"v1"
    # the survivors deduped both duplicates; the restarted replica's
    # registry was rebuilt from the committed stream and deduped
    # identically (dedup derives from the log, not leader-local memory)
    assert kv.deduped[1] == 2 and kv.deduped[2] == 2
    assert kv.deduped[0] == 2
    # election safety across the crash: nobody double-voted — a single
    # leader per term throughout
    assert int(c.last["term"][0]) == int(c.last["term"][1])


# ---------------------------------------------------------------------------
# nemesis runner
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_nemesis_smoke_clean_run():
    """Tier-1 smoke: a short seeded schedule (partitions, crash-restart,
    drops, delays, duplication, skew) over 3 replicas — invariants
    hold and the recorded client history linearizes."""
    obs = Observability()
    runner = NemesisRunner(n_replicas=3, seed=7, steps=50, obs=obs)
    v = runner.run()
    assert v["ok"], v
    assert v["invariant_violations"] == []
    assert v["linearizability"]["ok"] is True
    assert v["linearizability"]["ops"] > 10
    assert len(runner.schedule) > 0
    assert obs.metrics.get("faults_injected_total") > 0


@pytest.mark.chaos
def test_nemesis_seeded_run_is_bit_reproducible():
    """Acceptance: same seed ⇒ same schedule, same history (JSONL
    byte-identical — logical clocks only), same verdict."""
    r1 = NemesisRunner(n_replicas=3, seed=13, steps=40)
    v1 = r1.run()
    r2 = NemesisRunner(n_replicas=3, seed=13, steps=40)
    v2 = r2.run()
    assert r1.schedule.to_json() == r2.schedule.to_json()
    assert r1.history.to_jsonl() == r2.history.to_jsonl()
    assert v1 == v2


def _buggy_fold(self, r):
    """The dedup bug under test: ``last_req`` is still tracked but the
    skip is gone — a duplicated client message re-applies, so a stale
    retransmit can roll a key back."""
    stream = self.c.replayed[r]
    while self._cursor[r] < len(stream):
        etype, conn, req, payload = stream[self._cursor[r]]
        self._cursor[r] += 1
        if etype != int(EntryType.SEND):
            continue
        if len(payload) != CMD_W * 4:
            continue
        if req > 0 and conn > 0:
            self.last_req[r][conn] = max(self.last_req[r].get(conn, 0),
                                         req)
        cmd = jnp.asarray(np.frombuffer(payload, "<i4"))
        self.tables[r], _ = self._apply_jit(self.tables[r], cmd)


_BUG_RUN = dict(n_replicas=3, steps=80, n_keys=2,
                workload_opts=dict(dup_msg_p=0.9, dup_delay=6,
                                   p_write=0.6),
                fault_kinds=("partition", "crash"))


@pytest.mark.chaos
def test_injected_dedup_bug_is_caught_and_replayable(tmp_path,
                                                     monkeypatch):
    """Acceptance: break the exactly-once fold (test-only monkeypatch)
    and the linearizability checker catches the client-visible anomaly
    (a duplicated PUT rolling a key back), emitting a reproducer
    artifact that replays to the same verdict. The identical run with
    the real fold is clean — the checker flags the bug, not the
    schedule."""
    monkeypatch.setattr(ReplicatedKVS, "_fold", _buggy_fold)
    art = str(tmp_path / "dedup_bug.json")
    v = NemesisRunner(seed=1, artifact_path=art, **_BUG_RUN).run()
    assert not v["ok"]
    assert v["invariant_violations"] == []   # protocol is fine...
    assert v["linearizability"]["ok"] is False   # ...the CONTRACT broke
    assert v["artifact"] == art and os.path.exists(art)
    doc = load_reproducer(art)
    assert doc["seed"] == 1 and doc["history"]
    assert doc["violation"]["linearizability"]["violations"]
    # replay the artifact: deterministic harness, same verdict
    v2 = NemesisRunner.replay(art)
    assert v2["linearizability"]["violations"] == \
        v["linearizability"]["violations"]
    # control: the unbroken fold runs the SAME schedule clean
    monkeypatch.undo()
    v3 = NemesisRunner(seed=1, **_BUG_RUN).run()
    assert v3["ok"], v3


@pytest.mark.chaos
def test_nemesis_refuses_psum_incompatible_schedule(caplog):
    """Satellite: partitions cannot be modeled under fanout='psum'
    (SimCluster raises mid-step by design) — the runner must refuse at
    construction or skip with a clear log line, never die mid-run."""
    sched = (FaultSchedule()
             .at(2, "partition", groups=[[0], [1, 2]])
             .at(6, "heal")
             .at(10, "dup", p=0.5))
    with pytest.raises(ValueError, match="psum"):
        NemesisRunner(n_replicas=3, seed=0, steps=20, schedule=sched,
                      fanout="psum")
    with caplog.at_level(logging.WARNING, "rdma_paxos_tpu.chaos"):
        runner = NemesisRunner(n_replicas=3, seed=0, steps=20,
                               schedule=FaultSchedule(sched.events),
                               fanout="psum",
                               skip_incompatible_faults=True)
    assert runner.schedule.mask_affecting() == []
    assert len(runner.schedule) == 2          # heal + dup survive
    assert any("skipping" in r.message for r in caplog.records)


@pytest.mark.chaos
@pytest.mark.slow
def test_nemesis_long_mixed_schedule_five_replicas():
    """The full fault mix at R=5 over a long schedule — excluded from
    tier-1 (slow) so its wall time never taxes the fast suite."""
    for seed in (0, 1, 2):
        v = NemesisRunner(n_replicas=5, seed=seed, steps=100).run()
        assert v["ok"], (seed, v)


# ---------------------------------------------------------------------------
# jit-safety: the link model + chaos instrumentation are host-side only
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_compiled_step_cache_keys_unchanged_by_chaos():
    """Acceptance guard (same style as tests/test_obs.py): attaching a
    link model, history recorder, and chaos observability must not add
    or change any compiled-step cache key — faults are INPUT DATA, not
    program structure."""
    bare = SimCluster(KVCFG, 3)
    bare.run_until_elected(0)
    bare.submit(0, b"x")
    bare.step()
    keys_before = set(SimCluster._STEP_CACHE)

    # audit=False isolates this guard's property (chaos itself is pure
    # input data); the audit=True default DELIBERATELY compiles
    # distinct "audit"-marked variants — tests/test_audit.py guards
    # that separation
    v = NemesisRunner(n_replicas=3, seed=3, steps=25,
                      audit=False).run()
    assert v["ok"], v
    assert set(SimCluster._STEP_CACHE) == keys_before, (
        "chaos changed the compiled-step cache keys — the link model "
        "or instrumentation leaked into jitted code")
