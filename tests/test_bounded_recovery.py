"""Bounded recovery: app-state checkpoints + store compaction.

The reference's joiner snapshot is ALWAYS the full BerkeleyDB record
stream (``db-interface.c:98-134``) — O(entire history), fine at its
~10k-ops scale, fatal behind a multi-M-ops/s pipeline. Here a follower's
app state is checkpointed through an app-level snapshot hook (for the
toyserver: DUMPALL; the redis analog is an RDB) at a known store index,
and the store prefix the checkpoint covers is COMPACTED away
(crash-safe rewrite; absolute record indices survive). Donor transfer
and fresh-app rebuild become O(app state + suffix).

The done-gate: rejoin cost stays FLAT while total history grows."""

import os
import socket
import subprocess
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
CFG = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                batch_slots=32)
PORTS = [7441, 7442, 7443]


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


def toy_dump(sock) -> bytes:
    """App snapshot via the toyserver's DUMPALL listing."""
    sock.sendall(b"DUMPALL\n")
    f = sock.makefile("rb")
    out = []
    while True:
        ln = f.readline()
        if not ln or ln == b".\n":
            return b"".join(out)
        out.append(ln)


def toy_restore(sock, blob: bytes) -> None:
    """Rebuild toyserver state by feeding SETs from a DUMPALL listing."""
    f = sock.makefile("rb")
    for ln in blob.splitlines():
        if not ln.strip():
            continue
        sock.sendall(b"SET " + ln + b"\n")
        assert f.readline().strip() == b"+OK"


def toy_probe(sock) -> None:
    """Processed-input barrier probe: ECHO a unique token and wait for
    its reply, discarding buffered responses to earlier replayed
    commands (see ReplayEngine.barrier)."""
    import uuid
    tok = uuid.uuid4().hex.encode()
    sock.sendall(b"ECHO " + tok + b"\n")
    buf = b""
    want = b"=" + tok
    while want not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise OSError("app closed during barrier probe")
        buf += chunk


def spawn_app(tmp_path, r, port):
    env = dict(os.environ)
    env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
    env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path), f"proxy{r}.sock")
    return subprocess.Popen([os.path.join(NATIVE, "toyserver"), str(port)],
                            env=env, stderr=subprocess.DEVNULL)


class Client:
    def __init__(self, port):
        self.s = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.f = self.s.makefile("rb")

    def cmd(self, line: str) -> bytes:
        self.s.sendall(line.encode() + b"\n")
        return self.f.readline().strip()

    def close(self):
        try:
            self.s.close()
        except OSError:
            pass


def wait_kv(port, key, want, timeout=15.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            c = Client(port)
            last = c.cmd(f"GET {key}")
            c.close()
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.1)
    return last


def test_checkpoint_compaction_keeps_rejoin_cost_flat(tmp_path):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            # wide timeouts: no mid-test election is intended, and a
            # slow host's long driver iteration must not trigger a
            # spurious deposition that severs the drill's sessions
            timeout_cfg=TimeoutConfig(elec_timeout_low=2.0,
                                      elec_timeout_high=4.0),
            app_snapshot=(toy_dump, toy_restore, toy_probe))
        for r, port in enumerate(PORTS):
            apps.append(spawn_app(tmp_path, r, port))
        time.sleep(0.3)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        lead = driver.leader()
        assert lead >= 0
        victim = next(r for r in range(3) if r != lead)
        other = next(r for r in range(3) if r not in (lead, victim))

        def write_wave(tag, n):
            c = Client(PORTS[lead])
            for i in range(n):
                assert c.cmd(f"SET {tag}{i} v{i}") == b"+OK"
            c.close()
            # wait until the wave fully replicated everywhere
            for r in range(3):
                if r != lead:
                    assert wait_kv(PORTS[r], f"{tag}{n-1}",
                                   b"v%d" % (n - 1)) is not None

        # wave 1, then checkpoint + compact on the OTHER follower (the
        # future donor) — the victim will be rebuilt from it
        write_wave("a", 120)
        driver.checkpoint_app(other)
        st = driver.runtimes[other].store
        base1 = st.base
        assert base1 > 0, "compaction did not advance the store base"

        # grow history ~3x past the checkpoint, checkpoint again: the
        # retained suffix (len - base) stays bounded by the inter-
        # checkpoint window, NOT total history
        write_wave("b", 120)
        driver.checkpoint_app(other)
        base2 = st.base
        assert base2 > base1
        retained2 = len(st) - base2

        write_wave("c", 120)
        driver.checkpoint_app(other)
        base3 = st.base
        retained3 = len(st) - base3
        assert retained3 <= retained2 + 8, (
            "retained suffix grew with history: %d -> %d"
            % (retained2, retained3))

        # rejoin: kill the victim's app, rebuild it FRESH from the
        # compacted donor — transfer is checkpoint + suffix, and the
        # rebuilt app must hold the ENTIRE state (incl. wave a, which
        # exists only inside the checkpoint now)
        apps[victim].kill()
        apps[victim].wait()
        apps[victim] = spawn_app(tmp_path, victim, PORTS[victim])
        time.sleep(0.3)
        donor_retained = len(st) - st.base   # may have grown by a late
        driver.recover_replica(victim, donor=other)   # CLOSE event etc.
        vst = driver.runtimes[victim].store
        assert vst.base == base3, "victim did not inherit the compaction"
        assert len(vst) - vst.base <= donor_retained + 4, (
            "rejoin transferred more than the retained suffix")
        cv = Client(PORTS[victim])
        assert cv.cmd("GET a0") == b"v0"          # from the checkpoint
        assert cv.cmd("GET c119") == b"v119"      # from the suffix
        cv.close()

        # and the rebuilt replica still tracks NEW replicated writes
        # (the in-loop recovery stalls heartbeats long enough that a
        # re-election may have happened: find the CURRENT leader, retry
        # once across a possible late change)
        deadline = time.time() + 30
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        for _ in range(40):
            nl = driver.leader()
            try:
                c = Client(PORTS[nl])
                if c.cmd("SET after rejoin") == b"+OK":
                    c.close()
                    break
                c.close()
            except OSError:
                pass
            time.sleep(0.25)
        else:
            raise AssertionError("no leader accepted the post-rejoin write")
        assert wait_kv(PORTS[victim], "after", b"rejoin") == b"rejoin"
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()


def test_checkpoint_quiesce_fallback_without_probe(tmp_path):
    """A 2-tuple app_snapshot hook (no probe_fn) must still checkpoint
    correctly through the kernel-queue quiescence fallback: the
    compacted prefix has to cover exactly what the app consumed."""
    apps, driver = [], None
    ports = [7451, 7452, 7453]
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=ports,
            # wide timeouts: no mid-test election is intended, and a
            # slow host's long driver iteration must not trigger a
            # spurious deposition that severs the drill's sessions
            timeout_cfg=TimeoutConfig(elec_timeout_low=2.0,
                                      elec_timeout_high=4.0),
            app_snapshot=(toy_dump, toy_restore))   # NO probe
        for r, port in enumerate(ports):
            apps.append(spawn_app(tmp_path, r, port))
        time.sleep(0.3)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        lead = driver.leader()
        assert lead >= 0
        fol = next(r for r in range(3) if r != lead)

        c = Client(ports[lead])
        for i in range(80):
            assert c.cmd(f"SET q{i} v{i}") == b"+OK"
        c.close()
        assert wait_kv(ports[fol], "q79", b"v79") == b"v79"

        driver.checkpoint_app(fol)
        st = driver.runtimes[fol].store
        assert st.base > 0, "compaction did not advance"

        # the checkpoint must cover the compacted prefix: rebuild the
        # app FRESH from checkpoint + suffix and verify full state
        apps[fol].kill()
        apps[fol].wait()
        apps[fol] = spawn_app(tmp_path, fol, ports[fol])
        time.sleep(0.3)
        driver.reset_app(fol)
        cv = Client(ports[fol])
        assert cv.cmd("GET q0") == b"v0"      # from the checkpoint
        assert cv.cmd("GET q79") == b"v79"
        cv.close()
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()
