"""The unmodified-application proof on a REAL stock server: pristine
Redis 2.8.17 (the exact version the reference targets, ``apps/redis/mk``)
built from the vendored upstream tarball, run under
``LD_PRELOAD=interpose.so`` with zero modifications, replicated by the
TPU-native consensus core — the reference's headline scenario
(``benchmarks/run.sh --app=redis``, ``run.sh:24-37,73-82``).

The Redis build happens at test time from the reference tree's pristine
tarball (no vendored third-party code in this repo); the test skips if
the tarball or toolchain is unavailable."""

import os
import socket
import subprocess
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

CFG = LogConfig(n_slots=512, slot_bytes=256, window_slots=64,
                batch_slots=32)
_BASE = 9600 + (os.getpid() % 200)
PORTS = [_BASE, _BASE + 200, _BASE + 400]


@pytest.fixture(scope="module")
def redis_server():
    # single build recipe shared with benchmarks/redis_bench.py
    from benchmarks.redis_bench import ensure_redis
    try:
        server = ensure_redis()
    except (FileNotFoundError, RuntimeError,
            subprocess.SubprocessError) as e:
        pytest.skip(str(e))
    subprocess.run(["make", "-C", NATIVE], check=True,
                   capture_output=True)
    return server


class Resp:
    """Minimal client speaking Redis's inline protocol."""

    def __init__(self, port, timeout=15):
        self.s = socket.create_connection(("127.0.0.1", port),
                                          timeout=timeout)
        self.f = self.s.makefile("rb")

    def cmd(self, line: bytes) -> bytes:
        self.s.sendall(line + b"\r\n")
        head = self.f.readline().strip()
        if head.startswith(b"$"):            # bulk reply
            n = int(head[1:])
            if n < 0:
                return None
            body = self.f.read(n + 2)[:n]
            return body
        return head

    def close(self):
        try:
            self.s.close()
        except OSError:
            pass


@pytest.fixture()
def stack(tmp_path, redis_server):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            timeout_cfg=TimeoutConfig(elec_timeout_low=0.3,
                                      elec_timeout_high=0.6))
        for r, port in enumerate(PORTS):
            env = dict(os.environ)
            env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
            env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path),
                                                f"proxy{r}.sock")
            apps.append(subprocess.Popen(
                [redis_server, "--port", str(port),
                 "--bind", "127.0.0.1", "--save", "",
                 "--appendonly", "no", "--databases", "1"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        deadline = time.time() + 30
        for port in PORTS:                   # wait for redis to accept
            while True:
                try:
                    socket.create_connection(("127.0.0.1", port),
                                             timeout=2).close()
                    break
                except OSError:
                    assert time.time() < deadline, "redis did not start"
                    time.sleep(0.1)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.leader() >= 0, "no leader elected"
        yield driver
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()


def wait_get(port, key, want, timeout=20.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            c = Resp(port)
            last = c.cmd(b"GET " + key)
            c.close()
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.2)
    return last


def test_real_redis_replicates_writes(stack):
    lead = stack.leader()
    c = Resp(PORTS[lead])
    assert c.cmd(b"SET apus real-redis") == b"+OK"
    assert c.cmd(b"GET apus") == b"real-redis"
    c.close()
    for r in range(3):
        if r != lead:
            assert wait_get(PORTS[r], b"apus", b"real-redis") == \
                b"real-redis", f"follower {r} (redis) missed the write"


def test_real_redis_bulk_state_equality(stack):
    lead = stack.leader()
    n = 100
    c = Resp(PORTS[lead])
    for i in range(n):
        assert c.cmd(b"SET k%03d v%03d" % (i, i)) == b"+OK"
    c.close()
    fol = next(r for r in range(3) if r != lead)
    # spot-check head/middle/tail, then full count
    for i in (0, n // 2, n - 1):
        assert wait_get(PORTS[fol], b"k%03d" % i, b"v%03d" % i) == \
            b"v%03d" % i
    deadline = time.time() + 20
    size = None
    while time.time() < deadline:
        c = Resp(PORTS[fol])
        size = c.cmd(b"DBSIZE")
        c.close()
        if size == b":%d" % n:
            break
        time.sleep(0.3)
    assert size == b":%d" % n, size


def test_real_redis_incr_is_not_double_applied(stack):
    """INCR is the canonical non-idempotent op: state equality on the
    follower proves the byte stream replays exactly once, in order."""
    lead = stack.leader()
    c = Resp(PORTS[lead])
    for _ in range(7):
        c.cmd(b"INCR ctr")
    assert c.cmd(b"GET ctr") == b"7"
    c.close()
    fol = next(r for r in range(3) if r != lead)
    assert wait_get(PORTS[fol], b"ctr", b"7") == b"7"


def test_real_redis_leader_failover(stack):
    """The reconf_bench.sh RemoveLeader scenario on the real app: the
    leader is partitioned away mid-service, a follower takes over,
    clients continue against the new leader, and on heal the deposed
    leader's Redis catches up to the exact same state (its uncommitted
    reads were severed, never applied)."""
    lead = stack.leader()
    c = Resp(PORTS[lead])
    assert c.cmd(b"SET before failover") == b"+OK"
    c.close()
    for r in range(3):
        if r != lead:
            assert wait_get(PORTS[r], b"before", b"failover") == \
                b"failover"

    # partition the leader's replica (the kill -9 analog: its app is
    # still up but its consensus half cannot reach a quorum)
    others = [r for r in range(3) if r != lead]
    stack.cluster.partition([[lead], others])
    deadline = time.time() + 30
    while stack.leader() in (lead, -1):
        assert time.time() < deadline, "no failover"
        time.sleep(0.05)
    lead2 = stack.leader()
    assert lead2 != lead

    # service continues against the new leader
    c = Resp(PORTS[lead2])
    assert c.cmd(b"SET during outage") == b"+OK"
    c.close()
    other = next(r for r in others if r != lead2)
    assert wait_get(PORTS[other], b"during", b"outage") == b"outage"

    # heal: the deposed leader's app catches up via replay
    stack.cluster.heal()
    assert wait_get(PORTS[lead], b"during", b"outage") == b"outage", \
        "deposed leader's redis did not catch up after heal"

    # and the whole group keeps replicating new writes
    lead3 = stack.leader()
    c = Resp(PORTS[lead3])
    assert c.cmd(b"SET after heal") == b"+OK"
    c.close()
    for r in range(3):
        if r != lead3:
            assert wait_get(PORTS[r], b"after", b"heal") == b"heal"
