"""Cluster observability subsystem (rdma_paxos_tpu.obs): metrics
registry, protocol trace ring, health snapshots — unit level — plus the
driver/sim integration contracts:

* an elected cluster serving commits produces role/term gauges, a
  nonzero commit-latency histogram, schema-complete health snapshot
  files, and election/enqueue/ack trace events;
* a deliberate rebase-stall scenario shows ``rebase_stalled > 0`` and a
  matching trace event (ADVICE.md #3);
* instrumentation is host-side only — compiled-step cache keys are
  unchanged with observability attached;
* ``stop()`` with a wedged poll thread fails inflight waiters fast
  (ADVICE.md #4); ``quiesce()`` treats unverifiable kernel queues as
  unknown, never as empty (ADVICE.md #2); the rebase-threshold
  headroom accounts for fused bursts (ADVICE.md #5).
"""

import json
import os
import socket
import threading
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, MAX_BURST_K, TimeoutConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs import Observability, trace as obs_trace
from rdma_paxos_tpu.obs.health import (
    HealthReporter, make_snapshot, validate)
from rdma_paxos_tpu.obs.metrics import MetricsRegistry, default_registry
from rdma_paxos_tpu.obs.trace import TraceRing, default_ring
from rdma_paxos_tpu.proxy.proxy import PendingEvent, ReplayEngine
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import SimCluster
from rdma_paxos_tpu.utils.debug import ReplicaLog, StepTimer

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)  # manual


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_and_gauge_basics():
    reg = MetricsRegistry()
    reg.inc("ops_total", replica=0)
    reg.inc("ops_total", 4, replica=0)
    reg.inc("ops_total", replica=1)
    reg.set("role", 2, replica=0)
    reg.set("role", 1, replica=0)           # gauges overwrite
    assert reg.get("ops_total", replica=0) == 5
    assert reg.get("ops_total", replica=1) == 1
    assert reg.get("ops_total", replica=2) == 0
    assert reg.get("role", replica=0) == 1


def test_counter_concurrency_is_exact():
    reg = MetricsRegistry()
    N, T = 2000, 8

    def work():
        for _ in range(N):
            reg.inc("c_total", replica=1)
            reg.observe("h", 1.0, buckets=(10.0,), replica=1)

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.get("c_total", replica=1) == N * T
    assert reg.get("h", replica=1)["count"] == N * T


def test_histogram_fixed_buckets():
    reg = MetricsRegistry()
    bounds = (10.0, 20.0, 30.0)
    for v in (5, 10, 15, 25, 100):
        reg.observe("lat", v, buckets=bounds)
    h = reg.get("lat")
    # le semantics: a value equal to a bound lands in that bound
    assert h["buckets"]["10.0"] == 2          # 5, 10
    assert h["buckets"]["20.0"] == 1          # 15
    assert h["buckets"]["30.0"] == 1          # 25
    assert h["buckets"]["+Inf"] == 1          # 100 (overflow)
    assert h["count"] == 5
    assert h["sum"] == 155
    assert h["min"] == 5 and h["max"] == 100


def test_snapshot_json_round_trip(tmp_path):
    reg = MetricsRegistry()
    reg.inc("a_total", 3, replica=0)
    reg.set("g", 7.5)
    reg.observe("h", 0.5, buckets=(1.0, 2.0), replica=2)
    snap = reg.snapshot()
    # label rendering is deterministic
    assert snap["counters"]["a_total{replica=0}"] == 3
    assert snap["gauges"]["g"] == 7.5
    assert snap["histograms"]["h{replica=2}"]["count"] == 1
    # JSON round trip is lossless
    assert json.loads(reg.to_json()) == snap
    path = str(tmp_path / "metrics.json")
    reg.write_json(path)
    assert json.load(open(path)) == snap
    reg.reset()
    assert reg.snapshot()["counters"] == {}


# ---------------------------------------------------------------------------
# trace ring
# ---------------------------------------------------------------------------

def test_trace_ring_bounded_and_ordered():
    ring = TraceRing(capacity=8)
    for i in range(20):
        ring.record("tick", replica=i % 3, i=i)
    evs = ring.events()
    assert len(evs) == 8 and len(ring) == 8
    # oldest dropped, retained suffix exact and in order
    assert [e.fields["i"] for e in evs] == list(range(12, 20))
    assert [e.seq for e in evs] == sorted(e.seq for e in evs)
    assert all(evs[i].ts <= evs[i + 1].ts for i in range(len(evs) - 1))
    # filtering by kind and replica
    ring.record("other", replica=1, i=99)
    assert [e.fields["i"] for e in ring.events(kind="other")] == [99]
    assert all(e.replica == 1 for e in ring.events(replica=1))


def test_trace_dump_on_failure(tmp_path):
    ring = TraceRing(capacity=16)
    ring.record("election_win", replica=0, term=3)
    ring.record("commit_advance", replica=0, delta=5)
    path = ring.dump_on_failure(str(tmp_path / "dump.json"),
                                reason="injected failure")
    data = json.load(open(path))
    assert data["reason"] == "injected failure"
    kinds = [e["kind"] for e in data["events"]]
    assert kinds == ["election_win", "commit_advance"]
    assert data["events"][0]["term"] == 3
    ring.clear()
    assert len(ring) == 0


# ---------------------------------------------------------------------------
# health reporter
# ---------------------------------------------------------------------------

def test_health_reporter_write_read_cadence(tmp_path):
    clock = [0.0]
    rep = HealthReporter(str(tmp_path), period=5.0,
                         clock=lambda: clock[0])
    assert rep.due()                       # never written -> due
    snap = make_snapshot(replica=0, role=int(Role.LEADER), term=2,
                         leader_id=0, commit=10, apply=10, end=12,
                         head=0, log_headroom=1000, inflight=1)
    assert rep.maybe_write({0: snap})
    assert not rep.due()
    clock[0] = 6.0
    assert rep.due()
    back = rep.read(0)
    assert validate(back) == []
    assert back["commit"] == 10 and back["role"] == int(Role.LEADER)
    assert rep.read(1) is None
    assert rep.read_all(2) == [back, None]


def test_health_validate_flags_missing_fields():
    assert "commit" in validate({"replica": 0})


# ---------------------------------------------------------------------------
# debug.py routing (grep contract preserved, structured twin added)
# ---------------------------------------------------------------------------

def test_replica_log_routes_through_obs(tmp_path):
    obs = Observability()
    log = ReplicaLog(str(tmp_path / "r0.log"), replica=0, obs=obs)
    log.leader_elected(7)
    log.info_wtime("protocol event")
    log.close()
    text = open(str(tmp_path / "r0.log")).read()
    assert "[T7] LEADER" in text           # the run.sh grep contract
    assert obs.metrics.get("elections_won_total", replica=0) == 1
    wins = obs.trace.events(kind=obs_trace.ELECTION_WIN)
    assert wins and wins[0].fields["term"] == 7
    lines = obs.trace.events(kind=obs_trace.LOG_LINE)
    assert any(e.fields["msg"] == "protocol event" for e in lines)


def test_step_timer_routes_to_registry():
    reg = MetricsRegistry()
    t = StepTimer(metrics=reg, replica=2)
    t.start("fetch")
    t.stop("fetch")
    h = reg.get("timer_fetch_us", replica=2)
    assert h["count"] == 1 and h["sum"] > 0
    assert "fetch" in t.report()           # legacy surface preserved


# ---------------------------------------------------------------------------
# satellite: burst-aware rebase-threshold headroom (ADVICE.md #5)
# ---------------------------------------------------------------------------

def test_rebase_threshold_headroom_accounts_for_bursts():
    ns = 1024
    limit = (1 << 31) - 1 - (MAX_BURST_K + 2) * ns
    LogConfig(n_slots=ns, rebase_threshold=limit)       # at the bound
    with pytest.raises(ValueError, match="headroom"):
        LogConfig(n_slots=ns, rebase_threshold=limit + 1)


# ---------------------------------------------------------------------------
# satellite: quiesce unknown-vs-empty (ADVICE.md #2)
# ---------------------------------------------------------------------------

def _engine_with_live_conn():
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    eng = ReplayEngine("127.0.0.1", srv.getsockname()[1])
    eng.apply(int(EntryType.CONNECT), 1, b"")
    peer, _ = srv.accept()
    return eng, srv, peer


def test_quiesce_ioctl_failure_without_peer_rows_is_unknown(
        monkeypatch, tmp_path):
    """TIOCOUTQ unverifiable AND no visible peer row: nothing proves
    the bytes were consumed — must be unknown (False), never empty."""
    eng, srv, peer = _engine_with_live_conn()
    try:
        import fcntl

        def boom(*a, **k):
            raise OSError("TIOCOUTQ unsupported")
        monkeypatch.setattr(fcntl, "ioctl", boom)
        # a READABLE proc table with no matching rows (header only)
        fake = tmp_path / "proc_tcp"
        fake.write_text("  sl  local_address rem_address   st tx_queue "
                        "rx_queue tr tm->when retrnsmt uid\n")
        monkeypatch.setattr(ReplayEngine, "_PROC_TCP_PATHS",
                            (str(fake),))
        before = default_registry().get("quiesce_unknown_total")
        t0 = time.monotonic()
        assert eng.quiesce(timeout=5.0) is False
        assert time.monotonic() - t0 < 1.0     # immediate, not timeout
        assert default_registry().get("quiesce_unknown_total") > before
        assert default_ring().events(kind=obs_trace.QUIESCE_UNKNOWN)
    finally:
        eng.close()
        peer.close()
        srv.close()


@pytest.mark.skipif(not os.path.exists("/proc/net/tcp"),
                    reason="needs a readable /proc/net/tcp")
def test_quiesce_ioctl_failure_degrades_to_verified_peer_rx(
        monkeypatch):
    """TIOCOUTQ unverifiable but every replay port's peer row is
    visible with an empty rx queue: the degraded barrier verifies via
    the app side (and records the degradation)."""
    eng, srv, peer = _engine_with_live_conn()
    try:
        import fcntl

        def boom(*a, **k):
            raise OSError("TIOCOUTQ unsupported")
        monkeypatch.setattr(fcntl, "ioctl", boom)
        before = default_registry().get("quiesce_unknown_total")
        assert eng.quiesce(timeout=5.0) is True
        # no unknown event: the peer-rx check verified every socket
        assert default_registry().get("quiesce_unknown_total") == before
    finally:
        eng.close()
        peer.close()
        srv.close()


def test_quiesce_unreadable_proc_is_unknown_not_empty(monkeypatch):
    eng, srv, peer = _engine_with_live_conn()
    try:
        monkeypatch.setattr(ReplayEngine, "_PROC_TCP_PATHS",
                            ("/nonexistent/proc-net-tcp",))
        t0 = time.monotonic()
        assert eng.quiesce(timeout=5.0) is False
        assert time.monotonic() - t0 < 1.0
    finally:
        eng.close()
        peer.close()
        srv.close()


@pytest.mark.skipif(not os.path.exists("/proc/net/tcp"),
                    reason="needs a readable /proc/net/tcp")
def test_quiesce_verified_empty_is_true():
    eng, srv, peer = _engine_with_live_conn()
    try:
        assert eng.quiesce(timeout=5.0) is True
    finally:
        eng.close()
        peer.close()
        srv.close()


# ---------------------------------------------------------------------------
# satellite: stop() with a wedged poll thread (ADVICE.md #4)
# ---------------------------------------------------------------------------

def test_stop_releases_inflight_when_poll_thread_wedged():
    d = ClusterDriver(CFG, 3, timeout_cfg=TO)
    d.cluster.run_until_elected(0)
    d.step()
    handler = d._make_handler(0)
    conn = (0 << 24) | 1
    ev = handler(int(EntryType.CONNECT), conn, b"")
    assert isinstance(ev, PendingEvent) and not ev.done.is_set()
    # a poll thread that ignores the stop flag (e.g. blocked inside a
    # device step): stop() must fail the waiter fast, not hang it
    wedge = threading.Thread(target=lambda: time.sleep(3.0), daemon=True)
    wedge.start()
    d._thread = wedge
    t0 = time.monotonic()
    d.stop(join_timeout=0.2)
    assert time.monotonic() - t0 < 2.0
    assert ev.done.is_set() and ev.status == -1
    assert d.obs.trace.events(kind=obs_trace.STOP_FORCED)
    assert d.obs.metrics.get("inflight_failed_total", replica=0) >= 1
    # events arriving after the forced stop are refused immediately
    assert handler(int(EntryType.SEND), conn, b"late") == -1
    wedge.join()
    d._thread = None
    d.stop()                               # retry completes the close


# ---------------------------------------------------------------------------
# satellite: rebase-stall surfacing (ADVICE.md #3) — the subsystem's
# first real consumer
# ---------------------------------------------------------------------------

def test_rebase_stall_counter_and_trace():
    cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16,
                    batch_slots=8, rebase_threshold=128)
    c = SimCluster(cfg, 3)
    obs = Observability()
    c.obs = obs
    c.run_until_elected(0)
    # a heard-but-permanently-lagging row: partition replica 2 away; its
    # head stays pinned near 0 while forced pruning lets the majority's
    # end march past the threshold — min head rounds the delta to 0
    # forever, so the rollover can never fire
    c.partition([[0, 1], [2]])
    for i in range(400):
        c.submit(0, b"w%04d" % i)
        c.step()
        if int(c.last["end"].max()) >= cfg.rebase_threshold:
            break
    assert int(c.last["end"].max()) >= cfg.rebase_threshold, \
        "traffic never crossed the threshold"
    for _ in range(c.REBASE_STALL_STEPS + 5):
        c.step()
    assert c.rebases == 0                  # the rollover really is stuck
    assert c.rebase_stalled > 0
    assert obs.metrics.get("rebase_stalled") > 0
    evs = obs.trace.events(kind=obs_trace.REBASE_STALLED)
    assert evs, "stall produced no trace event"
    assert evs[0].fields["threshold"] == cfg.rebase_threshold
    assert evs[0].fields["min_head"] < cfg.n_slots
    # snapshot-recovering the laggard unpins the min head and the
    # stalled rollover finally fires — stall detection re-arms
    from rdma_paxos_tpu.consensus.snapshot import (
        install_snapshot, take_snapshot)
    snap = take_snapshot(c.state, donor=1, index=int(c.applied[1]))
    c.state = install_snapshot(c.state, 2, snap)
    c.applied[2] = snap.index
    c.replayed[2] = list(c.replayed[1][:])
    c.heal()
    for _ in range(80):
        c.step()
        if c.rebases:
            break
    assert c.rebases >= 1
    assert c.rebase_stall_steps == 0
    assert obs.trace.events(kind=obs_trace.REBASE_APPLIED)
    # the snapshot instrumentation (host wrappers, global obs) saw it
    assert default_ring().events(kind=obs_trace.SNAPSHOT_TAKEN)
    assert default_ring().events(kind=obs_trace.SNAPSHOT_INSTALLED)
    assert default_registry().get("snapshots_installed_total") >= 1


# ---------------------------------------------------------------------------
# integration: election + commits -> gauges, commit-latency histogram,
# health snapshots, trace events
# ---------------------------------------------------------------------------

def _step_until(d, pred, n=200):
    for _ in range(n):
        d.step()
        if pred():
            return True
    return False


def test_driver_election_commit_latency_and_health(tmp_path):
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, workdir=str(tmp_path),
                      health_period=0.0)
    try:
        d.runtimes[0].timer._deadline = 0.0    # expire replica 0's timer
        d.step()                               # election via the driver
        assert d.leader() == 0
        handler = d._make_handler(0)
        conn = (0 << 24) | 1
        ev1 = handler(int(EntryType.CONNECT), conn, b"")
        ev2 = handler(int(EntryType.SEND), conn, b"SET k v\n")
        assert _step_until(d, lambda: ev2.done.is_set())
        assert ev1.status == 0 and ev2.status == 0

        m = d.obs.metrics
        # per-replica role/term gauges
        assert m.get("replica_role", replica=0) == int(Role.LEADER)
        assert m.get("replica_role", replica=1) != int(Role.LEADER)
        assert m.get("replica_term", replica=0) >= 1
        # rebase-headroom gauge tracks the i32 ceiling margin
        head = m.get("rebase_headroom", replica=0)
        assert head == CFG.rebase_threshold - int(d.cluster.last["end"][0])
        # nonzero commit-latency histogram with bucketed counts
        hist = m.get("commit_latency_seconds", replica=0)
        assert hist["count"] >= 2
        assert sum(hist["buckets"].values()) == hist["count"]
        assert m.get("committed_entries_total", replica=0) >= 2
        assert m.get("proxy_events_total", replica=0) == 2

        # trace: election start+win, proxy enqueue, ack release
        for kind in (obs_trace.ELECTION_START, obs_trace.ELECTION_WIN,
                     obs_trace.PROXY_ENQUEUE,
                     obs_trace.PROXY_ACK_RELEASE,
                     obs_trace.COMMIT_ADVANCE):
            assert d.obs.trace.events(kind=kind), f"missing {kind}"

        # health snapshot files: schema-complete, per replica, atomic
        for r in range(3):
            snap = json.load(open(
                os.path.join(str(tmp_path), f"replica{r}.health.json")))
            assert validate(snap) == [], snap
            assert snap["replica"] == r
            assert snap["log_headroom"] > 0
            assert snap["store"]["records"] >= 0
        lead_snap = json.load(open(
            os.path.join(str(tmp_path), "replica0.health.json")))
        assert lead_snap["role"] == int(Role.LEADER)
        assert lead_snap["commit"] >= 2

        # live aggregation
        agg = d.health()
        assert agg["leader"] == 0 and len(agg["replicas"]) == 3
        assert agg["replicas"][0]["term"] == lead_snap["term"]

        # combined snapshot is JSON-serializable as-is
        json.dumps(d.obs.snapshot())
    finally:
        d.stop()
    # the greppable LEADER line survived the routing (run.sh contract)
    text = open(os.path.join(str(tmp_path), "replica0.log")).read()
    assert "] LEADER" in text


# ---------------------------------------------------------------------------
# jit-safety: instrumentation is host-side only — compiled-step cache
# keys are unchanged with observability attached
# ---------------------------------------------------------------------------

def test_compiled_step_cache_keys_unchanged_by_instrumentation():
    cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16,
                    batch_slots=8)
    bare = SimCluster(cfg, 3)
    bare.run_until_elected(0)
    bare.submit(0, b"x")
    bare.step()
    keys_before = set(SimCluster._STEP_CACHE)

    instrumented = SimCluster(cfg, 3)
    instrumented.obs = Observability()
    instrumented.run_until_elected(0)
    instrumented.submit(0, b"y")
    instrumented.step()
    d = ClusterDriver(cfg, 3, timeout_cfg=TO)   # driver attaches obs
    d.cluster.run_until_elected(0)
    d.cluster.submit(0, b"z")
    d.step()
    d.stop()
    assert set(SimCluster._STEP_CACHE) == keys_before, (
        "observability changed the compiled-step cache keys — "
        "instrumentation leaked into jitted code")
