"""Speculative execution + output commit (the shim's default discipline).

The reference blocks the app thread inside ``read()`` until the event is
committed (proxy.c:160) — fine at µs commit latency, but at a host-loop's
ms-scale latency it caps a single-threaded app at one read-buffer per
commit RTT. The TPU-native redesign (``native/interpose.cpp``): reads are
forwarded asynchronously and the app executes immediately; its REPLIES are
held until the commit frontier covers every input forwarded before the
reply was produced. Externally the contract is unchanged — a client that
holds a reply knows its request committed.

These tests pin the two sides of that contract:

* the happy path — replies only ever reflect committed input (follower
  state equality, exactly-once), at full pipeline depth;
* mis-speculation — a deposed leader whose app consumed input that never
  committed is QUARANTINED (``app_dirty``): its clients are severed, new
  sessions are refused, and ``ClusterDriver.reset_app`` rebuilds the
  restarted app from the committed store, after which the diverged write
  is provably gone.
"""

import os
import socket
import subprocess
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

CFG = LogConfig(n_slots=256, slot_bytes=128, window_slots=32, batch_slots=16)
PORTS = [7361, 7362, 7363]


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


def spawn_app(tmp_path, r, port):
    env = dict(os.environ)
    env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
    env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path), f"proxy{r}.sock")
    env.pop("RP_SPEC", None)          # default = speculative
    return subprocess.Popen([os.path.join(NATIVE, "toyserver"), str(port)],
                            env=env, stderr=subprocess.DEVNULL)


class Client:
    def __init__(self, port):
        self.s = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.f = self.s.makefile("rb")

    def cmd(self, line: str) -> bytes:
        self.s.sendall(line.encode() + b"\n")
        return self.f.readline().strip()

    def send_only(self, line: str) -> None:
        self.s.sendall(line.encode() + b"\n")

    def close(self):
        try:
            self.s.close()
        except OSError:
            pass


@pytest.fixture()
def stack(tmp_path):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            timeout_cfg=TimeoutConfig(elec_timeout_low=0.3,
                                      elec_timeout_high=0.6))
        for r, port in enumerate(PORTS):
            apps.append(spawn_app(tmp_path, r, port))
        time.sleep(0.3)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.leader() >= 0, "no leader elected"
        yield driver, apps, tmp_path
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()


def wait_kv(port, key, want, timeout=15.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            c = Client(port)
            last = c.cmd(f"GET {key}")
            c.close()
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.1)
    return last


def test_spec_mode_declared_and_replies_imply_commit(stack):
    driver, _apps, _tmp = stack
    lead = driver.leader()
    c = Client(PORTS[lead])
    # a deep pipeline of writes — the app executes speculatively, but
    # every reply we READ is an output-commit guarantee
    for i in range(40):
        assert c.cmd(f"SET k{i} v{i}") == b"+OK"
    c.close()
    # the shim declared itself speculative via HELLO
    assert driver.runtimes[lead].proxy.spec_mode
    # reply received => committed => must reach every follower
    for r in range(3):
        if r == lead:
            continue
        assert wait_kv(PORTS[r], "k39", b"v39") == b"v39", f"replica {r}"


def test_misspeculation_quarantine_and_reset(stack):
    driver, apps, tmp_path = stack
    lead = driver.leader()

    c = Client(PORTS[lead])
    assert c.cmd("SET committed yes") == b"+OK"
    for r in range(3):
        assert wait_kv(PORTS[r], "committed", b"yes") == b"yes"

    # isolate the leader, then feed it input that can never commit; the
    # speculative app EXECUTES it (that is the point of speculation)
    driver.cluster.partition([[lead], [r for r in range(3) if r != lead]])
    c.send_only("SET poison bad")

    # the majority side elects a new leader
    deadline = time.time() + 60
    while time.time() < deadline:
        nl = driver.leader()
        if nl >= 0 and nl != lead:
            break
        time.sleep(0.05)
    assert driver.leader() != lead, "no failover"

    # heal: the old leader hears the higher term, steps down, and its
    # un-committable inflight input marks the app dirty
    driver.cluster.heal()
    deadline = time.time() + 30
    while time.time() < deadline:
        if driver.runtimes[lead].app_dirty:
            break
        time.sleep(0.05)
    assert driver.runtimes[lead].app_dirty, "mis-speculation not flagged"

    # the poisoned client was severed (held reply dropped, never sent)
    c.s.settimeout(5)
    try:
        data = c.s.recv(64)
    except OSError:
        data = b""
    assert data == b"", "client of a mis-speculated event must be severed"
    c.close()

    # a dirty app refuses NEW sessions too (no stale/diverged reads)
    s = socket.create_connection(("127.0.0.1", PORTS[lead]), timeout=5)
    s.settimeout(5)
    try:
        s.sendall(b"GET committed\n")
        refused = s.recv(64) == b""
    except OSError:
        refused = True
    s.close()
    assert refused, "dirty app served a session"

    # operator path: restart the app fresh, rebuild from committed store
    apps[lead].kill()
    apps[lead].wait()
    apps[lead] = spawn_app(tmp_path, lead, PORTS[lead])
    time.sleep(0.3)
    driver.reset_app(lead)
    assert not driver.runtimes[lead].app_dirty

    # committed state survived; the diverged write is GONE
    assert wait_kv(PORTS[lead], "committed", b"yes") == b"yes"
    cchk = Client(PORTS[lead])
    assert cchk.cmd("GET poison") == b"-"
    cchk.close()

    # and the reset app resumes live replication from the new leader
    nl = driver.leader()
    cw = Client(PORTS[nl])
    assert cw.cmd("SET after reset-ok") == b"+OK"
    cw.close()
    assert wait_kv(PORTS[lead], "after", b"reset-ok") == b"reset-ok"


def test_refused_send_at_intake_quarantines_spec_app(stack):
    """A deposed leader with NO in-flight events is clean — but a
    surviving pre-deposition session that sends AFTER deposition has its
    bytes executed by the speculative app before intake refuses them
    (-1). That refusal must quarantine the app exactly like failing
    in-flight events does: otherwise the diverged app keeps serving
    stale local reads and serves clients again on re-election."""
    driver, _apps, _tmp = stack
    lead = driver.leader()

    c = Client(PORTS[lead])
    assert c.cmd("SET durable yes") == b"+OK"     # commits; inflight drains

    # depose the leader: partition it away, let the majority elect, heal
    driver.cluster.partition([[lead], [r for r in range(3) if r != lead]])
    deadline = time.time() + 60
    while time.time() < deadline:
        nl = driver.leader()
        if nl >= 0 and nl != lead:
            break
        time.sleep(0.05)
    assert driver.leader() != lead, "no failover"
    driver.cluster.heal()
    time.sleep(0.3)   # a few poll iterations under the healed mesh
    # no in-flight input was lost, so deposition alone leaves it clean
    assert not driver.runtimes[lead].app_dirty

    # the surviving session sends: spec app consumes, intake refuses
    c.send_only("SET sneaky bad")
    deadline = time.time() + 30
    while time.time() < deadline:
        if driver.runtimes[lead].app_dirty:
            break
        time.sleep(0.05)
    assert driver.runtimes[lead].app_dirty, (
        "refused-at-intake speculated SEND did not quarantine the app")
    c.close()


def test_driver_death_severs_without_fabricated_acks(stack):
    """The shim's driver-death discipline: replies held for input the
    dead driver never committed must NOT be released (that would
    fabricate +OK acks for lost writes — the output-commit violation
    round 5 found and fixed), and the diverged speculative app must
    serve nothing — not even new sessions — until replaced."""
    driver, _apps, _tmp = stack
    lead = driver.leader()
    c = Client(PORTS[lead])
    assert c.cmd("SET alive yes") == b"+OK"

    # an uncommittable write in flight (driver dies before stepping it)
    c.send_only("SET phantom write")
    driver.stop()

    # the held reply must never arrive: sever, not ack
    c.s.settimeout(5)
    try:
        data = c.s.recv(64)
    except OSError:
        data = b""
    assert data == b"", (
        "client received bytes after driver death: %r" % data)
    c.close()

    # the diverged app refuses NEW sessions too (a refused connect is
    # the strongest form of that refusal)
    try:
        s = socket.create_connection(("127.0.0.1", PORTS[lead]),
                                     timeout=5)
        s.settimeout(5)
        s.sendall(b"GET alive\n")
        refused = s.recv(64) == b""
        s.close()
    except OSError:
        refused = True
    assert refused, "diverged app served a session after driver death"
