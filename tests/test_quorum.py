"""Quorum commit-scan tests: jnp reference vs Pallas (interpret mode on CPU)
against a hand-written NumPy oracle — covering the semantics of the
reference's commit scan (``dare_ibv_rc.c:1725-1758``) incl. dual-quorum
transitional configs (``:2799-2957``) and the current-term commit guard."""

import jax.numpy as jnp
import numpy as np
import pytest

from rdma_paxos_tpu.ops.quorum import R_PAD, commit_scan_pallas, commit_scan_ref

W = 16


def oracle(ends, commit, my_term, my_end, terms, bm_old, bm_new, transit,
           maj_old, maj_new):
    """Straight-line NumPy restatement of the committed-prefix rule."""
    best = commit
    for j in range(W):
        g = commit + j
        if g >= my_end:
            break
        cnt_new = sum(1 for r in range(R_PAD)
                      if (bm_new >> r) & 1 and ends[r] > g)
        cnt_old = sum(1 for r in range(R_PAD)
                      if (bm_old >> r) & 1 and ends[r] > g)
        if cnt_new < maj_new or (transit and cnt_old < maj_old):
            break
        if terms[j] == my_term:
            best = g + 1
    return best


def run_all(ends_list, commit, my_term, my_end, terms, bm_old=0b111,
            bm_new=0b111, transit=0, maj_old=2, maj_new=2):
    ends = np.zeros(R_PAD, np.int32)
    ends[:len(ends_list)] = ends_list
    args = (jnp.asarray(ends), jnp.asarray(commit, jnp.int32),
            jnp.asarray(my_term, jnp.int32), jnp.asarray(my_end, jnp.int32),
            jnp.asarray(terms, jnp.int32), jnp.asarray(bm_old, jnp.uint32),
            jnp.asarray(bm_new, jnp.uint32), jnp.asarray(transit, jnp.int32),
            jnp.asarray(maj_old, jnp.int32), jnp.asarray(maj_new, jnp.int32))
    ref = int(commit_scan_ref(*args))
    pal = int(commit_scan_pallas(*args, interpret=True))
    exp = oracle(ends, commit, my_term, my_end, list(terms), bm_old, bm_new,
                 transit, maj_old, maj_new)
    assert ref == pal == exp, (ref, pal, exp)
    return ref


def test_simple_majority_advance():
    terms = [3] * W
    assert run_all([5, 5, 2], 0, 3, 5, terms) == 5  # 2-of-3 acked 5


def test_monotone_no_regress():
    terms = [3] * W
    assert run_all([0, 0, 0], 4, 3, 10, terms) == 4  # nobody acked: stays


def test_minority_does_not_commit():
    terms = [3] * W
    assert run_all([7, 0, 0], 0, 3, 7, terms) == 0


def test_capped_by_leader_end():
    terms = [3] * W
    assert run_all([9, 9, 9], 0, 3, 6, terms) == 6


def test_term_guard_blocks_old_term_only_prefix():
    """Entries of an older term never commit by counting alone — only
    transitively below a current-term entry (why a fresh leader appends a
    NOOP, dare_server.c:1403-1491)."""
    terms = [2, 2, 2] + [0] * (W - 3)
    assert run_all([3, 3, 3], 0, 5, 3, terms) == 0
    terms = [2, 2, 5] + [0] * (W - 3)
    assert run_all([3, 3, 3], 0, 5, 3, terms) == 3  # term-5 entry commits all


def test_gap_in_acks_stops_scan():
    terms = [3] * W
    # majority acked 2, one acked 5 -> only 2 commit
    assert run_all([5, 2, 2], 0, 3, 5, terms) == 2


def test_dual_quorum_transitional():
    """Joint consensus: both old and new majorities required."""
    terms = [7] * W
    # old = {0,1,2}, new = {0,3,4}; transit=1
    # ends: 0 and 1 acked (old maj ok), but new has only replica 0 -> blocked
    assert run_all([4, 4, 0, 0, 0], 0, 7, 4, terms, bm_old=0b00111,
                   bm_new=0b11001, transit=1, maj_old=2, maj_new=2) == 0
    # now replica 3 acked too -> both quorums satisfied
    assert run_all([4, 4, 0, 4, 0], 0, 7, 4, terms, bm_old=0b00111,
                   bm_new=0b11001, transit=1, maj_old=2, maj_new=2) == 4


def test_nonzero_commit_start():
    terms = [4] * W
    assert run_all([8, 8, 3], 3, 4, 8, terms) == 8
