"""Multi-chip (group, replica) mesh engine (parallel/mesh.py:
build_mesh_2d + build_spmd_group_step/burst behind
``ShardedCluster(mesh=...)``): the acceptance properties of the
scale-out tentpole.

* the mesh engine at G=1, R=3 is BIT-IDENTICAL to ``SimCluster`` on a
  recorded workload — the 2-D layout is an execution engine, not a
  protocol fork;
* a G×R mesh cluster is BIT-IDENTICAL to the single-device ``vmap``
  ``ShardedCluster`` on a recorded workload with elections, traffic,
  ONE group-leader crash (partition + failover) and heal — step
  outputs, replay (ack) streams, and apply cursors all match, on both
  the step and the fused-burst drivers;
* exactly-one-compile: the mesh program's cache key carries the static
  device layout and deliberately NOT the group count — clusters of any
  G on one mesh share one compiled program per variant;
* a fast 2-device mesh smoke keeps the path alive in tier-1 on the
  CPU backend (conftest forces 8 virtual devices);
* mesh construction validates axis names / replica-axis width / group
  divisibility loudly;
* ``GroupStepTimer`` (per-group jittered step-domain election timers
  in the production sharded driver) is deterministic per (seed, group)
  — chaos replays redraw identical periods.
"""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.parallel.mesh import (
    GROUP_AXIS, REPLICA_AXIS, build_mesh_2d)
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.runtime.timers import GroupStepTimer
from rdma_paxos_tpu.shard import ShardedCluster

CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)

# every per-replica column of the step-output dict — the full visible
# protocol state (same key set test_shard pins for G=1 ≡ SimCluster)
STEP_KEYS = ("term", "role", "leader_id", "voted_term", "voted_for",
             "head", "apply", "commit", "end", "hb_seen",
             "became_leader", "acked", "accepted", "peer_acked",
             "leadership_verified", "rebase_delta")


# ---------------------------------------------------------------------------
# mesh construction / validation
# ---------------------------------------------------------------------------

def test_build_mesh_2d_shape_and_axis_names():
    m = build_mesh_2d(2, 3)
    assert m.axis_names == (GROUP_AXIS, REPLICA_AXIS)
    assert m.devices.shape == (2, 3)


def test_mesh_validation_is_loud():
    import jax
    with pytest.raises(ValueError, match="devices"):
        build_mesh_2d(8, 3)             # 24 > the 8 virtual devices
    # replica axis must be one chip per replica
    with pytest.raises(ValueError, match="replica axis"):
        ShardedCluster(CFG, 3, 2, mesh=(2, 2))
    # groups must divide evenly over the group shards
    with pytest.raises(ValueError, match="divide"):
        ShardedCluster(CFG, 2, 3, mesh=(2, 2))
    # axis names are part of the engine contract
    from jax.sharding import Mesh
    bad = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
    with pytest.raises(ValueError, match="mesh axes"):
        ShardedCluster(CFG, 2, 2, mesh=bad)


# ---------------------------------------------------------------------------
# bit-equivalence: mesh engine ≡ SimCluster at G=1, R=3
# ---------------------------------------------------------------------------

def _recorded_workload():
    """(events, timeouts) per step: election, traffic bursts, a
    partition with failover (the group-leader crash analog), heal,
    post-heal traffic — the test_shard recorded-workload shape."""
    steps = [([], [0])]
    for t in range(1, 30):
        ev = []
        tmo = []
        if t in (3, 4, 7, 12, 20):
            ev += [("sub", 0, b"p%d-%d" % (t, i)) for i in range(5)]
        if t == 9:
            ev.append(("part", [[0], [1, 2]]))
            tmo = [1]
        if t == 15:
            ev.append(("heal",))
        if t in (16, 21):
            ev += [("sub", 1, b"q%d-%d" % (t, i)) for i in range(3)]
        steps.append((ev, tmo))
    return steps


def test_mesh_g1_r3_bit_identical_to_simcluster():
    sim = SimCluster(CFG, 3)
    sh = ShardedCluster(CFG, 3, 1, mesh=(1, 3))
    for ev, tmo in _recorded_workload():
        for e in ev:
            if e[0] == "sub":
                sim.submit(e[1], e[2])
                sh.submit(0, e[1], e[2])
            elif e[0] == "part":
                sim.partition(e[1])
                sh.partition(0, e[1])
            else:
                sim.heal()
                sh.heal()
        a = sim.step(timeouts=tmo)
        b = sh.step(timeouts={0: tmo} if tmo else ())
        for k in STEP_KEYS:
            assert np.array_equal(a[k], np.asarray(b[k][0])), k
    assert sim.replayed == sh.replayed[0]
    assert (sim.applied == sh.applied[0]).all()
    assert sim.leader() == sh.leader(0)


# ---------------------------------------------------------------------------
# bit-equivalence: G×R mesh ≡ single-device vmap ShardedCluster
# ---------------------------------------------------------------------------

def _drive_pair(a: ShardedCluster, b: ShardedCluster, G: int, R: int,
                *, burst: bool) -> None:
    """Drive both clusters through the same recorded sharded workload
    — all-group elections, interleaved traffic, a crash of group 0's
    leader (partition away + failover to a new candidate), heal, and
    post-heal traffic — asserting bit-identical step outputs at every
    step and identical replay streams / apply cursors at the end."""
    def lockstep(timeouts=()):
        ra = a.step(timeouts=timeouts)
        rb = b.step(timeouts=timeouts)
        for k in STEP_KEYS:
            assert np.array_equal(np.asarray(ra[k]),
                                  np.asarray(rb[k])), k

    # round-robin elections, one dispatch per candidate round
    for g in range(G):
        for c in (a, b):
            c.run_until_elected(g, g % R)
    leaders = [a.leader(g) for g in range(G)]
    assert leaders == [b.leader(g) for g in range(G)]

    for t in range(10):
        g = t % G
        for c in (a, b):
            c.submit(g, leaders[g], b"w%d-%d" % (g, t))
        lockstep()

    # group 0 leader "crash": with R >= 3 the leader is partitioned
    # away and the majority side fails over; at R = 2 a minority can
    # never re-reach quorum, so the crash is a timeout-forced
    # deposition instead (higher-term candidate, old leader steps
    # down) — either way group 0 changes leader mid-run
    dead = leaders[0]
    cand = (dead + 1) % R
    if R >= 3:
        for c in (a, b):
            c.partition(0, [[dead],
                            [r for r in range(R) if r != dead]])
    for _ in range(3 * R):
        if a.last["role"][0][cand] == int(Role.LEADER):
            break
        lockstep(timeouts={0: [cand]})
    assert a.last["role"][0][cand] == int(Role.LEADER)
    assert b.last["role"][0][cand] == int(Role.LEADER)
    # other groups keep committing through the outage
    for t in range(4):
        for g in range(1, G):
            for c in (a, b):
                c.submit(g, leaders[g], b"o%d-%d" % (g, t))
        lockstep()
    for c in (a, b):
        if R >= 3:
            c.heal(0)
        c.submit(0, cand, b"after-failover")
    if burst:
        for c in (a, b):
            for i in range(3 * CFG.batch_slots):
                c.submit(0, cand, b"burst-%03d" % i)
        da, db = a.dispatches, b.dispatches
        ra = a.step_burst()
        rb = b.step_burst()
        assert a.dispatches == da + 1       # K steps, ONE mesh dispatch
        assert b.dispatches == db + 1
        for k in STEP_KEYS:
            assert np.array_equal(np.asarray(ra[k]),
                                  np.asarray(rb[k])), k
    for _ in range(5):
        lockstep()

    for g in range(G):
        assert a.replayed[g] == b.replayed[g], f"group {g} ack stream"
        assert (a.applied[g] == b.applied[g]).all()
    stream0 = [p for (_t, _c, _r, p) in a.replayed[0][cand]]
    assert b"after-failover" in stream0


def test_mesh_4x2_bit_identical_to_vmap_sharded():
    G, R = 4, 2
    a = ShardedCluster(CFG, R, G)                   # single-device vmap
    b = ShardedCluster(CFG, R, G, mesh=(G, R))      # 8-chip mesh
    _drive_pair(a, b, G, R, burst=False)


def test_mesh_2x4_burst_bit_identical_to_vmap_sharded():
    G, R = 2, 4
    a = ShardedCluster(CFG, R, G)
    b = ShardedCluster(CFG, R, G, mesh=(G, R))
    _drive_pair(a, b, G, R, burst=True)


# ---------------------------------------------------------------------------
# compile-cache: the mesh program's key excludes G
# ---------------------------------------------------------------------------

def test_mesh_single_compile_excludes_group_count():
    """Two mesh clusters on the SAME device mesh with DIFFERENT group
    counts share one compiled program: the cache key carries the
    static device layout, deliberately not G (the per-device program
    is polymorphic in the local group rows)."""
    cfg = LogConfig(n_slots=64, slot_bytes=64, window_slots=16,
                    batch_slots=8)
    before = set(STEP_CACHE)
    sc = ShardedCluster(cfg, 2, 2, mesh=(2, 2),
                        stable_fast_path=False)
    for g in range(2):
        sc.run_until_elected(g, g % 2)
        for i in range(4):
            sc.submit(g, sc.leader(g), b"v%d" % i)
    for _ in range(3):
        sc.step()
    assert all(sc.last["commit"][g].max() >= 4 for g in range(2))
    assert len(sc.programs_used) == 1, sc.programs_used
    added = set(STEP_CACHE) - before
    mesh_steps = [k for k in added if "spmd-group" in k]
    assert len(mesh_steps) == 1, mesh_steps
    # G=4 on the same mesh: ZERO new cache entries
    now = set(STEP_CACHE)
    sc2 = ShardedCluster(cfg, 2, 4, mesh=(2, 2),
                         stable_fast_path=False)
    for g in range(4):
        sc2.run_until_elected(g, g % 2)
    sc2.step()
    assert set(STEP_CACHE) == now
    # ...and the mesh key is DISJOINT from the single-device key: the
    # vmap engine on the same shapes compiles its own entry
    sc3 = ShardedCluster(cfg, 2, 2, stable_fast_path=False)
    sc3.step()
    assert any("sim" in k for k in set(STEP_CACHE) - now)


# ---------------------------------------------------------------------------
# fast 2-device smoke (tier-1 keeps the mesh path alive off-TPU)
# ---------------------------------------------------------------------------

def test_mesh_two_device_smoke():
    """Smallest real mesh — 1 group shard × 2 replica chips, G=2
    groups riding the shard — elects, commits, and bursts. Runs on the
    conftest-forced virtual CPU devices, so the shard_map path cannot
    silently rot when no TPU is attached."""
    cfg = LogConfig(n_slots=64, slot_bytes=64, window_slots=16,
                    batch_slots=8)
    sc = ShardedCluster(cfg, 2, 2, mesh=(1, 2))
    assert sc.mesh.devices.shape == (1, 2)
    for g in range(2):
        sc.run_until_elected(g, g % 2)
        for i in range(6):
            sc.submit(g, sc.leader(g), b"s%d-%d" % (g, i))
    d0 = sc.dispatches
    res = sc.step_burst()
    assert sc.dispatches == d0 + 1
    for _ in range(2):
        res = sc.step()
    for g in range(2):
        assert res["commit"][g].max() >= 6
        got = [p for (_t, _c, _r, p) in sc.replayed[g][0]]
        assert got == [b"s%d-%d" % (g, i) for i in range(6)]


# ---------------------------------------------------------------------------
# production driver on the mesh engine (same pipelined ticket loop)
# ---------------------------------------------------------------------------

def test_sharded_driver_serves_the_mesh_engine():
    """``ShardedClusterDriver(mesh=(gs, R))`` drives the multi-chip
    engine through the unchanged double-buffered loop: jittered
    per-group step-domain timers elect every group, key-prefix-routed
    SENDs commit and ack, and health names the mesh layout."""
    import threading
    import time

    from rdma_paxos_tpu.config import TimeoutConfig
    from rdma_paxos_tpu.runtime.sharded_driver import (
        ShardedClusterDriver)

    d = ShardedClusterDriver(
        CFG, 2, 2, mesh=(2, 2),
        timeout_cfg=TimeoutConfig(elec_timeout_low=0.05,
                                  elec_timeout_high=0.1))
    assert d.cluster.mesh.devices.shape == (2, 2)
    try:
        d.run(period=0.002)
        t0 = time.time()
        while d.leader() < 0:           # ALL-GROUPS-LED aggregate
            time.sleep(0.02)
            assert time.time() - t0 < 60, (d.leaders(), d.loop_error)
        handlers = [d._make_handler(r) for r in range(2)]
        acks = []

        def client(r, tid):
            h = handlers[r]
            conn = (r << 24) | (1000 + tid)
            st = h(2, conn, b"")
            assert st == 0 or st is None, st
            evs = []
            for i in range(15):
                ev = h(3, conn, b"SET k%d-%d v%d\n" % (tid, i, i))
                assert not isinstance(ev, int), (r, tid, i, ev)
                evs.append(ev)
            for ev in evs:
                assert ev.done.wait(30), "ack timed out"
                assert ev.status == 0
                acks.append(tid)

        threads = [threading.Thread(target=client, args=(r, t))
                   for t, r in enumerate([0, 1, 0, 1])]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(acks) == 60
        assert d.loop_error is None
        h = d.health()
        assert h["engine"] == "spmd-group"
        assert h["mesh"]["layout"] == "2x2"
        assert len(h["mesh"]["devices"]) == 4
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# per-group jittered step-domain election timers
# ---------------------------------------------------------------------------

def test_group_step_timer_deterministic_and_jittered():
    def periods(t: GroupStepTimer, n: int):
        out, since = [], 0
        for _ in range(n):
            since += 1
            if t.tick():
                out.append(since)
                since = 0
        return out

    a = periods(GroupStepTimer(0, seed=7, lo=3, hi=9), 200)
    b = periods(GroupStepTimer(0, seed=7, lo=3, hi=9), 200)
    assert a == b                       # chaos-replay reproducibility
    assert all(3 <= p <= 9 for p in a)
    c = periods(GroupStepTimer(1, seed=7, lo=3, hi=9), 200)
    assert a != c                       # per-group desynchronization
    d = periods(GroupStepTimer(0, seed=8, lo=3, hi=9), 200)
    assert a != d                       # seed-sensitive
    # beat() resets the countdown (a led group never fires)
    t = GroupStepTimer(0, seed=0, lo=2, hi=2)
    for _ in range(50):
        t.beat()
        assert not t.tick()
    with pytest.raises(ValueError):
        GroupStepTimer(0, lo=0, hi=2)
    with pytest.raises(ValueError):
        GroupStepTimer(0, lo=5, hi=2)
