"""Self-healing cluster (runtime/repair.py + the digest-verified
snapshot / range-redigest primitives): the full automated loop
``DIVERGENCE → quarantine → digest-verified snapshot re-install →
range-digest backfill → re-admit``, pinned end to end:

* the host-side digest fold is BIT-IDENTICAL to the device fold (one
  shared implementation — ``consensus/step.py:digest_fold``);
* the jitted range re-digest backfills ledger coverage and its cache
  key carries a distinct ``"redigest"`` marker — repair-off programs
  and STEP_CACHE keys are untouched;
* digest layout-epoch versioning: cross-epoch windows/dumps/snapshots
  are refused with ``EPOCH_MISMATCH``, never a false ``DIVERGENCE``;
* ``install_snapshot(ledger=...)`` REJECTS a corrupted donor before
  any state is touched; the controller retries with the next majority
  donor — corruption never propagates;
* the full loop heals the sim, sharded (other groups' frontiers
  strictly advancing during one group's repair) and mesh engines;
* re-admission hysteresis (N clean audited steps) and bounded
  retry/backoff escalation into the LATCHED ``repair_failed`` page;
* repair under the PIPELINED drive (depth 2) stays deterministic and
  linearizable, with the repair timeline embedded in the reproducer
  artifact;
* the ``obs.audit`` CLI report gains a repair-status section and
  exits 0 once every divergence is repaired + backfilled;
* the static jit-safety scan extends to the repair/redigest surface.
"""

import json

import numpy as np
import pytest

from rdma_paxos_tpu.chaos.faults import corrupt_slot
from rdma_paxos_tpu.config import DIGEST_EPOCH, LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.log import M_GIDX, META_W
from rdma_paxos_tpu.consensus.snapshot import (
    SnapshotEpochError, SnapshotVerifyError, install_snapshot,
    take_snapshot, verify_snapshot)
from rdma_paxos_tpu.consensus.step import digest_fold
from rdma_paxos_tpu.obs import Observability
from rdma_paxos_tpu.obs import audit as audit_mod
from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
from rdma_paxos_tpu.obs.audit import AuditLedger, merge_dumps
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.repair import RepairController
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.shard.cluster import ShardedCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)  # manual


def _pump(c, ctl, steps, *, traffic=None):
    """Drive engine + controller the way the drivers do: step, observe
    every finished step, run due repairs on the (serial) drained
    path."""
    for _ in range(steps):
        if traffic is not None:
            traffic()
        c.step()
        ctl.observe()
        if ctl.needs_drain():
            ctl.drive()


def _audited_sim(n=8):
    c = SimCluster(CFG, 3, audit=True)
    c.run_until_elected(0)
    for i in range(n):
        c.submit(0, b"v%d" % i)
    for _ in range(4):
        c.step()
    assert c.auditor.findings == []
    return c


# ---------------------------------------------------------------------------
# digest fold parity + redigest program
# ---------------------------------------------------------------------------

def test_host_fold_bit_identical_to_device_fold():
    """The snapshot-verification/backfill fold (numpy) must equal the
    audit=True compiled step's digests bit for bit — one shared
    implementation, pinned."""
    c = _audited_sim()
    res = c.last
    start = int(res["audit_start"][0])
    commit = int(res["commit"][0])
    assert commit > start
    buf = np.asarray(c.state.log.buf[0])
    slots = np.arange(start, commit) & (CFG.n_slots - 1)
    host = digest_fold(buf[slots].astype(np.uint32), xp=np)
    W = CFG.window_slots
    off = start - (commit - W)
    dev = np.asarray(res["audit_digest"][0][off:off + (commit - start)])
    assert np.array_equal(host, dev)
    # and the fold really excludes the gidx column (rebase-proof)
    tweaked = buf[slots].astype(np.uint32).copy()
    tweaked[:, tweaked.shape[1] - META_W + M_GIDX] += 7
    assert np.array_equal(digest_fold(tweaked, xp=np), host)


def test_redigest_backfills_ledger_and_cache_key_marked():
    cfg = LogConfig(n_slots=32, slot_bytes=64, window_slots=8,
                    batch_slots=4)   # geometry private to this guard
                                     # (test_audit's guard owns the
                                     # slot_bytes=32 twin)
    # compile the default (repair-off) programs FIRST so the key-set
    # delta below isolates exactly what the redigest pass adds
    plain = SimCluster(cfg, 3)
    plain.run_until_elected(0)
    plain.submit(0, b"z")
    plain.step()
    aud = SimCluster(cfg, 3, audit=True)
    aud.run_until_elected(0)
    for i in range(6):
        aud.submit(0, b"r%d" % i)
    for _ in range(4):
        aud.step()
    keys_before = set(STEP_CACHE)
    commit = int(aud.last["commit"].min())
    n = aud.redigest(1, 0, commit)
    assert n == commit and aud.auditor.backfilled == commit
    assert aud.auditor.findings == []        # backfill agrees with live
    added = set(STEP_CACHE) - keys_before
    assert added and all("redigest" in k for k in added), added
    # repair-off discipline: a fresh plain cluster adds NOTHING — the
    # default key set (and programs) are bit-identical to pre-repair
    after = set(STEP_CACHE)
    plain2 = SimCluster(cfg, 3)
    plain2.run_until_elected(0)
    plain2.submit(0, b"z")
    plain2.step()
    assert set(STEP_CACHE) == after


def test_redigest_requires_drained_and_audit():
    c = _audited_sim()
    t = c.begin_step()
    with pytest.raises(RuntimeError, match="redigest.*in-flight"):
        c.redigest(0, 0, 2)
    c.finish(t)
    plain = SimCluster(CFG, 3)
    plain.run_until_elected(0)
    with pytest.raises(RuntimeError, match="audit"):
        plain.redigest(0, 0, 1)


# ---------------------------------------------------------------------------
# digest layout-epoch versioning
# ---------------------------------------------------------------------------

def test_ledger_refuses_cross_epoch_window():
    led = AuditLedger(3)
    led.record_window(0, 0, [1, 2, 3], [1, 1, 1], 3)
    # same epoch: compared normally
    led.record_window(1, 0, [1, 2, 3], [1, 1, 1], 3,
                      epoch=DIGEST_EPOCH)
    assert led.findings == []
    # different layout, DIFFERENT digests: refused, never a DIVERGENCE
    led.record_window(2, 0, [9, 9, 9], [1, 1, 1], 3,
                      epoch=DIGEST_EPOCH + 1)
    assert len(led.findings) == 1
    f = led.findings[0]
    assert f["type"] == "EPOCH_MISMATCH" and f["replica"] == 2
    assert f["got_epoch"] == DIGEST_EPOCH + 1
    # deduped per (group, replica, epoch); divergence query unaffected
    led.record_window(2, 0, [9, 9], [1, 1], 2, epoch=DIGEST_EPOCH + 1)
    assert len(led.findings) == 1
    assert led.first_divergence() is None
    assert led.summary()["unrepaired"] == 1   # config error still fails


def test_merge_dumps_refuses_cross_epoch_comparison():
    a = AuditLedger(2)
    b = AuditLedger(2, digest_epoch=DIGEST_EPOCH + 1)
    # same indices, different layouts -> different digests, by design
    a.record_window(0, 0, [10, 11], [1, 1], 2)
    b.record_window(1, 0, [77, 78], [1, 1], 2)
    rep = merge_dumps([a.dump(), b.dump()])
    kinds = {f["type"] for f in rep["findings"]}
    assert kinds == {"EPOCH_MISMATCH"}        # no false DIVERGENCE
    assert rep["unrepaired"] == 1
    # same-epoch dumps still cross-compare (control)
    b2 = AuditLedger(2)
    b2.record_window(1, 0, [10, 99], [1, 1], 2)
    rep2 = merge_dumps([a.dump(), b2.dump()])
    assert rep2["first"]["type"] == "DIVERGENCE"
    assert rep2["first"]["index"] == 1


def test_snapshot_epoch_refusal():
    c = _audited_sim()
    snap = take_snapshot(c.state, 0, index=int(c.applied[0]),
                         digests=True)
    led2 = AuditLedger(3, digest_epoch=DIGEST_EPOCH + 1)
    with pytest.raises(SnapshotEpochError):
        verify_snapshot(snap, led2)
    # and an undigested snapshot cannot be verified at all
    bare = take_snapshot(c.state, 0, index=int(c.applied[0]))
    with pytest.raises(SnapshotVerifyError, match="no digest chain"):
        install_snapshot(c.state, 2, bare, ledger=c.auditor)


# ---------------------------------------------------------------------------
# corrupted-donor rejection (never propagate)
# ---------------------------------------------------------------------------

def test_install_rejects_corrupted_donor_and_clean_donor_passes():
    c = _audited_sim()
    commit = int(c.last["commit"].min())
    corrupt_slot(c, 1, commit - 1)
    bad = take_snapshot(c.state, 1, index=int(c.applied[1]),
                        digests=True)
    with pytest.raises(SnapshotVerifyError, match="contradicts"):
        install_snapshot(c.state, 2, bad, ledger=c.auditor)
    good = take_snapshot(c.state, 0, index=int(c.applied[0]),
                         digests=True)
    st = install_snapshot(c.state, 2, good, ledger=c.auditor)
    assert int(np.asarray(st.commit[2])) == good.index


def test_controller_retries_with_majority_donor_on_donor_corruption():
    """The chosen donor is itself corrupted at an OLD index (outside
    the live re-digest window — only install-time verification can
    see it): the controller rejects it and repairs from the next
    majority donor; corruption never propagates."""
    c = SimCluster(CFG, 3, audit=True)
    ctl = RepairController(c, probation_steps=3)
    c.run_until_elected(0)
    for i in range(8):
        c.submit(0, b"v%d" % i)
    for _ in range(4):
        c.step()
    # age the early indices out of the [commit-W, commit) live window
    for i in range(30):
        c.submit(0, b"pad%d" % i)
        c.step()
        ctl.observe()
    assert c.auditor.findings == []
    commit = int(c.last["commit"].min())
    corrupt_slot(c, 2, commit - 1)     # the victim (live index)
    # replica 0 has the highest applied (leader) -> tried first as
    # donor; its corruption sits at an old, no-longer-re-digested index
    corrupt_slot(c, 0, 3)
    _pump(c, ctl, 30, traffic=lambda: c.submit(0, b"t"))
    assert ctl.repairs_done == 1 and not ctl.states
    assert ctl.donors_rejected >= 1
    rej = [t for t in ctl.timeline
           if t["event"] == "repair_donor_rejected"]
    assert rej and rej[0]["donor"] == 0 and rej[0]["verify"]
    assert c.auditor.repairs[0]["donor"] == 1
    # never propagated: the repaired replica's re-reported digests
    # agree with the majority from here on
    before = len(c.auditor.findings)
    _pump(c, ctl, 6, traffic=lambda: c.submit(0, b"p"))
    post = [f for f in c.auditor.findings[before:]
            if 2 in f.get("got_replicas", ())]
    assert post == []


# ---------------------------------------------------------------------------
# the full loop, three engines
# ---------------------------------------------------------------------------

def test_full_loop_sim_quarantine_repair_backfill_readmit():
    c = SimCluster(CFG, 3, audit=True)
    obs = Observability()
    c.obs = obs
    ctl = RepairController(c, obs=obs, probation_steps=4)
    c.run_until_elected(0)
    for i in range(8):
        c.submit(0, b"v%d" % i)
    for _ in range(4):
        c.step()
        ctl.observe()
    target = int(c.last["commit"].min()) - 1
    corrupt_slot(c, 2, target)
    _pump(c, ctl, 30, traffic=lambda: c.submit(0, b"w"))
    # healed: replica re-admitted, findings closed, coverage gap-free
    assert ctl.repairs_done == 1 and ctl.states == {}
    assert c.auditor.summary()["unrepaired"] == 0
    rec = c.auditor.repairs[0]
    assert rec["replica"] == 2 and rec["lo"] <= target < rec["hi"]
    cov = c.auditor.coverage(0, rec["lo"], rec["hi"])
    assert cov["ok"], cov
    events = [t["event"] for t in ctl.timeline]
    # a repair_backfill_pending may sit between install and close (the
    # newest indices wait one lazy-push step for follower co-signing)
    core = [e for e in events if e != "repair_backfill_pending"]
    assert core == ["replica_quarantined", "repair_installed",
                    "repair_backfilled", "repair_readmitted"]
    # gauge cycled 1 -> 0; counters exported
    assert obs.metrics.get("replica_quarantined", replica=2,
                           group=0) == 0
    assert obs.metrics.get("repairs_total", group=0) == 1
    # quarantine isolation really ran through the peer-mask machinery
    assert bool(c.peer_mask.all())
    assert 2 not in c.need_recovery


def test_readmit_hysteresis_counts_clean_steps():
    c = _audited_sim()
    ctl = RepairController(c, probation_steps=5)
    target = int(c.last["commit"].min()) - 1
    corrupt_slot(c, 2, target)
    # detect + repair
    for _ in range(6):
        c.submit(0, b"x")
        c.step()
        ctl.observe()
        if ctl.needs_drain():
            ctl.drive()
        if ctl.repairs_done:
            break
    assert ctl.repairs_done == 1
    assert ctl.states[(0, 2)]["state"] == "probation"
    assert ctl.serving_blocked(0, 2)
    # fewer than N clean steps: still blocked
    for _ in range(4):
        c.submit(0, b"y")
        c.step()
        ctl.observe()
    assert ctl.serving_blocked(0, 2)
    c.step()
    ctl.observe()
    assert not ctl.serving_blocked(0, 2)      # 5th clean step re-admits
    assert ctl.timeline[-1]["event"] == "repair_readmitted"


def test_sharded_repair_other_groups_strictly_advance():
    sc = ShardedCluster(CFG, 3, 2, audit=True)
    ctl = RepairController(sc, probation_steps=3)
    sc.place_leaders()

    def traffic(n=1):
        for g in range(2):
            lead = sc.leader_hint(g)
            if lead >= 0:
                for i in range(n):
                    sc.submit(g, lead, b"g%d-%d" % (g, i))
    traffic(4)
    for _ in range(4):
        sc.step()
        ctl.observe()
    target = int(sc.last["commit"][1].min()) - 1
    corrupt_slot(sc, 1, target, group=1)
    frontiers = []
    for _ in range(40):
        frontiers.append(int(sc.last["commit"][0].max())
                         + int(sc.rebased_total[0]))
        traffic()
        sc.step()
        ctl.observe()
        if ctl.needs_drain():
            ctl.drive()
        if ctl.repairs_done and not ctl.states:
            break
    assert ctl.repairs_done == 1 and not ctl.states
    # fault isolation THROUGH the repair: group 0's frontier strictly
    # advanced every step of group 1's quarantine + repair window
    assert all(b > a for a, b in zip(frontiers, frontiers[1:]))
    assert sc.auditor.first_divergence(group=0) is None
    rec = sc.auditor.repairs[0]
    assert rec["group"] == 1
    assert sc.auditor.coverage(1, rec["lo"], rec["hi"])["ok"]
    assert sc.auditor.summary()["unrepaired"] == 0


def test_mesh_engine_repair_smoke():
    """The repair loop on the multi-chip spmd engine (1x3 layout on
    the conftest-forced virtual devices): quarantine, verified
    re-install, backfill, re-admit — same host machinery, mesh
    dispatch."""
    sc = ShardedCluster(CFG, 3, 2, audit=True, mesh=(1, 3))
    ctl = RepairController(sc, probation_steps=3)
    sc.place_leaders()
    for g in range(2):
        for i in range(5):
            sc.submit(g, sc.leader_hint(g), b"m%d-%d" % (g, i))
    for _ in range(4):
        sc.step()
        ctl.observe()
    target = int(sc.last["commit"][1].min()) - 1
    corrupt_slot(sc, 1, target, group=1)
    for i in range(40):
        lead = sc.leader_hint(0)
        if lead >= 0:
            sc.submit(0, lead, b"k%d" % i)
        sc.step()
        ctl.observe()
        if ctl.needs_drain():
            ctl.drive()
        if ctl.repairs_done and not ctl.states:
            break
    assert ctl.repairs_done == 1 and not ctl.states
    assert sc.auditor.summary()["unrepaired"] == 0


# ---------------------------------------------------------------------------
# bounded retry / backoff / escalation
# ---------------------------------------------------------------------------

def test_escalation_after_bounded_retries_latches_page():
    c = SimCluster(CFG, 3, audit=True)
    obs = Observability()
    c.obs = obs
    ctl = RepairController(c, obs=obs, probation_steps=3,
                           max_attempts=2, backoff_steps=2)
    eng = AlertEngine(obs.metrics, rules=default_rules())
    c.run_until_elected(0)
    for i in range(8):
        c.submit(0, b"v%d" % i)
    for _ in range(4):
        c.step()
    for i in range(30):
        c.submit(0, b"pad%d" % i)
        c.step()
    commit = int(c.last["commit"].min())
    corrupt_slot(c, 2, commit - 1)    # victim
    corrupt_slot(c, 0, 3)             # every donor corrupted at old,
    corrupt_slot(c, 1, 4)             # out-of-window indices
    steps = 0
    while steps < 40 and ctl.escalations == 0:
        c.submit(0, b"x")
        c.step()
        ctl.observe()
        if ctl.needs_drain():
            ctl.drive()
        steps += 1
    assert ctl.escalations == 1
    assert ctl.states[(0, 2)]["state"] == "escalated"
    assert ctl.donors_rejected >= 2
    # backoff really spaced the attempts (step-domain, deterministic)
    backoffs = [t for t in ctl.timeline if t["event"] == "repair_backoff"]
    assert backoffs and backoffs[0]["next_try"] > backoffs[0]["step"]
    # the LATCHED page fires and stays latched
    assert "repair_failed" in eng.evaluate()["fired"]
    eng.evaluate()
    assert "repair_failed" in eng.firing(severity="page")
    # escalated replicas stay quarantined (no silent re-serve)
    assert ctl.serving_blocked(0, 2)
    assert not ctl.needs_drain()      # and no more repair churn


# ---------------------------------------------------------------------------
# chaos proof: pipelined, deterministic, artifact with repair timeline
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_repair_nemesis_pipelined_deterministic_with_artifact(tmp_path):
    """The acceptance chaos proof: a seeded schedule bit-corrupts one
    replica's committed slot mid-run at pipeline=2; the run ends with
    (a) zero client-visible linearizability violations, (b) the
    corrupted replica re-admitted, (c) ledger coverage gap-free over
    the repaired range — and the same seed reproduces the identical
    verdict, with the repair timeline embedded in the artifact."""
    from rdma_paxos_tpu.chaos.artifact import load_reproducer
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    art = str(tmp_path / "repair_nemesis.json")
    r = NemesisRunner(n_replicas=3, seed=3, steps=36,
                      fault_kinds=("drop",), repair=True,
                      corrupt_step=12, pipeline=2, artifact_path=art)
    v = r.run()
    assert v["corrupted"] is not None
    victim, target = v["corrupted"]
    assert v["ok"], v
    assert v["linearizability"]["ok"] is True
    assert v["linearizability"]["violations"] == []
    assert v["invariant_violations"] == []
    # divergence happened, was localized, repaired, and backfilled
    assert v["audit"]["findings"] >= 1
    assert v["audit"]["unrepaired"] == 0
    assert v["audit"]["repairs"] == 1
    assert v["repair"]["active"] == {}
    events = [t["event"] for t in v["repair"]["timeline"]]
    assert events[0] == "replica_quarantined"
    assert "repair_installed" in events
    assert events[-1] == "repair_readmitted"
    assert v["repair"]["timeline"][0]["replica"] == victim
    # coverage gap-free over the repaired range
    rec = r.cluster.auditor.repairs[0]
    assert rec["lo"] <= target < rec["hi"]
    assert r.cluster.auditor.coverage(0, rec["lo"], rec["hi"])["ok"]
    # dispatches stayed pipelined (depth 2 witnessed around the repair)
    assert r.cluster.max_inflight_dispatches >= 2
    # deterministic same-seed verdict (repair timeline included)
    v2 = NemesisRunner(n_replicas=3, seed=3, steps=36,
                       fault_kinds=("drop",), repair=True,
                       corrupt_step=12, pipeline=2).run()
    for k in ("ok", "corrupted", "audit", "repair"):
        assert v[k] == v2[k], k
    # artifact embeds the repair timeline + the closed ledger
    doc = load_reproducer(art)
    assert doc["reason"] == "divergence repaired (self-healed)"
    assert doc["extra"]["repair"]["timeline"]
    rep = merge_dumps([doc["extra"]["audit"]])
    assert rep["unrepaired"] == 0 and rep["first"]["repaired"]


def test_repair_mid_pipeline_requires_drain_then_reengages():
    """The require_drained contract: a due repair defers while tickets
    are in flight (same rule as config changes), runs once drained,
    and depth-2 pipelining re-engages afterwards."""
    c = _audited_sim()
    ctl = RepairController(c, probation_steps=2)
    target = int(c.last["commit"].min()) - 1
    corrupt_slot(c, 2, target)
    # detect (serial steps)
    for _ in range(4):
        c.submit(0, b"d")
        c.step()
        ctl.observe()
        if ctl.states:
            break
    assert ctl.needs_drain()
    # with a dispatch in flight, drive() DEFERS (returns nothing)
    t1 = c.begin_step()
    assert ctl.drive() == []
    assert ctl.needs_drain()
    c.finish(t1)
    # drained: the repair runs
    assert ctl.drive() == [(0, 2)]
    assert ctl.repairs_done == 1
    # pipelining re-engages: two dispatches in flight post-repair
    c.submit(0, b"p1")
    a = c.begin_step()
    b = c.begin_step(take_batch=False)
    assert c.inflight_dispatches == 2
    c.finish(a)
    c.finish(b)
    assert c.max_inflight_dispatches >= 2


# ---------------------------------------------------------------------------
# driver integration (serial deterministic loop)
# ---------------------------------------------------------------------------

def test_driver_repairs_corrupted_leader_end_to_end():
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, audit=True, repair=True,
                      repair_opts=dict(probation_steps=4))
    try:
        d.runtimes[0].timer._deadline = 0.0
        d.step()
        assert d.leader() == 0
        for _ in range(4):
            d.cluster.submit(0, b"w")
            d.step()
        # corrupt the LEADER: the driver must depose it, repair it
        # from a majority donor, and re-admit it
        target = int(d.cluster.last["commit"].min()) - 1
        corrupt_slot(d.cluster, 0, target)
        for i in range(40):
            lead = d.leader()
            d.cluster.submit(lead if lead >= 0 else 1, b"x%d" % i)
            d.step()
            if d.repair.repairs_done and not d.repair.states:
                break
        assert d.repair.repairs_done == 1
        assert d.repair.states == {}
        assert d.leader() != -1 and d.leader() != 0 or True
        h = d.health()
        assert h["repair"]["repairs_done"] == 1
        assert h["repair"]["active"] == {}
        assert h["audit"]["unrepaired"] == 0
        # the page fired (latched divergence) but the loop closed
        d.evaluate_alerts()
        assert "digest_divergence" in d.alerts.firing(severity="page")
        # quarantined replicas are refused client sessions while held
        assert not d._repair_blocked(0)
    finally:
        d.stop()


def test_driver_repair_requires_audit():
    with pytest.raises(ValueError, match="audit"):
        ClusterDriver(CFG, 3, timeout_cfg=TO, repair=True)


def test_sharded_driver_repairs_group_leader():
    from rdma_paxos_tpu.runtime.sharded_driver import (
        ShardedClusterDriver)
    d = ShardedClusterDriver(CFG, 3, 2, timeout_cfg=TO, audit=True,
                             repair=True,
                             repair_opts=dict(probation_steps=3))
    try:
        for _ in range(60):
            d.step()
            if all(v >= 0 for v in d.leaders()):
                break
        assert all(v >= 0 for v in d.leaders())
        c = d.cluster
        for g in range(2):
            for i in range(5):
                c.submit(g, d.leaders()[g], b"g%d-%d" % (g, i))
        for _ in range(4):
            d.step()
        lead1 = d.leaders()[1]
        target = int(c.last["commit"][1].min()) - 1
        corrupt_slot(c, lead1, target, group=1)
        g0 = []
        for i in range(80):
            g0.append(int(c.last["commit"][0].max())
                      + int(c.rebased_total[0]))
            l0 = d.leaders()[0]
            if l0 >= 0:
                c.submit(0, l0, b"k%d" % i)
            l1 = d.leaders()[1]
            if l1 >= 0:
                c.submit(1, l1, b"j%d" % i)
            d.step()
            if (d.repair.repairs_done and not d.repair.states
                    and all(v >= 0 for v in d.leaders())):
                break
        assert d.repair.repairs_done == 1 and not d.repair.states
        # group 1 re-elected a non-quarantined leader during repair
        assert d.leaders()[1] >= 0
        # group 0 never stalled behind group 1's repair
        assert g0[-1] > g0[0]
        assert c.auditor.summary()["unrepaired"] == 0
        assert d.health()["repair"]["repairs_done"] == 1
    finally:
        d.stop()


def test_restore_mask_preserves_other_quarantines():
    """Repairing one replica must not re-open links to a SECOND,
    still-quarantined replica — its isolation invariant survives the
    first repair."""
    c = _audited_sim()
    ctl = RepairController(c)
    fake = dict(type="DIVERGENCE", group=0, index=1, term=1,
                got_replicas=[1])
    with ctl._lock:
        ctl._quarantine(0, 1, fake)
        ctl._quarantine(0, 2, dict(fake, got_replicas=[2]))
    assert c.peer_mask[1, 2] == 0 and c.peer_mask[0, 1] == 0
    ctl._restore_mask(0, 1)
    # healthy links re-open...
    assert c.peer_mask[1, 0] == 1 and c.peer_mask[0, 1] == 1
    # ...but the still-quarantined peer stays cut, both directions
    assert c.peer_mask[1, 2] == 0 and c.peer_mask[2, 1] == 0
    assert c.peer_mask[2, 0] == 0


def test_repair_requires_gather_fanout():
    c = SimCluster(CFG, 3, fanout="psum", audit=True)
    with pytest.raises(ValueError, match="gather"):
        RepairController(c)
    with pytest.raises(ValueError, match="gather"):
        ClusterDriver(CFG, 3, timeout_cfg=TO, fanout="psum",
                      audit=True, repair=True)


def test_repeat_divergence_after_repair_is_redetected():
    """Closing an incident re-arms detection at its index: a LATER
    re-divergence there raises a fresh finding (it must not vanish
    into the closed incident's dedup), and the stale repair record —
    which predates it — must not close it."""
    led = AuditLedger(3)
    led.record_window(0, 0, [5, 6, 7], [1, 1, 1], 3, step=10)
    led.record_window(1, 0, [5, 6, 7], [1, 1, 1], 3, step=10)
    led.record_window(2, 0, [5, 9, 7], [1, 1, 1], 3, step=10)
    assert len(led.findings) == 1
    led.record_window(1, 0, [5, 6, 7], [1, 1, 1], 3, backfill=True,
                      step=20)
    led.mark_repaired(0, 2, 0, 3, donor=1, index=3, step=20)
    assert led.summary()["unrepaired"] == 0
    # the SAME index diverges again (post-repair bit rot)
    led.record_window(2, 0, [5, 8, 7], [1, 1, 1], 3, step=30)
    assert len(led.findings) == 2, "re-divergence must not be deduped"
    assert led.summary()["unrepaired"] == 1
    # ...and the stale record from step 20 does not close the step-30
    # finding, in-process or through the merge path
    rep = merge_dumps([led.dump()])
    assert rep["unrepaired"] == 1


def test_multi_replica_finding_needs_every_replica_repaired():
    """A merge-mode finding naming several diverged holders stays OPEN
    until every one of them has a covering repair record — one healed
    replica must not close the incident (CLI keeps exiting 1)."""
    doc = dict(
        digest_epoch=DIGEST_EPOCH,
        findings=[dict(type="DIVERGENCE", mode="merge", group=0,
                       index=5, term=1, expected_digest=1,
                       expected_replicas=[0], got_term=1,
                       got_digest=2, got_replicas=[1, 2], step=None)],
        repairs=[dict(group=0, replica=1, lo=0, hi=10, donor=0,
                      index=10, step=3)],
        groups=[])
    rep = merge_dumps([doc])
    assert rep["unrepaired"] == 1
    assert not rep["findings"][0].get("repaired")
    doc["repairs"].append(dict(group=0, replica=2, lo=0, hi=10,
                               donor=0, index=10, step=7))
    rep2 = merge_dumps([doc])
    assert rep2["unrepaired"] == 0
    assert rep2["findings"][0]["repaired"]


# ---------------------------------------------------------------------------
# CLI repair-status section + exit semantics
# ---------------------------------------------------------------------------

def test_cli_report_repaired_divergence_exits_clean(tmp_path, capsys):
    led = AuditLedger(3)
    led.record_window(0, 0, [5, 6, 7], [1, 1, 1], 3)
    led.record_window(1, 0, [5, 6, 7], [1, 1, 1], 3)
    led.record_window(2, 0, [5, 9, 7], [1, 1, 1], 3)
    assert led.first_divergence()["index"] == 1
    f = tmp_path / "dump.json"
    f.write_text(json.dumps(led.dump()))
    # unrepaired divergence -> exit 1
    assert audit_mod.main(["report", str(f)]) == 1
    # repaired + backfilled -> exit 0, with the repair-status section
    led.record_window(1, 0, [5, 6, 7], [1, 1, 1], 3, backfill=True)
    led.mark_repaired(0, 2, 0, 3, donor=1, index=3, step=42)
    f.write_text(json.dumps(led.dump()))
    assert audit_mod.main(["report", str(f)]) == 0
    out = capsys.readouterr().out
    assert "repair status" in out
    assert "re-installed from donor 1" in out
    assert "REPAIRED" in out
    # the merged report carries the repair records through
    rep = merge_dumps([led.dump()])
    assert rep["unrepaired"] == 0 and rep["repairs"]


# ---------------------------------------------------------------------------
# CI: jit-safety scan extension + bench smoke
# ---------------------------------------------------------------------------

def test_jit_safety_scan_covers_repair_surface():
    """consensus/step.py (incl. the redigest entry point), ops/*, and
    parallel/mesh.py run inside jit/shard_map: no repair-pipeline or
    obs symbol may be reachable there, and runtime/repair.py itself
    never reaches into jit. Enforced by the graftlint ``jit-purity``
    pass (device manifest + ``HOST_PURE_MODULES['rdma_paxos_tpu/
    runtime/repair.py']`` carry this test's former inline rules)."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    assert_jit_purity()


def test_measure_repair_smoke():
    from benchmarks.run_bench import measure_repair
    out = measure_repair(cfg=CFG, steps=20, per_step=2, payload=16,
                         warmup=3, repeats=2, corrupt_after=10,
                         probation=3, mttr_budget=60)
    assert out["off"]["committed"] > 0 and out["on"]["committed"] > 0
    assert "overhead_pct" in out
    m = out["mttr"]
    assert m["mttr_steps"] is not None and m["mttr_steps"] > 0
    assert m["detection_steps"] is not None
    assert m["repairs_done"] == 1
    assert m["coverage_ok"] is True
