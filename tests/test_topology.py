"""topology/ — elastic group split/merge: acceptance properties.

* ONE shared epoch abstraction: the term-watch/completion-proof
  machinery lives in ``topology/epoch.py`` and the txn coordinator
  imports it — no second copy of the rules anywhere;
* the router mutation surface (``install_rule``/``remove_rule`` +
  monotone ``version``) round-trips through serialization, through
  ``health()``, and through the fleet console; the golden router map
  gains a post-split fixture and checksum tampering is still refused;
* a split moves a live key range to its new owner group with values
  intact, a merge returns it, and the trace ring proves leases on
  every affected group were revoked BEFORE the cutover and re-granted
  after — with the cluster stepping the whole time;
* topology is a zero-device-change subsystem: STEP_CACHE keys and
  step outputs are bit-identical with a controller attached, even
  after a full split/merge cycle (splits reshape host routing only);
* an in-flight 2PC transaction whose key→group mapping moved aborts
  deterministically with the dedicated TOPOLOGY reason;
* the load policy proposes with hysteresis (AlertEngine ``for_evals``),
  sits out its own cooldown, respects the governor's shed veto, and
  never merges operator-pinned override rules;
* the seeded split-mid-nemesis chaos schedule is green and
  deterministic (same seed ⟹ byte-identical verdict).
"""

import json
import pathlib
from types import SimpleNamespace

import numpy as np
import pytest

from benchmarks.arrival_traces import zipf_keys
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.obs import AlertEngine, Observability
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.obs.console import _topo_state
from rdma_paxos_tpu.runtime import reads as reads_mod
from rdma_paxos_tpu.runtime.sim import STEP_CACHE
from rdma_paxos_tpu.shard import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS
from rdma_paxos_tpu.shard.router import KeyRouter, RangeRule
from rdma_paxos_tpu.topology import attach_topology
from rdma_paxos_tpu.topology import epoch as epoch_mod
from rdma_paxos_tpu.topology.policy import (
    MERGE_RULE, SPLIT_RULE, TopologyPolicy)
from rdma_paxos_tpu.txn import attach_coordinator
from rdma_paxos_tpu.txn.chaos import keys_for_groups

# a geometry no other test uses: the cache-key guard below reasons
# about which keys THIS test file's clusters add to the shared cache
CFG = LogConfig(n_slots=256, slot_bytes=128, window_slots=32,
                batch_slots=8)

GOLDEN = pathlib.Path(__file__).parent / "golden" / "router_map.json"


def _cluster(G=2, *, cfg=CFG, txn=False, **opts):
    """Direct-stepped sharded cluster with obs + leases + topology."""
    shard = ShardedCluster(cfg, 3, G, txn=txn)
    obs = Observability()
    shard.obs = obs
    kv = ShardedKVS(shard, cap=256)
    reads_mod.attach(shard)
    opts.setdefault("cooldown_steps", 4)
    ctl = attach_topology(kv, obs=obs, **opts)
    shard.place_leaders()
    return shard, kv, ctl, obs


def _run_window(shard, ctl, max_steps=300):
    """Step + drive until the transition window closes."""
    for _ in range(max_steps):
        shard.step()
        ctl.drive()
        if not ctl.in_window():
            return
    raise AssertionError("transition window did not close: "
                         f"{ctl.status()}")


def _seed_keys(shard, kv, per_group=6):
    """Write a known value under ``per_group`` keys per group; ->
    ``keys[g]`` lists (committed before return)."""
    keys = keys_for_groups(kv.router, per_group)
    for g, ks in enumerate(keys):
        for k in ks:
            kv.put(k, b"v0:" + k, leader=shard.leader_hint(g))
    for _ in range(4):
        shard.step()
    return keys


# ---------------------------------------------------------------------------
# the shared epoch abstraction (one copy, two users)
# ---------------------------------------------------------------------------

def test_epoch_machinery_is_shared_not_copied():
    """The txn coordinator and the transition window must consume the
    SAME module object — the factored-out machinery, not a fork."""
    from rdma_paxos_tpu.topology import transition as transition_mod
    from rdma_paxos_tpu.txn import coordinator as txn_coord
    assert txn_coord._epoch is epoch_mod
    assert transition_mod._epoch is epoch_mod
    # the coordinator keeps no private copies of the factored helpers
    src = pathlib.Path(txn_coord.__file__).read_text()
    for dup in ("def commit_frontier", "def placement_status",
                "class TermWatch", "def term_now"):
        assert dup not in src, f"coordinator re-grew {dup!r}"


def test_epoch_placement_status_rules():
    P, C, I = epoch_mod.PENDING, epoch_mod.COMPLETE, epoch_mod.INVALIDATED
    # unplaced: pending regardless of frontiers
    assert epoch_mod.placement_status(-1, 0, 100, 9) == P
    # committed under an unchanged term: durable
    assert epoch_mod.placement_status(5, 3, 6, 3) == C
    # term advanced: the frontier proves nothing — forget and retry
    assert epoch_mod.placement_status(5, 3, 6, 4) == I
    assert epoch_mod.placement_status(5, 3, 4, 4) == I
    # not yet committed, term unchanged: keep waiting
    assert epoch_mod.placement_status(5, 3, 5, 3) == P


def test_epoch_term_watch_and_clock():
    w = epoch_mod.TermWatch(2)
    assert not w.deposed(0, 5)          # nothing appended: never deposed
    w.note(0, 3)
    assert not w.deposed(0, 3) and w.deposed(0, 4)
    w.reset(0)
    assert not w.deposed(0, 9)
    clk = epoch_mod.EpochClock(2)
    assert clk.current() == 2 and clk.bump() == 3 and clk.current() == 3


# ---------------------------------------------------------------------------
# router mutation surface + serialization
# ---------------------------------------------------------------------------

def test_router_mutation_versions_and_candidate_purity():
    r = KeyRouter(4)
    assert r.version == 0
    rule = RangeRule(b"m", b"n", 3)
    cand = r.with_rule(rule)
    # candidates are PURE: the live router is untouched
    assert r.version == 0 and not r.overrides
    assert cand.group_of(b"mid") == 3
    assert r.install_rule(rule) == 1 and r.version == 1
    assert r.group_of(b"mid") == 3
    back = r.without_rule(rule)
    assert back.group_of(b"mid") == KeyRouter(4).group_of(b"mid")
    assert r.remove_rule(rule) == 2 and r.version == 2
    assert r.group_of(b"mid") == KeyRouter(4).group_of(b"mid")


def test_router_serialization_carries_version_and_refuses_tamper():
    r = KeyRouter(4)
    r.install_rule(RangeRule(b"user:", b"user;", 2))
    d = r.to_dict()
    assert d["version"] == 1
    r2 = KeyRouter.from_dict(d)
    assert r2.version == 1 and r2.overrides == r.overrides
    for k in (b"", b"user:42", b"key7", "ключ"):
        assert r2.group_of(k) == r.group_of(k)
    # checksum tamper still refused with overrides + version present
    with pytest.raises(ValueError, match="checksum mismatch"):
        KeyRouter.from_dict(dict(d, ring_checksum=d["ring_checksum"] ^ 1))
    # pre-elastic snapshots (no version field) reconstruct as 0
    legacy = {k: v for k, v in d.items() if k != "version"}
    assert KeyRouter.from_dict(legacy).version == 0


def test_router_golden_map_and_post_split_fixture():
    doc = json.loads(GOLDEN.read_text())
    base = KeyRouter.from_dict(doc["router"])
    for key, g in doc["mapping"].items():
        assert base.group_of(key) == g, key
    ps = doc["post_split"]
    rule = RangeRule.from_dict(ps["rule"])
    # installing the pinned split rule reproduces the pinned post-split
    # table exactly (version, override order, checksum — everything)
    live = KeyRouter.from_dict(doc["router"])
    live.install_rule(rule)
    assert live.to_dict() == ps["router"]
    # and the post-split serialized form round-trips on its own
    after = KeyRouter.from_dict(ps["router"])
    assert after.version == ps["router"]["version"] == 1
    moved = 0
    for key, g in ps["mapping"].items():
        assert after.group_of(key) == g, key
        moved += int(base.group_of(key) != g)
    assert moved >= 3, "fixture must pin keys the split actually moved"
    with pytest.raises(ValueError, match="checksum mismatch"):
        KeyRouter.from_dict(dict(
            ps["router"],
            ring_checksum=ps["router"]["ring_checksum"] ^ 1))


# ---------------------------------------------------------------------------
# split / merge end-to-end (live cluster, lease fence proven)
# ---------------------------------------------------------------------------

def test_split_then_merge_moves_range_and_fences_leases():
    shard, kv, ctl, obs = _cluster(G=2)
    keys = _seed_keys(shard, kv)
    hot = sorted(keys[0])
    lo, hi = hot[len(hot) // 2], hot[-1] + b"\x00"
    moving = [k for k in hot if lo <= k < hi]
    assert moving
    assert ctl.propose_split(lo, hi, 1)
    assert not ctl.propose_split(lo, hi, 1), "window already open"
    _run_window(shard, ctl)

    st = ctl.status()
    assert st["phase"] == "idle" and st["frozen"] is False
    # straight out of the window: cooling, so a new proposal is refused
    rule = RangeRule(lo, hi, 1)
    assert ctl.cooling() and not ctl.propose_merge(rule)
    assert st["transitions_total"] == 1 and st["abandoned_total"] == 0
    assert st["epoch"] == 1 and kv.router.version == 1
    assert RangeRule(lo, hi, 1) in kv.router.overrides
    for k in moving:          # values survived the move, routing moved
        assert kv.group_of(k) == 1
        assert kv.get(k) == b"v0:" + k
    for k in hot:             # below the median: still the old owner
        if k < lo:
            assert kv.group_of(k) == 0
    # a post-split write routes to (and lands in) the new owner
    kv.put(moving[0], b"v1", leader=shard.leader_hint(1))
    for _ in range(4):
        shard.step()
    assert kv.get(moving[0]) == b"v1"
    assert dict(kv.groups[1].items_in_range(
        shard.leader_hint(1), lo, hi))[moving[0]] == b"v1"

    # merge = the same window in reverse, after the cooldown
    while ctl.cooling():
        shard.step()
    assert ctl.propose_merge(rule)
    _run_window(shard, ctl)
    assert not kv.router.overrides and kv.router.version == 2
    assert ctl.status()["epoch"] == 2
    assert ctl.transitions_total == 2 and ctl.abandoned_total == 0
    for k in moving:
        assert kv.group_of(k) == 0
    assert kv.get(moving[0]) == b"v1"       # the post-split write moved back
    for k in moving[1:]:
        assert kv.get(k) == b"v0:" + k

    # lease fence, from the trace ring: every affected group's lease
    # was revoked BEFORE each cutover and granted again after the last
    ev = obs.trace.events()
    cuts = [e for e in ev if e.kind == obs_trace.TOPOLOGY_CUTOVER]
    assert len(cuts) == 2
    for cut in cuts:
        affected = set(cut.fields.get("donors", ())) | set(
            cut.fields.get("targets", ()))
        assert affected
        for g in affected:
            assert any(e.kind == obs_trace.LEASE_REVOKED
                       and e.fields.get("reason") == "topology_cutover"
                       and e.fields.get("group") == g
                       and e.seq < cut.seq for e in ev), (g, cut)
    for _ in range(8):        # lease re-grant is step-driven (guard
        shard.step()          # steps first), so step past the barrier
    kv.get(moving[0], linearizable=True)
    ev = obs.trace.events()
    last_cut = max(e.seq for e in ev
                   if e.kind == obs_trace.TOPOLOGY_CUTOVER)
    assert any(e.kind == obs_trace.LEASE_GRANTED and e.seq > last_cut
               for e in ev), "leases must re-grant after the cutover"


def test_proposal_refusals_and_would_block_gate():
    shard, kv, ctl, obs = _cluster(G=2)
    with pytest.raises(ValueError, match="rule not installed"):
        ctl.propose_merge(RangeRule(b"a", b"b", 1))
    assert not ctl.would_block(b"anything")     # idle: gate wide open
    assert not ctl.in_window() and not ctl.frozen()


# ---------------------------------------------------------------------------
# health / console round-trip
# ---------------------------------------------------------------------------

def test_health_router_roundtrip_and_console_after_split():
    from rdma_paxos_tpu.obs import console as console_mod
    shard, kv, ctl, obs = _cluster(G=2)
    keys = _seed_keys(shard, kv)
    hot = sorted(keys[0])
    assert ctl.propose_split(hot[len(hot) // 2], hot[-1] + b"\x00", 1)
    _run_window(shard, ctl)

    h = shard.health()
    # the override table round-trips through the health document:
    # an observer rebuilds the EXACT post-split mapping without code
    rebuilt = KeyRouter.from_dict(h["router"])
    assert rebuilt.version == 1 and len(rebuilt.overrides) == 1
    for ks in keys:
        for k in ks:
            assert rebuilt.group_of(k) == kv.group_of(k)
    topo = h["topology"]
    assert topo["transitions_total"] == 1 and topo["epoch"] == 1
    assert topo["phase"] == "idle"

    # console column: direct renderer + the fleet table
    assert _topo_state(h) == "e1/1t"
    assert _topo_state(dict()) == "-"
    assert _topo_state(dict(topology=dict(
        epoch=0, transitions_total=0, phase="seed",
        direction="split"))) == "e0/0t split:seed"
    h["ts"] = 1.0
    view = console_mod.fleet_view([dict(src="local", health=h)])
    assert [r["topo"] for r in view["groups"]] == ["e1/1t", "-"]
    out = console_mod.render_table(view)
    assert "TOPO" in out and "e1/1t" in out


# ---------------------------------------------------------------------------
# zero device changes (the audit=/telemetry=/txn= discipline)
# ---------------------------------------------------------------------------

def test_topology_adds_no_step_cache_keys_and_outputs_identical():
    # fresh geometry: no other test has populated the cache for it,
    # so "adds nothing" is an exact set comparison
    cfg = LogConfig(n_slots=64, slot_bytes=128, window_slots=8,
                    batch_slots=4)

    def workload(shard, kv):
        shard.place_leaders()
        keys = keys_for_groups(kv.router, 4)
        for t in range(3):
            for g, ks in enumerate(keys):
                kv.put(ks[t], b"w%d" % t, leader=shard.leader_hint(g))
            shard.step()
        shard.step()
        return keys

    plain = ShardedCluster(cfg, 3, 2)
    kv_p = ShardedKVS(plain, cap=64)
    workload(plain, kv_p)
    keys_before = set(STEP_CACHE)

    topo = ShardedCluster(cfg, 3, 2)
    kv_t = ShardedKVS(topo, cap=64)
    ctl = attach_topology(kv_t, cooldown_steps=2)
    keys_t = workload(topo, kv_t)
    assert set(STEP_CACHE) == keys_before, (
        "attaching topology must add NOTHING to the step cache")
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(np.asarray(plain.last[k]),
                              np.asarray(topo.last[k])), k

    # even a FULL split/merge cycle compiles nothing new: seeding is
    # ordinary stamped client records through the existing programs
    hot = sorted(keys_t[0])
    assert ctl.propose_split(hot[len(hot) // 2], hot[-1] + b"\x00", 1)
    _run_window(topo, ctl)
    assert ctl.transitions_total == 1
    assert set(STEP_CACHE) == keys_before, (
        "a transition window must add NOTHING to the step cache")


# ---------------------------------------------------------------------------
# txn integration: the deterministic TOPOLOGY abort
# ---------------------------------------------------------------------------

def test_inflight_txn_aborts_when_mapping_moves():
    shard, kv, ctl, obs = _cluster(G=2, txn=True)
    coord = attach_coordinator(kv)
    keys = keys_for_groups(kv.router, 4)
    # warm the lane, then open a 2PC txn and move a participant's key
    # range out from under it BEFORE it can decide
    h = kv.transact([("put", keys[0][3], b"w"),
                     ("put", keys[1][3], b"w")])
    for _ in range(6):
        if h.done:
            break
        shard.step()
    assert h.committed

    ka, kb = keys[0][0], keys[1][0]
    h = kv.transact([("put", ka, b"A"), ("put", kb, b"B")])
    kv.router.install_rule(RangeRule(ka, ka + b"\x00", 1))
    for _ in range(8):
        if h.done:
            break
        shard.step()
    assert h.done and not h.committed
    assert h.abort_reason == "topology"
    # no partial writes anywhere, and the dedicated counter ticked
    shard.step()
    assert kv.get(ka) is None and kv.get(kb) is None
    m = shard.obs.metrics.snapshot()["counters"]
    assert m.get("txn_aborted_total{reason=topology}") == 1


# ---------------------------------------------------------------------------
# the load-driven policy loop
# ---------------------------------------------------------------------------

def test_policy_stock_rules_fire_on_transition_with_hysteresis():
    obs = Observability()
    pol = TopologyPolicy(skew_ratio=2.0, cold_ratio=0.5, for_evals=3)
    engine = AlertEngine(obs.metrics, rules=pol.stock_rules())
    fired = []
    engine.add_hook(lambda name, sev: fired.append(name))
    obs.metrics.set("topology_skew", 3.0)
    obs.metrics.set("topology_override_load", 4.0)   # never cold
    engine.evaluate()
    engine.evaluate()
    assert fired == [], "hysteresis: a 2-eval spike must not fire"
    engine.evaluate()
    assert fired == [SPLIT_RULE]
    engine.evaluate()
    assert fired == [SPLIT_RULE], "firing->firing is not a transition"
    # resolve, then re-cross: fires again
    obs.metrics.set("topology_skew", 1.0)
    engine.evaluate()
    obs.metrics.set("topology_skew", 3.0)
    for _ in range(3):
        engine.evaluate()
    assert fired == [SPLIT_RULE, SPLIT_RULE]
    # the cold side fires the merge rule the same way
    obs.metrics.set("topology_override_load", 0.2)
    for _ in range(3):
        engine.evaluate()
    assert fired[-1] == MERGE_RULE


def test_policy_proposes_split_cooldown_and_governor_veto():
    pol = TopologyPolicy(window=8, skew_ratio=1.5, for_evals=2,
                         cooldown_evals=6, min_keys=2)
    shard, kv, ctl, obs = _cluster(G=2, policy=pol)
    keys = keys_for_groups(kv.router, 6)
    # skew all the work onto group 0; observe() rides the finish tail,
    # so plain stepping feeds the policy's trailing window
    for t in range(10):
        for k in keys[0]:
            kv.put(k, b"s%d" % t, leader=shard.leader_hint(0))
        shard.step()
    assert pol.status()["shares"][0] > 0.9
    g = obs.metrics.snapshot()["gauges"]
    assert g.get("topology_skew") > 1.5
    assert g.get("topology_group_share{group=0}") > 0.9

    pol.on_alert(SPLIT_RULE, "warn")        # the engine's hook path
    assert pol.proposals == 1 and ctl.in_window()
    st = ctl.status()
    assert st["direction"] == "split" and st["rule"]["group"] == 1
    _run_window(shard, ctl)
    assert ctl.transitions_total == 1
    assert pol.status()["rules"], "policy must track the rule as its own"

    # policy-level cooldown: a refire inside cooldown_evals proposes
    # nothing even with the controller idle again
    pol.on_alert(SPLIT_RULE, "warn")
    assert pol.proposals == 1

    # governor veto: shed latch up ⟹ no proposal, vetoes counted
    for _ in range(8):                      # walk past the cooldown
        shard.step()
    shard.governor = SimpleNamespace(
        decision=SimpleNamespace(shed=True))
    pol.on_alert(SPLIT_RULE, "warn")
    assert pol.proposals == 1 and pol.vetoes == 1
    shard.governor = None

    # merge only ever touches policy-installed rules: an operator-
    # pinned override is never proposed for merge
    mine = pol.status()["rules"]
    op_rule = RangeRule(b"\x00op", b"\x00oq", 1)
    kv.router.install_rule(op_rule)
    with pol._lock:
        pol._mine = []                      # pretend ours was merged
    pol.on_alert(MERGE_RULE, "warn")
    assert not ctl.in_window() and pol.proposals == 1
    assert mine and mine[0]["group"] == 1


def test_policy_median_range_needs_min_keys():
    pol = TopologyPolicy(min_keys=4)
    shard, kv, ctl, obs = _cluster(G=2, policy=pol)
    keys = keys_for_groups(kv.router, 2)
    for k in keys[0]:
        kv.put(k, b"x", leader=shard.leader_hint(0))
    for _ in range(4):
        shard.step()
    assert pol._median_range(0) is None     # 2 keys < min_keys
    pol.on_alert(SPLIT_RULE, "warn")
    assert pol.proposals == 0 and not ctl.in_window()


# ---------------------------------------------------------------------------
# the Zipf key-shape generator (benchmarks satellite)
# ---------------------------------------------------------------------------

def test_zipf_keys_deterministic_and_skew_scales_with_s():
    a = zipf_keys(500, s=1.2, n_keys=16, seed=3)
    assert a == zipf_keys(500, s=1.2, n_keys=16, seed=3)
    assert a != zipf_keys(500, s=1.2, n_keys=16, seed=4)
    assert len(a) == 500 and all(k.startswith(b"key") for k in a)

    def top_share(s):
        draws = zipf_keys(4000, s=s, n_keys=16, seed=0)
        counts = sorted((draws.count(k) for k in set(draws)),
                        reverse=True)
        return counts[0] / len(draws)
    assert top_share(2.0) > top_share(0.8) > top_share(0.0)
    # s=0 is uniform: the hottest key stays near the fair share
    assert top_share(0.0) < 2.5 / 16


# ---------------------------------------------------------------------------
# chaos: split mid-nemesis, deterministic verdict
# ---------------------------------------------------------------------------

def test_topology_chaos_split_mid_crash_green_and_deterministic():
    from rdma_paxos_tpu.topology.chaos import run_topology_chaos
    v1 = run_topology_chaos(seed=0)
    assert v1["ok"], v1
    assert v1["invariant_violations"] == []
    assert v1["linearizability"]["ok"] and v1["linearizability"]["ops"] > 200
    assert v1["lease_fence"]["ok"] and v1["lease_fence"]["cutovers"] == 2
    assert v1["topology"]["transitions"] == 2
    assert v1["topology"]["abandoned"] == 0
    assert v1["new_leader"] != v1["crashed_leader"]
    v2 = run_topology_chaos(seed=0)
    assert v1 == v2, "same seed must re-derive the identical verdict"
