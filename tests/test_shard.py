"""Sharded multi-group consensus (rdma_paxos_tpu.shard): router
unit/edge/golden contracts plus the subsystem's acceptance properties:

* G=1 ``ShardedCluster`` is BIT-IDENTICAL to ``SimCluster`` on a
  recorded workload (election, traffic, partition + failover, heal) —
  single-group is the G=1 special case, not a parallel code path;
* a homogeneous G=4 cluster runs every group through exactly ONE
  compiled step program (shared runtime cache; no per-group compiles),
  and ``prewarm()`` tiers are shared across clusters and group counts;
* crashing ONE group's leader leaves the other groups' commit
  frontiers strictly advancing (fault isolation), with the existing
  I1–I5 invariants checked per group (shard nemesis);
* routed KVS sessions keep per-group dedup sequence numbers and
  survive a single-group leader failover with exactly-once applies;
* per-group observability: ``...{group=g}`` metric series, the
  ``(group, term, index)`` span correlation key, and the router
  serialized into the health document.
"""

import json
import os

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs import Observability
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.shard import (
    KeyRouter, RangeRule, ShardedCluster, ShardedKVS)
from rdma_paxos_tpu.shard.chaos import ShardNemesisRunner
from rdma_paxos_tpu.shard.router import canon_key, ring_hash

CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)
GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "router_map.json")


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

def test_router_edge_cases():
    r = KeyRouter(4)
    # empty key is a legal key with a stable home
    g_empty = r.group_of(b"")
    assert 0 <= g_empty < 4
    assert r.group_of("") == g_empty
    # unicode str keys canonicalize to their UTF-8 bytes
    assert r.group_of("ключ") == r.group_of("ключ".encode("utf-8"))
    assert r.group_of("鍵") == r.group_of("鍵".encode("utf-8"))
    # long keys route fine and deterministically
    long_key = b"x" * 65536
    assert r.group_of(long_key) == r.group_of(bytearray(long_key))
    # non-key types are rejected loudly
    with pytest.raises(TypeError):
        r.group_of(42)
    # determinism across independently built routers (same params)
    r2 = KeyRouter(4)
    for i in range(200):
        k = b"edge%d" % i
        assert r.group_of(k) == r2.group_of(k)


def test_router_balance_is_reasonable():
    r = KeyRouter(4)
    counts = [0] * 4
    for i in range(4000):
        counts[r.group_of(b"key%d" % i)] += 1
    # hash-ring balance: no group starved or hot beyond ~2x fair share
    assert min(counts) > 400 and max(counts) < 2000, counts


def test_router_range_override_precedence():
    # narrow rule listed first wins over the broad rule and the ring
    r = KeyRouter(4, overrides=[("user:vip", "user:viq", 3),
                                ("user:", "user;", 1)])
    assert r.group_of(b"user:vip42") == 3      # narrow first match
    assert r.group_of(b"user:alice") == 1      # broad rule
    assert r.group_of(b"user:vio") == 1        # below the narrow lo
    # outside every override: ring routing, consistent with a
    # no-override router (overrides never perturb the ring)
    bare = KeyRouter(4)
    assert r.group_of(b"other:key") == bare.group_of(b"other:key")
    # hi=None is unbounded
    r2 = KeyRouter(4, overrides=[RangeRule(b"zz", None, 2)])
    assert r2.group_of(b"zzz-anything") == 2
    # invalid rules are rejected at construction
    with pytest.raises(ValueError, match="empty range"):
        KeyRouter(4, overrides=[("b", "a", 0)])
    with pytest.raises(ValueError, match="out of range"):
        KeyRouter(4, overrides=[("a", "b", 7)])


def test_router_golden_mapping_stable_across_restarts():
    """The golden file pins the exact mapping a previous process
    computed — a rebuilt router (fresh process, fresh ring) must agree
    key for key, and its serialized form must checksum-match."""
    with open(GOLDEN) as f:
        doc = json.load(f)
    router = KeyRouter.from_dict(doc["router"])
    rebuilt = KeyRouter(doc["router"]["n_groups"],
                        vnodes=doc["router"]["vnodes"],
                        overrides=[RangeRule.from_dict(o)
                                   for o in doc["router"]["overrides"]])
    assert (router.to_dict()["ring_checksum"]
            == doc["router"]["ring_checksum"])
    for key, want in doc["mapping"].items():
        assert router.group_of(key) == want, key
        assert rebuilt.group_of(key) == want, key


def test_router_serialization_roundtrip_and_tamper_guard():
    r = KeyRouter(8, overrides=[("a", "b", 4)])
    d = r.to_dict()
    r2 = KeyRouter.from_dict(d)
    for i in range(100):
        assert r.group_of(b"rt%d" % i) == r2.group_of(b"rt%d" % i)
    bad = dict(d, ring_checksum=d["ring_checksum"] ^ 1)
    with pytest.raises(ValueError, match="checksum mismatch"):
        KeyRouter.from_dict(bad)
    with pytest.raises(ValueError, match="unknown router"):
        KeyRouter.from_dict(dict(d, hash="md5"))


def test_ring_hash_is_pure_bytes_arithmetic():
    # restart/process-independence reduces to this: the hash is a pure
    # function of the bytes with pinned constants
    assert ring_hash(b"") == ring_hash(b"")
    assert canon_key("k") == b"k"
    assert ring_hash(b"group:0:vnode:0") != ring_hash(b"group:1:vnode:0")


# ---------------------------------------------------------------------------
# G=1 ≡ SimCluster (bit-identical on a recorded workload)
# ---------------------------------------------------------------------------

def _recorded_workload():
    """(events, timeouts) per step: elections, traffic bursts, a
    partition with failover, heal, post-heal traffic."""
    steps = []
    steps.append((["tmo0"], []))
    for t in range(1, 30):
        ev = []
        tmo = []
        if t in (3, 4, 7, 12, 20):
            ev += [("sub", 0, b"p%d-%d" % (t, i)) for i in range(5)]
        if t == 9:
            ev.append(("part", [[0], [1, 2]]))
            tmo = [1]
        if t == 15:
            ev.append(("heal",))
        if t in (16, 21):
            ev += [("sub", 1, b"q%d-%d" % (t, i)) for i in range(3)]
        steps.append((ev, tmo))
    return steps


def test_g1_bit_identical_to_simcluster():
    sim = SimCluster(CFG, 3)
    sh = ShardedCluster(CFG, 3, 1)
    keys = ("term", "role", "leader_id", "voted_term", "voted_for",
            "head", "apply", "commit", "end", "hb_seen",
            "became_leader", "acked", "accepted", "peer_acked",
            "leadership_verified", "rebase_delta")
    for ev, tmo in _recorded_workload():
        if ev == ["tmo0"]:
            ev, tmo = [], [0]
        for e in ev:
            if e[0] == "sub":
                sim.submit(e[1], e[2])
                sh.submit(0, e[1], e[2])
            elif e[0] == "part":
                sim.partition(e[1])
                sh.partition(0, e[1])
            elif e[0] == "heal":
                sim.heal()
                sh.heal()
        a = sim.step(timeouts=tmo)
        b = sh.step(timeouts={0: tmo} if tmo else ())
        for k in keys:
            assert np.array_equal(a[k], np.asarray(b[k][0])), k
    assert sim.replayed == sh.replayed[0]
    assert (sim.applied == sh.applied[0]).all()
    assert sim.leader() == sh.leader(0)


# ---------------------------------------------------------------------------
# compile-cache dedup: one program for a homogeneous cluster
# ---------------------------------------------------------------------------

def test_single_compile_for_homogeneous_g4():
    """G groups sharing one LogConfig share ONE compiled step: the
    whole G=4 workload — elections in every group plus committed
    traffic — runs through exactly one program, and the shared cache
    gains exactly one group-step entry."""
    cfg = LogConfig(n_slots=64, slot_bytes=64, window_slots=16,
                    batch_slots=8)
    before = set(STEP_CACHE)
    sc = ShardedCluster(cfg, 3, 4, stable_fast_path=False)
    sc.place_leaders()
    for g in range(4):
        for i in range(6):
            sc.submit(g, sc.leader(g), b"v%d" % i)
    for _ in range(3):
        sc.step()
    assert all(sc.last["commit"][g].max() >= 6 for g in range(4))
    assert len(sc.programs_used) == 1, sc.programs_used
    added = set(STEP_CACHE) - before
    group_steps = [k for k in added if "group" in k]
    assert len(group_steps) == 1, group_steps
    # a second homogeneous cluster — even a DIFFERENT group count —
    # adds no cache entries: the group-step callable is batch-size-
    # polymorphic, so the cache cannot proliferate per G
    now = set(STEP_CACHE)
    sc2 = ShardedCluster(cfg, 3, 8, stable_fast_path=False)
    sc2.place_leaders()
    sc2.step()
    assert set(STEP_CACHE) == now


def test_prewarm_tiers_shared_across_groups_and_clusters():
    cfg = LogConfig(n_slots=64, slot_bytes=64, window_slots=16,
                    batch_slots=8)
    sc = ShardedCluster(cfg, 3, 2)
    sc.prewarm(tiers=(2,))
    warmed = set(STEP_CACHE)
    # same-shape cluster: everything already compiled
    sc2 = ShardedCluster(cfg, 3, 2)
    sc2.prewarm(tiers=(2,))
    assert set(STEP_CACHE) == warmed
    # different group count: SAME cache entries (shared tiers)
    sc3 = ShardedCluster(cfg, 3, 4)
    sc3.prewarm(tiers=(2,))
    assert set(STEP_CACHE) == warmed


def test_step_burst_commits_backlog_in_one_dispatch():
    sc = ShardedCluster(CFG, 3, 2)
    sc.place_leaders()
    for g in range(2):
        for i in range(40):                 # > 2 batches per group
            sc.submit(g, sc.leader(g), b"b%d-%d" % (g, i))
    d0 = sc.dispatches
    res = sc.step_burst()
    assert sc.dispatches == d0 + 1          # K fused steps, ONE dispatch
    for g in range(2):
        assert res["commit"][g].max() >= 40
        got = [p for (_t, _c, _r, p) in sc.replayed[g][0]]
        assert got == [b"b%d-%d" % (g, i) for i in range(40)]


# ---------------------------------------------------------------------------
# chaos smoke: single-group leader crash is contained
# ---------------------------------------------------------------------------

def test_fault_isolation_one_group_leader_crash():
    """Shard nemesis (chaos-subsystem primitives, I1–I5 per group):
    crash group 0's leader mid-run — the other three groups' commit
    frontiers must keep STRICTLY advancing through the outage, and the
    victim group must recover under a new leader."""
    v = ShardNemesisRunner(n_replicas=3, n_groups=4, seed=0,
                           steps=40, crash_step=15).run()
    assert v["ok"], v
    assert not v["invariant_violations"]
    f = v["frontiers"]
    for g in range(1, 4):
        assert f["at_heal"][g] > f["at_crash"][g], (g, f)
    assert v["target_recovered"]
    assert v["new_leader"] != v["crashed_leader"]
    # determinism: same seed, same verdict (chaos contract)
    v2 = ShardNemesisRunner(n_replicas=3, n_groups=4, seed=0,
                            steps=40, crash_step=15).run()
    assert v2 == v


def test_partition_is_per_group():
    sc = ShardedCluster(CFG, 3, 2)
    sc.place_leaders()
    sc.partition(0, [[0], [1, 2]])
    assert not sc.peer_mask[0].all()
    assert sc.peer_mask[1].all()            # group 1 untouched
    sc.heal(0)
    assert sc.peer_mask.all()


# ---------------------------------------------------------------------------
# sharded KVS: routing, per-group sessions, failover dedup
# ---------------------------------------------------------------------------

def test_sharded_kvs_routes_and_reads():
    sc = ShardedCluster(CFG, 3, 4)
    sc.place_leaders()
    kv = ShardedKVS(sc, cap=256)
    data = {b"city%d" % i: b"v%d" % i for i in range(24)}
    for k, v in data.items():
        kv.put(k, v)
    for _ in range(3):
        sc.step()
    groups_hit = set()
    for k, v in data.items():
        assert kv.get(k, linearizable=True) == v
        groups_hit.add(kv.group_of(k))
    assert len(groups_hit) > 1              # keys actually spread
    kv.remove(next(iter(data)))
    sc.step()
    sc.step()
    assert kv.get(next(iter(data))) is None


def test_sharded_session_per_group_seqnos_and_dedup():
    sc = ShardedCluster(CFG, 3, 4)
    sc.place_leaders()
    kv = ShardedKVS(sc, cap=256)
    sess = kv.session(7)
    placed = {}
    for i in range(12):
        k = b"s%d" % i
        g, rid = sess.put(k, b"val%d" % i)
        placed.setdefault(g, []).append(rid)
    # per-group dedup sequence numbers: each group's stream is 1..n
    for g, rids in placed.items():
        assert rids == list(range(1, len(rids) + 1)), (g, rids)
    for _ in range(3):
        sc.step()
    # a network-duplicated retransmit applies exactly once
    k0 = b"s0"
    g0 = kv.group_of(k0)
    sess.retransmit_put(k0, b"val0", req_id=placed[g0][0]
                        if placed[g0] else 1)
    sc.step()
    sc.step()
    lead = sc.leader_hint(g0)
    kv.groups[g0]._fold(lead)
    assert kv.groups[g0].deduped[lead] >= 1
    assert kv.get(k0, linearizable=True) == b"val0"


def test_direct_puts_share_the_session_conn_namespace():
    """A direct stamped ShardedKVS.put and a ShardedSession with the
    same external client id hit the SAME per-group dedup stream — a
    direct put can never alias a DIFFERENT session's high-water mark
    (the two submission paths use one conn_for mapping)."""
    sc = ShardedCluster(CFG, 3, 4)
    sc.place_leaders()
    kv = ShardedKVS(sc, cap=256)
    sess = kv.session(2)
    k = b"alias-probe"
    g = kv.group_of(k)
    assert kv.conn_for(2, g) == sess.conn_for(g)
    # client 5's raw external id can no longer collide with client 2's
    # namespaced conn in any group (injective mapping both paths)
    assert kv.conn_for(5, g) != sess.conn_for(g) or 5 * 4 + g == 2 * 4 + g
    _, rid = sess.put(k, b"v1")
    for _ in range(3):
        sc.step()
    # a direct put as the SAME client with the same req_id is deduped
    kv.put(k, b"v1", client_id=2, req_id=rid)
    sc.step()
    sc.step()
    lead = sc.leader_hint(g)
    kv.groups[g]._fold(lead)
    assert kv.groups[g].deduped[lead] >= 1
    assert kv.get(k, linearizable=True) == b"v1"
    # unstamped puts stay dedup-exempt (conn 0 is preserved)
    assert kv.conn_for(0, g) == 0


def test_sharded_session_failover_in_one_group_only():
    sc = ShardedCluster(CFG, 3, 4)
    sc.place_leaders()
    kv = ShardedKVS(sc, cap=256)
    sess = kv.session(3)
    # seed every group with one committed write
    seeds = {}
    for i in range(40):
        k = b"f%d" % i
        g = kv.group_of(k)
        if g not in seeds:
            seeds[g] = k
            sess.put(k, b"seed")
        if len(seeds) == 4:
            break
    for _ in range(3):
        sc.step()
    # crash group g0's leader; an in-flight put must survive via
    # retransmit to the new leader, deduped exactly-once
    g0 = kv.group_of(b"hotkey")
    old = sc.leader(g0)
    _, rid = sess.put(b"hotkey", b"v1")
    others = [r for r in range(3) if r != old]
    sc.partition(g0, [[old], others])
    sc.step(timeouts={g0: [others[0]]})
    sc.step()
    assert sc.leader_hint(g0) == others[0]
    sess.retransmit_put(b"hotkey", b"v1", rid)
    for _ in range(3):
        sc.step()
    assert kv.get(b"hotkey", linearizable=True) == b"v1"
    # every OTHER group kept its leader and its data
    for g, k in seeds.items():
        if g == g0:
            continue
        assert sc.last["role"][g].tolist().count(int(Role.LEADER)) == 1
        assert kv.get(k, linearizable=True) == b"seed"


# ---------------------------------------------------------------------------
# observability: per-group metrics, span keys, health router
# ---------------------------------------------------------------------------

def test_per_group_metric_series():
    sc = ShardedCluster(CFG, 3, 2)
    sc.obs = Observability()
    sc.place_leaders()
    for g in range(2):
        sc.submit(g, sc.leader(g), b"m")
    sc.step()
    sc.step()
    snap = sc.obs.metrics.snapshot()
    for g in range(2):
        assert f"shard_commit{{group={g}}}" in snap["gauges"]
        assert f"shard_term{{group={g}}}" in snap["gauges"]
        assert f"shard_leader{{group={g}}}" in snap["gauges"]
        assert (snap["counters"]
                [f"shard_committed_entries_total{{group={g}}}"] >= 1)


def test_span_correlation_keyed_by_group_term_index():
    sc = ShardedCluster(CFG, 3, 2)
    obs = Observability()
    obs.spans.set_sample_every(1)
    sc.obs = obs
    sc.place_leaders()
    kv = ShardedKVS(sc, cap=256)
    sess = kv.session(1)
    # one write per group (find a key for each)
    done = set()
    i = 0
    while len(done) < 2:
        k = b"sp%d" % i
        g = kv.group_of(k)
        if g not in done:
            sess.put(k, b"x")
            done.add(g)
        i += 1
    for _ in range(3):
        sc.step()
    dump = obs.spans.dump()
    stamped = [s for s in dump["spans"] if s.get("term") is not None]
    assert stamped, dump
    # every stamped span carries its group, and the (group, term,
    # index) key resolves while same (term, index) in the OTHER group
    # does not collide
    groups_seen = {s["group"] for s in stamped}
    assert groups_seen <= {0, 1} and groups_seen
    for s in stamped:
        key = obs.spans.key_for(s["term"], s["index"], group=s["group"])
        other = obs.spans.key_for(s["term"], s["index"],
                                  group=1 - s["group"])
        if s["status"] == "open":
            assert key == (s["conn"], s["req"])
            assert other != key
        # ALL of a span's replica ids live in ONE namespace (g*R + r):
        # the session's submit origin must match the append leader's
        # namespaced id, and every event replica must belong to the
        # span's group's track range
        assert s["origin"] == s["leader"]
        assert s["origin"] // sc.R == s["group"]
        for phase, rep, _ts in s["events"]:
            if rep >= 0:
                assert rep // sc.R == s["group"], (phase, rep, s)


def test_health_document_serializes_router():
    sc = ShardedCluster(CFG, 3, 2)
    sc.place_leaders()
    doc = sc.health()
    assert doc["n_groups"] == 2
    assert len(doc["groups"]) == 2
    for g, snap in enumerate(doc["groups"]):
        assert snap["group"] == g
        assert snap["leader"] == sc.leader_hint(g)
        assert len(snap["commit"]) == 3
        assert "anchor" in snap and "ts_monotonic" in snap
    # the routing table rides the health doc and reconstructs exactly
    r2 = KeyRouter.from_dict(doc["router"])
    for i in range(50):
        assert r2.group_of(b"h%d" % i) == sc.router.group_of(b"h%d" % i)
    # the whole document is JSON-serializable (operator contract)
    json.dumps(doc)
