"""Snapshot-based recovery (§3.5): a replica pruned past (or fresh) cannot
catch up from the log and recovers via snapshot install + ordinary window
replication from the determinant onward."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.snapshot import install_snapshot, take_snapshot
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=16, slot_bytes=32, window_slots=8, batch_slots=4)


def test_pruned_past_laggard_is_stuck_then_recovers():
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.partition([[0, 1], [2]])
    # push far beyond ring capacity: pressure-pruning advances head past
    # the laggard's end
    for i in range(40):
        c.submit(0, b"x%02d" % i)
        c.step()
    c.step()
    assert int(c.last["head"][0]) > int(c.last["end"][2])
    c.heal()
    for _ in range(4):
        res = c.step()
    # stuck: the window cannot reach below the leader's head (gap reject)
    assert int(res["end"][2]) < int(res["end"][0])

    # --- snapshot recovery: donor dumps, joiner installs ---
    snap = take_snapshot(c.state, donor=1)
    assert snap.index > 0 and snap.term > 0
    c.state = install_snapshot(c.state, 2, snap)
    c.applied[2] = snap.index       # host restored the event history blob
    for _ in range(3):
        res = c.step()
    assert int(res["end"][2]) == int(res["end"][0])
    res = c.step()
    assert int(res["commit"][2]) == int(res["commit"][0])
    # post-recovery entries replay on the recovered replica
    c.submit(0, b"fresh")
    c.step()
    c.step()
    assert [p for (_, _, _, p) in c.replayed[2]][-1] == b"fresh"


def test_fresh_learner_bootstraps_via_snapshot():
    """A brand-new replica (empty log, beyond the group) installs a donor
    snapshot and follows as a learner — the joiner flow before its CONFIG
    entry admits it to the group."""
    c = SimCluster(CFG, 4, group_size=3)
    c.run_until_elected(0)
    for i in range(30):             # scroll the ring well past capacity
        c.submit(0, b"h%02d" % i)
        c.step()
    c.step()
    assert int(c.last["head"][0]) > 0

    snap = take_snapshot(c.state, donor=0)
    c.state = install_snapshot(c.state, 3, snap)
    c.applied[3] = snap.index
    for _ in range(3):
        res = c.step()
    assert int(res["end"][3]) == int(res["end"][0])
    c.submit(0, b"seen-by-learner")
    c.step()
    c.step()
    assert [p for (_, _, _, p) in c.replayed[3]][-1] == b"seen-by-learner"


def test_snapshot_preserves_membership_config():
    from rdma_paxos_tpu.consensus.membership import MembershipManager
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    mm.change(0, 0b11111)
    snap = take_snapshot(c.state, donor=0)
    assert snap.bitmask_new == 0b11111
    c.state = install_snapshot(c.state, 6, snap)
    c.applied[6] = snap.index
    assert mm.current(6)["bitmask_new"] == 0b11111
