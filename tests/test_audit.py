"""Silent-divergence auditing (rdma_paxos_tpu.obs.audit) + SLO
alerting (rdma_paxos_tpu.obs.alerts): the on-device digest chain, the
cluster audit ledger, flight recorder, alert rules, and the
integration contracts:

* clean runs (elections, traffic, partitions with skewed frontiers,
  fused bursts, sharded groups) produce ZERO divergence findings;
* injected single-bit corruption of a replica's committed log memory
  (sim and sharded engines) is detected and localized to its exact
  first ``(term, index)`` within a few steps, deterministically;
* ``audit=False`` compiled-step cache keys are bit-identical to the
  pre-audit set (the audit variants carry a distinct marker);
* no obs call site is reachable from jitted modules — the scan covers
  ``obs/audit.py`` explicitly;
* the driver exports audit + alert state in ``health()``, fires the
  digest-mismatch page, and dumps a flight-recorder audit artifact;
* per-replica dumps merge through the ``obs.audit`` CLI into a
  first-divergence report;
* the sharded engine gains StepPhaseProfiler hooks (apply histograms
  tagged ``{group=g}``) and byte-identical ``collect_frames`` parity;
* chaos runners audit at 100%: clean seeds verdict zero findings, and
  a mid-run corruption fails the run with audit + flight evidence
  embedded in the reproducer artifact.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.log import Log
from rdma_paxos_tpu.obs import Observability
from rdma_paxos_tpu.obs import audit as audit_mod
from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
from rdma_paxos_tpu.obs.audit import (
    AuditLedger, FlightRecorder, merge_dumps, write_audit_artifact)
from rdma_paxos_tpu.obs.metrics import MetricsRegistry
from rdma_paxos_tpu.obs.spans import StepPhaseProfiler
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.shard.cluster import ShardedCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)  # manual


def _corrupt(cluster, replica, g_idx, *, group=None, word=0):
    """Flip one payload bit of the slot holding global index ``g_idx``
    in device log memory — the silent fault the audit exists for."""
    slot = g_idx & (cluster.cfg.n_slots - 1)
    buf = cluster.state.log.buf
    if group is None:
        buf = buf.at[replica, slot, word].add(1)
    else:
        buf = buf.at[group, replica, slot, word].add(1)
    cluster.state = dataclasses.replace(cluster.state, log=Log(buf=buf))


# ---------------------------------------------------------------------------
# ledger unit
# ---------------------------------------------------------------------------

def test_ledger_cross_replica_and_self_mismatch():
    led = AuditLedger(3)
    led.record_window(0, 10, [111, 222, 333], [1, 1, 2], 13)
    led.record_window(1, 10, [111, 222, 333], [1, 1, 2], 13)
    assert led.findings == []
    # replica 2 disagrees at index 11 on its FIRST report
    led.record_window(2, 10, [111, 999, 333], [1, 1, 2], 13)
    f = led.first_divergence()
    assert f["index"] == 11 and f["mode"] == "replica"
    assert f["got_replicas"] == [2] and f["expected_digest"] == 222
    assert sorted(f["expected_replicas"]) == [0, 1]
    # the stored mask means "replicas holding THIS digest": the
    # divergent replica must NOT be added to it (dump/merge-based
    # repair would otherwise quarantine the wrong replica set)
    assert led.dump()["groups"][0]["indices"]["11"][2] == 0b011
    # replica 0 RE-reports index 12 with a different digest (its
    # memory changed after commit): self-mismatch at the exact index
    led.record_window(0, 11, [222, 777], [1, 2], 13)
    selfs = [x for x in led.findings if x["mode"] == "self"]
    assert len(selfs) == 1 and selfs[0]["index"] == 12
    assert selfs[0]["got_replicas"] == [0]
    # dedup: re-reporting the flagged indices adds no new findings
    n = len(led.findings)
    led.record_window(0, 11, [222, 777], [1, 2], 13)
    assert len(led.findings) == n
    s = led.summary()
    assert s["findings"] == n and s["first"]["index"] == 11


def test_ledger_skew_and_regression_tolerated():
    """Replicas reporting the same indices at different times (frontier
    skew) and a recovered replica re-reporting a regressed window must
    not false-positive."""
    led = AuditLedger(2)
    led.record_window(0, 0, [5, 6, 7, 8], [1, 1, 1, 1], 4)
    # replica 1 lags, then catches up in two smaller windows
    led.record_window(1, 0, [5, 6], [1, 1], 2)
    led.record_window(1, 1, [6, 7, 8], [1, 1, 1], 4)
    # replica 0 crash-recovers: its window REGRESSES, same bytes
    led.record_window(0, 1, [6, 7], [1, 1], 3)
    assert led.findings == []
    assert led.summary()["indices_checked"] >= 8


def test_ledger_bounded_retention():
    led = AuditLedger(1, history=16)
    for start in range(0, 512, 4):
        led.record_window(0, start, [start] * 4, [1] * 4, start + 4)
    assert led.findings == []
    assert led.summary()["tracked"] <= 2 * 16 + 4


def test_merge_dumps_cross_host_divergence():
    a, b = AuditLedger(3), AuditLedger(3)
    a.record_window(0, 5, [10, 11, 12], [1, 1, 1], 8)
    b.record_window(1, 5, [10, 99, 12], [1, 1, 1], 8)
    rep = merge_dumps([a.dump(), b.dump()])
    assert rep["first"]["index"] == 6 and rep["first"]["mode"] == "merge"
    assert rep["indices"] == 3
    clean = merge_dumps([a.dump(), a.dump()])
    assert clean["findings"] == [] and clean["first"] is None


# ---------------------------------------------------------------------------
# alert engine unit
# ---------------------------------------------------------------------------

def test_alert_engine_rules_fire_and_resolve():
    reg = MetricsRegistry()
    eng = AlertEngine(reg, rules=default_rules(), trace=None)
    assert eng.evaluate() == {"fired": [], "resolved": []}

    # digest mismatch pages immediately (counter_nonzero, no hysteresis)
    reg.inc("audit_divergence_total", group=0)
    out = eng.evaluate()
    assert out["fired"] == ["digest_divergence"]
    assert eng.firing(severity="page") == ["digest_divergence"]
    assert reg.get("alert_firing", alert="digest_divergence") == 1

    # leaderless needs 5 consecutive evals
    reg.set("cluster_leader", -1)
    for _ in range(4):
        assert "leaderless" not in eng.evaluate()["fired"]
    assert "leaderless" in eng.evaluate()["fired"]
    reg.set("cluster_leader", 1)
    assert "leaderless" in eng.evaluate()["resolved"]

    # commit-latency p99 ceiling (0.5s default), for_evals=2
    for _ in range(200):
        reg.observe("commit_latency_seconds", 2.0, replica=0)
    eng.evaluate()
    out = eng.evaluate()
    assert "commit_latency_p99" in out["fired"]
    st = eng.state()["commit_latency_p99"]
    assert st["firing"] and st["value"] > 0.5

    # rebase_stalled rate: fires on a tick, resolves when quiet
    reg.inc("rebase_stalled")
    assert "rebase_stalled" in eng.evaluate()["fired"]
    assert "rebase_stalled" in eng.evaluate()["resolved"]


def test_alert_engine_rejects_bad_rules():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="unknown kind"):
        AlertEngine(reg, rules=[dict(name="x", metric="m", kind="nope")])
    with pytest.raises(ValueError, match="duplicate"):
        AlertEngine(reg, rules=[
            dict(name="x", metric="m", kind="counter_nonzero"),
            dict(name="x", metric="m", kind="counter_nonzero")])
    # kind-specific completeness fails at CONSTRUCTION, never as a
    # KeyError inside the driver poll loop
    with pytest.raises(ValueError, match="gauge_cmp"):
        AlertEngine(reg, rules=[dict(name="x", metric="m",
                                     kind="gauge_cmp")])
    with pytest.raises(ValueError, match="bad op"):
        AlertEngine(reg, rules=[dict(name="x", metric="m",
                                     kind="hist_quantile",
                                     threshold=1.0, op="=>")])
    with pytest.raises(ValueError, match="threshold"):
        AlertEngine(reg, rules=[dict(name="x", metric="m",
                                     kind="hist_quantile")])


def test_ledger_findings_capped():
    led = AuditLedger(2)
    led.MAX_FINDINGS = 4
    led.record_window(0, 0, list(range(100, 110)), [1] * 10, 10)
    led.record_window(1, 0, list(range(200, 210)), [1] * 10, 10)
    assert len(led.findings) == 4
    s = led.summary()
    assert s["findings"] == 4 and s["findings_dropped"] == 6
    assert s["first"]["index"] == 0


# ---------------------------------------------------------------------------
# sim integration: clean runs, exact-index detection, determinism
# ---------------------------------------------------------------------------

def _run_traffic(c, leader, n=6, steps=4, tag=b"v"):
    for i in range(n):
        c.submit(leader, tag + b"%d" % i)
    for _ in range(steps):
        c.step()


def test_sim_clean_run_with_partition_no_findings():
    c = SimCluster(CFG, 3, audit=True)
    c.run_until_elected(0)
    _run_traffic(c, 0)
    # partition skews frontiers (the minority replica stalls), then
    # heals and catches up — per-index alignment must absorb the skew
    c.partition([[0, 1], [2]])
    _run_traffic(c, 0, n=4)
    c.heal()
    _run_traffic(c, 0, n=4, steps=6)
    assert c.auditor.findings == []
    assert c.auditor.indices_checked > 0
    assert int(c.last["commit"].min()) >= 14


def test_sim_burst_audit_tiles_all_entries():
    c = SimCluster(CFG, 3, audit=True)
    c.run_until_elected(0)
    c.step()
    for i in range(20):                  # > 2 batches -> multi-step burst
        c.submit(0, b"b%d" % i)
    c.step_burst()
    assert c.auditor.findings == []
    # every committed index was digested at least once (no gaps)
    commit = int(c.last["commit"].min())
    tracked = set(c.auditor._idx[0])
    assert set(range(commit)) <= tracked


def _detect_corruption(seed_steps=3):
    c = SimCluster(CFG, 3, audit=True)
    c.run_until_elected(0)
    _run_traffic(c, 0)
    target = int(c.last["commit"].min()) - 1
    _corrupt(c, 2, target)
    for _ in range(seed_steps):
        c.step()
    return target, c.auditor.first_divergence()


def test_sim_corruption_detected_at_exact_index_deterministically():
    target1, f1 = _detect_corruption()
    assert f1 is not None, "corruption not detected"
    assert f1["index"] == target1
    assert f1["got_replicas"] == [2]
    assert f1["term"] >= 1
    assert f1["got_digest"] != f1["expected_digest"]
    # deterministic same-script verdict (the acceptance contract)
    target2, f2 = _detect_corruption()
    assert (target2, f2) == (target1, f1)


def test_sharded_corruption_localized_to_group():
    sc = ShardedCluster(CFG, 3, 2, audit=True)
    sc.place_leaders()
    for g in range(2):
        for i in range(5):
            sc.submit(g, sc.leader(g), b"g%d-%d" % (g, i))
    for _ in range(4):
        sc.step()
    assert sc.auditor.findings == []
    target = int(sc.last["commit"][1].min()) - 1
    _corrupt(sc, 1, target, group=1)
    for _ in range(3):
        sc.step()
    f = sc.auditor.first_divergence()
    assert f is not None and f["group"] == 1 and f["index"] == target
    assert f["got_replicas"] == [1]
    # fault isolation: the untouched group has zero findings
    assert sc.auditor.first_divergence(group=0) is None
    assert sc.health()["audit"]["findings"] >= 1


# ---------------------------------------------------------------------------
# cache-key guard: audit=False programs unchanged, audit variants marked
# ---------------------------------------------------------------------------

def test_audit_off_cache_keys_bit_identical():
    # a geometry no other test uses: this guard reasons about which
    # keys THIS test's clusters add to the shared cache
    cfg = LogConfig(n_slots=32, slot_bytes=32, window_slots=8,
                    batch_slots=4)
    plain = SimCluster(cfg, 3)
    plain.run_until_elected(0)
    plain.submit(0, b"x")
    plain.step()
    keys_before = set(STEP_CACHE)

    aud = SimCluster(cfg, 3, audit=True)
    aud.run_until_elected(0)
    aud.submit(0, b"y")
    aud.step()
    added = set(STEP_CACHE) - keys_before
    assert added and all("audit" in k for k in added), (
        "audit variants must carry the 'audit' cache-key marker")
    assert keys_before <= set(STEP_CACHE)

    # a fresh audit=False cluster adds NOTHING: default keys (and
    # therefore default programs) are bit-identical to the pre-audit
    # world
    after_audit = set(STEP_CACHE)
    plain2 = SimCluster(cfg, 3)
    plain2.run_until_elected(0)
    plain2.submit(0, b"z")
    plain2.step()
    assert set(STEP_CACHE) == after_audit


def test_audit_off_outputs_bit_identical():
    """The audit=False step computes the exact same outputs as before
    the audit existed (the extra StepOutput fields are None — no
    pytree leaves)."""
    a = SimCluster(CFG, 3)
    b = SimCluster(CFG, 3, audit=True)
    for c in (a, b):
        c.run_until_elected(0)
        _run_traffic(c, 0, n=4, steps=3)
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(a.last[k], b.last[k]), k
    assert "audit_digest" not in a.last and "audit_digest" in b.last


def test_jit_safety_scan_covers_audit_module():
    """consensus/step.py, ops/*, and parallel/mesh.py run inside
    jit/shard_map: no host-side obs symbol (including obs.audit /
    obs.alerts) may be reachable there — the digest chain is pure
    jnp. Enforced by the graftlint ``jit-purity`` pass (the single
    source of truth replacing this test's former inline regex copy;
    ``analysis/purity.py:SCAN_PATTERNS`` carries the deduped union)."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    assert_jit_purity()


# ---------------------------------------------------------------------------
# driver integration: health export, page alert, artifact dump
# ---------------------------------------------------------------------------

def test_driver_audit_health_alert_and_artifact():
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, audit=True)
    try:
        d.runtimes[0].timer._deadline = 0.0
        d.step()
        assert d.leader() == 0
        for _ in range(3):
            d.cluster.submit(0, b"w")
            d.step()
        h = d.health()
        assert h["audit"]["findings"] == 0
        assert h["audit"]["indices_checked"] > 0
        assert h["alerts"]["digest_divergence"]["firing"] is False
        assert d.evaluate_alerts()["fired"] == []

        target = int(d.cluster.last["commit"].min()) - 1
        _corrupt(d.cluster, 1, target)
        for _ in range(3):
            d.step()
        d.evaluate_alerts()
        assert "digest_divergence" in d.alerts.firing(severity="page")
        h = d.health()
        assert h["audit"]["first"]["index"] == target
        assert h["audit_artifact"] and os.path.exists(h["audit_artifact"])
        doc = json.load(open(h["audit_artifact"]))
        assert doc["kind"] == "audit_artifact"
        assert doc["audit"]["findings"][0]["index"] == target
        assert doc["flight"]["steps"], "flight ring missing"
        # the dumped artifact replays to the same verdict via the CLI
        assert audit_mod.main(["report", h["audit_artifact"]]) == 1
    finally:
        d.stop()
        if d.audit_artifact and os.path.exists(d.audit_artifact):
            os.unlink(d.audit_artifact)


# ---------------------------------------------------------------------------
# flight recorder + CLI
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_bounded_and_replayable_dump(tmp_path):
    c = SimCluster(CFG, 3, audit=True, flight_capacity=4)
    c.run_until_elected(0)
    for i in range(8):
        c.submit(0, b"f%d" % i)
        c.step()
    assert len(c.flight) == 4                    # bounded ring
    dump = c.flight.dump()
    assert dump["capacity"] == 4 and len(dump["steps"]) == 4
    entry = dump["steps"][-1]
    assert set(entry) >= {"step", "inputs", "outputs", "digests",
                          "applied", "rebased_total"}
    # digest heads in the ring re-derive the ledger's view: the dump is
    # self-contained evidence, fully JSON-plain (arrays and payload
    # bytes were converted at dump time)
    assert entry["digests"]["commit"] == entry["outputs"]["commit"]
    assert len(entry["digests"]["window"]) == 3
    for batch in entry["inputs"]:
        for (_t, _c, _q, payload) in batch:
            bytes.fromhex(payload)       # hex-converted at dump
    path = write_audit_artifact(str(tmp_path / "art.json"),
                                reason="test", ledger=c.auditor,
                                flight=c.flight)
    doc = json.load(open(path))
    assert doc["flight"]["steps"] and doc["audit"]["groups"]
    json.dumps(doc)                              # fully serializable


def test_cli_merge_and_report_per_replica_dumps(tmp_path, capsys):
    a, b = AuditLedger(3), AuditLedger(3)
    a.record_window(0, 0, [7, 8, 9], [1, 1, 1], 3)
    b.record_window(2, 0, [7, 8, 6], [1, 1, 1], 3)
    fa = tmp_path / "replica0.audit.json"
    fb = tmp_path / "replica2.audit.json"
    fa.write_text(json.dumps(a.dump()))
    fb.write_text(json.dumps(b.dump()))
    out = tmp_path / "merged.json"
    assert audit_mod.main(["merge", str(fa), str(fb),
                           "-o", str(out)]) == 1
    merged = json.load(open(out))
    assert merged["first"]["index"] == 2
    assert audit_mod.main(["report", str(fa), str(fb)]) == 1
    cap = capsys.readouterr().out
    assert "FIRST DIVERGENCE" in cap and "index 2" in cap
    # clean pair exits 0
    assert audit_mod.main(["report", str(fa), str(fa)]) == 0


# ---------------------------------------------------------------------------
# satellite: sharded profiler hooks + collect_frames parity
# ---------------------------------------------------------------------------

def test_sharded_profiler_phases_and_group_apply_histograms():
    reg = MetricsRegistry()
    sc = ShardedCluster(CFG, 3, 2)
    sc.obs = Observability(metrics_registry=reg)
    sc.profiler = StepPhaseProfiler(metrics=reg)
    sc.place_leaders()
    for g in range(2):
        sc.submit(g, sc.leader(g), b"p%d" % g)
    sc.step()
    sc.step()
    for phase in ("host_encode", "device_dispatch", "quorum_wait",
                  "apply"):
        h = reg.get("step_phase_us", phase=phase, replica=-1)
        assert h["count"] >= 1, phase
    # per-group apply attribution: {group=g}-tagged histograms
    for g in range(2):
        h = reg.get("step_phase_us", phase="apply", group=g)
        assert h["count"] >= 1, g
    # fencing off by default: no device_sync series
    assert reg.get("step_phase_us", phase="device_sync",
                   replica=-1) == 0


def test_sharded_collect_frames_parity_with_simcluster():
    sim = SimCluster(CFG, 3)
    sim.collect_frames = True
    sh = ShardedCluster(CFG, 3, 1)
    sh.collect_frames = True
    sim.run_until_elected(0)
    sh.run_until_elected(0, 0)
    for i in range(6):
        sim.submit(0, b"fr%d" % i)
        sh.submit(0, 0, b"fr%d" % i)
    for _ in range(3):
        sim.step()
        sh.step()
    assert sh.frames[0] == sim.frames            # byte-identical
    assert any(sim.frames[r] for r in range(3))


# ---------------------------------------------------------------------------
# satellite: chaos integration (audit at 100%)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_nemesis_clean_seed_zero_audit_findings():
    from rdma_paxos_tpu.chaos.runner import NemesisRunner
    v = NemesisRunner(n_replicas=3, seed=13, steps=40).run()
    assert v["ok"], v
    assert v["audit"]["findings"] == 0
    assert v["audit"]["indices_checked"] > 0


@pytest.mark.chaos
def test_shard_nemesis_clean_seed_zero_audit_findings():
    from rdma_paxos_tpu.shard.chaos import ShardNemesisRunner
    v = ShardNemesisRunner(n_replicas=3, n_groups=2, seed=2,
                           steps=30, crash_step=10).run()
    assert v["ok"], v
    assert v["audit"]["findings"] == 0
    assert v["audit"]["indices_checked"] > 0


@pytest.mark.chaos
def test_nemesis_corruption_fails_run_with_audit_artifact(tmp_path):
    """Mid-run single-bit corruption of a follower's committed log
    memory: the nemesis verdict fails with reason 'audit divergence'
    and the reproducer artifact embeds the audit dump + flight ring."""
    from rdma_paxos_tpu.chaos.artifact import load_reproducer
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    class Corrupting(NemesisRunner):
        corrupted_at = None

        def _one_step(self, t, leader, violations):
            c = self.cluster
            if (self.corrupted_at is None and t >= 12 and leader >= 0
                    and c.last is not None
                    and int(c.last["commit"].min()) >= 1):
                victim = (leader + 1) % self.R
                target = int(c.last["commit"].min()) - 1
                _corrupt(c, victim, target)
                type(self).corrupted_at = (victim, target)
            return super()._one_step(t, leader, violations)

    art = str(tmp_path / "audit_nemesis.json")
    v = Corrupting(n_replicas=3, seed=3, steps=25,
                   fault_kinds=("drop",), artifact_path=art).run()
    assert Corrupting.corrupted_at is not None
    victim, target = Corrupting.corrupted_at
    assert not v["ok"]
    assert v["invariant_violations"] == []
    assert v["audit"]["findings"] >= 1
    assert v["audit"]["first"]["index"] == target
    assert victim in v["audit"]["first"]["got_replicas"]
    assert v["artifact"] == art
    doc = load_reproducer(art)
    assert doc["reason"] == "audit divergence"
    assert doc["extra"]["audit"]["findings"]
    assert doc["extra"]["flight"]["steps"]
    # the embedded dump re-derives the same first divergence via merge
    rep = merge_dumps([doc["extra"]["audit"]])
    assert rep["first"]["index"] == target


# ---------------------------------------------------------------------------
# satellite: bench overhead A/B (tiny smoke — the real row runs via
# `benchmarks/run_bench.py --audit`)
# ---------------------------------------------------------------------------

def test_measure_audit_overhead_smoke():
    from benchmarks.run_bench import measure_audit_overhead
    ab = measure_audit_overhead(cfg=CFG, steps=30, per_step=2,
                                payload=16, warmup=3)
    assert ab["off"]["committed"] == ab["on"]["committed"] > 0
    assert ab["audit"]["findings"] == 0
    assert "overhead_pct" in ab
