"""The fleet ops plane (obs/series.py, obs/export.py,
obs/console.py + the alerts window-domain kinds and driver wiring).

Covers the PR 12 acceptance surface:

* TimeSeriesStore sampling semantics (counters→windowed rates with
  exact cumulative deltas, gauges→last, histograms→quantile/CDF
  sub-series), bounded retention, and append-only JSONL whose
  cross-host merge is a file concat;
* the Prometheus text renderer (cumulative ``le=`` buckets) and the
  ops HTTP exporter's five endpoints on an ephemeral port;
* the window-domain rule kinds: ``rate_window`` and multi-window
  ``burn_rate`` — a scripted latency regression fires the DEFAULT
  commit-latency SLO burn rule and resolves after recovery;
* per-alert ``since``/``duration_s`` and the
  ``alert_firing{alert=}`` gauge dropping to 0 on resolve;
* the cluster health schema (``validate_cluster``) round-tripping
  through JSON for BOTH drivers — leases/reads/repair/alerts/
  audit_artifact keys always present;
* live-scrape e2e: a driver serves /metrics + /healthz, a
  single-process NodeDaemon (subprocess) serves the same via
  RP_METRICS_PORT, and ``obs.console --once`` renders a fleet table
  merged from ≥2 sources;
* postmortem bundles: assemble from a workdir, ``--verify`` exits 0,
  a tampered or section-missing bundle exits 1;
* the cache-key guard (exporter+series attached vs detached →
  bit-identical step outputs, ZERO new STEP_CACHE keys) and the
  static jit-safety scan extended to the three new modules.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs import console as console_mod
from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
from rdma_paxos_tpu.obs.export import OpsExporter, render_prometheus
from rdma_paxos_tpu.obs.health import (
    validate, validate_cluster)
from rdma_paxos_tpu.obs.metrics import (
    LATENCY_BUCKETS_S, MetricsRegistry)
from rdma_paxos_tpu.obs.series import (
    TimeSeriesStore, merge_docs, read_jsonl, split_series_key)
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import STEP_CACHE

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16,
                batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _get_json(url, timeout=10.0):
    return json.loads(_get(url, timeout)[1])


# ---------------------------------------------------------------------------
# TimeSeriesStore
# ---------------------------------------------------------------------------

def test_series_counter_rates_and_deltas():
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=16)
    w = 100.0
    for i in range(5):
        reg.inc("ops_total", 10, replica=0)
        store.sample(reg.snapshot(), step=i, wall=w + i * 2.0)
    pts = store.points("ops_total{replica=0}")
    assert len(pts) == 5
    # first point establishes the baseline (rate 0); later points are
    # the windowed rate: 10 ops / 2 s = 5/s
    assert pts[0][2] == 0.0
    assert all(p[2] == pytest.approx(5.0) for p in pts[1:])
    # cumulative deltas over the trailing window are exact
    assert store.window_delta("ops_total{replica=0}",
                              wall_s=4.0) == pytest.approx(20.0)
    assert store.window_rate("ops_total{replica=0}",
                             wall_s=4.0) == pytest.approx(5.0)
    # step-domain windows work too
    assert store.window_delta("ops_total{replica=0}",
                              steps=2) == pytest.approx(20.0)


def test_series_gauge_last_and_hist_sub_series():
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=16)
    reg.set("cluster_leader", 2)
    for _ in range(100):
        reg.observe("lat", 0.01, buckets=LATENCY_BUCKETS_S)
    store.sample(reg.snapshot(), step=0, wall=1.0)
    assert store.latest("cluster_leader") == 2
    # histogram decomposes into quantile + count/sum + CDF series
    assert store.latest("lat|p50") == pytest.approx(0.01)
    assert store.latest("lat|p99") == pytest.approx(0.01)
    names = store.names()
    assert "lat|count" in names and "lat|sum" in names
    assert "lat|le|0.01" in names
    assert store.le_bounds("lat") == sorted(
        float(b) for b in LATENCY_BUCKETS_S)
    base, labels, sub = split_series_key("lat{replica=0}|le|0.01")
    assert (base, labels, sub) == ("lat", {"replica": "0"},
                                   "le|0.01")


def test_series_bounded_retention():
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=8)
    for i in range(50):
        reg.inc("c")
        store.sample(reg.snapshot(), step=i, wall=float(i))
    pts = store.points("c")
    assert len(pts) == 8                       # ring bounded
    assert pts[0][0] == 42 and pts[-1][0] == 49   # newest retained


def test_series_jsonl_concat_merge(tmp_path):
    """Cross-host merge is a file concat: two stores' logs
    concatenated come apart cleanly by src tag."""
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    a = TimeSeriesStore(capacity=8, path=str(tmp_path / "a.jsonl"),
                        source="hostA")
    b = TimeSeriesStore(capacity=8, path=str(tmp_path / "b.jsonl"),
                        source="hostB")
    for i in range(3):
        reg_a.inc("x", 2)
        reg_b.set("g", i)
        a.sample(reg_a.snapshot(), step=i, wall=10.0 + i)
        b.sample(reg_b.snapshot(), step=i, wall=20.0 + i)
    a.close()
    b.close()
    concat = tmp_path / "fleet.jsonl"
    concat.write_bytes((tmp_path / "a.jsonl").read_bytes()
                       + (tmp_path / "b.jsonl").read_bytes())
    docs = merge_docs(read_jsonl(str(concat)))
    assert set(docs) == {"hostA", "hostB"}
    assert docs["hostA"]["anchor"] is not None
    assert len(docs["hostA"]["series"]["x"]) == 3
    # counter lines carry [rate, cum]; cum is exact after the merge
    assert docs["hostA"]["series"]["x"][-1][3] == 6.0
    assert docs["hostB"]["series"]["g"][-1][2] == 2.0


def test_series_window_cold_start_guard():
    """A window longer than the retained history is UNKNOWN (None)
    until the ring either spans it or saturates — a short boot
    history must never masquerade as the slow burn window."""
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=8)
    for i in range(3):
        reg.inc("c", 5)
        store.sample(reg.snapshot(), step=i, wall=float(i * 2))
    # 4 s of history cannot answer a 100 s window
    assert store.window_delta("c", wall_s=100.0) is None
    assert store.window_rate("c", wall_s=100.0) is None
    for i in range(3, 9):           # saturate the ring (capacity 8)
        reg.inc("c", 5)
        store.sample(reg.snapshot(), step=i, wall=float(i * 2))
    # saturated: full retention is all we can know — evaluate over it
    assert store.window_delta("c", wall_s=100.0) == pytest.approx(35.0)


def test_series_log_open_failure_never_raises(tmp_path):
    """Retention I/O must never kill the caller: a missing workdir
    costs the JSONL log, not the store (in-memory sampling keeps
    working) — and ClusterDriver construction survives it."""
    store = TimeSeriesStore(
        capacity=8, path=str(tmp_path / "no" / "such" / "x.jsonl"))
    reg = MetricsRegistry()
    reg.inc("c")
    assert store.sample(reg.snapshot(), step=0, wall=1.0) == 1
    assert store.points("c")
    store.close()


def test_series_to_dict_is_json_serializable():
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=4)
    reg.inc("c")
    store.sample(reg.snapshot(), step=1, wall=1.0)
    doc = json.loads(json.dumps(store.to_dict()))
    assert doc["kind"] == "series" and doc["samples"] == 1
    assert "c" in doc["series"]


# ---------------------------------------------------------------------------
# Prometheus rendering + the exporter endpoints
# ---------------------------------------------------------------------------

def test_render_prometheus_shapes():
    reg = MetricsRegistry()
    reg.inc("ops_total", 3, replica=0)
    reg.set("role", 1, replica=2)
    for v in (0.01, 0.01, 2.0):
        reg.observe("lat_seconds", v, buckets=(0.1, 1.0))
    text = render_prometheus(reg.snapshot())
    assert "# TYPE ops_total counter" in text
    assert 'ops_total{replica="0"} 3' in text
    assert 'role{replica="2"} 1' in text
    # buckets are CUMULATIVE in the exposition format
    assert 'lat_seconds_bucket{le="0.1"} 2' in text
    assert 'lat_seconds_bucket{le="1.0"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text


def test_exporter_endpoints_ephemeral_port():
    reg = MetricsRegistry()
    reg.inc("c", 7)
    store = TimeSeriesStore(capacity=8)
    store.sample(reg.snapshot(), step=0, wall=1.0)
    eng = AlertEngine(reg, rules=default_rules(), series=store)
    eng.evaluate()
    health = {"leader": 0, "loop_error": None}
    exp = OpsExporter(registry=reg, health_fn=lambda: dict(health),
                      alerts=eng, series=store, port=0).start()
    try:
        assert exp.port > 0
        st, body = _get(exp.url + "/metrics")
        assert st == 200 and b"c 7" in body
        assert _get_json(exp.url + "/metrics.json")["counters"][
            "c"] == 7
        st, body = _get(exp.url + "/healthz")
        assert st == 200 and json.loads(body)["leader"] == 0
        doc = _get_json(exp.url + "/series")
        assert doc["samples"] == 1
        doc = _get_json(exp.url + "/alerts")
        assert "leaderless" in doc["state"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exp.url + "/nope")
        assert ei.value.code == 404
        # a dead poll loop fails the health probe with 503
        health["loop_error"] = "RuntimeError('boom')"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exp.url + "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["loop_error"]
    finally:
        exp.close()


# ---------------------------------------------------------------------------
# window-domain alert kinds
# ---------------------------------------------------------------------------

def test_rate_window_rule_fires_and_resolves():
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=64)
    eng = AlertEngine(
        reg, rules=[dict(name="hot", severity="warn",
                         kind="rate_window", metric="errors_total",
                         window_s=10.0, threshold=5.0)],
        series=store)
    w = 0.0
    for i in range(4):          # quiet: 1/s
        reg.inc("errors_total", 2)
        store.sample(reg.snapshot(), step=i, wall=w)
        w += 2.0
    assert eng.evaluate() == dict(fired=[], resolved=[])
    for i in range(4, 10):      # hot: 10/s
        reg.inc("errors_total", 20)
        store.sample(reg.snapshot(), step=i, wall=w)
        w += 2.0
    out = eng.evaluate()
    assert out["fired"] == ["hot"]
    assert eng.state()["hot"]["value"] > 5.0
    for i in range(10, 22):     # quiet again
        store.sample(reg.snapshot(), step=i, wall=w)
        w += 2.0
    assert "hot" in eng.evaluate()["resolved"]


def test_burn_rate_default_rule_fires_and_resolves():
    """The scripted latency regression of the acceptance criteria:
    the DEFAULT commit-latency SLO burn rule (bound 0.25 s, 99%
    objective, 30 s / 300 s windows) fires during a regression and
    resolves after recovery — through the same sample/evaluate
    cadence the drivers run."""
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=256)
    eng = AlertEngine(reg, rules=default_rules(), series=store)
    w = 1000.0

    def drive(n, latency, per=20):
        nonlocal w
        out = []
        for i in range(n):
            for _ in range(per):
                reg.observe("commit_latency_seconds", latency,
                            buckets=LATENCY_BUCKETS_S, replica=0)
            store.sample(reg.snapshot(), step=store.samples, wall=w)
            w += 5.0
            out.append(eng.evaluate())
        return out

    drive(10, 0.01)
    assert not eng.state()["commit_latency_slo_burn"]["firing"]
    fired_at = None
    for i, out in enumerate(drive(70, 2.0)):
        if "commit_latency_slo_burn" in out["fired"]:
            fired_at = i
            break
    assert fired_at is not None, "regression never fired the burn rule"
    st = eng.state()["commit_latency_slo_burn"]
    assert st["firing"] and st["value"] > 6.0
    resolved = False
    for out in drive(140, 0.01, per=60):
        if "commit_latency_slo_burn" in out["resolved"]:
            resolved = True
            break
    assert resolved, "recovery never resolved the burn rule"


def test_window_rules_silent_without_series():
    eng = AlertEngine(MetricsRegistry(), rules=default_rules())
    out = eng.evaluate()
    assert out == dict(fired=[], resolved=[])
    assert eng.state()["commit_latency_slo_burn"]["value"] is None


def test_new_rule_kind_validation():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="rate_window needs"):
        AlertEngine(reg, rules=[dict(name="x", kind="rate_window",
                                     metric="m", threshold=1)])
    with pytest.raises(ValueError, match="burn_rate needs"):
        AlertEngine(reg, rules=[dict(name="x", kind="burn_rate",
                                     metric="m", bound=0.1)])
    with pytest.raises(ValueError, match="objective"):
        AlertEngine(reg, rules=[dict(
            name="x", kind="burn_rate", metric="m", bound=0.1,
            objective=1.5, fast_window_s=1, slow_window_s=10)])
    with pytest.raises(ValueError, match="slow_window_s"):
        AlertEngine(reg, rules=[dict(
            name="x", kind="burn_rate", metric="m", bound=0.1,
            objective=0.99, fast_window_s=10, slow_window_s=10)])


def test_alert_since_duration_and_gauge_drop_on_resolve():
    """Satellite pin: state() carries since/duration_s while firing,
    and the alert_firing{alert=} gauge drops to 0 the evaluation the
    rule resolves — the console trusts the gauge."""
    reg = MetricsRegistry()
    eng = AlertEngine(reg, rules=[dict(
        name="lag", severity="warn", kind="gauge_cmp",
        metric="depth", op=">", value=10)])
    reg.set("depth", 50)
    t0 = time.time()
    assert eng.evaluate()["fired"] == ["lag"]
    assert reg.get("alert_firing", alert="lag") == 1
    st = eng.state()["lag"]
    assert st["since"] is not None and st["since"] >= t0 - 1
    assert st["duration_s"] is not None and st["duration_s"] >= 0
    time.sleep(0.02)
    assert eng.state()["lag"]["duration_s"] >= 0.02
    reg.set("depth", 0)
    assert eng.evaluate()["resolved"] == ["lag"]
    assert reg.get("alert_firing", alert="lag") == 0
    st = eng.state()["lag"]
    assert st["since"] is None and st["duration_s"] is None
    assert not st["firing"]


# ---------------------------------------------------------------------------
# cluster health schema (satellite 1)
# ---------------------------------------------------------------------------

def test_cluster_health_schema_roundtrip_single_group():
    d = ClusterDriver(CFG, 3, timeout_cfg=TO)
    try:
        d.cluster.run_until_elected(0)
        d.cluster.submit(0, b"x")
        d.step()
        h = json.loads(json.dumps(d.health()))
        assert validate_cluster(h) == []
        assert h["schema"] == 2 and "anchor" in h
        # the PR 8-10 fields are not just present but live
        assert h["leases"]["holders"][0] in (0, 1, 2)
        assert h["reads"]["pending"] == 0
        assert "commit_latency_slo_burn" in h["alerts"]
        assert h["repair"] is None and h["audit"] is None
        for rep in h["replicas"]:
            assert validate(rep) == []
    finally:
        d.stop()


def test_cluster_health_schema_roundtrip_sharded():
    from rdma_paxos_tpu.runtime.sharded_driver import (
        ShardedClusterDriver)
    d = ShardedClusterDriver(CFG, 3, 2, timeout_cfg=TO)
    try:
        h = json.loads(json.dumps(d.health()))
        assert validate_cluster(h) == []
        assert h["leaders"] == [-1, -1]        # nothing elected yet
        assert len(h["groups"]) == 2
    finally:
        d.stop()


def test_validate_cluster_detects_missing_fields():
    assert "leases" in validate_cluster(dict(leader=0, ts=1.0))
    assert "leader|leaders" in validate_cluster(dict(ts=1.0))


# ---------------------------------------------------------------------------
# driver live-scrape e2e + console + bundle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served_driver(tmp_path_factory):
    wd = str(tmp_path_factory.mktemp("ops_e2e"))
    d = ClusterDriver(CFG, 3, workdir=wd, timeout_cfg=TO,
                      health_period=0.0)
    d.cluster.run_until_elected(0)
    for i in range(6):
        d.cluster.submit(0, b"v%d" % i)
        d.step()
    d.evaluate_alerts()
    exp = d.serve_metrics(0)
    yield d, exp, wd
    d.stop()


def test_driver_serves_metrics_and_healthz(served_driver):
    d, exp, wd = served_driver
    assert exp.port > 0
    st, body = _get(exp.url + "/metrics")
    assert st == 200
    text = body.decode()
    assert "committed_entries_total" in text
    assert "# TYPE step_batch_entries histogram" in text
    h = _get_json(exp.url + "/healthz")
    assert validate_cluster(h) == []
    assert h["leader"] == d.leader()
    s = _get_json(exp.url + "/series")
    assert s["samples"] >= 1 and s["series"]
    a = _get_json(exp.url + "/alerts")
    assert "commit_latency_slo_burn" in a["state"]
    # serve_metrics is idempotent — same exporter back
    assert d.serve_metrics() is exp


def test_console_once_merges_two_sources(served_driver, tmp_path,
                                         capsys):
    d, exp, wd = served_driver
    # a second source kind: one bare replica health file (the shape a
    # NodeDaemon host writes)
    hpath = tmp_path / "replica7.health.json"
    hpath.write_text(json.dumps(dict(
        replica=7, role=int(Role.LEADER), term=9, leader_id=7,
        commit=123, apply=120, end=125, head=0, log_headroom=99,
        inflight=0, ts=time.time())))
    rc = console_mod.main(["--scrape", exp.url,
                           "--health", str(hpath), "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "GROUP" in out and "LEADER" in out
    assert "2 source(s)" in out
    # the scraped cluster row and the merged member row both render
    assert str(d.leader()) in out and "123" in out
    assert "[cluster]" in out and "[replica]" in out


def test_console_fleet_view_merges_member_files():
    """N daemon health files = one cluster seen from N sides: leader
    = the highest-term LEADER claimant, frontiers = maxima."""
    mk = lambda r, role, term, commit: dict(     # noqa: E731
        src=f"h{r}", health=dict(replica=r, role=role, term=term,
                                 leader_id=1, commit=commit,
                                 apply=commit, end=commit, head=0,
                                 log_headroom=9, inflight=0, ts=1.0))
    view = console_mod.fleet_view([
        mk(0, int(Role.FOLLOWER), 3, 40),
        mk(1, int(Role.LEADER), 3, 41),
        mk(2, int(Role.LEADER), 2, 39),      # stale deposed claimant
    ])
    [row] = view["groups"]
    assert row["leader"] == 1 and row["term"] == 3
    assert row["commit"] == 41 and row["members"] == 3


def test_console_role_leader_pin():
    assert console_mod.ROLE_LEADER == int(Role.LEADER)


def test_fleet_view_tied_leader_terms_no_crash():
    """Two stale member files claiming LEADER at the SAME term (a
    deposed leader's last snapshot beside the fresh one) must render,
    not crash the console on a dict comparison."""
    mk = lambda r: dict(                         # noqa: E731
        src=f"h{r}", health=dict(replica=r, role=int(Role.LEADER),
                                 term=5, leader_id=r, commit=10,
                                 apply=10, end=10, head=0,
                                 log_headroom=9, inflight=0, ts=1.0))
    view = console_mod.fleet_view([mk(0), mk(1)])
    [row] = view["groups"]
    assert row["leader"] in (0, 1) and row["term"] == 5


def test_scrape_source_parses_503_dead_loop_health():
    """A dead poll loop answers /healthz with 503 + the full health
    document; the console must render the loop-error row, not a
    generic unreachable error."""
    reg = MetricsRegistry()
    exp = OpsExporter(
        registry=reg,
        health_fn=lambda: dict(leader=-1, replicas=[],
                               loop_error="RuntimeError('boom')",
                               ts=time.time()),
        port=0).start()
    try:
        doc = console_mod.scrape_source(exp.url)
        assert "error" not in doc
        assert doc["health"]["loop_error"].startswith("RuntimeError")
        view = console_mod.fleet_view([doc])
        [hst] = view["hosts"]
        assert hst["loop_error"]
        assert "LOOP ERROR" in console_mod.render_table(view)
    finally:
        exp.close()


def test_bundle_assemble_verify_tamper(served_driver, tmp_path):
    d, exp, wd = served_driver
    from rdma_paxos_tpu.obs.audit import write_audit_artifact
    # force every dump surface the bundle gathers
    d.obs.spans.write_json(os.path.join(wd, "spans.json"))
    write_audit_artifact(os.path.join(wd, "audit_dump.json"),
                         reason="test", obs=d.obs)
    d.obs.trace.dump_on_failure(os.path.join(wd, "trace_dump.json"),
                                reason="test")
    d.obs.metrics.write_json(os.path.join(wd, "metrics.json"))
    d._health.write(d._health_snapshots(d.cluster.last))
    d._health.write_cluster(d.health())

    out = str(tmp_path / "bundle.json")
    assert console_mod.main(["bundle", "--workdir", wd,
                             "--out", out]) == 0
    assert console_mod.main(["bundle", "--verify", out]) == 0
    doc = json.load(open(out))
    for name in console_mod.REQUIRED_SECTIONS:
        assert name in doc["sections"], name
        assert doc["manifest"][name]["sha256"]
    # series section really is the retention log, concat-mergeable
    assert doc["sections"]["series"]["lines"]
    # alert state rode in from the cluster health document
    assert "commit_latency_slo_burn" in doc["sections"]["alerts"]

    # tamper -> verify fails naming the section
    doc["sections"]["telemetry"]["counters"]["forged"] = 1
    json.dump(doc, open(out, "w"))
    assert console_mod.main(["bundle", "--verify", out]) == 1

    # a bundle missing a core section fails verification
    doc2 = console_mod.assemble_bundle(workdir=wd)
    del doc2["sections"]["spans"]
    del doc2["manifest"]["spans"]
    out2 = str(tmp_path / "partial.json")
    console_mod.write_bundle(doc2, out2)
    assert console_mod.main(["bundle", "--verify", out2]) == 1


def test_bundle_from_scrape(served_driver, tmp_path):
    d, exp, wd = served_driver
    doc = console_mod.assemble_bundle(scrape=exp.url)
    # the live endpoints alone provide series/telemetry/alerts/health
    for name in ("series", "telemetry", "alerts", "health"):
        assert name in doc["sections"], name
    assert doc["sections"]["series"]["kind"] == "series"
    assert "counters" in doc["sections"]["telemetry"]


# ---------------------------------------------------------------------------
# NodeDaemon e2e (single-process world, subprocess-isolated because
# jax.distributed.initialize is once-per-process)
# ---------------------------------------------------------------------------

_DAEMON_SCRIPT = r"""
import json, os, socket, sys, tempfile, urllib.request
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.runtime.node import NodeDaemon
wd = tempfile.mkdtemp(prefix="rp_node_ops_")
cfg = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)
d = NodeDaemon(cfg, process_id=0, num_processes=1,
               coordinator="127.0.0.1:%d" % port, workdir=wd)
assert d.exporter is not None and d.exporter.port > 0
for _ in range(6):
    d.iterate()
import time; time.sleep(1.1)      # cross the 1 s alert/health cadence
for _ in range(3):
    d.iterate()
h = json.loads(urllib.request.urlopen(
    d.exporter.url + "/healthz", timeout=10).read())
m = urllib.request.urlopen(
    d.exporter.url + "/metrics", timeout=10).read().decode()
a = json.loads(urllib.request.urlopen(
    d.exporter.url + "/alerts", timeout=10).read())
d.close()
print(json.dumps(dict(
    workdir=wd, port=d.exporter.port,
    health=h, has_role_metric="replica_role" in m,
    alert_names=sorted(a["state"]),
    health_file=os.path.exists(
        os.path.join(wd, "replica0.health.json")),
    series_lines=sum(1 for _ in open(
        os.path.join(wd, "replica0.series.jsonl"))))))
"""


def test_node_daemon_serves_ops_plane(tmp_path):
    """A real NodeDaemon (1-host world) with RP_METRICS_PORT=0: the
    exporter serves /metrics + /healthz + /alerts on an ephemeral
    port, the health file + series JSONL land in the workdir, and the
    console renders the health file afterwards."""
    env = dict(os.environ, RP_METRICS_PORT="0", JAX_PLATFORMS="cpu")
    env.pop("RP_AUDIT", None)
    proc = subprocess.run(
        [sys.executable, "-c", _DAEMON_SCRIPT],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["port"] > 0
    h = out["health"]
    assert validate(h) == []
    assert h["replica"] == 0 and h["role"] == int(Role.LEADER)
    assert out["has_role_metric"]
    assert "commit_latency_slo_burn" in out["alert_names"]
    assert out["health_file"] and out["series_lines"] >= 2
    # the console merges the daemon's health file like any member's
    view = console_mod.fleet_view(console_mod.load_health_files(
        [os.path.join(out["workdir"], "replica0.health.json")]))
    [row] = view["groups"]
    assert row["leader"] == 0


# ---------------------------------------------------------------------------
# cache-key guard + jit-safety scan (satellite 3)
# ---------------------------------------------------------------------------

def test_ops_plane_adds_zero_step_cache_keys_outputs_identical():
    """Exporter attached + series sampling + live scrapes vs a bare
    driver: step outputs BIT-IDENTICAL, STEP_CACHE unchanged — the
    whole ops plane is host bookkeeping."""
    cfg = LogConfig(n_slots=64, slot_bytes=96, window_slots=16,
                    batch_slots=8)          # geometry unique to this test

    def drive(d, scrape_url=None):
        d.cluster.run_until_elected(0)
        for i in range(6):
            d.cluster.submit(0, b"p%d" % i)
            d.step()
            if scrape_url is not None:
                d.evaluate_alerts()
                _get(scrape_url + "/metrics")
                _get_json(scrape_url + "/healthz")
        return {k: np.array(d.cluster.last[k])
                for k in ("term", "commit", "end", "apply", "head",
                          "role")}

    plain = ClusterDriver(cfg, 3, timeout_cfg=TO)
    try:
        base = drive(plain)
    finally:
        plain.stop()
    keys_before = set(STEP_CACHE)

    served = ClusterDriver(cfg, 3, timeout_cfg=TO, series_capacity=32)
    exp = served.serve_metrics(0)
    try:
        out = drive(served, scrape_url=exp.url)
        assert served.series.samples >= 6
    finally:
        served.stop()
    assert set(STEP_CACHE) == keys_before
    for k, v in base.items():
        assert np.array_equal(v, out[k]), k


def test_jit_safety_scan_covers_ops_plane_modules():
    """consensus/step.py, ops/*, and parallel/mesh.py run inside
    jit/shard_map: no ops-plane symbol may be reachable there, and
    obs/series.py, obs/export.py, obs/console.py themselves never
    reach into the accelerator stack. Enforced by the graftlint
    ``jit-purity`` pass (device manifest + ``HOST_PURE_MODULES``
    carry this test's former inline rules)."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    assert_jit_purity()


# ---------------------------------------------------------------------------
# bench smoke
# ---------------------------------------------------------------------------

def test_export_overhead_bench_smoke():
    from benchmarks.run_bench import measure_export_overhead
    cfg = LogConfig(n_slots=256, slot_bytes=128, window_slots=32,
                    batch_slots=16)
    out = measure_export_overhead(cfg, steps=40, per_step=4,
                                  warmup=4, repeats=1,
                                  sample_period_s=0.0,
                                  scrape_period_s=0.05)
    assert out["on"]["committed"] == out["off"]["committed"] > 0
    assert out["export"]["samples"] > 0
    assert out["export"]["scrapes"] > 0
    assert out["export"]["rule_evals"] == out["export"]["samples"]
