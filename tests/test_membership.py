"""Live membership change via joint consensus — the reference's
EXTENDED→TRANSIT→STABLE config machine (§3.5) driven through CONFIG log
entries, with dual-quorum enforcement while transitional."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.membership import MembershipManager
from rdma_paxos_tpu.consensus.state import ConfigState, Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def test_upsize_3_to_5():
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.submit(0, b"before")
    c.step()

    mm.change(0, 0b11111)       # add replicas 3 and 4
    cur = mm.current(0)
    assert cur["cid_state"] == int(ConfigState.STABLE)
    assert cur["bitmask_new"] == 0b11111

    # every member (incl. the new ones) converged on the config
    for r in range(5):
        assert mm.current(r)["bitmask_new"] == 0b11111

    # new quorum is 3-of-5: two failures tolerated...
    c.partition([[0, 1, 2], [3], [4]])
    c.submit(0, b"with-2-down")
    res = c.step()
    assert res["commit"][0] == res["end"][0]
    # ...three failures not
    c.partition([[0, 1], [2], [3], [4]])
    c.submit(0, b"with-3-down")
    res = c.step()
    assert res["commit"][0] < res["end"][0]
    c.heal()


def test_downsize_5_to_3():
    c = SimCluster(CFG, 8, group_size=5)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    mm.change(0, 0b00111)
    assert mm.current(0)["bitmask_new"] == 0b111
    # removed replicas no longer count toward quorum: 2-of-3 commits even
    # with 3 and 4 gone
    c.partition([[0, 1, 2], [3], [4]])
    c.submit(0, b"small-group")
    res = c.step()
    assert res["commit"][0] == res["end"][0]


def test_transit_requires_both_majorities_for_commit():
    """While TRANSIT is in the log (before STABLE), commits need majorities
    of BOTH configs — losing the old majority blocks commit even though
    the new majority is intact (dare_ibv_rc.c:2799-2957 semantics)."""
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.step()
    # enter joint consensus 0b111 -> 0b11111 but do NOT finalize
    mm.submit_transit(0, 0b111, 0b11111, epoch=1)
    res = c.step()
    assert mm.current(0)["cid_state"] == int(ConfigState.TRANSIT)
    committed_to = int(res["commit"][0])
    # old majority {0,1,2} broken (1,2 gone); new majority {0,3,4} intact
    c.partition([[0, 3, 4], [1], [2]])
    c.submit(0, b"blocked")
    res = c.step()
    res = c.step()
    assert int(res["commit"][0]) <= committed_to + 0, (
        "commit advanced without the old-config majority")
    # heal -> both quorums available -> commits flow again
    c.heal()
    res = c.step()
    res = c.step()
    assert int(res["commit"][0]) == int(res["end"][0])


def test_eviction_of_failed_member():
    """Failure-driven downsize (check_failure_count analog,
    dare_server.c:1189-1227): a permanently dead member is removed so the
    effective quorum shrinks."""
    c = SimCluster(CFG, 8, group_size=5)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.step()
    # replicas 3 and 4 die; 3-of-5 quorum still holds, but the operator
    # (or failure detector) evicts them
    c.partition([[0, 1, 2], [3], [4]])
    mm.change(0, 0b00111)
    assert mm.current(0)["bitmask_new"] == 0b111
    # now a single further failure is tolerated (2-of-3)
    c.partition([[0, 1], [2], [3], [4]])
    c.submit(0, b"after-evict")
    res = c.step()
    assert res["commit"][0] == res["end"][0]


def test_election_under_new_config_after_upsize():
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    mm.change(0, 0b11111)
    # old leader dies; a NEW member wins an election under the new config
    c.partition([[0], [1, 2, 3, 4]])
    res = c.step(timeouts=[3])
    assert res["role"][3] == int(Role.LEADER)
    c.submit(3, b"new-member-leads")
    res = c.step()
    assert res["commit"][3] == res["end"][3]


def test_extended_joiner_replicates_but_does_not_vote():
    """EXTENDED phase: the joiner receives the replication window (it is
    in bitmask_new) but quorum stays on the OLD config — the joiner's ack
    is neither needed nor counted for commit, and the joiner cannot stand
    for election (reference EXTENDED semantics,
    dare_ibv_ud.c:1024-1037)."""
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.step()
    mm.submit_extended(0, 0b111, 3, epoch=1)
    res = c.step()
    cur = mm.current(0)
    assert cur["cid_state"] == int(ConfigState.EXTENDED)
    assert cur["bitmask_new"] == 0b1111

    # the joiner absorbs windows: its end catches up to the leader's
    for _ in range(3):
        res = c.step()
    assert int(res["end"][3]) == int(res["end"][0])

    # quorum unchanged: commit advances with the joiner partitioned away
    c.partition([[0, 1, 2], [3]])
    c.submit(0, b"no-joiner-needed")
    res = c.step()
    assert int(res["commit"][0]) == int(res["end"][0])

    # but still needs 2 of the OLD three: joiner's ack cannot substitute
    c.partition([[0, 3], [1], [2]])
    c.submit(0, b"joiner-cannot-vote")
    res = c.step()
    res = c.step()
    assert int(res["commit"][0]) < int(res["end"][0])

    # joiner firing its election timer while EXTENDED goes nowhere
    c.heal()
    c.step(timeouts=[3])
    assert int(c.last["role"][3]) != int(Role.LEADER)


def test_full_join_ladder_extended_transit_stable():
    """EXTENDED → TRANSIT → STABLE admits the joiner as a full voting
    member at the end (the reference's complete join path,
    dare_server.c:1861-1937)."""
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.submit(0, b"history")
    c.step()
    mm.join(0, 3)
    cur = mm.current(0)
    assert cur["cid_state"] == int(ConfigState.STABLE)
    assert cur["bitmask_new"] == 0b1111
    # the joiner now counts: 3-of-4 majority holds with one old member out
    c.partition([[0, 1, 3], [2]])
    c.submit(0, b"joiner-votes-now")
    res = c.step()
    assert int(res["commit"][0]) == int(res["end"][0])
    # joiner replayed the full history
    c.heal()
    c.step()
    stream3 = [p for (_, _, _, p) in c.replayed[3]]
    assert b"history" in stream3
