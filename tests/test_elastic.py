"""Elastic multi-host: generation-based world rebuild (SURVEY §3.5's
join/recovery chain re-homed to a DCN control plane).

The headline scenario is the reference's ``reconf_bench.sh`` AddServer
story made real: a 3-host cluster loses a host, keeps serving as 2, the
host restarts, rejoins via the donor snapshot (consensus row + stable
store), and serves the FULL replicated history — plus new writes."""

import json
import os
import socket
import subprocess
import threading
import time

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType, M_TYPE
from rdma_paxos_tpu.consensus.membership import MembershipManager
from rdma_paxos_tpu.consensus.snapshot import export_row, genesis_row
from rdma_paxos_tpu.consensus.state import ConfigState, Role
from rdma_paxos_tpu.runtime.sim import SimCluster
from tests.conftest import jax_multiprocess_cpu

# the full elastic worlds run one NodeDaemon OS process per host over
# jax.distributed — impossible on a jaxlib whose CPU backend lacks
# cross-process collectives (the workers die at boot and the
# supervisors churn generations until the assertion timeout)
needs_multiprocess_cpu = pytest.mark.skipif(
    not jax_multiprocess_cpu(),
    reason="cross-process CPU collectives unavailable (jaxlib raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'); needs jax >= 0.5")

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


# ---------------------------------------------------------------------------
# unit level: the genesis transform
# ---------------------------------------------------------------------------

def test_export_and_genesis_row():
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.submit(0, b"payload-1")
    c.step()
    mm.change(0, 0b1111)            # leave a CONFIG entry in the log
    c.submit(0, b"payload-2")
    c.step()
    c.step()

    row = export_row(c.state, 0)
    assert int(row["commit"]) >= 4
    sw = CFG.slot_words
    assert (row["log_buf"][:, sw + M_TYPE]
            == int(EntryType.CONFIG)).any(), "precondition: CONFIG present"

    g = genesis_row(row, group_mask=0b11, epoch=9, n_replicas=2,
                    term=int(row["term"]) + 5)
    # CONFIG entries neutralized; old-world masks cannot resurface
    assert not (g["log_buf"][:, sw + M_TYPE]
                == int(EntryType.CONFIG)).any()
    # log content otherwise carried verbatim
    assert int(g["end"]) == int(row["end"])
    assert int(g["commit"]) == int(row["commit"])
    # new-world config installed as live AND committed checkpoint
    for k in ("bitmask_old", "bitmask_new", "ccfg_old", "ccfg_new"):
        assert int(g[k]) == 0b11
    assert int(g["epoch"]) == 9 and int(g["ccfg_epoch"]) == 9
    # fresh elections: term past every survivor, votes cleared
    assert int(g["term"]) == int(row["term"]) + 6
    assert int(g["voted_for"]) == -1 and int(g["voted_term"]) == 0
    assert int(g["role"]) == int(Role.FOLLOWER)
    assert g["vote_rec_term"].shape == (2,)
    # the original row is untouched
    assert (row["log_buf"][:, sw + M_TYPE]
            == int(EntryType.CONFIG)).any()


def test_genesis_boot_in_sim():
    """A cluster rebuilt from a genesis row elects and serves — and the
    carried log replays the full history on every member."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    for i in range(5):
        c.submit(0, b"hist-%d" % i)
        c.step()
    c.step()
    donor = export_row(c.state, 0)
    g = genesis_row(donor, group_mask=0b11, epoch=1, n_replicas=2)

    import jax.numpy as jnp
    import jax
    c2 = SimCluster(CFG, 2)
    # install the genesis row on every replica of the new world
    leaves = {}
    import dataclasses
    from rdma_paxos_tpu.consensus.log import Log
    from rdma_paxos_tpu.consensus.state import ReplicaState
    for f in dataclasses.fields(ReplicaState):
        if f.name == "log":
            continue
        cur = getattr(c2.state, f.name)
        leaves[f.name] = jnp.broadcast_to(
            jnp.asarray(np.asarray(g[f.name]).astype(cur.dtype)),
            cur.shape)
    leaves["log"] = Log(buf=jnp.broadcast_to(
        jnp.asarray(g["log_buf"]), c2.state.log.buf.shape))
    c2.state = ReplicaState(**leaves)
    c2.run_until_elected(1)
    c2.submit(1, b"new-world")
    c2.step()
    c2.step()
    for r in range(2):
        stream = [p for (_, _, _, p) in c2.replayed[r]]
        assert stream == [b"hist-%d" % i for i in range(5)] + \
            [b"new-world"], stream


# ---------------------------------------------------------------------------
# unit level: controller cut safety
# ---------------------------------------------------------------------------

def test_controller_unusable_survivors_cannot_justify_cut():
    """A cut must wait until donor-ELIGIBLE survivors alone include a
    majority of the previous world. Scenario: commit acked by leader +
    a follower that then wedges (usable=0); leader dies; the remaining
    follower lags. The wedged follower is the only surviving holder of
    the committed entry, so cutting with the laggard as donor would
    silently drop an acked write — the controller must refuse until a
    provably complete donor set registers."""
    from rdma_paxos_tpu.runtime.elastic import GroupController
    ctl = GroupController(expect=3, settle=0.0)
    try:
        full = dict(term=5, last_log_term=5, end=10, commit=10,
                    apply=10, applied=10, leader=1, usable=1)
        for h in range(3):
            ctl._handle({"op": "register", "host": h,
                         "addr": "127.0.0.1:1", "meta": None})
        assert ctl._spec is not None and ctl._spec["gen"] == 1
        ctl._handle({"op": "fail", "host": 1, "gen": 1})
        wedged = dict(full, leader=0, usable=0)
        laggard = dict(full, leader=0, end=5, commit=5, apply=5,
                       applied=5)
        ctl._handle({"op": "register", "host": 1,
                     "addr": "127.0.0.1:1", "meta": wedged})
        ctl._handle({"op": "register", "host": 2,
                     "addr": "127.0.0.1:1", "meta": laggard})
        r = ctl._handle({"op": "poll", "host": 2})
        # supervisors ignore spec gens they already ran; the check is
        # that no NEW generation was cut from this survivor set
        assert r["gen"] == 1, (
            "cut proceeded with 1 donor-eligible survivor of 3 — the "
            "wedged follower's committed entries would be dropped")
        # the dead leader returns with its complete log: two eligible
        # survivors now overlap the previous world -> cut, donor = the
        # most up-to-date ELIGIBLE host
        ctl._handle({"op": "register", "host": 0,
                     "addr": "127.0.0.1:1", "meta": dict(full)})
        r = ctl._handle({"op": "poll", "host": 0})
        assert r.get("ok") and r["gen"] == 2
        assert r["donor"] == 0
    finally:
        ctl.close()


def test_controller_all_meta_less_survivors_cut_fresh_world():
    """When EVERY surviving registration is meta-less (all disks lost),
    nothing is recoverable anywhere: the controller must cut a fresh
    world (donor -1) rather than deadlock waiting for an eligible donor
    that can never appear."""
    from rdma_paxos_tpu.runtime.elastic import GroupController
    ctl = GroupController(expect=3, settle=0.0)
    try:
        for h in range(3):
            ctl._handle({"op": "register", "host": h,
                         "addr": "127.0.0.1:1", "meta": None})
        assert ctl._spec is not None and ctl._spec["gen"] == 1
        ctl._handle({"op": "fail", "host": 0, "gen": 1})
        for h in range(3):
            ctl._handle({"op": "register", "host": h,
                         "addr": "127.0.0.1:1", "meta": None})
        r = ctl._handle({"op": "poll", "host": 0})
        assert r.get("ok") and r["gen"] == 2, r
        assert r["donor"] == -1
        # oversized host ids are refused at the door (the proxy layer
        # cannot encode them) — they must never enter a generation
        r = ctl._handle({"op": "register", "host": 128,
                         "addr": "127.0.0.1:1", "meta": None})
        assert "error" in r
    finally:
        ctl.close()


# ---------------------------------------------------------------------------
# full multi-process scenario
# ---------------------------------------------------------------------------

# all offsets share one residue class mod 300 so two pytest processes
# (different pids) can never collide on each other's host ports; the
# 17000+ base clears every other test file's range
_BASE = 17000 + (os.getpid() % 300)
APP_PORTS = {0: _BASE, 1: _BASE + 300, 2: _BASE + 600,
             3: _BASE + 900}

CFG_JSON = json.dumps({
    "log": {"n_slots": 256, "slot_bytes": 64, "window_slots": 32,
            "batch_slots": 16},
    "timing": {"elec_timeout_low": 0.4, "elec_timeout_high": 0.9},
})


def _kv(port, line, timeout=5.0):
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    f = s.makefile("rb")
    s.sendall(line)
    out = f.readline().strip()
    s.close()
    return out


def _wait_kv(port, key, want, timeout=60.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            last = _kv(port, b"GET %s\n" % key)
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.3)
    return last


def _dump_meta(workdir, h):
    from rdma_paxos_tpu.runtime.elastic import read_rowdump
    d = read_rowdump(workdir, h)
    return d[1] if d is not None else None


def _wait_leader(dirs, hosts, gen, timeout=240.0):
    """Wait until some member's fresh dump (of this generation) claims
    leadership; returns its host id."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        for h in hosts:
            m = _dump_meta(dirs[h], h)
            if m and m.get("gen") == gen and m.get("leader"):
                return h
        time.sleep(0.3)
    raise AssertionError(f"no leader dump for gen {gen}")


def _replicated_set(dirs, hosts, key, val, timeout=240.0):
    """Write ``key=val`` through whichever member currently leads and
    wait until every OTHER member's app serves it — retrying across
    leadership moves and generation churn (both are legitimate elastic
    behavior the test must ride out)."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        # freshest leadership claim wins; fall back to trying everyone
        order = sorted(
            hosts,
            key=lambda h: -(_dump_meta(dirs[h], h) or {}).get("leader", 0))
        for h in order:
            try:
                if _kv(APP_PORTS[h],
                       b"SET %s %s\n" % (key, val)) != b"+OK":
                    continue
            except OSError:
                continue
            ok = True
            for o in hosts:
                if o == h:
                    continue
                last = _wait_kv(APP_PORTS[o], key, val, timeout=25)
                if last != val:
                    ok = False
                    break
            if ok:
                return h
        time.sleep(0.5)
    raise AssertionError(
        f"write {key!r} never replicated to all of {hosts} "
        f"(last observed {last!r})")


def _wait_gen(ctl, g, timeout=240.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with ctl._lock:
            if ctl._spec is not None and ctl._spec["gen"] >= g:
                return dict(ctl._spec)
        time.sleep(0.2)
    raise AssertionError(f"generation {g} never cut")


def _wait_member(ctl, host, after_gen, timeout=240.0):
    """Wait (across generation churn) for a generation that includes
    ``host``; returns its spec."""
    spec = _wait_gen(ctl, after_gen + 1)
    deadline = time.time() + timeout
    while host not in [m["host"] for m in spec["members"]]:
        assert time.time() < deadline, f"host {host} never admitted"
        spec = _wait_gen(ctl, spec["gen"] + 1)
    return spec


@pytest.fixture(scope="module")
def built_native():
    subprocess.run(["make", "-C", NATIVE], check=True,
                   capture_output=True)


@needs_multiprocess_cpu
def test_elastic_loss_restart_rejoin(tmp_path, built_native):
    from rdma_paxos_tpu.runtime.elastic import (ElasticSupervisor,
                                                GroupController)
    # barrier_timeout must exceed a generation's FIRST round, which
    # includes cold XLA compiles (~20-40s on a loaded CPU host); the
    # compile cache is machine-stable so later runs are warm
    ctl = GroupController(expect=3, settle=1.2, barrier_timeout=90.0)
    dirs = {h: str(tmp_path / f"h{h}") for h in range(3)}
    cache = "/tmp/rp_elastic_jaxcache"
    # tests opt into the CPU backend EXPLICITLY (workers no longer
    # default to CPU — a silent CPU fallback on a TPU deployment was an
    # advisor finding); the outer environment may carry an accelerator
    # JAX_PLATFORMS that must not leak into the worker world
    wenv = {"JAX_COMPILATION_CACHE_DIR": cache,
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1",
            "RP_BENCH_CPU": "1"}

    def mk_sup(h):
        sup = ElasticSupervisor(
            host_id=h, controller=f"127.0.0.1:{ctl.port}",
            workdir=dirs[h], app_port=APP_PORTS[h],
            round_iters=12, cfg_json=CFG_JSON, worker_env=wenv)
        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        return sup

    sups = {h: mk_sup(h) for h in range(3)}
    try:
        # ---- generation 1: 3 hosts, write replicates ----
        spec1 = _wait_gen(ctl, 1)
        assert [m["host"] for m in spec1["members"]] == [0, 1, 2]
        lead = _wait_leader(dirs, [0, 1, 2], 1)
        lead = _replicated_set(dirs, [0, 1, 2], b"era", b"first")

        # ---- kill a non-leader host hard (worker dies mid-world) ----
        victim = next(h for h in range(3) if h != lead)
        sups[victim].stop()
        spec2 = _wait_gen(ctl, 2)
        survivors = [m["host"] for m in spec2["members"]]
        assert victim not in survivors and len(survivors) == 2

        # ---- generation 2: survivors still serve and replicate ----
        _wait_leader(dirs, survivors, spec2["gen"])
        _replicated_set(dirs, survivors, b"during", b"outage")

        # ---- restart the victim: it must rejoin via snapshot ----
        sups[victim] = mk_sup(victim)
        spec3 = _wait_member(ctl, victim, spec2["gen"])
        gen3 = spec3["gen"]

        # the rejoined host serves the FULL history: the gen-1 write it
        # saw before dying AND the gen-2 write it completely missed
        assert _wait_kv(APP_PORTS[victim], b"era", b"first",
                        timeout=240) == b"first"
        assert _wait_kv(APP_PORTS[victim], b"during", b"outage") == \
            b"outage", "rejoined host missed the write from its outage"

        # ---- and the rebuilt world replicates new writes everywhere ----
        members3 = [m["host"] for m in spec3["members"]]
        _wait_leader(dirs, members3, gen3)
        _replicated_set(dirs, members3, b"back", b"three")

        # ---- a BRAND-NEW host joins the running group (the reference's
        # AddServer: a server never seen before is admitted and
        # snapshot-recovers the full history, reconf_bench.sh:153) ----
        dirs[3] = str(tmp_path / "h3")
        sups[3] = mk_sup(3)
        spec4 = _wait_member(ctl, 3, gen3)
        # the joiner serves history it never witnessed...
        assert _wait_kv(APP_PORTS[3], b"era", b"first",
                        timeout=240) == b"first"
        assert _wait_kv(APP_PORTS[3], b"back", b"three") == b"three"
        # ...and participates in new replication
        members4 = [m["host"] for m in spec4["members"]]
        _wait_leader(dirs, members4, spec4["gen"])
        _replicated_set(dirs, members4, b"four", b"hosts")
    finally:
        for sup in sups.values():
            sup.stop()
        ctl.close()


@needs_multiprocess_cpu
def test_leader_sigkill_under_speculative_load(tmp_path, built_native):
    """The reference's RemoveLeader scenario (reconf_bench.sh:96-123) at
    FULL stack depth with speculative clients in flight: SIGKILL the
    LEADER's worker mid-drain while a pipelined spec-mode client is
    streaming SETs. Asserts:

    * output commit — every reply the client READ corresponds to an
      entry that survives on the new world (acked => committed =>
      durable across the leader's death);
    * the dead host's diverged speculative app is discarded and a FRESH
      app is rebuilt from the committed store (quarantine discipline at
      generation granularity: new app pid, full history served);
    * the rebuilt world replicates new writes everywhere.
    """
    from rdma_paxos_tpu.runtime.elastic import (ElasticSupervisor,
                                                GroupController)
    ctl = GroupController(expect=3, settle=1.2, barrier_timeout=90.0)
    dirs = {h: str(tmp_path / f"h{h}") for h in range(3)}
    cache = "/tmp/rp_elastic_jaxcache"
    wenv = {"JAX_COMPILATION_CACHE_DIR": cache,
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1",
            "RP_BENCH_CPU": "1"}

    def mk_sup(h):
        sup = ElasticSupervisor(
            host_id=h, controller=f"127.0.0.1:{ctl.port}",
            # long drain rounds: this test pushes a deep pipelined
            # backlog, and the worker must not stall it on control
            # beats (the default 12-iteration rounds are tuned for the
            # churn-heavy rejoin test above)
            workdir=dirs[h], app_port=APP_PORTS[h],
            round_iters=100, cfg_json=CFG_JSON, worker_env=wenv)
        t = threading.Thread(target=sup.run, daemon=True)
        t.start()
        return sup

    sups = {h: mk_sup(h) for h in range(3)}
    try:
        spec1 = _wait_gen(ctl, 1)
        assert [m["host"] for m in spec1["members"]] == [0, 1, 2]
        lead = _wait_leader(dirs, [0, 1, 2], 1)
        old_app_pid = sups[lead]._app.pid if sups[lead]._app else None

        # pipelined speculative client: stream N SETs in one blob; the
        # spec shim lets the app execute ahead while replies are held
        # until commit
        N = 40000
        s = socket.create_connection(("127.0.0.1", APP_PORTS[lead]),
                                     timeout=20)

        # CONTINUOUS writer thread: keeps the submit backlog deep for
        # the whole window so the kill provably lands with speculative
        # input in flight (a single pre-sent blob can fully commit
        # before the signal arrives — replies flush in large batches)
        def writer():
            try:
                for i in range(N):
                    s.sendall(b"SET kq%05d v%05d\n" % (i, i))
            except OSError:
                pass              # severed by the kill — expected
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        s.settimeout(10)
        got = b""
        while got.count(b"\n") < 2000:
            chunk = s.recv(65536)
            assert chunk, "connection died before the kill"
            got += chunk

        # ---- SIGKILL the leader's WORKER mid-burst ----
        assert sups[lead]._child is not None
        sups[lead]._child.kill()

        # drain whatever replies still arrive until the shim severs
        try:
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                got += chunk
        except OSError:
            pass
        s.close()
        wt.join(timeout=30)
        acked = got.count(b"\n")
        assert 0 < acked < N, (
            f"kill did not land mid-burst (acked={acked}/{N})")

        # ---- the survivors cut a new generation without the leader ----
        spec2 = _wait_gen(ctl, spec1["gen"] + 1)
        survivors = [m["host"] for m in spec2["members"]]
        # (the supervisor auto-re-registers the dead host, so it may
        # already be back in spec2 — what matters is the group serves)
        serving = [h for h in survivors]
        _wait_leader(dirs, serving, spec2["gen"])

        # ---- output commit: every ACKED reply's entry survives ----
        # acks release in connection order, so the acked set is exactly
        # the prefix kq0000..kq{acked-1}
        check = next(h for h in serving if h != lead) \
            if any(h != lead for h in serving) else serving[0]
        assert _wait_kv(APP_PORTS[check], b"kq%05d" % (acked - 1),
                        b"v%05d" % (acked - 1), timeout=240) == \
            b"v%05d" % (acked - 1), "last acked write lost"
        # spot-check the whole acked prefix in one connection
        sc = socket.create_connection(("127.0.0.1", APP_PORTS[check]),
                                      timeout=20)
        fc = sc.makefile("rb")
        for i in range(0, acked, max(1, acked // 50)):
            sc.sendall(b"GET kq%05d\n" % i)
            assert fc.readline().strip() == b"v%05d" % i, f"kq{i} lost"
        sc.close()

        # ---- the dead host rejoins with a FRESH app rebuilt from the
        # committed store (the generation-level quarantine) ----
        spec3 = _wait_member(ctl, lead, spec2["gen"] - 1)
        assert _wait_kv(APP_PORTS[lead], b"kq%05d" % (acked - 1),
                        b"v%05d" % (acked - 1), timeout=240) == \
            b"v%05d" % (acked - 1), "rejoined host missing acked write"
        new_app_pid = sups[lead]._app.pid if sups[lead]._app else None
        assert new_app_pid is not None and new_app_pid != old_app_pid, \
            "speculative app was not replaced after the kill"

        # ---- and the rebuilt world replicates new writes ----
        members3 = [m["host"] for m in spec3["members"]]
        _replicated_set(dirs, members3, b"post", b"kill")
    finally:
        for sup in sups.values():
            sup.stop()
        ctl.close()
