"""Unit tests for the slot-ring log — the wrap/fit edge cases the reference
log (``dare_log.h:466-558``) handles with byte-level splitting rules, here
exercised on the slot-based TPU design (SURVEY.md §7 step 1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import (
    EntryType, M_LEN, M_TERM, M_TYPE, META_W,
    absorb_window, append_batch, extract_window, last_term, make_log,
)

CFG = LogConfig(n_slots=16, slot_bytes=16, window_slots=8, batch_slots=4)


def mk_batch(vals, typ=EntryType.SEND):
    B = CFG.batch_slots
    data = np.zeros((B, CFG.slot_words), np.int32)
    meta = np.zeros((B, META_W), np.int32)
    for i, v in enumerate(vals):
        data[i, 0] = v
        meta[i, M_TYPE] = int(typ)
        meta[i, M_LEN] = 4
    return jnp.asarray(data), jnp.asarray(meta), jnp.asarray(
        len(vals), jnp.int32)


def i32(v):
    return jnp.asarray(v, jnp.int32)


def test_append_and_extract():
    log = make_log(CFG)
    data, meta, cnt = mk_batch([10, 11, 12])
    log, end = append_batch(log, i32(0), i32(0), data, meta, cnt, i32(5))
    assert int(end) == 3
    wd, wm = extract_window(log, i32(0), 8)
    assert wd[0, 0] == 10 and wd[2, 0] == 12
    assert wm[0, M_TERM] == 5
    assert int(last_term(log, end)) == 5


def test_append_clamps_to_capacity():
    """Appends never overtake head (free-space check of log_append_entry);
    capacity is n_slots-1 — one slot stays free so the prev-term check
    never reads a recycled slot."""
    log = make_log(CFG)
    end, head = i32(0), i32(0)
    for k in range(5):  # try to push 20 entries into a 16-slot ring
        data, meta, cnt = mk_batch([k * 4, k * 4 + 1, k * 4 + 2, k * 4 + 3])
        log, end = append_batch(log, end, head, data, meta, cnt, i32(1))
    assert int(end) == 15  # clamped at n_slots-1 with head=0
    # prune head -> space opens up
    data, meta, cnt = mk_batch([99])
    log, end = append_batch(log, end, i32(4), data, meta, cnt, i32(1))
    assert int(end) == 16
    wd, _ = extract_window(log, i32(15), 1)
    assert wd[0, 0] == 99


def test_wraparound_extract():
    """The ring wrap that costs the reference two RDMA sends
    (dare_ibv_rc.c:1539-1545) is a plain modular gather here."""
    log = make_log(CFG)
    end, head = i32(0), i32(0)
    for k in range(7):
        data, meta, cnt = mk_batch([4 * k, 4 * k + 1, 4 * k + 2, 4 * k + 3])
        head = i32(max(0, int(end) - 4))
        log, end = append_batch(log, end, head, data, meta, cnt, i32(1))
    assert int(end) == 28
    wd, _ = extract_window(log, i32(24), 4)  # crosses slot 15 -> 0
    np.testing.assert_array_equal(np.asarray(wd[:4, 0]), [24, 25, 26, 27])


def test_absorb_extends():
    leader, follower = make_log(CFG), make_log(CFG)
    data, meta, cnt = mk_batch([1, 2, 3])
    leader, lend = append_batch(leader, i32(0), i32(0), data, meta, cnt,
                                i32(2))
    wd, wm = extract_window(leader, i32(0), 8)
    follower, fend = absorb_window(follower, i32(0), wd, wm, i32(0), i32(3))
    assert int(fend) == 3
    fd, fm = extract_window(follower, i32(0), 8)
    np.testing.assert_array_equal(np.asarray(fd[:3, 0]), [1, 2, 3])
    assert fm[0, M_TERM] == 2


def test_absorb_gap_rejected():
    follower = make_log(CFG)
    wd = jnp.zeros((8, CFG.slot_words), jnp.int32)
    wm = jnp.zeros((8, META_W), jnp.int32)
    follower, fend = absorb_window(follower, i32(0), wd, wm, i32(5), i32(3))
    assert int(fend) == 0  # wstart(5) > my_end(0): ignored


def test_absorb_truncates_divergent_suffix():
    """Raft log-matching: a stale uncommitted suffix (deposed leader's
    entries) is discarded at the first term mismatch — the analog of
    log_adjustment rewinding via NC determinants (dare_ibv_rc.c:1292)."""
    a, b = make_log(CFG), make_log(CFG)
    d, m, c = mk_batch([1, 2])
    a, aend = append_batch(a, i32(0), i32(0), d, m, c, i32(1))
    b, bend = append_batch(b, i32(0), i32(0), d, m, c, i32(1))
    # b (deposed leader) appends garbage in term 2
    d2, m2, c2 = mk_batch([97, 98, 99])
    b, bend = append_batch(b, bend, i32(0), d2, m2, c2, i32(2))
    assert int(bend) == 5
    # a (new leader, term 3) appends one entry and sends window from 0
    d3, m3, c3 = mk_batch([42])
    a, aend = append_batch(a, aend, i32(0), d3, m3, c3, i32(3))
    wd, wm = extract_window(a, i32(0), 8)
    b, bend = absorb_window(b, bend, wd, wm, i32(0), aend)
    assert int(bend) == 3  # truncated from 5 to leader's end
    bd, bm = extract_window(b, i32(0), 8)
    np.testing.assert_array_equal(np.asarray(bd[:3, 0]), [1, 2, 42])
    np.testing.assert_array_equal(np.asarray(bm[:3, M_TERM]), [1, 1, 3])


def test_absorb_shorter_window_never_truncates():
    a = make_log(CFG)
    d, m, c = mk_batch([1, 2, 3, 4])
    a, aend = append_batch(a, i32(0), i32(0), d, m, c, i32(1))
    wd, wm = extract_window(a, i32(0), 8)
    # absorb only first 2 entries (same term): end must stay 4
    a, aend2 = absorb_window(a, aend, wd, wm, i32(0), i32(2))
    assert int(aend2) == 4
