"""True multi-process deployment: 3 OS processes (one replica each, the
reference's one-process-per-machine topology) coordinate via
jax.distributed; election, replication, commit, and per-host window fetch
all cross real process boundaries through gloo collectives."""

import os
import subprocess
import sys

import pytest

from tests.conftest import jax_multiprocess_cpu

pytestmark = pytest.mark.skipif(
    not jax_multiprocess_cpu(),
    reason="cross-process CPU collectives unavailable (jaxlib raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'); needs jax >= 0.5")

WORKER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)    # 1 device per process
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.runtime.host import HostReplicaDriver

cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
hd = HostReplicaDriver(cfg, process_id=pid, num_processes=n,
                       coordinator="127.0.0.1:%s" % port)

# step 1: host 0's election timer fires
res = hd.step(timeout_fired=(pid == 0))
assert res["term"] == 1, res
if pid == 0:
    assert res["role"] == 3, res     # LEADER
    assert res["became_leader"] == 1

# step 2: host 0 submits a client entry
batch = ([(int(EntryType.SEND), (0 << 24) | 1, 1, b"mh-write")]
         if pid == 0 else [])
res = hd.step(batch=batch, apply_done=int(res["commit"]))
if pid == 0:
    assert res["commit"] == 2, res

# step 3: lazy commit reaches every host
res = hd.step(apply_done=int(res["commit"]))
assert res["commit"] == 2, res

# every host reads the committed entry from its own replica's log
from rdma_paxos_tpu.consensus.log import M_LEN
wd, wm = hd.fetch_local_window(1)
payload = wd[0].astype("<i4").tobytes()[:int(wm[0, M_LEN])]
assert payload == b"mh-write", payload
print("HOST%d OK commit=%d leader=%d" % (pid, res["commit"],
                                         res["leader_id"]), flush=True)
"""



def test_three_process_cluster(tmp_path):
    port = str(9250 + (os.getpid() % 40))
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), "3", port],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(3)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=170)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {i} failed:\n{out}"
        assert f"HOST{i} OK commit=2 leader=0" in out, out


SCAN_WORKER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)    # 1 device per process
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.runtime.host import HostReplicaDriver
from rdma_paxos_tpu.runtime import hostpath

cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
hd = HostReplicaDriver(cfg, process_id=pid, num_processes=n,
                       coordinator="127.0.0.1:%s" % port)

res = hd.step(timeout_fired=(pid == 0))
assert res["term"] == 1, res

# K=2 scan: host 0 feeds one batch per fused step; every host calls
# the SAME collective in the same iteration (lock-step contract)
batches = ([[(int(EntryType.SEND), (0 << 24) | 1, 1, b"sc-one")],
            [(int(EntryType.SEND), (0 << 24) | 1, 2, b"sc-two")]]
           if pid == 0 else [])
res, rows = hd.step_scan(2, batches, apply_done=int(res["commit"]))
# one more (empty) scan so the lazy commit reaches every host; rows
# are staged at apply_done=1 — the committed client entries arrive in
# the SAME dispatch, no fetch_local_window needed
res, (wd, wm) = hd.step_scan(2, [], apply_done=1)
commit = int(res["commit"])
assert commit == 3, res
batch = hostpath.decode_batch(wm, wd, commit - 1)
assert [t[3] for t in batch.tuples()] == [b"sc-one", b"sc-two"], (
    batch.tuples())
assert int(res["accepted"]) == 0          # nothing submitted this scan
print("HOST%d SCAN OK commit=%d leader=%d" % (pid, commit,
                                              int(res["leader_id"])),
      flush=True)
"""


def test_three_process_scan_tier(tmp_path):
    """The K-window scan tier across REAL process boundaries: fused
    steps + the consolidated readback + each host's replay window
    staged inside the one collective dispatch."""
    port = str(9450 + (os.getpid() % 40))
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
        "PYTHONPATH", "")
    script = tmp_path / "scan_worker.py"
    script.write_text(SCAN_WORKER)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), "3", port],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(3)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=170)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {i} failed:\n{out}"
        assert f"HOST{i} SCAN OK commit=3 leader=0" in out, out


REBASE_WORKER = r"""
import os, sys
pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)    # 1 device per process
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType, M_LEN
from rdma_paxos_tpu.runtime.host import HostReplicaDriver

# tiny threshold so a short stream crosses the i32-rollover boundary
cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8,
                rebase_threshold=100)
hd = HostReplicaDriver(cfg, process_id=pid, num_processes=n,
                       coordinator="127.0.0.1:%s" % port)

res = hd.step(timeout_fired=(pid == 0))
assert res["role"] == (3 if pid == 0 else 1)
applied = 0
seq = 0
rebases = 0
sent = 0
TOTAL = 160
# every host runs the SAME loop; host 0 feeds batches. The gathered
# rebase_delta is identical on every host, so all apply the SAME
# subtraction in the same iteration — the NodeDaemon discipline.
for _ in range(220):
    batch = []
    if pid == 0:
        for _ in range(8):
            if sent < TOTAL:
                seq += 1; sent += 1
                batch.append((int(EntryType.SEND), (0 << 24) | 1, seq,
                              b"rb%05d" % seq))
    res = hd.step(batch=batch, apply_done=applied)
    applied = int(res["commit"])
    rd = int(res["rebase_delta"])
    if rd > 0:
        hd.rebase(rd)
        applied -= rd
        rebases += 1
assert rebases >= 1, "no rollover happened"
assert int(res["end"]) < cfg.rebase_threshold
# the last committed entry is readable at its POST-rollover index on
# every host's local shard
wd, wm = hd.fetch_local_window(int(res["commit"]) - 1)
payload = wd[0].astype("<i4").tobytes()[:int(wm[0, M_LEN])]
assert payload == b"rb%05d" % TOTAL, payload
print("HOST%d REBASE OK rebases=%d end=%d" % (pid, rebases,
                                              int(res["end"])), flush=True)
"""


def test_three_process_rebase(tmp_path):
    port = str(9350 + (os.getpid() % 40))
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    script = tmp_path / "rebase_worker.py"
    script.write_text(REBASE_WORKER)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), "3", port],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(3)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=170)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {i} failed:\n{out}"
        assert f"HOST{i} REBASE OK" in out, out
