"""End-to-end protocol tests on the simulated cluster: election →
replication → quorum commit → replay — the §3.2 hot path plus §3.4 failover
of SURVEY.md, deterministic and in-process."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def fresh3():
    # compiled protocol steps are cached per static config in SimCluster,
    # so fresh clusters are cheap after the first
    return SimCluster(CFG, 3)


def test_bootstrap_election():
    c = fresh3()
    res = c.step(timeouts=[0])
    assert res["role"][0] == int(Role.LEADER)
    assert res["became_leader"][0] == 1
    assert list(res["term"]) == [1, 1, 1]
    assert list(res["leader_id"]) == [0, 0, 0]
    # NOOP appended on election; commits once followers ack
    c.step()
    assert c.last["commit"][0] == 1


def test_replicate_and_commit():
    c = fresh3()
    c.run_until_elected(0)
    c.submit(0, b"SET k v1")
    c.submit(0, b"SET k v2")
    res = c.step()
    # same-step commit on the leader: append, fan-out, ack, quorum scan
    assert res["end"][0] == 3          # NOOP + 2 entries
    assert res["commit"][0] == 3
    # followers absorbed the window and learn commit next step (lazy push)
    assert list(res["end"]) == [3, 3, 3]
    res = c.step()
    assert list(res["commit"]) == [3, 3, 3]
    # replay produced the identical byte stream on every replica
    for r in range(3):
        assert [p for (_, _, _, p) in c.replayed[r]] == [b"SET k v1",
                                                      b"SET k v2"]


def test_submit_on_follower_is_ignored():
    c = fresh3()
    c.run_until_elected(0)
    c.submit(1, b"nope")
    res = c.step()
    assert res["end"][1] == res["end"][0]  # follower didn't self-append


def test_heartbeat_seen_by_followers():
    c = fresh3()
    c.run_until_elected(0)
    res = c.step()
    assert res["hb_seen"][1] == 1 and res["hb_seen"][2] == 1


def test_minority_partition_blocks_commit():
    c = fresh3()
    c.run_until_elected(0)
    c.step()
    base = int(c.last["commit"][0])
    c.partition([[0], [1, 2]])   # leader isolated
    c.submit(0, b"lost?")
    res = c.step()
    assert res["end"][0] == base + 1     # appended locally
    assert res["commit"][0] == base      # but no quorum -> no commit
    # heal: new entries commit again and the isolated write survives
    # (leader kept quorum-less entries; followers catch up)
    c.heal()
    res = c.step()
    res = c.step()
    assert res["commit"][0] == base + 1
    assert list(res["end"]) == [base + 1] * 3


def test_failover_preserves_committed_entries():
    c = fresh3()
    c.run_until_elected(0)
    c.submit(0, b"durable")
    c.step()
    c.step()
    assert list(c.last["commit"]) == [2, 2, 2]
    # leader 0 crashes (partitioned away); follower 1 times out
    c.partition([[0], [1, 2]])
    res = c.step(timeouts=[1])
    assert res["role"][1] == int(Role.LEADER)
    assert res["term"][1] == 2
    # new leader serves writes
    c.submit(1, b"after failover")
    res = c.step()
    assert res["commit"][1] == 4          # durable(2) + NOOP(3) + new(4)
    replayed1 = [p for (_, _, _, p) in c.replayed[1]]
    assert replayed1 == [b"durable", b"after failover"]


def test_deposed_leader_rejoins_and_truncates():
    """Reference §3.4: old-leader fencing + log adjustment. The deposed
    leader's uncommitted suffix is discarded; committed prefix survives."""
    c = fresh3()
    c.run_until_elected(0)
    c.submit(0, b"committed")
    c.step()
    c.step()
    c.partition([[0], [1, 2]])
    # deposed leader keeps appending garbage without quorum
    c.submit(0, b"garbage1")
    c.submit(0, b"garbage2")
    c.step()
    assert c.last["end"][0] == 4 and c.last["commit"][0] == 2
    # majority side elects a new leader and commits different entries
    c.step(timeouts=[1])
    c.submit(1, b"winner")
    c.step()
    # heal: old leader steps down, truncates garbage, converges
    c.heal()
    for _ in range(3):
        res = c.step()
    assert res["role"][0] == int(Role.FOLLOWER)
    assert list(res["term"]) == [2, 2, 2]
    assert list(res["end"]) == [4, 4, 4]   # committed+NOOP(t2)+winner
    assert list(res["commit"]) == [4, 4, 4]
    payloads0 = [p for (_, _, _, p) in c.replayed[0]]
    assert payloads0 == [b"committed", b"winner"]


def test_laggard_catches_up_through_window_floor():
    c = fresh3()
    c.run_until_elected(0)
    c.partition([[0, 1], [2]])   # replica 2 offline
    for i in range(10):
        c.submit(0, b"e%d" % i)
        c.step()
    assert c.last["commit"][0] == 11       # NOOP + 10 (majority 0,1)
    assert c.last["end"][2] == 1   # only the pre-partition NOOP
    c.heal()
    # window floors at the laggard's end -> catches up W entries per step
    for _ in range(3):
        res = c.step()
    assert res["end"][2] == 11
    res = c.step()
    assert res["commit"][2] == 11
    assert [p for (_, _, _, p) in c.replayed[2]] == [b"e%d" % i
                                                  for i in range(10)]


def test_ring_full_backpressure_retries():
    """Entries that don't fit the ring are NOT lost: the step reports how
    many it accepted and the submitter requeues the rest (the reference
    instead forces log pruning — our host driver retries + prunes)."""
    c = fresh3()
    c.run_until_elected(0)
    total = 3 * CFG.n_slots
    for i in range(total):
        c.submit(0, b"p%04d" % i)
    for _ in range(80):
        c.step()
        if not c.pending[0] and c.last["commit"][0] >= total + 1:
            break
    c.step()
    assert [p for (_, _, _, p) in c.replayed[1]] == [b"p%04d" % i
                                                  for i in range(total)]


def test_five_replica_cluster():
    c = SimCluster(CFG, 5)
    c.run_until_elected(2)
    c.submit(2, b"five")
    res = c.step()
    assert res["commit"][2] == 2
    res = c.step()
    assert list(res["commit"]) == [2] * 5
    # minority failure (2 of 5) does not block commit
    c.partition([[0, 2, 4], [1], [3]])
    c.submit(2, b"still-up")
    res = c.step()
    assert res["commit"][2] == 3
