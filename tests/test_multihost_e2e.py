"""The COMPLETE reference topology across real OS processes: 3 node
daemons (one per 'machine'), each running its own unmodified toyserver
under LD_PRELOAD, coordinating via jax.distributed collectives. A real TCP
client writes through whichever node won the election (found by the
reference's '] LEADER' log grep) and the data appears in every follower's
app."""

import os
import socket
import subprocess
import sys
import time

import pytest

from tests.conftest import jax_multiprocess_cpu

pytestmark = pytest.mark.skipif(
    not jax_multiprocess_cpu(),
    reason="cross-process CPU collectives unavailable (jaxlib raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'); needs jax >= 0.5")

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_BASE = 7800 + (os.getpid() % 400)
PORTS = [_BASE, _BASE + 400, _BASE + 800]
COORD_PORT = str(9300 + (os.getpid() % 500))


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


def wait_kv(port, key, want, timeout=30.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            f = s.makefile("rb")
            s.sendall(b"GET %s\n" % key)
            last = f.readline().strip()
            s.close()
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.2)
    return last


def test_full_stack_multiprocess(tmp_path):
    wd = str(tmp_path)
    procs, leader, ports = _boot_nodes(wd, iterations=4000)
    try:
        s = socket.create_connection(("127.0.0.1", ports[leader]),
                                     timeout=20)
        f = s.makefile("rb")
        s.sendall(b"SET dist yes\n")
        assert f.readline().strip() == b"+OK"
        s.close()

        for r in range(3):
            if r == leader:
                continue
            assert wait_kv(ports[r], b"dist", b"yes") == b"yes", \
                f"replica {r} missing the replicated write"
    finally:
        _teardown(procs)


_BOOT_SEQ = [0]


def _teardown(procs):
    """Kill the daemons and surface their output tails — a failed
    multiprocess boot is otherwise undebuggable (stdout is piped).
    The pipe is read NON-BLOCKING after the kill: the orphaned
    toyserver grandchild inherits the write end, so a blocking read
    (or communicate()) would never see EOF."""
    for i, p in enumerate(procs):
        p.kill()
        p.wait()
        tail = b""
        if p.stdout is not None:
            os.set_blocking(p.stdout.fileno(), False)
            try:
                tail = p.stdout.read() or b""
            except OSError:
                pass
        print(f"--- node {i} output tail ---\n"
              f"{tail.decode(errors='replace')[-1500:]}")


def _boot_nodes(wd, iterations=20000, extra_env=None, _retry=True):
    # unique coordinator AND app ports per boot: killing launch_node
    # orphans its toyserver child, which would keep serving stale state
    # on a reused port in the next test
    _BOOT_SEQ[0] += 1
    coord = str(int(COORD_PORT) + 7 * _BOOT_SEQ[0])
    ports = [p + 3 * _BOOT_SEQ[0] for p in PORTS]
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update(extra_env or {})
    procs = []
    for i in range(3):
        e = dict(env)
        e["server_idx"] = str(i)
        e["group_size"] = "3"
        procs.append(subprocess.Popen(
            [sys.executable, "benchmarks/launch_node.py",
             "--coordinator", "127.0.0.1:" + coord, "--workdir", wd,
             "--app-port", str(ports[i]),
             "--iterations", str(iterations)],
            env=e, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    leader, deadline = -1, time.time() + 90
    try:
        while leader < 0 and time.time() < deadline:
            for r in range(3):
                p = os.path.join(wd, f"replica{r}.log")
                if os.path.exists(p) and "] LEADER" in open(p).read():
                    leader = r
            time.sleep(0.3)
        assert leader >= 0, "no leader line found"
    except BaseException as exc:
        # never leak three daemons (and their orphaned toyservers)
        # into the rest of the session on a failed boot — and dump
        # their output tails, the only boot-failure evidence there is
        _teardown(procs)
        # a cold boot on this contended one-core box occasionally loses
        # a daemon to rendezvous/port races before the world forms;
        # that is harness fragility, not protocol behavior — retry ONCE
        # in a FRESH subdirectory (stale appended replica logs /
        # hardstate from the dead boot must not leak into the retry's
        # leader grep or vote restore). Only ordinary failures retry:
        # KeyboardInterrupt/SystemExit must propagate.
        if _retry and isinstance(exc, Exception):
            retry_wd = os.path.join(wd, "boot_retry")
            os.makedirs(retry_wd, exist_ok=True)
            return _boot_nodes(retry_wd, iterations=iterations,
                               extra_env=extra_env, _retry=False)
        raise
    return procs, leader, ports


def test_deep_queue_drains_through_bursts(tmp_path):
    """Deep pipelined load on the real multihost path WITH BURSTS
    FORCED ON (RP_BURST=1 — the TPU-default path, off by default on
    this CPU harness): the leader's submit backlog rides the control
    gather as burst_hint, every host agrees on a fused K-step dispatch,
    and the queue drains through fused bursts. Correctness gate: every
    reply arrives (output commit) and follower state converges
    exactly."""
    wd = str(tmp_path)
    N = 2000
    procs, leader, ports = _boot_nodes(wd, extra_env={"RP_BURST": "1"})
    try:
        s = socket.create_connection(("127.0.0.1", ports[leader]),
                                     timeout=20)
        f = s.makefile("rb")
        t0 = time.time()
        # pipeline the whole load in large chunks (the spec-mode shim
        # keeps the app reading; replies are held until commit)
        payload = b"".join(b"SET mk%04d v%04d\n" % (i, i)
                           for i in range(N))
        s.sendall(payload)
        got = 0
        while got < 4 * N:        # every reply is "+OK\n"
            chunk = f.read1(65536)
            assert chunk, "connection died mid-drain"
            got += len(chunk)
        dt = time.time() - t0
        s.close()
        print(f"multihost drain: {N} SETs in {dt:.2f}s "
              f"({N / dt:.0f} ops/s)")
        for r in range(3):
            if r == leader:
                continue
            assert wait_kv(ports[r], b"mk%04d" % (N - 1),
                           b"v%04d" % (N - 1)) == b"v%04d" % (N - 1)
        # sanity bound only: the burst path must complete the drain
        # promptly (its value — dispatch amortization — shows on real
        # TPU hosts; this CPU harness validates correctness)
        assert dt < 60, "burst-mode drain too slow"
    finally:
        _teardown(procs)


def test_multi_client_exactly_once_under_pipeline(tmp_path):
    """Several concurrent pipelined clients against the leader; a
    non-idempotent per-client counter pattern proves no event is applied
    twice or dropped on any follower."""
    import threading
    wd = str(tmp_path)
    procs, leader, ports = _boot_nodes(wd)
    try:
        errors = []

        def client(cid, n=300):
            try:
                s = socket.create_connection(
                    ("127.0.0.1", ports[leader]), timeout=20)
                f = s.makefile("rb")
                s.sendall(b"".join(b"SET c%d_%03d x\n" % (cid, i)
                                   for i in range(n)))
                got = 0
                while got < 4 * n:
                    chunk = f.read1(65536)
                    if not chunk:
                        raise OSError("severed")
                    got += len(chunk)
                s.close()
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append((cid, repr(exc)))
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        # a swallowed client failure must fail HERE with its cause, not
        # later at the replication check with no context
        assert not errors, f"clients failed: {errors}"
        for r in range(3):
            if r == leader:
                continue
            for c in range(4):
                assert wait_kv(ports[r], b"c%d_299" % c, b"x") == b"x", \
                    f"replica {r} client {c}"
    finally:
        _teardown(procs)
