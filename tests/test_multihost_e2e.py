"""The COMPLETE reference topology across real OS processes: 3 node
daemons (one per 'machine'), each running its own unmodified toyserver
under LD_PRELOAD, coordinating via jax.distributed collectives. A real TCP
client writes through whichever node won the election (found by the
reference's '] LEADER' log grep) and the data appears in every follower's
app."""

import os
import socket
import subprocess
import sys
import time

import pytest

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_BASE = 7800 + (os.getpid() % 400)
PORTS = [_BASE, _BASE + 400, _BASE + 800]
COORD_PORT = str(9300 + (os.getpid() % 500))


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


def wait_kv(port, key, want, timeout=30.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            f = s.makefile("rb")
            s.sendall(b"GET %s\n" % key)
            last = f.readline().strip()
            s.close()
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.2)
    return last


def test_full_stack_multiprocess(tmp_path):
    wd = str(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    procs = []
    for i in range(3):
        e = dict(env)
        e["server_idx"] = str(i)
        e["group_size"] = "3"
        procs.append(subprocess.Popen(
            [sys.executable, "benchmarks/launch_node.py",
             "--coordinator", "127.0.0.1:" + COORD_PORT, "--workdir", wd,
             "--app-port", str(PORTS[i]), "--iterations", "4000"],
            env=e, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    try:
        # find the leader the reference way: grep '] LEADER' in the logs
        leader, deadline = -1, time.time() + 90
        while leader < 0 and time.time() < deadline:
            for r in range(3):
                p = os.path.join(wd, f"replica{r}.log")
                if os.path.exists(p) and "] LEADER" in open(p).read():
                    leader = r
            time.sleep(0.3)
        assert leader >= 0, "no leader line found"

        s = socket.create_connection(("127.0.0.1", PORTS[leader]),
                                     timeout=20)
        f = s.makefile("rb")
        s.sendall(b"SET dist yes\n")
        assert f.readline().strip() == b"+OK"
        s.close()

        for r in range(3):
            if r == leader:
                continue
            assert wait_kv(PORTS[r], b"dist", b"yes") == b"yes", \
                f"replica {r} missing the replicated write"
    finally:
        for p in procs:
            p.kill()
            p.wait()
