"""Log-as-product streams (``streams/``): ordered range scans,
watch/subscribe with exactly-once resume, digest-verified CDC export.

Covers the PR 16 acceptance surface:

* the wire-codec constants the tail follower redeclares (host-purity)
  pinned equal to ``models/kvs.py``'s, plus a decode round-trip;
* scan pagination with a consistent-cut token: a leader crash plus
  overwrites/deletes MID-SCAN never tear the result — later pages
  still serve the at-cut values; pin expiry is an explicit
  ``TokenExpired``, never a silent tear;
* watch exactly-once: unit-level token resume (zero dups, zero
  gaps), retention-window ``ResumeExpired``, and the chaos verdict —
  a NemesisRunner crash/partition schedule with two scripted
  mid-run reconnects delivers the committed PUT/RM sequence exactly
  once, deterministically for a seed;
* CDC export verified against the AuditLedger (chain + digests), a
  flipped byte detected and named by ``(term, index)``, and the
  ``python -m rdma_paxos_tpu.streams verify`` CLI exiting 0/1;
* sharded range fan-out with router-aware narrowing;
* the cache-key guard: streams add ZERO STEP_CACHE keys and leave
  step outputs bit-identical attached vs detached;
* drain-path decoupling (S2): a WEDGED watcher (never polls, queue
  overflowed) does not delay queued point reads;
* the RP_SANITIZE runtime lock sanitizer armed on the streams hubs.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.obs import Observability
from rdma_paxos_tpu.runtime import reads as reads_mod
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu import streams as streams_mod
from rdma_paxos_tpu.streams import tail as tail_mod
from rdma_paxos_tpu.streams.cdc import chain_link, verify_export
from rdma_paxos_tpu.streams.scan import (
    TokenExpired, groups_for_range, key_range)
from rdma_paxos_tpu.streams.watch import ResumeExpired

# same geometry as tests/test_reads.py so compiled steps are shared
CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)


def _cluster(audit=False, **stream_kw):
    c = SimCluster(CFG, 3, audit=audit)
    c.obs = Observability()
    reads_mod.attach(c)
    hub = streams_mod.attach(c, **stream_kw)
    return c, hub


def _put_committed(c, kv, leader, key, val, req, client=9):
    kv.put(leader, key, val, client_id=client, req_id=req)
    for _ in range(8):
        c.step()
        kv._fold(leader)
        if kv.last_req[leader].get(client, 0) >= req:
            return
    raise AssertionError("put did not commit")


def _rm_committed(c, kv, leader, key, req, client=9):
    kv.remove(leader, key, client_id=client, req_id=req)
    for _ in range(8):
        c.step()
        kv._fold(leader)
        if kv.last_req[leader].get(client, 0) >= req:
            return
    raise AssertionError("rm did not commit")


def _serve_blocking(c, fn, max_steps=600):
    """Run a blocking client call (scan) in a thread while stepping
    the cluster so the ReadHub can confirm and serve its pages."""
    box = {}

    def work():
        try:
            box["out"] = fn()
        except BaseException as exc:  # noqa: BLE001 — reraised below
            box["err"] = exc

    th = threading.Thread(target=work)
    th.start()
    for _ in range(max_steps):
        c.step()
        if not th.is_alive():
            break
    th.join(10)
    if "err" in box:
        raise box["err"]
    assert "out" in box, "client call did not complete"
    return box["out"]


def _drain(sub, n, timeout=8.0):
    evs = []
    deadline = time.time() + timeout
    while len(evs) < n and time.time() < deadline:
        evs.extend(sub.poll())
        time.sleep(0.005)
    return evs


# ---------------------------------------------------------------------------
# codec constants (host-pure redeclaration pinned to models/kvs.py)
# ---------------------------------------------------------------------------

def test_tail_codec_constants_pinned_to_models_kvs():
    from rdma_paxos_tpu.models import kvs as mkvs
    assert tail_mod.CMD_BYTES == mkvs.CMD_W * 4
    assert tail_mod.KEY_BYTES == mkvs.KEY_W * 4
    assert tail_mod.VAL_BYTES == mkvs.VAL_W * 4
    assert (tail_mod.OP_PUT, tail_mod.OP_RM) == (mkvs.OP_PUT,
                                                 mkvs.OP_RM)
    # decode round-trip over the real encoder
    payload = mkvs.encode_cmd(mkvs.OP_PUT, b"key", b"val").tobytes()
    assert tail_mod.decode_kvs(payload) == (mkvs.OP_PUT, b"key",
                                            b"val")
    assert tail_mod.decode_kvs(b"short") is None


def test_key_range_prefix_math():
    assert key_range(prefix=b"user/") == (b"user/", b"user0")
    assert key_range(lo=b"a", hi=b"b") == (b"a", b"b")
    assert key_range() == (b"", None)
    assert key_range(prefix=b"\xff\xff") == (b"\xff\xff", None)
    with pytest.raises(ValueError):
        key_range(prefix=b"p", lo=b"a")


# ---------------------------------------------------------------------------
# ordered range scans: pagination, consistent cut, expiry
# ---------------------------------------------------------------------------

def test_scan_pagination_ordered():
    c, hub = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    hub.kvs = kv
    for i in range(10):
        _put_committed(c, kv, 0, b"k%02d" % i, b"v%d" % i, i + 1)
    _put_committed(c, kv, 0, b"zz", b"out-of-range", 11)
    page = _serve_blocking(c, lambda: hub.scan(prefix=b"k", limit=4))
    assert [k for k, _ in page["items"]] == [b"k00", b"k01", b"k02",
                                             b"k03"]
    assert page["token"] is not None and not page["done"]
    rows = _serve_blocking(
        c, lambda: hub.scan_all(prefix=b"k", limit=4))
    assert [k for k, _ in rows] == [b"k%02d" % i for i in range(10)]
    assert all(v == b"v%d" % i for i, (_, v) in enumerate(rows))
    assert hub.scans.pin_count() == 0     # whole-scan end released it


def test_scan_consistent_cut_survives_leader_crash_and_writes():
    """The pinned acceptance scenario: pagination that STARTED under
    leader 0 keeps serving the at-cut values after 0 crashes, a new
    leader commits overwrites and a delete, and the remaining pages
    are fetched under the new regime — no torn read, no duplicate,
    no skip. A FRESH scan afterwards sees the new world."""
    c, hub = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    hub.kvs = kv
    for i in range(8):
        _put_committed(c, kv, 0, b"k%02d" % i, b"A%d" % i, i + 1)
    page1 = _serve_blocking(c, lambda: hub.scan(prefix=b"k", limit=3))
    assert [k for k, _ in page1["items"]] == [b"k00", b"k01", b"k02"]
    tok = page1["token"]
    assert tok is not None
    # leader 0 crashes (isolated); 1 takes over and mutates mid-scan
    c.partition([[0], [1, 2]])
    c.run_until_elected(1)
    _put_committed(c, kv, 1, b"k04", b"B4", 1, client=7)
    _rm_committed(c, kv, 1, b"k06", 2, client=7)
    _put_committed(c, kv, 1, b"k08", b"B8", 3, client=7)  # new key
    # continue the SAME scan: at-cut values, k06 still present, no k08
    rest = []
    while tok is not None:
        page = _serve_blocking(c, lambda t=tok: hub.scan(token=t))
        rest.extend(page["items"])
        tok = page["token"]
    got = dict(page1["items"]) | dict(rest)
    assert sorted(got) == [b"k%02d" % i for i in range(8)]
    assert got[b"k04"] == b"A4"          # overwrite invisible at cut
    assert got[b"k06"] == b"A6"          # delete invisible at cut
    # a fresh scan sees the post-crash world
    rows = dict(_serve_blocking(
        c, lambda: hub.scan_all(prefix=b"k", limit=16)))
    assert rows[b"k04"] == b"B4" and b"k06" not in rows
    assert rows[b"k08"] == b"B8"
    assert hub.scans.pin_count() == 0


def test_scan_pin_expiry_is_explicit_token_expired():
    c, hub = _cluster(pin_steps=4)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    hub.kvs = kv
    for i in range(6):
        _put_committed(c, kv, 0, b"k%d" % i, b"v", i + 1)
    page = _serve_blocking(c, lambda: hub.scan(prefix=b"k", limit=2))
    tok = page["token"]
    for _ in range(8):                  # pin_steps elapse
        c.step()
    with pytest.raises(TokenExpired):
        _serve_blocking(c, lambda: hub.scan(token=tok))
    assert hub.scans.status()["pins_expired"] >= 1


# ---------------------------------------------------------------------------
# watch/subscribe: exactly-once resume
# ---------------------------------------------------------------------------

def test_watch_token_resume_no_dups_no_gaps():
    c, hub = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    sub = hub.subscribe(0, prefix=b"u/")
    for i in range(6):
        _put_committed(c, kv, 0, b"u/%d" % i, b"v%d" % i, i + 1)
    first = _drain(sub, 6)
    assert [e.key for e in first] == [b"u/%d" % i for i in range(6)]
    tok = sub.token()
    assert tok["group"] == 0 and tok["index"] >= 0
    sub.close()
    # deltas committed while disconnected
    for i in range(6, 10):
        _put_committed(c, kv, 0, b"u/%d" % i, b"v%d" % i, i + 1)
    sub2 = hub.subscribe(0, prefix=b"u/", token=tok)
    rest = _drain(sub2, 4)
    assert [e.key for e in rest] == [b"u/%d" % i for i in range(6, 10)]
    # exactly-once across the reconnect: zero dups, zero gaps
    idents = [(e.conn, e.req) for e in first + rest]
    assert len(idents) == len(set(idents)) == 10
    # the live fan-out delivered 6 (the replayed 4 ride the resume)
    assert hub.status()["watch"]["events_total"] >= 6
    assert c.obs.metrics.get("watch_events_delivered_total",
                             group=0) >= 6


def test_watch_resume_past_retention_raises():
    c, hub = _cluster(retain=3)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    sub = hub.subscribe(0)
    _put_committed(c, kv, 0, b"k0", b"v", 1)
    got = _drain(sub, 1)
    tok = got[0].token()
    sub.close()
    for i in range(1, 8):               # push the window past tok
        _put_committed(c, kv, 0, b"k%d" % i, b"v", i + 1)
    deadline = time.time() + 5
    while time.time() < deadline:       # pump is async: await trim
        try:
            hub.subscribe(0, token=tok).close()
        except ResumeExpired:
            break
        time.sleep(0.01)
    else:
        pytest.fail("resume past the retained window never expired")


def test_watch_chaos_leader_crash_exactly_once_deterministic():
    """The chaos acceptance: an all-keys watch with two scripted
    token reconnects under a crash/partition schedule delivers the
    committed PUT/RM sequence exactly once and in order — and the
    same seed reproduces the identical streams verdict."""
    from rdma_paxos_tpu.chaos.runner import NemesisRunner
    verdicts = []
    for _ in range(2):
        r = NemesisRunner(seed=11, steps=100,
                          fault_kinds=("crash", "partition"),
                          streams=True)
        v = r.run()
        assert v["ok"], v
        s = v["streams"]
        assert s["dups"] == 0 and s["gaps"] == 0 and s["ordered"]
        assert s["events"] == s["expected"] > 0
        assert s["resumes"] == 2
        verdicts.append({k: s[k] for k in ("events", "expected",
                                           "dups", "gaps", "ordered",
                                           "resumes")})
    assert verdicts[0] == verdicts[1]


# ---------------------------------------------------------------------------
# CDC export: digest verification, tamper detection, CLI
# ---------------------------------------------------------------------------

def test_chain_link_is_order_sensitive():
    a = chain_link(0, 0, 1, 5, 3, 9, 1, b"payload")
    assert a == chain_link(0, 0, 1, 5, 3, 9, 1, b"payload")
    assert a != chain_link(0, 0, 1, 6, 3, 9, 1, b"payload")
    assert a != chain_link(1, 0, 1, 5, 3, 9, 1, b"payload")
    assert a != chain_link(0, 0, 1, 5, 3, 9, 1, b"payloae")


def test_cdc_export_verifies_and_flipped_byte_is_named(tmp_path):
    cdc_path = str(tmp_path / "cdc.jsonl")
    c = SimCluster(CFG, 3, audit=True)
    c.obs = Observability()
    reads_mod.attach(c)
    hub = streams_mod.attach(c, cdc_path=cdc_path, auditor=c.auditor)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    for i in range(8):
        _put_committed(c, kv, 0, b"k%d" % i, b"v%d" % i, i + 1)
    # flush: wait for the async pump, then close the sink
    target = hub.tails[0].length()
    deadline = time.time() + 5
    while (hub.watch.cursors().get(0, 0) < target
           and time.time() < deadline):
        time.sleep(0.01)
    hub.fail_all("test flush")
    dump = c.auditor.dump()
    v = verify_export(cdc_path, [dump])
    assert v["ok"] and v["records"] > 0 and v["checked_digests"] > 0
    # tamper: flip one payload byte -> FAIL naming the first bad entry
    data = open(cdc_path, "r").read().splitlines()
    rec0 = json.loads(data[0])
    p = rec0["payload"]
    rec0["payload"] = ("0" if p[0] != "0" else "1") + p[1:]
    bad_path = str(tmp_path / "cdc_bad.jsonl")
    with open(bad_path, "w") as f:
        f.write("\n".join([json.dumps(rec0)] + data[1:]) + "\n")
    v2 = verify_export(bad_path, [dump])
    assert not v2["ok"]
    assert v2["bad"] == (rec0["term"], rec0["index"])
    # the CLI is the operator surface: 0 on clean, 1 naming the entry
    audit_path = str(tmp_path / "audit.json")
    with open(audit_path, "w") as f:
        json.dump(dump, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, "-m", "rdma_paxos_tpu.streams", "verify",
         cdc_path, audit_path], capture_output=True, text=True,
        env=env)
    assert ok.returncode == 0, ok.stderr
    bad = subprocess.run(
        [sys.executable, "-m", "rdma_paxos_tpu.streams", "verify",
         bad_path, audit_path], capture_output=True, text=True,
        env=env)
    assert bad.returncode == 1
    assert "term=%d" % rec0["term"] in bad.stderr
    assert "index=%d" % rec0["index"] in bad.stderr


# ---------------------------------------------------------------------------
# sharded engines: range fan-out, router narrowing
# ---------------------------------------------------------------------------

def test_sharded_scan_fans_out_and_router_narrows():
    from rdma_paxos_tpu.shard.cluster import ShardedCluster
    from rdma_paxos_tpu.shard.kvs import ShardedKVS
    from rdma_paxos_tpu.shard.router import KeyRouter, RangeRule

    router = KeyRouter(4, overrides=[RangeRule(b"pin/", b"pin0", 2)])
    sc = ShardedCluster(CFG, 3, 4, router=router)
    sc.obs = Observability()
    reads_mod.attach(sc)
    hub = streams_mod.attach(sc)
    sc.place_leaders()
    for _ in range(4):
        sc.step()
    holders = sc.leases.holders()
    kvs = ShardedKVS(sc, cap=256)
    hub.kvs = kvs
    keys = ([b"user/%02d" % i for i in range(12)]
            + [b"pin/%02d" % i for i in range(4)])
    req = {}
    for k in keys:
        g = kvs.group_of(k)
        r = req[g] = req.get(g, 0) + 1
        kvs.groups[g].put(holders[g], k, b"V" + k, client_id=5,
                          req_id=r)
        for _ in range(5):
            sc.step()
    assert len({kvs.group_of(k) for k in keys}) > 1   # really scatters
    # router narrowing: the pinned range maps to exactly one group
    lo, hi = key_range(prefix=b"pin/")
    assert groups_for_range(router, lo, hi) == [2]
    assert groups_for_range(router, *key_range(prefix=b"user/")) \
        == [0, 1, 2, 3]
    rows = _serve_blocking(
        sc, lambda: hub.scan_all(prefix=b"user/", limit=5), 2000)
    assert [k for k, _ in rows] == sorted(
        b"user/%02d" % i for i in range(12))      # merge-sorted
    assert all(v == b"V" + k for k, v in rows)
    pins = _serve_blocking(
        sc, lambda: hub.scan_all(prefix=b"pin/", limit=8), 2000)
    assert [k for k, _ in pins] == sorted(
        b"pin/%02d" % i for i in range(4))
    # the narrowed scan only ever touched group 2's index
    assert sc.obs.metrics.get("scan_pages_total", group=2) >= 1
    folded = hub.scans.status()["folded"]
    touched = {g for g, pos in folded.items() if pos > 0}
    assert 2 in touched
    hub.fail_all("test done")


def test_sharded_watch_isolates_groups():
    # regression: the pump fans each group's decoded batch over ALL
    # subscriptions, so Subscription._matches must check the group —
    # before it did, a G>1 subscriber received sibling groups' events
    # too (every single-group watch test passes that vacuously)
    from rdma_paxos_tpu.shard.cluster import ShardedCluster
    from rdma_paxos_tpu.shard.kvs import ShardedKVS

    sc = ShardedCluster(CFG, 3, 2)
    sc.obs = Observability()
    reads_mod.attach(sc)
    hub = streams_mod.attach(sc)
    sc.place_leaders()
    for _ in range(4):
        sc.step()
    holders = sc.leases.holders()
    kvs = ShardedKVS(sc, cap=256)
    subs = [hub.subscribe(g) for g in range(2)]
    keys = [b"iso%02d" % i for i in range(12)]
    owner = {k: kvs.group_of(k) for k in keys}
    assert len(set(owner.values())) == 2          # both groups written
    req = {}
    for k in keys:
        g = owner[k]
        r = req[g] = req.get(g, 0) + 1
        kvs.groups[g].put(holders[g], k, b"V" + k, client_id=6,
                          req_id=r)
        for _ in range(5):
            sc.step()
    assert hub.watch.wait_caught_up(
        {g: hub.tails[g].length() for g in range(2)})
    for g, sub in enumerate(subs):
        evs = sub.poll(max_n=256)
        assert evs and all(e.group == g for e in evs)
        assert sorted(e.key for e in evs) == sorted(
            k for k in keys if owner[k] == g)
    assert hub.watch.events_total == len(keys)    # each delivered once
    hub.fail_all("test done")


# ---------------------------------------------------------------------------
# cache-key guard + bit-identity (attached vs detached)
# ---------------------------------------------------------------------------

def test_streams_add_zero_step_cache_keys():
    # a geometry no other test uses: this guard reasons about which
    # keys THIS test's clusters add to the shared cache
    cfg = LogConfig(n_slots=64, slot_bytes=256, window_slots=8,
                    batch_slots=4)
    plain = SimCluster(cfg, 3)
    plain.run_until_elected(0)
    plain.submit(0, b"x")
    plain.step()
    keys_before = set(STEP_CACHE)

    attached = SimCluster(cfg, 3)
    attached.obs = Observability()
    reads_mod.attach(attached)
    hub = streams_mod.attach(attached)
    attached.run_until_elected(0)
    kv = ReplicatedKVS(attached, cap=256)
    hub.kvs = kv
    sub = hub.subscribe(0)
    for i in range(4):
        _put_committed(attached, kv, 0, b"k%d" % i, b"v", i + 1)
    rows = _serve_blocking(
        attached, lambda: hub.scan_all(prefix=b"k", limit=2))
    assert len(rows) == 4 and len(_drain(sub, 4)) == 4
    # the WHOLE streams surface (tails + scans + watch + pump) added
    # ZERO compiled-step cache keys: pure host bookkeeping
    assert set(STEP_CACHE) == keys_before
    hub.fail_all("test done")


def test_streams_outputs_bit_identical_attached_vs_detached():
    a = SimCluster(CFG, 3)
    b = SimCluster(CFG, 3)
    b.obs = Observability()
    reads_mod.attach(b)
    hub = streams_mod.attach(b)
    for c in (a, b):
        c.run_until_elected(0)
    kva = ReplicatedKVS(a, cap=256)
    kvb = ReplicatedKVS(b, cap=256)
    sub = hub.subscribe(0)
    for i in range(5):
        kva.put(0, b"k%d" % i, b"v%d" % i, client_id=3, req_id=i + 1)
        kvb.put(0, b"k%d" % i, b"v%d" % i, client_id=3, req_id=i + 1)
        a.step()
        b.step()
    # a scan serving on b while BOTH step in lockstep
    box = {}

    def work():
        box["rows"] = hub.scan_all(prefix=b"k", limit=2)

    th = threading.Thread(target=work)
    th.start()
    for _ in range(100):
        a.step()
        b.step()
        if not th.is_alive():
            break
    th.join(10)
    assert len(box["rows"]) == 5 and len(_drain(sub, 5)) == 5
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(a.last[k], b.last[k]), k
    hub.fail_all("test done")


# ---------------------------------------------------------------------------
# S2: drain-path decoupling — a wedged watcher never delays reads
# ---------------------------------------------------------------------------

def test_wedged_watcher_does_not_delay_point_reads():
    """The decoupling pin: a subscriber that NEVER polls (tiny queue,
    overflowed) wedges only ITSELF — the pump thread keeps the
    ReadHub drain path untouched, so a queued read-index point read
    still completes in the same couple of steps it needs with no
    watcher at all."""
    c, hub = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    hub.kvs = kv
    wedged = hub.subscribe(0, cap=2)          # never polled
    for i in range(12):
        _put_committed(c, kv, 0, b"k%02d" % i, b"v", i + 1)
    deadline = time.time() + 5
    while not wedged.overflowed and time.time() < deadline:
        time.sleep(0.005)
    assert wedged.overflowed          # backpressure is EXPLICIT
    # point read through the hub with the watcher still wedged
    t = c.reads.submit(lambda: kv.serve_local(1, b"k00"), replica=1)
    steps = 0
    for _ in range(4):
        if t.done:
            break
        c.step()
        steps += 1
    assert t.done and t.status == "ok" and t.value == b"v"
    assert steps <= 3                 # unchanged point-read latency
    # backlog is visible as governor demand + gauge
    assert hub.backlogs()[0] >= 0
    assert c.obs.metrics.get("watch_backlog_entries", group=0) >= 0
    hub.fail_all("test done")
    assert wedged.closed and wedged.fail_reason == "test done"
    assert len(wedged.poll(max_n=16)) <= 2    # the bounded remnant
    assert wedged.next(timeout=0.1) is None   # wakes, never hangs


# ---------------------------------------------------------------------------
# S1: runtime lock sanitizer armed on the streams hubs
# ---------------------------------------------------------------------------

def test_rp_sanitize_arms_streams_hubs(monkeypatch):
    monkeypatch.setenv("RP_SANITIZE", "1")
    from rdma_paxos_tpu.analysis.runtime_guard import (
        LockDisciplineError)
    c = SimCluster(CFG, 3)
    c.obs = Observability()
    reads_mod.attach(c)
    hub = streams_mod.attach(c)
    assert type(hub).__name__.endswith("+sanitized")
    assert type(hub.watch).__name__.endswith("+sanitized")
    assert type(hub.scans).__name__.endswith("+sanitized")
    # the guarded surface still works end to end under the sanitizer
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    sub = hub.subscribe(0)
    _put_committed(c, kv, 0, b"k", b"v", 1)
    assert len(_drain(sub, 1)) == 1
    # ...and an unlocked write of a guarded field is CAUGHT
    with pytest.raises(LockDisciplineError):
        hub.watch.events_total = 99
    hub.fail_all("test done")


# ---------------------------------------------------------------------------
# wiring: driver lifecycle, alert rule, governor demand
# ---------------------------------------------------------------------------

def test_driver_streams_wiring_health_and_stop():
    from rdma_paxos_tpu.config import TimeoutConfig
    from rdma_paxos_tpu.obs.health import validate_cluster
    from rdma_paxos_tpu.runtime.driver import ClusterDriver
    tcfg = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)
    d = ClusterDriver(CFG, 3, timeout_cfg=tcfg, streams=True)
    try:
        hub = d.cluster.streams
        assert hub is not None and hub.cdc is None   # no workdir
        h = d.health()
        assert validate_cluster(h) == []
        assert h["streams"]["stopped"] is False
        sub = hub.subscribe(0)
        waiter = {}

        def blocked():
            waiter["got"] = sub.next(timeout=30)

        th = threading.Thread(target=blocked)
        th.start()
        time.sleep(0.1)
    finally:
        d.stop()
    th.join(5)
    assert not th.is_alive()          # stop released the watcher
    assert waiter["got"] is None and sub.closed
    assert sub.fail_reason == "stop"
    assert d.cluster.streams.status()["stopped"] is True


def test_driver_streams_off_by_default():
    from rdma_paxos_tpu.config import TimeoutConfig
    from rdma_paxos_tpu.runtime.driver import ClusterDriver
    tcfg = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)
    d = ClusterDriver(CFG, 3, timeout_cfg=tcfg)
    assert d.cluster.streams is None
    assert d.health()["streams"] is None
    d.stop()


def test_cdc_backpressure_alert_rule_in_defaults():
    from rdma_paxos_tpu.obs.alerts import default_rules
    rules = {r["name"]: r for r in default_rules()}
    r = rules["cdc_backpressure"]
    assert r["metric"] == "cdc_lag_entries" and r["op"] == ">"
    assert default_rules(cdc_lag_ceiling=7)[
        [x["name"] for x in default_rules()].index("cdc_backpressure")
    ]["value"] == 7


def test_watch_mix_bench_smoke(tmp_path):
    """S5: the ``run_bench --watch-ratio`` A/B at smoke scale — both
    variants complete the identical committed write mix, the fan-out
    and CDC rows account for every watched write, and the exporter
    finishes the round caught up (lag 0)."""
    from benchmarks.run_bench import measure_watch_mix
    out = measure_watch_mix(0.5, cfg=CFG, n_ops=240, n_keys=8,
                            repeats=1, seed=4,
                            cdc_dir=str(tmp_path))
    assert out["plain"]["writes"] == out["attached"]["writes"] == 240
    # 4 watchers x the watched half of the keyspace
    assert out["attached"]["events"] > 0
    assert out["attached"]["watch_fanout_events_per_sec"] > 0
    assert out["cdc"]["exported"] == 240 and out["cdc"]["lag"] == 0
    assert out["watch"]["overflowed"] == 0


def test_governor_counts_watch_backlog_as_demand():
    from rdma_paxos_tpu.runtime.governor import attach_governor
    c = SimCluster(CFG, 3)
    c.obs = Observability()
    reads_mod.attach(c)
    hub = streams_mod.attach(c)
    gov = attach_governor(c, obs=c.obs)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    hub.subscribe(0, cap=1 << 16)     # deep, never-drained queue
    for i in range(6):
        _put_committed(c, kv, 0, b"k%d" % i, b"v", i + 1)
    # streams backlog reaches the governor's observe without deadlock
    for _ in range(4):
        c.step()
    assert gov.status() is not None
    assert hub.backlogs()[0] >= 1     # the wedged queue is demand
    hub.fail_all("test done")
