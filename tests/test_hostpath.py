"""Vectorized host data plane (runtime/hostpath.py) — bit-identity
pins.

The perf PR's correctness bar: every vectorized operation (window
encode, window decode, frame assembly, replay-run/ack planning) must be
BYTE-IDENTICAL to the scalar reference loops it replaced, on recorded
workloads through every engine (sim, sharded vmap, spmd mesh) and
through the real driver loop. The frames builder is additionally pinned
golden against the legacy two-pass masked-gather implementation."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import (
    EntryType, M_CONN, M_GEN, M_LEN, M_REQID, M_TYPE, META_W)
from rdma_paxos_tpu.runtime import hostpath
from rdma_paxos_tpu.runtime.hostpath import (
    LazyReplayStream, decode_batch, pack_window, plan_segment,
    replay_plan, set_vectorized)

CFG = LogConfig(n_slots=128, slot_bytes=64, window_slots=32,
                batch_slots=8)


@pytest.fixture(autouse=True)
def _restore_mode():
    yield
    set_vectorized(True)


def _rng(seed):
    return np.random.RandomState(seed)


def _random_take(rng, n, slot_bytes, with_empty=True):
    out = []
    for i in range(n):
        choices = [0, 1, slot_bytes // 2, slot_bytes] if with_empty \
            else [1, slot_bytes]
        ln = int(rng.choice(choices)) if rng.rand() < 0.5 \
            else int(rng.randint(0, slot_bytes + 1))
        out.append((int(rng.choice([2, 3, 4])),
                    int(rng.randint(1, 1 << 26)),
                    int(rng.randint(0, 1 << 30)),
                    rng.bytes(ln)))
    return out


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _pack_both(take, slot_bytes, gen=None):
    bufs = []
    for vec in (False, True):
        set_vectorized(vec)
        data = np.zeros((len(take) + 3, slot_bytes // 4), np.int32)
        meta = np.zeros((len(take) + 3, META_W), np.int32)
        du8 = data.view(np.uint8).reshape(data.shape[0], -1)
        n = pack_window(du8, meta, take, slot_bytes, gen=gen)
        bufs.append((n, data.copy(), meta.copy()))
    return bufs


def test_pack_vectorized_bit_identical_to_scalar():
    for seed in range(5):
        take = _random_take(_rng(seed), 1 + seed * 7, CFG.slot_bytes)
        (ns, ds, ms), (nv, dv, mv) = _pack_both(take, CFG.slot_bytes)
        assert ns == nv == len(take)
        assert ds.tobytes() == dv.tobytes()
        assert ms.tobytes() == mv.tobytes()


def test_pack_stamps_gen_column():
    take = _random_take(_rng(3), 9, CFG.slot_bytes)
    (_, _, ms), (_, _, mv) = _pack_both(take, CFG.slot_bytes, gen=7)
    assert ms.tobytes() == mv.tobytes()
    assert (mv[:9, M_GEN] == 7).all()


def test_pack_oversize_payload_raises_both_modes():
    take = [(3, 1, 1, b"x" * (CFG.slot_bytes + 1))]
    for vec in (False, True):
        set_vectorized(vec)
        data = np.zeros((4, CFG.slot_bytes // 4), np.int32)
        meta = np.zeros((4, META_W), np.int32)
        du8 = data.view(np.uint8).reshape(4, -1)
        with pytest.raises(ValueError):
            pack_window(du8, meta, take, CFG.slot_bytes)


# ---------------------------------------------------------------------------
# decode + frames
# ---------------------------------------------------------------------------

def _random_window(rng, n, slot_words=CFG.slot_bytes // 4):
    """A synthetic fetched window: client entries interleaved with
    NOOP/CONFIG rows the decode must skip."""
    wm = np.zeros((n, META_W), np.int32)
    wd = rng.randint(-2**31, 2**31 - 1, size=(n, slot_words),
                     dtype=np.int32)
    for j in range(n):
        if rng.rand() < 0.25:
            wm[j, M_TYPE] = int(rng.choice(
                [int(EntryType.NOOP), int(EntryType.CONFIG), 0]))
        else:
            wm[j, M_TYPE] = int(rng.choice([2, 3, 4]))
        wm[j, M_CONN] = rng.randint(1, 1 << 26)
        wm[j, M_REQID] = rng.randint(0, 1 << 30)
        wm[j, M_GEN] = rng.randint(0, 4)
        wm[j, M_LEN] = rng.randint(0, slot_words * 4 + 1)
    return wm, wd


def legacy_assemble_frames(types, conns, lens, raw, idxs) -> bytes:
    """The pre-PR two-pass masked-gather frame assembly — the golden
    reference the offset-table builder is pinned against."""
    row = raw.shape[1]
    cl = lens[idxs].astype(np.uint32)
    mat = np.zeros((idxs.size, 9 + row), np.uint8)
    mat[:, 0:4] = (cl + 5).astype("<u4")[:, None].view(np.uint8)
    mat[:, 4] = types[idxs]
    mat[:, 5:9] = conns[idxs].astype("<i4")[:, None].view(np.uint8)
    mat[:, 9:] = raw[idxs]
    keep = (np.arange(9 + row, dtype=np.uint32)[None]
            < (9 + cl)[:, None])
    return mat[keep].tobytes()


def test_decode_vectorized_bit_identical_to_scalar():
    for seed in range(6):
        wm, wd = _random_window(_rng(seed + 10), 5 + seed * 9)
        n = wm.shape[0]
        set_vectorized(False)
        bs = decode_batch(wm, wd, n)
        set_vectorized(True)
        bv = decode_batch(wm, wd, n)
        if bs is None:
            assert bv is None
            continue
        assert bs.tuples() == bv.tuples()
        assert bs.blob == bv.blob
        assert np.array_equal(bs.gens, bv.gens)
        assert bs.frames() == bv.frames()


def test_frames_golden_against_legacy_masked_gather():
    for seed in range(6):
        wm, wd = _random_window(_rng(seed + 20), 4 + seed * 11)
        n = wm.shape[0]
        types = wm[:n, M_TYPE]
        client = (types >= 2) & (types <= 4)
        idxs = np.nonzero(client)[0]
        if not idxs.size:
            continue
        raw = np.ascontiguousarray(wd[:n]).view(np.uint8).reshape(n, -1)
        # legacy reference clamps payload at the slot width through its
        # keep mask; clamp lens the same way for the comparison
        lens = np.minimum(wm[:n, M_LEN], raw.shape[1])
        golden = legacy_assemble_frames(types, wm[:n, M_CONN], lens,
                                        raw, idxs)
        batch = decode_batch(wm, wd, n)
        assert batch.frames() == golden
        from rdma_paxos_tpu.runtime.sim import assemble_frames
        assert assemble_frames(types, wm[:n, M_CONN], lens, raw,
                               idxs) == golden


def test_decode_zero_and_empty_windows():
    wm = np.zeros((4, META_W), np.int32)     # all EMPTY rows
    wd = np.zeros((4, CFG.slot_bytes // 4), np.int32)
    assert decode_batch(wm, wd, 0) is None
    assert decode_batch(wm, wd, 4) is None


# ---------------------------------------------------------------------------
# replay/ack planning
# ---------------------------------------------------------------------------

def _batch_of(entries):
    n = len(entries)
    types = np.array([e[0] for e in entries], np.int32)
    conns = np.array([e[1] for e in entries], np.int32)
    reqs = np.array([e[2] for e in entries], np.int32)
    lens = np.array([len(e[3]) for e in entries], np.int64)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    return hostpath.ReplayBatch(types, conns, reqs,
                                np.zeros(n, np.int32), lens,
                                b"".join(e[3] for e in entries), offs)


def test_plan_vectorized_bit_identical_to_scalar():
    rng = _rng(42)
    for trial in range(8):
        n = 3 + trial * 6
        entries = []
        for i in range(n):
            origin = int(rng.choice([0, 1]))       # 0 = "own"
            conn = (origin << 24) | int(rng.randint(1, 5))
            etype = int(rng.choice([2, 3, 3, 3, 4]))
            entries.append((etype, conn, i + 1,
                            rng.bytes(int(rng.randint(0, 12)))))
        batch = _batch_of(entries)
        own = (batch.conns >> 24) == 0
        set_vectorized(False)
        ms, os_ = replay_plan(batch, own)
        set_vectorized(True)
        mv, ov = replay_plan(batch, own)
        assert ms == mv
        assert os_ == ov


def test_plan_coalesces_send_runs_across_own_entries():
    # remote SENDs on one conn, interrupted by an OWN entry: the run
    # must NOT flush (the scalar loop never flushed on own entries)
    remote = (1 << 24) | 7
    own = (0 << 24) | 9
    entries = [(3, remote, 1, b"aa"), (3, own, 2, b"xx"),
               (3, remote, 3, b"bb"), (4, remote, 4, b""),
               (3, remote, 5, b"cc")]
    batch = _batch_of(entries)
    mask = (batch.conns >> 24) == 0
    for vec in (False, True):
        set_vectorized(vec)
        own_max, ops = replay_plan(batch, mask)
        assert own_max == 2
        assert ops == [(3, remote, b"aabb"), (4, remote, b""),
                       (3, remote, b"cc")], vec


def test_plan_segment_handles_plain_tuple_lists():
    entries = [(3, (1 << 24) | 3, 5, b"zz"), (3, (0 << 24) | 2, 9, b"q")]
    own_max, ops, n_rem = plan_segment(
        entries, lambda conns, _g: (conns >> 24) == 0)
    assert own_max == 9 and n_rem == 1
    assert ops == [(3, (1 << 24) | 3, b"zz")]


# ---------------------------------------------------------------------------
# the lazy replay stream
# ---------------------------------------------------------------------------

def test_lazy_stream_list_compat_and_segments():
    s = LazyReplayStream()
    b1 = _batch_of([(3, 1, 1, b"a"), (3, 1, 2, b"b")])
    b2 = _batch_of([(4, 2, 3, b"")])
    s.append_batch(b1)
    assert len(s) == 2
    s.append_batch(b2)
    assert len(s) == 3
    # segments at a batch boundary: the batches come back whole
    segs = s.segments_from(2)
    assert len(segs) == 1 and segs[0] is b2
    # mid-batch cursor: a sliced batch
    segs = s.segments_from(1)
    assert [e for seg in segs for e in
            (seg.tuples() if isinstance(seg, hostpath.ReplayBatch)
             else seg)] == s[1:]
    # materialized view: indexing, slicing, equality vs plain lists
    assert s[0] == (3, 1, 1, b"a")
    assert list(s) == b1.tuples() + b2.tuples()
    assert s == b1.tuples() + b2.tuples()
    assert LazyReplayStream(list(s)) == s
    # appends after materialization keep order
    s.append((3, 9, 4, b"z"))
    assert s[-1] == (3, 9, 4, b"z")
    b3 = _batch_of([(3, 5, 5, b"w")])
    s.append_batch(b3)
    assert len(s) == 5 and s[-1] == (3, 5, 5, b"w")
    # segments spanning a materialized head + an unmaterialized tail
    segs = s.segments_from(3)
    flat = [e for seg in segs for e in
            (seg.tuples() if isinstance(seg, hostpath.ReplayBatch)
             else seg)]
    assert flat == [(3, 9, 4, b"z"), (3, 5, 5, b"w")]


# ---------------------------------------------------------------------------
# engine-level recorded workloads: vectorized == scalar
# ---------------------------------------------------------------------------

def _drive_sim(mode="sim"):
    from rdma_paxos_tpu.runtime.sim import SimCluster
    c = SimCluster(CFG, 3, mode=mode)
    c.collect_frames = True
    c.run_until_elected(0)
    rng = _rng(99)
    for i in range(12):
        for p in _random_take(rng, 6, CFG.slot_bytes):
            c.submit(0, p[3], EntryType(p[0] if p[0] in (2, 3, 4)
                                        else 3),
                     conn=p[1], req_id=p[2])
        (c.step_burst if i % 3 else c.step)()
    for _ in range(4):
        c.step()
    return ([list(c.replayed[r]) for r in range(3)],
            [list(c.frames[r]) for r in range(3)],
            c.applied.copy())


@pytest.mark.parametrize("mode", ["sim", "spmd"])
def test_engine_streams_vectorized_equal_scalar(mode):
    set_vectorized(False)
    streams_s, frames_s, applied_s = _drive_sim(mode)
    set_vectorized(True)
    streams_v, frames_v, applied_v = _drive_sim(mode)
    assert streams_s == streams_v
    assert frames_s == frames_v
    assert np.array_equal(applied_s, applied_v)


def _drive_sharded(mesh=None):
    from rdma_paxos_tpu.shard.cluster import ShardedCluster
    c = ShardedCluster(CFG, 2, 2, mesh=mesh)
    c.collect_frames = True
    c.place_leaders()
    rng = _rng(7)
    for i in range(8):
        for g in range(2):
            lead = c.leader_hint(g)
            for p in _random_take(rng, 5, CFG.slot_bytes):
                c.submit(g, lead, p[3], EntryType.SEND,
                         conn=p[1], req_id=p[2])
        (c.step_burst if i % 2 else c.step)()
    for _ in range(4):
        c.step()
    return ([[list(c.replayed[g][r]) for r in range(2)]
             for g in range(2)],
            [[list(c.frames[g][r]) for r in range(2)]
             for g in range(2)])


@pytest.mark.parametrize("mesh", [None, (2, 2)])
def test_sharded_streams_vectorized_equal_scalar(mesh):
    set_vectorized(False)
    streams_s, frames_s = _drive_sharded(mesh)
    set_vectorized(True)
    streams_v, frames_v = _drive_sharded(mesh)
    assert streams_s == streams_v
    assert frames_s == frames_v
