"""Adaptive dispatch governor (runtime/governor.py) — acceptance pins.

* **Ladder-only compile guard** — a governed run serves a full
  climb-and-descend workload with ZERO ``STEP_CACHE`` keys beyond the
  prewarmed ladder, and enabling the governor adds no key an
  ungoverned cluster of the same geometry would not have (the
  governor-off key/program sets are bit-identical to PR 14).
* **Pinned-tier bit-identity** — the governor pinned to a fixed tier
  produces step outputs and replay streams bit-identical to the
  equivalent static dispatch calls.
* **Scripted SLO-shed regression** — the commit-latency burn-rate
  pager fires → the tier drops to serial on the fire transition (well
  inside the 2-eval acceptance bound) → resolves after recovery and
  the ladder re-climbs.
* **Chaos** — a ``pipeline=2`` nemesis schedule with the governor
  attached: zero invariant/linearizability violations, deterministic
  same-seed verdict (governor summary included).
* **Daemon host-agreement** — N independent :class:`HintGovernor`
  instances fed the same gathered-hint sequence derive the same tier
  sequence (the RP_GOVERNOR collective-schedule contract), with the
  admission coalesce bounded.
* **Idle quiescence** — an idle driver skips device dispatches
  (``idle_dispatches_avoided_total``), keeps its leadership, and
  wakes instantly for late traffic.
"""

import time

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
from rdma_paxos_tpu.obs.metrics import (
    LATENCY_BUCKETS_S, MetricsRegistry)
from rdma_paxos_tpu.obs.series import TimeSeriesStore
from rdma_paxos_tpu.runtime.governor import (
    DispatchGovernor, HintGovernor, SHED_RULE, attach_governor,
    tier_label)
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster

CFG = LogConfig(n_slots=512, slot_bytes=128, window_slots=64,
                batch_slots=16)
BLOB = b"g" * 24


def _drive_governed(c, gov, loads):
    """Replay a per-tick arrival list through the governed dispatch
    rule (the driver/bench contract): serial decision -> step(),
    fused decision -> step_burst(max_k=rung)."""
    for n in loads:
        if n:
            c.submit_many(0, [(3, 1, 0, BLOB)] * n)
        d = gov.decision
        if d.max_k > 1 and max(len(q) for q in c.pending):
            c.step_burst(max_k=d.max_k)
        else:
            c.step()
    while int(c.last["commit"].min()) < int(c.last["end"].max()):
        d = gov.decision
        if d.max_k > 1:
            c.step_burst(max_k=d.max_k)
        else:
            c.step()


# ---------------------------------------------------------------------------
# ladder-only compile guard + governor-off bit-identity
# ---------------------------------------------------------------------------

def test_ladder_only_compile_guard():
    """A governed run that provably climbs and descends the whole
    ladder compiles nothing beyond the prewarmed tier set — and the
    governor itself adds zero STEP_CACHE keys over an ungoverned
    cluster of the same (fresh) geometry."""
    cfg = LogConfig(n_slots=1024, slot_bytes=128, window_slots=64,
                    batch_slots=8)      # geometry unique to this test
    base = SimCluster(cfg, 3, fanout="psum")
    base.run_until_elected(0)
    base.prewarm()
    keys_off = {k for k in STEP_CACHE if k[0] == cfg}

    c = SimCluster(cfg, 3, fanout="psum")
    c.run_until_elected(0)
    gov = attach_governor(c, obs=None)
    assert gov.ladder == (1,) + tuple(c.K_TIERS)
    c.prewarm()
    assert {k for k in STEP_CACHE if k[0] == cfg} == keys_off, (
        "attaching the governor changed the compiled key set")
    # storm / valley / storm: walks the ladder up and down
    loads = [60] * 12 + [0] * 20 + [200] * 8 + [0] * 30
    _drive_governed(c, gov, loads)
    assert gov.evals > 0
    assert {k for k in STEP_CACHE if k[0] == cfg} == keys_off, (
        "governed run compiled a program outside the prewarmed ladder")


def test_max_k_cap_never_exceeds_rung():
    """A capped burst never picks a tier above the cap (the engine's
    _tiers rule) — and an out-of-ladder pin is refused."""
    c = SimCluster(CFG, 3, fanout="psum")
    c.run_until_elected(0)
    c.submit_many(0, [(3, 1, 0, BLOB)] * (CFG.batch_slots * 10))
    before = int(c.last["end"].max())
    c.step_burst(max_k=2)
    assert int(c.last["end"].max()) - before <= 2 * CFG.batch_slots
    gov = attach_governor(c, obs=None)
    with pytest.raises(ValueError, match="ladder"):
        gov.pin("burst", 3)
    with pytest.raises(ValueError, match="unknown tier"):
        gov.pin("warp", 4)


# ---------------------------------------------------------------------------
# pinned-tier bit-identity
# ---------------------------------------------------------------------------

RES_COMPARE = ("term", "role", "commit", "apply", "end", "head",
               "accepted")


def _run_recorded(c, dispatch, loads):
    out = []
    for n in loads:
        if n:
            c.submit_many(0, [(3, 1, 0, BLOB)] * n)
        res = dispatch(c)
        out.append({k: np.asarray(res[k]).copy() for k in RES_COMPARE})
    return out


def _assert_streams_equal(a, b, ca, cb):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        for k in RES_COMPARE:
            assert np.array_equal(ra[k], rb[k]), k
    for r in range(3):
        assert list(ca.replayed[r]) == list(cb.replayed[r])


@pytest.mark.parametrize("tier,k", [("serial", 1), ("burst", 4)])
def test_pinned_tier_bit_identity(tier, k):
    """The governor pinned to a fixed tier is bit-identical to the
    equivalent static dispatch: same step outputs, same replay
    streams — the governor can only pick WHICH prewarmed program
    runs, never change what any program computes."""
    loads = [0, 30, 30, 0, 7, 50, 0, 0, 12, 40, 0, 3]

    ca = SimCluster(CFG, 3, fanout="psum")
    ca.run_until_elected(0)
    gov = attach_governor(ca, obs=None)
    gov.pin(tier, k)

    def governed(c):
        d = gov.decision
        assert d.max_k == k
        if d.max_k > 1 and max(len(q) for q in c.pending):
            return c.step_burst(max_k=d.max_k)
        return c.step()

    cb = SimCluster(CFG, 3, fanout="psum")
    cb.run_until_elected(0)

    def static(c):
        if k > 1 and max(len(q) for q in c.pending):
            return c.step_burst(max_k=k)
        return c.step()

    a = _run_recorded(ca, governed, loads)
    b = _run_recorded(cb, static, loads)
    _assert_streams_equal(a, b, ca, cb)


def test_governor_off_outputs_bit_identical():
    """An ATTACHED (unpinned) governor observes but never mutates
    engine state: outputs bit-identical to a governor-less cluster
    when the same dispatch sequence runs."""
    loads = [20, 20, 0, 5, 60, 0]

    def burst_always(c):
        if max(len(q) for q in c.pending):
            return c.step_burst()
        return c.step()

    ca = SimCluster(CFG, 3, fanout="psum")
    ca.run_until_elected(0)
    attach_governor(ca, obs=None)
    cb = SimCluster(CFG, 3, fanout="psum")
    cb.run_until_elected(0)
    a = _run_recorded(ca, burst_always, loads)
    b = _run_recorded(cb, burst_always, loads)
    _assert_streams_equal(a, b, ca, cb)


# ---------------------------------------------------------------------------
# scripted SLO-shed regression
# ---------------------------------------------------------------------------

def test_slo_shed_fires_drops_tier_and_resolves():
    """The commit-latency burn-rate pager sheds the governor: tier
    drops to serial ON the fire transition (within the 2-eval
    acceptance bound), pipelining disengages, coalescing stops; after
    the regression recovers and the pager resolves, the next observe
    clears the latch and the ladder re-climbs."""
    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=256)
    eng = AlertEngine(reg, rules=default_rules(), series=store)

    c = SimCluster(CFG, 3, fanout="psum")
    c.run_until_elected(0)
    gov = attach_governor(c, obs=None, alerts=eng)
    eng.add_hook(gov.on_alert)

    # climb first: a loaded cluster runs a fused tier
    _drive_governed(c, gov, [50] * 6)
    assert gov.decision.max_k > 1

    w = 1000.0

    def drive(n, latency, per=20):
        nonlocal w
        out = []
        for _ in range(n):
            for _ in range(per):
                reg.observe("commit_latency_seconds", latency,
                            buckets=LATENCY_BUCKETS_S, replica=0)
            store.sample(reg.snapshot(), step=store.samples, wall=w)
            w += 5.0
            out.append(eng.evaluate())
        return out

    drive(10, 0.01)
    assert not gov.decision.shed
    fired = False
    for out in drive(70, 2.0):
        if SHED_RULE in out["fired"]:
            fired = True
            break
    assert fired, "the scripted regression never fired the pager"
    # the hook dropped the tier on the fire transition itself —
    # zero further evaluations needed (well inside the 2-eval bound)
    d = gov.decision
    assert d.shed and d.max_k == 1 and not d.pipeline \
        and d.coalesce_us == 0
    assert gov.sheds == 1
    # while shedding, load does NOT climb the ladder
    c.submit_many(0, [(3, 1, 0, BLOB)] * 100)
    c.step()
    assert gov.decision.max_k == 1
    # recovery: the pager resolves, the next observe clears the latch
    resolved = False
    for out in drive(140, 0.01, per=60):
        if SHED_RULE in out["resolved"]:
            resolved = True
            break
    assert resolved, "recovery never resolved the pager"
    _drive_governed(c, gov, [80] * 4)
    assert not gov.decision.shed
    assert gov.decision.max_k > 1, "ladder never re-climbed"


# ---------------------------------------------------------------------------
# chaos: pipeline=2 with the governor attached
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_nemesis_pipeline2_with_governor_deterministic():
    """Chaos schedule driven at pipeline depth 2 WITH the governor
    attached: zero invariant/linearizability violations, and the
    same-seed rerun produces a bit-identical verdict (governor
    decisions are pure step-domain functions of the observed run)."""
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    def run_once():
        runner = NemesisRunner(n_replicas=3, seed=7, steps=50,
                               pipeline=2, governor=True)
        return runner.run()

    v1 = run_once()
    assert v1["ok"], v1
    assert v1["invariant_violations"] == []
    assert v1["linearizability"]["ok"] is True
    assert v1["governor"]["evals"] > 0
    v2 = run_once()
    assert v1 == v2, "same-seed governed chaos verdict diverged"


# ---------------------------------------------------------------------------
# daemon host-agreement (RP_GOVERNOR)
# ---------------------------------------------------------------------------

def test_hint_governor_host_agreement():
    """The multi-host rule: N independent instances fed the identical
    gathered-hint sequence decide identically at every iteration —
    the collective program schedule can never desync."""
    import random
    rng = random.Random("hints")
    hints = [rng.choice([0, 0, 3, 7, 12, 16, 40]) for _ in range(200)]
    govs = [HintGovernor(16) for _ in range(3)]
    seqs = [[g.decide(h) for h in hints] for g in govs]
    assert seqs[0] == seqs[1] == seqs[2]


def test_hint_governor_semantics_and_bounded_coalesce():
    g = HintGovernor(16, coalesce_limit=2)
    assert g.decide(0) == "step"           # idle -> serial heartbeat
    assert g.decide(16) == "burst"         # full batch -> burst
    assert g.decide(2) == "burst"          # falling small backlog ships
    # rising small backlog coalesces, but BOUNDED: after the limit the
    # partial window ships regardless
    assert g.decide(4) == "coalesce"
    assert g.decide(6) == "coalesce"
    assert g.decide(8) == "burst"
    # a fresh rise re-arms the budget
    assert g.decide(9) == "coalesce"


# ---------------------------------------------------------------------------
# per-group decisions (sharded engine)
# ---------------------------------------------------------------------------

def test_single_group_sharded_backlog_shape():
    """Regression: a G==1 ShardedCluster nests pending as [G][R] like
    any other group count — the governor must read queue DEPTHS, not
    the replica-list length (which read as a phantom backlog of R)."""
    from rdma_paxos_tpu.shard.cluster import ShardedCluster
    sc = ShardedCluster(CFG, 3, 1, fanout="gather")
    gov = attach_governor(sc, obs=None)
    assert gov._backlogs(sc) == [0]
    sc.place_leaders()
    leader = int(np.argmax(sc.last["role"][0] == int(Role.LEADER)))
    sc.submit_many(0, leader, [(3, 1, 0, BLOB)] * 7)
    assert gov._backlogs(sc) == [7]


def test_serial_cap_refused_not_smallest_burst():
    """Regression: ``max_k <= 1`` means the SERIAL step (the SLO-shed
    contract) — a capped burst must refuse loudly, never silently
    dispatch the smallest fused tier."""
    c = SimCluster(CFG, 3, fanout="psum")
    c.run_until_elected(0)
    c.submit_many(0, [(3, 1, 0, BLOB)] * 4)
    with pytest.raises(ValueError, match="serial step"):
        c.step_burst(max_k=1)


def test_sharded_per_group_rungs():
    """One loaded group climbs its rung while an idle group descends
    to serial — the dispatch cap is the max rung (one program spans
    all groups), and per-group rungs ride the decision."""
    from rdma_paxos_tpu.shard.cluster import ShardedCluster
    sc = ShardedCluster(CFG, 3, 2, fanout="gather")
    sc.place_leaders()
    gov = attach_governor(sc, obs=None)
    assert gov.G == 2
    # group 0 gets a standing backlog; group 1 stays idle
    for _ in range(8):
        leader0 = int(np.argmax(
            sc.last["role"][0] == int(Role.LEADER)))
        sc.submit_many(0, leader0, [(3, 1, 0, BLOB)] * 80)
        d = gov.decision
        if d.max_k > 1:
            sc.step_burst(max_k=d.max_k)
        else:
            sc.step()
    d = gov.decision
    assert d.rungs[0] > 1, d
    assert d.max_k == max(d.rungs)
    assert d.rungs[1] <= d.rungs[0]


# ---------------------------------------------------------------------------
# idle quiescence (driver)
# ---------------------------------------------------------------------------

def test_idle_quiescence_skips_dispatches_and_wakes():
    """An idle driver parks instead of free-running heartbeat
    dispatches: idle_dispatches_avoided_total advances, leadership
    stays put (the margin rule re-heartbeats before any follower
    timer), and a late submission wakes the loop and commits."""
    from rdma_paxos_tpu.runtime.driver import ClusterDriver
    d = ClusterDriver(CFG, 3, fanout="psum", pipeline=2)
    d.prewarm()
    d.run(period=0.01)
    try:
        t0 = time.time()
        while d.leader() < 0:
            assert time.time() - t0 < 60, "no leader"
            time.sleep(0.01)
        lead = d.leader()
        term0 = int(d.cluster.last["term"].max())
        time.sleep(1.0)                      # idle phase
        snap = d.obs.metrics.snapshot()
        avoided = snap["counters"].get(
            "idle_dispatches_avoided_total", 0)
        assert avoided > 0, "idle loop never quiesced"
        assert d.leader() == lead
        assert int(d.cluster.last["term"].max()) == term0, (
            "quiescence churned leadership")
        # late traffic: the wake path must serve it promptly
        base = (int(d.cluster.last["commit"].max())
                + d.cluster.rebased_total)
        d.cluster.submit_many(lead, [(3, 1, 0, BLOB)] * 5)
        d._wake.set()
        t0 = time.time()
        while (int(d.cluster.last["commit"].max())
               + d.cluster.rebased_total) < base + 5:
            assert time.time() - t0 < 30, "late submit never committed"
            time.sleep(0.005)
    finally:
        d.stop()
    assert d.loop_error is None


def test_idle_quiesce_disabled_keeps_stepping():
    """idle_quiesce=False restores the free-running loop (the A/B
    bench's off-variant): no skips are counted."""
    from rdma_paxos_tpu.runtime.driver import ClusterDriver
    d = ClusterDriver(CFG, 3, fanout="psum", pipeline=2,
                      idle_quiesce=False)
    d.prewarm()
    d.run(period=0.001)
    try:
        t0 = time.time()
        while d.leader() < 0:
            assert time.time() - t0 < 60
            time.sleep(0.01)
        time.sleep(0.3)
        snap = d.obs.metrics.snapshot()
        assert "idle_dispatches_avoided_total" not in snap["counters"]
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# governed driver e2e
# ---------------------------------------------------------------------------

def test_governed_driver_serves_and_reports():
    """A governor=True driver serves a queued workload end to end:
    all entries commit, dispatch_tier counters show fused tiers were
    used, the governor status rides health(), and stop() is clean."""
    from rdma_paxos_tpu.runtime.driver import ClusterDriver
    d = ClusterDriver(CFG, 3, fanout="psum", governor=True, pipeline=2)
    d.prewarm()
    d.run(period=0.01)
    try:
        t0 = time.time()
        while d.leader() < 0:
            assert time.time() - t0 < 60
            time.sleep(0.01)
        lead = d.leader()
        base = (int(d.cluster.last["commit"].max())
                + d.cluster.rebased_total)
        total = 600
        for _ in range(20):
            d.cluster.submit_many(lead, [(3, 1, 0, BLOB)] * 30)
            d._wake.set()
            time.sleep(0.002)
        t0 = time.time()
        while (int(d.cluster.last["commit"].max())
               + d.cluster.rebased_total) < base + total:
            assert time.time() - t0 < 60, "workload never drained"
            time.sleep(0.01)
        snap = d.obs.metrics.snapshot()
        tiers = {k: v for k, v in snap["counters"].items()
                 if k.startswith("dispatch_tier")}
        assert any("burst" in k or "scan" in k for k in tiers), tiers
        h = d.health()
        assert h["governor"] is not None
        assert h["governor"]["ladder"] == [1] + list(d.cluster.K_TIERS)
    finally:
        d.stop()
    assert d.loop_error is None


def test_tier_label():
    assert tier_label("serial", 1) == "serial"
    assert tier_label("burst", 8) == "burst8"
    assert tier_label("scan", 16) == "scan16"


def test_coalesce_decision_bounded_and_off_while_shed():
    """Coalescing engages only at high arrival with a filling window,
    is capped at the configured bound, and is forced off by a shed."""
    gov = DispatchGovernor(batch_slots=16, ladder=(2, 4, 8, 16),
                           coalesce_us=250)

    class _FakeLock:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    class _Fake:
        _host_lock = _FakeLock()
        scan = False

        def __init__(self, backlog):
            self.pending = [[0] * backlog]

    # climb to a high rung, then dip the backlog below the held
    # tier's half-window while arrival stays hot: the window is
    # filling -> bounded coalesce (descent hysteresis keeps the rung)
    for backlog in (100, 100, 40):
        gov.observe(_Fake(backlog), dict(accepted=np.array([16, 0, 0])))
    d = gov.decision
    assert d.max_k == 8
    assert 0 < d.coalesce_us <= 250
    gov.on_alert(SHED_RULE, "page")
    d = gov.decision
    assert d.shed and d.coalesce_us == 0 and d.max_k == 1


def test_arrival_trace_determinism():
    """The bench traces replay bit-identically per (shape, seed) and
    differ across seeds (actually seeded)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from benchmarks.arrival_traces import SHAPES, make_trace
    for shape in SHAPES:
        a = make_trace(shape, 200, seed=3, hi=96)
        b = make_trace(shape, 200, seed=3, hi=96)
        assert a == b
        assert a != make_trace(shape, 200, seed=4, hi=96)
        assert len(a) == 200 and all(v >= 0 for v in a)
