"""Lost-majority step-down — the reference leader SUICIDES when it fails
to reach a majority (``dare_server.c:1213-1217``). Here the equivalent is
service-level: a leader whose ``leadership_verified`` stays 0 for
``step_down_steps`` consecutive steps fails its blocked commit waiters,
severs/refuses replicated sessions, and resumes only when re-verified or
deposed (strictly better than the reference's process exit, which can
never resume)."""

import os
import socket
import subprocess
import threading
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
CFG = LogConfig(n_slots=256, slot_bytes=128, window_slots=32, batch_slots=16)
PORTS = [7421, 7422, 7423]


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


def test_minority_leader_steps_down_and_severs_clients(tmp_path):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            timeout_cfg=TimeoutConfig(elec_timeout_low=0.4,
                                      elec_timeout_high=0.8),
            step_down_steps=10)
        for r, port in enumerate(PORTS):
            env = dict(os.environ)
            env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
            env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path),
                                                f"proxy{r}.sock")
            apps.append(subprocess.Popen(
                [os.path.join(NATIVE, "toyserver"), str(port)], env=env,
                stderr=subprocess.DEVNULL))
        time.sleep(0.3)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        lead = driver.leader()
        assert lead >= 0

        # a committed write, then a client parked on the leader
        c = socket.create_connection(("127.0.0.1", PORTS[lead]), timeout=10)
        f = c.makefile("rb")
        c.sendall(b"SET before ok\n")
        assert f.readline().strip() == b"+OK"

        # isolate the leader WITH the client attached; its next write
        # can never commit
        driver.cluster.partition([[lead],
                                  [r for r in range(3) if r != lead]])
        c.sendall(b"SET never commits\n")

        # the leader must step down (not hang the client forever): the
        # held reply is dropped and the connection severed
        got = []

        def reader():
            try:
                got.append(f.readline())
            except OSError:
                got.append(b"")
        t = threading.Thread(target=reader, daemon=True)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive(), "client still parked: no step-down"
        assert got[0] == b"", "stale leader answered an uncommitted write"
        assert lead in driver.stepped_down
        c.close()

        # new sessions on the stepped-down leader are refused while the
        # partition lasts
        s2 = socket.create_connection(("127.0.0.1", PORTS[lead]), timeout=5)
        s2.settimeout(5)
        try:
            s2.sendall(b"GET before\n")
            refused = s2.recv(64) == b""
        except OSError:
            refused = True
        s2.close()
        assert refused, "stepped-down leader served a session"

        # heal: a new leader exists (majority side elected), the old one
        # is deposed and leaves the stepped_down set
        deadline = time.time() + 60
        while time.time() < deadline:
            nl = driver.leader()
            if nl >= 0 and nl != lead:
                break
            time.sleep(0.05)
        driver.cluster.heal()
        deadline = time.time() + 30
        while lead in driver.stepped_down and time.time() < deadline:
            time.sleep(0.05)
        assert lead not in driver.stepped_down, "step-down did not clear"
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()
