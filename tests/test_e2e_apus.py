"""Full-stack APUS end-to-end: an UNMODIFIED TCP key-value server is made
fault-tolerant by LD_PRELOAD interposition + the TPU-native consensus core.

Topology (the reference's run.sh scenario, §3.2/§3.3 call stacks, collapsed
onto one host): three toyserver processes (one per replica) run under
``LD_PRELOAD=interpose.so`` with ``RP_PROXY_SOCK`` pointing at their
replica's driver socket; one ClusterDriver process simulates the 3-replica
consensus group; a real TCP client talks to the leader's app; followers'
apps receive the identical byte stream via loopback replay.
"""

import os
import socket
import subprocess
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

CFG = LogConfig(n_slots=256, slot_bytes=128, window_slots=32, batch_slots=16)
PORTS = [7301, 7302, 7303]


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)


class Client:
    def __init__(self, port):
        self.s = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.f = self.s.makefile("rb")

    def cmd(self, line: str) -> bytes:
        self.s.sendall(line.encode() + b"\n")
        return self.f.readline().strip()

    def close(self):
        try:
            self.s.close()
        except OSError:
            pass


@pytest.fixture()
def stack(tmp_path):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            timeout_cfg=TimeoutConfig(elec_timeout_low=0.3,
                                      elec_timeout_high=0.6))
        for r, port in enumerate(PORTS):
            env = dict(os.environ)
            env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
            env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path),
                                                f"proxy{r}.sock")
            apps.append(subprocess.Popen(
                [os.path.join(NATIVE, "toyserver"), str(port)], env=env,
                stderr=subprocess.DEVNULL))
        time.sleep(0.3)            # let apps bind
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.leader() >= 0, "no leader elected"
        yield driver
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()


def wait_kv(port, key, want, timeout=15.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            c = Client(port)
            last = c.cmd(f"GET {key}")
            c.close()
            if last == want:
                return last
        except OSError:
            pass
        time.sleep(0.1)
    return last


def test_replicated_set_reaches_followers(stack):
    driver = stack
    lead = driver.leader()
    c = Client(PORTS[lead])
    assert c.cmd("SET alpha 1") == b"+OK"
    assert c.cmd("SET beta two") == b"+OK"
    assert c.cmd("GET alpha") == b"1"
    c.close()
    for r in range(3):
        if r == lead:
            continue
        assert wait_kv(PORTS[r], "alpha", b"1") == b"1", f"replica {r}"
        assert wait_kv(PORTS[r], "beta", b"two") == b"two", f"replica {r}"


def test_failover_preserves_state_and_serves_writes(stack):
    driver = stack
    lead = driver.leader()
    c = Client(PORTS[lead])
    assert c.cmd("SET durable yes") == b"+OK"
    c.close()
    for r in range(3):
        assert wait_kv(PORTS[r], "durable", b"yes") == b"yes"

    # crash the leader replica (driver-side partition = dead consensus node)
    driver.cluster.partition([[lead], [r for r in range(3) if r != lead]])
    deadline = time.time() + 60
    while time.time() < deadline:
        nl = driver.leader()
        if nl >= 0 and nl != lead:
            break
        time.sleep(0.05)
    new_lead = driver.leader()
    assert new_lead >= 0 and new_lead != lead, "failover did not happen"

    # the new leader's app already holds the replicated state…
    c = Client(PORTS[new_lead])
    assert c.cmd("GET durable") == b"yes"
    # …and serves new writes that replicate to the remaining follower
    assert c.cmd("SET after failover-ok") == b"+OK"
    c.close()
    other = next(r for r in range(3) if r not in (lead, new_lead))
    assert wait_kv(PORTS[other], "after", b"failover-ok") == b"failover-ok"


def test_events_persisted_to_stable_store(stack):
    driver = stack
    lead = driver.leader()
    c = Client(PORTS[lead])
    c.cmd("SET persisted 42")
    c.close()
    time.sleep(1.0)
    # every replica persisted the CONNECT/SEND/CLOSE stream natively
    for rt in driver.runtimes:
        assert rt.store is not None and len(rt.store) >= 2
