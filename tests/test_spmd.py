"""shard_map path: the identical protocol program over a real 8-device mesh
(virtual CPU devices here; one replica per TPU chip in production). This is
the compilation/sharding contract the driver's dryrun validates."""

import jax
import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def test_spmd_replication_8_replicas():
    c = SimCluster(CFG, 8, mode="spmd")
    c.run_until_elected(0)
    c.submit(0, b"spmd!")
    res = c.step()
    assert res["commit"][0] == 2
    res = c.step()
    assert list(res["commit"]) == [2] * 8
    for r in range(8):
        assert [p for (_, _, _, p) in c.replayed[r]] == [b"spmd!"]


@pytest.mark.parametrize("mode", ["sim", "spmd"])
def test_psum_fanout_matches_gather(mode):
    """The O(W) psum window broadcast must be observably identical to the
    O(R·W) gather-select fan-out under full connectivity (the only regime
    it is specified for): same commits, same replayed bytes, same log.
    Parametrized over both execution modes because the collective
    LOWERING differs only under ``shard_map`` (a real masked all-reduce
    vs an all-gather + select); the vmap simulation lowers both to data
    movement on one device."""
    runs = {}
    for fo in ("gather", "psum"):
        c = SimCluster(CFG, 5, mode=mode, fanout=fo)
        c.run_until_elected(0)
        for i in range(6):
            c.submit(0, b"op-%d" % i)
            c.step()
        # leadership churn under full connectivity: new leader takes over
        c.step(timeouts=[2])
        c.submit(2, b"after-churn")
        for _ in range(3):
            res = c.step()
        runs[fo] = (res, c.replayed, np.asarray(c.state.log.buf))
    rg, replg, bufg = runs["gather"]
    rp, replp, bufp = runs["psum"]
    for k in ("term", "role", "commit", "end", "head"):
        assert list(rg[k]) == list(rp[k]), k
    assert replg == replp
    assert (bufg == bufp).all()


def test_spmd_group3_with_learners():
    """Mesh bigger than the voting group: replicas outside the membership
    bitmask are learners — they absorb the log but neither vote nor count
    toward quorum (the joiner state of the reference before its CONFIG
    entry commits, dare_ibv_ud.c:972-1068)."""
    c = SimCluster(CFG, 8, group_size=3, mode="spmd")
    c.run_until_elected(1)
    c.submit(1, b"learn")
    c.step()
    res = c.step()
    # everyone (members + learners) converges on the log...
    assert list(res["end"]) == [2] * 8
    # ...and commit required only the 3-member quorum
    assert res["commit"][1] == 2


def test_spmd_failover():
    c = SimCluster(CFG, 8, mode="spmd")
    c.run_until_elected(0)
    c.submit(0, b"pre")
    c.step()
    c.step()
    c.partition([[0], list(range(1, 8))])
    res = c.step(timeouts=[3])
    assert res["role"][3] == int(Role.LEADER)
    c.submit(3, b"post")
    res = c.step()
    assert res["commit"][3] == 4
