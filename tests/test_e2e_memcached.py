"""Full-stack e2e on PRISTINE memcached 1.4.21 — the reference's second
proof app (``/root/reference/apps/memcached/mk``, driven by
``benchmarks/run.sh:74-76``), replicated with zero modifications.

memcached exercises what Redis does not: a MULTI-THREADED event-loop
server (4 worker threads, connections handed off the accept thread via a
notify pipe), `sendmsg`-based replies (the shim's held-output path must
hook scatter-gather output, not just write()), and libevent-driven IO —
built here against the in-repo miniev compat library (native/miniev)
because the image carries no libevent dev headers.

Mirrors the Redis suite: replication to followers, bulk state equality,
and a NON-idempotent op (incr) applied exactly once on followers.
"""

import os
import socket
import subprocess
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
MK = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "apps", "memcached", "mk")
BUILD = "/tmp/rp_memcached_build"
BIN = os.path.join(BUILD, "memcached-1.4.21", "memcached")

CFG = LogConfig(n_slots=512, slot_bytes=256, window_slots=64,
                batch_slots=32)
PORTS = [7401, 7402, 7403]


def ensure_memcached() -> str:
    if os.path.exists(BIN):
        return BIN
    r = subprocess.run(["sh", MK, BUILD], capture_output=True, timeout=600)
    if r.returncode != 0 or not os.path.exists(BIN):
        pytest.skip("memcached build unavailable: %s"
                    % r.stderr.decode()[-200:])
    return BIN


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)
    ensure_memcached()


class McClient:
    """Minimal memcached text-protocol client."""

    def __init__(self, port):
        self.s = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.f = self.s.makefile("rb")

    def set(self, key, val: bytes) -> bytes:
        self.s.sendall(b"set %s 0 0 %d\r\n%s\r\n"
                       % (key.encode(), len(val), val))
        return self.f.readline().strip()

    def get(self, key):
        self.s.sendall(b"get %s\r\n" % key.encode())
        hdr = self.f.readline().strip()
        if hdr == b"END":
            return None
        n = int(hdr.rsplit(b" ", 1)[1])
        val = self.f.read(n)
        self.f.readline()              # trailing \r\n
        assert self.f.readline().strip() == b"END"
        return val

    def incr(self, key, by: int) -> bytes:
        self.s.sendall(b"incr %s %d\r\n" % (key.encode(), by))
        return self.f.readline().strip()

    def close(self):
        try:
            self.s.close()
        except OSError:
            pass


@pytest.fixture()
def stack(tmp_path):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            timeout_cfg=TimeoutConfig(elec_timeout_low=0.3,
                                      elec_timeout_high=0.6))
        for r, port in enumerate(PORTS):
            env = dict(os.environ)
            env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
            env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path),
                                                f"proxy{r}.sock")
            # -U 0: UDP off (recvfrom is outside the hooked surface,
            # matching the reference's TCP-only replication scope)
            apps.append(subprocess.Popen(
                [BIN, "-p", str(port), "-U", "0", "-l", "127.0.0.1",
                 "-u", "root"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        for port in PORTS:
            deadline = time.time() + 20
            while True:
                try:
                    socket.create_connection(("127.0.0.1", port),
                                             timeout=1).close()
                    break
                except OSError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.1)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.leader() >= 0, "no leader elected"
        yield driver
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()


def wait_get(port, key, want, timeout=20.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            c = McClient(port)
            last = c.get(key)
            c.close()
            if last == want:
                return last
        except (OSError, AssertionError, ValueError):
            pass
        time.sleep(0.1)
    return last


def test_set_replicates_to_followers(stack):
    driver = stack
    lead = driver.leader()
    c = McClient(PORTS[lead])
    assert c.set("alpha", b"one") == b"STORED"
    assert c.set("beta", b"two") == b"STORED"
    assert c.get("alpha") == b"one"
    c.close()
    for r in range(3):
        if r == lead:
            continue
        assert wait_get(PORTS[r], "alpha", b"one") == b"one", f"replica {r}"
        assert wait_get(PORTS[r], "beta", b"two") == b"two", f"replica {r}"


def test_bulk_state_equality(stack):
    driver = stack
    lead = driver.leader()
    c = McClient(PORTS[lead])
    for i in range(60):
        assert c.set(f"k{i}", b"v%d" % i) == b"STORED"
    c.close()
    for r in range(3):
        if r == lead:
            continue
        assert wait_get(PORTS[r], "k59", b"v59") == b"v59", f"replica {r}"
        cc = McClient(PORTS[r])
        vals = [cc.get(f"k{i}") for i in range(60)]
        cc.close()
        assert vals == [b"v%d" % i for i in range(60)], f"replica {r}"


def test_incr_applied_exactly_once_on_followers(stack):
    driver = stack
    lead = driver.leader()
    c = McClient(PORTS[lead])
    assert c.set("ctr", b"5") == b"STORED"
    assert c.incr("ctr", 3) == b"8"
    assert c.incr("ctr", 2) == b"10"
    c.close()
    # a double-applied incr would show 13/15, a dropped one 8
    for r in range(3):
        if r == lead:
            continue
        assert wait_get(PORTS[r], "ctr", b"10") == b"10", f"replica {r}"
