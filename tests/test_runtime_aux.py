"""Auxiliary runtime subsystems: read-index verified reads, observability
logs (greppable leader line), config-file loading, adaptive timers."""

import json
import os
import re

import numpy as np
import pytest

from rdma_paxos_tpu.config import (LogConfig, TimeoutConfig, load_config)
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.timers import ElectionTimer

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)


def test_read_index_leadership_verification(tmp_path):
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, workdir=str(tmp_path))
    d.cluster.run_until_elected(0)
    d.step()
    assert d.can_serve_read(0)          # majority acked this step
    assert not d.can_serve_read(1)      # followers never serve reads
    # isolated leader loses verification (reads would be stale)
    d.cluster.partition([[0], [1, 2]])
    d.step()
    d.step()
    assert not d.can_serve_read(0)
    d.stop()


def test_leader_line_greppable(tmp_path):
    """run.sh finds the leader by grepping '] LEADER' from per-server
    logs — the exact same grep works here."""
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, workdir=str(tmp_path))
    d.runtimes[1].timer._deadline = 0.0   # expire replica 1's timer
    d.step()                              # election runs through the driver
    assert d.leader() == 1
    d.stop()
    text = open(os.path.join(str(tmp_path), "replica1.log")).read()
    assert re.search(r"\[T\d+\] LEADER", text)
    for r in (0, 2):
        assert "] LEADER" not in open(
            os.path.join(str(tmp_path), f"replica{r}.log")).read()


def test_config_file_loading(tmp_path):
    p = tmp_path / "nodes.json"
    p.write_text(json.dumps({
        "log": {"n_slots": 128, "slot_bytes": 64},
        "timing": {"hb_period": 0.001, "elec_timeout_low": 0.01,
                   "elec_timeout_high": 0.03},
        "cluster": {"group_size": 5, "peers": ["h0:9000", "h1:9000"]},
    }))
    log_cfg, timing, cluster = load_config(
        str(p), env={"server_idx": "2", "server_type": "start"})
    assert log_cfg.n_slots == 128 and log_cfg.slot_bytes == 64
    assert timing.hb_period == 0.001
    assert cluster.group_size == 5 and cluster.server_idx == 2
    assert cluster.peers == ("h0:9000", "h1:9000")
    assert cluster.majority == 3


def test_adaptive_timeout_widens_on_false_positive():
    clock = [0.0]
    t = ElectionTimer(TimeoutConfig(elec_timeout_low=0.1,
                                    elec_timeout_high=0.3),
                      seed=1, clock=lambda: clock[0])
    low0 = t.low
    t.false_positive()
    assert t.low > low0
    for _ in range(20):
        t.false_positive()
    assert t.low <= t.high              # capped
