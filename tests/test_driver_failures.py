"""Driver-level failure handling: automatic eviction of dead members
(check_failure_count analog) and snapshot recovery through the driver."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.state import ConfigState
from rdma_paxos_tpu.runtime.driver import ClusterDriver

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)  # manual


def make_driver(**kw):
    d = ClusterDriver(CFG, 5, timeout_cfg=TO, **kw)
    return d


def test_auto_eviction_of_dead_member():
    d = make_driver(auto_evict=True, fail_threshold=5)
    d.runtimes[0].timer.beat = lambda: None
    # elect replica 0 manually
    d.cluster.run_until_elected(0)
    d.step()
    assert d.leader() == 0
    # replica 4 dies
    d.cluster.partition([[0, 1, 2, 3], [4]])
    for _ in range(40):
        d.step()
    cur = d._mm.current(0)
    assert cur["bitmask_new"] == 0b01111, cur
    assert cur["cid_state"] == int(ConfigState.STABLE)
    # quorum shrank with it: 3-of-4 commits with one more member down
    d.cluster.partition([[0, 1, 2], [3], [4]])
    d.cluster.submit(0, b"post-evict")
    r = d.step()
    assert r["commit"][0] == r["end"][0]
    d.stop()


def test_driver_snapshot_recovery_path():
    d = make_driver()
    d.cluster.run_until_elected(0)
    d.step()
    # replica 3 pruned past: tiny ring + partition + load
    d.cluster.partition([[0, 1, 2], [3], [4]])
    small = 3 * CFG.n_slots
    for i in range(small):
        d.cluster.submit(0, b"w%03d" % i)
        d.step()
    d.step()
    assert int(d.cluster.last["head"][0]) > int(d.cluster.last["end"][3])
    d.cluster.heal()
    for _ in range(4):
        d.step()
    assert int(d.cluster.last["end"][3]) < int(d.cluster.last["end"][0])
    d.recover_replica(3)
    for _ in range(4):
        r = d.step()
    assert int(r["end"][3]) == int(r["end"][0])
    d.stop()


def test_flagged_leader_is_deposed_and_recovered():
    """A force-pruned replica that holds leadership acks windows and
    heartbeats normally, so nothing deposes it naturally — its app and
    store stay frozen (stale reads) and every other flagged member's
    recovery starves behind it. The driver must actively depose it by
    firing a healthy member's election timeout, then heal it once
    leadership has moved."""
    d = make_driver()
    d.cluster.run_until_elected(0)
    d.step()
    assert d.leader() == 0
    d.cluster.need_recovery.add(0)
    for _ in range(50):
        d.step()
        if d.leader() not in (-1, 0) and not d.cluster.need_recovery:
            break
    assert d.leader() >= 0 and d.leader() != 0, (
        "flagged leader was never deposed")
    assert not d.cluster.need_recovery, (
        "deposed ex-leader was never recovered")
    d.stop()


def test_poll_loop_crash_releases_and_rejects_events():
    """A step exception on the poll thread must fail every blocked
    commit waiter AND fail-fast any event arriving afterwards — app
    threads must never hang on a dead loop (advisor finding: the old
    loop died silently with waiters parked forever)."""
    import time

    d = make_driver()
    d.cluster.run_until_elected(0)
    d.step()
    handler = d._make_handler(0)
    conn = (0 << 24) | 1
    handler(2, conn, b"")               # CONNECT on the leader
    ev = handler(3, conn, b"blocked-op")
    assert ev is not None and not isinstance(ev, int)

    # poison the next cluster step, then run the loop (all four entry
    # points: the pipelined loop dispatches via begin_*, the serial
    # path via step/step_burst)
    def boom(*a, **k):
        raise RuntimeError("injected step failure")
    d.cluster.step = boom
    d.cluster.step_burst = boom
    d.cluster.begin_step = boom
    d.cluster.begin_burst = boom
    d.run()
    assert ev.done.wait(10), "blocked event never released"
    assert ev.status == -1
    assert isinstance(d.loop_error, RuntimeError)
    # post-crash events are rejected immediately, not queued
    t0 = time.time()
    assert handler(3, conn, b"late-op") == -1
    assert time.time() - t0 < 1.0
    d.stop()
