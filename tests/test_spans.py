"""Causal command tracing (rdma_paxos_tpu.obs.spans): span lifecycle,
cross-replica correlation, step-phase attribution, Perfetto export —
unit level plus the driver/sim/chaos integration contracts:

* a sampled command's span walks submit/enqueue → append ``(term,
  index)`` → quorum → per-replica commit → per-replica apply → ack,
  and retires bounded;
* orphaned spans on leader failover are closed with a ``failover``
  status, never leaked;
* the Chrome trace-event export validates against the trace-event
  schema and matches a golden file byte-for-byte on a scripted clock;
* every obs dump (trace ring, health snapshot, span dump) carries the
  SAME process ``(monotonic, wall)`` anchor pair;
* instrumentation is host-side only: no ``obs`` call site is reachable
  from the jitted modules, and compiled-step cache keys are unchanged
  with tracing at 100% and fencing on;
* chaos reproducer artifacts embed the span dump;
* ``benchmarks/reporting.emit`` produces the standardized BENCH line +
  registry snapshot.
"""

import collections
import json
import os
import threading

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus.log import EntryType
from rdma_paxos_tpu.obs import Observability, clock as obs_clock
from rdma_paxos_tpu.obs import spans as spans_mod
from rdma_paxos_tpu.obs.health import make_snapshot
from rdma_paxos_tpu.obs.metrics import MetricsRegistry
from rdma_paxos_tpu.obs.spans import (
    SpanRecorder, StepPhaseProfiler, breakdown, format_breakdown,
    to_chrome_trace)
from rdma_paxos_tpu.obs.trace import TraceRing
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)  # manual

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "spans_chrome_trace.json")


def _scripted_clock(step_s: float = 0.001, start: float = 0.0):
    """Deterministic monotonic clock: start+0.001, start+0.002, ..."""
    t = [start]

    def clock():
        t[0] += step_s
        return round(t[0], 6)
    return clock


def _scripted_recorder():
    """A recorder driven through one full span + one failover span on
    the scripted clock — the golden-file scenario."""
    rec = SpanRecorder(sample_every=1, clock=_scripted_clock())
    rec.begin(7, 1, 0)                        # enqueue on replica 0
    rec.stamp_append(7, 1, term=3, index=5, leader=0, replicas=(0, 1))
    rec.commit_advance(0, 6)                  # leader commit -> quorum
    rec.apply_advance(0, 6)
    rec.commit_advance(1, 6)
    rec.apply_advance(1, 6)
    rec.ack_release(0, 1)
    rec.begin(7, 2, 0)                        # orphaned at failover
    rec.fail_open(0)
    return rec


# ---------------------------------------------------------------------------
# span recorder lifecycle
# ---------------------------------------------------------------------------

def test_span_lifecycle_full_chain():
    rec = _scripted_recorder()
    c = rec.counts()
    assert c["open"] == 0 and c["done"] == 2     # both retired, bounded
    assert c["sampled"] == {"done": 1, "failover": 1}
    dump = rec.dump()
    done = [s for s in dump["spans"] if s["status"] == "done"][0]
    assert (done["term"], done["index"], done["leader"]) == (3, 5, 0)
    phases = [p for p, _, _ in done["events"]]
    assert phases == ["enqueue", "append", "commit", "quorum",
                      "apply", "commit", "apply", "ack"]
    # commit/apply marks landed on BOTH correlated replicas
    assert sorted(r for p, r, _ in done["events"] if p == "commit") \
        == [0, 1]
    # timestamps are monotone in event order
    ts = [t for _, _, t in done["events"]]
    assert ts == sorted(ts)


def test_failover_spans_closed_never_leaked():
    rec = SpanRecorder(sample_every=1)
    for i in range(5):
        rec.begin(9, i + 1, 2)
    rec.stamp_append(9, 1, term=1, index=0, leader=2, replicas=(2,))
    assert rec.open_count == 5
    assert rec.fail_open(2) == 5
    assert rec.open_count == 0                  # never leaked
    statuses = {s["status"] for s in rec.dump()["spans"]}
    assert statuses == {"failover"}
    # the (term, index) correlation entry is cleaned up too
    assert rec.key_for(1, 0) is None


def test_sampling_rate_limit_and_capacity():
    rec = SpanRecorder(sample_every=4, capacity=3)
    sampled = sum(rec.begin(1, i + 1, 0) for i in range(16))
    # one in four hits the sampler; the 4th sampled hits capacity
    assert sampled == 3
    assert rec.open_count == 3 and rec.dropped == 1
    off = SpanRecorder(sample_every=0)
    assert off.begin(1, 1, 0) is False and not off.enabled
    assert off.open_count == 0


def test_acked_spans_with_dead_replica_do_not_wedge_recorder():
    """A permanently-stopped replica's frontier never advances, so
    acked spans keep pending commit/apply marks: at capacity the
    oldest such span is evicted (the client has its ack; the missing
    marks are the evidence) instead of refusing every future sample."""
    rec = SpanRecorder(sample_every=1, capacity=4)
    for i in range(10):
        req = i + 1
        rec.begin(8, req, 0)
        # replica 1 is dead: only replica 0's frontier ever advances
        rec.stamp_append(8, req, term=1, index=i, leader=0,
                         replicas=(0, 1))
        rec.commit_advance(0, i + 1)
        rec.apply_advance(0, i + 1)
        rec.ack_release(0, req)
    c = rec.counts()
    # tracing never stopped: no sample was refused (the overflow was
    # evicted into the bounded done ring, whose oldest entries age
    # out), the open set stayed bounded, and sampling is still live
    assert c["dropped"] == 0
    assert c["open"] <= 4 and c["done"] == 4
    assert rec.begin(8, 99, 0) is True        # still sampling


def test_retransmit_reuses_span_and_first_append_wins():
    rec = SpanRecorder(sample_every=1)
    rec.begin(5, 1, 0)
    rec.begin(5, 1, 1)                           # retransmit elsewhere
    assert rec.open_count == 1
    rec.stamp_append(5, 1, term=2, index=9, leader=0, replicas=(0,))
    rec.stamp_append(5, 1, term=3, index=12, leader=1, replicas=(1,))
    sp = rec.dump()["spans"][0]
    assert (sp["term"], sp["index"]) == (2, 9)   # first commit wins
    assert sp["retransmits"] == 2
    assert [p for p, _, _ in sp["events"]].count("retransmit") == 2


def test_recorder_thread_safety_smoke():
    rec = SpanRecorder(sample_every=1, capacity=10000)

    def work(base):
        for i in range(300):
            rec.begin(base, i + 1, 0)
            rec.stamp_append(base, i + 1, 1, base * 1000 + i, 0,
                             replicas=(0,))
        rec.ack_release(0, 300)
    ts = [threading.Thread(target=work, args=(b,)) for b in (1, 2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    c = rec.counts()
    assert c["open"] + c["done"] + c["dropped"] >= 900


# ---------------------------------------------------------------------------
# satellite: unified clocks — one (monotonic, wall) anchor pair on
# every dump (trace, health, spans)
# ---------------------------------------------------------------------------

def test_all_dumps_share_one_clock_anchor():
    a = obs_clock.anchor()
    assert set(a) == {"monotonic", "wall"}
    assert obs_clock.anchor() == a               # stable per process
    ring = TraceRing(capacity=4)
    ring.record("tick")
    assert json.loads(ring.dump_json())["anchor"] == a
    snap = make_snapshot(replica=0)
    assert snap["anchor"] == a and "ts_monotonic" in snap and "ts" in snap
    rec = SpanRecorder(sample_every=1)
    assert rec.dump()["anchor"] == a
    obs = Observability()
    assert obs.snapshot()["anchor"] == a
    # projection: monotonic ts maps onto the wall timebase exactly
    assert obs_clock.to_wall(a["monotonic"], a) == pytest.approx(
        a["wall"])


# ---------------------------------------------------------------------------
# Perfetto export: schema validation + golden file
# ---------------------------------------------------------------------------

def _validate_chrome_trace(doc):
    """The Chrome trace-event schema subset Perfetto requires: a
    traceEvents list whose entries carry name/ph/pid/tid, a numeric
    ts (except metadata), 'X' events a numeric dur, instants a scope."""
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert isinstance(ev["name"], str) and ev["name"]
        assert ev["ph"] in ("X", "i", "M")
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert "name" in ev["args"]
            continue
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] == "i":
            assert ev["s"] in ("t", "p", "g")
    json.dumps(doc)                              # serializable as-is


def test_chrome_trace_golden_file():
    rec = _scripted_recorder()
    dump = rec.dump(anchor={"monotonic": 0.0, "wall": 100.0})
    doc = to_chrome_trace(dump)
    _validate_chrome_trace(doc)
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert doc == golden, (
        "Perfetto export drifted from the golden file — if the change "
        "is intentional, regenerate tests/golden/spans_chrome_trace"
        ".json (see test module docstring)")


def test_chrome_trace_merges_multi_replica_dumps_on_anchor():
    """Two 'processes' with different anchors: the merged timeline
    aligns their events on the shared wall timebase."""
    r0 = SpanRecorder(sample_every=1, clock=_scripted_clock())
    r0.begin(3, 1, 0)
    r0.stamp_append(3, 1, term=1, index=0, leader=0, replicas=(0,))
    r0.commit_advance(0, 1)
    r0.apply_advance(0, 1)
    r0.ack_release(0, 1)
    # host 1's monotonic clock reads 1000s ahead of host 0's, but its
    # anchor says so — the merge must cancel the offset exactly
    r1 = SpanRecorder(sample_every=1,
                      clock=_scripted_clock(start=1000.0))
    r1.begin(3, 1, 1)                 # same (conn, req) seen on host 1
    r1.stamp_append(3, 1, term=1, index=0, leader=0, replicas=(1,))
    r1.commit_advance(1, 1)
    r1.apply_advance(1, 1)
    d0 = r0.dump(anchor={"monotonic": 0.0, "wall": 50.0})
    d1 = r1.dump(anchor={"monotonic": 1000.0, "wall": 50.0})
    doc = to_chrome_trace([d0, d1])
    _validate_chrome_trace(doc)
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert pids == {0, 1}             # one track per replica
    # anchor alignment: host 1's marks land near host 0's on the
    # merged timeline (µs apart), not 1000 s away
    ts = [e["ts"] for e in doc["traceEvents"] if e["ph"] == "i"]
    assert max(ts) - min(ts) < 1e6
    # correlation: both replicas' marks carry the same (term, index)
    args = [e["args"] for e in doc["traceEvents"] if e["ph"] == "i"]
    assert {(a["term"], a["index"]) for a in args} == {(1, 0)}


def test_breakdown_report():
    rec = _scripted_recorder()
    bd = breakdown(rec.dump())
    assert bd["spans"] == {"done": 1, "failover": 1}
    assert set(bd["segments"]) == {"enqueue->append", "append->quorum",
                                   "quorum->apply", "apply->ack"}
    for st in bd["segments"].values():
        assert st["n"] == 1 and st["p50_us"] >= 0
    text = format_breakdown(bd)
    assert "enqueue->append" in text and "p99_us" in text


def test_cli_merge_and_report(tmp_path, capsys):
    rec = _scripted_recorder()
    f1 = tmp_path / "spans0.json"
    f1.write_text(json.dumps(rec.dump(
        anchor={"monotonic": 0.0, "wall": 10.0})))
    f2 = tmp_path / "spans1.json"
    f2.write_text(json.dumps(rec.dump(
        anchor={"monotonic": 5.0, "wall": 10.0})))
    out = tmp_path / "trace.json"
    assert spans_mod.main(["merge", str(f1), str(f2),
                           "-o", str(out)]) == 0
    doc = json.load(open(out))
    _validate_chrome_trace(doc)
    assert doc["otherData"]["dumps"] == 2
    assert spans_mod.main(["report", str(f1)]) == 0
    cap = capsys.readouterr().out
    assert "append->quorum" in cap and "perfetto" in cap


# ---------------------------------------------------------------------------
# step-phase profiler
# ---------------------------------------------------------------------------

def test_phase_profiler_feeds_registry_and_fence_is_separate():
    reg = MetricsRegistry()
    prof = StepPhaseProfiler(metrics=reg, fence=False)
    c = SimCluster(CFG, 3)
    c.profiler = prof
    c.run_until_elected(0)
    c.submit(0, b"x")
    c.step()
    for phase in ("host_encode", "device_dispatch", "quorum_wait",
                  "apply"):
        h = reg.get("step_phase_us", phase=phase, replica=-1)
        assert h["count"] >= 1, phase
    # fencing OFF by default: no device_sync series exists
    assert reg.get("step_phase_us", phase="device_sync",
                   replica=-1) == 0
    assert "device_dispatch" in prof.report()

    # fence on: device-sync time lands in its OWN series
    reg2 = MetricsRegistry()
    c.profiler = StepPhaseProfiler(metrics=reg2, fence=True)
    c.submit(0, b"y")
    c.step()
    assert reg2.get("step_phase_us", phase="device_sync",
                    replica=-1)["count"] >= 1
    assert reg2.get("step_phase_us", phase="device_dispatch",
                    replica=-1)["count"] >= 1


# ---------------------------------------------------------------------------
# driver integration: end-to-end spans through the poll loop
# ---------------------------------------------------------------------------

def _step_until(d, pred, n=200):
    for _ in range(n):
        d.step()
        if pred():
            return True
    return False


def test_driver_end_to_end_spans_and_failover():
    d = ClusterDriver(CFG, 3, timeout_cfg=TO)
    try:
        d.obs.spans.set_sample_every(1)
        d.runtimes[0].timer._deadline = 0.0
        d.step()
        assert d.leader() == 0
        handler = d._make_handler(0)
        conn = (0 << 24) | 1
        ev1 = handler(int(EntryType.CONNECT), conn, b"")
        ev2 = handler(int(EntryType.SEND), conn, b"SET k v\n")
        assert _step_until(d, lambda: ev2.done.is_set())
        assert ev1.status == 0 and ev2.status == 0
        for _ in range(5):
            d.step()                  # follower frontiers catch up
        c = d.obs.spans.counts()
        assert c["done"] == 2 and c["open"] == 0
        dump = d.obs.spans.dump()
        for sp in dump["spans"]:
            assert sp["status"] == "done"
            assert sp["term"] is not None and sp["index"] is not None
            # correlated (term, index) marks across ALL three replicas
            for phase in ("commit", "apply"):
                reps = {r for p, r, _ in sp["events"] if p == phase}
                assert reps == {0, 1, 2}, (phase, sp)
            # the ack fired (followers' marks may trail it in order)
            assert "ack" in [p for p, _, _ in sp["events"]]
        # (term, index) pairs are unique -> cross-replica join key
        tis = [(sp["term"], sp["index"]) for sp in dump["spans"]]
        assert len(set(tis)) == len(tis)
        doc = to_chrome_trace(dump)
        _validate_chrome_trace(doc)
        cp = [e for e in doc["traceEvents"]
              if e["ph"] == "X" and e["pid"] == spans_mod.CP_PID]
        assert cp                     # critical-path track exists

        # failover: a span left inflight is closed, not leaked
        ev3 = handler(int(EntryType.SEND), conn, b"SET k2 v\n")
        assert ev3 is not None
        with d._lock:
            d._fail_inflight_locked(d.runtimes[0], "test-failover")
        c = d.obs.spans.counts()
        assert c["open"] == 0
        assert c["sampled"].get("failover") == 1
    finally:
        d.stop()


def test_kvs_session_spans_via_sim():
    from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
    # KVS commands are CMD_W*4 bytes — same geometry as
    # tests/test_replicated_kvs.py so compiled steps are shared
    kv_cfg = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                       batch_slots=16)
    c = SimCluster(kv_cfg, 3)
    c.obs = Observability()
    c.obs.spans.set_sample_every(1)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=64)
    sess = kv.session(1)
    rid = sess.put(0, b"k", b"v1")
    for _ in range(4):
        c.step()
    kv._fold(0)
    assert kv.last_req[0].get(1, 0) >= rid
    c.obs.spans.ack_key(1, rid)
    sp = [s for s in c.obs.spans.dump()["spans"]
          if s["req"] == rid and s["conn"] == 1][0]
    phases = [p for p, _, _ in sp["events"]]
    assert phases[0] == "submit" and "append" in phases
    assert sp["status"] == "done"
    assert {r for p, r, _ in sp["events"] if p == "commit"} == {0, 1, 2}


# ---------------------------------------------------------------------------
# satellite: static jit-safety guard — no obs call site reachable from
# the jitted modules, and cache keys unchanged at 100% tracing
# ---------------------------------------------------------------------------

def test_no_obs_reachable_from_jitted_modules():
    """consensus/step.py and ops/* run inside jit/shard_map: no
    metrics/trace/spans call site may exist there — statically, by
    transitive import provenance AND source scan. Enforced by the
    graftlint ``jit-purity`` pass (the deduped ``SCAN_PATTERNS``
    union carries this test's former inline list)."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    assert_jit_purity()


def test_cache_keys_unchanged_with_full_tracing_and_fence():
    """Compiled-step cache keys are bit-identical with spans at 100%
    sampling AND the profiler fencing enabled — instrumentation stays
    host-side (the fence only blocks on already-compiled outputs)."""
    cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16,
                    batch_slots=8)
    bare = SimCluster(cfg, 3)
    bare.run_until_elected(0)
    bare.submit(0, b"x")
    bare.step()
    keys_before = set(SimCluster._STEP_CACHE)

    traced = SimCluster(cfg, 3)
    traced.obs = Observability()
    traced.obs.spans.set_sample_every(1)
    traced.profiler = StepPhaseProfiler(metrics=traced.obs.metrics,
                                        fence=True)
    traced.run_until_elected(0)
    traced.obs.spans.begin(1, 1, 0)     # span birth (the driver's job)
    traced.submit(0, b"y", conn=1, req_id=1)
    traced.step()
    traced.step()
    assert traced.obs.spans.counts()["open"] \
        + traced.obs.spans.counts()["done"] >= 1
    d = ClusterDriver(cfg, 3, timeout_cfg=TO, fence=True)
    d.obs.spans.set_sample_every(1)
    d.cluster.run_until_elected(0)
    d.step()
    d.stop()
    assert set(SimCluster._STEP_CACHE) == keys_before, (
        "causal tracing / fencing changed the compiled-step cache "
        "keys — instrumentation leaked into jitted code")


# ---------------------------------------------------------------------------
# satellite: chaos artifacts carry the span dump
# ---------------------------------------------------------------------------

def test_reproducer_artifact_embeds_span_dump(tmp_path):
    from rdma_paxos_tpu.chaos.artifact import (
        load_reproducer, write_reproducer)
    obs = Observability()
    obs.spans.set_sample_every(1)
    obs.spans.begin(4, 1, 0)
    obs.spans.stamp_append(4, 1, term=1, index=0, leader=0,
                           replicas=(0,))
    path = write_reproducer(str(tmp_path / "repro.json"), seed=3,
                            schedule=[], reason="test", obs=obs)
    doc = load_reproducer(path)
    assert doc["spans"]["spans"], "artifact lost the span dump"
    assert doc["spans"]["anchor"] == obs_clock.anchor()
    sp = doc["spans"]["spans"][0]
    assert (sp["term"], sp["index"]) == (1, 0)


@pytest.mark.chaos
def test_nemesis_runner_records_spans():
    """The nemesis runner traces every command (sample_every=1), so a
    violation artifact would ship the full causal timeline; the
    healthy run here just proves spans flow end to end under chaos."""
    from rdma_paxos_tpu.chaos.runner import NemesisRunner
    runner = NemesisRunner(n_replicas=3, seed=11, steps=30,
                           settle_steps=15, fault_kinds=("drop",))
    verdict = runner.run()
    assert verdict["ok"] is True
    dump = runner.obs.spans.dump()
    assert dump["spans"], "no spans recorded under the nemesis"
    stamped = [s for s in dump["spans"] if s["term"] is not None]
    assert stamped, "no span gained a (term, index) correlation"
    assert any(s["status"] == "done" for s in dump["spans"])


# ---------------------------------------------------------------------------
# satellite: shared bench reporting emitter
# ---------------------------------------------------------------------------

def test_reporting_emit_line_and_snapshot(tmp_path, capsys):
    from benchmarks.reporting import emit
    reg = MetricsRegistry()
    reg.inc("ops_total", 5, replica=0)
    path = str(tmp_path / "bench.jsonl")
    row = emit("test_metric", 42.5, "ops/s",
               detail=dict(replicas=3), registry=reg, json_path=path)
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines() if l.startswith("BENCH:"))
    doc = json.loads(line[len("BENCH:"):])
    assert doc["metric"] == "test_metric" and doc["value"] == 42.5
    assert doc["unit"] == "ops/s" and doc["detail"] == {"replicas": 3}
    assert "metrics" not in doc            # stdout line stays lean
    filed = json.loads(open(path).read().splitlines()[0])
    assert filed["metrics"]["counters"]["ops_total{replica=0}"] == 5
    assert set(filed["anchor"]) == {"monotonic", "wall"}
    assert row["metrics"] == filed["metrics"]
