"""Read scaling — leader leases, read-index follower reads, and the
driver read queue (``runtime/reads.py``).

Covers the PR 10 acceptance surface:

* lease grant/renew piggybacked on the verified-quorum outputs every
  step already carries; conservative step-domain expiry; the
  new-leader wait-out barrier;
* the scripted stale-holder safety argument: by the step a usurper's
  first write can commit, the deposed holder's lease has provably
  expired;
* read-index follower reads through the queued hub (confirm once,
  wait for the local apply frontier, serve) and their step-domain
  patience;
* quarantine (digest AND storm-policy) revoking leases and refusing
  reads;
* lease-aware serving on all three engines (SimCluster, vmap
  ShardedCluster, spmd mesh) and both drivers' read queues;
* chaos: leaseholding-leader crash mid-read-burst and timeout-skew
  schedules verdict ZERO per-key linearizability violations,
  deterministically, on the single-group and sharded runners;
* the cache-key guard: the read path adds ZERO STEP_CACHE keys and
  leaves programs bit-identical (it is pure host bookkeeping);
* the jit-safety scan extension to ``runtime/reads.py``.
"""

import threading
import time

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.obs import Observability, trace as obs_trace
from rdma_paxos_tpu.runtime import reads as reads_mod
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.shard.cluster import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS

CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)


def _cluster(leases=True, **kw):
    c = SimCluster(CFG, 3, **kw)
    c.obs = Observability()
    if leases:
        reads_mod.attach(c)
    return c


def _put_committed(c, kv, leader, key, val, req):
    kv.put(leader, key, val, client_id=9, req_id=req)
    for _ in range(6):
        c.step()
        kv._fold(leader)
        if kv.last_req[leader].get(9, 0) >= req:
            return
    raise AssertionError("put did not commit")


# ---------------------------------------------------------------------------
# lease lifecycle
# ---------------------------------------------------------------------------

def test_lease_grant_renew_and_lease_read():
    c = _cluster()
    lm = c.leases
    c.run_until_elected(0)
    for _ in range(4):
        c.step()
    assert lm.serving_holder(0) == 0
    assert lm.valid(0, 0) and not lm.valid(0, 1)
    assert lm.grants == 1 and lm.renewals >= 3
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 0, b"k", b"v1", 1)
    assert kv.get(0, b"k", linearizable=True) == b"v1"
    m = c.obs.metrics
    assert m.get("reads_served_total", path="lease", replica=0) == 1
    # the latency histogram and the grant trace event exist
    assert m.get("read_latency_us", path="lease")["count"] == 1
    assert c.obs.trace.events(obs_trace.LEASE_GRANTED)


def test_lease_expires_and_new_leader_waits_out_barrier():
    c = _cluster()
    lm = c.leases
    c.run_until_elected(0)
    c.step()
    c.partition([[0], [1, 2]])
    c.step()
    # age 1 < lease_steps: the isolated holder may still serve (its
    # reads precede any possible usurper commit — see the safety test)
    assert lm.valid(0, 0)
    c.step()
    assert not lm.valid(0, 0)           # age 2: expired
    # majority side elects a new leader; its lease must WAIT OUT the
    # old one (barrier) — read-index still serves there meanwhile
    c.run_until_elected(1)
    kv = ReplicatedKVS(c, cap=256)
    served_ri = False
    for _ in range(12):
        if lm.valid(0, 1):
            break
        v = kv.get(1, b"nope", linearizable=True)   # read_index path
        served_ri = True
        assert v is None                # key absent, but SERVED
        c.step()
    assert lm.valid(0, 1), "new leader's lease never activated"
    assert served_ri
    assert lm.revocations >= 1
    st = lm.status()
    assert st["holders"] == [1]
    assert c.obs.trace.events(obs_trace.LEASE_REVOKED)
    m = c.obs.metrics
    assert m.get("reads_served_total", path="read_index",
                 replica=1) >= 1


def test_stale_holder_expires_before_usurper_can_commit():
    """The step-domain safety argument, scripted: a partitioned
    leaseholder's lease is INVALID by the step a usurper's first
    write can possibly commit — even under maximal timer skew a
    candidate needs one step to win votes and one more to commit, so
    lease_steps=2 leaves no overlap."""
    c = _cluster()
    lm = c.leases
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 0, b"k", b"v1", 1)
    c.partition([[0], [1, 2]])
    # step P+1: old holder may serve its last lease read (age 1)
    c.step()
    assert lm.valid(0, 0)
    assert kv.get(0, b"k", linearizable=True) == b"v1"
    # the FASTEST possible usurper: timer fires the very next step
    res = c.step(timeouts=[1])
    # by the step the usurper can first append+commit, the old lease
    # is already invalid — no read window overlaps the new write
    assert not lm.valid(0, 0)
    kv.put(1, b"k", b"v2", client_id=8, req_id=1)
    c.step()
    assert not lm.valid(0, 0)
    assert kv.get(0, b"k", linearizable=True) is None   # refused
    del res


# ---------------------------------------------------------------------------
# read-index follower reads (the hub)
# ---------------------------------------------------------------------------

def test_wedged_apply_leaseholder_refuses_instead_of_serving_stale():
    """A wedged apply keeps acking windows, so leadership_verified —
    and the lease — stay live while the table freezes below commit:
    the serving gate must refuse rather than return pre-write state
    for writes already acked."""
    c = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 0, b"k", b"v1", 1)
    c.wedge_apply(0)
    kv.put(0, b"k", b"v2", client_id=9, req_id=2)
    for _ in range(3):
        c.step()
    assert int(c.last["commit"][0]) > int(c.applied[0])
    assert c.leases.valid(0, 0)             # lease itself stays live
    assert kv.get(0, b"k", linearizable=True) is None   # refused
    c.unwedge_apply(0)
    c.step()
    assert kv.get(0, b"k", linearizable=True) == b"v2"


def test_hub_follower_read_waits_for_apply_frontier():
    c = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 0, b"k", b"v1", 1)
    hub = c.reads
    t = hub.submit(lambda: kv.serve_local(2, b"k"), replica=2)
    for _ in range(4):
        if t.done:
            break
        c.step()
    assert t.status == "ok" and t.path == "read_index"
    assert t.value == b"v1"
    assert t.read_index is not None
    snap = c.obs.metrics.snapshot()["counters"]
    assert any(k.startswith("reads_served_total")
               and "path=read_index" in k and "replica=2" in k
               for k in snap)


def test_hub_read_times_out_without_leader():
    c = _cluster()          # never elected: no leader to confirm
    hub = c.reads
    t = hub.submit(lambda: b"x", replica=1, patience=3)
    for _ in range(6):
        c.step()
    assert t.done and t.status == "failed" and t.path is None
    assert hub.failed == 1


def test_hub_fail_all_releases_waiters():
    c = _cluster()
    c.run_until_elected(0)
    hub = c.reads
    # no drain runs between submit and fail_all: the read is parked
    t = hub.submit(lambda: b"x", replica=2, patience=10_000)
    assert not t.done
    assert hub.fail_all("test") == 1
    assert t.done and t.status == "failed"
    assert hub.pending_count() == 0


# ---------------------------------------------------------------------------
# quarantine (digest + storm policy) revokes leases / refuses reads
# ---------------------------------------------------------------------------

def test_digest_quarantine_revokes_lease_and_refuses_reads():
    from rdma_paxos_tpu.chaos.faults import corrupt_slot
    from rdma_paxos_tpu.runtime.repair import RepairController

    c = _cluster(audit=True)
    lm = c.leases
    ctl = RepairController(c, obs=c.obs, probation_steps=2)
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 0, b"k", b"v1", 1)
    assert lm.valid(0, 0)
    # corrupt the LEASEHOLDER's committed slot: divergence implicates
    # it, quarantine must revoke its lease before serving resumes
    corrupt_slot(c, 0, int(c.last["commit"].min()) - 1)
    for _ in range(4):
        c.step()
        ctl.observe()
        if ctl.serving_blocked(0, 0):
            break
    assert ctl.serving_blocked(0, 0)
    assert not lm.valid(0, 0)
    assert kv.get(0, b"k", linearizable=True) is None   # refused
    assert c.obs.metrics.get("lease_revoked_total", replica=0,
                             group=0, reason="quarantine") >= 1


def test_storm_policy_quarantine_holds_replica_and_releases():
    from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
    from rdma_paxos_tpu.runtime.repair import RepairController

    c = _cluster(audit=True)
    lm = c.leases
    ctl = RepairController(c, obs=c.obs, probation_steps=2,
                           storm_policy=True)
    c.run_until_elected(2)
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 2, b"k", b"v1", 1)
    assert lm.valid(0, 2)
    assert kv.get(2, b"k", linearizable=True) == b"v1"
    engine = AlertEngine(c.obs.metrics, default_rules(),
                         trace=c.obs.trace)
    engine.add_hook(ctl.on_alert)
    # device-truth storm signal: replica 2's on-device election
    # counter races ahead (the PR 8 series the rule reads)
    engine.evaluate()                       # rate baseline
    c.obs.metrics.inc("device_elections_started_total", 5, replica=2)
    engine.evaluate()                       # pending 1 (for_evals=2)
    c.obs.metrics.inc("device_elections_started_total", 5, replica=2)
    out = engine.evaluate()                 # fires -> hook -> policy
    assert "election_storm" in out["fired"]
    assert ctl.serving_blocked(0, 2)
    assert not lm.valid(0, 2)               # lease revoked
    # the held replica refuses a PRESENT key outright — the hold is
    # effective even while its last leadership_verified snapshot is
    # still 1 (no step ran since the hook fired)
    assert kv.get(2, b"k", linearizable=True) is None
    assert 2 in c.read_blocked
    # hub reads at the held replica fail too
    t = c.reads.submit(lambda: kv.serve_local(2, b"k"), replica=2)
    c.step()
    ctl.observe()
    assert t.done and t.status == "failed"
    assert ctl.policy_quarantines == 1
    # release: drive() -> probation (no install), clean steps -> readmit
    assert ctl.needs_drain()
    ctl.drive()
    assert not ctl.needs_drain()
    for _ in range(4):
        c.step()
        ctl.observe()
        if not ctl.serving_blocked(0, 2):
            break
    assert not ctl.serving_blocked(0, 2)
    st = ctl.status()
    assert st["policy_quarantines"] == 1
    assert any(t["event"] == "repair_policy_released"
               for t in st["timeline"])


# ---------------------------------------------------------------------------
# sharded + mesh engines: per-group leases, read fan-out
# ---------------------------------------------------------------------------

def test_sharded_leases_fan_out_across_replicas():
    sc = ShardedCluster(CFG, 3, 4)
    sc.obs = Observability()
    reads_mod.attach(sc)
    sc.place_leaders()
    for _ in range(4):
        sc.step()
    holders = sc.leases.holders()
    assert holders == sc.leaders()          # every group lease-served
    assert len(set(holders)) > 1            # ...spread across replicas
    kvs = ShardedKVS(sc, cap=256)
    key = b"fan"
    g = kvs.group_of(key)
    kvs.groups[g].put(holders[g], key, b"v1", client_id=7, req_id=1)
    for _ in range(4):
        sc.step()
    assert kvs.get(key, linearizable=True) == b"v1"
    snap = sc.obs.metrics.snapshot()["counters"]
    assert any(k.startswith("reads_served_total") and "path=lease" in k
               and f"group={g}" in k for k in snap)
    # follower read-index read through the hub, per group
    f = (holders[g] + 1) % 3
    t = sc.reads.submit(lambda: kvs.groups[g].serve_local(f, key),
                        replica=f, group=g)
    for _ in range(4):
        if t.done:
            break
        sc.step()
    assert t.status == "ok" and t.path == "read_index"
    assert t.value == b"v1"
    assert sc.health()["leases"]["holders"] == holders


def test_mesh_engine_lease_reads():
    if len(__import__("jax").devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    sc = ShardedCluster(CFG, 2, 2, mesh=(2, 2))
    sc.obs = Observability()
    reads_mod.attach(sc)
    sc.place_leaders()
    for _ in range(4):
        sc.step()
    holders = sc.leases.holders()
    assert all(h >= 0 for h in holders)
    kvs = ShardedKVS(sc, cap=256)
    key = b"meshkey"
    g = kvs.group_of(key)
    kvs.groups[g].put(holders[g], key, b"mv", client_id=7, req_id=1)
    for _ in range(4):
        sc.step()
    assert kvs.get(key, linearizable=True) == b"mv"


# ---------------------------------------------------------------------------
# the drivers' read queues
# ---------------------------------------------------------------------------

TCFG = TimeoutConfig(elec_timeout_low=0.3, elec_timeout_high=0.6)


def _wait_leader(d, timeout=60):
    t0 = time.time()
    while d.leader() < 0:
        time.sleep(0.02)
        assert time.time() - t0 < timeout, "no leader"


def test_driver_read_queue_serves_without_ring_slots():
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    d = ClusterDriver(CFG, 3, timeout_cfg=TCFG, pipeline=2)
    d.run(period=0.005)
    try:
        _wait_leader(d)
        lead = d.leader()
        for i in range(8):
            d.cluster.submit(lead, b"w%d" % i)
        deadline = time.time() + 30
        while (int(d.cluster.last["commit"].max()) < 8
               and time.time() < deadline):
            time.sleep(0.02)
        end_before = int(d.cluster.last["end"].max())
        results = [d.read(lambda: int(d.cluster.applied[lead]))
                   for _ in range(10)]
        assert all(t.status == "ok" for t in results)
        assert {t.path for t in results} <= {"lease", "read_index"}
        # reads consumed ZERO ring slots: the append frontier is
        # exactly where the writes left it
        assert int(d.cluster.last["end"].max()) == end_before
        assert d.cluster.reads.status()["served"]["lease"] >= 1
        h = d.health()
        assert h["leases"]["holders"] == [lead]
        assert h["reads"]["served"]
    finally:
        d.stop()


def test_sharded_driver_read_routes_to_group_holder():
    from rdma_paxos_tpu.runtime.sharded_driver import (
        ShardedClusterDriver)

    d = ShardedClusterDriver(CFG, 3, 2, timeout_cfg=TCFG, pipeline=2)
    d.run(period=0.005)
    try:
        t0 = time.time()
        while d.leader() < 0:           # all groups led
            time.sleep(0.02)
            assert time.time() - t0 < 60
        got = []
        for key in (b"alpha", b"beta", b"gamma", b"delta"):
            t = d.read(key=key)
            got.append((d._router.group_of(key), t.replica, t.status,
                        t.path))
        assert all(s == "ok" for _, _, s, _ in got)
        # reads targeted each key's group's lease holder
        holders = d.cluster.leases.holders()
        for g, rep, _s, path in got:
            if path == "lease":
                assert rep == holders[g]
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# chaos: leaseholder crash mid-read-burst + timeout skew — zero
# linearizability violations, deterministically, on both runners
# ---------------------------------------------------------------------------

READ_BURST = dict(p_holder_read=0.9, p_follower_read=0.9)


@pytest.mark.chaos
def test_chaos_leaseholding_leader_crash_mid_read_burst():
    from rdma_paxos_tpu.chaos.faults import FaultSchedule
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    # seed 3 elects replica 0 as the first leaseholder (deterministic
    # harness); the schedule crashes it mid-read-burst
    sched = (FaultSchedule()
             .at(20, "crash", replica=0)
             .at(40, "restart", replica=0))
    v = NemesisRunner(n_replicas=3, seed=3, steps=55, schedule=sched,
                      workload_opts=dict(READ_BURST)).run()
    assert v["ok"], v
    assert v["linearizability"]["violations"] == []
    reads = v["reads"]
    assert reads["lease"] > 0 and reads["read_index"] > 0
    # the crash deposed the leaseholder: a second grant (the new
    # holder) and a revocation are on the deterministic timeline
    assert reads["leases"]["grants"] >= 2
    assert reads["leases"]["revocations"] >= 1
    # same seed ⇒ identical verdict (the chaos determinism contract)
    v2 = NemesisRunner(n_replicas=3, seed=3, steps=55,
                       schedule=FaultSchedule(sched.events),
                       workload_opts=dict(READ_BURST)).run()
    assert v2 == v


@pytest.mark.chaos
def test_chaos_timeout_skew_with_reads():
    from rdma_paxos_tpu.chaos.faults import FaultSchedule
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    # trigger-happy AND sluggish timers while lease + read-index
    # reads flow: the conservative expiry must hold under exactly the
    # skew the nemesis injects
    sched = (FaultSchedule()
             .at(8, "skew", replica=1, factor=0.3)
             .at(8, "skew", replica=2, factor=3.0)
             .at(18, "partition", groups=[[0], [1, 2]])
             .at(30, "heal")
             .at(36, "skew", replica=1, factor=1.0)
             .at(36, "skew", replica=2, factor=1.0))
    runner = NemesisRunner(n_replicas=3, seed=11, steps=50,
                           schedule=sched,
                           workload_opts=dict(READ_BURST))
    v = runner.run()
    assert v["ok"], v
    assert v["linearizability"]["violations"] == []
    assert v["reads"]["lease"] > 0 and v["reads"]["read_index"] > 0
    # the lease timeline rode the trace ring (reproducer artifacts
    # embed this ring, so a failing run ships it as evidence)
    kinds = {e.kind for e in runner.obs.trace.events()}
    assert obs_trace.LEASE_GRANTED in kinds
    assert (obs_trace.LEASE_EXPIRED in kinds
            or obs_trace.LEASE_REVOKED in kinds)
    v2 = NemesisRunner(n_replicas=3, seed=11, steps=50,
                       schedule=FaultSchedule(sched.events),
                       workload_opts=dict(READ_BURST)).run()
    assert v2 == v


@pytest.mark.chaos
def test_shard_chaos_reads_linearizable_through_leader_crash():
    from rdma_paxos_tpu.shard.chaos import ShardNemesisRunner

    v = ShardNemesisRunner(n_replicas=3, n_groups=4, seed=2,
                           steps=36, crash_step=14).run()
    assert v["ok"], v
    assert v["linearizability"]["ok"] is True
    assert v["linearizability"]["violations"] == []
    assert v["reads"]["lease"] > 0
    assert v["reads"]["hub"]["served"]["read_index"] > 0
    v2 = ShardNemesisRunner(n_replicas=3, n_groups=4, seed=2,
                            steps=36, crash_step=14).run()
    assert v2 == v


# ---------------------------------------------------------------------------
# cache-key guard + jit-safety scan
# ---------------------------------------------------------------------------

def test_read_path_adds_zero_step_cache_keys():
    # a geometry no other test uses: this guard reasons about which
    # keys THIS test's clusters add to the shared cache
    cfg = LogConfig(n_slots=32, slot_bytes=128, window_slots=8,
                    batch_slots=4)
    plain = SimCluster(cfg, 3)
    plain.run_until_elected(0)
    plain.submit(0, b"x")
    plain.step()
    keys_before = set(STEP_CACHE)

    leased = SimCluster(cfg, 3)
    leased.obs = Observability()
    reads_mod.attach(leased)
    leased.run_until_elected(0)
    kv = ReplicatedKVS(leased, cap=256)
    kv.put(0, b"k", b"v", client_id=3, req_id=1)
    for _ in range(3):
        leased.step()
    assert kv.get(0, b"k", linearizable=True) == b"v"   # lease served
    t = leased.reads.submit(lambda: kv.serve_local(1, b"k"), replica=1)
    leased.step()
    assert t.status == "ok"
    # the WHOLE read path (leases + hub + lease/read-index serves)
    # added ZERO compiled-step cache keys: programs are bit-identical
    # to the read-path-free world
    assert set(STEP_CACHE) == keys_before


def test_read_path_outputs_bit_identical():
    a = SimCluster(CFG, 3)
    b = SimCluster(CFG, 3)
    b.obs = Observability()
    reads_mod.attach(b)
    for c in (a, b):
        c.run_until_elected(0)
        for i in range(4):
            c.submit(0, b"v%d" % i)
        for _ in range(3):
            c.step()
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(a.last[k], b.last[k]), k


def test_jit_safety_scan_covers_reads_module():
    """consensus/step.py, ops/*, and parallel/mesh.py run inside
    jit/shard_map: no read-path symbol may be reachable there, and
    runtime/reads.py itself never reaches into jit. Enforced by the
    graftlint ``jit-purity`` pass (device manifest +
    ``HOST_PURE_MODULES`` carry this test's former inline rules)."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    assert_jit_purity()


# ---------------------------------------------------------------------------
# bench smoke
# ---------------------------------------------------------------------------

def test_read_mix_bench_smoke():
    from benchmarks.run_bench import measure_read_mix
    out = measure_read_mix(0.8, cfg=CFG, n_ops=240, n_keys=8,
                           repeats=1, seed=4)
    assert out["lease"]["reads"] == out["log"]["reads"] > 0
    assert out["lease"]["writes"] == out["log"]["writes"] > 0
    assert out["lease_read_speedup"] > 0
    acc = out["accounting"]
    # the path accounting covers every read each variant claims
    assert acc["lease_variant"]["lease"] >= out["lease"]["reads"]
    assert acc["log_variant"]["log"] >= out["log"]["reads"]
    assert acc["log_variant"]["lease"] == 0


def test_hub_serve_exception_fails_read_not_thread():
    c = _cluster()
    c.run_until_elected(0)

    def boom():
        raise RuntimeError("serve failed")

    t = c.reads.submit(boom, replica=0)
    for _ in range(3):
        if t.done:
            break
        c.step()
    assert t.done and t.status == "failed"
    # the finishing thread survived: the cluster still steps
    c.step()


def test_driver_leases_off_has_no_read_path():
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    d = ClusterDriver(CFG, 3, timeout_cfg=TCFG, leases=False)
    assert d.cluster.leases is None and d.cluster.reads is None
    with pytest.raises(RuntimeError, match="read path"):
        d.read()
    d.stop()


def test_concurrent_submit_during_drain():
    """Reads submitted from another thread while the engine steps —
    the hub queue is shared between client threads and the finishing
    thread."""
    c = _cluster()
    c.run_until_elected(0)
    kv = ReplicatedKVS(c, cap=256)
    _put_committed(c, kv, 0, b"k", b"v1", 1)
    out = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            t = c.reads.submit(lambda: kv.serve_local(2, b"k"),
                               replica=2)
            t.wait(5)
            out.append(t.status)

    th = threading.Thread(target=reader)
    th.start()
    for _ in range(30):
        c.step()
    stop.set()
    c.reads.fail_all("test end")
    th.join(timeout=5)
    assert not th.is_alive()
    assert "ok" in out
