"""graftlint — the repo-native static analysis engine + runtime lock
sanitizer.

Three layers:

* per-pass fixture tests: each of the five passes catches a seeded
  synthetic violation (naming the exact file:line) and stays silent
  on a clean fixture — the analyzer's own regression harness;
* the live gate: ``run_analysis()`` on THIS checkout reports zero
  non-baselined findings (the CI ``analysis`` step runs the same
  command before pytest);
* the runtime sanitizer: under ``RP_SANITIZE=1`` a pipelined
  (pipeline=2) driver workload runs clean, while a deliberately
  unlocked mutation of a guarded field is caught at the exact access.
"""

import json
import os
import threading
import time

import pytest

from rdma_paxos_tpu.analysis import assert_jit_purity, run_analysis
from rdma_paxos_tpu.analysis.__main__ import main as lint_main
from rdma_paxos_tpu.analysis.engine import (
    Finding, PASS_IDS, Suppression, load_baseline, render_baseline,
    repo_root)
from rdma_paxos_tpu.analysis.runtime_guard import (
    LockDisciplineError, OwnedLock, guard, maybe_guard)


def _write(root, rel, text):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return path


def _run(root, pass_id):
    return run_analysis(root=str(root), passes=(pass_id,),
                        baseline=None).findings


# ---------------------------------------------------------------------------
# jit-purity fixtures
# ---------------------------------------------------------------------------

def test_jit_purity_catches_direct_host_import(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/consensus/step.py",
           "import jax\nimport threading\n")
    fs = _run(tmp_path, "jit-purity")
    assert any(f.file == "rdma_paxos_tpu/consensus/step.py"
               and f.line == 2 and "threading" in f.message
               for f in fs), fs


def test_jit_purity_catches_transitive_obs_reachability(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/consensus/step.py",
           "from rdma_paxos_tpu.consensus import helper\n")
    _write(tmp_path, "rdma_paxos_tpu/consensus/helper.py",
           "import numpy\nfrom rdma_paxos_tpu.obs import metrics\n")
    fs = _run(tmp_path, "jit-purity")
    assert len(fs) == 1
    f = fs[0]
    # reported at the DEVICE module, chain names the indirection
    assert f.file == "rdma_paxos_tpu/consensus/step.py"
    assert f.line == 1
    assert "helper" in f.message and "rdma_paxos_tpu.obs" in f.message


def test_jit_purity_catches_source_pattern(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/parallel/mesh.py",
           "import jax\n\n\ndef f(state, obs):\n"
           "    obs.metrics.inc('boom')\n")
    fs = _run(tmp_path, "jit-purity")
    assert any(f.line == 5 and "metrics" in f.message for f in fs), fs


def test_jit_purity_catches_host_pure_regression(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/runtime/hostpath.py",
           "import numpy as np\nimport jax\n")
    fs = _run(tmp_path, "jit-purity")
    assert any(f.file == "rdma_paxos_tpu/runtime/hostpath.py"
               and f.line == 2 and "accelerator" in f.message
               for f in fs), fs


def test_jit_purity_silent_on_clean_fixture(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/consensus/step.py",
           "import jax\nimport jax.numpy as jnp\n"
           "from rdma_paxos_tpu.consensus.log import M_GIDX\n")
    _write(tmp_path, "rdma_paxos_tpu/consensus/log.py", "M_GIDX = 0\n")
    _write(tmp_path, "rdma_paxos_tpu/runtime/hostpath.py",
           "import numpy as np\n")
    assert _run(tmp_path, "jit-purity") == []


# ---------------------------------------------------------------------------
# cache-key fixtures
# ---------------------------------------------------------------------------

_BUILDER_BAD = """\
STEP_CACHE = {}


class Engine:
    def _build(self, elections):
        key = (self.cfg, self.R, elections)
        fn = STEP_CACHE.get(key)
        if fn is None:
            fn = build_step(self.cfg, self.R, audit=self._audit,
                            elections=elections)
            STEP_CACHE[key] = fn
        return fn
"""

_BUILDER_OK = _BUILDER_BAD.replace(
    "key = (self.cfg, self.R, elections)",
    "key = (self.cfg, self.R, elections)"
    " + (('audit',) if self._audit else ())")


def test_cache_key_catches_missing_flag(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/runtime/builder.py", _BUILDER_BAD)
    fs = _run(tmp_path, "cache-key")
    assert len(fs) == 1
    f = fs[0]
    assert f.file == "rdma_paxos_tpu/runtime/builder.py"
    assert "'_audit'" in f.message and f.line == 9, f


def test_cache_key_silent_when_flag_in_key(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/runtime/builder.py", _BUILDER_OK)
    assert _run(tmp_path, "cache-key") == []


def test_cache_key_clean_on_main_builders():
    """Every real STEP_CACHE builder (runtime/sim.py, shard/cluster.py
    — 9+ store sites) folds every static flag it reads into its key,
    with zero baseline entries needed."""
    report = run_analysis(passes=("cache-key",), baseline=None)
    assert report.findings == [], [str(f) for f in report.findings]


# ---------------------------------------------------------------------------
# lock-discipline fixtures
# ---------------------------------------------------------------------------

_LOCKMOD_BAD = """\
import threading


class Engine:
    def __init__(self):
        self._host_lock = threading.RLock()
        self.pending = []       # guarded-by: _host_lock

    def good(self):
        with self._host_lock:
            return len(self.pending)

    def bad(self):
        self.pending.append(1)

    def also_fine_locked(self):
        return self.pending

    # holds-lock: _host_lock
    def documented(self):
        return self.pending
"""


def test_lock_discipline_flags_unlocked_access(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/runtime/sim.py", _LOCKMOD_BAD)
    fs = _run(tmp_path, "lock-discipline")
    assert len(fs) == 1
    f = fs[0]
    assert f.line == 14 and "bad()" in f.message and \
        "pending" in f.message, f


def test_lock_discipline_honors_writes_mode_and_conflict(tmp_path):
    mod = _LOCKMOD_BAD.replace("# guarded-by: _host_lock",
                               "# guarded-by: _host_lock [writes]")
    _write(tmp_path, "rdma_paxos_tpu/runtime/sim.py", mod)
    assert _run(tmp_path, "lock-discipline") == []   # reads exempt
    # conflicting re-declaration across modules is itself a finding
    _write(tmp_path, "rdma_paxos_tpu/runtime/driver.py",
           "class D:\n"
           "    def __init__(self):\n"
           "        self.pending = []   # guarded-by: _lock\n")
    fs = _run(tmp_path, "lock-discipline")
    assert any("re-declared" in f.message for f in fs), fs


# ---------------------------------------------------------------------------
# determinism fixtures
# ---------------------------------------------------------------------------

def test_determinism_catches_wall_clock_and_global_rng(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/chaos/faults.py",
           "import random\nimport time\n"
           "rng = random.Random('seed:1')\n"
           "def bad():\n"
           "    return time.time() + random.random()\n")
    fs = _run(tmp_path, "determinism")
    msgs = [f.message for f in fs]
    assert any("time.time" in m for m in msgs), msgs
    assert any("random.random" in m for m in msgs), msgs
    assert all(f.line == 5 for f in fs), fs   # Random('seed:1') legal


def test_determinism_catches_from_imports(tmp_path):
    """``from time import perf_counter`` is a bare Name at the call
    site — the import itself is flagged (post-review rider)."""
    _write(tmp_path, "rdma_paxos_tpu/chaos/faults.py",
           "from time import perf_counter\n"
           "from datetime import datetime\n"
           "from random import randint\n")
    fs = _run(tmp_path, "determinism")
    msgs = [f.message for f in fs]
    assert any("time.perf_counter" in m for m in msgs), msgs
    assert any("datetime.datetime" in m for m in msgs), msgs
    assert any("random.randint" in m for m in msgs), msgs
    assert [f.line for f in fs] == [1, 2, 3]


def test_determinism_silent_on_seeded_fixture(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/chaos/faults.py",
           "import random\nimport numpy as np\n"
           "rng = random.Random('x:3')\n"
           "g = np.random.default_rng(7)\n")
    assert _run(tmp_path, "determinism") == []


# ---------------------------------------------------------------------------
# thread-hygiene fixtures
# ---------------------------------------------------------------------------

def test_thread_hygiene_catches_unreaped_thread(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "import threading\n"
           "def spawn(fn):\n"
           "    t = threading.Thread(target=fn)\n"
           "    t.start()\n"
           "    return t\n")
    fs = _run(tmp_path, "thread-hygiene")
    assert len(fs) == 1 and fs[0].line == 3, fs


def test_thread_hygiene_accepts_daemon_or_join(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "import threading\n"
           "def spawn(fn):\n"
           "    t = threading.Thread(target=fn, daemon=True)\n"
           "    t.start()\n"
           "    u = threading.Thread(target=fn)\n"
           "    u.start()\n"
           "    u.join()\n")
    assert _run(tmp_path, "thread-hygiene") == []
    # post-construction daemon flag counts too (post-review rider)
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "import threading\n"
           "def spawn(fn):\n"
           "    t = threading.Thread(target=fn)\n"
           "    t.daemon = True\n"
           "    t.start()\n")
    assert _run(tmp_path, "thread-hygiene") == []


def test_thread_hygiene_string_join_blesses_nothing(tmp_path):
    """An unrelated ``self._sep.join(parts)`` string join must not
    count as a thread stop path (post-review rider)."""
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "import threading\n"
           "class S:\n"
           "    def spawn(self, fn):\n"
           "        self._w = threading.Thread(target=fn)\n"
           "        self._w.start()\n"
           "    def fmt(self, parts):\n"
           "        return self._sep.join(parts)\n")
    fs = _run(tmp_path, "thread-hygiene")
    assert len(fs) == 1 and fs[0].line == 4, fs
    # a join on the THREAD attribute is a stop path
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "import threading\n"
           "class S:\n"
           "    def spawn(self, fn):\n"
           "        self._w = threading.Thread(target=fn)\n"
           "        self._w.start()\n"
           "    def stop(self):\n"
           "        self._w.join()\n"
           "    def fmt(self, parts):\n"
           "        return self._sep.join(parts)\n")
    assert _run(tmp_path, "thread-hygiene") == []


def test_thread_hygiene_flags_bare_http_handler(tmp_path):
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "from http.server import BaseHTTPRequestHandler\n"
           "class H(BaseHTTPRequestHandler):\n"
           "    def do_GET(self):\n"
           "        self.wfile.write(b'x')\n")
    fs = _run(tmp_path, "thread-hygiene")
    assert len(fs) == 1 and "try/except" in fs[0].message, fs
    # wrapped body passes
    _write(tmp_path, "rdma_paxos_tpu/obs/srv.py",
           "from http.server import BaseHTTPRequestHandler\n"
           "class H(BaseHTTPRequestHandler):\n"
           "    def do_GET(self):\n"
           "        try:\n"
           "            self.wfile.write(b'x')\n"
           "        except Exception:\n"
           "            pass\n")
    assert _run(tmp_path, "thread-hygiene") == []


# ---------------------------------------------------------------------------
# baseline + CLI
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_and_matching(tmp_path):
    entries = [Suppression(pass_id="determinism",
                           file="rdma_paxos_tpu/chaos/faults.py",
                           contains="time.time",
                           reason='has "quotes" and\nnewline')]
    path = tmp_path / "b.toml"
    path.write_text(render_baseline(entries, header="hdr"))
    back = load_baseline(str(path))
    assert len(back) == 1
    assert back[0].contains == "time.time"
    assert back[0].reason == 'has "quotes" and\nnewline'
    f = Finding(file="rdma_paxos_tpu/chaos/faults.py", line=3,
                pass_id="determinism", message="wall clock time.time")
    assert back[0].matches(f)
    assert not back[0].matches(
        Finding(file="other.py", line=3, pass_id="determinism",
                message="wall clock time.time"))


def test_baseline_symbol_pins_field_and_function(tmp_path):
    """A lock-discipline suppression with ``symbol`` excuses ONLY the
    (field, function) pair it was triaged for — a different field's
    unlocked access in the same function stays a failure
    (post-review rider: function-only matching silently blessed the
    exact race class the pass exists to catch)."""
    s = Suppression(pass_id="lock-discipline", file="f.py",
                    contains="read of '_tickets'",
                    symbol="block in step()", reason="peek")
    excused = Finding(file="f.py", line=9, pass_id="lock-discipline",
                      message="read of '_tickets' (guarded-by x) "
                              "outside a `with ...x` block in step()")
    other_field = Finding(file="f.py", line=9,
                          pass_id="lock-discipline",
                          message="write of 'last' (guarded-by x) "
                                  "outside a `with ...x` block in "
                                  "step()")
    other_fn = Finding(file="f.py", line=9, pass_id="lock-discipline",
                       message="read of '_tickets' (guarded-by x) "
                               "outside a `with ...x` block in "
                               "drain()")
    assert s.matches(excused)
    assert not s.matches(other_field)
    assert not s.matches(other_fn)


def test_write_baseline_appends_preserving_comments(tmp_path):
    """--write-baseline APPENDS stubs — curated comments and section
    headers in the checked-in baseline survive a triage round
    (post-review rider: the old load/render round-trip destroyed
    them)."""
    _write(tmp_path, "rdma_paxos_tpu/chaos/faults.py",
           "import time\nT = time.time\n")
    base = tmp_path / "b.toml"
    base.write_text("# hand-curated header\n"
                    "# ---- section marker ----\n")
    rc = lint_main(["--root", str(tmp_path), "--baseline", str(base),
                    "--write-baseline", "-q", "determinism"])
    assert rc == 1
    text = base.read_text()
    assert "# hand-curated header" in text
    assert "# ---- section marker ----" in text
    assert len(load_baseline(str(base))) == 1


def test_baseline_rejects_malformed(tmp_path):
    p = tmp_path / "b.toml"
    p.write_text("[[suppress]]\npass = unquoted\n")
    with pytest.raises(ValueError):
        load_baseline(str(p))
    p.write_text('[[suppress]]\npass = "x"\n')   # missing keys
    with pytest.raises(ValueError):
        load_baseline(str(p))


def test_cli_exit_semantics_and_json(tmp_path, capsys):
    _write(tmp_path, "rdma_paxos_tpu/chaos/faults.py",
           "import time\nT = time.time\n")
    out_json = str(tmp_path / "findings.json")
    rc = lint_main(["--root", str(tmp_path), "--no-baseline",
                    "--json", out_json, "determinism"])
    assert rc == 1
    doc = json.load(open(out_json))
    assert doc["ok"] is False and len(doc["findings"]) == 1
    printed = capsys.readouterr().out
    assert "rdma_paxos_tpu/chaos/faults.py:2" in printed
    # a baselined finding exits 0 and lands in `suppressed`
    base = tmp_path / "b.toml"
    base.write_text(render_baseline([Suppression(
        pass_id="determinism",
        file="rdma_paxos_tpu/chaos/faults.py",
        contains="time.time", reason="fixture")]))
    rc = lint_main(["--root", str(tmp_path), "--baseline", str(base),
                    "--json", out_json, "determinism"])
    assert rc == 0
    doc = json.load(open(out_json))
    assert doc["ok"] is True and len(doc["suppressed"]) == 1


def test_cli_write_baseline_records_stubs(tmp_path, capsys):
    _write(tmp_path, "rdma_paxos_tpu/chaos/faults.py",
           "import time\nT = time.time\n")
    base = str(tmp_path / "b.toml")
    rc = lint_main(["--root", str(tmp_path), "--baseline", base,
                    "--write-baseline", "determinism"])
    assert rc == 1                  # recording does not bless
    entries = load_baseline(base)
    assert len(entries) == 1
    rc = lint_main(["--root", str(tmp_path), "--baseline", base,
                    "determinism"])
    assert rc == 0                  # now suppressed


# ---------------------------------------------------------------------------
# the live gate: this checkout is clean
# ---------------------------------------------------------------------------

def test_graftlint_clean_on_this_checkout():
    """The CI gate, in-process: all five passes over the real tree,
    checked-in baseline applied — zero live findings, zero unused
    suppressions, and the budget holds with two orders of margin."""
    t0 = time.monotonic()
    report = run_analysis()
    dt = time.monotonic() - t0
    assert report.findings == [], [str(f) for f in report.findings]
    assert report.unused_suppressions == [], [
        (s.pass_id, s.file, s.contains)
        for s in report.unused_suppressions]
    assert report.suppressed, "baseline should be exercised"
    assert dt < 60.0, "analysis must stay under the CI budget"
    assert set(PASS_IDS) == {
        "jit-purity", "cache-key", "lock-discipline", "determinism",
        "thread-hygiene"}


def test_jit_purity_wrapper_contract():
    """The helper the six tier-1 jit-safety wrappers call."""
    assert_jit_purity()            # must not raise on this checkout
    assert os.path.isdir(os.path.join(repo_root(), "rdma_paxos_tpu"))


# ---------------------------------------------------------------------------
# runtime sanitizer: unit level
# ---------------------------------------------------------------------------

class _Toy:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = []            # write-guarded in the tests below
        self.name = "free"


def test_owned_lock_tracks_ownership():
    lk = OwnedLock()
    assert not lk._is_owned()
    with lk:
        assert lk._is_owned() and lk.locked()
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(lk._is_owned()))
        t.start()
        t.join()
        assert seen == [False]   # held, but not by THAT thread
    assert not lk._is_owned() and not lk.locked()


def test_guard_write_and_strict_read_checks():
    obj = _Toy()
    guard(obj, "_lock", write_fields=("q",), read_fields=("q",))
    assert type(obj).__name__ == "_Toy+sanitized"
    with pytest.raises(LockDisciplineError):
        obj.q = [1]
    with pytest.raises(LockDisciplineError):
        len(obj.q)
    with obj._lock:
        obj.q = [1]
        assert len(obj.q) == 1
    obj.name = "still-free"      # unguarded fields stay unchecked


def test_maybe_guard_noop_without_env(monkeypatch):
    monkeypatch.delenv("RP_SANITIZE", raising=False)
    obj = _Toy()
    maybe_guard(obj, "_lock", __file__)
    assert type(obj).__name__ == "_Toy"
    obj.q = [2]                  # unchecked


# ---------------------------------------------------------------------------
# runtime sanitizer: the tier-1 pipelined regression
# ---------------------------------------------------------------------------

def test_sanitized_pipelined_driver_workload(monkeypatch):
    """A pipeline=2 driver workload runs CLEAN under RP_SANITIZE=1 —
    every guarded write in the dispatch/readback split holds its
    declared lock — while a deliberately unlocked test-injected
    mutation is caught at the exact access."""
    from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    monkeypatch.setenv("RP_SANITIZE", "1")
    cfg = LogConfig(n_slots=128, slot_bytes=64, window_slots=32,
                    batch_slots=8)
    to = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)
    d = ClusterDriver(cfg, 3, timeout_cfg=to, pipeline=2)
    try:
        assert type(d.cluster).__name__ == "SimCluster+sanitized"
        d.cluster.run_until_elected(0)
        d.step()
        assert d.leader() == 0
        handler = d._make_handler(0)
        conn = (0 << 24) | 31
        assert not isinstance(handler(2, conn, b""), int)
        # pre-queued record sized past one fused burst (the
        # test_pipeline overlap recipe) so pipelining engages
        evs = [handler(3, conn, b"s%03d" % i) for i in range(160)]
        d.run(period=0.001)
        for i, ev in enumerate(evs):
            assert ev.done.wait(30), f"ack {i} never released"
            assert ev.status == 0, (i, ev.status)
    finally:
        d.stop()
    assert d.loop_error is None, d.loop_error
    assert d.cluster.max_inflight_dispatches >= 2, (
        "pipelining never engaged — the sanitize run must cover the "
        "dispatch/readback overlap")
    # the deliberate race: mutate a guarded field off-lock
    with pytest.raises(LockDisciplineError):
        d.cluster.pending = [[] for _ in range(3)]
    with d.cluster._host_lock:
        d.cluster.pending = [[] for _ in range(3)]


def test_sanitized_read_hub_strict(monkeypatch):
    """ReadHub._q is declared [strict]: under RP_SANITIZE=1 even a
    lock-free READ trips the sanitizer."""
    monkeypatch.setenv("RP_SANITIZE", "1")
    from rdma_paxos_tpu.runtime.reads import ReadHub
    hub = ReadHub()
    assert hub.pending_count() == 0      # locked read path stays fine
    with pytest.raises(LockDisciplineError):
        len(hub._q)
