"""Election durability and config rollback — the crash-safety properties
the reference gets from vote replication (``rc_replicate_vote`` /
``rc_get_replicated_vote``, ``dare_ibv_rc.c:1049-1109,394-473``) and Raft's
fall-back-to-previous-configuration rule on log truncation."""

import numpy as np

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.membership import MembershipManager
from rdma_paxos_tpu.consensus.snapshot import (
    install_snapshot, recover_vote, take_snapshot)
from rdma_paxos_tpu.consensus.state import ConfigState, Role
from rdma_paxos_tpu.proxy.stablestore import HardState
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def _elect_with_2_partitioned(c):
    """Elect 0 with votes from {0, 2} while 1 is partitioned away, so 1
    stays at term 0 and can later become a candidate for term 1."""
    c.partition([[0, 2], [1]])
    res = c.step(timeouts=[0])
    assert res["role"][0] == int(Role.LEADER)
    assert int(res["term"][0]) == 1
    assert int(res["term"][1]) == 0
    return res


def test_peers_retain_vote_records():
    c = SimCluster(CFG, 3)
    _elect_with_2_partitioned(c)
    # replica 0 and 2 voted for 0 in term 1; both live peers retain it
    vt, vf = recover_vote(c.state, 2, peers=[0])
    assert (vt, vf) == (1, 0)
    vt, vf = recover_vote(c.state, 0, peers=[2])
    assert (vt, vf) == (1, 0)
    # partitioned replica 1 never voted
    vt, vf = recover_vote(c.state, 1)
    assert vt == 0


def test_recovered_replica_cannot_double_vote():
    """A crash-recovered replica restores its vote from peers' records:
    it must NOT grant a second vote in a term it already voted in
    (election safety: at most one leader per term)."""
    c = SimCluster(CFG, 3)
    _elect_with_2_partitioned(c)

    # crash replica 2; recover from leader snapshot + peer vote records
    snap = take_snapshot(c.state, donor=0)
    vt, vf = recover_vote(c.state, 2, peers=[0])
    c.state = install_snapshot(c.state, 2, snap, voted_term=vt,
                               voted_for=vf)

    # 1 (still at term 0) campaigns for term 1 with only {1, 2} reachable:
    # 2 already voted for 0 in term 1 and must refuse
    c.partition([[1, 2], [0]])
    res = c.step(timeouts=[1])
    assert res["role"][1] != int(Role.LEADER), (
        "replica 2 double-voted in term 1 — two leaders in one term")


def test_unrestored_vote_would_double_vote():
    """Control for the test above: WITHOUT vote restoration the same
    scenario elects a second term-1 leader — proving the restored vote is
    what provides the safety."""
    c = SimCluster(CFG, 3)
    _elect_with_2_partitioned(c)
    snap = take_snapshot(c.state, donor=0)
    c.state = install_snapshot(c.state, 2, snap)   # vote NOT restored
    c.partition([[1, 2], [0]])
    res = c.step(timeouts=[1])
    assert res["role"][1] == int(Role.LEADER), (
        "scenario no longer exercises the double-vote hazard")


def test_hardstate_roundtrip(tmp_path):
    hs = HardState(str(tmp_path / "r0.hs"))
    assert hs.load() is None
    hs.save(3, 3, 1)
    assert hs.load() == (3, 3, 1)
    hs.save(5, 4, 2)
    fresh = HardState(str(tmp_path / "r0.hs"))
    assert fresh.load() == (5, 4, 2)


def test_install_floors_term_at_recovered_vote():
    c = SimCluster(CFG, 3)
    _elect_with_2_partitioned(c)
    snap = take_snapshot(c.state, donor=0)
    c.state = install_snapshot(c.state, 2, snap, voted_term=7,
                               voted_for=0, cur_term=5)
    assert int(np.asarray(c.state.term[2])) == 7
    assert int(np.asarray(c.state.voted_term[2])) == 7
    assert int(np.asarray(c.state.voted_for[2])) == 0


def test_host_driver_restores_hardstate():
    """A restarted NodeDaemon restores (term, voted_term, voted_for) from
    its HardState file into its replica row before stepping (the
    multi-host analog of ClusterDriver._do_recover's restore)."""
    from rdma_paxos_tpu.runtime.host import HostReplicaDriver
    hd = HostReplicaDriver(CFG, process_id=0, num_processes=3,
                           coordinator="", initialize_distributed=False)
    hd.restore_hardstate(4, 4, 1)
    assert int(np.asarray(hd.state.term[0])) == 4
    assert int(np.asarray(hd.state.voted_term[0])) == 4
    assert int(np.asarray(hd.state.voted_for[0])) == 1
    # stale persisted state never regresses newer in-memory state
    hd.restore_hardstate(2, 2, 0)
    assert int(np.asarray(hd.state.term[0])) == 4
    assert int(np.asarray(hd.state.voted_for[0])) == 1
    # the cluster is live after restore: a campaign from the restored
    # replica runs at term 5 (> restored term 4) and the other replicas
    # hear and grant — exercising that single-process padding rows are
    # neutral (peer_mask ones), not deaf
    res = hd.step(timeout_fired=True)
    assert int(res["role"]) == int(Role.LEADER)
    assert int(res["term"]) == 5


def test_truncated_config_rolls_back():
    """An adopted-but-uncommitted CONFIG entry that is truncated by the
    divergence rule must stop governing the replica: the config reverts
    to the newest surviving configuration (Raft's fall-back rule). The
    reference's incremental poll_config_entries cannot revert; the
    derive-from-log scan here does."""
    c = SimCluster(CFG, 8, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    c.step()
    base = mm.current(0)
    assert base["bitmask_new"] == 0b111

    # leader 0 is partitioned alone, appends a TRANSIT config locally —
    # adopted immediately (append-time rule) but never replicated
    c.partition([[0], [1, 2]])
    mm.submit_transit(0, 0b111, 0b11111, epoch=1)
    c.step()
    assert mm.current(0)["cid_state"] == int(ConfigState.TRANSIT)
    assert mm.current(0)["bitmask_new"] == 0b11111

    # meanwhile 1 wins a higher-term election and appends entries
    res = c.step(timeouts=[1])
    assert res["role"][1] == int(Role.LEADER)
    c.submit(1, b"overwrite")
    c.step()

    # heal: 0 absorbs the higher-term window, its uncommitted CONFIG is
    # truncated -> config must roll back to the stable base config
    c.heal()
    for _ in range(4):
        res = c.step()
    cur = mm.current(0)
    assert cur["bitmask_new"] == 0b111, (
        "truncated CONFIG still governs replica 0")
    assert cur["cid_state"] == int(ConfigState.STABLE)
    assert cur["epoch"] == base["epoch"]
    # and the cluster still functions under the rolled-back config
    c.submit(1, b"after-rollback")
    res = c.step()
    res = c.step()
    assert int(res["commit"][1]) == int(res["end"][1])


def test_committed_config_survives_pruning():
    """Once a CONFIG entry commits, its config must keep governing even
    after the entry is pruned from the ring (checkpoint fallback)."""
    small = LogConfig(n_slots=16, slot_bytes=32, window_slots=8,
                      batch_slots=4)
    c = SimCluster(small, 5, group_size=3)
    mm = MembershipManager(c)
    c.run_until_elected(0)
    mm.change(0, 0b11111)          # commit an upsize to 5
    assert mm.current(0)["bitmask_new"] == 0b11111
    # flood the tiny ring so pruning advances head past the CONFIG entries
    for i in range(40):
        c.submit(0, b"x%d" % i)
        c.step()
    for _ in range(4):
        res = c.step()
    head = int(res["head"][0])
    assert head > 0, "ring never pruned"
    cur = mm.current(0)
    assert cur["bitmask_new"] == 0b11111, (
        "config lost when its entry was pruned")
    # quorum is still 3-of-5
    c.partition([[0, 1, 2], [3], [4]])
    c.submit(0, b"still-5")
    res = c.step()
    assert int(res["commit"][0]) == int(res["end"][0])
    c.heal()
