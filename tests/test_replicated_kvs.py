"""Standalone-DARE mode: the device KVS replicated through consensus —
every replica's table converges; linearizable reads obey read-index."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)


def test_replicated_kvs_end_to_end():
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    kv.put(0, b"city", b"zurich")
    kv.put(0, b"temp", b"7C")
    c.step()
    c.step()
    # every replica's device table converged to the same contents
    for r in range(3):
        assert kv.get(r, b"city") == b"zurich"
        assert kv.get(r, b"temp") == b"7C"
    kv.remove(0, b"temp")
    kv.put(0, b"city", b"basel")
    c.step()
    c.step()
    for r in range(3):
        assert kv.get(r, b"city") == b"basel"
        assert kv.get(r, b"temp") is None


def test_linearizable_get_requires_verified_leadership():
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    kv.put(0, b"k", b"v")
    c.step()
    assert kv.get(0, b"k", linearizable=True) == b"v"
    assert kv.get(1, b"k", linearizable=True) is None   # not the leader
    # isolated leader can no longer verify -> refuses linearizable reads
    c.partition([[0], [1, 2]])
    c.step()
    c.step()
    assert kv.get(0, b"k", linearizable=True) is None
    assert kv.get(0, b"k") == b"v"                      # weak read fine


def test_kvs_survives_failover():
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    kv.put(0, b"persist", b"1")
    c.step()
    c.step()
    c.partition([[0], [1, 2]])
    c.step(timeouts=[1])
    kv.put(1, b"persist", b"2")
    c.step()
    c.step()
    assert kv.get(1, b"persist", linearizable=True) == b"2"
    assert kv.get(2, b"persist") == b"2"
