"""Standalone-DARE mode: the device KVS replicated through consensus —
every replica's table converges; linearizable reads obey read-index."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.models.replicated_kvs import ReplicatedKVS
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=128, slot_bytes=128, window_slots=32,
                batch_slots=16)


def test_replicated_kvs_end_to_end():
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    kv.put(0, b"city", b"zurich")
    kv.put(0, b"temp", b"7C")
    c.step()
    c.step()
    # every replica's device table converged to the same contents
    for r in range(3):
        assert kv.get(r, b"city") == b"zurich"
        assert kv.get(r, b"temp") == b"7C"
    kv.remove(0, b"temp")
    kv.put(0, b"city", b"basel")
    c.step()
    c.step()
    for r in range(3):
        assert kv.get(r, b"city") == b"basel"
        assert kv.get(r, b"temp") is None


def test_linearizable_get_requires_verified_leadership():
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    kv.put(0, b"k", b"v")
    c.step()
    assert kv.get(0, b"k", linearizable=True) == b"v"
    assert kv.get(1, b"k", linearizable=True) is None   # not the leader
    # isolated leader can no longer verify -> refuses linearizable reads
    c.partition([[0], [1, 2]])
    c.step()
    c.step()
    assert kv.get(0, b"k", linearizable=True) is None
    assert kv.get(0, b"k") == b"v"                      # weak read fine


def test_kvs_survives_failover():
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    kv.put(0, b"persist", b"1")
    c.step()
    c.step()
    c.partition([[0], [1, 2]])
    c.step(timeouts=[1])
    kv.put(1, b"persist", b"2")
    c.step()
    c.step()
    assert kv.get(1, b"persist", linearizable=True) == b"2"
    assert kv.get(2, b"persist") == b"2"


def test_client_dedup_retransmit_applies_once():
    """The dare_ep_db last_req_id analog: a client that retransmits after
    seeing no ack must have its op applied exactly once — even when both
    the original AND the duplicate committed (dare_ep_db.h:20-30)."""
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    sess = kv.session(client_id=7)
    rid = sess.put(0, b"k", b"v1")
    c.step()
    c.step()
    # the ack was lost: client retransmits the same request (twice!)
    sess.retransmit_put(0, b"k", b"v1", rid)
    sess.retransmit_put(0, b"k", b"v1", rid)
    c.step()
    c.step()
    assert kv.get(0, b"k", linearizable=True) == b"v1"
    assert kv.deduped[0] == 2
    # every replica deduped identically (fold is deterministic)
    assert kv.get(1, b"k") == b"v1" and kv.get(2, b"k") == b"v1"
    assert kv.deduped[1] == 2 and kv.deduped[2] == 2


def test_client_dedup_late_duplicate_cannot_regress():
    """A stale duplicate arriving AFTER a newer op from the same client
    must not roll the value back (first-commit-wins ordering)."""
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    sess = kv.session(client_id=9)
    r1 = sess.put(0, b"x", b"old")
    c.step()
    sess.put(0, b"x", b"new")
    c.step()
    # duplicate of the FIRST request shows up late (e.g. a queued
    # retransmit raced the second request)
    sess.retransmit_put(0, b"x", b"old", r1)
    c.step()
    c.step()
    assert kv.get(0, b"x", linearizable=True) == b"new"
    assert kv.deduped[0] == 1


def test_client_dedup_survives_failover():
    """Retransmit against the NEW leader after the old one died: the
    committed original is not re-applied (dedup derives from the
    replicated log, not leader-local memory)."""
    c = SimCluster(CFG, 3)
    kv = ReplicatedKVS(c, cap=256)
    c.run_until_elected(0)
    sess = kv.session(client_id=3)
    rid = sess.put(0, b"f", b"committed")
    c.step()                          # committed by leader 0
    c.step()
    c.partition([[0], [1, 2]])        # leader dies before acking client
    c.step(timeouts=[1])
    # client retries against the new leader; also writes something new
    sess.retransmit_put(1, b"f", b"committed", rid)
    sess.put(1, b"g", b"after")
    c.step()
    c.step()
    c.heal()
    c.step()
    c.step()
    for r in range(3):
        assert kv.get(r, b"f") == b"committed"
        assert kv.get(r, b"g") == b"after"
    assert kv.deduped[1] == 1         # new leader's fold skipped the dup
