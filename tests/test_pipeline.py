"""Pipelined dispatch (begin_*/finish) — the perf PR's correctness bar.

Pipelining must be a PURE latency/throughput transform:

* engine level: the same submission schedule driven serial vs depth-2
  pipelined yields bit-identical step outputs, committed replay
  streams, and apply cursors — with the dispatch-concurrency counter
  proving the pipelined run really overlapped dispatches
* driver level: a recorded workload through ``ClusterDriver`` with
  ``pipeline=0`` vs ``pipeline=2`` run loops commits the identical
  client entry stream and releases the identical ack sequence — no
  duplicate, missing, or reordered acks
* under chaos: ``NemesisRunner(pipeline=2)`` schedules (crash,
  drops, partitions) keep I1–I5 + per-key linearizability green at
  100% audit
* auditing: injected log corruption is localized to the exact first
  ``(term, index)`` while dispatches overlap
* the sharded e2e driver routes connections by key prefix onto G
  groups and releases per-group acks through the same pipeline
* observability export runs on the READBACK thread, never the
  dispatch path
"""

import threading
import time

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=128, slot_bytes=64, window_slots=32,
                batch_slots=8)
# manual elections only — wall-clock timers must never fire mid-test
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)

# commit-stream-relevant outputs. ``apply``/``head`` are deliberately
# EXCLUDED: the device apply echo / pruning frontier follow the
# apply_done INPUT, which lags by design while dispatches overlap (the
# readback hasn't run yet) — a capacity effect, not a protocol one.
# The replayed streams and final apply cursors are compared directly.
RES_CMP = ("term", "role", "leader_id", "commit", "end", "accepted",
           "acked", "hb_seen", "leadership_verified")


# ---------------------------------------------------------------------------
# engine-level bit-identity
# ---------------------------------------------------------------------------

def _drive_engine(pipelined: bool):
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    outs = []
    inflight = []
    for i in range(24):
        for j in range(5):
            c.submit(0, b"p%d-%d" % (i, j))
        if pipelined:
            inflight.append(c.begin_step())
            if len(inflight) >= 2:
                outs.append(c.finish(inflight.pop(0)))
        else:
            outs.append(c.step())
    while inflight:
        outs.append(c.finish(inflight.pop(0)))
    # drain the committed tail so replay streams are complete
    for _ in range(4):
        outs.append(c.step())
    return c, outs


def test_engine_pipelined_step_stream_bit_identical():
    cs, serial = _drive_engine(False)
    cp, piped = _drive_engine(True)
    assert cp.max_inflight_dispatches >= 2, (
        "pipelined drive never overlapped dispatches")
    assert cs.max_inflight_dispatches <= 1
    assert len(serial) == len(piped)
    for k, (a, b) in enumerate(zip(serial, piped)):
        for key in RES_CMP:
            assert np.array_equal(a[key], b[key]), (k, key)
    for r in range(3):
        assert cs.replayed[r] == cp.replayed[r], r
    assert np.array_equal(cs.applied, cp.applied)


def test_engine_pipelined_burst_reservation_no_loss():
    """Two bursts in flight: the second's capacity clamp must reserve
    the first's not-yet-finished appends (they are invisible in
    ``last["end"]``) so the ring can never drop mid-burst — every
    submitted entry commits exactly once, in order."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    sent = [b"e%03d" % i for i in range(160)]
    for p in sent[:100]:
        c.submit(0, p)
    t1 = c.begin_burst()                    # takes the first 100
    for p in sent[100:]:
        c.submit(0, p)
    # without the reservation this burst would size itself against the
    # PRE-t1 end/head and overrun the 128-slot ring mid-burst
    t2 = c.begin_burst()
    assert c.max_inflight_dispatches >= 2
    assert sum(len(t.taken[r]) for t in (t1, t2)
               for r in range(3)) <= CFG.n_slots - 1
    c.finish(t1)
    c.finish(t2)
    for _ in range(40):
        if not c.pending[0] and all(
                int(c.last["commit"][r]) == int(c.last["end"][0])
                for r in range(3)):
            break
        c.step_burst()
    got = [p for (_t, _c, _r, p) in c.replayed[0]]
    assert got == sent


def test_engine_pipelined_audit_localizes_corruption():
    """Digest auditing stays exact under overlapped dispatches: a
    single-bit flip of a follower's committed slot is localized to the
    exact first (term, index) while the pipeline is in flight."""
    import dataclasses
    from rdma_paxos_tpu.consensus.log import Log

    c = SimCluster(CFG, 3, audit=True)
    c.run_until_elected(0)
    for i in range(12):
        c.submit(0, b"a%d" % i)
        c.step()
    target = int(c.last["commit"].min()) - 1
    slot = target & (CFG.n_slots - 1)
    buf = c.state.log.buf.at[2, slot, 0].add(1)
    c.state = dataclasses.replace(c.state, log=Log(buf=buf))
    t1 = c.begin_step()
    t2 = c.begin_step()
    c.finish(t1)
    c.finish(t2)
    assert c.max_inflight_dispatches >= 2
    f = c.auditor.first_divergence()
    assert f is not None, "corruption not detected under pipelining"
    assert f["index"] == target
    assert f["got_replicas"] == [2]


# ---------------------------------------------------------------------------
# driver-level identity (recorded workload, real run loop)
# ---------------------------------------------------------------------------

def _drive_driver(pipeline: int):
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, pipeline=pipeline)
    d.cluster.run_until_elected(0)
    d.step()
    assert d.leader() == 0
    handler = d._make_handler(0)
    conns = [(0 << 24) | 11, (0 << 24) | 12]
    for conn in conns:
        st = handler(2, conn, b"")
        assert not isinstance(st, int) or st == 0
    # recorded workload: one intake thread, alternating connections,
    # no waiting between submissions — the submit order IS the record.
    # The whole record is queued BEFORE the loop starts and is SIZED
    # PAST one fused burst's capacity (K_TIERS[-1] * batch_slots,
    # further clamped by the 127-slot ring): on a fast
    # idle host, trickling events in against a live loop lets the
    # readback retire every ticket before the dispatch thread sees a
    # standing backlog — and a backlog one burst can swallow whole
    # vanishes at the first dispatch — so _pipeline_ready (which
    # needs a standing backlog) never engages and the overlap
    # assertion below races the machine instead of testing the
    # driver. A pre-queued record longer than one burst makes the
    # pipelined variant's overlap structural; the serial variant
    # drains the identical record.
    evs = []
    for i in range(200):
        ev = handler(3, conns[i % 2], b"w%03d" % i)
        assert not isinstance(ev, int), (i, ev)
        evs.append(ev)
    d.run(period=0.001)
    for i, ev in enumerate(evs):
        assert ev.done.wait(30), f"ack {i} never released"
    time.sleep(0.1)          # let follower replay frontiers settle
    d.stop()
    assert d.loop_error is None
    stream = [e for e in d.cluster.replayed[0]]
    statuses = [ev.status for ev in evs]
    return d, stream, statuses


def test_driver_pipelined_commit_and_ack_stream_identical():
    ds, stream_s, st_s = _drive_driver(0)
    dp, stream_p, st_p = _drive_driver(2)
    assert dp.cluster.max_inflight_dispatches >= 2, (
        "pipelined driver never overlapped dispatches")
    assert ds.cluster.max_inflight_dispatches <= 1
    # ack stream: every submission acked exactly once, successfully,
    # identically across the two drivers
    assert st_s == [0] * 200
    assert st_p == st_s
    # commit stream bit-identity: same entries, same order, same bytes
    assert stream_p == stream_s
    payloads = [p for (_t, _c, _r, p) in stream_s
                if p.startswith(b"w")]
    assert payloads == [b"w%03d" % i for i in range(200)]
    # per-connection req stamps strictly increase (no reorder, no dup)
    for conn_sel in (11, 12):
        reqs = [r for (_t, c, r, _p) in stream_p
                if c & 0xFFFFFF == conn_sel]
        assert reqs == sorted(reqs) and len(set(reqs)) == len(reqs)


def test_driver_observability_rides_readback_thread():
    """The small-fix satellite: _observe_step (and the whole post-step
    rule set) must run on the READBACK thread under pipelining, so
    observability can never serialize the dispatch path it measures."""
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, pipeline=2)
    d.cluster.run_until_elected(0)
    d.step()
    seen = []
    orig = d._observe_step

    def spy(res):
        seen.append(threading.current_thread())
        return orig(res)
    d._observe_step = spy
    handler = d._make_handler(0)
    conn = (0 << 24) | 21
    handler(2, conn, b"")
    d.run(period=0.001)
    evs = [handler(3, conn, b"x%d" % i) for i in range(20)]
    for ev in evs:
        assert ev.done.wait(30)
    d.stop()
    assert d.loop_error is None
    assert d._rb_thread in seen, (
        "post-step observability never ran on the readback thread")


def test_driver_pipeline_crash_releases_waiters():
    """A dispatch-path exception under pipelining fails blocked waiters
    fast (no hang) and latches loop_error — same contract as serial."""
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, pipeline=2)
    d.cluster.run_until_elected(0)
    d.step()
    handler = d._make_handler(0)
    conn = (0 << 24) | 31
    handler(2, conn, b"")
    ev = handler(3, conn, b"doomed")

    def boom(*a, **k):
        raise RuntimeError("injected dispatch failure")
    d.cluster.begin_step = boom
    d.cluster.begin_burst = boom
    d.cluster.step = boom
    d.cluster.step_burst = boom
    d.run()
    assert ev.done.wait(10), "waiter never released after crash"
    assert ev.status == -1
    assert isinstance(d.loop_error, RuntimeError)
    d.stop()


# ---------------------------------------------------------------------------
# chaos under pipelining
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_nemesis_pipelined_green_and_overlapped():
    """NemesisRunner schedules (crash-restart, drops, partitions,
    skew) with pipeline depth 2: I1–I5 + per-key linearizability hold,
    audit (100%) finds nothing, no duplicate/reordered client acks —
    and the run provably overlapped dispatches."""
    from rdma_paxos_tpu.chaos.runner import NemesisRunner
    runner = NemesisRunner(n_replicas=3, seed=7, steps=50, pipeline=2)
    v = runner.run()
    assert v["ok"], v
    assert v["invariant_violations"] == []
    assert v["linearizability"]["ok"] is True
    assert v["audit"] is not None and v["audit"]["findings"] == 0
    assert runner.cluster.max_inflight_dispatches >= 2, (
        "chaos run never engaged the pipeline")
    # ack discipline: every client op completed at most once (the
    # recorder rejects double completion; re-assert through the data)
    ops = runner.history.ops(include_weak=True)
    ids = [o["op_id"] for o in ops if "op_id" in o]
    assert len(ids) == len(set(ids))


@pytest.mark.chaos
def test_nemesis_pipelined_leader_crash_midflight():
    """A schedule that provably crashes the elected leader mid-run:
    failover + retransmit under a depth-2 pipeline stays correct."""
    from rdma_paxos_tpu.chaos.faults import FaultSchedule
    from rdma_paxos_tpu.chaos.runner import NemesisRunner

    # probe the fault-free trajectory of THIS seed to learn who leads
    # at step 24, then crash exactly that replica mid-run — identical
    # seeds make the pre-crash trajectories bit-identical, so the
    # crash provably hits the serving leader
    probe = NemesisRunner(n_replicas=3, seed=21, steps=24,
                          schedule=FaultSchedule([]))
    violations: list = []
    lead = -1
    for t in range(24):
        lead = probe._one_step(t, lead, violations)
    lead = probe._drain(lead, violations)
    assert lead >= 0 and not violations
    sch = (FaultSchedule()
           .at(24, "crash", replica=lead)
           .at(27, "drop", p=0.25)
           .at(34, "drop", p=0.0)
           .at(42, "restart", replica=lead)
           .at(48, "heal"))
    runner = NemesisRunner(n_replicas=3, seed=21, steps=60,
                           schedule=sch, pipeline=2)
    v = runner.run()
    assert v["ok"], v
    assert runner.cluster.max_inflight_dispatches >= 2


# ---------------------------------------------------------------------------
# sharded e2e driver (key-prefix routing through the same pipeline)
# ---------------------------------------------------------------------------

def test_sharded_driver_key_prefix_routing_and_acks():
    from rdma_paxos_tpu.runtime.sharded_driver import (
        ShardedClusterDriver, key_prefix_of)

    assert key_prefix_of(b"SET k3-17 v1\n") == b"k3"
    assert key_prefix_of(
        b"*3\r\n$3\r\nSET\r\n$5\r\nk4-99\r\n$2\r\nv0\r\n") == b"k4"
    assert key_prefix_of(b"") == b""
    # the FIRST-occurring delimiter wins, not the first in scan order
    assert key_prefix_of(b"SET user.1-x v\n") == b"user"
    assert key_prefix_of(b"SET a:b.c-d v\n") == b"a"

    d = ShardedClusterDriver(
        CFG, 3, 4,
        timeout_cfg=TimeoutConfig(elec_timeout_low=0.05,
                                  elec_timeout_high=0.1))
    try:
        d.run(period=0.002)
        t0 = time.time()
        while d.leader() < 0:
            time.sleep(0.02)
            assert time.time() - t0 < 60, (d.leaders(), d.loop_error)
        # round-robin placement: G leaderships spread over R replicas
        assert sorted(set(d.leaders())) == [0, 1, 2]

        handlers = [d._make_handler(r) for r in range(3)]

        def client(r, tid, wave, n, acks):
            # flood the connection's SENDs, then collect the acks: the
            # pipeline engages only while append BACKLOG flows (strict
            # request-ack-request clients ride the serial latency path
            # by design), so depth >= 2 needs pipelined traffic
            h = handlers[r]
            conn = (r << 24) | (wave << 12) | (1000 + tid)
            st = h(2, conn, b"")
            assert st == 0 or st is None, st
            evs = []
            for i in range(n):
                ev = h(3, conn, b"SET k%d-%d v%d\n" % (tid, i, i))
                assert not isinstance(ev, int), (r, tid, i, ev)
                evs.append(ev)
            for i, ev in enumerate(evs):
                assert ev.done.wait(30), "ack timed out"
                assert ev.status == 0
                acks.append((tid, i))

        # overlap is opportunistic (the loop drains whenever backlog
        # momentarily empties), so under host load one wave may retire
        # every ticket before the next dispatch — repeat waves until a
        # depth >= 2 overlap is witnessed
        for wave in range(4):
            acks = []
            threads = [
                threading.Thread(target=client,
                                 args=(r, t, wave, 25, acks))
                for t, r in enumerate([0, 1, 2, 0, 1, 2])]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(acks) == 150
            assert d.loop_error is None
            if d.cluster.max_inflight_dispatches >= 2:
                break
        assert d.cluster.max_inflight_dispatches >= 2
        # the six prefixes really demuxed onto more than one group
        groups = {d.router.group_of(b"k%d" % t) for t in range(6)}
        assert len(groups) > 1
        # every group's committed stream replayed into every replica:
        # the per-(replica, group) apply cursors reached the commit
        c = d.cluster
        for g in groups:
            for r in range(3):
                assert c.applied[g, r] == int(
                    c.last["commit"][g, r]), (g, r)
        h = d.health()
        assert h["n_groups"] == 4 and len(h["leaders"]) == 4
        assert h["router"]["n_groups"] == 4
    finally:
        d.stop()


def test_sharded_driver_unsupported_admin_surfaces_raise():
    from rdma_paxos_tpu.runtime.sharded_driver import (
        ShardedClusterDriver)
    d = ShardedClusterDriver(CFG, 3, 2, timeout_cfg=TO)
    for call in (lambda: d.request_membership(0b11),
                 lambda: d.recover_replica(1),
                 lambda: d.reset_app(1),
                 lambda: d.checkpoint_app(1)):
        with pytest.raises(NotImplementedError):
            call()
    d.stop()
