"""Device-native KVS state machine (dare_kvs_sm analog) — PUT/GET/RM
semantics, collision handling, batch apply, and replicated determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rdma_paxos_tpu.models.kvs import (
    CMD_W, OP_GET, OP_PUT, OP_RM,
    apply_batch, apply_cmd, decode_val, encode_cmd, make_kvs)


def run(kv, op, key, val=b""):
    kv, out = jax.jit(apply_cmd)(kv, jnp.asarray(encode_cmd(op, key, val)))
    return kv, decode_val(np.asarray(out))


def test_put_get_rm():
    kv = make_kvs(64)
    kv, _ = run(kv, OP_PUT, b"alpha", b"1")
    kv, v = run(kv, OP_GET, b"alpha")
    assert v == b"1"
    kv, _ = run(kv, OP_PUT, b"alpha", b"2")     # overwrite
    kv, v = run(kv, OP_GET, b"alpha")
    assert v == b"2"
    kv, _ = run(kv, OP_RM, b"alpha")
    kv, v = run(kv, OP_GET, b"alpha")
    assert v == b""


def test_get_missing_and_unknown_op():
    kv = make_kvs(64)
    kv, v = run(kv, OP_GET, b"ghost")
    assert v == b""
    kv, _ = run(kv, 99, b"x", b"y")             # garbage op: no-op
    kv, v = run(kv, OP_GET, b"x")
    assert v == b""


def test_many_keys_with_collisions():
    kv = make_kvs(512)
    n = 150
    for i in range(n):
        kv, _ = run(kv, OP_PUT, b"key%03d" % i, b"val%03d" % i)
    for i in range(0, n, 7):
        kv, v = run(kv, OP_GET, b"key%03d" % i)
        assert v == b"val%03d" % i
    for i in range(0, n, 3):
        kv, _ = run(kv, OP_RM, b"key%03d" % i)
    kv, v = run(kv, OP_GET, b"key%03d" % 3)
    assert v == b""
    kv, v = run(kv, OP_GET, b"key%03d" % 7)     # survivors intact
    assert v == b"val%03d" % 7


def test_batch_apply_in_log_order():
    kv = make_kvs(64)
    cmds = np.stack([
        encode_cmd(OP_PUT, b"k", b"first"),
        encode_cmd(OP_PUT, b"k", b"second"),
        encode_cmd(OP_RM, b"dead"),
        encode_cmd(OP_PUT, b"k2", b"x"),
        encode_cmd(OP_PUT, b"ignored", b"beyond-count"),
    ])
    kv, _ = jax.jit(apply_batch)(kv, jnp.asarray(cmds),
                                 jnp.asarray(4, jnp.int32))
    kv, v = run(kv, OP_GET, b"k")
    assert v == b"second"                       # log order respected
    kv, v = run(kv, OP_GET, b"k2")
    assert v == b"x"
    kv, v = run(kv, OP_GET, b"ignored")
    assert v == b""                             # beyond count: not applied


def test_replicated_kvs_determinism():
    """Two replicas applying the same committed command stream reach
    bit-identical state — the state-machine-replication contract."""
    import random
    rng = random.Random(7)
    cmds = []
    for _ in range(200):
        op = rng.choice([OP_PUT, OP_PUT, OP_RM, OP_GET])
        key = b"k%d" % rng.randrange(30)
        val = b"v%d" % rng.randrange(1000)
        cmds.append(encode_cmd(op, key, val))
    a, b = make_kvs(128), make_kvs(128)
    for c in cmds:
        a, _ = jax.jit(apply_cmd)(a, jnp.asarray(c))
    arr = np.stack(cmds)
    b, _ = jax.jit(apply_batch)(b, jnp.asarray(arr),
                                jnp.asarray(len(cmds), jnp.int32))
    for f in ("keys", "vals", "used"):
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)))
