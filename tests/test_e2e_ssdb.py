"""Full-stack e2e on PRISTINE SSDB — the reference's third proof app
(``/root/reference/apps/ssdb/mk``; leveldb-backed NoSQL server),
replicated with zero source modifications.

SSDB exercises yet another app shape: a C++ epoll event-loop server with
a PERSISTENT on-disk state machine (leveldb) and a chatty length-prefixed
native protocol. Its inbound path is plain ``accept()`` + ``read()``
(src/net/link.cpp:186,222) — exactly the hooked surface. The offline
build recipe (apps/ssdb/mk) needs two build-environment accommodations
(no autoconf in the image; jemalloc stubbed to libc malloc) but zero app
changes.

Covers: replication to followers, bulk equality, non-idempotent incr
applied exactly once.
"""

import os
import socket
import subprocess
import time

import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
MK = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "apps", "ssdb", "mk")
BUILD = "/tmp/rp_ssdb_build"
SRC = os.path.join(BUILD, "ssdb-master")
BIN = os.path.join(SRC, "ssdb-server")

CFG = LogConfig(n_slots=512, slot_bytes=256, window_slots=64,
                batch_slots=32)
PORTS = [7411, 7412, 7413]


def ensure_ssdb() -> str:
    if os.path.exists(BIN):
        return BIN
    r = subprocess.run(["sh", MK, BUILD], capture_output=True,
                       timeout=1200)
    if r.returncode != 0 or not os.path.exists(BIN):
        pytest.skip("ssdb build unavailable: %s"
                    % r.stderr.decode()[-200:])
    return BIN


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE], check=True, capture_output=True)
    ensure_ssdb()


def write_conf(workdir: str, r: int, port: int) -> str:
    var = os.path.join(workdir, f"ssdb_var{r}")
    os.makedirs(var, exist_ok=True)
    path = os.path.join(workdir, f"ssdb{r}.conf")
    with open(os.path.join(SRC, "ssdb.conf")) as f:
        conf = f.read()
    conf = conf.replace("port: 8888", f"port: {port}")
    conf = conf.replace("work_dir = ./var", f"work_dir = {var}")
    conf = conf.replace("pidfile = ./var/ssdb.pid",
                        f"pidfile = {var}/ssdb.pid")
    with open(path, "w") as f:
        f.write(conf)
    return path


class SsdbClient:
    """Minimal SSDB native-protocol client (len\\ndata\\n ... \\n)."""

    def __init__(self, port):
        self.s = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.f = self.s.makefile("rb")

    def cmd(self, *args):
        out = b""
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out += str(len(b)).encode() + b"\n" + b + b"\n"
        self.s.sendall(out + b"\n")
        resp = []
        while True:
            ln = self.f.readline()
            if not ln:
                raise OSError("connection closed")
            ln = ln.strip()
            if ln == b"":             # blank line terminates the response
                return resp
            n = int(ln)
            data = self.f.read(n)
            self.f.readline()
            resp.append(data)

    def close(self):
        try:
            self.s.close()
        except OSError:
            pass


@pytest.fixture()
def stack(tmp_path):
    apps, driver = [], None
    try:
        driver = ClusterDriver(
            CFG, 3, workdir=str(tmp_path), app_ports=PORTS,
            timeout_cfg=TimeoutConfig(elec_timeout_low=0.3,
                                      elec_timeout_high=0.6))
        for r, port in enumerate(PORTS):
            env = dict(os.environ)
            env["LD_PRELOAD"] = os.path.join(NATIVE, "interpose.so")
            env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path),
                                                f"proxy{r}.sock")
            conf = write_conf(str(tmp_path), r, port)
            apps.append(subprocess.Popen(
                [BIN, conf], env=env, cwd=SRC,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        for port in PORTS:
            deadline = time.time() + 30
            while True:
                try:
                    socket.create_connection(("127.0.0.1", port),
                                             timeout=1).close()
                    break
                except OSError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.1)
        driver.run(period=0.002)
        deadline = time.time() + 60
        while driver.leader() < 0 and time.time() < deadline:
            time.sleep(0.05)
        assert driver.leader() >= 0, "no leader elected"
        yield driver
    finally:
        if driver is not None:
            driver.stop()
        for a in apps:
            a.kill()
            a.wait()


def wait_get(port, key, want, timeout=20.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            c = SsdbClient(port)
            resp = c.cmd("get", key)
            c.close()
            last = resp
            if resp[:1] == [b"ok"] and resp[1:2] == [want]:
                return want
        except (OSError, ValueError, IndexError):
            pass
        time.sleep(0.1)
    return last


def test_set_replicates_to_followers(stack):
    driver = stack
    lead = driver.leader()
    c = SsdbClient(PORTS[lead])
    assert c.cmd("set", "alpha", "one")[:1] == [b"ok"]
    assert c.cmd("get", "alpha") == [b"ok", b"one"]
    c.close()
    for r in range(3):
        if r == lead:
            continue
        assert wait_get(PORTS[r], "alpha", b"one") == b"one", f"replica {r}"


def test_bulk_state_equality(stack):
    driver = stack
    lead = driver.leader()
    c = SsdbClient(PORTS[lead])
    for i in range(40):
        assert c.cmd("set", f"k{i}", f"v{i}")[:1] == [b"ok"]
    c.close()
    for r in range(3):
        if r == lead:
            continue
        assert wait_get(PORTS[r], "k39", b"v39") == b"v39", f"replica {r}"
        cc = SsdbClient(PORTS[r])
        vals = [cc.cmd("get", f"k{i}")[1:2] for i in range(40)]
        cc.close()
        assert vals == [[b"v%d" % i] for i in range(40)], f"replica {r}"


def test_incr_applied_exactly_once_on_followers(stack):
    driver = stack
    lead = driver.leader()
    c = SsdbClient(PORTS[lead])
    assert c.cmd("set", "ctr", "5")[:1] == [b"ok"]
    assert c.cmd("incr", "ctr", "3") == [b"ok", b"8"]
    assert c.cmd("incr", "ctr", "2") == [b"ok", b"10"]
    c.close()
    for r in range(3):
        if r == lead:
            continue
        assert wait_get(PORTS[r], "ctr", b"10") == b"10", f"replica {r}"
