"""Stalled-follower pruning unblock — the ``force_log_pruning`` analog
(``dare_server.c:2069-2122``).

Normal pruning floors the head at the minimum apply offset over reachable
members, so a REACHABLE follower whose apply is frozen (a wedged app)
would otherwise block head advance forever and wedge the leader's ring.
Under hard ring pressure the leader force-advances its head past the
laggard (bounded by its own applied offset); the laggard detects that its
log was pruned past its apply cursor, stops replaying (recycled slots
must never reach the app), and is flagged for snapshot recovery — exactly
the reference's straggler-eviction-then-rejoin semantics."""

import os

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.snapshot import install_snapshot, take_snapshot
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
CFG_APP = LogConfig(n_slots=64, slot_bytes=64, window_slots=16,
                    batch_slots=8)


def _flood(c, leader, n, tag=b"f"):
    sent = 0
    for i in range(n):
        c.submit(leader, b"%s%04d" % (tag, i))
    steps = 0
    while c.pending[leader] and steps < 200:
        c.step()
        steps += 1
    c.step()


def test_wedged_follower_no_longer_blocks_the_ring():
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()

    # wedge follower 2's apply (its app stopped consuming)
    c.wedge_apply(2)
    # flood well past ring capacity (63 usable slots): without forced
    # pruning the head would floor at replica 2's frozen apply and the
    # leader would wedge after ~63 accepted entries
    total = 300
    for i in range(total):
        c.submit(0, b"w%04d" % i)
    for _ in range(250):
        if not c.pending[0]:
            break
        c.step()
    c.step()
    assert not c.pending[0], (
        f"leader wedged: {len(c.pending[0])} entries still queued "
        f"(head {int(c.last['head'][0])}, end {int(c.last['end'][0])})")
    # the healthy replicas replayed everything
    for r in (0, 1):
        stream = [p for (_, _, _, p) in c.replayed[r]]
        assert [p for p in stream if p.startswith(b"w")] == \
            [b"w%04d" % i for i in range(total)]
    # the wedged app resumes: its first replay attempt detects the
    # recycled slot (stamped gidx mismatch), flags recovery, and does
    # NOT pollute the stream with garbage
    c.unwedge_apply(2)
    c.step()
    assert 2 in c.need_recovery
    stream2 = [p for (_, _, _, p) in c.replayed[2]]
    assert all(p == b"w%04d" % i
               for i, p in enumerate(
                   p for p in stream2 if p.startswith(b"w")))

    # recovery: snapshot from the leader rejoins it (the reference's
    # straggler rejoin, rc_recover_sm)
    snap = take_snapshot(c.state, 0)
    c.state = install_snapshot(c.state, 2, snap)
    c.applied[2] = snap.index
    c.need_recovery.discard(2)
    c.submit(0, b"after-recovery")
    c.step()
    c.step()
    stream2 = [p for (_, _, _, p) in c.replayed[2]]
    assert stream2[-1] == b"after-recovery"


def test_normal_pressure_still_respects_laggard():
    """Below the hard-pressure threshold the old invariant holds: the
    head never passes a reachable member's apply (P1/P2/P3 of
    log_pruning, dare_server.c:1996-2067)."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()
    c.wedge_apply(2)
    # stay under the forced threshold (7/8 of 64 = 56): submit few
    for i in range(20):
        c.submit(0, b"n%02d" % i)
        c.step()
    c.step()
    assert int(c.last["head"][0]) <= c.applied[2]
    assert 2 not in c.need_recovery
    c.unwedge_apply(2)


def test_forced_pruning_bounded_by_leader_apply():
    """Forced pruning never advances the head past the leader's OWN
    applied offset — entries must be applied (and persisted) somewhere
    before their slots recycle, or snapshot recovery would have no
    source."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()
    c.wedge_apply(1)
    c.wedge_apply(2)
    for i in range(300):
        c.submit(0, b"b%04d" % i)
    for _ in range(250):
        if not c.pending[0]:
            break
        c.step()
    c.step()
    assert int(c.last["head"][0]) <= c.applied[0]
    # leader alone cannot commit without a quorum? it CAN: acks come
    # from absorb, which is independent of apply — both followers still
    # ack, so commits flow and the leader's own apply advances
    assert not c.pending[0]


def test_driver_auto_recovers_force_pruned_replica(tmp_path):
    """ClusterDriver heals a force-pruned replica automatically with a
    donor snapshot (the straggler-eviction-then-rejoin path collapsed
    into the polling loop)."""
    from rdma_paxos_tpu.runtime.driver import ClusterDriver
    d = ClusterDriver(CFG, 3, workdir=str(tmp_path))
    try:
        # elect THROUGH the driver so its election timers stay beaten
        # (randomized timeouts need wall time to stagger)
        for _ in range(500):
            d.step()
            if d.leader() >= 0:
                break
        lead = d.leader()
        assert lead >= 0
        victim = (lead + 1) % 3
        d.cluster.wedge_apply(victim)
        for i in range(300):
            d.cluster.submit(lead, b"a%04d" % i)
        for _ in range(250):
            d.step()
            if not d.cluster.pending[lead]:
                break
        assert not d.cluster.pending[lead]
        # the app unwedges; the next replay attempt flags recovery and
        # the poll loop snapshots it back to health
        d.cluster.unwedge_apply(victim)
        for _ in range(10):
            d.step()
            if (victim not in d.cluster.need_recovery
                    and d.cluster.applied[victim]
                    >= d.cluster.applied[lead]):
                break
        assert victim not in d.cluster.need_recovery
        # prove the recovered replica serves again (riding out any
        # post-recovery leadership churn by retrying the write)
        for _ in range(100):
            lead_now = d.leader()
            if lead_now >= 0:
                d.cluster.submit(lead_now, b"post-recovery")
            d.step()
            d.step()
            stream2 = [p for (_, _, _, p)
                       in d.cluster.replayed[victim]]
            if b"post-recovery" in stream2:
                break
        assert b"post-recovery" in [
            p for (_, _, _, p) in d.cluster.replayed[victim]]
    finally:
        d.stop()


def test_auto_recovery_live_app_exactly_once(tmp_path):
    """Force-pruned follower WITH a real app attached: auto-recovery
    must deliver only the DELTA into the still-running app — a full
    history replay would double-apply (key counts prove exactly-once)."""
    import socket
    import subprocess
    import time as _t
    from rdma_paxos_tpu.config import TimeoutConfig
    from rdma_paxos_tpu.runtime.driver import ClusterDriver

    native = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    subprocess.run(["make", "-C", native], check=True,
                   capture_output=True)
    base = 9950 + (os.getpid() % 40)
    ports = [base, base + 40, base + 80]
    # wide election timeouts: the drill needs NO mid-test election, and
    # on a slow/loaded host a single driver iteration can exceed a
    # sub-second timeout — the spurious deposition severs the client
    # session mid-drill (empty readline) for a pure environment reason
    d = ClusterDriver(CFG_APP, 3, workdir=str(tmp_path), app_ports=ports,
                      timeout_cfg=TimeoutConfig(elec_timeout_low=2.0,
                                                elec_timeout_high=4.0))
    apps = []
    try:
        for r, port in enumerate(ports):
            env = dict(os.environ)
            env["LD_PRELOAD"] = os.path.join(native, "interpose.so")
            env["RP_PROXY_SOCK"] = os.path.join(str(tmp_path),
                                                f"proxy{r}.sock")
            apps.append(subprocess.Popen(
                [os.path.join(native, "toyserver"), str(port)],
                env=env, stderr=subprocess.DEVNULL))
        _t.sleep(0.3)
        d.run(period=0.002)
        t0 = _t.time()
        while d.leader() < 0 and _t.time() - t0 < 60:
            _t.sleep(0.05)
        lead = d.leader()
        assert lead >= 0
        victim = (lead + 1) % 3

        def kv(port, line):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            f = s.makefile("rb")
            s.sendall(line)
            out = f.readline().strip()
            s.close()
            return out

        assert kv(ports[lead], b"SET pre wedge\n") == b"+OK"
        _t.sleep(0.5)
        d.cluster.wedge_apply(victim)
        s = socket.create_connection(("127.0.0.1", ports[lead]),
                                     timeout=30)
        f = s.makefile("rb")
        for i in range(300):        # way past the 64-slot ring
            s.sendall(b"SET k%03d v%03d\n" % (i, i))
            assert f.readline().strip() == b"+OK"
        s.close()
        d.cluster.unwedge_apply(victim)
        deadline = _t.time() + 40
        while (victim in d.cluster.need_recovery
               or d.cluster.applied[victim]
               < d.cluster.applied[lead] - 20):
            assert _t.time() < deadline, "auto-recovery incomplete"
            _t.sleep(0.1)
        _t.sleep(1.0)
        assert kv(ports[victim], b"COUNT\n") == \
            kv(ports[lead], b"COUNT\n"), "double/missed apply"
        assert kv(ports[victim], b"GET k250\n") == b"v250"
        assert kv(ports[victim], b"GET pre\n") == b"wedge"
    finally:
        d.stop()
        for a in apps:
            a.kill()
            a.wait()
