"""Stable fast path (``elections=False``) + multi-step burst dispatch.

The reference's latency story is a µs-scale busy commit loop on the NIC
(``rc_write_remote_logs`` ``dare_ibv_rc.c:1870-1948``). Here the analogs are
(a) the STABLE protocol step with the election phase statically removed —
one fewer collective per step — dispatched whenever no election timer
fired, and (b) the K-step burst (``lax.scan``) that amortizes host→device
dispatch over many protocol steps. Both must be behavior-identical to the
full step; these tests pin that down, including the failure interactions
(deposition around a burst, partitioned leader inside a burst)."""

import numpy as np
import pytest

import jax

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def _drive(c, n_ops=5, extra_steps=2):
    c.step(timeouts=[0])
    for i in range(n_ops):
        c.submit(0, b"op-%04d" % i)
        c.step()
    for _ in range(extra_steps):
        c.step()


def test_stable_step_bit_identical_to_full_step():
    """On iterations with no timeout fired, the stable step must produce
    bit-identical state AND outputs vs the full step (the docstring's
    contract in consensus/step.py)."""
    full = SimCluster(CFG, 3, stable_fast_path=False)
    fast = SimCluster(CFG, 3, stable_fast_path=True)
    _drive(full)
    _drive(fast)
    for k in full.last:
        assert np.array_equal(full.last[k], fast.last[k]), k
    for a, b in zip(jax.tree.leaves(full.state), jax.tree.leaves(fast.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_stable_step_compiles_and_steps():
    """Regression: elections=False used to crash with UnboundLocalError
    at trace time (advisor round-2 finding)."""
    c = SimCluster(CFG, 3, stable_fast_path=True)
    c.run_until_elected(0)
    c.submit(0, b"hello")
    res = c.step()          # no timeouts -> stable step dispatched
    assert res["commit"][0] >= 1


def test_stable_step_still_adopts_higher_term():
    """A deposed leader must step down even in stable steps (term adoption
    and window absorption are NOT part of Phase B)."""
    c = SimCluster(CFG, 3, stable_fast_path=False)
    c.run_until_elected(0)
    # partition 0 away; elect 1 at a higher term
    c.partition([[0], [1, 2]])
    c.step(timeouts=[1])
    assert c.last["role"][1] == int(Role.LEADER)
    c.heal()
    # healed step WITHOUT timeouts — force the stable path explicitly
    c._stable_fast_path = True
    res = c.step()
    assert res["role"][0] != int(Role.LEADER)
    assert res["term"][0] == res["term"][1]
    assert res["leader_id"][0] == 1


def test_vote_records_refresh_on_stable_steps_after_heal():
    """The durable vote pair now rides the control gather, so a replica
    partitioned during an election learns peers' votes on the first healed
    step — even a stable one."""
    c = SimCluster(CFG, 3, stable_fast_path=False)
    c.run_until_elected(0)
    c.partition([[2], [0, 1]])
    c.step(timeouts=[1])    # 1 elected at term 2; 2 heard nothing
    assert c.last["role"][1] == int(Role.LEADER)
    rec_before = np.asarray(c.state.vote_rec_term)[2]
    c.heal()
    c._stable_fast_path = True
    c.step()                # stable step: retention via control gather
    rec_after = np.asarray(c.state.vote_rec_term)[2]
    assert rec_after.max() > rec_before.max()


# ---------------------------------------------------------------------------
# burst dispatch
# ---------------------------------------------------------------------------

def test_burst_deep_queue_drain():
    """A deep queue drains through one burst dispatch with every entry
    committed in order."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()
    n = 40                              # 5 batches -> K=8 tier
    for i in range(n):
        c.submit(0, b"b%04d" % i)
    res = c.step_burst()
    assert int(res["accepted"][0]) == n
    assert int(res["commit"][0]) >= n   # NOOP + n, minus lazy tail
    c.step()
    for r in range(3):
        assert [p for (_, _, _, p) in c.replayed[r]] == \
            [b"b%04d" % i for i in range(n)]


def test_burst_near_ring_full_sizing_requeues_rest():
    """Sizing must clamp the burst to ring capacity and leave the
    remainder queued — never drop or reorder."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()
    n = 120                             # ring holds 63
    for i in range(n):
        c.submit(0, b"r%04d" % i)
    for _ in range(60):
        if not c.pending[0]:
            break
        c.step_burst()
        # let pruning free space (apply echo)
        c.step()
    assert not c.pending[0]
    c.step()
    for r in range(3):
        assert [p for (_, _, _, p) in c.replayed[r]] == \
            [b"r%04d" % i for i in range(n)]


def test_burst_after_leadership_change():
    """A burst issued right after a leadership change (old leader's queue
    still loaded) must not commit via the deposed leader."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()
    for i in range(20):
        c.submit(0, b"x%04d" % i)
    # depose 0: elect 1 at a higher term while 0 is partitioned
    c.partition([[0], [1, 2]])
    c.step(timeouts=[1])
    c.heal()
    c.step()                            # 0 steps down, absorbs 1's window
    assert c.last["role"][0] != int(Role.LEADER)
    res = c.step_burst()                # 0's queue nonempty but 0 follower
    # nothing from 0's queue was appended by a non-leader
    assert int(res["accepted"][0]) == 0
    stream = [p for (_, _, _, p) in c.replayed[1]]
    assert b"x0000" not in stream


def test_burst_with_partitioned_leader_no_commit_no_divergence():
    """Leader partitioned right before a burst: it appends locally but
    cannot commit (no quorum); after heal + re-election the divergent
    suffix is truncated and the cluster converges."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.submit(0, b"committed")
    c.step()
    c.step()
    commit0 = int(c.last["commit"][0])
    c.partition([[0], [1, 2]])
    for i in range(10):
        c.submit(0, b"lost%04d" % i)
    res = c.step_burst()                # leader-only burst: appends, no commit
    assert int(res["commit"][0]) == commit0
    assert int(res["end"][0]) > commit0
    # majority side elects a new leader and commits new traffic
    c.step(timeouts=[1])
    assert c.last["role"][1] == int(Role.LEADER)
    c.submit(1, b"won")
    c.step()
    c.heal()
    for _ in range(4):
        c.step()
    # old leader converged onto the new history; its lost suffix is gone
    assert int(c.last["end"][0]) == int(c.last["end"][1])
    stream0 = [p for (_, _, _, p) in c.replayed[0]]
    assert b"won" in stream0
    assert not any(p.startswith(b"lost") for p in stream0)


def test_burst_shortfall_requeues_instead_of_raising():
    """If a burst cannot append everything (ring pressure), the remainder
    must be requeued in order on the pending queue — the poll thread must
    never see an exception."""
    small = LogConfig(n_slots=16, slot_bytes=32, window_slots=8,
                      batch_slots=4)
    c = SimCluster(small, 3)
    c.run_until_elected(0)
    c.step()
    for i in range(30):
        c.submit(0, b"s%02d" % i)
    for _ in range(20):
        if not c.pending[0]:
            break
        c.step_burst()
        c.step()
    c.step()
    assert [p for (_, _, _, p) in c.replayed[0]] == \
        [b"s%02d" % i for i in range(30)]
