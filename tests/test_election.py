"""Election edge cases — the vote-granting and counting rules of the
reference (``dare_server.c:1264-1743``) under simultaneous candidacies,
stale logs, and vote-durability constraints."""

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def test_simultaneous_candidates_single_winner():
    """Two candidates in the same step: voters all rank the same best
    candidate (deterministic lexicographic pick), so exactly one wins —
    no split-vote livelock."""
    c = SimCluster(CFG, 3)
    res = c.step(timeouts=[0, 1])
    leaders = [r for r in range(3) if res["role"][r] == int(Role.LEADER)]
    assert len(leaders) == 1
    assert res["term"][leaders[0]] == 1


def test_stale_log_candidate_loses():
    """Vote refusal for out-of-date logs (dare_server.c:1596-1652): a
    candidate missing committed entries cannot win."""
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.submit(0, b"x")
    c.step()
    c.step()
    # replica 2 partitioned away, misses entries
    c.partition([[0, 1], [2]])
    c.submit(0, b"y")
    c.step()
    c.step()
    # heal the network but replica 2 immediately stands for election
    # with a stale log: 0 and 1 must refuse; 2 cannot win.
    c.heal()
    res = c.step(timeouts=[2])
    assert res["role"][2] != int(Role.LEADER)
    # (the failed candidacy bumped terms; a fresh election by an
    # up-to-date replica succeeds)
    res = c.step(timeouts=[1])
    assert res["role"][1] == int(Role.LEADER)
    # committed data survives the churn
    res = c.step()
    res = c.step()
    assert [p for (_, _, _, p) in c.replayed[2]] == [b"x", b"y"]


def test_leader_steps_down_on_higher_term():
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    c.step()
    # partition: majority side elects a new leader at a higher term
    c.partition([[0], [1, 2]])
    c.step(timeouts=[1])
    c.heal()
    res = c.step()
    assert res["role"][0] == int(Role.FOLLOWER)
    assert res["leader_id"][0] == 1
    assert len([r for r in range(3)
                if res["role"][r] == int(Role.LEADER)]) == 1


def test_transitional_config_election_uses_both_quorums():
    """During joint consensus (CID_TRANSIT, dare_config.h:17-24) a winner
    needs majorities of BOTH configs, and old-config members must still be
    allowed to vote — regression test for the old-only-voter deadlock."""
    import jax.numpy as jnp
    import dataclasses
    from rdma_paxos_tpu.consensus.state import ConfigState

    c = SimCluster(CFG, 5)
    # force a transitional config old={0,1,2} new={0,3,4} on every replica
    c.state = dataclasses.replace(
        c.state,
        cid_state=jnp.full((5,), int(ConfigState.TRANSIT), jnp.int32),
        bitmask_old=jnp.full((5,), 0b00111, jnp.uint32),
        bitmask_new=jnp.full((5,), 0b11001, jnp.uint32),
    )
    # candidate 0 is in both configs: old-only members 1,2 must grant votes
    res = c.step(timeouts=[0])
    assert res["role"][0] == int(Role.LEADER)
    # replica 3 is new-only; with old-members 1 and 2 partitioned away it
    # cannot reach the old-config majority -> must NOT win
    c2 = SimCluster(CFG, 5)
    c2.state = dataclasses.replace(
        c2.state,
        cid_state=jnp.full((5,), int(ConfigState.TRANSIT), jnp.int32),
        bitmask_old=jnp.full((5,), 0b00111, jnp.uint32),
        bitmask_new=jnp.full((5,), 0b11001, jnp.uint32),
    )
    c2.partition([[0, 3, 4], [1], [2]])
    res = c2.step(timeouts=[3])
    assert res["role"][3] != int(Role.LEADER)


def test_no_quorum_no_leader():
    """A candidate in a minority partition cannot win (losing majority
    means no leadership — the reference's suicide-on-lost-majority,
    dare_server.c:1213-1217, is a host-layer policy on top of this)."""
    c = SimCluster(CFG, 5)
    c.partition([[0], [1], [2, 3, 4]])
    res = c.step(timeouts=[0])
    assert res["role"][0] != int(Role.LEADER)
    res = c.step(timeouts=[2])
    assert res["role"][2] == int(Role.LEADER)  # majority side elects fine
