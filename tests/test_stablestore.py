"""Native stable store (BerkeleyDB-RECNO analog) through the ctypes
binding: append/read/dump/load round trips and crash-truncation recovery."""

import os
import struct
import subprocess

import pytest

from rdma_paxos_tpu.proxy.stablestore import StableStore, _NATIVE_DIR


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", _NATIVE_DIR, "libstablestore.so"],
                   check=True, capture_output=True)


def test_append_read_roundtrip(tmp_path):
    with StableStore(str(tmp_path / "a.db")) as s:
        assert len(s) == 0
        i0 = s.append(b"hello")
        i1 = s.append(b"world" * 100)
        assert (i0, i1) == (0, 1)
        assert len(s) == 2
        assert s.read(0) == b"hello"
        assert s.read(1) == b"world" * 100
        with pytest.raises(IndexError):
            s.read(2)


def test_reopen_persists(tmp_path):
    p = str(tmp_path / "b.db")
    with StableStore(p) as s:
        for i in range(10):
            s.append(b"rec%d" % i)
        s.sync()
    with StableStore(p) as s:
        assert len(s) == 10
        assert s.read(7) == b"rec7"


def test_dump_load_snapshot_transfer(tmp_path):
    """The joiner-recovery path: publisher dumps, joiner loads
    (dump_records/stablestorage_load_records analog)."""
    with StableStore(str(tmp_path / "src.db")) as src:
        for i in range(5):
            src.append(b"event-%d" % i)
        blob = src.dump()
    with StableStore(str(tmp_path / "dst.db")) as dst:
        assert dst.load(blob) == 5
        assert len(dst) == 5
        assert dst.read(4) == b"event-4"


def test_torn_tail_record_dropped(tmp_path):
    """A crash mid-append leaves a torn record; reopen must recover the
    intact prefix and discard the tail (it was never acked)."""
    p = str(tmp_path / "c.db")
    with StableStore(p) as s:
        s.append(b"good")
        s.sync()
    with open(p, "ab") as f:          # simulate torn write
        f.write(struct.pack("<I", 100) + b"short")
    with StableStore(p) as s:
        assert len(s) == 1
        assert s.read(0) == b"good"
        s.append(b"next")             # and the store keeps working
        assert len(s) == 2


def test_compaction_preserves_absolute_indices(tmp_path):
    """compact(n) drops records below n but indices stay ABSOLUTE: the
    suffix reads back at its original positions, appends continue the
    numbering, and the base survives close/reopen (it lives in the file
    header, not memory)."""
    p = str(tmp_path / "cp.db")
    with StableStore(p) as s:
        for i in range(10):
            s.append(b"rec-%d" % i)
        assert s.base == 0
        assert s.compact(6) == 6
        assert s.base == 6
        assert len(s) == 10
        assert s.read(6) == b"rec-6"
        assert s.read(9) == b"rec-9"
        with pytest.raises(IndexError):
            s.read(5)                 # compacted away
        assert s.append(b"rec-10") == 10
    with StableStore(p) as s:         # base is durable
        assert s.base == 6
        assert len(s) == 11
        assert s.read(10) == b"rec-10"


def test_compacted_dump_carries_base(tmp_path):
    """A compacted store's dump restores the same absolute indexing on
    the receiving side (donor transfer of checkpoint + suffix)."""
    src_p = str(tmp_path / "src2.db")
    with StableStore(src_p) as src:
        for i in range(8):
            src.append(b"e%d" % i)
        src.compact(5)
        blob = src.dump()
    with StableStore(str(tmp_path / "dst2.db")) as dst:
        dst.reset()
        assert dst.load(blob) == 3
        assert dst.base == 5
        assert len(dst) == 8
        assert dst.read(7) == b"e7"
        with pytest.raises(IndexError):
            dst.read(4)


def test_load_based_dump_into_nonempty_store_refused(tmp_path):
    """Loading a compacted (based) dump into a non-empty or already-
    based store would append its records at the wrong absolute indices,
    silently misaligning ss_read/replay — the C API must refuse (-1)
    rather than corrupt (Python callers reset() first, but the binding
    is not the only possible caller)."""
    src_p = str(tmp_path / "src3.db")
    with StableStore(src_p) as src:
        for i in range(8):
            src.append(b"e%d" % i)
        src.compact(5)
        blob = src.dump()
    # non-empty destination: refuse
    with StableStore(str(tmp_path / "dst3.db")) as dst:
        dst.append(b"pre-existing")
        with pytest.raises(OSError):
            dst.load(blob)
        assert len(dst) == 1               # nothing was appended
        assert dst.read(0) == b"pre-existing"
    # already-based destination: refuse too
    with StableStore(str(tmp_path / "dst4.db")) as dst:
        dst.reset()
        assert dst.load(blob) == 3         # first load adopts base 5
        with pytest.raises(OSError):
            dst.load(blob)                 # second load must not stack
        assert len(dst) == 8
