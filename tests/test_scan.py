"""Device-resident K-window scan tier — correctness pins.

The scan tier must be a PURE readback transform: K fused protocol
steps with a consolidated minimal readback (scalar matrix + in-dispatch
replay rows) produce step outputs, replay streams, frames, and apply
cursors bit-identical to the burst path (which is itself pinned
bit-identical to K serial steps) on every engine; scan-off clusters'
STEP_CACHE key sets and programs are untouched; the driver's ack/commit
streams are unchanged; and a chaos schedule crashing a leader drains
the scan tier to the serial path with zero violations."""

import threading
import time

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster

CFG = LogConfig(n_slots=128, slot_bytes=64, window_slots=32,
                batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)

RES_CMP = ("term", "role", "leader_id", "commit", "end", "accepted",
           "acked", "hb_seen", "leadership_verified", "head", "apply",
           "peer_acked", "rebase_delta", "voted_term", "voted_for",
           "became_leader")


def _drive_engine(scan: bool, audit: bool = False):
    c = SimCluster(CFG, 3, scan=scan, audit=audit)
    c.collect_frames = True
    c.run_until_elected(0)
    outs = []
    for i in range(10):
        for j in range(20):
            c.submit(0, b"p%d-%d" % (i, j))
        outs.append(c.step_burst())
    for _ in range(4):
        outs.append(c.step())
    return c, outs


def test_engine_scan_bit_identical_to_burst():
    cb, ob = _drive_engine(False)
    cs, os_ = _drive_engine(True)
    assert cs.scan_dispatches > 0
    assert cb.scan_dispatches == 0
    assert len(ob) == len(os_)
    for k, (a, b) in enumerate(zip(ob, os_)):
        for key in RES_CMP:
            assert np.array_equal(a[key], b[key]), (k, key)
    for r in range(3):
        assert cb.replayed[r] == cs.replayed[r], r
        assert list(cb.frames[r]) == list(cs.frames[r]), r
    assert np.array_equal(cb.applied, cs.applied)
    # the scan tier replaced the standalone replay fetch dispatches:
    # every burst's replay rode the staged rows (commit deltas fit
    # the replay window on this workload)
    assert cs.applied.min() > 0


def test_scan_equals_k_serial_steps():
    """The satellite pin, direct form: ONE K-step scan dispatch
    produces the same committed stream and final frontiers as the K
    serial steps it fuses (the serial drive takes the identical
    per-step batch prefixes the scan packs)."""
    def drive(scan_mode):
        c = SimCluster(CFG, 3, scan=scan_mode)
        c.run_until_elected(0)
        for i in range(30):                  # ceil(30/8) -> tier K=4
            c.submit(0, b"s%02d" % i)
        if scan_mode:
            c.step_burst()
        else:
            for _ in range(4):
                c.step()
        for _ in range(4):                   # settle the replay tail
            c.step()
        return c

    cs = drive(True)
    cb = drive(False)
    assert cs.scan_dispatches == 1
    for r in range(3):
        assert cs.replayed[r] == cb.replayed[r], r
    for key in ("term", "role", "leader_id", "commit", "end", "head"):
        assert np.array_equal(cs.last[key], cb.last[key]), key
    assert np.array_equal(cs.applied, cb.applied)
    assert cs.step_index == cb.step_index


def test_engine_scan_audit_windows_identical():
    cb, _ = _drive_engine(False, audit=True)
    cs, _ = _drive_engine(True, audit=True)
    assert cb.auditor.summary() == cs.auditor.summary()
    assert cb.auditor.summary()["findings"] == 0
    assert cb.auditor.summary()["indices_checked"] > 0


def test_scan_off_cache_keys_unchanged():
    keys_before = set(STEP_CACHE)
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    for j in range(9):
        c.submit(0, b"k%d" % j)
    c.step_burst()
    added = set(STEP_CACHE) - keys_before
    assert not any("scan" in k for k in added), added
    base = set(STEP_CACHE)
    # scan-on adds ONLY distinct "scan"-marked keys; every pre-scan
    # key (and thus program) is untouched
    cs = SimCluster(CFG, 3, scan=True)
    cs.run_until_elected(0)
    for j in range(9):
        cs.submit(0, b"k%d" % j)
    cs.step_burst()
    new = set(STEP_CACHE) - base
    assert new and all("scan" in k for k in new), new
    assert base <= set(STEP_CACHE)


@pytest.mark.parametrize("mesh", [None, (2, 2)])
def test_sharded_scan_bit_identical_to_burst(mesh):
    from rdma_paxos_tpu.shard.cluster import ShardedCluster

    def drive(scan):
        c = ShardedCluster(CFG, 2, 2, scan=scan, mesh=mesh)
        c.collect_frames = True
        c.place_leaders()
        outs = []
        for i in range(8):
            for g in range(2):
                lead = c.leader_hint(g)
                for j in range(12):
                    c.submit(g, lead, b"g%d-%d-%d" % (g, i, j))
            outs.append(c.step_burst())
        for _ in range(4):
            outs.append(c.step())
        return c, outs

    cb, ob = drive(False)
    cs, os_ = drive(True)
    assert cs.scan_dispatches > 0
    for k, (a, b) in enumerate(zip(ob, os_)):
        for key in RES_CMP:
            if key in a:
                assert np.array_equal(a[key], b[key]), (k, key)
    for g in range(2):
        for r in range(2):
            assert cb.replayed[g][r] == cs.replayed[g][r], (g, r)
            assert (list(cb.frames[g][r])
                    == list(cs.frames[g][r])), (g, r)
    assert np.array_equal(cb.applied, cs.applied)


# ---------------------------------------------------------------------------
# driver-level identity (recorded workload through the real run loop)
# ---------------------------------------------------------------------------

def _drive_driver(scan: bool):
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, pipeline=0, scan=scan)
    d.cluster.run_until_elected(0)
    d.step()
    assert d.leader() == 0
    handler = d._make_handler(0)
    conns = [(0 << 24) | 11, (0 << 24) | 12]
    for conn in conns:
        st = handler(2, conn, b"")
        assert not isinstance(st, int) or st == 0
    evs = []
    for i in range(160):
        ev = handler(3, conns[i % 2], b"w%03d" % i)
        assert not isinstance(ev, int), (i, ev)
        evs.append(ev)
    d.run(period=0.001)
    for i, ev in enumerate(evs):
        assert ev.done.wait(30), f"ack {i} never released"
    time.sleep(0.1)
    d.stop()
    assert d.loop_error is None
    stream = [e for e in d.cluster.replayed[0]]
    statuses = [ev.status for ev in evs]
    return d, stream, statuses


def test_driver_scan_commit_and_ack_stream_identical():
    db, stream_b, st_b = _drive_driver(False)
    ds, stream_s, st_s = _drive_driver(True)
    assert ds.cluster.scan_dispatches > 0, (
        "the scan driver never engaged the scan tier")
    assert db.cluster.scan_dispatches == 0
    assert st_b == [0] * 160
    assert st_s == st_b
    assert stream_s == stream_b
    payloads = [p for (_t, _c, _r, p) in stream_s
                if p.startswith(b"w")]
    assert payloads == [b"w%03d" % i for i in range(160)]


# ---------------------------------------------------------------------------
# chaos: a NemesisRunner schedule drives the scan tier
# ---------------------------------------------------------------------------

def _chaos_verdict(seed=5):
    from rdma_paxos_tpu.chaos.runner import NemesisRunner
    r = NemesisRunner(steps=80, seed=seed, scan=True,
                      fault_kinds=("crash", "partition", "drop"))
    # the schedule must actually exercise the drain-to-serial path
    assert any(ev["op"] == "crash" for ev in r.schedule.events), (
        "seed produced no crash — pick another")
    v = r.run()
    return r, v


def test_chaos_scan_leader_crash_drains_to_serial():
    r, v = _chaos_verdict()
    assert v["ok"] is True, v
    assert v["invariant_violations"] == []
    assert v["linearizability"]["ok"] is True
    assert v["linearizability"]["violations"] == []
    assert r.cluster.scan_dispatches > 0, (
        "the chaos run never dispatched through the scan tier")
    # determinism: the same seed yields the identical verdict
    _r2, v2 = _chaos_verdict()
    for key in ("ok", "invariant_violations", "linearizability",
                "schedule_events", "steps"):
        assert v[key] == v2[key], key


def test_runner_rejects_scan_with_pipeline():
    from rdma_paxos_tpu.chaos.runner import NemesisRunner
    with pytest.raises(ValueError):
        NemesisRunner(steps=10, scan=True, pipeline=2)
