"""Coordinated i32-offset rollover (LogConfig.rebase_threshold).

All log offsets are i32 entry indices — ~13 minutes of headroom at the
benched multi-M ops/s. The runtime rolls over BEFORE the ceiling by a
coordinated rebase: every offset on every replica (and the host apply
cursors) drops by the minimum head, invisibly to clients. The reference
is structurally immune via u64 byte offsets (dare_log.h:77-103); we
renumber instead of widening so offset arithmetic stays i32 on the VPU.

These tests shrink the threshold to a few hundred entries so ordinary
traffic crosses the boundary repeatedly:

* clients keep committing across rollovers, replay streams stay exact;
* a snapshot rejoin lands between rollovers and converges through more;
* a fuzzed schedule (partitions, elections) spans the boundary with all
  safety invariants restated in ABSOLUTE indices (offset + total rebase);
* the shard_map (spmd) path rebases the sharded state identically.
"""

import random

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.snapshot import install_snapshot, take_snapshot
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8,
                rebase_threshold=300)


def drain(c, lead, payloads, per_wave=8):
    """Submit payloads on the leader and step until all committed."""
    i = 0
    while i < len(payloads) or c.pending[lead]:
        for _ in range(per_wave):
            if i < len(payloads):
                c.submit(lead, payloads[i])
                i += 1
        c.step()
    for _ in range(3):
        c.step()


def test_commits_continue_across_rebase():
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    payloads = [b"w%05d" % i for i in range(900)]
    drain(c, 0, payloads)
    assert c.rebases >= 1, "traffic never crossed the boundary"
    # offsets rolled back under the threshold and stay ordered
    assert int(c.last["end"].max()) < CFG.rebase_threshold
    for r in range(3):
        assert (c.last["head"][r] <= c.last["apply"][r]
                <= c.last["commit"][r] <= c.last["end"][r])
    # the replay stream is EXACT on every replica: every payload, once,
    # in order — a rollover lost or duplicated nothing
    for r in range(3):
        got = [p for (_, _, _, p) in c.replayed[r]]
        assert got == payloads, f"replica {r} stream diverged"
    # and the cluster still serves
    c.submit(0, b"after-rollover")
    for _ in range(3):
        c.step()
    assert all(c.replayed[r][-1][3] == b"after-rollover" for r in range(3))


def test_snapshot_rejoin_between_rebases():
    c = SimCluster(CFG, 3)
    c.run_until_elected(0)
    first = [b"a%05d" % i for i in range(400)]
    drain(c, 0, first)
    assert c.rebases >= 1
    # partition replica 2 away and scroll the ring past its reach
    c.partition([[0, 1], [2]])
    second = [b"b%05d" % i for i in range(120)]
    drain(c, 0, second)
    assert int(c.last["head"][0]) > int(c.last["end"][2])

    # rejoin via snapshot WHILE offsets are post-rollover values
    snap = take_snapshot(c.state, donor=1,
                         index=int(c.applied[1]))
    c.state = install_snapshot(c.state, 2, snap)
    c.applied[2] = snap.index
    c.replayed[2] = list(c.replayed[1][:])   # host restored event blob
    c.heal()
    for _ in range(6):
        c.step()
    assert int(c.last["end"][2]) == int(c.last["end"][0])

    # more traffic forces MORE rollovers with the rejoined member present
    third = [b"c%05d" % i for i in range(600)]
    drain(c, 0, third)
    assert c.rebases >= 2
    want = first + second + third
    for r in range(3):
        got = [p for (_, _, _, p) in c.replayed[r]]
        assert got == want, f"replica {r} stream diverged after rejoin"


def test_fuzz_schedule_spans_rebase_boundary():
    """Randomized partitions/elections/traffic across rollovers; the
    fuzzer's invariants restated in ABSOLUTE indices (offset +
    cumulative rebase) must keep holding."""
    rng = random.Random(7)
    cfg = LogConfig(n_slots=64, slot_bytes=32, window_slots=16,
                    batch_slots=8, rebase_threshold=100)
    R = 3
    c = SimCluster(cfg, R)
    prev_commit_abs = np.zeros(R, np.int64)
    seen_terms = {}
    payload_n = 0
    for step_i in range(400):
        action = rng.random()
        if action < 0.10:
            c.partition([[0, 1], [2]] if rng.random() < 0.5
                        else [[0, 2], [1]])
        elif action < 0.25:
            c.heal()
        timeouts = [r for r in range(R) if rng.random() < 0.06]
        for r in range(R):
            if rng.random() < 0.7:
                payload_n += 1
                c.submit(r, b"p%05d" % payload_n)
        res = c.step(timeouts=timeouts)
        base = c.rebased_total
        for r in range(R):
            # I2 (absolute): commit never regresses
            assert res["commit"][r] + base >= prev_commit_abs[r], \
                (step_i, r)
            prev_commit_abs[r] = res["commit"][r] + base
            # I5: offset chain survives rollovers
            assert (res["head"][r] <= res["apply"][r]
                    <= res["commit"][r] <= res["end"][r]), (step_i, r)
            # I4: single leader per term
            if res["role"][r] == int(Role.LEADER):
                t = int(res["term"][r])
                assert seen_terms.setdefault(t, r) == r, (step_i, t)
    assert c.rebases >= 1, "schedule never crossed the boundary"
    c.heal()
    for _ in range(8):
        c.step()
    streams = [[tuple(e) for e in c.replayed[r]] for r in range(R)]
    longest = max(streams, key=len)
    for r, s in enumerate(streams):
        assert s == longest[:len(s)], r


def test_spmd_rebase_on_sharded_state():
    """The rollover program is elementwise, so it must apply cleanly to
    a shard_map-sharded state on the virtual device mesh."""
    c = SimCluster(CFG, 3, mode="spmd")
    c.run_until_elected(0)
    payloads = [b"s%05d" % i for i in range(700)]
    drain(c, 0, payloads)
    assert c.rebases >= 1
    assert int(c.last["end"].max()) < CFG.rebase_threshold
    for r in range(3):
        got = [p for (_, _, _, p) in c.replayed[r]]
        assert got == payloads, f"replica {r} stream diverged"
