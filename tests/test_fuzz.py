"""Randomized-schedule safety fuzzer — seeded model-checking-lite.

The reference has no race detection or fault injection (SURVEY.md §5);
its safety rests on design comments. Here, every step of a seeded random
schedule (random partitions, heals, election timeouts, client
submissions) checks the core safety invariants of the protocol — the
I1–I5 definitions live in ``rdma_paxos_tpu.chaos.invariants`` (shared
with the nemesis runner, so the fuzzer and the chaos harness can never
drift apart):

  I1 (committed-prefix agreement): all replicas agree on entries below
      their commit indices — byte-for-byte identical replay streams.
  I2 (commit monotonicity): no replica's commit index ever regresses.
  I3 (durability): once ANY replica commits index k, the entries below k
      never change on any replica that subsequently commits past k.
  I4 (single leader per term): two replicas never claim leadership in
      the same term.
  I5 (invariant chain): head <= apply <= commit <= end on every replica.

On any violation the fuzzer dumps a reproducer artifact (seed, the
recorded action schedule, the obs trace ring, metrics) and puts its
path in the assertion message — a failing CI line is replayable, not
just a (seed, step, replica) tuple.
"""

import random

import pytest

from rdma_paxos_tpu.chaos.artifact import load_reproducer, write_reproducer
from rdma_paxos_tpu.chaos.invariants import (
    InvariantChecker, InvariantViolation)
from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def random_partition(rng, R):
    ids = list(range(R))
    rng.shuffle(ids)
    cut = rng.randrange(1, R)
    return [ids[:cut], ids[cut:]]


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_random_schedule_preserves_safety(seed):
    _fuzz_schedule(seed, random.Random(seed).choice([3, 5]))


@pytest.mark.parametrize("R", [9, 11, 13])
def test_random_schedule_max_group_sizes(R):
    """The reference supports 1..13 replicas (MAX_SERVER_COUNT,
    dare.h:26); run the same safety fuzz at its maximum group sizes —
    the quorum kernel pads to 128 lanes, so this exercises test
    coverage, not new code paths."""
    _fuzz_schedule(100 + R, R)


def _dump(seed, R, schedule, exc: InvariantViolation) -> str:
    """Reproducer artifact for a failed fuzz run: the recorded action
    schedule (evidence) + trace ring + metrics. A fuzz run is fully
    determined by ``(seed, R)``, so the artifact replays with
    :func:`replay_fuzz_artifact` (NOT ``NemesisRunner.replay`` — the
    recorded ``op="step"`` actions are the fuzzer's own vocabulary,
    not FaultSchedule ops)."""
    return write_reproducer(
        seed=seed, schedule=schedule,
        reason=f"fuzz invariant violation: {exc.invariant}",
        config=dict(harness="fuzz", seed=seed, n_replicas=R,
                    log=dict(n_slots=CFG.n_slots,
                             slot_bytes=CFG.slot_bytes,
                             window_slots=CFG.window_slots,
                             batch_slots=CFG.batch_slots)),
        violation=exc.as_dict())


def replay_fuzz_artifact(path: str) -> None:
    """Re-run the failing fuzz schedule from a reproducer artifact.
    The run is deterministic in (seed, n_replicas), so this reproduces
    the identical schedule and re-raises the identical violation."""
    doc = load_reproducer(path)
    _fuzz_schedule(doc["config"]["seed"], doc["config"]["n_replicas"])


def test_fuzz_reproducer_artifact_replays(monkeypatch, tmp_path):
    """The artifact a failing fuzz run dumps must actually replay: it
    carries (seed, n_replicas) and replay_fuzz_artifact re-enters the
    deterministic schedule with exactly those parameters."""
    import os
    import tests.test_fuzz as tf
    exc = InvariantViolation("I5", "synthetic", replica=0, step=3)
    path = _dump(4, 3, [dict(step=0, op="heal")], exc)
    try:
        calls = []
        monkeypatch.setattr(tf, "_fuzz_schedule",
                            lambda s, r: calls.append((s, r)))
        replay_fuzz_artifact(path)
        assert calls == [(4, 3)]
    finally:
        os.unlink(path)


def _fuzz_schedule(seed, R):
    rng = random.Random(seed)
    c = SimCluster(CFG, R)
    inv = InvariantChecker(R)
    payload_n = 0
    schedule = []       # recorded actions -> the reproducer artifact

    for step_i in range(120):
        action = rng.random()
        if action < 0.15:
            groups = random_partition(rng, R)
            schedule.append(dict(step=step_i, op="partition",
                                 groups=groups))
            c.partition(groups)
        elif action < 0.30:
            schedule.append(dict(step=step_i, op="heal"))
            c.heal()
        timeouts = [r for r in range(R) if rng.random() < 0.08]
        submitted = []
        for r in range(R):
            if rng.random() < 0.5:
                payload_n += 1
                c.submit(r, b"p%05d" % payload_n)
                submitted.append(r)
        if timeouts or submitted:
            schedule.append(dict(step=step_i, op="step",
                                 timeouts=timeouts,
                                 submitted=submitted))
        res = c.step(timeouts=timeouts)

        # I2 + I4 + I5, shared implementation (chaos.invariants)
        try:
            inv.check_step(res, step=step_i,
                           rebased_total=c.rebased_total)
        except InvariantViolation as exc:
            raise AssertionError(
                f"{exc} [seed={seed} R={R}; reproducer: "
                f"{_dump(seed, R, schedule, exc)}]") from exc

    c.heal()
    schedule.append(dict(step=120, op="heal"))
    for _ in range(6):
        res = c.step()

    # I1 + I3: all replicas' replay streams agree on the common prefix,
    # and every stream is a prefix of the longest one
    try:
        inv.check_convergence(c.replayed)
    except InvariantViolation as exc:
        raise AssertionError(
            f"{exc} [seed={seed} R={R}; reproducer: "
            f"{_dump(seed, R, schedule, exc)}]") from exc

    # liveness smoke: after healing, the cluster still elects and commits
    # (rotating candidacies, as a real driver's randomized timers would —
    # a stale-logged candidate loses and a fresh one eventually stands)
    lead = -1
    for attempt in range(4 * R):
        res = c.step(timeouts=[attempt % R])
        res = c.step()
        leads = [r for r in range(R)
                 if res["role"][r] == int(Role.LEADER)]
        if len(leads) == 1:
            lead = leads[0]
            break
    assert lead >= 0, seed
    c.submit(lead, b"final")
    for _ in range(3):
        res = c.step()
    assert any(p == b"final" for (_, _, _, p) in c.replayed[lead])
