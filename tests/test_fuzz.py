"""Randomized-schedule safety fuzzer — seeded model-checking-lite.

The reference has no race detection or fault injection (SURVEY.md §5);
its safety rests on design comments. Here, every step of a seeded random
schedule (random partitions, heals, election timeouts, client
submissions) checks the core safety invariants of the protocol:

  I1 (committed-prefix agreement): all replicas agree on entries below
      their commit indices — byte-for-byte identical replay streams.
  I2 (commit monotonicity): no replica's commit index ever regresses.
  I3 (durability): once ANY replica commits index k, the entries below k
      never change on any replica that subsequently commits past k.
  I4 (single leader per term): two replicas never claim leadership in
      the same term.
  I5 (invariant chain): head <= apply <= commit <= end on every replica.
"""

import random

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.state import Role
from rdma_paxos_tpu.runtime.sim import SimCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)


def random_partition(rng, R):
    ids = list(range(R))
    rng.shuffle(ids)
    cut = rng.randrange(1, R)
    return [ids[:cut], ids[cut:]]


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_random_schedule_preserves_safety(seed):
    _fuzz_schedule(seed, random.Random(seed).choice([3, 5]))


@pytest.mark.parametrize("R", [9, 11, 13])
def test_random_schedule_max_group_sizes(R):
    """The reference supports 1..13 replicas (MAX_SERVER_COUNT,
    dare.h:26); run the same safety fuzz at its maximum group sizes —
    the quorum kernel pads to 128 lanes, so this exercises test
    coverage, not new code paths."""
    _fuzz_schedule(100 + R, R)


def _fuzz_schedule(seed, R):
    rng = random.Random(seed)
    c = SimCluster(CFG, R)
    prev_commit = np.zeros(R, np.int64)
    seen_terms = {}          # term -> leader id (I4)
    durable = {}             # index -> payload bytes (I3 witness)
    payload_n = 0

    for step_i in range(120):
        action = rng.random()
        if action < 0.15:
            c.partition(random_partition(rng, R))
        elif action < 0.30:
            c.heal()
        timeouts = [r for r in range(R) if rng.random() < 0.08]
        for r in range(R):
            if rng.random() < 0.5:
                payload_n += 1
                c.submit(r, b"p%05d" % payload_n)
        res = c.step(timeouts=timeouts)

        # I2: commit monotone
        for r in range(R):
            assert res["commit"][r] >= prev_commit[r], (seed, step_i, r)
            prev_commit[r] = res["commit"][r]
        # I4: single leader per term
        for r in range(R):
            if res["role"][r] == int(Role.LEADER):
                t = int(res["term"][r])
                assert seen_terms.setdefault(t, r) == r, (seed, step_i, t)
        # I5: offset chain
        for r in range(R):
            assert (res["head"][r] <= res["apply"][r]
                    <= res["commit"][r] <= res["end"][r]), (seed, step_i, r)

    c.heal()
    for _ in range(6):
        res = c.step()

    # I1 + I3: all replicas' replay streams agree on the common prefix,
    # and every stream is a prefix of the longest one
    streams = [[(t, conn, req, p) for (t, conn, req, p) in c.replayed[r]]
               for r in range(R)]
    longest = max(streams, key=len)
    for r, s in enumerate(streams):
        assert s == longest[:len(s)], (seed, r)

    # liveness smoke: after healing, the cluster still elects and commits
    # (rotating candidacies, as a real driver's randomized timers would —
    # a stale-logged candidate loses and a fresh one eventually stands)
    lead = -1
    for attempt in range(4 * R):
        res = c.step(timeouts=[attempt % R])
        res = c.step()
        leads = [r for r in range(R)
                 if res["role"][r] == int(Role.LEADER)]
        if len(leads) == 1:
            lead = leads[0]
            break
    assert lead >= 0, seed
    c.submit(lead, b"final")
    for _ in range(3):
        res = c.step()
    assert any(p == b"final" for (_, _, _, p) in c.replayed[lead])
