"""Device telemetry (rdma_paxos_tpu.obs.device): the on-device
protocol-counter vector, its host ingestion, the telemetry-backed
alert rules, the bounded profiler capture + merged Perfetto timeline,
and the per-variant compiled-program cost reports. The contracts:

* the counter-vector column layout in ``consensus/step.py`` (T_*) and
  ``obs/device.py`` (NAMES) are pinned against each other — step.py
  must never import obs, so the mirror is enforced here;
* ``telemetry=False`` compiled-step cache keys (and outputs) are
  bit-identical to the pre-telemetry world — the telemetry variants
  carry a distinct ``"telemetry"`` marker (the ``fence=``/``audit=``
  discipline);
* counter EXACTNESS on all three engines: a scripted election +
  traffic + fused burst + partition produces asserted exact values on
  ``SimCluster``, the vmap ``ShardedCluster``, and the spmd mesh
  engine (whose per-shard vectors survive the ``shard_map`` gather —
  mesh ≡ vmap telemetry parity);
* the registry gains ``device_*{replica=,group=}`` series at
  ``finish()`` time (the readback thread under the pipelined driver);
* ``default_rules`` fires ``election_storm`` (counter_rate, page) and
  ``log_headroom_low`` (gauge_cmp, warn) off the device series, and
  stays silent when telemetry is off (the series don't exist);
* the static jit-safety scan extends to ``obs/device.py``:
  profiler/registry symbols stay unreachable from compiled code;
* ``StepPhaseProfiler``/``phase_accumulate`` suppress zero-sample
  phases (no dead ``device_sync`` columns with ``fence=`` off) and the
  opt-in event ring feeds the host-phase track;
* ``ProfilerSession`` captures a bounded device trace whose events
  merge with span dumps and host phases into ONE Perfetto timeline on
  the shared clock anchors;
* ``program_report`` emits per-STEP_CACHE-variant flops / bytes /
  memory for the step and burst programs.
"""

import json
import time
import types

import numpy as np
import pytest

from rdma_paxos_tpu.config import LogConfig, TimeoutConfig
from rdma_paxos_tpu.consensus import step as step_mod
from rdma_paxos_tpu.obs import Observability
from rdma_paxos_tpu.obs import device as device_mod
from rdma_paxos_tpu.obs.alerts import AlertEngine, default_rules
from rdma_paxos_tpu.obs.metrics import MetricsRegistry
from rdma_paxos_tpu.obs.spans import SpanRecorder, StepPhaseProfiler
from rdma_paxos_tpu.runtime.driver import ClusterDriver
from rdma_paxos_tpu.runtime.sim import STEP_CACHE, SimCluster
from rdma_paxos_tpu.shard.cluster import ShardedCluster

CFG = LogConfig(n_slots=64, slot_bytes=32, window_slots=16, batch_slots=8)
TO = TimeoutConfig(elec_timeout_low=1e9, elec_timeout_high=2e9)  # manual

IDX = device_mod.INDEX


# ---------------------------------------------------------------------------
# layout mirror: step.py T_* columns == obs/device.py NAMES
# ---------------------------------------------------------------------------

def test_layout_matches_step_columns():
    assert step_mod.T_N == device_mod.WIDTH
    expected = {
        "elections_started": step_mod.T_ELECTIONS,
        "votes_granted": step_mod.T_VOTES_GRANTED,
        "votes_denied": step_mod.T_VOTES_DENIED,
        "accepted_entries": step_mod.T_ACCEPTED,
        "committed_entries": step_mod.T_COMMITTED,
        "links_unheard": step_mod.T_UNHEARD,
        "quorum_width": step_mod.T_QUORUM_W,
        "log_headroom": step_mod.T_HEADROOM,
    }
    assert expected == IDX
    # counters come first, gauges last — the reduce/accumulate split
    assert device_mod.COUNTERS + device_mod.GAUGES == device_mod.NAMES
    assert set(device_mod.GAUGES) == {"quorum_width", "log_headroom"}


# ---------------------------------------------------------------------------
# cache-key + output bit-identity guard for telemetry=False
# ---------------------------------------------------------------------------

def test_telemetry_off_cache_keys_bit_identical():
    # a geometry no other test uses: this guard reasons about which
    # keys THIS test's clusters add to the shared cache
    cfg = LogConfig(n_slots=32, slot_bytes=64, window_slots=8,
                    batch_slots=4)
    plain = SimCluster(cfg, 3)
    plain.run_until_elected(0)
    plain.submit(0, b"x")
    plain.step()
    keys_before = set(STEP_CACHE)

    tel = SimCluster(cfg, 3, telemetry=True)
    tel.run_until_elected(0)
    tel.submit(0, b"y")
    tel.step()
    added = set(STEP_CACHE) - keys_before
    assert added and all("telemetry" in k for k in added), (
        "telemetry variants must carry the 'telemetry' cache-key "
        "marker")

    # a fresh telemetry=False cluster adds NOTHING: default keys (and
    # therefore default programs) are bit-identical to the
    # pre-telemetry world
    after = set(STEP_CACHE)
    plain2 = SimCluster(cfg, 3)
    plain2.run_until_elected(0)
    plain2.submit(0, b"z")
    plain2.step()
    assert set(STEP_CACHE) == after


def test_telemetry_off_outputs_bit_identical():
    a = SimCluster(CFG, 3)
    b = SimCluster(CFG, 3, telemetry=True)
    for c in (a, b):
        c.run_until_elected(0)
        for i in range(4):
            c.submit(0, b"v%d" % i)
        for _ in range(3):
            c.step()
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(a.last[k], b.last[k]), k
    assert "telemetry" not in a.last and "telemetry" in b.last
    assert a.device_counters is None
    assert b.device_counters.shape == (3, device_mod.WIDTH)


# ---------------------------------------------------------------------------
# counter exactness: scripted election + traffic + burst + partition
# ---------------------------------------------------------------------------

def _assert_script_counters(dc, last, rebased, *, n_slots):
    """The exact expected counters after _run_script (engine-neutral:
    ``dc`` is [R, WIDTH], ``last``/``rebased`` that group's view)."""
    # exactly ONE election: candidate 0 started it, 1 and 2 granted
    assert dc[:, IDX["elections_started"]].tolist() == [1, 0, 0]
    assert dc[:, IDX["votes_granted"]].tolist() == [0, 1, 1]
    assert dc[:, IDX["votes_denied"]].tolist() == [0, 0, 0]
    # appends land only on the leader: 5 singles + 20 via one burst
    assert dc[:, IDX["accepted_entries"]].tolist() == [25, 0, 0]
    # commit-advance counters == the committed prefix, per replica
    for r in range(3):
        assert dc[r, IDX["committed_entries"]] == (
            int(last["commit"][r]) + rebased)
    # partition [[0,1],[2]]: 2 steps × (1,1,2) masked links
    assert dc[:, IDX["links_unheard"]].tolist() == [2, 2, 4]
    # under the partition the leader's window is acked by {0,1} only
    assert dc[0, IDX["quorum_width"]] == 2
    assert dc[1, IDX["quorum_width"]] == 0
    # headroom gauge is device truth: free slots after the last step
    for r in range(3):
        assert dc[r, IDX["log_headroom"]] == (
            (n_slots - 1)
            - (int(last["end"][r]) - int(last["head"][r])))


def test_sim_counter_exactness():
    c = SimCluster(CFG, 3, telemetry=True)
    c.run_until_elected(0)
    for i in range(5):
        c.submit(0, b"v%d" % i)
    for _ in range(3):
        c.step()
    for i in range(20):                  # > 2 batches -> fused burst
        c.submit(0, b"b%d" % i)
    c.step_burst()
    c.partition([[0, 1], [2]])
    c.step()
    c.step()
    _assert_script_counters(c.device_counters, c.last, c.rebased_total,
                            n_slots=CFG.n_slots)
    # deterministic same-script counters (the acceptance contract)
    c2 = SimCluster(CFG, 3, telemetry=True)
    c2.run_until_elected(0)
    for i in range(5):
        c2.submit(0, b"v%d" % i)
    for _ in range(3):
        c2.step()
    for i in range(20):
        c2.submit(0, b"b%d" % i)
    c2.step_burst()
    c2.partition([[0, 1], [2]])
    c2.step()
    c2.step()
    assert np.array_equal(c.device_counters, c2.device_counters)


def _run_sharded_script(sc):
    """The sim script on group 0 of a 2-group cluster; group 1 takes a
    little traffic of its own (isolation witness)."""
    sc.run_until_elected(0, 0)
    sc.run_until_elected(1, 0)
    for i in range(5):
        sc.submit(0, 0, b"v%d" % i)
    sc.submit(1, 0, b"w")
    for _ in range(3):
        sc.step()
    for i in range(20):
        sc.submit(0, 0, b"b%d" % i)
    sc.step_burst()
    sc.partition(0, [[0, 1], [2]])
    sc.step()
    sc.step()


def test_sharded_counter_exactness_and_group_isolation():
    sc = ShardedCluster(CFG, 3, 2, telemetry=True)
    _run_sharded_script(sc)
    dc = sc.device_counters
    # group 0 matches the scripted expectations exactly (group 1's
    # election rides the same dispatches but is isolated per group)
    last0 = {k: sc.last[k][0] for k in ("commit", "end", "head")}
    _assert_script_counters(dc[0], last0, int(sc.rebased_total[0]),
                            n_slots=CFG.n_slots)
    # fault isolation, from device truth alone: group 1 never saw a
    # masked link, and its own election/commit counters are its own
    assert dc[1, :, IDX["links_unheard"]].tolist() == [0, 0, 0]
    assert dc[1, 0, IDX["elections_started"]] == 1
    assert dc[1, 0, IDX["accepted_entries"]] == 1
    for r in range(3):
        assert dc[1, r, IDX["committed_entries"]] == (
            int(sc.last["commit"][1, r]) + int(sc.rebased_total[1]))


def test_mesh_vs_vmap_telemetry_parity():
    """The spmd mesh engine's counter vectors survive the shard_map
    (per-shard gather): bit-identical to the vmap engine on the same
    recorded workload — including the partition + failover steps."""
    vm = ShardedCluster(CFG, 3, 2, telemetry=True)
    ms = ShardedCluster(CFG, 3, 2, mesh=(2, 3), telemetry=True)
    for sc in (vm, ms):
        _run_sharded_script(sc)
    assert np.array_equal(vm.device_counters, ms.device_counters)
    assert np.array_equal(np.asarray(vm.last["telemetry"]),
                          np.asarray(ms.last["telemetry"]))


# ---------------------------------------------------------------------------
# registry export (finish()-side — the readback thread under pipelining)
# ---------------------------------------------------------------------------

def test_registry_gains_device_series():
    reg = MetricsRegistry()
    c = SimCluster(CFG, 3, telemetry=True)
    c.obs = Observability(metrics_registry=reg)
    c.run_until_elected(0)
    for i in range(4):
        c.submit(0, b"r%d" % i)
    for _ in range(3):
        c.step()
    assert reg.get("device_elections_started_total", replica=0) == 1
    assert reg.get("device_votes_granted_total", replica=1) == 1
    assert reg.get("device_accepted_entries_total", replica=0) == 4
    assert reg.get("device_committed_entries_total", replica=0) == \
        c.device_counters[0, IDX["committed_entries"]]
    assert reg.get("device_log_headroom", replica=2) == \
        c.device_counters[2, IDX["log_headroom"]]
    # sharded series carry the group label
    reg2 = MetricsRegistry()
    sc = ShardedCluster(CFG, 3, 2, telemetry=True)
    sc.obs = Observability(metrics_registry=reg2)
    sc.place_leaders()
    sc.submit(1, sc.leader_hint(1), b"g1")
    sc.step()
    sc.step()
    assert reg2.get("device_accepted_entries_total",
                    replica=sc.leader_hint(1), group=1) == 1
    assert reg2.get("device_log_headroom", replica=0, group=0) > 0


# ---------------------------------------------------------------------------
# telemetry-backed default alert rules
# ---------------------------------------------------------------------------

def test_telemetry_alert_rules_fire_and_resolve():
    reg = MetricsRegistry()
    eng = AlertEngine(reg, rules=default_rules(), trace=None)
    # telemetry off -> the device series don't exist -> rules silent
    assert eng.evaluate() == {"fired": [], "resolved": []}

    # election_storm: counter_rate (page) with for_evals=2 — a delta
    # above the threshold between evaluations, twice in a row (the
    # silent first evaluate above established the zero baseline)
    reg.inc("device_elections_started_total", 10, replica=0)
    out = eng.evaluate()                  # delta 10 -> pending 1
    assert "election_storm" not in out["fired"]
    reg.inc("device_elections_started_total", 10, replica=1)
    out = eng.evaluate()                  # pending 2 -> fires
    assert "election_storm" in out["fired"]
    assert eng.severity("election_storm") == "page"
    out = eng.evaluate()                  # quiet -> resolves
    assert "election_storm" in out["resolved"]

    # log_headroom_low: gauge_cmp (warn) with agg=min across replicas
    reg.set("device_log_headroom", 100, replica=0)
    reg.set("device_log_headroom", 4, replica=1)
    out = eng.evaluate()
    assert "log_headroom_low" in out["fired"]
    assert eng.severity("log_headroom_low") == "warn"
    st = eng.state()["log_headroom_low"]
    assert st["value"] == 4
    reg.set("device_log_headroom", 100, replica=1)
    assert "log_headroom_low" in eng.evaluate()["resolved"]


# ---------------------------------------------------------------------------
# static jit-safety scan: obs/device.py symbols unreachable from
# compiled code
# ---------------------------------------------------------------------------

def test_jit_safety_scan_covers_device_module():
    """consensus/step.py, ops/*, and parallel/mesh.py run inside
    jit/shard_map: no obs.device symbol (ProfilerSession, registry
    ingest, jax.profiler) may be reachable there — the telemetry
    vector is pure jnp, produced blind and consumed host-side.
    Enforced by the graftlint ``jit-purity`` pass (the deduped
    ``SCAN_PATTERNS`` union carries this test's former inline list)."""
    from rdma_paxos_tpu.analysis import assert_jit_purity
    assert_jit_purity()


# ---------------------------------------------------------------------------
# satellite: zero-sample phase suppression + host-phase event ring
# ---------------------------------------------------------------------------

def test_phase_profiler_suppresses_zero_sample_phases():
    prof = StepPhaseProfiler()
    prof.start("host_encode")
    prof.stop("host_encode")
    # a dead accumulator row (what an empty fenced series used to
    # leave behind) must not surface in the printed breakdown or the
    # bench detail sums
    prof.acc["device_sync"] = (0, 0.0, 0.0)
    assert "device_sync" not in prof.report()
    assert "host_encode" in prof.report()
    assert set(prof.sums()) == {"host_encode"}
    assert prof.sums()["host_encode"]["n"] == 1


def test_phase_accumulate_suppresses_zero_delta_phases():
    from benchmarks.reporting import phase_accumulate, phase_snapshot
    prof = StepPhaseProfiler()
    fake = types.SimpleNamespace(_phase_prof=prof)
    prof.start("host_encode")
    prof.stop("host_encode")
    pre = phase_snapshot(fake)
    prof.start("apply")
    prof.stop("apply")
    agg: dict = {}
    phase_accumulate(fake, pre, agg)
    # host_encode did not advance in this window: no dead n=0 column
    assert set(agg) == {"apply"} and agg["apply"]["n"] == 1
    # a phase already in agg keeps accumulating even across a quiet
    # window (the fold stays additive)
    pre2 = phase_snapshot(fake)
    phase_accumulate(fake, pre2, agg)
    assert agg["apply"]["n"] == 1


def test_phase_profiler_event_ring():
    prof = StepPhaseProfiler()
    assert prof.events is None            # off by default: zero cost
    prof.enable_events(capacity=4)
    for _ in range(6):
        prof.start("quorum_wait")
        prof.stop("quorum_wait")
    assert len(prof.events) == 4          # bounded ring
    phase, t0, t1 = prof.events[-1]
    assert phase == "quorum_wait"
    assert t0 <= t1 <= time.monotonic()


# ---------------------------------------------------------------------------
# merged timeline (spans + host phases; device leg tested below)
# ---------------------------------------------------------------------------

def _scripted_span_dump():
    t = [0.0]

    def clock():
        t[0] += 0.001
        return round(t[0], 6)
    rec = SpanRecorder(sample_every=1, clock=clock)
    rec.begin(7, 1, 0)
    rec.stamp_append(7, 1, term=3, index=5, leader=0, replicas=(0,))
    rec.commit_advance(0, 6)
    rec.apply_advance(0, 6)
    rec.ack_release(0, 1)
    return rec.dump(anchor={"monotonic": 0.0, "wall": 100.0})


def test_merge_timeline_spans_and_phases_only():
    dump = _scripted_span_dump()
    anchor = {"monotonic": 0.0, "wall": 100.0}
    phases = [("host_encode", 0.0005, 0.0010),
              ("device_dispatch", 0.0010, 0.0030)]
    doc = device_mod.merge_timeline([dump], phase_events=phases,
                                    phase_anchor=anchor)
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert device_mod.HOST_PHASE_PID in pids
    assert 0 in pids                      # replica span track
    # ONE epoch: the earliest phase start (0.5 ms) precedes the first
    # span mark (1 ms) — both land on the same axis
    assert doc["otherData"]["t0_wall"] == pytest.approx(100.0005)
    ph = [e for e in doc["traceEvents"]
          if e["pid"] == device_mod.HOST_PHASE_PID and e["ph"] == "X"]
    assert len(ph) == 2
    assert ph[0]["ts"] == pytest.approx(0.0, abs=1.0)
    assert ph[1]["dur"] == pytest.approx(2000.0, abs=1.0)   # 2 ms
    assert doc["otherData"]["host_phase_events"] == 2
    assert doc["otherData"]["device_events"] == 0
    json.dumps(doc)


# ---------------------------------------------------------------------------
# ProfilerSession + driver integration + full merged timeline
# ---------------------------------------------------------------------------

def test_profiler_session_driver_capture_and_merged_timeline(tmp_path):
    d = ClusterDriver(CFG, 3, timeout_cfg=TO, telemetry=True)
    try:
        d.obs.spans.set_sample_every(1)
        d.runtimes[0].timer._deadline = 0.0
        d.step()
        assert d.leader() == 0
        d._phase_prof.enable_events()
        session = d.start_profile(seconds=120,
                                  log_dir=str(tmp_path / "prof"))
        assert session.active
        with pytest.raises(RuntimeError):
            d.start_profile()             # one capture at a time
        for i in range(3):
            # span birth normally happens at proxy intake; bare-engine
            # submits need it by hand for the merged-timeline check
            d.obs.spans.begin(7, i + 1, 0)
            d.cluster.submit(0, b"p%d" % i, conn=7, req_id=i + 1)
            d.step()
        d.stop_profile()
        assert not session.active
        assert session.trace_files, "no trace.json.gz captured"
        events = session.chrome_events()
        assert events, "captured trace contains no events"

        # ONE merged Perfetto document: spans + host phases + device
        doc = device_mod.merge_timeline(
            [d.obs.spans.dump()],
            phase_events=list(d._phase_prof.events),
            profiler=session)
        assert doc["otherData"]["device_events"] > 0
        assert doc["otherData"]["host_phase_events"] > 0
        assert doc["otherData"]["spans"] > 0
        pids = {e["pid"] for e in doc["traceEvents"]}
        assert device_mod.HOST_PHASE_PID in pids
        assert any(p >= device_mod.DEVICE_PID_BASE for p in pids)
        # all three layers share the epoch: every ts is finite + >= 0
        for e in doc["traceEvents"]:
            if "ts" in e:
                assert e["ts"] >= 0
        json.dumps(doc)

        # the device telemetry flowed during the same run
        assert d.obs.metrics.get("device_committed_entries_total",
                                 replica=0) > 0

        # alert-triggered capture: a page starts ONE bounded session
        d._profile_on_page = 30.0
        d.obs.metrics.inc("audit_divergence_total")
        d.evaluate_alerts()
        assert d.profile_session is not session
        assert d.profile_session.active
        d.stop_profile()
        # one capture per process: a second page never re-triggers
        d.evaluate_alerts()
        assert not d.profile_session.active
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# program cost reports
# ---------------------------------------------------------------------------

def test_program_report_variants_and_artifact(tmp_path):
    c = SimCluster(CFG, 3, telemetry=True)
    c.run_until_elected(0)
    rep = device_mod.write_program_report(
        str(tmp_path / "program_report.json"), c, tiers=(2,))
    assert [v["variant"] for v in rep["variants"]] == [
        "step/full", "step/stable", "burst/K=2"]
    for v in rep["variants"]:
        assert "error" not in v, v
        assert v["memory"]["peak_bytes"] > 0
        assert v.get("bytes_accessed", 0) > 0
    assert rep["telemetry"] is True and rep["n_groups"] == 1
    doc = json.load(open(rep["path"]))
    assert doc["kind"] == "program_report"
    assert len(doc["variants"]) == 3


def test_program_report_sharded_engine():
    sc = ShardedCluster(CFG, 3, 2)
    sc.place_leaders()
    rep = device_mod.program_report(sc)
    assert rep["n_groups"] == 2 and rep["engine"] == "sim"
    assert all("error" not in v for v in rep["variants"])


# ---------------------------------------------------------------------------
# satellite: bench overhead A/B (tiny smoke — the real row runs via
# `benchmarks/run_bench.py --telemetry`)
# ---------------------------------------------------------------------------

def test_measure_telemetry_overhead_smoke():
    from benchmarks.run_bench import measure_telemetry_overhead
    ab = measure_telemetry_overhead(cfg=CFG, steps=30, per_step=2,
                                    payload=16, warmup=3)
    assert ab["off"]["committed"] == ab["on"]["committed"] > 0
    assert "overhead_pct" in ab
    # the ON cluster's device counters carry the committed work
    assert ab["device_counters"]["committed_entries"][0] > 0
    assert ab["device_counters"]["elections_started"] == [1, 0, 0]
