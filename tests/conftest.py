"""Test harness config: run everything on a virtual 8-device CPU mesh.

The reference has NO automated tests (SURVEY.md §4) — validation was
end-to-end on a real InfiniBand cluster. Here the whole protocol (election,
replication, commit, pruning, reconfig, recovery) runs deterministically
in-process: N replicas = N virtual CPU devices (shard_map path) or one
vmapped axis (sim path).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

# The environment's sitecustomize may register an accelerator plugin and
# force jax_platforms; tests always run on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)


def jax_multiprocess_cpu() -> bool:
    """True when this jax/jaxlib can run CROSS-PROCESS collectives on
    the CPU backend (jax.distributed + gloo CPU collectives). jaxlib
    0.4.x CPU raises ``XlaRuntimeError: Multiprocess computations
    aren't implemented on the CPU backend`` the moment a sharded
    device_put crosses process boundaries — the multi-process
    deployment tests (multihost, elastic worker worlds) gate on this
    so an older-jax environment skips them instead of burning their
    full boot timeouts and failing."""
    try:
        ver = tuple(int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:
        return True
    return ver >= (0, 5)
