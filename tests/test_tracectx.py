"""obs/tracectx — ONE causal trace plane: acceptance properties.

* ``TraceContext`` is bounded, deterministic (``kind-N`` ids), and
  leaf-locked; open traces terminate (end / fail_open / eviction),
  never leak;
* ``RP_TRACE_SAMPLE`` overrides the span sampling default AND — via
  :func:`active_tracer` — silences the whole subsystem trace plane
  with the same switch;
* latency histograms keep a bounded, deterministic exemplar reservoir
  per bucket; ``/metrics`` renders OpenMetrics exemplar tails; an
  AlertEngine firing carries resolvable exemplar trace ids;
* the chaos schedule (a real topology split window + concurrent
  cross-group txns + a TOPOLOGY-aborted txn) yields a merged Perfetto
  timeline that is byte-deterministic per seed, with every span and
  trace closed and the aborted txn's blocking parent pointing at the
  transition-window trace;
* the blame report decomposes per-command latency into the
  ``BLAME_PHASES`` components and names the dominant phase per
  percentile; the ``obs`` CLI round-trips merge + blame over dump
  files;
* the trace plane is host-side only: STEP_CACHE keys and step
  outputs are bit-identical with full tracing on.
"""

import json
import time

import numpy as np

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.obs import AlertEngine, Observability
from rdma_paxos_tpu.obs import spans as spans_mod
from rdma_paxos_tpu.obs.__main__ import main as obs_main
from rdma_paxos_tpu.obs.console import _blame_state, assemble_bundle
from rdma_paxos_tpu.obs.export import render_prometheus
from rdma_paxos_tpu.obs.health import CLUSTER_HEALTH_FIELDS
from rdma_paxos_tpu.obs.metrics import (
    EXEMPLARS_PER_BUCKET, MetricsRegistry)
from rdma_paxos_tpu.obs.spans import SpanRecorder, span_trace_id
from rdma_paxos_tpu.obs.tracectx import (
    BLAME_PHASES, SUBSYS_PIDS, TraceContext, active_tracer, blame,
    blame_summary, format_blame, merge_timeline)
from rdma_paxos_tpu.runtime import reads as reads_mod
from rdma_paxos_tpu.runtime.sim import STEP_CACHE
from rdma_paxos_tpu.shard import ShardedCluster
from rdma_paxos_tpu.shard.kvs import ShardedKVS
from rdma_paxos_tpu.shard.router import RangeRule
from rdma_paxos_tpu.topology import attach_topology
from rdma_paxos_tpu.txn import attach_coordinator
from rdma_paxos_tpu.txn.chaos import keys_for_groups

CFG = LogConfig(n_slots=256, slot_bytes=128, window_slots=32,
                batch_slots=8)

# a fixed anchor makes two runs' dumps (and their merged timeline)
# byte-comparable: no wall clock leaks into the documents
ANCHOR = {"monotonic": 0.0, "wall": 1000.0}


def _scripted_clock(step_s: float = 0.001, start: float = 0.0):
    """Deterministic monotonic clock: start+0.001, start+0.002, ..."""
    t = [start]

    def clock():
        t[0] += step_s
        return round(t[0], 6)
    return clock


# ---------------------------------------------------------------------------
# TraceContext lifecycle
# ---------------------------------------------------------------------------

def test_trace_lifecycle_deterministic_ids_and_phases():
    tc = TraceContext(clock=_scripted_clock())
    a = tc.begin("txn", groups=[0, 1])
    b = tc.begin("txn")
    w = tc.begin("topology", direction="split")
    assert (a, b, w) == ("txn-0", "txn-1", "topology-0")
    assert tc.open_count == 3
    tc.phase(a, "lock_wait")
    tc.phase(a, "prepare")
    tc.phase(a, "prepare", once=True)            # deduped
    tc.link(a, 7, 3, 0)
    tc.annotate(a, reason="conflict")
    tc.set_parent(a, w)                          # late-bound parent
    tc.end(a, status="aborted")
    tc.end(b, status="committed")
    tc.end(w)
    assert tc.open_count == 0
    d = tc.get(a)
    assert d["status"] == "aborted" and d["parent"] == w
    assert [p for p, _ in d["phases"]] == ["lock_wait", "prepare"]
    assert d["links"] == [[7, 3, 0]]
    assert d["attrs"]["reason"] == "conflict"
    assert d["t1"] > d["t0"]
    c = tc.counts()
    assert c["open"] == 0 and c["done"] == 3 and c["dropped"] == 0
    assert c["by_kind"] == {"txn": 2, "topology": 1}
    # unknown/ended ids no-op, never raise
    tc.phase("nope-9", "x")
    tc.end(a)
    assert tc.get("nope-9") is None


def test_capacity_eviction_and_fail_open_never_leak():
    tc = TraceContext(capacity=2, clock=_scripted_clock())
    t0 = tc.begin("watch")
    tc.begin("watch")
    tc.begin("watch")                            # evicts the oldest
    assert tc.open_count == 2 and tc.dropped == 1
    assert tc.get(t0)["status"] == "evicted"
    assert tc.fail_open() == 2                   # driver-crash path
    assert tc.open_count == 0
    # bounded: the done deque holds `capacity` entries, so the evicted
    # record rotated out when the two failover closes landed
    statuses = {t["status"] for t in tc.dump()["traces"]}
    assert statuses == {"failover"}
    tc.reset()
    assert tc.counts() == dict(open=0, done=0, dropped=0, by_kind={})
    assert tc.begin("watch") == "watch-0"        # counters reset too


# ---------------------------------------------------------------------------
# RP_TRACE_SAMPLE: one switch for spans AND the subsystem trace plane
# ---------------------------------------------------------------------------

def test_rp_trace_sample_env_override(monkeypatch):
    monkeypatch.delenv(spans_mod.SAMPLE_ENV, raising=False)
    assert (spans_mod.default_sample_every()
            == spans_mod.DEFAULT_SAMPLE_EVERY)
    monkeypatch.setenv(spans_mod.SAMPLE_ENV, "7")
    assert spans_mod.default_sample_every() == 7
    # resolved at CONSTRUCTION, not import: a recorder built now sees it
    assert SpanRecorder().sample_every == 7
    monkeypatch.setenv(spans_mod.SAMPLE_ENV, "not-a-number")
    assert (spans_mod.default_sample_every()
            == spans_mod.DEFAULT_SAMPLE_EVERY)
    monkeypatch.setenv(spans_mod.SAMPLE_ENV, "-3")
    assert spans_mod.default_sample_every() == 0   # clamped = off
    monkeypatch.setenv(spans_mod.SAMPLE_ENV, "0")
    obs = Observability(span_recorder=SpanRecorder())
    assert not obs.spans.enabled
    # the SAME switch silences the subsystem trace plane
    assert active_tracer(obs) is None
    assert active_tracer(None) is None
    obs_on = Observability(span_recorder=SpanRecorder(sample_every=1))
    assert active_tracer(obs_on) is obs_on.tracectx


# ---------------------------------------------------------------------------
# exemplars: reservoir -> /metrics tail -> alert firing evidence
# ---------------------------------------------------------------------------

def test_exemplar_reservoir_is_bounded_and_deterministic():
    reg = MetricsRegistry()
    for i in range(10):
        reg.observe("commit_latency_seconds", 0.2,
                    exemplar=span_trace_id(0, i + 1))
    h = reg.snapshot()["histograms"]["commit_latency_seconds"]
    (res,) = h["exemplars"].values()
    assert len(res) == EXEMPLARS_PER_BUCKET     # bounded, one bucket
    # deterministic replacement (count-cycled slot, no RNG): a second
    # identical registry produces the identical reservoir
    reg2 = MetricsRegistry()
    for i in range(10):
        reg2.observe("commit_latency_seconds", 0.2,
                     exemplar=span_trace_id(0, i + 1))
    assert reg2.snapshot()["histograms"]["commit_latency_seconds"] \
        == h
    # exemplar-free histograms snapshot WITHOUT the key (golden-file
    # compatibility)
    reg3 = MetricsRegistry()
    reg3.observe("commit_latency_seconds", 0.2)
    assert "exemplars" not in \
        reg3.snapshot()["histograms"]["commit_latency_seconds"]


def test_openmetrics_exemplar_tail_rendering():
    reg = MetricsRegistry()
    reg.observe("commit_latency_seconds", 0.2,
                exemplar=span_trace_id(3, 9))
    text = render_prometheus(reg.snapshot())
    assert ' # {trace_id="c3/r9"} 0.2' in text
    # without exemplars the scrape is byte-identical to the classic
    # v0.0.4 form: no stray exemplar syntax anywhere
    reg2 = MetricsRegistry()
    reg2.observe("commit_latency_seconds", 0.2)
    assert "trace_id" not in render_prometheus(reg2.snapshot())


def test_alert_firing_carries_resolvable_exemplars():
    reg = MetricsRegistry()
    rule = dict(name="slow_commit", severity="warn",
                kind="hist_quantile", metric="commit_latency_seconds",
                q=0.5, op=">", threshold=0.01, for_evals=1)
    eng = AlertEngine(reg, rules=[rule])
    # the spans these exemplars resolve against
    rec = SpanRecorder(sample_every=1, clock=_scripted_clock())
    for i in range(3):
        rec.begin(0, i + 1, 0)
        rec.stamp_append(0, i + 1, term=1, index=i, leader=0,
                         replicas=(0,))
    rec.commit_advance(0, 3)
    rec.apply_advance(0, 3)
    for conn, req in rec.ack_release(0, 3):
        reg.observe("commit_latency_seconds", 0.9,
                    exemplar=span_trace_id(conn, req))
    out = eng.evaluate()
    assert "slow_commit" in out["fired"]
    st = eng.state()["slow_commit"]
    assert st["firing"] and st["exemplars"]
    # every attached exemplar RESOLVES to a span in the dump
    dump = rec.dump(anchor=ANCHOR)
    span_ids = {span_trace_id(s["conn"], s["req"])
                for s in dump["spans"]}
    assert set(st["exemplars"]) <= span_ids


# ---------------------------------------------------------------------------
# blame: per-command latency decomposition + dominant phase
# ---------------------------------------------------------------------------

def _synthetic_pair():
    """One fully-retired span plus a txn trace (large lock wait,
    linking the span) and a topology window overlapping it."""
    rec = SpanRecorder(sample_every=1, clock=_scripted_clock())
    rec.begin(7, 1, 0)                          # enqueue t=.001
    rec.stamp_append(7, 1, term=3, index=5, leader=0, replicas=(0, 1))
    rec.commit_advance(0, 6)
    rec.apply_advance(0, 6)
    rec.commit_advance(1, 6)
    rec.apply_advance(1, 6)
    rec.ack_release(0, 1)
    tc = TraceContext(clock=_scripted_clock())
    t = tc.begin("txn", ts=0.0)
    tc.phase(t, "lock_wait", ts=0.0005)
    tc.phase(t, "prepare", ts=0.0505)           # 50ms lock wait
    tc.link(t, 7, 1, 0)
    tc.end(t, status="committed", ts=0.06)
    w = tc.begin("topology", ts=0.0, direction="split")
    tc.phase(w, "freeze", ts=0.001)
    tc.phase(w, "cutover", ts=0.004)
    tc.end(w, ts=0.005)
    return rec, tc


def test_blame_decomposition_and_dominant_phase():
    rec, tc = _synthetic_pair()
    doc = blame([rec.dump(anchor=ANCHOR)], [tc.dump(anchor=ANCHOR)])
    assert doc["commands"] == 1
    assert set(doc["phases"]) <= set(BLAME_PHASES)
    # the pure-span segments, the linked txn lock wait, and the
    # freeze-window overlap all show up as components
    for ph in ("dispatch", "quorum", "apply", "ack", "txn_lock",
               "topology_freeze"):
        assert ph in doc["phases"], ph
    # the 50ms lock wait dominates every percentile of this 1-command
    # distribution — blame NAMES it
    for pname in ("p50", "p95", "p99"):
        pe = doc["percentiles"][pname]
        assert pe["dominant"] == "txn_lock"
        assert pe["latency_us"] > 50_000        # extent + lock wait
    txt = format_blame(doc)
    assert "dominated by txn_lock" in txt
    s = blame_summary(doc)
    assert s["p99"] == "txn_lock" and s["p99_us"] > 50_000
    assert blame_summary(dict(commands=0)) is None


def test_console_blame_column_and_health_field():
    assert "blame" in CLUSTER_HEALTH_FIELDS
    assert _blame_state({}) == "-"
    assert _blame_state({"blame": None}) == "-"
    assert _blame_state({"blame": {"p50": "quorum", "p95": "quorum",
                                   "p99": "apply",
                                   "p99_us": 1200.0}}) \
        == "p99:apply 1200us"


# ---------------------------------------------------------------------------
# the obs CLI: merge + blame over dump files; bundle gains perfetto
# ---------------------------------------------------------------------------

def test_cli_merge_and_blame_round_trip(tmp_path, capsys):
    rec, tc = _synthetic_pair()
    sp = tmp_path / "spans.json"
    tr = tmp_path / "traces.json"
    sp.write_text(json.dumps(rec.dump(anchor=ANCHOR)))
    tr.write_text(json.dumps(tc.dump(anchor=ANCHOR)))
    out = tmp_path / "merged.perfetto.json"
    assert obs_main(["merge", str(sp), str(tr), "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["otherData"]["traces"] == 2
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert SUBSYS_PIDS["txn"] in pids
    assert SUBSYS_PIDS["topology"] in pids
    capsys.readouterr()
    assert obs_main(["blame", str(sp), str(tr)]) == 0
    assert "dominated by txn_lock" in capsys.readouterr().out
    # --json emits the raw document
    assert obs_main(["blame", "--json", str(sp), str(tr)]) == 0
    assert json.loads(capsys.readouterr().out)["commands"] == 1


def test_bundle_gains_merged_perfetto_section(tmp_path):
    rec, tc = _synthetic_pair()
    (tmp_path / "spans.json").write_text(
        json.dumps(rec.dump(anchor=ANCHOR)))
    (tmp_path / "traces.json").write_text(
        json.dumps(tc.dump(anchor=ANCHOR)))
    bundle = assemble_bundle(reason="test", workdir=str(tmp_path))
    sec = bundle["sections"]
    assert sec["perfetto"]["otherData"]["traces"] == 2
    assert "perfetto" in bundle["manifest"]
    # and the CLI can read the BUNDLE itself (classification by shape)
    bp = tmp_path / "bundle.json"
    bp.write_text(json.dumps(bundle))
    out = tmp_path / "from_bundle.json"
    assert obs_main(["merge", str(bp), "-o", str(out)]) == 0
    assert json.loads(out.read_text())["otherData"]["traces"] == 2


# ---------------------------------------------------------------------------
# the chaos schedule: split window + concurrent txns, deterministic
# ---------------------------------------------------------------------------

def _traced_cluster():
    shard = ShardedCluster(CFG, 3, 2, txn=True)
    obs = Observability(
        span_recorder=SpanRecorder(sample_every=1,
                                   clock=_scripted_clock()),
        trace_context=TraceContext(clock=_scripted_clock()))
    shard.obs = obs
    kv = ShardedKVS(shard, cap=256)
    reads_mod.attach(shard)
    ctl = attach_topology(kv, obs=obs, cooldown_steps=4)
    attach_coordinator(kv)
    shard.place_leaders()
    return shard, kv, ctl, obs


def _run_window(shard, ctl, max_steps=300):
    for _ in range(max_steps):
        shard.step()
        ctl.drive()
        if not ctl.in_window():
            return
    raise AssertionError("transition window did not close: "
                         f"{ctl.status()}")


def _chaos_schedule():
    """Seeded schedule: a txn committing THROUGH an open split window,
    then a txn whose mapping moves out from under it mid-flight.
    Returns the merged timeline (sorted JSON) plus a summary."""
    shard, kv, ctl, obs = _traced_cluster()
    keys = keys_for_groups(kv.router, 4)
    h = kv.transact([("put", keys[0][3], b"w"),
                     ("put", keys[1][3], b"w")])
    for _ in range(6):
        if h.done:
            break
        shard.step()
    assert h.committed
    # a REAL split window over group 0's upper range, with a
    # cross-group txn riding through it
    hot = sorted(keys[0])
    assert ctl.propose_split(hot[len(hot) // 2], hot[-1] + b"\x00", 1)
    h2 = kv.transact([("put", keys[0][0], b"x"),
                      ("put", keys[1][1], b"y")])
    _run_window(shard, ctl)
    for _ in range(8):
        if h2.done:
            break
        shard.step()
    assert h2.done
    # the doomed txn: its key's group mapping moves while in flight
    keys2 = keys_for_groups(kv.router, 2)
    ka, kb = keys2[0][0], keys2[1][0]
    h3 = kv.transact([("put", ka, b"A"), ("put", kb, b"B")])
    kv.router.install_rule(RangeRule(ka, ka + b"\x00", 1))
    for _ in range(8):
        if h3.done:
            break
        shard.step()
    assert h3.done and not h3.committed
    assert h3.abort_reason == "topology"
    # the abort DECISION records land a couple of steps after the
    # handle resolves; their spans retire with them (still a fixed,
    # deterministic schedule — the sim flips the condition at the
    # same step every run)
    for _ in range(20):
        if (obs.spans.counts()["open"] == 0
                and obs.tracectx.open_count == 0):
            break
        shard.step()
    merged = merge_timeline([obs.spans.dump(anchor=ANCHOR)],
                            [obs.tracectx.dump(anchor=ANCHOR)])
    return (json.dumps(merged, sort_keys=True),
            dict(spans=obs.spans.counts(),
                 traces=obs.tracectx.counts(),
                 dump=obs.tracectx.dump(anchor=ANCHOR)))


def test_chaos_schedule_deterministic_closed_and_blamed():
    blob1, s1 = _chaos_schedule()
    # every span and every subsystem trace closed — no leaks, even
    # through the window and the TOPOLOGY abort
    assert s1["spans"]["open"] == 0
    assert s1["traces"]["open"] == 0
    assert s1["traces"]["by_kind"]["topology"] == 1
    assert s1["traces"]["by_kind"]["txn"] == 3
    by_id = {t["tid"]: t for t in s1["dump"]["traces"]}
    win = by_id["topology-0"]
    assert win["status"] == "done"
    phases = [p for p, _ in win["phases"]]
    for ph in ("freeze", "cutover"):
        assert ph in phases, ph
    # the TOPOLOGY-aborted txn names the transition window as its
    # blocking parent and carries the abort reason
    aborted = [t for t in s1["dump"]["traces"]
               if t["kind"] == "txn"
               and t["attrs"].get("reason") == "topology"]
    assert len(aborted) == 1
    assert aborted[0]["status"] == "aborted"
    assert aborted[0]["parent"] == "topology-0"
    assert [p for p, _ in aborted[0]["phases"]][-1] == "abort"
    # committed txns closed as committed, with their span links
    committed = [t for t in s1["dump"]["traces"]
                 if t["kind"] == "txn" and t["status"] == "committed"]
    assert committed and all(t["links"] for t in committed)
    # same seed, fresh cluster -> byte-identical merged timeline
    blob2, _ = _chaos_schedule()
    assert blob1 == blob2
    # and the merged doc carries both planes
    doc = json.loads(blob1)
    assert doc["otherData"]["traces"] == 4
    assert doc["otherData"]["spans"] > 0


def test_merged_timeline_includes_watch_deliveries():
    from rdma_paxos_tpu import streams as streams_mod
    shard, kv, ctl, obs = _traced_cluster()
    hub = streams_mod.attach(shard)
    try:
        keys = keys_for_groups(kv.router, 2)
        sub = hub.subscribe(0)
        for k in keys[0]:
            kv.put(k, b"V" + k, leader=shard.leader_hint(0))
        for _ in range(5):
            shard.step()
        assert hub.watch.wait_caught_up(
            {0: hub.tails[0].length()})
        # one committed cross-group txn for the txn track
        h = kv.transact([("put", keys[0][0], b"w"),
                         ("put", keys[1][0], b"w")])
        for _ in range(6):
            if h.done:
                break
            shard.step()
        assert h.committed
        # watch traces retire with the deliveries; give the pump a
        # beat, then merge — all THREE subsystem tracks present
        deadline = time.time() + 5
        while (obs.tracectx.counts()["by_kind"].get("watch", 0) < 1
               and time.time() < deadline):
            time.sleep(0.01)
        assert sub.poll(max_n=64)
        doc = merge_timeline([obs.spans.dump(anchor=ANCHOR)],
                             [obs.tracectx.dump(anchor=ANCHOR)])
        pids = {e["pid"] for e in doc["traceEvents"]}
        for kind in ("txn", "watch"):
            assert SUBSYS_PIDS[kind] in pids, kind
        watch = [t for t in obs.tracectx.dump()["traces"]
                 if t["kind"] == "watch"]
        assert watch
        for t in watch:
            names = [p for p, _ in t["phases"]]
            assert names[:1] == ["pump"] and "deliver" in names
    finally:
        hub.fail_all("test done")


# ---------------------------------------------------------------------------
# zero-device discipline: tracing changes NOTHING on the step path
# ---------------------------------------------------------------------------

def test_step_cache_and_outputs_bit_identical_with_tracing():
    # fresh geometry: exact "adds nothing" set comparison
    cfg = LogConfig(n_slots=128, slot_bytes=128, window_slots=16,
                    batch_slots=4)

    def workload(shard, kv):
        shard.place_leaders()
        keys = keys_for_groups(kv.router, 3)
        h = kv.transact([("put", keys[0][0], b"w"),
                         ("put", keys[1][0], b"w")])
        for _ in range(6):
            if h.done:
                break
            shard.step()
        assert h.committed
        for _ in range(3):
            shard.step()

    plain = ShardedCluster(cfg, 3, 2, txn=True)
    kv_p = ShardedKVS(plain, cap=64)
    attach_coordinator(kv_p)
    workload(plain, kv_p)
    keys_before = set(STEP_CACHE)

    traced = ShardedCluster(cfg, 3, 2, txn=True)
    traced.obs = Observability(
        span_recorder=SpanRecorder(sample_every=1),
        trace_context=TraceContext())
    kv_t = ShardedKVS(traced, cap=64)
    attach_topology(kv_t, obs=traced.obs, cooldown_steps=2)
    attach_coordinator(kv_t)
    workload(traced, kv_t)
    assert set(STEP_CACHE) == keys_before, (
        "full tracing must add NOTHING to the step cache")
    for k in ("term", "commit", "end", "apply", "head", "role"):
        assert np.array_equal(np.asarray(plain.last[k]),
                              np.asarray(traced.last[k])), k
    # and it actually traced: the txn trace retired as committed
    c = traced.obs.tracectx.counts()
    assert c["by_kind"].get("txn") == 1 and c["open"] == 0
