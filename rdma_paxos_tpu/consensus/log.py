"""The replicated log as fixed-shape on-device arrays.

Reference: the DARE log is a byte-granular 64 MB circular buffer, remotely
writable via one-sided RDMA, with four offsets ``head/apply/commit/end`` and
entry framing ``{idx, term, req_id, clt_id, type, reply[], data}``
(``src/include/dare/dare_log.h:33-47,76-103``) plus wrap-around splitting
rules (``dare_log.h:466-558``).

TPU-native redesign (NOT a translation):

* **Slot-based ring, SoA layout.** Fixed-size slots; payload lives in an
  ``[n_slots, slot_words] int32`` array, per-entry metadata in an
  ``[n_slots, META_W] int32`` array (struct-of-arrays — XLA/VPU-friendly,
  where the reference packs variable-size structs into a byte buffer).
  Oversize payloads are fragmented by the proxy into consecutive SEND
  entries, which is semantically lossless for stream replay.
* **Global monotone indices.** ``head/apply/commit/end`` are monotonically
  increasing int32 *entry* indices; the slot of global index ``g`` is
  ``g % n_slots``. The reference's wrap-around entry-splitting machinery
  (``dare_log.h:496-545``) disappears: wrap is a single cheap mask, and the
  two-segment RDMA write on wrap (``dare_ibv_rc.c:1539-1545``) becomes a
  gather/scatter with modular indices.
* **No reply[] array in the entry.** The reference embeds a per-entry ACK
  byte-array that followers RDMA-write into the leader's log
  (``dare_log.h:44``). On TPU, acknowledgement is an ``all_gather`` of
  follower ``end`` offsets (see ``consensus/step.py``) — the per-entry ACK
  bitmap materializes only inside the quorum kernel (``ops/quorum.py``).

Everything here is pure and shape-static: callable under ``jit``, ``vmap``
and ``shard_map``.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Tuple

import jax
import jax.numpy as jnp

from rdma_paxos_tpu.config import LogConfig


class EntryType(enum.IntEnum):
    """Log entry types — reference ``dare_log.h:22-25`` (NOOP/CSM/CONFIG)
    plus proxy event types carried in CSM entries (CONNECT/SEND/CLOSE,
    reference ``src/include/dare/message.h``).

    The reference's fourth type, HEAD (``dare_log.h:25`` — a durable log
    entry publishing the pruned head offset, ``log_pruning``
    ``dare_server.c:1996-2067``), has NO analog here by design: the head
    offset rides EVERY leader window message as a scalar column
    (``S_HEAD``, consensus/step.py Phase D/E), so followers learn head
    advancement continuously instead of through an in-log record, and a
    restarted replica recovers head from its snapshot determinant
    (consensus/snapshot.py). A durable in-log HEAD entry would be
    redundant state with no consumer."""

    EMPTY = 0       # unwritten slot
    NOOP = 1        # blank entry appended by a fresh leader (dare_server.c:1487)
    CONNECT = 2     # proxy: new client connection     (proxy.c:163-228)
    SEND = 3        # proxy: client payload bytes      (proxy.c:230-239)
    CLOSE = 4       # proxy: connection closed         (proxy.c:241-261)
    CONFIG = 5      # membership change                (dare_log.h:24)


# Metadata columns (SoA): meta[slot, col]. M_GIDX is the entry's global
# monotone index, stamped at append time — it lets a full-ring scan
# reconstruct which slots are live ([head, end)) without walking offsets,
# e.g. the CONFIG-derivation scan in consensus/step.py. A recycled slot's
# stale gidx is always < head (the ring holds <= n_slots live entries), so
# `gidx >= head` alone identifies liveness.
#
# DESIGN CONSTRAINT: all log offsets (head/apply/commit/end and M_GIDX)
# are i32 entry indices, so a deployment is bounded at 2^31-1 entries
# (~13 minutes at the benched multi-M ops/s). The epoch-rebase path
# already exists: snapshot install renumbers offsets from the snapshot
# index (consensus/snapshot.py), so a long-running cluster rolls over by
# a coordinated snapshot+install well before the ceiling — the same
# mechanism a joiner uses. The reference has the analogous bound in its
# uint64 byte offsets (dare_log.h:77-103), just further away.
M_TYPE, M_TERM, M_CONN, M_REQID, M_LEN, M_GIDX = 0, 1, 2, 3, 4, 5
# M_GEN: the elastic generation of the submitting host incarnation —
# lets a rebuilt host distinguish entries ITS CURRENT app served live
# (gen matches: ack, don't replay) from entries a previous incarnation
# originated (gen differs: replay into the rebuilt app like any remote
# entry). An explicit column, not high bits of req_id, so neither
# counter can overflow into misclassification.
M_GEN = 6
META_W = 8  # padded for alignment


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Log:
    """Per-replica log. Payload words and framing metadata live FUSED in
    one ``[n_slots, slot_words + META_W]`` array so every ring gather /
    scatter in the replication hot path touches a single array (the
    dominant step cost scales with the number of these ops, measured ~2x
    win over separate data/meta arrays). ``data`` / ``meta`` are computed
    column views — XLA fuses the slices away."""

    buf: jax.Array    # [..., n_slots, slot_words + META_W] int32

    # Shape/view properties are axis-agnostic: they work both on a single
    # replica's [n_slots, cols] buf and on batched [R, n_slots, cols] state
    # (vmap/stacked), so callers never hand-compute fused-layout offsets.

    @property
    def n_slots(self) -> int:
        return self.buf.shape[-2]

    @property
    def slot_words(self) -> int:
        return self.buf.shape[-1] - META_W

    @property
    def data(self) -> jax.Array:   # [..., n_slots, slot_words]
        return self.buf[..., :self.slot_words]

    @property
    def meta(self) -> jax.Array:   # [..., n_slots, META_W]
        return self.buf[..., self.slot_words:]


def make_log(cfg: LogConfig) -> Log:
    return Log(buf=jnp.zeros((cfg.n_slots, cfg.slot_words + META_W),
                             jnp.int32))


def _fuse(data: jax.Array, meta: jax.Array) -> jax.Array:
    return jnp.concatenate([data, meta], axis=-1)


def slot_of(g: jax.Array, n_slots: int) -> jax.Array:
    """Slot index of global entry index ``g`` (n_slots is a power of two)."""
    return jnp.bitwise_and(g, n_slots - 1)


def last_term(log: Log, end: jax.Array) -> jax.Array:
    """Term of the last entry (0 for an empty log) — used for the election
    up-to-date check (reference ``dare_server.c:1596-1652``)."""
    t = log.meta[slot_of(end - 1, log.n_slots), M_TERM]
    return jnp.where(end > 0, t, 0)


# ---------------------------------------------------------------------------
# Append (leader)
# ---------------------------------------------------------------------------

def append_batch(
    log: Log,
    end: jax.Array,
    head: jax.Array,
    batch_data: jax.Array,   # [B, slot_words] int32
    batch_meta: jax.Array,   # [B, META_W] int32 (M_TERM overwritten here)
    count: jax.Array,        # scalar int32, entries actually present (<= B)
    term: jax.Array,         # scalar int32, leader's current term
) -> Tuple[Log, jax.Array]:
    """Append up to ``count`` entries at ``end`` stamped with ``term``.

    The capacity clamp enforces the reference's invariant that appends never
    overtake ``head`` (``log_append_entry``'s free-space check,
    ``dare_log.h:466-558``); entries that do not fit are dropped here and the
    proxy retries them next step (the reference instead forces log pruning,
    ``dare_server.c:2069-2122`` — our host driver does the same by feeding
    apply offsets forward, see ``consensus/step.py``).

    Returns ``(log', new_end)``.
    """
    n_slots = log.n_slots
    B = batch_data.shape[0]
    # Capacity is n_slots-1 (one slot always kept free) so that for any
    # window start >= head, slot(wstart-1) still physically holds entry
    # wstart-1 — the AppendEntries prev-term check in the step never reads
    # a recycled slot.
    avail = (n_slots - 1) - (end - head)
    n = jnp.clip(jnp.minimum(count, avail), 0, B).astype(jnp.int32)

    offs = jnp.arange(B, dtype=jnp.int32)
    valid = offs < n
    # out-of-range index => dropped by scatter mode="drop"
    idx = jnp.where(valid, slot_of(end + offs, n_slots), n_slots)

    meta = batch_meta.at[:, M_TERM].set(term)
    meta = meta.at[:, M_GIDX].set(end + offs)
    new_buf = log.buf.at[idx].set(_fuse(batch_data, meta), mode="drop")
    return Log(new_buf), end + n


# ---------------------------------------------------------------------------
# Window extract (leader fan-out) / absorb (follower accept)
# ---------------------------------------------------------------------------

def extract_window(
    log: Log, start: jax.Array, window_slots: int
) -> Tuple[jax.Array, jax.Array]:
    """Gather ``window_slots`` consecutive entries beginning at global index
    ``start`` into dense ``[W, ...]`` arrays.

    This is the replication payload the leader broadcasts — the analog of the
    RDMA WRITE of ``log[remote_end : end]`` (reference
    ``dare_ibv_rc.c:1526-1642``); the ring wrap that costs the reference two
    RDMA sends (``:1539-1545``) is absorbed by the modular gather.
    """
    idx = slot_of(start + jnp.arange(window_slots, dtype=jnp.int32),
                  log.n_slots)
    w = log.buf[idx]                         # ONE gather for data + meta
    return w[:, :log.slot_words], w[:, log.slot_words:]


def absorb_window(
    log: Log,
    my_end: jax.Array,
    wdata: jax.Array,     # [W, slot_words]
    wmeta: jax.Array,     # [W, META_W]
    wstart: jax.Array,    # global index of window[0]
    wcount: jax.Array,    # valid entries in the window
) -> Tuple[Log, jax.Array]:
    """Follower-side accept: merge a leader window into the local log.

    Implements the log-adjustment semantics of the reference
    (``log_adjustment`` steps LR_GET_WRITE→…→SET_END,
    ``dare_ibv_rc.c:1292-1451``; NC-buffer determinants,
    ``dare_log.h:58-65,339-359``) as pure data flow:

    * **Gap gate**: if ``wstart > my_end`` the follower cannot verify
      continuity and ignores the window (it will be covered next step, since
      the leader floors the window at the minimum active ``end``).
    * **Divergence truncation**: in the overlap ``[wstart, min(my_end,
      wend))`` compare per-entry terms; at the first mismatch the local
      suffix is stale (uncommitted entries of a deposed leader) and is
      discarded — the window contents replace it. With no mismatch a shorter
      window never truncates a longer log.
    * **Copy**: all valid window entries are scattered in (overwriting
      matching prefixes with identical bytes is a no-op).

    Term gating (stale-leader fencing — the analog of the QP revoke fencing,
    ``rc_revoke_log_access`` ``dare_ibv_rc.c:2156-2255``) happens in the
    caller (``consensus/step.py``): a window stamped with an old term never
    reaches this function.

    Returns ``(log', new_end)``.
    """
    n_slots = log.n_slots
    W = wdata.shape[0]
    offs = jnp.arange(W, dtype=jnp.int32)
    g = wstart + offs                       # global index per window position
    valid = offs < wcount
    wend = wstart + wcount

    accept = wstart <= my_end

    # --- divergence scan over the overlap ---
    local_terms = log.meta[slot_of(g, n_slots), M_TERM]
    in_overlap = valid & (g < my_end)
    mismatch = in_overlap & (local_terms != wmeta[:, M_TERM])
    any_conflict = jnp.any(mismatch)

    # --- scatter the window in (one fused scatter) ---
    do_copy = valid & accept
    idx = jnp.where(do_copy, slot_of(g, n_slots), n_slots)
    new_buf = log.buf.at[idx].set(_fuse(wdata, wmeta), mode="drop")

    new_end = jnp.where(
        accept,
        jnp.where(any_conflict, wend, jnp.maximum(my_end, wend)),
        my_end,
    ).astype(jnp.int32)
    return Log(new_buf), new_end
