"""Per-replica consensus state.

The reference derives a replica's role from a single 64-bit SID
``[TERM | L | IDX]`` updated by CAS (``src/include/dare/dare_server.h:46-72``,
macros ``src/dare/dare_server.c:42-53``, ``server_update_sid``
``:2288-2297``). The CAS exists because app threads and the DARE thread race
on it; in the TPU design the state is only ever updated inside the jitted
replica step (single logical writer per replica), so the SID unpacks into
plain fields: ``term``, ``leader_id``, ``role``.

Membership is a bitmask configuration with dual-quorum transitional states,
exactly the reference's ``cid`` (``src/include/dare/dare_config.h:17-44``):
``CID_STABLE`` needs one majority over ``bitmask_new``; ``CID_TRANSIT``
needs majorities over both ``bitmask_old`` and ``bitmask_new``.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import Log, make_log


class Role(enum.IntEnum):
    """Reference ``dare_server.h`` roles (NONE/FOLLOWER/CANDIDATE/LEADER)."""

    NONE = 0        # not an active member (joiner before CONFIG commit)
    FOLLOWER = 1
    CANDIDATE = 2
    LEADER = 3


class ConfigState(enum.IntEnum):
    """Membership-change configuration states — reference
    ``dare_config.h:17-24`` (CID_STABLE / CID_TRANSIT / CID_EXTENDED)."""

    STABLE = 0
    TRANSIT = 1     # joint consensus: both masks must reach majority
    EXTENDED = 2    # group up-size announced, not yet transitional


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ReplicaState:
    """Everything one replica carries between steps (one pytree per device).

    Log offsets are global monotone entry indices with the reference's
    invariant chain ``head <= apply <= commit <= end``
    (``dare_log.h:77-103``).
    """

    log: Log
    # --- SID fields (dare_server.h:46-72) ---
    term: jax.Array         # i32 — current term
    role: jax.Array         # i32 — Role
    leader_id: jax.Array    # i32 — known leader, -1 if none
    # --- election durability (rc_replicate_vote, dare_ibv_rc.c:1049) ---
    voted_term: jax.Array   # i32 — highest term in which we voted
    voted_for: jax.Array    # i32 — candidate voted for in voted_term
    # Peer vote records — the rc_replicate_vote durability analog: every
    # replica retains, for each peer, the newest (voted_term, voted_for)
    # pair it has heard in the vote gather. A crash-recovered replica
    # restores its own vote by reading these records back from live peers
    # (rc_get_replicated_vote, dare_ibv_rc.c:394-473), so it can never
    # grant a second vote in a term where its first vote was counted.
    vote_rec_term: jax.Array  # [R] i32 — peer r's voted_term as heard
    vote_rec_for: jax.Array   # [R] i32 — peer r's voted_for as heard
    # --- log offsets (dare_log.h:77-103) ---
    head: jax.Array         # i32 — oldest retained entry
    apply: jax.Array        # i32 — applied up to here (host echoes back)
    commit: jax.Array       # i32 — committed up to here (monotone)
    end: jax.Array          # i32 — next append position
    # --- membership (dare_config.h:26-44) ---
    cid_state: jax.Array    # i32 — ConfigState
    bitmask_old: jax.Array  # u32 — member bitmask (old config)
    bitmask_new: jax.Array  # u32 — member bitmask (new/current config)
    epoch: jax.Array        # i32 — config epoch (bumped per change)
    # gidx of the log entry backing the live config cache above, or -1
    # when the cache came from the committed checkpoint / initial state.
    # The step adopts newer CONFIG entries incrementally (from the
    # appended batch / absorbed window) and re-derives by full-ring scan
    # only when THIS entry is truncated or overwritten — see the CONFIG
    # derivation block in consensus/step.py. cfg_src_term is the source
    # entry's term: an absorbed window row at the same gidx but a
    # different term is a DIFFERENT entry (a new leader's conflicting
    # CONFIG) and must invalidate the cache.
    cfg_src: jax.Array      # i32
    cfg_src_term: jax.Array  # i32
    # Committed-config checkpoint — the newest CONFIG entry known
    # committed. The live config above is DERIVED each step as "newest
    # CONFIG entry retained in the log, else this checkpoint" (Raft's
    # latest-configuration-in-the-log rule), so truncating an uncommitted
    # CONFIG entry automatically rolls the config back instead of leaving
    # an abandoned config adopted forever.
    ccfg_old: jax.Array     # u32
    ccfg_new: jax.Array     # u32
    ccfg_cid: jax.Array     # i32
    ccfg_epoch: jax.Array   # i32


def make_replica_state(
    cfg: LogConfig,
    group_size: int,
    n_replicas: int | None = None,
    *,
    role: Role = Role.FOLLOWER,
) -> ReplicaState:
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    R = n_replicas if n_replicas is not None else group_size
    mask = jnp.asarray((1 << group_size) - 1, jnp.uint32)
    return ReplicaState(
        log=make_log(cfg),
        term=i32(0),
        role=i32(int(role)),
        leader_id=i32(-1),
        voted_term=i32(0),
        voted_for=i32(-1),
        vote_rec_term=jnp.zeros((R,), jnp.int32),
        vote_rec_for=jnp.full((R,), -1, jnp.int32),
        head=i32(0),
        apply=i32(0),
        commit=i32(0),
        end=i32(0),
        cid_state=i32(int(ConfigState.STABLE)),
        bitmask_old=mask,
        bitmask_new=mask,
        epoch=i32(0),
        cfg_src=i32(-1),
        cfg_src_term=i32(0),
        ccfg_old=mask,
        ccfg_new=mask,
        ccfg_cid=i32(int(ConfigState.STABLE)),
        ccfg_epoch=i32(0),
    )
