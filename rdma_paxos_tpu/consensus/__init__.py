from rdma_paxos_tpu.consensus.log import (  # noqa: F401
    Log,
    EntryType,
    make_log,
    append_batch,
    extract_window,
    absorb_window,
)
from rdma_paxos_tpu.consensus.state import (  # noqa: F401
    Role,
    ReplicaState,
    make_replica_state,
)
from rdma_paxos_tpu.consensus.step import (  # noqa: F401
    StepInput,
    StepOutput,
    replica_step,
    make_step_input,
)
