"""Live membership change — join / upsize / downsize via joint consensus.

Reference (§3.5): a joiner multicasts JOIN; the leader allocates a slot or
up-sizes the group (``handle_server_join_request``,
``dare_ibv_ud.c:972-1068``), appends a CONFIG entry, and drives the config
state machine EXTENDED → TRANSIT → STABLE through committed CONFIG entries
(``apply_committed_entries`` ``dare_server.c:1861-1937``), requiring BOTH
majorities while transitional (``CID_TRANSIT``, ``dare_config.h:17-24``).

Here a CONFIG log entry's payload is four int32 words
``[bitmask_old, bitmask_new, cid_state, epoch]``; replicas adopt the newest
config present in their log immediately on append/absorb (the device-side
scan in ``consensus/step.py`` Phase G — matching ``poll_config_entries``),
while quorum rules switch to dual-majority the moment the TRANSIT entry is
in the leader's log. The host-side manager below drives the two-phase
change: submit TRANSIT, wait for commit, submit STABLE.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from rdma_paxos_tpu.consensus.state import ConfigState


def config_payload(bitmask_old: int, bitmask_new: int, cid_state: int,
                   epoch: int) -> bytes:
    return np.array([bitmask_old, bitmask_new, cid_state, epoch],
                    dtype="<i4").tobytes()


class MembershipManager:
    """Drives joint-consensus membership changes on a cluster harness
    (SimCluster or ClusterDriver.cluster)."""

    def __init__(self, cluster):
        self.cluster = cluster

    def current(self, r: int = 0):
        st = self.cluster.state
        return dict(
            bitmask_old=int(np.asarray(st.bitmask_old[r])),
            bitmask_new=int(np.asarray(st.bitmask_new[r])),
            cid_state=int(np.asarray(st.cid_state[r])),
            epoch=int(np.asarray(st.epoch[r])),
        )

    def change(self, leader: int, new_mask: int, *,
               max_steps: int = 50) -> None:
        """Two-phase change to ``new_mask``: TRANSIT (dual quorum), then
        STABLE once the transitional entry committed. Blocking; steps the
        cluster (driver integration calls the phases separately)."""
        cur = self.current(leader)
        old_mask = cur["bitmask_new"]
        if old_mask == new_mask:
            return
        epoch = cur["epoch"]
        self.submit_transit(leader, old_mask, new_mask, epoch + 1)
        target = self._step_until_config(leader,
                                         int(ConfigState.TRANSIT),
                                         epoch + 1, max_steps)
        # TRANSIT is in the log and committed -> finalize
        self.submit_stable(leader, new_mask, epoch + 2)
        self._step_until_config(leader, int(ConfigState.STABLE),
                                epoch + 2, max_steps)
        del target

    def join(self, leader: int, joiner: int, *,
             max_steps: int = 50) -> None:
        """Three-phase joiner admission: EXTENDED (joiner replicates,
        old quorum) → TRANSIT (dual quorum) → STABLE — the full
        reference join ladder (``handle_server_join_request`` →
        ``apply_committed_entries`` EXTENDED→TRANSIT→STABLE,
        ``dare_server.c:1861-1937``). Blocking; the driver integration
        drives the same phases incrementally."""
        cur = self.current(leader)
        old_mask = cur["bitmask_new"]
        if (old_mask >> joiner) & 1:
            return
        epoch = cur["epoch"]
        self.submit_extended(leader, old_mask, joiner, epoch + 1)
        self._step_until_config(leader, int(ConfigState.EXTENDED),
                                epoch + 1, max_steps)
        # EXTENDED committed ⟹ the joiner is inside the replication
        # window fan-out; it must actually CATCH UP before it may count
        # toward quorum (a joiner whose lag exceeds window_slots can
        # never catch up passively — it needs snapshot recovery first,
        # exactly the reference's joiner SM-recovery prerequisite,
        # dare_ibv_rc.c:603-710)
        for _ in range(max_steps):
            st = self.cluster.state
            if (int(np.asarray(st.end[joiner]))
                    >= int(np.asarray(st.end[leader]))):
                break
            self.cluster.step()
        else:
            raise TimeoutError(
                f"joiner {joiner} did not catch up within {max_steps} "
                "steps (lag beyond window_slots requires snapshot "
                "recovery before join)")
        # joiner caught up: flip to dual quorum
        self.submit_transit(leader, old_mask, old_mask | (1 << joiner),
                            epoch + 2)
        self._step_until_config(leader, int(ConfigState.TRANSIT),
                                epoch + 2, max_steps)
        self.submit_stable(leader, old_mask | (1 << joiner), epoch + 3)
        self._step_until_config(leader, int(ConfigState.STABLE),
                                epoch + 3, max_steps)

    def submit_extended(self, leader: int, old_mask: int, joiner: int,
                        epoch: int) -> None:
        """Announce an up-size for ``joiner`` (EXTENDED): the joiner is
        added to ``bitmask_new`` so it receives the replication window
        and counts in the pruning floor, but quorum stays on
        ``bitmask_old`` until the leader submits TRANSIT — the
        reference's EXTENDED config (``dare_ibv_ud.c:1024-1037``)."""
        from rdma_paxos_tpu.consensus.log import EntryType
        self.cluster.submit(
            leader,
            config_payload(old_mask, old_mask | (1 << joiner),
                           int(ConfigState.EXTENDED), epoch),
            EntryType.CONFIG)

    def submit_transit(self, leader: int, old_mask: int, new_mask: int,
                       epoch: int) -> None:
        from rdma_paxos_tpu.consensus.log import EntryType
        self.cluster.submit(
            leader,
            config_payload(old_mask, new_mask,
                           int(ConfigState.TRANSIT), epoch),
            EntryType.CONFIG)

    def submit_stable(self, leader: int, new_mask: int,
                      epoch: int) -> None:
        from rdma_paxos_tpu.consensus.log import EntryType
        self.cluster.submit(
            leader,
            config_payload(new_mask, new_mask,
                           int(ConfigState.STABLE), epoch),
            EntryType.CONFIG)

    def _step_until_config(self, leader: int, want_state: int,
                           want_epoch: int, max_steps: int):
        """Step until the leader's applied config reaches (state, epoch)
        AND the config entry itself is committed (commit >= its index)."""
        for _ in range(max_steps):
            res = self.cluster.step()
            cur = self.current(leader)
            if (cur["epoch"] >= want_epoch
                    and cur["cid_state"] == want_state
                    and int(res["commit"][leader]) >= int(res["end"][leader])):
                return cur
        raise TimeoutError(
            f"config change to state={want_state} epoch={want_epoch} "
            f"did not commit in {max_steps} steps")
