"""Snapshot-based recovery — the joiner/straggler catch-up path.

Reference (§3.5 of SURVEY.md): a joiner RDMA-reads a donor's serialized
BerkeleyDB record stream plus the determinant of the last applied entry
(``snapshot_t``, ``dare_log.h:105-112``; ``rc_recover_sm``
``dare_ibv_rc.c:603-710``; ``proxy_apply_db_snapshot`` ``proxy.c:306-339``),
then RDMA-reads the log tail (``rc_recover_log`` ``:726-856``).

TPU-native equivalent: the app/event state travels as the stable store's
dump blob (host side, DCN); the device-side install sets the replica's log
offsets to the snapshot determinant ``(index, term)`` — the Raft
InstallSnapshot pair — and stamps the determinant term into the slot of
``index-1`` so the AppendEntries prev-term check passes and ordinary window
replication takes over from there (no special log-recovery path needed: the
leader's window floors at the restored ``end``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.config import DIGEST_EPOCH
from rdma_paxos_tpu.consensus.log import (
    Log, M_GIDX, M_TERM, META_W, slot_of)
from rdma_paxos_tpu.consensus.state import ReplicaState
from rdma_paxos_tpu.consensus.step import digest_fold
from rdma_paxos_tpu.obs import trace as obs_trace
from rdma_paxos_tpu.obs.metrics import default_registry
from rdma_paxos_tpu.obs.trace import default_ring


class SnapshotVerifyError(RuntimeError):
    """The snapshot's digest chain contradicts the audit ledger's
    majority digests: the DONOR is corrupted (or unverifiable). Raised
    by :func:`install_snapshot` BEFORE any state is touched — a
    corrupted donor is rejected at install time, never propagated; the
    repair pipeline retries with another majority donor."""


class SnapshotEpochError(SnapshotVerifyError):
    """The snapshot's digests were computed under a different digest
    LAYOUT (``config.DIGEST_EPOCH``): incomparable, not unequal —
    refuse rather than mis-verdict during a rolling digest upgrade."""


def _row_idx(group, r):
    """State-row index tuple: ``(r,)`` on the [R]-batched SimCluster
    state, ``(group, r)`` on the [G, R]-batched sharded state — the
    one place the snapshot path widens by the group axis."""
    return (r,) if group is None else (int(group), r)


@dataclasses.dataclass
class Snapshot:
    """Host-transferable snapshot: consensus determinant + event history.

    The config fields are the donor's COMMITTED-config checkpoint
    (``ccfg_*``), not its live adopted config: a live-but-uncommitted
    CONFIG entry always has ``gidx >= commit >= apply = index``, so the
    recovered replica re-absorbs it through ordinary window replication if
    it survives — and must NOT inherit it if it is truncated cluster-wide
    (the abandoned-config trap).

    ``digest_epoch``/``audit_start``/``audit_digests`` are the AUDIT
    CHAIN POSITION (``take_snapshot(digests=True)``): one u32 digest
    per physically-present committed entry ``[audit_start,
    audit_start + len)`` in ABSOLUTE indices (the donor's
    ``rebased_total`` folded in), computed with the same fold as the
    on-device audit windows (``consensus/step.py:digest_fold``).
    ``install_snapshot(ledger=...)`` verifies them against the
    ledger's majority digests and REFUSES a contradicting donor."""

    index: int            # last applied entry index + 1 (= donor apply)
    term: int             # term of entry index-1 (prev-check anchor)
    store_blob: bytes     # serialized stable store (full event history)
    epoch: int            # committed membership epoch at the donor
    bitmask_old: int
    bitmask_new: int
    cid_state: int
    # --- audit-chain binding (digests=True snapshots only) ---
    digest_epoch: int = 0              # digest LAYOUT version; 0 = none
    audit_start: int = -1              # ABSOLUTE index of audit_digests[0]
    audit_digests: Optional[np.ndarray] = None   # u32 [n]


def take_snapshot(state_b: ReplicaState, donor: int,
                  store_blob: bytes = b"",
                  index: Optional[int] = None, *,
                  group: Optional[int] = None,
                  digests: bool = False,
                  rebased_total: int = 0) -> Snapshot:
    """Capture a snapshot from replica ``donor`` of a batched state.

    Batched state carries the fused log as ``buf[R, n_slots, slot_words +
    META_W]`` (``[G, R, ...]`` with ``group``); the determinant term of
    entry ``apply-1`` lives at ``buf[..., slot, slot_words + M_TERM]``.

    ``index`` overrides the determinant index: pass the donor's HOST
    apply counter when the accompanying ``store_blob`` was produced by
    the host — the device-side ``apply`` can LAG the host's by one
    step's echo, and a snapshot whose index undershoots its store would
    make the recovered replica re-apply (and re-persist) records the
    store already holds.

    ``digests=True`` folds the donor's AUDIT CHAIN POSITION into the
    snapshot: its physically-present committed prefix ``[head, index)``
    is re-digested host-side with the device fold
    (``consensus/step.py:digest_fold``) and stamped in ABSOLUTE indices
    (``rebased_total`` added) together with ``config.DIGEST_EPOCH`` —
    the evidence ``install_snapshot(ledger=...)`` verifies against the
    ledger's majority digests so a corrupted donor is rejected, not
    propagated. Entries whose stamped gidx disagrees with the expected
    index (slot recycled mid-capture) truncate the chain from below."""
    log = state_b.log
    idx = _row_idx(group, donor)
    apply_ = (int(np.asarray(state_b.apply[idx])) if index is None
              else int(index))
    term = 0
    if apply_ > 0:
        slot = (apply_ - 1) & (log.n_slots - 1)
        # single-element device read — never pulls the full log to host
        term = int(log.buf[idx + (slot, log.slot_words + M_TERM)])
    digest_epoch, a_start, a_dig = 0, -1, None
    if digests:
        # one device->host pull of the donor's fused row; the digest
        # chain is host-computed with the SHARED fold (xp=numpy)
        buf_np = np.asarray(log.buf[idx])
        sw = buf_np.shape[-1] - META_W
        n_slots = buf_np.shape[0]
        lo = max(int(np.asarray(state_b.head[idx])), 0)
        slots = (np.arange(lo, apply_) & (n_slots - 1)
                 if apply_ > lo else np.zeros(0, np.int64))
        rows = buf_np[slots]
        stamped = rows[:, sw + M_GIDX] if rows.size else rows[:, :0]
        good = stamped == np.arange(lo, apply_, dtype=stamped.dtype) \
            if rows.size else np.zeros(0, bool)
        # truncate from below past any recycled slot: the chain must
        # be contiguous up to the determinant
        first_good = int(len(good) - np.argmin(good[::-1])
                         if good.size and not good.all() else 0)
        rows = rows[first_good:]
        lo += first_good
        digest_epoch = DIGEST_EPOCH
        a_start = lo + int(rebased_total)
        a_dig = (digest_fold(rows.astype(np.uint32), xp=np)
                 if len(rows) else np.zeros(0, np.uint32))
    snap = Snapshot(
        index=apply_, term=term, store_blob=store_blob,
        epoch=int(np.asarray(state_b.ccfg_epoch[idx])),
        bitmask_old=int(np.asarray(state_b.ccfg_old[idx])),
        bitmask_new=int(np.asarray(state_b.ccfg_new[idx])),
        cid_state=int(np.asarray(state_b.ccfg_cid[idx])),
        digest_epoch=digest_epoch, audit_start=a_start,
        audit_digests=a_dig,
    )
    # host-side wrapper instrumentation (never inside the jitted body):
    # snapshot traffic is the recovery-path signal operators watch
    default_registry().inc("snapshots_taken_total")
    default_ring().record(obs_trace.SNAPSHOT_TAKEN, replica=donor,
                          index=snap.index, term=snap.term,
                          store_bytes=len(store_blob))
    return snap


def _install_body(state_b: ReplicaState, idx, index, term, cur_term,
                  voted_term, voted_for, epoch, bm_old, bm_new,
                  cid) -> ReplicaState:
    """Shared install body; ``idx`` is the state-row index tuple —
    ``(r,)`` for the [R]-batched state, ``(g, r)`` for the sharded
    [G, R]-batched state (the two thin jitted wrappers below)."""
    i32 = jnp.int32
    n_slots = state_b.log.n_slots
    slot_words = state_b.log.slot_words
    n_rec = state_b.vote_rec_term.shape[-1]
    # wipe the replica's fused log row and stamp the determinant term at the
    # slot of index-1 (the prev-term anchor for the first absorbed window)
    buf = state_b.log.buf.at[idx].set(0)
    anchor = slot_of(jnp.maximum(index - 1, 0), n_slots)
    buf = buf.at[idx + (anchor, slot_words + M_TERM)].set(
        jnp.where(index > 0, term, 0).astype(i32))
    log = Log(buf=buf)
    bm_old_u = bm_old.astype(jnp.uint32)
    bm_new_u = bm_new.astype(jnp.uint32)
    sets = dict(head=index, apply=index, commit=index, end=index,
                term=cur_term, role=1, leader_id=-1,
                voted_term=voted_term, voted_for=voted_for,
                # a fresh process has no memory of peers' votes
                vote_rec_term=jnp.zeros((n_rec,), i32),
                vote_rec_for=jnp.full((n_rec,), -1, i32),
                epoch=epoch, bitmask_old=bm_old_u, bitmask_new=bm_new_u,
                cid_state=cid,
                cfg_src=-1,      # cache backed by the checkpoint below
                cfg_src_term=0,

                # the snapshot's config IS the donor's committed-config
                # checkpoint (see Snapshot docstring); the wiped log holds
                # no CONFIG entries, so the first derivation falls back
                # here, and any surviving newer CONFIG re-arrives through
                # window replication
                ccfg_old=bm_old_u, ccfg_new=bm_new_u, ccfg_cid=cid,
                ccfg_epoch=epoch)
    out = {k: getattr(state_b, k).at[idx].set(
               jnp.asarray(v).astype(getattr(state_b, k).dtype))
           for k, v in sets.items()}
    return dataclasses.replace(state_b, log=log, **out)


@jax.jit
def _install(state_b: ReplicaState, r, *rest) -> ReplicaState:
    return _install_body(state_b, (r,), *rest)


@jax.jit
def _install_group(state_b: ReplicaState, g, r, *rest) -> ReplicaState:
    return _install_body(state_b, (g, r), *rest)


@jax.jit
def rebase_offsets(state_b: ReplicaState, delta) -> ReplicaState:
    """Subtract ``delta`` from every log offset of every replica — the
    coordinated i32-overflow rollover (LogConfig.rebase_threshold).

    Offsets are RELATIVE quantities everywhere in the protocol (window
    starts, acks, commit scans all compare offsets to each other), so a
    uniform subtraction is invisible to consensus as long as (a) every
    replica shifts in the same host iteration (the drivers guarantee
    it: SimCluster shifts the whole batched state between steps;
    NodeDaemon shifts collectively on a gathered, deterministic signal),
    (b) ``delta <= min(head)`` so no live offset goes negative, and
    (c) ``delta`` is a MULTIPLE OF n_slots — the slot of global index
    ``g`` is ``g % n_slots`` and entries do not move, so the mapping
    must be preserved (callers round the min head down).
    The stamped M_GIDX column shifts too; a recycled slot's stale gidx
    stays < head under uniform subtraction, so the liveness rule
    ``gidx >= head`` is preserved. The reference needs no analog — its
    u64 byte offsets outlive any deployment (dare_log.h:77-103).

    Works on the vmap-batched state and (transparently, no collectives)
    on a shard_map-sharded state: every operation is elementwise."""
    i32 = jnp.int32
    d = jnp.asarray(delta, i32)
    sw = state_b.log.slot_words
    gcol = sw + M_GIDX
    buf = state_b.log.buf
    buf = buf.at[..., gcol].add(-d)
    return dataclasses.replace(
        state_b,
        log=Log(buf=buf),
        head=state_b.head - d,
        apply=state_b.apply - d,
        commit=state_b.commit - d,
        end=state_b.end - d,
        cfg_src=jnp.where(state_b.cfg_src >= 0,
                          state_b.cfg_src - d, state_b.cfg_src),
    )


def export_row(state_b: ReplicaState, r: int) -> dict:
    """Pull replica ``r``'s full state row to host numpy — the transfer
    unit of cross-generation recovery (the analog of the joiner
    RDMA-reading the donor's snapshot buffer AND log tail in one shot,
    ``rc_recover_sm`` + ``rc_recover_log``, ``dare_ibv_rc.c:603-856``).
    Keys are ReplicaState field names; the log travels as ``log_buf``."""
    out = {"log_buf": np.asarray(state_b.log.buf[r])}
    for f in dataclasses.fields(ReplicaState):
        if f.name == "log":
            continue
        out[f.name] = np.asarray(getattr(state_b, f.name)[r])
    return out


def genesis_row(donor_row: dict, *, group_mask: int, epoch: int,
                n_replicas: int, term: Optional[int] = None) -> dict:
    """Sanitize a donor row into the shared GENESIS state of a new
    generation (elastic world rebuild — every member of the new world
    installs an identical copy, so the cluster boots pre-synchronized).

    Rules:

    * The log (and head/apply/commit/end) carries over verbatim — the
      donor is the most up-to-date survivor by Raft's election ordering
      ``(last_log_term, end)``, so its log contains every entry committed
      in the previous generation (Leader Completeness); its uncommitted
      suffix is carried as an ordinary suffix the next leader's NOOP
      commits or truncates.
    * Retained CONFIG entries are re-typed NOOP: slot numbering changes
      across generations, so an old-world bitmask must never resurface
      through the latest-config-in-the-log derivation. The new world's
      config is installed as both the live bitmasks and the committed
      checkpoint (``ccfg_*``).
    * ``term`` is bumped past every surviving member's term (caller
      passes the gathered max) so no vote or leadership claim from the
      dead world can conflict; votes and vote records reset — elections
      in the new world are fresh.
    * Roles reset to FOLLOWER; the new world elects normally.
    """
    from rdma_paxos_tpu.consensus.log import EntryType, M_TYPE
    from rdma_paxos_tpu.consensus.state import ConfigState, Role

    row = {k: np.array(v, copy=True) for k, v in donor_row.items()}
    buf = row["log_buf"]
    slot_words = buf.shape[-1] - META_W
    types = buf[:, slot_words + M_TYPE]
    types[types == int(EntryType.CONFIG)] = int(EntryType.NOOP)
    new_term = (int(row["term"]) if term is None else int(term)) + 1
    i32, u32 = np.int32, np.uint32
    mask = u32(group_mask)
    row.update(
        term=i32(new_term), role=i32(int(Role.FOLLOWER)),
        leader_id=i32(-1),
        voted_term=i32(0), voted_for=i32(-1),
        vote_rec_term=np.zeros(n_replicas, i32),
        vote_rec_for=np.full(n_replicas, -1, i32),
        cid_state=i32(int(ConfigState.STABLE)),
        bitmask_old=mask, bitmask_new=mask, epoch=i32(epoch),
        cfg_src=i32(-1),        # CONFIG entries were re-typed NOOP above
        cfg_src_term=i32(0),
        ccfg_old=mask, ccfg_new=mask,
        ccfg_cid=i32(int(ConfigState.STABLE)), ccfg_epoch=i32(epoch),
    )
    return row


def recover_vote(state_b: ReplicaState, r: int,
                 peers=None, *, group: Optional[int] = None) -> tuple:
    """Read replica ``r``'s replicated vote back from peers' vote records
    — the ``rc_get_replicated_vote`` analog (``dare_ibv_rc.c:394-473``).
    Returns the newest ``(voted_term, voted_for)`` any queried peer
    retains for ``r`` (query BEFORE installing a snapshot into ``r``).
    ``peers`` defaults to everyone EXCEPT ``r`` — a crashed replica's own
    in-memory record is exactly what the crash lost, so consulting it
    would mask real double-vote hazards in simulation. ``group``
    selects one consensus group's records on the sharded state."""
    rec_t = (state_b.vote_rec_term if group is None
             else state_b.vote_rec_term[group])
    rec_f = (state_b.vote_rec_for if group is None
             else state_b.vote_rec_for[group])
    if peers is None:
        peers = [p for p in range(rec_t.shape[0]) if p != r]
    sel = list(peers)
    vt = np.asarray(rec_t[sel, r])
    vf = np.asarray(rec_f[sel, r])
    if vt.size == 0:
        return 0, -1
    i = int(vt.argmax())
    return int(vt[i]), int(vf[i])


def verify_snapshot(snap: Snapshot, ledger, *, group: int = 0,
                    min_verified: int = 1) -> int:
    """Check ``snap``'s digest chain against ``ledger``'s
    MAJORITY-held digests (``obs/audit.py:AuditLedger``): every
    snapshot index the ledger retains with a replica-majority mask
    must carry the identical digest. Returns the number of verified
    indices; raises :class:`SnapshotVerifyError` on any contradiction
    (the donor is corrupted) or when fewer than ``min_verified``
    indices could be checked (an unverifiable donor is refused, not
    trusted), and :class:`SnapshotEpochError` on a digest-layout
    mismatch. Indices the ledger holds with only minority backing are
    SKIPPED — a first report may have come from the diverged minority
    itself, so only majority-held digests are evidence."""
    if snap.audit_digests is None or snap.audit_start < 0:
        raise SnapshotVerifyError(
            "snapshot carries no digest chain (take_snapshot("
            "digests=True) required for a verified install)")
    if snap.digest_epoch != ledger.digest_epoch:
        raise SnapshotEpochError(
            "snapshot digest epoch %d vs ledger epoch %d: layouts are "
            "incomparable — finish the rolling digest upgrade first"
            % (snap.digest_epoch, ledger.digest_epoch))
    maj = ledger.majority
    verified = 0
    chain = np.asarray(snap.audit_digests)
    # one bulk ledger read for the whole chain — per-index locking
    # would contend with the live readback thread for the entire walk
    entries = ledger.digest_range(group, snap.audit_start,
                                  snap.audit_start + len(chain))
    for i, (d, ent) in enumerate(zip(chain, entries)):
        if ent is None:
            continue
        _t, dd, mask = ent
        if bin(mask).count("1") < maj:
            continue
        if int(d) != dd:
            raise SnapshotVerifyError(
                "donor digest 0x%08x contradicts the ledger majority "
                "0x%08x at absolute index %d (group %d): corrupted "
                "donor rejected at install time"
                % (int(d), dd, snap.audit_start + i, group))
        verified += 1
    if verified < int(min_verified):
        raise SnapshotVerifyError(
            "only %d of the snapshot's %d chain indices are "
            "majority-covered by the ledger (need >= %d): donor is "
            "unverifiable" % (verified, len(snap.audit_digests),
                              min_verified))
    return verified


def install_snapshot(state_b: ReplicaState, r: int, snap: Snapshot, *,
                     voted_term: int = 0, voted_for: int = -1,
                     cur_term: int = 0, group: Optional[int] = None,
                     ledger=None, ledger_group: Optional[int] = None,
                     min_verified: int = 1) -> ReplicaState:
    """Install ``snap`` into replica ``r`` of a batched state: the replica
    resumes as a follower at the determinant; ordinary replication catches
    it up from there. The event-history blob is the host's concern
    (StableStore.load + app replay).

    ``voted_term``/``voted_for``/``cur_term`` restore election durability
    across the crash (HardState file + ``recover_vote`` peer records): the
    current term is floored at both the snapshot term and the recovered
    vote term, so a recovered replica can never re-grant a vote it already
    cast (reference ``rc_get_replicated_vote``).

    ``group`` installs into one consensus group of a sharded [G, R]
    state. ``ledger`` (an ``AuditLedger``) makes the install
    DIGEST-VERIFIED: :func:`verify_snapshot` runs FIRST and a
    contradicting (corrupted) donor raises before any state is
    touched — the repair pipeline's never-propagate guarantee."""
    if ledger is not None:
        lg = group if ledger_group is None else ledger_group
        verify_snapshot(snap, ledger, group=(lg or 0),
                        min_verified=min_verified)
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    eff_term = max(int(snap.term), int(cur_term), int(voted_term))
    rest = (i32(snap.index), i32(snap.term),
            i32(eff_term), i32(voted_term), i32(voted_for),
            i32(snap.epoch), i32(snap.bitmask_old),
            i32(snap.bitmask_new), i32(snap.cid_state))
    if group is None:
        out = _install(state_b, i32(r), *rest)
    else:
        out = _install_group(state_b, i32(group), i32(r), *rest)
    # host-side wrapper instrumentation (the jitted _install stays
    # pure) — recorded AFTER the install so a raising _install (or a
    # refused verification) is never reported as an installed snapshot
    default_registry().inc("snapshots_installed_total")
    default_ring().record(obs_trace.SNAPSHOT_INSTALLED, replica=int(r),
                          index=snap.index, term=snap.term,
                          epoch=snap.epoch)
    return out
