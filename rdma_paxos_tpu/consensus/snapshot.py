"""Snapshot-based recovery — the joiner/straggler catch-up path.

Reference (§3.5 of SURVEY.md): a joiner RDMA-reads a donor's serialized
BerkeleyDB record stream plus the determinant of the last applied entry
(``snapshot_t``, ``dare_log.h:105-112``; ``rc_recover_sm``
``dare_ibv_rc.c:603-710``; ``proxy_apply_db_snapshot`` ``proxy.c:306-339``),
then RDMA-reads the log tail (``rc_recover_log`` ``:726-856``).

TPU-native equivalent: the app/event state travels as the stable store's
dump blob (host side, DCN); the device-side install sets the replica's log
offsets to the snapshot determinant ``(index, term)`` — the Raft
InstallSnapshot pair — and stamps the determinant term into the slot of
``index-1`` so the AppendEntries prev-term check passes and ordinary window
replication takes over from there (no special log-recovery path needed: the
leader's window floors at the restored ``end``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from rdma_paxos_tpu.consensus.log import Log, M_TERM, META_W, slot_of
from rdma_paxos_tpu.consensus.state import ReplicaState


@dataclasses.dataclass
class Snapshot:
    """Host-transferable snapshot: consensus determinant + event history."""

    index: int            # last applied entry index + 1 (= donor apply)
    term: int             # term of entry index-1 (prev-check anchor)
    store_blob: bytes     # serialized stable store (full event history)
    epoch: int            # membership epoch at the donor
    bitmask_old: int
    bitmask_new: int
    cid_state: int


def take_snapshot(state_b: ReplicaState, donor: int,
                  store_blob: bytes = b"") -> Snapshot:
    """Capture a snapshot from replica ``donor`` of a batched state.

    Batched state carries the fused log as ``buf[R, n_slots, slot_words +
    META_W]``; the determinant term of entry ``apply-1`` lives at
    ``buf[donor, slot, slot_words + M_TERM]``.
    """
    log = state_b.log
    apply_ = int(np.asarray(state_b.apply[donor]))
    term = 0
    if apply_ > 0:
        slot = (apply_ - 1) & (log.n_slots - 1)
        # single-element device read — never pulls the full log to host
        term = int(log.buf[donor, slot, log.slot_words + M_TERM])
    return Snapshot(
        index=apply_, term=term, store_blob=store_blob,
        epoch=int(np.asarray(state_b.epoch[donor])),
        bitmask_old=int(np.asarray(state_b.bitmask_old[donor])),
        bitmask_new=int(np.asarray(state_b.bitmask_new[donor])),
        cid_state=int(np.asarray(state_b.cid_state[donor])),
    )


@jax.jit
def _install(state_b: ReplicaState, r, index, term, epoch, bm_old, bm_new,
             cid) -> ReplicaState:
    i32 = jnp.int32
    n_slots = state_b.log.n_slots
    slot_words = state_b.log.slot_words
    # wipe the replica's fused log row and stamp the determinant term at the
    # slot of index-1 (the prev-term anchor for the first absorbed window)
    buf = state_b.log.buf.at[r].set(0)
    anchor = slot_of(jnp.maximum(index - 1, 0), n_slots)
    buf = buf.at[r, anchor, slot_words + M_TERM].set(
        jnp.where(index > 0, term, 0).astype(i32))
    log = Log(buf=buf)
    sets = dict(head=index, apply=index, commit=index, end=index,
                term=term, role=1, leader_id=-1,
                epoch=epoch, bitmask_old=bm_old.astype(jnp.uint32),
                bitmask_new=bm_new.astype(jnp.uint32), cid_state=cid)
    out = {k: getattr(state_b, k).at[r].set(
               jnp.asarray(v).astype(getattr(state_b, k).dtype))
           for k, v in sets.items()}
    return dataclasses.replace(state_b, log=log, **out)


def install_snapshot(state_b: ReplicaState, r: int,
                     snap: Snapshot) -> ReplicaState:
    """Install ``snap`` into replica ``r`` of a batched state: the replica
    resumes as a follower at the determinant; ordinary replication catches
    it up from there. The event-history blob is the host's concern
    (StableStore.load + app replay)."""
    i32 = lambda v: jnp.asarray(v, jnp.int32)
    return _install(state_b, i32(r), i32(snap.index), i32(snap.term),
                    i32(snap.epoch), i32(snap.bitmask_old),
                    i32(snap.bitmask_new), i32(snap.cid_state))
