"""The SPMD replica step — the entire DARE protocol as ONE collective program.

The reference drives consensus from a libev event loop (``polling()``,
``src/dare/dare_server.c:1004-1125``) issuing one-sided RDMA verbs per peer:
log adjustment (``dare_ibv_rc.c:1292-1451``), log-delta writes
(``:1465-1826``), per-entry ACK replies (``:1828-1863``), vote requests
(``:969-1043``), heartbeats (``:868-912``), QP-reset fencing
(``:2156-2255``). Followers' CPUs are passive in the replication hot path.

TPU-native redesign: all replicas advance in lock-step through a single
jitted SPMD step over a 1-D ``replica`` mesh axis (one replica per chip).
Every asymmetric, per-peer interaction of the reference becomes *data* inside
a uniform program (SURVEY.md §7 "model follower lag as data"):

=====================================  =======================================
reference mechanism                     TPU-native equivalent (here)
=====================================  =======================================
RDMA WRITE of log delta per follower   leader window ``all_gather`` + local
(``update_remote_logs``)               term-gated ``absorb_window``
log adjustment / NC determinants       prev-term consistency check + data-
(``log_adjustment``)                   driven end backoff (AppendEntries rule)
per-entry ACK reply[] bytes            ``all_gather`` of verified match
(``rc_send_entries_reply``)            offsets (acks)
commit scan + majority count           ``ops.quorum.commit_scan`` (Pallas)
(``dare_ibv_rc.c:1725-1758``)
lazy commit push to followers          leader commit scalar rides the window
(``:1760-1819``)                       message (one-step lazy, like the ref)
HB RDMA write of SID into hb[]         window message with wcount==0
(``rc_send_hb``)                       (term+commit are the heartbeat)
QP RESET fencing of deposed leaders    term gating: a stale leader's window
(``rc_revoke_log_access``)             is never selected (dominant-leader
                                       rule) and never absorbed (term gate)
vote request / vote ack RDMA writes    one-round election: candidacy in the
(``rc_send_vote_request/_ack``)        control gather, votes in a second
                                       gather, winner derived locally
per-follower LR step state machines    none needed — lock-step; laggards are
(``handle_lr_work_completion``)        expressed by window flooring + acks
dual-quorum transitional configs       dual bitmask quorum in vote counting
(``dare_ibv_rc.c:2799-2957``)          and in the commit kernel
log pruning via remote apply offsets   min-of-applies head advance riding the
(``dare_server.c:1976-2122``)          control gather + window message
=====================================  =======================================

Failure semantics: ``peer_mask`` is each replica's local view of which peers
are reachable. On a real slice all-ones (an ICI chip failure kills the whole
SPMD program and is handled by the host layer: mesh rebuild + recovery from
stable storage). In simulation the mask models partitions/crashes exactly —
gathered rows from unheard peers are ignored, so a partitioned stale leader
can keep appending locally but can neither replicate nor commit (it lacks a
quorum), and steps down the moment it hears a higher term.

Collective cost per step: 3 small ``all_gather`` (control, votes, acks) + 1
window ``all_gather`` (W·slot_bytes per contributor). The window gather is
deliberately an all_gather rather than a masked ``psum`` so that split-brain
double-contribution cannot corrupt the payload — receivers *select* the
dominant leader's row.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from rdma_paxos_tpu.config import LogConfig
from rdma_paxos_tpu.consensus.log import (
    EntryType, Log, M_GIDX, M_TERM, M_TYPE, META_W,
    append_batch, absorb_window, extract_window, last_term, slot_of,
)
from rdma_paxos_tpu.consensus.state import ConfigState, ReplicaState, Role
from rdma_paxos_tpu.ops.quorum import R_PAD, commit_scan

I32_MIN = jnp.iinfo(jnp.int32).min
I32_MAX = jnp.iinfo(jnp.int32).max

# telemetry counter-vector columns (``telemetry=True`` steps emit one
# u32 vector per replica per step; the host-side consumer is
# obs/device.py, which mirrors this layout — this module must NOT
# import obs, so the two are pinned against each other by
# tests/test_device_obs.py instead). Counters are per-step counts the
# host accumulates; the last two columns are point-in-time gauges.
(T_ELECTIONS, T_VOTES_GRANTED, T_VOTES_DENIED, T_ACCEPTED,
 T_COMMITTED, T_UNHEARD, T_QUORUM_W, T_HEADROOM, T_N) = range(9)

# control-gather columns (C_VTERM/C_VFOR carry each replica's durable vote
# pair so vote records refresh on EVERY step — full or stable — not only
# through the election-phase vote gather; C_QDEP carries each host's
# submit backlog so every host derives the SAME burst-size hint — the
# collective-count coordination that lets multihost drivers dispatch
# fused multi-step bursts without an extra gather)
(C_TERM, C_ROLE, C_END, C_COMMIT, C_LTERM, C_APPLY, C_TMO,
 C_VTERM, C_VFOR, C_QDEP, C_HEAD, C_N) = range(12)
# window-message scalar columns
S_VALID, S_WSTART, S_WCOUNT, S_TERM, S_PREV, S_COMMIT, S_HEAD, S_N = range(8)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepInput:
    """Per-replica host→device inputs for one step."""

    batch_data: jax.Array    # [B, slot_words] i32 — client entries (leader)
    batch_meta: jax.Array    # [B, META_W] i32
    batch_count: jax.Array   # i32 — valid entries in the batch
    timeout_fired: jax.Array  # i32 — host election timer expired
    peer_mask: jax.Array     # [R] i32 — which peers this replica can hear
    apply_done: jax.Array    # i32 — host's applied index (echo)
    queue_depth: jax.Array   # i32 — host submit backlog beyond this batch
                             #   (rides the control gather; feeds the
                             #   burst-size hint every host computes
                             #   identically)
    # --- cross-group transaction commit lane (txn=True only) ---
    # None in the default program: None leaves add no pytree nodes, so
    # txn=False inputs (and programs) are BYTE-IDENTICAL to the
    # pre-txn step (cache-key guarded by tests/test_txn.py). The watch
    # is this group's outstanding PREPARE entry in LOG-OFFSET domain
    # (the host subtracts its rebase total); -1 = no watch armed.
    txn_watch: Optional[jax.Array] = None   # i32 — prepare log offset
    txn_term: Optional[jax.Array] = None    # i32 — term it was appended in


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepOutput:
    """Per-replica device→host results of one step (small scalars only; bulk
    committed payload is fetched separately, see ``fetch_window``)."""

    term: jax.Array
    role: jax.Array
    leader_id: jax.Array
    voted_term: jax.Array     # durable vote pair — the host persists these
    voted_for: jax.Array      #   to HardState between steps
    head: jax.Array
    apply: jax.Array
    commit: jax.Array
    end: jax.Array
    hb_seen: jax.Array        # leader heartbeat arrived — reset election timer
    became_leader: jax.Array  # this replica won an election this step
    acked: jax.Array          # absorbed/verified the leader window this step
    accepted: jax.Array       # client entries actually appended from the
                              # batch (< batch_count ⟹ ring full: RETRY rest)
    peer_acked: jax.Array     # [R] — which peers acked THIS replica's
                              # window (meaningful on the leader; feeds the
                              # host failure detector, check_failure_count
                              # analog dare_server.c:1189-1227)
    leadership_verified: jax.Array  # read-index safety: a majority (dual
                              # majority in transit) accepted this leader's
                              # authority THIS step, so reads at commit are
                              # linearizable (rc_verify_leadership analog,
                              # dare_ibv_rc.c:1182-1280)
    burst_hint: jax.Array     # max queue depth heard from any self-claimed
                              # leader (identical on every host under full
                              # connectivity): hosts use it to agree on a
                              # fused multi-step burst size next iteration
    rebase_delta: jax.Array   # >0 when any heard end crossed
                              # cfg.rebase_threshold: the agreed uniform
                              # offset subtraction (min member head) for
                              # the coordinated i32 rollover. Identical
                              # on every host under full connectivity —
                              # NodeDaemon applies it collectively; the
                              # in-process drivers use their omniscient
                              # min-head instead (partition-safe).
    # --- correctness-observability digest chain (audit=True only) ---
    # None in the default program: None leaves add no pytree nodes, so
    # the audit=False step is BYTE-IDENTICAL to the pre-audit program
    # (cache-key guarded by tests/test_audit.py).
    audit_start: Optional[jax.Array] = None    # i32 — first digested index
    audit_digest: Optional[jax.Array] = None   # [W] u32 — per-entry digests
    audit_term: Optional[jax.Array] = None     # [W] i32 — per-entry terms
    # --- device telemetry (telemetry=True only) ---
    # [T_N] u32 counter vector (see the T_* columns above): protocol
    # counts as the DEVICE saw them, reduced in-program to scalars so
    # readback is O(counters), never O(log). None in the default
    # program — telemetry=False steps stay byte-identical
    # (cache-key guarded by tests/test_device_obs.py).
    telemetry: Optional[jax.Array] = None
    # --- cross-group transaction lane (txn=True only) ---
    # i32 prepare vote (txn/lane.py constants) for the group's armed
    # watch, evaluated against THIS replica's post-absorb log. None in
    # the default program — txn=False steps stay byte-identical
    # (cache-key guarded by tests/test_txn.py).
    txn_vote: Optional[jax.Array] = None


def make_step_input(cfg: LogConfig, n_replicas: int) -> StepInput:
    """An idle (no client traffic, no timeout) input."""
    i32 = jnp.int32
    return StepInput(
        batch_data=jnp.zeros((cfg.batch_slots, cfg.slot_words), i32),
        batch_meta=jnp.zeros((cfg.batch_slots, META_W), i32),
        batch_count=jnp.zeros((), i32),
        timeout_fired=jnp.zeros((), i32),
        peer_mask=jnp.ones((n_replicas,), i32),
        apply_done=jnp.zeros((), i32),
        queue_depth=jnp.zeros((), i32),
    )


def digest_fold(rows, *, xp=jnp):
    """The audit digest: one u32 mul-fold (FNV-1a accumulate + a
    murmur3-style finalizer so a low-order flip diffuses) per fused
    slot row, EXCLUDING the M_GIDX column (the coordinated i32
    rollover rewrites gidx in place — position binding comes from the
    ledger's absolute index instead; see the audit block in
    :func:`replica_step`).

    ONE implementation serves every digest producer — the ``audit=``
    compiled step variant, the jitted range re-digest
    (:func:`build_redigest`), and the host-side snapshot verification
    in ``consensus/snapshot.py`` (``xp=numpy``) — so device and host
    digests can never drift. The layout version is
    ``config.DIGEST_EPOCH``; bump it whenever this fold changes.

    ``rows``: ``[N, slot_words + META_W]`` u32 (jnp or numpy — both
    wrap u32 arithmetic identically)."""
    u32 = xp.uint32
    prime = u32(0x01000193)                     # FNV-1a prime
    acc = xp.full((rows.shape[0],), 0x811C9DC5, u32)   # FNV offset basis
    gidx_col = rows.shape[1] - META_W + M_GIDX
    for c in range(rows.shape[1]):
        if c == gidx_col:
            continue
        acc = acc * prime + rows[:, c]
    acc = acc ^ (acc >> 15)
    acc = acc * u32(0x2C1B3C6D)
    acc = acc ^ (acc >> 12)
    acc = acc * u32(0x297A2D39)
    acc = acc ^ (acc >> 15)
    return acc


def build_redigest(cfg: LogConfig, *, window_slots: int):
    """Jitted ``[start, start + window_slots)`` digest pass over ONE
    replica's fused log row — the backfill instrument of the repair
    pipeline (``runtime/repair.py``): after a digest-verified snapshot
    re-install, the donor's committed range is re-digested on device
    and fed to the host-side audit ledger so the repaired range
    returns to fully-audited (gap-free) coverage, not just healed
    state.

    Exactly the ``audit=`` window fold (:func:`digest_fold` — shared),
    so backfilled digests are bit-comparable with live audit windows.
    Returns ``(digests u32[W], terms i32[W], gidx i32[W])``; the host
    validates the stamped gidx column against the expected indices
    (slot-recycling integrity — same rule as the replay path) and
    clips to the committed range.

    CACHE-KEY GUARD: engines cache the compiled fn in the shared
    ``STEP_CACHE`` under a distinct ``("redigest", W)``-marked key —
    default / repair-off programs and their keys are untouched
    (tests/test_repair.py pins it)."""
    W = int(window_slots)
    i32, u32 = jnp.int32, jnp.uint32
    sw = cfg.slot_words

    def fn(buf_row, start):
        g = start + jnp.arange(W, dtype=i32)
        rows = buf_row[slot_of(g, cfg.n_slots)]
        dig = digest_fold(rows.astype(u32))
        return dig, rows[:, sw + M_TERM].astype(i32), rows[:, sw + M_GIDX]
    return jax.jit(fn)


def _lex_argmax(valid: jax.Array, keys) -> jax.Array:
    """Index of the lexicographically-largest row among ``valid`` ones
    (ties → smallest index); -1 if none valid."""
    v = valid
    for k in keys:
        kk = jnp.where(v, k, I32_MIN)
        v = v & (kk == jnp.max(kk))
    return jnp.where(jnp.any(v), jnp.argmax(v).astype(jnp.int32), -1)


def _popcount_vec(bitmask: jax.Array, n: int) -> jax.Array:
    """[n] membership 0/1 vector from a bitmask."""
    r = jnp.arange(n, dtype=jnp.uint32)
    return jnp.bitwise_and(jnp.right_shift(bitmask, r), 1).astype(jnp.int32)


def replica_step(
    state: ReplicaState,
    inp: StepInput,
    *,
    cfg: LogConfig,
    n_replicas: int,
    axis_name: str = "replica",
    use_pallas: bool = False,
    interpret: bool = False,
    fanout: str = "gather",
    elections: bool = True,
    audit: bool = False,
    telemetry: bool = False,
    txn: bool = False,
) -> Tuple[ReplicaState, StepOutput]:
    """One protocol step for this replica (call under ``shard_map`` over the
    ``replica`` mesh axis, or under ``vmap(axis_name=...)`` for single-chip
    simulation — see ``parallel/mesh.py``).

    ``fanout`` selects how the leader's window reaches followers:

    * ``"gather"`` — every replica ``all_gather``s a (zeroed-unless-leader)
      window and receivers SELECT the dominant claimant's row. Split-brain
      safe under arbitrary ``peer_mask`` partitions (two self-claimed
      leaders cannot corrupt each other's payload), at O(R·W·slot_bytes)
      ICI traffic per replica. Required for partition simulation.
    * ``"psum"`` — the leader's window is broadcast as a masked ``psum``:
      O(W·slot_bytes) per replica (bandwidth independent of R — the analog
      of the reference's per-follower delta writes costing the leader one
      NIC pass, ``dare_ibv_rc.c:1526-1642``). Sound ONLY under full
      connectivity (``peer_mask`` all-ones — the real ICI mesh, where a
      chip failure kills the whole program rather than partitioning it):
      with full pairwise hearing, Phase B leaves at most one replica in
      the LEADER role per step (any lower-term leader hears the higher
      term and steps down; same-term double-win is impossible by election
      safety), so the psum has at most one contributor and equals the
      dominant row the gather path would have selected. The tiny scalar
      claim gather is kept — receivers still term-gate absorption, so
      even a violated assumption degrades to a rejected window, not a
      corrupted log... except the summed payload itself; hence the
      partition-capable paths (SimCluster default, fuzzer) keep "gather".

    ``elections=False`` compiles the STABLE fast-path step: Phase B (one
    collective + the candidacy/vote logic) is statically removed. With no
    ``timeout_fired`` input set, the full step and the stable step compute
    bit-identical results — candidacies are the only thing Phase B can
    change — so a driver may freely dispatch the stable step on every
    iteration where no election timer fired (the latency hot path) and
    the full step otherwise. Term adoption from the control gather and
    window absorption still run, so a deposed leader steps down and a
    higher-term leader is followed even in stable steps.

    ``telemetry=True`` compiles the device-counter vector: one u32
    ``[T_N]`` row per replica per step (elections started, votes
    granted/denied, appends accepted, commit advance, unheard links,
    quorum width, log headroom — the T_* columns above), built from
    scalars already in registers and returned as the optional
    ``StepOutput.telemetry`` field. The host consumer is
    ``obs/device.py`` (never imported here); ``telemetry=False`` (the
    default) is byte-identical to the pre-telemetry program.

    ``audit=True`` compiles the silent-divergence digest chain: one
    u32 checksum per committed entry in the window ``[commit - W,
    commit)``, emitted as extra ``StepOutput`` fields (see the audit
    block below and the host-side ledger in ``obs/audit.py``; nothing
    from that host layer is ever called here). The followers of
    this design are passive in the replication hot path — one-sided
    window absorption lands bytes in log memory with no receiver-side
    end-to-end check — so bit corruption of replicated state is silent
    without it. ``audit=False`` (the default) is byte-identical to the
    pre-audit program.
    """
    assert fanout in ("gather", "psum"), fanout
    i32 = jnp.int32
    R, W = n_replicas, cfg.window_slots
    me = lax.axis_index(axis_name).astype(i32)
    heard = inp.peer_mask.astype(bool)                      # [R]

    in_new = _popcount_vec(state.bitmask_new, R)            # [R] 0/1
    in_old = _popcount_vec(state.bitmask_old, R)
    transit = (state.cid_state == int(ConfigState.TRANSIT)).astype(i32)
    # EXTENDED: the group was up-sized for a joiner that REPLICATES (it is
    # in bitmask_new, so the window fan-out and pruning floor include it)
    # but does not yet VOTE or count toward commit — quorum stays on the
    # old config until the joiner has caught up and the leader submits
    # TRANSIT (reference EXTENDED semantics: handle_server_join_request
    # up-sizes via an EXTENDED config, dare_ibv_ud.c:1024-1037, and the
    # joiner only joins quorums after EXTENDED→TRANSIT,
    # dare_server.c:1861-1937).
    ext = state.cid_state == int(ConfigState.EXTENDED)
    in_vote = jnp.where(ext, in_old, in_new)                # voting members
    maj_vote = jnp.sum(in_vote) // 2 + 1
    maj_old = jnp.sum(in_old) // 2 + 1
    # During joint consensus, old-config members must still vote (the win
    # condition demands a majority of BOTH configs — dare_server.c:1366-1373)
    i_member = (in_vote[me] > 0) | ((transit > 0) & (in_old[me] > 0))
    my_lterm = last_term(state.log, state.end)

    # ------------------------------------------------------------------
    # Phase A — control gather (terms, roles, offsets, candidacies,
    # apply offsets for pruning).  The analog of reading peers' cached
    # SIDs / ctrl arrays (dare_ibv_rc.c:1182-1280).
    # ------------------------------------------------------------------
    ctrl = jnp.zeros((C_N,), i32)
    ctrl = ctrl.at[C_TERM].set(state.term)
    ctrl = ctrl.at[C_ROLE].set(state.role)
    ctrl = ctrl.at[C_END].set(state.end)
    ctrl = ctrl.at[C_COMMIT].set(state.commit)
    ctrl = ctrl.at[C_LTERM].set(my_lterm)
    ctrl = ctrl.at[C_APPLY].set(jnp.minimum(inp.apply_done, state.commit))
    ctrl = ctrl.at[C_TMO].set(inp.timeout_fired)
    ctrl = ctrl.at[C_VTERM].set(state.voted_term)
    ctrl = ctrl.at[C_VFOR].set(state.voted_for)
    ctrl = ctrl.at[C_QDEP].set(inp.queue_depth)
    ctrl = ctrl.at[C_HEAD].set(state.head)
    allc = lax.all_gather(ctrl, axis_name)                  # [R, C_N]

    g_term, g_end = allc[:, C_TERM], allc[:, C_END]
    g_lterm, g_apply = allc[:, C_LTERM], allc[:, C_APPLY]
    g_tmo = allc[:, C_TMO]

    # vote-record retention from the control gather (rc_replicate_vote
    # analog, dare_ibv_rc.c:1049): runs on EVERY step, so a replica that
    # was partitioned during an election still learns peers' durable vote
    # pairs once healed — identically in the full and stable paths.
    rec_upd0 = heard & (allc[:, C_VTERM] > state.vote_rec_term)
    vote_rec_term1 = jnp.where(rec_upd0, allc[:, C_VTERM],
                               state.vote_rec_term)
    vote_rec_for1 = jnp.where(rec_upd0, allc[:, C_VFOR],
                              state.vote_rec_for)

    # ------------------------------------------------------------------
    # Phase B — one-round election (start_election dare_server.c:1264,
    # voting :1526-1743, counting :1327-1518 — collapsed to one step).
    # Statically removed in the stable fast path (elections=False).
    # ------------------------------------------------------------------
    if not elections:
        new_voted_term = state.voted_term
        new_voted_for = state.voted_for
        vote_rec_term2 = vote_rec_term1
        vote_rec_for2 = vote_rec_for1
        win = jnp.zeros((), bool)
        became = jnp.zeros((), bool)
        max_heard = jnp.max(jnp.where(heard, g_term, I32_MIN))
        new_term = jnp.maximum(state.term, max_heard)
        role = jnp.where(new_term > state.term, int(Role.FOLLOWER),
                         state.role).astype(i32)
        i_lead = role == int(Role.LEADER)
        leader_id = jnp.where(new_term > state.term, -1,
                              state.leader_id).astype(i32)
        log2, end2 = append_batch(
            state.log, state.end, state.head, inp.batch_data,
            inp.batch_meta,
            jnp.where(i_lead, inp.batch_count, 0).astype(i32), new_term)
        end1 = state.end
    else:
        is_cand = (g_tmo > 0) & (in_vote > 0)               # [R]
        cand_term = g_term + 1
        i_cand = is_cand[me] & (state.role != int(Role.LEADER))

        # voter logic (vote durability: the vote all_gather below
        # replicates the durable (voted_term, voted_for) pair to every
        # live peer, which RETAINS it in vote_rec_* — the
        # rc_replicate_vote analog; the host additionally persists the
        # pair to a HardState file between steps, and recovery restores
        # max(persisted, peer records) — see consensus/snapshot.py
        # recover_vote)
        can_grant = (
            heard & is_cand
            & (cand_term >= state.term)
            & ((cand_term > state.voted_term)
               | ((cand_term == state.voted_term)
                  & (jnp.arange(R) == state.voted_for)))
            & ((g_lterm > my_lterm)
               | ((g_lterm == my_lterm) & (g_end >= state.end)))
        )
        best = _lex_argmax(can_grant, [cand_term, g_lterm, g_end])
        my_vote = jnp.where(i_cand, me, jnp.where(i_member, best, -1))
        vote_cast = my_vote >= 0
        new_voted_term = jnp.where(
            vote_cast, jnp.maximum(state.voted_term, cand_term[my_vote]),
            state.voted_term)
        new_voted_for = jnp.where(vote_cast, my_vote, state.voted_for)

        vote_msg = jnp.stack([my_vote, new_voted_term, new_voted_for])
        g_votes = lax.all_gather(vote_msg, axis_name)       # [R, 3]
        votes = g_votes[:, 0]
        got = (votes == me) & heard
        # retain votes CAST THIS STEP immediately (the control-gather
        # retention above only carries pre-step pairs): the vote gather
        # doubles as same-step durable replication to every live peer
        rec_upd = heard & (g_votes[:, 1] > vote_rec_term1)
        vote_rec_term2 = jnp.where(rec_upd, g_votes[:, 1], vote_rec_term1)
        vote_rec_for2 = jnp.where(rec_upd, g_votes[:, 2], vote_rec_for1)
        win = (
            i_cand
            & (jnp.sum(got.astype(i32) * in_vote) >= maj_vote)
            & jnp.where(transit > 0,
                        jnp.sum(got.astype(i32) * in_old) >= maj_old, True)
        )

        # term adoption: everyone adopts the max term heard (incl.
        # candidacies); a deposed leader steps down here — the fencing of
        # server_to_follower (dare_server.c:2238).
        my_term1 = jnp.where(i_cand, state.term + 1, state.term)
        eff_term = jnp.where(is_cand, cand_term, g_term)
        max_heard = jnp.max(jnp.where(heard, eff_term, I32_MIN))
        new_term = jnp.maximum(my_term1, max_heard)

        role = jnp.where(
            win, int(Role.LEADER),
            jnp.where(new_term > my_term1, int(Role.FOLLOWER),
                      jnp.where(i_cand, int(Role.CANDIDATE), state.role)),
        ).astype(i32)
        became = win & (state.role != int(Role.LEADER))
        i_lead = role == int(Role.LEADER)
        leader_id = jnp.where(win, me,
                              jnp.where(new_term > state.term, -1,
                                        state.leader_id)).astype(i32)

        # --------------------------------------------------------------
        # Phase C — leader append: NOOP on election (dare_server.c:1487),
        # then the client batch (get_tailq_message → log_append_entry,
        # dare_ibv_ud.c:780-790).
        # --------------------------------------------------------------
        noop_data = jnp.zeros((1, cfg.slot_words), i32)
        noop_meta = jnp.zeros((1, META_W), i32).at[0, M_TYPE].set(
            int(EntryType.NOOP))
        log1, end1 = append_batch(
            state.log, state.end, state.head, noop_data, noop_meta,
            jnp.where(became, 1, 0).astype(i32), new_term)
        log2, end2 = append_batch(
            log1, end1, state.head, inp.batch_data, inp.batch_meta,
            jnp.where(i_lead, inp.batch_count, 0).astype(i32), new_term)

    # ------------------------------------------------------------------
    # Phase D — leader fan-out. Window floored at the minimum reachable
    # member end (so laggards within W catch up — beyond W they need
    # snapshot recovery, the analog of force_log_pruning eviction,
    # dare_server.c:2069) and at the leader's own head (pruned entries
    # are gone).
    # ------------------------------------------------------------------
    others = heard & (in_new > 0) & (jnp.arange(R) != me)
    min_end = jnp.min(jnp.where(others, g_end, I32_MAX))
    wstart = jnp.clip(min_end, end2 - W, end2)
    wstart = jnp.maximum(jnp.maximum(wstart, state.head), 0)
    wcount = jnp.clip(end2 - wstart, 0, W)
    wdata, wmeta = extract_window(log2, wstart, W)
    prev_term = jnp.where(
        wstart > 0, log2.meta[slot_of(wstart - 1, cfg.n_slots), M_TERM], 0)

    # pruning input: min apply over reachable members (leader-only use)
    min_apply = jnp.min(jnp.where(heard & (in_new > 0), g_apply, I32_MAX))

    msg_scal = jnp.zeros((S_N,), i32)
    msg_scal = msg_scal.at[S_VALID].set(1)
    msg_scal = msg_scal.at[S_WSTART].set(wstart)
    msg_scal = msg_scal.at[S_WCOUNT].set(wcount)
    msg_scal = msg_scal.at[S_TERM].set(new_term)
    msg_scal = msg_scal.at[S_PREV].set(prev_term)
    msg_scal = msg_scal.at[S_COMMIT].set(state.commit)
    msg_scal = msg_scal.at[S_HEAD].set(state.head)

    contrib = jnp.where(i_lead, 1, 0)
    gw_scal = lax.all_gather(msg_scal * contrib, axis_name)  # [R, S_N]

    # dominant leader: the highest-term valid claim this replica can hear
    claim = heard & (gw_scal[:, S_VALID] > 0)
    dom = _lex_argmax(claim, [gw_scal[:, S_TERM]])
    has_msg = dom >= 0
    dsafe = jnp.maximum(dom, 0)
    m_scal = gw_scal[dsafe]
    m_term = m_scal[S_TERM]

    if fanout == "psum":
        # single-contributor broadcast (see docstring for the safety
        # argument): O(W) bandwidth instead of O(R·W)
        m_data = lax.psum(wdata * contrib, axis_name)       # [W, sw]
        m_meta = lax.psum(wmeta * contrib, axis_name)       # [W, MW]
    else:
        gw_data = lax.all_gather(wdata * contrib, axis_name)  # [R, W, sw]
        gw_meta = lax.all_gather(wmeta * contrib, axis_name)  # [R, W, MW]
        m_data = gw_data[dsafe]
        m_meta = gw_meta[dsafe]

    # ------------------------------------------------------------------
    # Phase E — absorb (uniform; the leader absorbs its own window as a
    # no-op). Term gate = fencing; prev-term check = AppendEntries
    # consistency; backoff on mismatch = nextIndex rewind, expressed as
    # data (our advertised end drops, so the next window reaches lower).
    # ------------------------------------------------------------------
    use = has_msg & (m_scal[S_VALID] > 0) & (m_term >= new_term)
    new_term2 = jnp.where(use, jnp.maximum(new_term, m_term), new_term)
    role2 = jnp.where(
        use & ((m_term > new_term) | (dom != me)),
        jnp.where(i_lead & (dom == me), role, int(Role.FOLLOWER)),
        role).astype(i32)
    leader_id2 = jnp.where(use, dom, leader_id)
    i_lead2 = role2 == int(Role.LEADER)

    m_wstart, m_wcount = m_scal[S_WSTART], m_scal[S_WCOUNT]
    gap = m_wstart > end2
    local_prev = jnp.where(
        m_wstart > 0,
        log2.meta[slot_of(m_wstart - 1, cfg.n_slots), M_TERM], 0)
    prev_ok = (m_wstart == 0) | (local_prev == m_scal[S_PREV])
    can_absorb = use & ~gap & prev_ok

    log3, end3 = absorb_window(
        log2, end2, m_data, m_meta, m_wstart,
        jnp.where(can_absorb, m_wcount, 0))
    # backoff: advertised end rewinds to just before the mismatch (never
    # below commit — committed entries cannot conflict)
    end3 = jnp.where(use & ~gap & ~prev_ok,
                     jnp.maximum(m_wstart - 1, state.commit), end3)

    # follower commit/head riding the message (lazy, one step behind the
    # leader's scan — matching the reference's lazy commit push). The
    # advance is CLAMPED to W per step: the committed-config checkpoint
    # (Phase G) scans only the W-entry commit-crossing window, so an
    # unbounded jump (rejoiner with a long matching log but stale
    # commit) could carry a CONFIG entry past the scan unseen. W per
    # step is also the host's apply/replay catch-up rate, so the clamp
    # costs no end-to-end liveness.
    commit1 = jnp.where(
        can_absorb & ~i_lead2,
        jnp.maximum(state.commit,
                    jnp.minimum(jnp.minimum(m_scal[S_COMMIT], end3),
                                state.commit + W)),
        state.commit)
    head1 = jnp.where(
        can_absorb,
        jnp.maximum(state.head, jnp.minimum(m_scal[S_HEAD], commit1)),
        state.head)

    # ------------------------------------------------------------------
    # CONFIG derivation — Raft's latest-configuration-in-the-log rule,
    # carried INCREMENTALLY: the live config (bitmask_old/new, cid_state,
    # epoch) is cached state backed by the log entry at ``cfg_src``. Each
    # step adopts any newer CONFIG arriving through the appended batch
    # (O(B)) or the absorbed window (O(W)) — data already in registers —
    # and only when the cached source entry is truncated or overwritten
    # does a full-ring rescan run, under ``lax.cond`` (rare: divergence
    # backoff / conflicting absorb). The rescan branch reproduces the
    # original rule exactly — newest CONFIG retained in [head, end), else
    # the committed checkpoint — so truncating an uncommitted CONFIG
    # still rolls the config back (no abandoned-config trap).
    #
    # Cost honesty: under ``shard_map`` (the real multi-chip path) the
    # predicate is a per-device scalar and the rescan truly only runs on
    # invalidation; under ``vmap`` (single-chip simulation) a batched-
    # predicate cond lowers to select_n and BOTH branches execute, so
    # the sim still pays one full-ring scan per step — the same cost as
    # the pre-incremental code, no worse. The committed-checkpoint scan
    # below was removed outright on every path. CONFIG entries take
    # effect from append/absorb time (poll_config_entries,
    # dare_server.c:2133-2187). Runs BEFORE the commit scan (joint
    # consensus needs the new quorum rules from append time).
    # ------------------------------------------------------------------
    wend_abs = m_wstart + m_wcount
    # invalidation: source truncated away (divergence backoff or
    # in-window conflict both leave end3 at/below it) …
    stale_src = state.cfg_src >= end3
    # … or overwritten by an absorbed window row that is no longer the
    # same CONFIG entry
    wp = jnp.clip(state.cfg_src - m_wstart, 0, W - 1)
    # same gidx + type is NOT enough: a new leader's conflicting CONFIG
    # at the same index is a different entry — the term disambiguates
    same_entry = ((m_meta[wp, M_GIDX] == state.cfg_src)
                  & (m_meta[wp, M_TYPE] == int(EntryType.CONFIG))
                  & (m_meta[wp, M_TERM] == state.cfg_src_term))
    replaced = (can_absorb & (state.cfg_src >= m_wstart)
                & (state.cfg_src < wend_abs) & ~same_entry)
    cfg_invalid = (state.cfg_src >= 0) & (stale_src | replaced)

    def _cfg_rescan(_):
        all_gidx = log3.meta[:, M_GIDX]
        live = ((log3.meta[:, M_TYPE] == int(EntryType.CONFIG))
                & (all_gidx >= head1) & (all_gidx < end3))
        pos = _lex_argmax(live, [all_gidx])
        found = pos >= 0
        psafe = jnp.maximum(pos, 0)
        w = log3.data[psafe]
        return (jnp.where(found, all_gidx[psafe], -1),
                jnp.where(found, log3.meta[psafe, M_TERM], 0),
                jnp.where(found, w[0].astype(jnp.uint32), state.ccfg_old),
                jnp.where(found, w[1].astype(jnp.uint32), state.ccfg_new),
                jnp.where(found, w[2], state.ccfg_cid),
                jnp.where(found, w[3], state.ccfg_epoch))

    def _cfg_keep(_):
        return (state.cfg_src, state.cfg_src_term, state.bitmask_old,
                state.bitmask_new, state.cid_state, state.epoch)

    (base_src, base_sterm, base_old, base_new, base_cid,
     base_epoch) = lax.cond(cfg_invalid, _cfg_rescan, _cfg_keep, None)

    # newest CONFIG in the absorbed window (followers learn configs here)
    w_offs = jnp.arange(W, dtype=i32)
    w_gidx = m_wstart + w_offs
    w_is_cfg = (can_absorb & (w_offs < m_wcount)
                & (m_meta[:, M_TYPE] == int(EntryType.CONFIG))
                & (m_meta[:, M_GIDX] == w_gidx)
                & (w_gidx >= head1) & (w_gidx < end3))
    wpos = _lex_argmax(w_is_cfg, [w_gidx])
    w_words = m_data[jnp.maximum(wpos, 0)]
    w_src = jnp.where(wpos >= 0, m_wstart + wpos, -1)

    # newest CONFIG in the just-appended batch (the leader learns its
    # own submissions here — its fan-out window may trail its end)
    Bn = inp.batch_meta.shape[0]
    b_offs = jnp.arange(Bn, dtype=i32)
    b_is_cfg = ((b_offs < (end2 - end1))
                & (inp.batch_meta[:, M_TYPE] == int(EntryType.CONFIG))
                & ((end1 + b_offs) < end3))
    bpos = _lex_argmax(b_is_cfg, [b_offs])
    b_words = inp.batch_data[jnp.maximum(bpos, 0)]
    b_src = jnp.where(bpos >= 0, end1 + bpos, -1)

    # adopt the candidate with the largest (gidx, term) — an absorbed
    # window row at the SAME gidx as the base but a newer term is a new
    # leader's conflicting CONFIG and must win; ties/absences fall back
    # to the base cache (index 0)
    w_term = m_meta[jnp.maximum(wpos, 0), M_TERM]
    cand_src = jnp.stack([base_src, w_src, b_src])
    cand_sterm = jnp.stack([
        base_sterm, jnp.where(wpos >= 0, w_term, 0),
        jnp.where(bpos >= 0, new_term, 0)])
    cand_old = jnp.stack([base_old, w_words[0].astype(jnp.uint32),
                          b_words[0].astype(jnp.uint32)])
    cand_new = jnp.stack([base_new, w_words[1].astype(jnp.uint32),
                          b_words[1].astype(jnp.uint32)])
    cand_cid = jnp.stack([base_cid, w_words[2], b_words[2]])
    cand_epoch = jnp.stack([base_epoch, w_words[3], b_words[3]])
    pick = _lex_argmax(cand_src >= -1, [cand_src, cand_sterm])
    pick = jnp.maximum(pick, 0)
    cfg_src2 = cand_src[pick]
    cfg_src_term2 = cand_sterm[pick]
    bm_old2 = cand_old[pick]
    bm_new2 = cand_new[pick]
    cid2 = cand_cid[pick]
    epoch2 = cand_epoch[pick]
    in_new2 = _popcount_vec(bm_new2, R)
    in_old2 = _popcount_vec(bm_old2, R)
    maj_old2 = jnp.sum(in_old2) // 2 + 1
    transit2 = (cid2 == int(ConfigState.TRANSIT)).astype(i32)
    # EXTENDED post-absorb: commit quorum on the old config (joiner
    # replicates but does not count) — same rule as the pre-step masks
    ext2 = cid2 == int(ConfigState.EXTENDED)
    q_mask2 = jnp.where(ext2, bm_old2, bm_new2)
    in_q2 = _popcount_vec(q_mask2, R)
    maj_q2 = jnp.sum(in_q2) // 2 + 1

    # ------------------------------------------------------------------
    # Phase F — ACK + quorum commit. The ack is the *verified match
    # offset* (everything ≤ the absorbed window end matches the leader's
    # log), gathered from all replicas — the analog of followers RDMA-
    # writing reply[] bytes into the leader's entries. The commit scan
    # itself is ops/quorum.commit_scan (Pallas on TPU), under the
    # POST-absorb membership config.
    # ------------------------------------------------------------------
    my_ack = jnp.where(can_absorb, m_wstart + m_wcount, 0).astype(i32)
    ack_pair = jnp.stack([my_ack, jnp.where(can_absorb, dom, -1)])
    g_acks = lax.all_gather(ack_pair, axis_name)            # [R, 2]
    acks_for_me = jnp.where(heard & (g_acks[:, 1] == me), g_acks[:, 0], 0)
    acks_pad = jnp.zeros((R_PAD,), i32).at[:R].set(acks_for_me)

    cwin_g = state.commit + jnp.arange(W, dtype=i32)
    cwin_meta = log3.meta[slot_of(cwin_g, cfg.n_slots)]     # [W, META_W]
    terms_win = cwin_meta[:, M_TERM]
    scanned = commit_scan(
        acks_pad, state.commit, new_term2, end3, terms_win,
        bm_old2, q_mask2, transit2, maj_old2, maj_q2,
        use_pallas=use_pallas, interpret=interpret)
    commit2 = jnp.where(i_lead2, jnp.maximum(state.commit, scanned), commit1)

    # ------------------------------------------------------------------
    # Phase G — apply echo, pruning, CONFIG application.
    # ------------------------------------------------------------------
    apply2 = jnp.clip(jnp.maximum(state.apply, inp.apply_done),
                      head1, commit2)
    # Pruning is lazy and pressure-gated, like the reference: the periodic
    # pruner only trims what every reachable member has applied
    # (log_pruning P1/P2/P3 invariants, dare_server.c:1996-2067), and only
    # once the ring is 3/4 full — so a transiently-partitioned laggard can
    # still catch up from the log; one pruned past must snapshot-recover
    # (host path), which is exactly the reference's straggler-eviction
    # semantics.
    pressure = (end3 - head1) > (3 * cfg.n_slots) // 4
    head2 = jnp.where(
        i_lead2 & pressure,
        jnp.clip(jnp.maximum(head1, min_apply), head1, apply2),
        head1)
    # FORCED pruning (force_log_pruning analog, dare_server.c:2069-2122):
    # a reachable member whose apply is frozen (wedged app) must not
    # block the ring forever. Under HARD pressure (7/8 full) the leader
    # advances the head past the laggard, bounded by its OWN applied
    # offset — every recycled entry is applied + persisted on the leader,
    # so the left-behind member can snapshot-recover from its store. The
    # laggard's host detects head > its apply cursor and stops replaying
    # (recycled slots must never reach the app) — see
    # SimCluster._replay_committed / need_recovery.
    hard = (end3 - head1) > (7 * cfg.n_slots) // 8
    head2 = jnp.where(i_lead2 & hard, jnp.maximum(head2, apply2), head2)

    # committed-config checkpoint: a CONFIG entry below commit can never
    # be truncated (backoff floors at commit), so it becomes the
    # fallback when the ring holds no live CONFIG entry (pruned past, or
    # every newer CONFIG was truncated). Incremental form: (a) promote
    # the live cache once its source entry commits; (b) scan the
    # commit-CROSSING window [state.commit, commit2) — bounded by W —
    # for an older CONFIG committing while a newer uncommitted one is
    # cached (two-configs-in-flight; the driver serializes changes so
    # this is a churn-replay corner). Newest-wins by epoch (epochs are
    # strictly increasing along the committed config order by
    # construction — MembershipManager bumps per change, and elastic
    # genesis re-types old-world CONFIGs to NOOP).
    crossed = ((cwin_meta[:, M_TYPE] == int(EntryType.CONFIG))
               & (cwin_meta[:, M_GIDX] == cwin_g)
               & (cwin_g < commit2))
    xpos = _lex_argmax(crossed, [cwin_g])
    xw = log3.data[slot_of(state.commit + jnp.maximum(xpos, 0),
                           cfg.n_slots)]
    x_found = xpos >= 0
    cc1_old = jnp.where(x_found & (xw[3] > state.ccfg_epoch),
                        xw[0].astype(jnp.uint32), state.ccfg_old)
    cc1_new = jnp.where(x_found & (xw[3] > state.ccfg_epoch),
                        xw[1].astype(jnp.uint32), state.ccfg_new)
    cc1_cid = jnp.where(x_found & (xw[3] > state.ccfg_epoch),
                        xw[2], state.ccfg_cid)
    cc1_epoch = jnp.where(x_found & (xw[3] > state.ccfg_epoch),
                          xw[3], state.ccfg_epoch)
    promote = (cfg_src2 >= 0) & (cfg_src2 < commit2) & (epoch2 > cc1_epoch)
    ccfg_old2 = jnp.where(promote, bm_old2, cc1_old)
    ccfg_new2 = jnp.where(promote, bm_new2, cc1_new)
    ccfg_cid2 = jnp.where(promote, cid2, cc1_cid)
    ccfg_epoch2 = jnp.where(promote, epoch2, cc1_epoch)

    # ------------------------------------------------------------------
    # Silent-divergence audit digests (audit=True only; statically
    # removed otherwise — the default program stays byte-identical).
    # One digest per entry in the window [commit2 - W, commit2): commit
    # advances at most W per step (the leader scans a W-entry window;
    # the follower advance is clamped to W), so consecutive windows
    # tile the committed prefix with NO gaps, and each entry is
    # RE-digested on every step while commit2 <= g + W — the host
    # ledger (obs/audit.py) both cross-checks replicas at matching
    # absolute indices and re-checks a replica's own earlier reports,
    # catching post-commit bit corruption of log memory. The mul-fold
    # covers the fused slot row (payload words + metadata incl. the
    # term column — the HardState binding) EXCEPT the M_GIDX column:
    # the coordinated i32 rollover rewrites gidx in place, and a
    # digest covering it would tear between replicas that digest the
    # same entry on opposite sides of a rollover; position binding
    # comes from the ledger's absolute index instead. Entries below
    # ``head`` are masked out (their slots may be recycled), which is
    # safe: g >= head implies the slot physically holds entry g (the
    # ring retains at most n_slots - 1 live entries).
    audit_start = audit_digest = audit_terms = None
    if audit:
        u32 = jnp.uint32
        a_g = (commit2 - W) + jnp.arange(W, dtype=i32)
        audit_start = jnp.maximum(jnp.maximum(commit2 - W, head2), 0)
        a_valid = a_g >= audit_start
        a_rows = log3.buf[slot_of(a_g, cfg.n_slots)].astype(u32)
        # the fold lives in digest_fold — shared with the range
        # re-digest program and the host-side snapshot verification,
        # so no digest producer can drift from another
        audit_digest = jnp.where(a_valid, digest_fold(a_rows), u32(0))
        audit_terms = jnp.where(
            a_valid, a_rows[:, cfg.slot_words + M_TERM].astype(i32), 0)

    # ------------------------------------------------------------------
    # Device telemetry (telemetry=True only; statically removed
    # otherwise). Every value is a scalar already in registers — no
    # log reads, no collectives — so the vector costs a handful of
    # integer ops and its readback is O(T_N). Counter semantics are
    # DEVICE truth: what this replica's program actually did this
    # step, not what the host inferred (the gap this closes: unheard
    # links count the link-model drops/partitions as consumed by the
    # compiled step; quorum width is the ack count the commit scan
    # really saw; headroom is the ring occupancy inside the dispatch).
    # ------------------------------------------------------------------
    telemetry_vec = None
    if telemetry:
        if elections:
            t_elec = i_cand.astype(i32)
            # granted = voted for ANOTHER replica's candidacy this
            # step; denied = heard candidacies (own excluded) that did
            # not get this replica's vote
            t_grant = (vote_cast & (my_vote != me)).astype(i32)
            n_cand = jnp.sum((is_cand & heard).astype(i32))
            t_deny = jnp.maximum(n_cand - t_elec - t_grant, 0)
        else:
            t_elec = t_grant = t_deny = jnp.zeros((), i32)
        telemetry_vec = jnp.stack([
            t_elec,
            t_grant,
            t_deny,
            (end2 - end1).astype(i32),
            (commit2 - state.commit).astype(i32),
            (R - jnp.sum(heard.astype(i32))).astype(i32),
            jnp.sum((heard & (g_acks[:, 1] == me)).astype(i32)),
            ((cfg.n_slots - 1) - (end3 - head2)).astype(i32),
        ]).astype(jnp.uint32)

    # ------------------------------------------------------------------
    # Cross-group transaction prepare-vote lane (txn=True only;
    # statically removed otherwise — the default program stays
    # byte-identical). The host coordinator arms a per-group watch
    # ``(prepare index, term)``; each replica reads the watched slot of
    # its OWN post-absorb log and votes (txn/lane.py): PREPARED when
    # the index committed under the watched term (or was already
    # pruned — pruning trails the host apply cursor, so a pruned index
    # was committed and replayed), CONFLICT when it committed under a
    # different term (a failover leader overwrote the prepare), else
    # PENDING. One gather-free slot read per replica — the vote rides
    # the SAME dispatch that replicated the prepare entries, which is
    # what makes a cross-group commit ~2 protocol steps.
    # ------------------------------------------------------------------
    txn_vote = None
    if txn:
        from rdma_paxos_tpu.txn.lane import prepare_vote
        t_w = (inp.txn_watch if inp.txn_watch is not None
               else jnp.full((), -1, i32))
        t_wt = (inp.txn_term if inp.txn_term is not None
                else jnp.zeros((), i32))
        t_row = log3.buf[slot_of(jnp.maximum(t_w, 0), cfg.n_slots)]
        txn_vote = prepare_vote(
            watch=t_w, watch_term=t_wt, head=head2, commit=commit2,
            entry_term=t_row[cfg.slot_words + M_TERM].astype(i32),
            entry_gidx=t_row[cfg.slot_words + M_GIDX].astype(i32))

    new_state = ReplicaState(
        log=log3, term=new_term2, role=role2, leader_id=leader_id2,
        voted_term=new_voted_term, voted_for=new_voted_for,
        vote_rec_term=vote_rec_term2, vote_rec_for=vote_rec_for2,
        head=head2, apply=apply2, commit=commit2, end=end3,
        cid_state=cid2, bitmask_old=bm_old2, bitmask_new=bm_new2,
        epoch=epoch2, cfg_src=cfg_src2, cfg_src_term=cfg_src_term2,
        ccfg_old=ccfg_old2, ccfg_new=ccfg_new2, ccfg_cid=ccfg_cid2,
        ccfg_epoch=ccfg_epoch2,
    )
    out = StepOutput(
        term=new_term2, role=role2, leader_id=leader_id2,
        voted_term=new_voted_term, voted_for=new_voted_for,
        head=head2, apply=apply2, commit=commit2, end=end3,
        hb_seen=(has_msg & use).astype(i32),
        became_leader=became.astype(i32),
        acked=can_absorb.astype(i32),
        accepted=(end2 - end1).astype(i32),
        peer_acked=(heard & (g_acks[:, 1] == me)).astype(i32),
        leadership_verified=(
            i_lead2
            & (jnp.sum((heard & (g_acks[:, 1] == me)).astype(i32)
                       * in_q2) >= maj_q2)
            & ((transit2 <= 0)
               | (jnp.sum((heard & (g_acks[:, 1] == me)).astype(i32)
                          * in_old2) >= maj_old2))).astype(i32),
        burst_hint=jnp.max(jnp.where(
            heard & (allc[:, C_ROLE] == int(Role.LEADER)),
            allc[:, C_QDEP], 0)).astype(i32),
        # coordinated i32-rollover signal: when any heard end crossed
        # the threshold, the agreed subtraction is the min PRE-step head
        # over ALL heard rows (every live offset stays >= 0), rounded
        # DOWN to a multiple of n_slots (slot = g % n_slots and entries
        # do not move, so the mapping must be preserved). The min is
        # deliberately NOT filtered by membership: bitmask_new skews by
        # one step during CONFIG adoption (leader adopts at append,
        # followers at absorb), and a membership-filtered min would let
        # hosts derive DIFFERENT deltas in that window — permanent
        # offset divergence. ``heard`` is the only mask that is
        # provably identical on every host under full connectivity; a
        # catching-up row's low head merely defers the rollover.
        rebase_delta=jnp.where(
            jnp.max(jnp.where(heard, g_end, 0))
            >= cfg.rebase_threshold,
            jnp.maximum(
                jnp.bitwise_and(
                    jnp.min(jnp.where(heard, allc[:, C_HEAD], I32_MAX)),
                    ~(cfg.n_slots - 1)),
                0),
            0).astype(i32),
        audit_start=audit_start,
        audit_digest=audit_digest,
        audit_term=audit_terms,
        telemetry=telemetry_vec,
        txn_vote=txn_vote,
    )
    return new_state, out


def group_step(
    *,
    cfg: LogConfig,
    n_replicas: int,
    axis_name: str = "replica",
    use_pallas: bool = False,
    interpret: bool = False,
    fanout: str = "gather",
    elections: bool = True,
    audit: bool = False,
    telemetry: bool = False,
    txn: bool = False,
):
    """The group-batched protocol step: G independent consensus groups
    advanced by ONE program.

    :func:`replica_step` is documented as vmappable over the replica
    axis; sharding the keyspace across G groups adds a second,
    *unnamed* leading ``group`` batch axis. Groups are fully
    independent state machines — no collective may ever cross the
    group axis — so the outer ``vmap`` carries no axis name and XLA
    simply widens every tensor op and every replica-axis collective by
    a factor of G. G groups therefore replicate in ONE compiled
    dispatch instead of G (the sharded-throughput win
    ``benchmarks/shard_bench.py`` measures).

    Takes/returns pytrees with leading axes ``[group, replica, ...]``.

    CACHE-KEY GUARD: everything that shapes the compiled program is in
    this signature — the group count G deliberately is NOT. The
    returned callable is batch-size-polymorphic until ``jit``
    specializes it on the input shapes, so a homogeneous
    ``ShardedCluster`` (G groups sharing one ``LogConfig``) runs all
    its groups through exactly ONE compiled program per step variant,
    cached once in the shared runtime step cache
    (``runtime/sim.py:STEP_CACHE``; ``tests/test_shard.py`` proves the
    single-compile property).
    """
    import functools

    core = functools.partial(
        replica_step, cfg=cfg, n_replicas=n_replicas,
        axis_name=axis_name, use_pallas=use_pallas,
        interpret=interpret, fanout=fanout, elections=elections,
        audit=audit, telemetry=telemetry, txn=txn)
    vstep = jax.vmap(core, in_axes=(0, 0), axis_name=axis_name)
    return jax.vmap(vstep, in_axes=(0, 0))


# ---------------------------------------------------------------------------
# device-resident K-window scan: the consolidated minimal readback
# ---------------------------------------------------------------------------

# per-replica scalar outputs the host rules actually consume, packed
# into ONE [..., len(SCAN_KEYS)] i32 matrix by :func:`scan_scalars` so
# a K-step scan dispatch returns a single consolidated array instead
# of one device->host transfer per field. ``accepted`` carries the
# CUMULATIVE accepted count across the scan (the burst-sum semantics,
# computed in-program). Order is part of the host contract
# (runtime/sim.py unpacks by index) — append only.
SCAN_KEYS = ("term", "role", "leader_id", "voted_term", "voted_for",
             "head", "apply", "commit", "end", "hb_seen",
             "became_leader", "acked", "accepted",
             "leadership_verified", "rebase_delta", "burst_hint")


def scan_scalars(out: StepOutput, accepted_total: jax.Array
                 ) -> jax.Array:
    """Stack one step's :data:`SCAN_KEYS` outputs along a trailing
    axis (``[..., len(SCAN_KEYS)]`` i32) — the scan tier's one-array
    scalar readback. ``accepted_total`` substitutes the cumulative
    accepted count for the per-step ``accepted`` field."""
    cols = [accepted_total if k == "accepted" else getattr(out, k)
            for k in SCAN_KEYS]
    return jnp.stack([c.astype(jnp.int32) for c in cols], axis=-1)


def scan_readback(out: StepOutput, accepted_total: jax.Array, *,
                  audit: bool, telemetry: bool) -> dict:
    """One scan step's readback dict — the SINGLE assembly rule every
    scan builder uses (sim, group, spmd, spmd-group), so the
    consolidated-readback contract can never drift between engines:
    the :func:`scan_scalars` matrix + ``peer_acked``, plus the
    per-step audit windows / telemetry vector only when those
    variants are compiled."""
    ys = dict(scal=scan_scalars(out, accepted_total),
              peer_acked=out.peer_acked)
    if audit:
        ys.update(audit_start=out.audit_start,
                  audit_digest=out.audit_digest,
                  audit_term=out.audit_term,
                  audit_commit=out.commit)
    if telemetry:
        ys["telemetry"] = out.telemetry
    return ys


def fetch_window(log: Log, start: jax.Array, *, window_slots: int):
    """Host helper: gather ``window_slots`` entries beginning at ``start`` —
    used by the driver to read newly committed payloads for replay/persist
    (the analog of apply_committed_entries walking the log,
    ``dare_server.c:1815-1974``)."""
    return extract_window(log, start, window_slots)
